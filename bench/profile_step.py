#!/usr/bin/env python
"""Step-time breakdown for the L4 rollup hot path (feeds PERF.md).

Times each stage of the ingest step in isolation on the attached chip:
dispatch overhead, fanout, fingerprint, batch-local sort+reduce, and the
full stash fold, across batch sizes. Run from repo root:

    python bench/profile_step.py [--cpu]
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

if "--cpu" in sys.argv:
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from deepflow_tpu.aggregator.fanout import FANOUT_LANES, FanoutConfig, fanout_l4
from deepflow_tpu.aggregator.pipeline import _KEY_COLS, _doc_fingerprint, make_ingest_step
from deepflow_tpu.aggregator.stash import accum_init, stash_init
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.ingest.replay import SyntheticFlowGen
from deepflow_tpu.ops.hashing import fingerprint64_t
from deepflow_tpu.ops.segment import groupby_reduce


def timeit(fn, *args, iters=20, warmup=3, donate=None):
    jfn = jax.jit(fn, donate_argnums=donate) if donate else jax.jit(fn)
    out = None
    for _ in range(warmup):
        out = jfn(*args)
        if donate:
            args = (out,) + args[1:]
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jfn(*args)
        if donate:
            args = (out,) + args[1:]
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--batches", type=int, nargs="*", default=[1 << 14, 1 << 16, 1 << 18])
    args = p.parse_args()

    print(f"platform={jax.devices()[0].platform} device={jax.devices()[0]}")
    sum_cols = np.nonzero(FLOW_METER.sum_mask)[0].astype(np.int32)
    max_cols = np.nonzero(FLOW_METER.max_mask)[0].astype(np.int32)

    for batch in args.batches:
        gen = SyntheticFlowGen(num_tuples=10_000, seed=0)
        fb = gen.flow_batch(batch, 1_700_000_000)
        tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
        meters = jnp.asarray(fb.meters)
        valid = jnp.asarray(fb.valid)
        capacity = 1 << 16

        res = {}

        # 0. dispatch floor: trivial donated state update
        state0 = jnp.zeros((capacity,), jnp.float32)
        res["dispatch_floor"] = timeit(lambda s: s + 1.0, state0, donate=(0,))

        # 1. fanout alone
        fo = FanoutConfig()
        res["fanout"] = timeit(lambda t, m, v: fanout_l4(t, m, v, fo), tags, meters, valid)

        # 2. fingerprint alone (on fanned-out tags)
        doc_tags, doc_meters, ts, doc_valid = jax.jit(
            lambda t, m, v: fanout_l4(t, m, v, fo)
        )(tags, meters, valid)
        jax.block_until_ready(doc_tags)
        key_cols = jnp.asarray(_KEY_COLS)

        def fp_raw(dt):
            # legacy raw-column fold: key row select + 32-column murmur
            km = jnp.take(dt, key_cols, axis=0)
            return fingerprint64_t(km)

        res["fingerprint_raw"] = timeit(fp_raw, doc_tags)
        # production path since r6: packed key words (PERF.md §9d)
        res["fingerprint_packed"] = timeit(_doc_fingerprint, doc_tags)

        # 3. batch-local sort+reduce ([4N] rows)
        hi, lo = jax.jit(_doc_fingerprint)(doc_tags)
        window = (ts // jnp.uint32(1)).astype(jnp.uint32)

        def local_reduce(w, h, l, dt, dm, dv):
            return groupby_reduce(w, h, l, dt, jnp.transpose(dm), dv,
                                  sum_cols, max_cols)

        res["local_sort_reduce_4N"] = timeit(
            local_reduce, window, hi, lo, doc_tags, doc_meters, doc_valid
        )

        # 3b. sort only, key lanes only ([4N])
        def sort_only(w, h, l):
            iota = jnp.arange(w.shape[0], dtype=jnp.int32)
            return jax.lax.sort((w, h, l, iota), num_keys=3)

        res["sort_keys_4N"] = timeit(sort_only, window, hi, lo)

        # 3c. sort at fold size ([4N + capacity])
        wq = jnp.concatenate([window, jnp.zeros((capacity,), jnp.uint32)])
        hq = jnp.concatenate([hi, jnp.zeros((capacity,), jnp.uint32)])
        lq = jnp.concatenate([lo, jnp.zeros((capacity,), jnp.uint32)])
        res["sort_keys_4N+cap"] = timeit(sort_only, wq, hq, lq)

        # 4. production cadence: append per batch + fold every
        # accum_batches (aggregator/pipeline.make_ingest_step).
        accum_batches = 8
        append_fn, fold_fn = make_ingest_step(FanoutConfig(), interval=1)
        append_j = jax.jit(append_fn, donate_argnums=(0, 1))
        fold_j = jax.jit(fold_fn, donate_argnums=(0, 1))
        doc_rows = FANOUT_LANES * batch
        state = stash_init(capacity, TAG_SCHEMA, FLOW_METER)
        acc = accum_init(accum_batches * doc_rows, TAG_SCHEMA, FLOW_METER)

        # warm both compiles
        state, acc = append_j(state, acc, jnp.int32(0), tags, meters, valid)
        state, acc = fold_j(state, acc)
        jax.block_until_ready(acc.slot)

        # append timed over a full ring of iterations so dispatch overlap
        # matches the cycle loop below (a single synced sample would
        # overstate it and could push fold_amortized negative)
        t0 = time.perf_counter()
        for k in range(accum_batches):
            state, acc = append_j(
                state, acc, jnp.int32(k * doc_rows), tags, meters, valid
            )
        jax.block_until_ready(acc.slot)
        res["append"] = (time.perf_counter() - t0) / accum_batches
        state, acc = fold_j(state, acc)  # reset ring for the cycle loop

        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            for k in range(accum_batches):
                state, acc = append_j(
                    state, acc, jnp.int32(k * doc_rows), tags, meters, valid
                )
            state, acc = fold_j(state, acc)
        jax.block_until_ready(acc.slot)
        cyc = (time.perf_counter() - t0) / iters
        res["fold_amortized"] = cyc / accum_batches - res["append"]
        res["cycle_per_batch"] = cyc / accum_batches

        print(f"\nbatch={batch} ({doc_rows} doc rows, capacity={capacity}):")
        for k, v in res.items():
            print(f"  {k:24s} {v * 1e3:8.3f} ms")
        print(
            f"  -> amortized rate: {batch / res['cycle_per_batch'] / 1e6:.2f} M flows/s"
        )


if __name__ == "__main__":
    main()
