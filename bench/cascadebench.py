"""Rollup-cascade A/B bench (ISSUE 9): double-ingest vs cascade on the
§14 feeder-shaped dual-granularity workload, plus a long-range query
benchmark.

Part A — ingest: the same synthetic flow stream (10k 5-tuples, 1s
cadence with periodic window advances) through

  * `double`  — DoubleIngestPipeline: the pre-ISSUE-9 implementation,
    a full second device dispatch per batch into a parallel 1m
    pipeline;
  * `cascade` — DualGranularityPipeline over the rollup cascade: ONE
    fused dispatch per batch, the 1m series folded on device from
    closed 1s windows at each advance.

Reports rec/s, host fetches/batch and device dispatches/batch for
each; the acceptance criterion is ≥1.5× cascade/double ingest
throughput on the CPU grid (the double-ingest pays the whole fused
step twice — sort, fanout, fingerprint — per batch).

Part B — long-range query: a 1h span of per-second rows vs the
cascade's 1m tier, answered through the querier's tier routing
(`network` + interval(time, 60) → network_1m). Reports rows scanned
and wall time per query; the acceptance criterion is tier row count
≤ span/60 per series.

Protocol + committed CPU numbers: PERF.md §18 (on-chip columns
reserved). Knobs: CASCADEBENCH_BATCHES, CASCADEBENCH_BATCH,
CASCADEBENCH_TUPLES, CASCADEBENCH_ADV (batches per window advance),
CASCADEBENCH_REPS (interleaved reps, median reported),
CASCADEBENCH_CAP_LOG2, CASCADEBENCH_SPAN_S. Emits one JSON record on
the last stdout line (bench_all.py c10 re-emits it)."""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepflow_tpu.aggregator.pipeline import (  # noqa: E402
    DoubleIngestPipeline,
    DualGranularityPipeline,
    PipelineConfig,
)
from deepflow_tpu.aggregator.window import WindowConfig  # noqa: E402
from deepflow_tpu.datamodel.batch import FlowBatch  # noqa: E402
from deepflow_tpu.ingest.replay import SyntheticFlowGen  # noqa: E402
from deepflow_tpu.utils.spans import SPAN_INGEST_DISPATCH  # noqa: E402

T0 = 1_700_000_040


def _ingest_ab(n_batches: int, batch: int, tuples: int) -> dict:
    gen = SyntheticFlowGen(num_tuples=tuples, seed=7)
    # warmup stream compiles EVERY code path before timing — the fused
    # step, the capacity fold, the advance flush, and (for the cascade)
    # the tier fold + tier flush at a minute close; without it compile
    # seconds land inside the timing and swamp the A/B
    warm = [
        FlowBatch.from_records(gen.records(batch, t))
        for t in (T0, T0 + 1, T0 + 2, T0 + 30, T0 + 70, T0 + 71)
    ]
    # timed stream: the §14 feeder cadence — steady bucket-sized
    # batches, one window advance per `adv` batches, crossing a minute
    # boundary mid-run so the cascade's tier close cost is inside the
    # measurement
    adv = int(os.environ.get("CASCADEBENCH_ADV", "8"))
    t_base = T0 + 100
    batches = [
        FlowBatch.from_records(gen.records(batch, t_base + i // adv))
        for i in range(n_batches)
    ]
    # capacity holds the full doc-key space of a minute so neither
    # variant sheds — under overflow the two implementations
    # legitimately diverge (different rows survive) and the flushed-row
    # sanity check below would be meaningless
    cap = 1 << int(os.environ.get("CASCADEBENCH_CAP_LOG2", "14"))
    cfg = PipelineConfig(window=WindowConfig(capacity=cap), batch_size=batch)
    reps = int(os.environ.get("CASCADEBENCH_REPS", "3"))

    def run_once(name, mk):
        pipe = mk(cfg)
        for fb in warm:
            pipe.ingest(fb)
        t0 = time.perf_counter()
        docs = 0
        for fb in batches:
            docs += sum(db.size for _fl, db in pipe.ingest(fb))
        docs += sum(db.size for _fl, db in pipe.drain())
        dt = time.perf_counter() - t0
        if name == "double":
            fetches = (pipe.second.wm.host_fetches
                       + pipe.minute.wm.host_fetches)
            dispatches = (
                pipe.second.tracer.summary()[SPAN_INGEST_DISPATCH]["count"]
                + pipe.minute.tracer.summary()[SPAN_INGEST_DISPATCH]["count"]
            )
        else:
            fetches = pipe.pipe.wm.host_fetches
            dispatches = (
                pipe.pipe.tracer.summary()[SPAN_INGEST_DISPATCH]["count"]
            )
        n_total = len(warm) + n_batches
        return {
            "rec_s": round(batch * n_batches / dt, 1),
            "wall_s": round(dt, 3),
            "flushed_rows": docs,
            "host_fetches": fetches,
            "fetches_per_batch": round(fetches / n_total, 3),
            "dispatches_per_batch": round(dispatches / n_total, 3),
        }

    # interleave the variants and report each one's MEDIAN rec_s rep —
    # the build container's CPU is noisy (±30% rep-to-rep), and an A/B
    # where one variant eats a contention spike is not a measurement
    out = {}
    runs = {"double": [], "cascade": []}
    for _ in range(reps):
        for name, mk in (("double", DoubleIngestPipeline),
                         ("cascade", DualGranularityPipeline)):
            runs[name].append(run_once(name, mk))
    for name, rs in runs.items():
        rs.sort(key=lambda r: r["rec_s"])
        out[name] = {**rs[len(rs) // 2], "rec_s_reps": [r["rec_s"] for r in rs]}
    out["speedup_cascade_vs_double"] = round(
        out["cascade"]["rec_s"] / out["double"]["rec_s"], 3
    )
    return out


def _query_bench(span_s: int) -> dict:
    """1h-span range query at 1m step: 1s replay vs tier-selected."""
    from deepflow_tpu.querier.engine import QueryEngine
    from deepflow_tpu.storage.store import (
        ColumnarStore,
        ColumnSpec,
        TableSchema,
    )

    store = ColumnarStore()
    n_series = 8
    for name, iv in (("network_1s", 1), ("network_1m", 60)):
        store.create_table("flow_metrics", TableSchema(
            name,
            (ColumnSpec("time", "u4"), ColumnSpec("server_port", "u4"),
             ColumnSpec("byte_tx", "f4")),
            partition_s=3600,
        ))
        n = span_s // iv
        t = np.repeat(np.arange(n, dtype=np.uint32) * iv, n_series)
        store.insert("flow_metrics", name, {
            "time": t,
            "server_port": np.tile(
                np.arange(n_series, dtype=np.uint32), n
            ),
            "byte_tx": np.full(n * n_series, float(iv), np.float32),
        })
    eng = QueryEngine(store)
    sql_step = ("select interval(time, 60) as t, server_port, "
                "Sum(byte_tx) as b from {} group by t, server_port")
    out = {}
    for label, table, rows_scanned in (
        ("replay_1s", "network.1s", span_s * n_series),
        ("tier_1m", "network", (span_s // 60) * n_series),
    ):
        q = sql_step.format(table)
        eng.execute(q)  # warm the scan cache path
        t0 = time.perf_counter()
        res = eng.execute(q)
        out[label] = {
            "wall_ms": round((time.perf_counter() - t0) * 1e3, 2),
            "rows_scanned": rows_scanned,
            "result_rows": res.rows,
        }
    out["rows_ratio"] = round(
        out["replay_1s"]["rows_scanned"] / out["tier_1m"]["rows_scanned"], 1
    )
    out["speedup_tier_vs_replay"] = round(
        out["replay_1s"]["wall_ms"] / max(out["tier_1m"]["wall_ms"], 1e-3), 2
    )
    return out


def main():
    # defaults mirror the §14 feeder workload: ~2k active 5-tuples,
    # bucket-sized batches, ~4k records/s (one window advance per 8
    # batches of 512)
    n_batches = int(os.environ.get("CASCADEBENCH_BATCHES", "384"))
    batch = int(os.environ.get("CASCADEBENCH_BATCH", "512"))
    tuples = int(os.environ.get("CASCADEBENCH_TUPLES", "2000"))
    span_s = int(os.environ.get("CASCADEBENCH_SPAN_S", "3600"))
    out = {
        "bench": "cascadebench",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "n_batches": n_batches,
        "batch": batch,
        "tuples": tuples,
        "span_s": span_s,
    }
    try:
        out["ingest"] = _ingest_ab(n_batches, batch, tuples)
        out["query"] = _query_bench(span_s)
    except Exception as e:  # partial-JSON convention (bench.py stance)
        out["partial"] = True
        out["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(out))


if __name__ == "__main__":
    main()
