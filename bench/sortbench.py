#!/usr/bin/env python
"""Shared-sort A/B bench (ISSUE 17): the multi-sort oracle vs the
one-pass shared sort through the windowed raw-doc ingest path, at the
PERF.md §17 +top-K shape where the extra sorts dominate.

Per shape, the SAME seeded high-cardinality stream (the sketchbench
Zipf + scan generator) runs through a top-K-enabled WindowManager
twice — DEEPFLOW_SHARED_SORT=0 then =1 (the knob is read at dispatch
time, so one process can A/B honestly) — and the row records both
rates, the speedup, and a bit-parity digest of the first flushed
window's sketch block (the A/B is only meaningful if the outputs are
identical). Census-attributed sorts/dispatch for each mode ride along
from a small L4Pipeline probe (`telemetry()["profile"]["census"]` —
the r16 face), so the JSON embeds the sort counts the rewrite claims.

DEEPFLOW_FUSED_SKETCH stays OFF by default here: on CPU the kernel
runs in interpret mode — a parity artifact, not a perf path — and its
on-chip columns are reserved in PERF.md §25. SORTBENCH_FUSED=1 adds
the fused rows anyway (expect interpret-mode rates far below both XLA
modes on CPU).

Knobs: SORTBENCH_SHAPES="batch:stash,...", SORTBENCH_BATCHES,
SORTBENCH_KEYS, SORTBENCH_TOPK, SORTBENCH_FUSED. Emits one JSON record
on the last stdout line (bench_all.py c17 re-emits it); per-row records
stream to stderr."""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from sketchbench import _KeyGen, _doc_batch  # noqa: E402
from deepflow_tpu.aggregator.sketchplane import SketchConfig  # noqa: E402
from deepflow_tpu.aggregator.window import WindowConfig, WindowManager  # noqa: E402
from deepflow_tpu.ops.histogram import LogHistSpec  # noqa: E402

T0 = 1_700_000_000

MODES = {"multisort": "0", "onepass": "1"}


def _shapes() -> list[tuple[int, int]]:
    env = os.environ.get("SORTBENCH_SHAPES")
    if env:
        return [tuple(int(x) for x in s.split(":")) for s in env.split(",")]
    # the §17 +topk shapes where the per-hash-row sorts dominate
    return [(1 << 16, 1 << 13), (1 << 18, 1 << 13)]


def _sketch_config(k_top: int) -> SketchConfig:
    return SketchConfig(
        num_groups=8, hll_precision=14, cms_depth=4, cms_width=1 << 16,
        hist=LogHistSpec(bins=128, vmin=1.0, gamma=1.1),
        topk_rows=2,
        topk_cols=max(64, 1 << (max(k_top, 1) - 1).bit_length() + 3),
        pending=8,
    )


def _block_digest(flushed) -> str:
    """Stable digest of the first flushed window's exact rows + sketch
    block — the A/B's bit-parity cross-check."""
    import hashlib

    f0 = next((f for f in flushed if f.window_idx == T0), None)
    if f0 is None:
        return "no-window"
    h = hashlib.sha256()
    h.update(np.asarray(f0.key_hi).tobytes())
    if f0.sketches is not None:
        for lane in ("hll", "cms", "hist", "tk_votes", "tk_hi", "tk_lo"):
            h.update(np.asarray(getattr(f0.sketches, lane)).tobytes())
    return h.hexdigest()[:16]


def _run_mode(mode: str, batch: int, stash: int, batches: int,
              n_keys: int, k_top: int) -> dict:
    os.environ["DEEPFLOW_SHARED_SORT"] = MODES[mode]
    wm = WindowManager(WindowConfig(
        capacity=stash, delay=2, sketch=_sketch_config(k_top),
    ))
    gen = _KeyGen(np.random.default_rng(7), n_keys, 1.1)
    # warmup compiles the fused step outside the timed loop
    wk = _KeyGen(np.random.default_rng(1), n_keys, 1.1).batch(
        min(batch, 1 << 14))
    wm.ingest(*_doc_batch(wk, T0 - 100))
    wm.flush_all()

    flushed = []
    t_ingest = 0.0
    for _ in range(batches):
        b = _doc_batch(gen.batch(batch), T0)
        t0 = time.perf_counter()
        flushed += wm.ingest(*b)
        jax.block_until_ready(wm.acc.slot)
        t_ingest += time.perf_counter() - t0
    flushed += wm.flush_all()
    return {
        "mode": mode,
        "rec_s": batch * batches / t_ingest if t_ingest else 0.0,
        "digest": _block_digest(flushed),
        "sketch_rows": wm.get_counters()["sketch_rows"],
    }


def _census_sorts(k_top: int) -> dict:
    """Sorts/dispatch per mode from the census face on a small
    L4Pipeline probe — static jaxpr attribution, seconds of work."""
    from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig
    from deepflow_tpu.datamodel.batch import FlowBatch
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    out = {}
    for mode, env in MODES.items():
        os.environ["DEEPFLOW_SHARED_SORT"] = env
        pipe = L4Pipeline(PipelineConfig(
            window=WindowConfig(
                capacity=1 << 12,
                sketch=SketchConfig(
                    num_groups=4, hll_precision=7, cms_depth=2,
                    cms_width=256,
                    hist=LogHistSpec(bins=32, vmin=1.0, gamma=1.3),
                    topk_rows=2, topk_cols=64, pending=8,
                ),
            ),
            batch_size=256,
        ))
        gen = SyntheticFlowGen(num_tuples=100, seed=17)
        pipe.ingest(FlowBatch.from_records(gen.records(128, T0)))
        rows = [r for r in pipe.telemetry()["profile"]["census"]
                if r["step"] == "fused_step" and "sorts" in r]
        out[mode] = max((r["sorts"] for r in rows), default=None)
    return out


def main():
    batches = int(os.environ.get("SORTBENCH_BATCHES", "4"))
    n_keys = int(os.environ.get("SORTBENCH_KEYS", str(1 << 20)))
    k_top = int(os.environ.get("SORTBENCH_TOPK", "128"))
    with_fused = os.environ.get("SORTBENCH_FUSED", "0") == "1"
    rows = []
    err = None
    sorts = {}
    try:
        sorts = _census_sorts(k_top)
        modes = list(MODES)
        if with_fused:
            MODES["fused"] = "1"
            modes.append("fused")
        for batch, stash in _shapes():
            recs = {}
            for mode in modes:
                if mode == "fused":
                    os.environ["DEEPFLOW_FUSED_SKETCH"] = "1"
                r = _run_mode(mode, batch, stash, batches, n_keys, k_top)
                os.environ["DEEPFLOW_FUSED_SKETCH"] = "0"
                r.update(batch=batch, stash=stash,
                         sorts_per_dispatch=sorts.get(mode))
                recs[mode] = r
                print(json.dumps(r), file=sys.stderr, flush=True)
            speedup = recs["onepass"]["rec_s"] / max(
                recs["multisort"]["rec_s"], 1e-9)
            parity = recs["onepass"]["digest"] == recs["multisort"]["digest"]
            for r in recs.values():
                r["speedup_vs_multisort"] = round(
                    r["rec_s"] / max(recs["multisort"]["rec_s"], 1e-9), 3)
                r["bit_parity"] = parity
            rows.extend(recs.values())
            print(json.dumps({"batch": batch, "stash": stash,
                              "speedup": round(speedup, 3),
                              "bit_parity": parity}),
                  file=sys.stderr, flush=True)
    except Exception as e:  # partial-JSON convention (bench.py stance)
        err = repr(e)
    out = {
        "bench": "sortbench", "rows": rows,
        "sorts_per_dispatch": sorts,
        "n_keys": n_keys, "k_top": k_top, "batches_per_mode": batches,
        "backend": jax.default_backend(),
    }
    if err:
        out["partial"] = True
        out["error"] = err
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
