#!/usr/bin/env python
"""Frame-journal overhead probe (ISSUE 6 acceptance): the SAME
wire-to-window feeder workload as bench/feeder_probe.py, run journal-off
then journal-on (and journal-on + fsync-per-mark), so the A/B isolates
exactly what crash-safe ingest costs on the steady-state path — the
per-frame append (one buffered write + crc32) and the per-pump
mark+flush.

Usage: python bench/journal_probe.py [repo_root]   (default: parent)
Prints one JSON line with rec_s per mode, overhead %, and journal byte
accounting. Knobs: JOURNAL_ITERS, JOURNAL_BUCKETS (comma list),
JOURNAL_DIR (default: a tempdir; point at the real target volume for
honest fsync numbers). Protocol + committed numbers: PERF.md §16.
"""

import json
import os
import sys
import tempfile
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)
sys.path.insert(0, root)

from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig  # noqa: E402
from deepflow_tpu.aggregator.window import WindowConfig  # noqa: E402
from deepflow_tpu.feeder import (  # noqa: E402
    FeederConfig,
    FeederRuntime,
    FrameJournal,
    PipelineFeedSink,
    encode_flowbatch_frames,
)
from deepflow_tpu.ingest.queues import PyOverwriteQueue  # noqa: E402
from deepflow_tpu.ingest.replay import SyntheticFlowGen  # noqa: E402


def run_mode(steps, buckets, journal_path, fsync):
    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 14, stats_ring=4),
        batch_size=buckets[-1], bucket_sizes=buckets,
    ))
    journal = (
        FrameJournal(journal_path, fsync=fsync)
        if journal_path is not None else None
    )
    queues = [PyOverwriteQueue(1 << 12) for _ in range(4)]
    feeder = FeederRuntime(
        queues, PipelineFeedSink(pipe), FeederConfig(frames_per_queue=16),
        journal=journal,
    )
    gen = SyntheticFlowGen(num_tuples=2000, seed=0)
    t0 = 1_700_000_000
    for b in buckets:  # warm every bucket's compile path
        for fr in encode_flowbatch_frames(gen.flow_batch(b, t0), max_rows_per_frame=256):
            queues[0].put(fr)
        feeder.pump()
    if journal is not None:
        journal.rotate()  # time only the steady-state appends

    f0 = feeder.get_counters()
    start = time.perf_counter()
    for frames in steps:
        for j, fr in enumerate(frames):
            queues[j % 4].put(fr)
        feeder.pump()
    feeder.flush()
    pipe.drain()
    elapsed = time.perf_counter() - start
    f1 = feeder.get_counters()
    records = f1["records_in"] - f0["records_in"]
    out = {
        "rec_s": round(records / elapsed, 1),
        "elapsed_s": round(elapsed, 4),
        "records": records,
    }
    if journal is not None:
        jc = journal.get_counters()
        out["journal_frames"] = jc["frames"]
        out["journal_bytes"] = jc["bytes"]
        out["journal_marks"] = jc["marks"]
        out["bytes_per_record"] = round(jc["bytes"] / max(records, 1), 1)
        journal.close()
    return out


def main():
    iters = int(os.environ.get("JOURNAL_ITERS", 48))
    buckets = tuple(
        int(b) for b in os.environ.get("JOURNAL_BUCKETS", "256,512,1024").split(",")
    )
    gen = SyntheticFlowGen(num_tuples=2000, seed=0)
    t0 = 1_700_000_000
    sizes = [buckets[(i % len(buckets))] - (17 * i) % 64 for i in range(iters)]
    steps = [
        encode_flowbatch_frames(gen.flow_batch(n, t0 + 10 + i // 4),
                                agent_id=i, max_rows_per_frame=256)
        for i, n in enumerate(sizes)
    ]

    jdir = os.environ.get("JOURNAL_DIR")
    tmp = None
    if jdir is None:
        tmp = tempfile.TemporaryDirectory(prefix="dfj_")
        jdir = tmp.name
    try:
        # throwaway full run: the first pipeline in the process pays
        # one-time compile/alloc costs that would skew the A/B, then
        # best-of-2 per mode to shed host-jitter outliers
        run_mode(steps, buckets, None, False)

        def best(path, fsync):
            runs = [run_mode(steps, buckets, path, fsync) for _ in range(2)]
            return max(runs, key=lambda r: r["rec_s"])

        off = best(None, False)
        on = best(os.path.join(jdir, "probe.journal"), False)
        on_fsync = best(os.path.join(jdir, "probe_fsync.journal"), True)
        rec = {
            "journal_off": off,
            "journal_on": on,
            "journal_on_fsync": on_fsync,
            "overhead_pct": round(
                (off["rec_s"] / max(on["rec_s"], 1e-9) - 1.0) * 100, 2
            ),
            "overhead_fsync_pct": round(
                (off["rec_s"] / max(on_fsync["rec_s"], 1e-9) - 1.0) * 100, 2
            ),
            "iters": iters,
            "buckets": list(buckets),
        }
    except Exception as e:  # partial-but-parseable (bench contract)
        rec = {"error": repr(e), "partial": True}
    finally:
        if tmp is not None:
            tmp.cleanup()
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
