#!/usr/bin/env python
"""Host-ingest scale-out: N shared-nothing ingester worker PROCESSES
(one receiver + decoder pool + writer each — the reference's
multi-analyzer deployment, flow_metrics.go:55-61 + per-analyzer
processes), fed disjoint agent shards of one workload.

    python bench/e2e_scaleout.py [--procs 1 2 4] [--docs N]

Each worker is its own OS process with its own TCP receiver port; the
parent generates the doc frames once, shards them by agent id (the same
hash fanout the receiver applies internally), feeds every worker its
shard concurrently, and reports per-worker and aggregate docs/s.

HONESTY NOTE: this build container exposes ONE CPU core
(sched_getaffinity = 1), so aggregate throughput here measures
timesharing, not parallel speedup — the harness demonstrates the
shared-nothing property (no cross-process contention point: aggregate ≈
N × single ÷ N on one core, i.e. per-worker rate stays flat as N grows)
and records the per-core rate; on an M-core host the same harness is
the ≥Mx deployment shape. PERF.md carries the measured table.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import socket
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _prepare(docs_target: int, frame_docs: int, agents: int) -> list[tuple[int, bytes]]:
    """(agent_id, frame bytes) pairs — built once in the parent."""
    from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig
    from deepflow_tpu.aggregator.window import WindowConfig
    from deepflow_tpu.datamodel.batch import FlowBatch
    from deepflow_tpu.ingest.codec import encode_docbatch
    from deepflow_tpu.ingest.framing import FlowHeader, MessageType, encode_frame
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    pipe = L4Pipeline(PipelineConfig(window=WindowConfig(capacity=1 << 15), batch_size=4096))
    gen = SyntheticFlowGen(num_tuples=5_000, seed=0)
    t = 1_700_000_000
    docs = []
    while sum(d.size for d in docs) < docs_target:
        docs += pipe.ingest(FlowBatch.from_records(gen.records(4096, t)))
        t += 1
    docs += pipe.drain()
    msgs = []
    for db in docs:
        msgs += encode_docbatch(db, flags=1)
    msgs = msgs[:docs_target]
    frames = []
    for i in range(0, len(msgs), frame_docs):
        agent = 1 + (i // frame_docs) % agents
        h = FlowHeader(msg_type=int(MessageType.METRICS), agent_id=agent,
                       organization_id=1)
        frames.append((agent, encode_frame(h, msgs[i : i + frame_docs]),
                       len(msgs[i : i + frame_docs])))
    return frames


def _worker(port_q, result_q, warm_docs: int, n_docs_expected: int,
            n_decoders: int):
    """One shared-nothing ingester process. The parent first sends a
    warm shard (JAX import + enrich-kernel compile happen there); the
    timed region covers only the steady frames after `ready`."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import threading

    from deepflow_tpu.controller.resources import ResourceDB
    from deepflow_tpu.ingest.receiver import Receiver
    from deepflow_tpu.server.flow_metrics import FlowMetricsIngester

    class CountWriter:
        def __init__(self):
            self.docs = 0
            self.lock = threading.Lock()

        def put(self, batch):
            with self.lock:
                self.docs += int(batch.keep.sum())

    recv = Receiver()
    recv.start()
    writer = CountWriter()
    platform = ResourceDB().build_platform_table(1).build()
    ing = FlowMetricsIngester(
        recv, writer, platform_state=platform, n_workers=n_decoders,
        queue_capacity=1 << 15, prefer_native=True,
    )
    port_q.put(recv.tcp_port)
    deadline = time.time() + 600
    while writer.docs < warm_docs and time.time() < deadline:
        time.sleep(0.01)
    warm_seen = writer.docs  # may exceed warm_docs if the frame was resent
    result_q.put({"ready": True})
    # steady clock starts at the FIRST steady doc, not at `ready` —
    # the parent still has to drain every worker's handshake before it
    # feeds, and that idle gap must not deflate the rate
    while writer.docs <= warm_seen and time.time() < deadline:
        time.sleep(0.002)
    t0 = time.perf_counter()
    base = warm_seen
    while writer.docs < warm_seen + n_docs_expected and time.time() < deadline:
        time.sleep(0.005)
    dt = time.perf_counter() - t0
    result_q.put({"docs": writer.docs - base, "seconds": round(dt, 3)})
    ing.stop()
    recv.stop()


def run(n_procs: int, frames, total_docs: int) -> dict:
    ctx = mp.get_context("spawn")
    port_q = ctx.Queue()
    result_qs = [ctx.Queue() for _ in range(n_procs)]
    # shard frames by agent — the receiver-level hash fanout, applied
    # across processes (flow_metrics.go:55-61 at deployment scale)
    shards: list[list[bytes]] = [[] for _ in range(n_procs)]
    shard_docs = [0] * n_procs
    warm: list[tuple[bytes, int] | None] = [None] * n_procs
    for agent, frame, ndocs in frames:
        i = agent % n_procs
        if warm[i] is None:
            warm[i] = (frame, ndocs)
        else:
            shards[i].append(frame)
            shard_docs[i] += ndocs

    procs = []
    for i in range(n_procs):
        p = ctx.Process(
            target=_worker,
            args=(port_q, result_qs[i],
                  warm[i][1] if warm[i] is not None else 0, shard_docs[i], 2),
        )
        p.start()
        procs.append(p)
    ports = [port_q.get(timeout=300) for _ in procs]

    socks = [socket.create_connection(("127.0.0.1", port)) for port in ports]
    # warm phase: compiles + imports happen outside the timed region.
    # The warm frame is resent on timeout — worker startup on an
    # oversubscribed host can race the first delivery. A proc whose
    # shard is empty (more procs than agent ids) gets no warm frame and
    # reports 0 docs immediately.
    for s, w in zip(socks, warm):
        if w is not None:
            s.sendall(w[0])
    # NOTE: a ready timeout means the worker is still starting (TCP
    # already delivered the frame) — the resend is a last-resort nudge
    # whose duplicate docs are absorbed by the worker's warm_seen
    # baseline, not counted into the steady region
    for q, s, w in zip(result_qs, socks, warm):
        if w is None:
            continue
        for attempt in range(6):
            try:
                assert q.get(timeout=120).get("ready")
                break
            except Exception:
                if attempt == 5:
                    raise
                s.sendall(w[0])

    t0 = time.perf_counter()
    import threading

    def feed(sock, shard):
        sock.sendall(b"".join(shard))

    feeders = [threading.Thread(target=feed, args=(s, sh))
               for s, sh in zip(socks, shards)]
    for f in feeders:
        f.start()
    results = [q.get(timeout=600) for q in result_qs]
    dt = time.perf_counter() - t0
    for f in feeders:
        f.join()
    for s in socks:
        s.close()
    for p in procs:
        p.join(timeout=30)
    done = sum(r["docs"] for r in results)
    return {
        "n_procs": n_procs,
        "docs": done,
        "wall_s": round(dt, 3),
        "agg_docs_s": round(done / dt, 1),
        "per_proc_docs_s": [round(r["docs"] / max(r["seconds"], 1e-9), 1)
                            for r in results],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, nargs="+", default=[1, 2, 4])
    ap.add_argument("--docs", type=int, default=200_000)
    ap.add_argument("--frame-docs", type=int, default=256)
    args = ap.parse_args()
    frames = _prepare(args.docs, args.frame_docs, agents=16)
    total = sum(n for _, _, n in frames)
    print(f"prepared {total} docs in {len(frames)} frames", flush=True)
    rows = [run(n, frames, total) for n in args.procs]
    print(json.dumps({"cores": len(os.sched_getaffinity(0)), "rows": rows}),
          flush=True)


if __name__ == "__main__":
    main()
