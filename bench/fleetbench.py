#!/usr/bin/env python
"""Fleet telemetry plane bench (ISSUE 18 acceptance).

Three measurements, one JSON line at the end (bench contract:
partial-but-parseable on error):

1. **Ingest overhead** — the §14 wire-to-window feeder workload run
   passive vs with the FULL fleet export loop live (pipeline +
   freshness registered on a private collector; every 4th pump — the
   dashboard cadence — ticks a `FleetSink` that builds, encodes, and
   ships one frame over real TCP to a local `FleetAggregator`).
   Acceptance: overhead within noise; fetch parity itself is CI-gated
   deterministically in test_perf_gate::test_fleet_export_budget.

2. **Aggregator cost is O(hosts)** — merged-read latency
   (merged_counters + merged_hists + skew) swept over host count with
   fixed per-host lane content. The merge walks per-host SUMMARIES, so
   cost grows with hosts, and the sweep's per-host-normalized latency
   should stay ~flat.

3. **…not O(samples)** — one host's frame built from a span face that
   observed S samples, S swept ×64. Frame bytes and merge latency are
   bounded by the log-hist BIN count, not S: the ratio rows pin both
   near 1×.

Usage: python bench/fleetbench.py [repo_root]
Knobs: FLEETBENCH_ITERS (feeder pumps; default 64),
       FLEETBENCH_HOSTS (comma list; default 2,4,8,16).
Protocol + committed numbers: PERF.md §26.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)
sys.path.insert(0, root)

from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig  # noqa: E402
from deepflow_tpu.aggregator.window import WindowConfig  # noqa: E402
from deepflow_tpu.feeder import (  # noqa: E402
    FeederConfig,
    FeederRuntime,
    PipelineFeedSink,
    encode_flowbatch_frames,
)
from deepflow_tpu.fleet import (  # noqa: E402
    FleetAggregator,
    FleetExporter,
    FleetFrame,
    FleetSink,
    encode_fleet_frame,
)
from deepflow_tpu.ingest.queues import PyOverwriteQueue  # noqa: E402
from deepflow_tpu.ingest.replay import SyntheticFlowGen  # noqa: E402
from deepflow_tpu.utils.provenance import bench_provenance  # noqa: E402

ITERS = int(os.environ.get("FLEETBENCH_ITERS", "64"))
HOSTS = tuple(
    int(x) for x in os.environ.get("FLEETBENCH_HOSTS", "2,4,8,16").split(",")
)
BUCKETS = (64, 128, 256)
T0 = 1_700_000_000


def run_mode(fleet: bool) -> dict:
    from deepflow_tpu.tracing.lineage import FreshnessTracker
    from deepflow_tpu.utils.stats import StatsCollector

    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 14, stats_ring=4),
        batch_size=BUCKETS[-1], bucket_sizes=BUCKETS,
    ))
    q = PyOverwriteQueue(1 << 10)
    feeder = FeederRuntime(
        [q], PipelineFeedSink(pipe), FeederConfig(frames_per_queue=8),
        name="fleetbench",
    )
    agg = sink = col = None
    if fleet:
        agg = FleetAggregator(expiry_s=3600.0, autoregister=False).start()
        col = StatsCollector()
        fresh = FreshnessTracker(autoregister=False)
        col.register("tpu_pipeline", pipe, group="0")
        exporter = FleetExporter(
            "bench-host", group="0", collector=col,
            hist_faces={"fresh": fresh},
        )
        sink = FleetSink(agg.endpoint(), exporter)
        col.add_sink(sink)

    gen = SyntheticFlowGen(num_tuples=200, seed=47)

    def pump(t):
        fb = gen.flow_batch(128, t)
        for fr in encode_flowbatch_frames(fb, max_rows_per_frame=64):
            q.put(fr)
        return feeder.pump()

    rows = 0
    for t in (T0, T0 + 1):  # warmup: bucket compiles
        rows += sum(int(d.size) for d in pump(t))
    rows = 0
    t_start = time.perf_counter()
    for i in range(ITERS):
        t = T0 + 2 + i // 4
        rows += sum(int(d.size) for d in pump(t))
        if fleet and i % 4 == 3:  # dashboard cadence, profbench's §21
            col.tick(float(t))
    rows += sum(int(d.size) for d in feeder.flush())
    wall = time.perf_counter() - t_start
    out = {"rec_s": round(rows / wall, 1), "rows": rows,
           "wall_s": round(wall, 4)}
    if fleet:
        assert sink.flush(30)
        sc = sink.get_counters()
        deadline = time.time() + 30
        while (agg.counters["frames_rx"] < sc["frames_sent"]
               and time.time() < deadline):
            time.sleep(0.01)
        out["frames_sent"] = sc["frames_sent"]
        out["frame_bytes_avg"] = round(
            sc["bytes_sent"] / max(sc["frames_sent"], 1), 1
        )
        out["frames_rx"] = agg.counters["frames_rx"]
        out["send_errors"] = sc["send_errors"]
        sink.close()
        agg.stop()
    return out


def synth_frame(host: str, n_lanes: int = 4, bins: int = 64,
                n_fields: int = 16) -> FleetFrame:
    """Fixed-size per-host summary: the merge-cost sweeps hold lane
    content constant so the only variable is what each sweep varies."""
    return FleetFrame(
        host=host, group="0", epoch=0, seq=0, timestamp=float(T0),
        points=((float(T0), "tpu_mesh_swm", {"group": "0"},
                 {f"f{i}": i * 3 + 1 for i in range(n_fields)}),),
        hists={"g0": {
            f"lane{j}": [[b, b + 1] for b in range(bins)]
            for j in range(n_lanes)
        }},
    )


def merge_read_ms(agg, reps: int = 50) -> float:
    t0 = time.perf_counter()
    for _ in range(reps):
        agg.merged_counters()
        agg.merged_hists()
        agg.skew()
    return (time.perf_counter() - t0) / reps * 1e3


def host_scaling() -> list[dict]:
    rows = []
    for n in HOSTS:
        agg = FleetAggregator(expiry_s=3600.0, autoregister=False)
        for h in range(n):
            agg.ingest(synth_frame(f"host{h}"))
        ms = merge_read_ms(agg)
        rows.append({"hosts": n, "merge_read_ms": round(ms, 4),
                     "ms_per_host": round(ms / n, 5)})
    return rows


def sample_independence() -> list[dict]:
    """Same host, the span face fed S vs 64·S samples: frame bytes and
    merge cost must track the BIN count, not S."""
    from deepflow_tpu.utils.spans import SpanTracer

    rows = []
    for s in (2_000, 128_000):
        tr = SpanTracer()
        for i in range(s):
            tr.record("stage", 10 + (i % 500))
        exp = FleetExporter("hostS", group="0",
                            hist_faces={"spans": tr},
                            clock=lambda: float(T0))
        frame = exp.build(points=[])
        nbytes = len(encode_fleet_frame(frame))
        agg = FleetAggregator(expiry_s=3600.0, autoregister=False)
        agg.ingest(frame)
        rows.append({
            "samples": s, "frame_bytes": nbytes,
            "hist_bins_nonzero": sum(
                len(v) for v in frame.hists["spans"].values()
            ),
            "merge_read_ms": round(merge_read_ms(agg), 4),
        })
    return rows


def main() -> dict:
    run_mode(fleet=False)  # throwaway: heat the process-wide jit cache
    passive = run_mode(fleet=False)
    fleet = run_mode(fleet=True)
    overhead = (passive["rec_s"] / max(fleet["rec_s"], 1e-9) - 1.0) * 100
    hosts_rows = host_scaling()
    samples_rows = sample_independence()
    lo, hi = hosts_rows[0], hosts_rows[-1]
    srow_lo, srow_hi = samples_rows[0], samples_rows[-1]
    return {
        "iters": ITERS,
        "passive": passive,
        "fleet": fleet,
        "overhead_pct": round(overhead, 2),
        "hosts_rows": hosts_rows,
        # O(hosts) statement: read latency normalized per host is flat
        "per_host_ms_ratio": round(
            hi["ms_per_host"] / max(lo["ms_per_host"], 1e-9), 3
        ),
        "samples_rows": samples_rows,
        # O(samples) independence: 64× the samples, ~1× the cost/bytes
        "samples_ratio": srow_hi["samples"] / srow_lo["samples"],
        "frame_bytes_ratio": round(
            srow_hi["frame_bytes"] / max(srow_lo["frame_bytes"], 1), 3
        ),
        "merge_ms_ratio": round(
            srow_hi["merge_read_ms"] / max(srow_lo["merge_read_ms"], 1e-9), 3
        ),
        "provenance": bench_provenance(),
    }


if __name__ == "__main__":
    try:
        rec = main()
    except Exception as e:  # partial-but-parseable (bench contract)
        rec = {"error": repr(e), "partial": True}
    print(json.dumps(rec), flush=True)
