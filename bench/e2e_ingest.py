#!/usr/bin/env python
"""Receiver→decode→enrich e2e throughput (docs/s) — the server ingest
path around the kernel bench (VERDICT r3 #7: e2e must stay within ~3x
of the kernel-only number). Run from repo root:

    python bench/e2e_ingest.py [--cpu] [--docs N]

Pumps pre-encoded METRICS frames through a real TCP socket into the
batched unmarshaller (decode → device enrich → writer), then reports
documents/second end to end.
"""

from __future__ import annotations

import argparse
import sys
import threading
import time

if "--cpu" in sys.argv:
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, ".")

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--docs", type=int, default=200_000)
    p.add_argument("--frame-docs", type=int, default=256)
    p.add_argument("--workers", type=int, default=4)
    # frames spread across N agent ids — the receiver hash-fans by
    # agent, so one lone agent would serialize onto one decode queue
    p.add_argument("--agents", type=int, default=8)
    args = p.parse_args()

    from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig
    from deepflow_tpu.aggregator.window import WindowConfig
    from deepflow_tpu.controller.resources import ResourceDB
    from deepflow_tpu.datamodel.batch import FlowBatch
    from deepflow_tpu.ingest.codec import encode_docbatch
    from deepflow_tpu.ingest.framing import FlowHeader, MessageType, encode_frame
    from deepflow_tpu.ingest.receiver import Receiver
    from deepflow_tpu.ingest.replay import SyntheticFlowGen
    from deepflow_tpu.server.flow_metrics import FlowMetricsIngester

    # 1. produce realistic doc frames once (agent-side pipeline output)
    pipe = L4Pipeline(PipelineConfig(window=WindowConfig(capacity=1 << 15), batch_size=4096))
    gen = SyntheticFlowGen(num_tuples=5_000, seed=0)
    t0 = 1_700_000_000
    docs = []
    t = t0
    while sum(d.size for d in docs) < args.docs:
        docs += pipe.ingest(FlowBatch.from_records(gen.records(4096, t)))
        t += 1
    docs += pipe.drain()
    msgs = []
    for db in docs:
        msgs += encode_docbatch(db, flags=1)
    msgs = msgs[: args.docs]
    frames = []
    for i in range(0, len(msgs), args.frame_docs):
        h = FlowHeader(
            msg_type=int(MessageType.METRICS),
            agent_id=1 + (i // args.frame_docs) % args.agents,
            organization_id=1,
        )
        frames.append(encode_frame(h, msgs[i : i + args.frame_docs]))
    payload = b"".join(frames)
    print(f"prepared {len(msgs)} docs in {len(frames)} frames "
          f"({len(payload) / 1e6:.1f} MB)", flush=True)

    # 2. server side: receiver → batched unmarshaller → counting writer
    class CountWriter:
        def __init__(self):
            self.docs = 0
            self.lock = threading.Lock()

        def put(self, batch):
            with self.lock:
                self.docs += int(batch.keep.sum())

    recv = Receiver()
    recv.start()
    writer = CountWriter()
    platform = ResourceDB().build_platform_table(1).build()
    ing = FlowMetricsIngester(
        recv, writer, platform_state=platform, n_workers=args.workers,
        queue_capacity=1 << 15, prefer_native=not args.cpu,
    )

    # warm the enrich kernel compile out of the timed region
    import socket

    s = socket.create_connection(("127.0.0.1", recv.tcp_port))
    s.sendall(frames[0])
    deadline = time.time() + 120
    while writer.docs == 0 and time.time() < deadline:
        time.sleep(0.01)
    base = writer.docs

    t_start = time.perf_counter()
    s.sendall(payload)
    want = base + len(msgs)
    deadline = time.time() + 300
    while writer.docs < want and time.time() < deadline:
        time.sleep(0.005)
    dt = time.perf_counter() - t_start
    s.close()

    done = writer.docs - base
    print(f"e2e: {done} docs in {dt:.2f}s = {done / dt / 1e6:.3f} M docs/s "
          f"(counters: {ing.get_counters()})")
    ing.stop()
    recv.stop()


if __name__ == "__main__":
    main()
