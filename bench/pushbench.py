#!/usr/bin/env python
"""Push query plane A/B (ISSUE 11 acceptance): dashboard-storm fan-out
amplification + flush→watcher invalidation latency.

One JSON line with two measurements:

  * **fanout**: ONE subscribed PromQL query over a live open-window
    overlay (512 flow series), fanned out to W watchers, driven by E
    window-close events. Per watcher count: evaluations (must be E —
    one per event, NEVER per watcher), deliveries (E×W), amplification
    (deliveries/evals == W), evals/sec, deliveries/sec, and the
    flush→delivery latency (publish-to-first-watcher and
    publish-to-last-watcher, ms) — the push plane's answer to "how
    stale is a dashboard after a window closes". The acceptance shape
    is W ≥ 100 from a SINGLE evaluation per event.
  * **pinned**: the last delivered result compared bit-exact against a
    fresh pull evaluation of the same query at the same instant
    (cache=False) — push-invalidated results never serve a stale row.

The alert lane rides along: a threshold rule on the same metric
evaluated on the same events, with its eval latency recorded.

Usage: python bench/pushbench.py [repo_root]
Knobs: PUSHBENCH_WATCHERS (comma list, default "1,10,100"),
PUSHBENCH_EVENTS, PUSHBENCH_FLOWS. CPU-container numbers; on-chip
columns pending per the measurement-debt item (PERF.md §20).
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)
sys.path.insert(0, root)

T0 = 1_700_000_000


def _stack(n_flows):
    import numpy as np

    from deepflow_tpu.aggregator.window import WindowConfig, WindowManager
    from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
    from deepflow_tpu.integration.dfstats import (
        DEEPFLOW_SYSTEM_DB,
        DEEPFLOW_SYSTEM_TABLE,
        PipelineLiveSource,
        ensure_system_table,
    )
    from deepflow_tpu.querier.events import QueryEventBus
    from deepflow_tpu.querier.live import LiveRegistry, QueryResultCache
    from deepflow_tpu.storage.store import ColumnarStore

    store = ColumnarStore()
    ensure_system_table(store)
    reg = LiveRegistry()
    wm = WindowManager(WindowConfig(capacity=1 << 12, min_snapshot_interval=0.0))
    reg.register(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE,
                 PipelineLiveSource(wm))
    bus = QueryEventBus(name="pushbench")
    cache = QueryResultCache(max_entries=64)
    cache.attach_bus(bus)

    def ingest(t):
        meters = np.zeros((FLOW_METER.num_fields, n_flows), np.float32)
        meters[FLOW_METER.index("byte_tx")] = 64.0
        wm.ingest(
            np.full(n_flows, t, np.uint32),
            np.arange(n_flows, dtype=np.uint32),
            np.arange(n_flows, dtype=np.uint32),
            np.zeros((TAG_SCHEMA.num_fields, n_flows), np.uint32), meters,
            np.ones(n_flows, bool),
        )
        wm.snapshot_open(force=True)

    return store, reg, wm, bus, cache, ingest


def _run_fanout(watchers, events, n_flows):
    from deepflow_tpu.integration.dfstats import (
        DEEPFLOW_SYSTEM_DB,
        DEEPFLOW_SYSTEM_TABLE,
        LIVE_METRIC_FLOW_BYTES,
    )
    from deepflow_tpu.querier.alerts import AlertEngine, AlertRule
    from deepflow_tpu.querier.events import WindowClosed
    from deepflow_tpu.querier.promql import query_range
    from deepflow_tpu.querier.subscribe import SubscriptionManager

    store, reg, wm, bus, cache, ingest = _stack(n_flows)
    subs = SubscriptionManager(store, live=reg, cache=cache, bus=bus,
                               name=f"pushbench{watchers}")
    SPAN, STEP = 4, 1
    stamp = {"t": 0.0}
    first_lat, last_lat = [], []
    results = []

    def make_cb(i):
        if i == 0:
            def cb(r, s):
                first_lat.append(time.perf_counter() - stamp["t"])
                results.append(r)
            return cb
        if i == watchers - 1:
            return lambda r, s: last_lat.append(
                time.perf_counter() - stamp["t"]
            )
        return lambda r, s: None

    sub = None
    for i in range(watchers):
        sub, _ = subs.subscribe_promql(
            LIVE_METRIC_FLOW_BYTES, span_s=SPAN, step=STEP,
            db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE,
            callback=make_cb(i),
        )
    alerts = AlertEngine(store, live=reg, bus=bus, name=f"pb{watchers}",
                         log_sink=False)
    alerts.add_rule(AlertRule(
        name="hot", query=LIVE_METRIC_FLOW_BYTES, comparator=">",
        threshold=1.0, for_s=0,
    ))

    # warmup eval (compile nothing, but fault in the code paths)
    ingest(T0)
    bus.publish(WindowClosed(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, T0))
    ev0, first_lat[:], last_lat[:], results[:] = sub.evals, [], [], []

    t_start = time.perf_counter()
    for i in range(events):
        t = T0 + 1 + i
        ingest(t)
        stamp["t"] = time.perf_counter()
        bus.publish(WindowClosed(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, t))
    elapsed = time.perf_counter() - t_start

    evals = sub.evals - ev0
    sc = subs.get_counters()
    # the bit-exact pin: last delivered == fresh pull at the same now
    fresh = query_range(
        store, LIVE_METRIC_FLOW_BYTES, sub.last_now - SPAN, sub.last_now,
        STEP, db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE, live=reg,
        cache=False,
    )
    pinned = bool(results) and results[-1] == fresh and bool(fresh)
    lat_ms = lambda xs: round(sum(xs) / max(1, len(xs)) * 1e3, 3)
    return {
        "watchers": watchers,
        "events": events,
        "evals": evals,
        "deliveries": evals * watchers if sc["watcher_errors"] == 0 else None,
        "amplification": round(sc["deliveries"] / max(1, sc["evals"]), 1),
        "evals_per_s": round(evals / elapsed, 1),
        "deliveries_per_s": round(evals * watchers / elapsed, 1),
        "publish_to_first_watcher_ms": lat_ms(first_lat),
        "publish_to_last_watcher_ms": lat_ms(
            last_lat if watchers > 1 else first_lat
        ),
        "series": len(fresh),
        "pinned_bit_exact": pinned,
        "alert_state": alerts.state("hot"),
        "cache": cache.get_counters(),
    }


def main():
    watcher_counts = [
        int(w) for w in os.environ.get("PUSHBENCH_WATCHERS", "1,10,100").split(",")
    ]
    events = int(os.environ.get("PUSHBENCH_EVENTS", 32))
    n_flows = int(os.environ.get("PUSHBENCH_FLOWS", 512))
    try:
        rows = [_run_fanout(w, events, n_flows) for w in watcher_counts]
        rec = {
            "bench": "pushbench",
            "events": events,
            "flows": n_flows,
            "rows": rows,
        }
    except Exception as e:  # parseable partial record, never a traceback
        rec = {"bench": "pushbench", "partial": True, "error": repr(e)}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
