#!/usr/bin/env python
"""Round-4 kernel decision microbenchmark — run ON CHIP before any rewrite.

Measures, at bench shape (16k flows -> 65,536 fanout doc rows merged into a
65,536-row stash => 131,072 sort rows), every candidate for the group-by
hot loop:

  sort4          pure lax.sort of 4 u32 lanes (the floor of any sort design)
  r2_rowmajor    round-2 kernel: sort + cumsum seg-ids + segment_sum/max,
                 row-major [N, M] payloads
  r3_scan        round-3 kernel: sort + segmented associative_scan,
                 column-major [M, N] (the shipped regression)
  hybrid_col     col-major layout kept, reduction via transpose +
                 segment_sum/max (VERDICT option c)
  scatter_add    unsorted segment_sum [N,M] -> [H,M] (hash-stash cost model:
                 the per-batch meter accumulate)
  probe8         8 unrolled gather+compare probes over a 131k-slot table
                 (hash-stash lookup cost)
  claim_min      scatter-min slot claim (hash-stash insert-round cost)

Each prints compile time and steady-state ms. Writes PERF entries to stdout;
copy results into PERF.md.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

N_DOC = 1 << 16      # fanout doc rows per batch (16k flows x 4 lanes)
S = 1 << 16          # stash capacity
N_SORT = N_DOC + S   # rows in the per-batch merge sort today
H = 1 << 17          # hash table slots (load 0.5 at 64k keys)
T = 40               # tag columns (approx TAG_SCHEMA)
M = 17               # meter columns (FLOW_METER)
SUM_COLS = np.arange(0, 13, dtype=np.int32)
MAX_COLS = np.arange(13, 17, dtype=np.int32)


def timeit(name, fn, *args, iters=20):
    t0 = time.perf_counter()
    out = jax.block_until_ready(fn(*args))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    ms = (time.perf_counter() - t0) / iters * 1e3
    print(f"{name:16s} compile {compile_s:7.2f}s   steady {ms:9.3f} ms")
    return ms


def make_inputs(n, seed=0):
    rng = np.random.default_rng(seed)
    nkeys = 40_000
    kid = rng.integers(0, nkeys, n)
    uniq_hi = rng.integers(0, 2**32, nkeys, dtype=np.uint64).astype(np.uint32)
    uniq_lo = rng.integers(0, 2**32, nkeys, dtype=np.uint64).astype(np.uint32)
    slot = jnp.asarray(np.full(n, 7, np.uint32))
    hi = jnp.asarray(uniq_hi[kid])
    lo = jnp.asarray(uniq_lo[kid])
    tags_r = jnp.asarray(rng.integers(0, 1 << 16, (n, T)).astype(np.uint32))
    meters_r = jnp.asarray(rng.random((n, M)).astype(np.float32))
    valid = jnp.asarray(np.ones(n, bool))
    return slot, hi, lo, tags_r, meters_r, valid


@jax.jit
def sort4(slot, hi, lo):
    iota = jnp.arange(slot.shape[0], dtype=jnp.int32)
    return lax.sort((slot, hi, lo, iota), num_keys=3)


@jax.jit
def r2_rowmajor(slot, hi, lo, tags, meters, valid):
    n = slot.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    s_slot, s_hi, s_lo, perm = lax.sort((slot, hi, lo, iota), num_keys=3)
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (s_slot[1:] != s_slot[:-1]) | (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1])]
    )
    seg_id = jnp.cumsum(first.astype(jnp.int32)) - 1
    ms_sorted = jnp.take(meters, perm, axis=0)
    a = jax.ops.segment_sum(ms_sorted[:, SUM_COLS], seg_id, num_segments=n,
                            indices_are_sorted=True)
    b = jax.ops.segment_max(ms_sorted[:, MAX_COLS], seg_id, num_segments=n,
                            indices_are_sorted=True)
    rep = jax.ops.segment_min(iota, seg_id, num_segments=n, indices_are_sorted=True)
    rep = jnp.where(rep >= n, 0, rep)
    tags_out = jnp.take(tags, jnp.take(perm, rep), axis=0)
    return a, b, tags_out, jnp.take(s_slot, rep)


@jax.jit
def hybrid_col(slot, hi, lo, tags_t, meters_t, valid):
    n = slot.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    s_slot, s_hi, s_lo, perm = lax.sort((slot, hi, lo, iota), num_keys=3)
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (s_slot[1:] != s_slot[:-1]) | (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1])]
    )
    seg_id = jnp.cumsum(first.astype(jnp.int32)) - 1
    ms_sorted = jnp.take(meters_t, perm, axis=1)  # [M, N] lane gather
    row = ms_sorted.T  # [N, M]
    a = jax.ops.segment_sum(row[:, SUM_COLS], seg_id, num_segments=n,
                            indices_are_sorted=True)
    b = jax.ops.segment_max(row[:, MAX_COLS], seg_id, num_segments=n,
                            indices_are_sorted=True)
    rep = jax.ops.segment_min(iota, seg_id, num_segments=n, indices_are_sorted=True)
    rep = jnp.where(rep >= n, 0, rep)
    tags_out = jnp.take(tags_t, jnp.take(perm, rep), axis=1)
    return a.T, b.T, tags_out, jnp.take(s_slot, rep)


@jax.jit
def scatter_add(meters, ids):
    return jax.ops.segment_sum(meters, ids, num_segments=H)


@jax.jit
def scatter_max(meters, ids):
    return jax.ops.segment_max(meters, ids, num_segments=H)


@jax.jit
def probe8(t_hi, t_lo, t_fill, hi, lo):
    mask = jnp.uint32(H - 1)
    idx = (hi * jnp.uint32(0x9E3779B9) ^ lo) & mask
    value = jnp.full(hi.shape, jnp.uint32(0xFFFFFFFF))
    found = jnp.zeros(hi.shape, bool)
    for p in range(8):
        s = (idx + jnp.uint32(p)) & mask
        hit = t_fill[s] & (t_hi[s] == hi) & (t_lo[s] == lo) & ~found
        value = jnp.where(hit, s.astype(jnp.uint32), value)
        found |= hit
    return value, found


@jax.jit
def claim_min(cand, rowid):
    claims = jnp.full((H,), jnp.int32(2**31 - 1))
    claims = claims.at[cand].min(rowid)
    won = claims[cand] == rowid
    return claims, won


def main():
    print(f"device: {jax.devices()[0]}")
    for n in (1 << 15, N_SORT):
        print(f"--- shape N={n} ---")
        slot, hi, lo, tags_r, meters_r, valid = make_inputs(n)
        tags_t = jnp.asarray(np.asarray(tags_r).T.copy())
        meters_t = jnp.asarray(np.asarray(meters_r).T.copy())
        timeit("sort4", sort4, slot, hi, lo)
        timeit("r2_rowmajor", r2_rowmajor, slot, hi, lo, tags_r, meters_r, valid)
        timeit("hybrid_col", hybrid_col, slot, hi, lo, tags_t, meters_t, valid)
        if n <= 1 << 15:
            from deepflow_tpu.ops.segment import groupby_reduce

            def r3(slot, hi, lo, tags_t, meters_r, valid):
                return groupby_reduce(slot, hi, lo, tags_t, meters_r, valid,
                                      SUM_COLS, MAX_COLS)

            timeit("r3_scan", jax.jit(r3), slot, hi, lo, tags_t, meters_r, valid)

    print(f"--- hash-stash cost model (N={N_DOC}, H={H}) ---")
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, H, N_DOC).astype(np.int32))
    meters = jnp.asarray(rng.random((N_DOC, M)).astype(np.float32))
    timeit("scatter_add", scatter_add, meters, ids)
    timeit("scatter_max", scatter_max, meters, ids)
    t_hi = jnp.asarray(rng.integers(0, 2**32, H, dtype=np.uint64).astype(np.uint32))
    t_lo = jnp.asarray(rng.integers(0, 2**32, H, dtype=np.uint64).astype(np.uint32))
    t_fill = jnp.asarray(rng.random(H) < 0.5)
    hi = jnp.asarray(rng.integers(0, 2**32, N_DOC, dtype=np.uint64).astype(np.uint32))
    lo = jnp.asarray(rng.integers(0, 2**32, N_DOC, dtype=np.uint64).astype(np.uint32))
    timeit("probe8", probe8, t_hi, t_lo, t_fill, hi, lo)
    cand = jnp.asarray(rng.integers(0, H, N_DOC).astype(np.int32))
    rowid = jnp.arange(N_DOC, dtype=jnp.int32)
    timeit("claim_min", claim_min, cand, rowid)


def main_big():
    """Fold-cost scaling: the accumulate-then-fold design needs sort+reduce
    cost at accumulator scale (512k-4M rows) and the append cost."""
    print(f"device: {jax.devices()[0]}")

    @jax.jit
    def append(buf_t, buf_m, new_t, new_m, off):
        return (lax.dynamic_update_slice(buf_t, new_t, (0, off)),
                lax.dynamic_update_slice(buf_m, new_m, (0, off)))

    rng = np.random.default_rng(2)
    big_t = jnp.zeros((T, 1 << 20), jnp.uint32)
    big_m = jnp.zeros((M, 1 << 20), jnp.float32)
    new_t = jnp.asarray(rng.integers(0, 1 << 16, (T, N_DOC)).astype(np.uint32))
    new_m = jnp.asarray(rng.random((M, N_DOC)).astype(np.float32))
    timeit("append_65k", append, big_t, big_m, new_t, new_m, jnp.int32(0))

    for n in (int(sys.argv[1]) if sys.argv[1].isdigit() else 1 << 19,):
        print(f"--- fold shape N={n} ---")
        slot, hi, lo, tags_r, meters_r, valid = make_inputs(n, seed=3)
        timeit("sort4", sort4, slot, hi, lo, iters=5)
        timeit("r2_rowmajor", r2_rowmajor, slot, hi, lo, tags_r, meters_r, valid, iters=5)

if __name__ == "__main__":
    main_big() if sys.argv[-1] == "big" else main()
