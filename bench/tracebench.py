#!/usr/bin/env python
"""Window lineage tracing + freshness plane overhead probe (ISSUE 13
acceptance): the SAME wire-to-window feeder workload as
bench/feeder_probe.py, run passive versus with the FULL lineage stack
attached — receiver-admission stamps, feeder pump/journal context,
staged-upload + dispatch binding, advance/flush/store hops, per-tier
freshness lags — plus an aggressive consumer that drains span rows,
reads the lag lanes + exemplars and assembles a live trace tree every
4th pump (the §19/§21 dashboard cadence). The A/B isolates what the
tracing plane costs steady-state ingest; fetch parity itself is
CI-gated deterministically in
test_perf_gate.py::test_lineage_tracing_budget.

Also measured: span-row volume (rows exported per window / per 1k
records — the l7_flow_log lane cost of tracing yourself) and the
pull-path latencies dfctl trace window serves (live assemble, exported
query_trace).

Usage: python bench/tracebench.py [repo_root]   (default: parent)
Knobs: TRACEBENCH_ITERS, TRACEBENCH_BUCKETS (comma list).
Protocol + committed numbers: PERF.md §22, TRACEBENCH_r01.json.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)
sys.path.insert(0, root)

from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig  # noqa: E402
from deepflow_tpu.aggregator.window import WindowConfig  # noqa: E402
from deepflow_tpu.feeder import (  # noqa: E402
    FeederConfig,
    FeederRuntime,
    PipelineFeedSink,
    encode_flowbatch_frames,
)
from deepflow_tpu.ingest.queues import PyOverwriteQueue  # noqa: E402
from deepflow_tpu.ingest.replay import SyntheticFlowGen  # noqa: E402


def run_mode(steps, buckets, traced: bool):
    from deepflow_tpu.integration.dfstats import docbatch_window_sink
    from deepflow_tpu.storage.store import ColumnarStore
    from deepflow_tpu.tracing.builder import TraceTreeBuilder
    from deepflow_tpu.tracing.lineage import (
        FreshnessTracker,
        LineageTracker,
        query_window_trace,
    )

    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 14, stats_ring=4),
        batch_size=buckets[-1], bucket_sizes=buckets,
    ))
    lin = fresh = store = wsink = builder = None
    span_rows = 0
    if traced:
        fresh = FreshnessTracker(autoregister=False)
        lin = LineageTracker("tpu.pipeline", 1, freshness=fresh,
                             name="tracebench")
        pipe.attach_lineage(lin)
        store = ColumnarStore()
        wsink = docbatch_window_sink(store, lineage=lin)
        builder = TraceTreeBuilder(
            store, close_after_s=0.0, writer_args={"flush_interval_s": 0.01}
        )
    queues = [PyOverwriteQueue(1 << 12) for _ in range(4)]
    feeder = FeederRuntime(
        queues, PipelineFeedSink(pipe), FeederConfig(frames_per_queue=16),
        lineage=lin,
    )
    gen = SyntheticFlowGen(num_tuples=2000, seed=0)
    t0 = 1_700_000_000
    for b in buckets:  # warm every bucket's compile path
        for fr in encode_flowbatch_frames(gen.flow_batch(b, t0),
                                          max_rows_per_frame=256):
            queues[0].put(fr)
        feeder.pump()

    f0 = feeder.get_counters()
    windows = 0
    start = time.perf_counter()
    for i, frames in enumerate(steps):
        for j, fr in enumerate(frames):
            queues[j % 4].put(fr)
        out = feeder.pump()
        windows += len(out)
        if traced:
            if out:
                wsink(out)
            if (i + 1) % 4 == 0:
                # the dashboard cadence: EXPORT span rows into the
                # store's l7 lane (the real dogfood path — a bare
                # drain would discard the exactly-once rows), read the
                # lag lanes + exemplars, assemble one live tree
                span_rows += lin.export_store(store, builder=builder)
                fresh.get_counters()
                fresh.exemplars()
                lin.assemble(t0 + 10 + i // 4)
    out = feeder.flush()
    out += pipe.drain()
    windows += len(out)
    if traced and out:
        wsink(out)
    elapsed = time.perf_counter() - start
    f1 = feeder.get_counters()
    records = f1["records_in"] - f0["records_in"]
    rec = {
        "rec_s": round(records / elapsed, 1),
        "elapsed_s": round(elapsed, 4),
        "records": records,
        "windows": windows,
        "host_fetches": pipe.get_counters()["host_fetches"],
        "jit_retraces": pipe.get_counters()["jit_retraces"],
    }
    if traced:
        span_rows += lin.export_store(store, builder=builder)
        rec["span_rows"] = span_rows
        rec["span_rows_per_window"] = round(span_rows / max(windows, 1), 2)
        rec["span_rows_per_1k_records"] = round(
            span_rows * 1000.0 / max(records, 1), 2
        )
        rec["freshness"] = {
            k: v for k, v in fresh.get_counters().items()
            if k.endswith(("_lag_ms", "_samples"))
        }
        # pull-path latencies the REST/dfctl surface serves
        t = time.perf_counter()
        lin.assemble(t0 + 10)
        rec["pull_ms_live_assemble"] = round(
            (time.perf_counter() - t) * 1e3, 3
        )
        t = time.perf_counter()
        builder.tick()
        builder.flush()
        rec["assemble_flush_ms"] = round((time.perf_counter() - t) * 1e3, 2)
        # a REAL store-side pull: the l7 rows are in the store (the
        # in-loop exports), so this measures query_trace over them —
        # confirm it did not fall back to the live tracker by probing
        # a store without any live record would serve it too
        t = time.perf_counter()
        got = query_window_trace(store, t0 + 10)
        rec["pull_ms_store_query"] = round((time.perf_counter() - t) * 1e3, 3)
        rec["store_query_nodes"] = 0 if not got else len(got["nodes"])
        lin.close()
    return rec


def main():
    iters = int(os.environ.get("TRACEBENCH_ITERS", 48))
    buckets = tuple(
        int(b)
        for b in os.environ.get("TRACEBENCH_BUCKETS", "256,512,1024").split(",")
    )
    gen = SyntheticFlowGen(num_tuples=2000, seed=0)
    t0 = 1_700_000_000
    sizes = [buckets[(i % len(buckets))] - (17 * i) % 64 for i in range(iters)]
    steps = [
        encode_flowbatch_frames(gen.flow_batch(n, t0 + 10 + i // 4),
                                agent_id=i, max_rows_per_frame=256)
        for i, n in enumerate(sizes)
    ]
    try:
        # throwaway full run (first-pipeline compile/alloc skew), then
        # INTERLEAVED median-of-3 per mode (the §18/§21 recipe — this
        # container's CPU is ±30% noisy)
        run_mode(steps, buckets, False)
        runs = {False: [], True: []}
        for _ in range(3):
            for mode in (False, True):
                runs[mode].append(run_mode(steps, buckets, mode))

        def median(mode):
            return sorted(runs[mode], key=lambda r: r["rec_s"])[1]

        passive = median(False)
        traced = median(True)
        rec = {
            "passive": passive,
            "traced": {k: v for k, v in traced.items()
                       if k not in ("freshness",)},
            "overhead_pct": round(
                (passive["rec_s"] / max(traced["rec_s"], 1e-9) - 1.0) * 100, 2
            ),
            "fetch_parity": traced["host_fetches"] == passive["host_fetches"],
            "freshness": traced["freshness"],
            "iters": iters,
            "buckets": list(buckets),
            # on-chip columns reserved (PERF.md §22 protocol): the same
            # A/B re-run on a real TPU fills these
            "on_chip": None,
        }
    except Exception as e:  # partial-but-parseable (bench contract)
        rec = {"error": repr(e), "partial": True}
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
