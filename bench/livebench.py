#!/usr/bin/env python
"""Live read plane A/B (ISSUE 10 acceptance): snapshot overhead on the
§14 feeder-shaped workload + cached vs uncached repeated-query latency.

Two measurements, one JSON line:

  * **ingest**: the §14 feeder workload (multi-queue fan-in → bucketed
    coalescing → fused step, K-batch counter ring) run twice on
    identical streams — without live reads, and with
    `snapshot_interval_pumps` snapshots scheduled between pumps — so
    `overhead_pct` is the end-to-end cost of keeping a live dashboard's
    snapshot warm. The per-ingest fetch budget is asserted unchanged
    (the CI gate owns the hard guarantee; the bench records the rates).
  * **query**: the repeated-dashboard path — one PromQL `query_range`
    over the open-window live overlay evaluated Q times uncached vs
    through the result cache, plus the cache counters. The cached reps
    hit until a new snapshot generation lands, which is exactly the
    production cadence (`min_snapshot_interval`).

Usage: python bench/livebench.py [repo_root]
Knobs: LIVEBENCH_ITERS, LIVEBENCH_SNAP_EVERY, LIVEBENCH_QUERY_REPS,
LIVEBENCH_BUCKETS. CPU-container numbers; on-chip columns pending per
the measurement-debt item (PERF.md §19).
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)
sys.path.insert(0, root)

from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig  # noqa: E402
from deepflow_tpu.aggregator.window import WindowConfig  # noqa: E402
from deepflow_tpu.feeder import (  # noqa: E402
    FeederConfig,
    FeederRuntime,
    PipelineFeedSink,
    encode_flowbatch_frames,
)
from deepflow_tpu.ingest.queues import PyOverwriteQueue  # noqa: E402
from deepflow_tpu.ingest.replay import SyntheticFlowGen  # noqa: E402

T0 = 1_700_000_000


def _run_ingest(iters, buckets, snap_every):
    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 14, stats_ring=4,
                            min_snapshot_interval=0.0),
        batch_size=buckets[-1], bucket_sizes=buckets,
    ))
    queues = [PyOverwriteQueue(1 << 12) for _ in range(4)]
    feeder = FeederRuntime(
        queues, PipelineFeedSink(pipe),
        FeederConfig(frames_per_queue=16,
                     snapshot_interval_pumps=snap_every),
        name=f"livebench{snap_every}",
    )
    gen = SyntheticFlowGen(num_tuples=2000, seed=0)
    # warmup: compile every bucket + the snapshot read
    for i in range(3):
        fb = gen.flow_batch(buckets[-1], T0 + i)
        for j, fr in enumerate(encode_flowbatch_frames(fb, max_rows_per_frame=256)):
            queues[j % 4].put(fr)
        feeder.pump()
    if snap_every:
        pipe.snapshot_open(force=True)
    rec = 0
    t_start = time.perf_counter()
    for i in range(iters):
        fb = gen.flow_batch(buckets[-1], T0 + 4 + i // 4)
        rec += fb.size
        for j, fr in enumerate(encode_flowbatch_frames(fb, max_rows_per_frame=256)):
            queues[j % 4].put(fr)
        feeder.pump()
    feeder.flush()
    elapsed = time.perf_counter() - t_start
    c = pipe.get_counters()
    fc = feeder.get_counters()
    batches = max(1, fc["batches_out"])
    return {
        "rec_s": round(rec / elapsed, 1),
        "elapsed_s": round(elapsed, 3),
        "records": rec,
        "fetches_per_batch": round(c["host_fetches"] / batches, 3),
        "snapshot_reads": c["snapshot_reads"],
        "snapshot_bytes": c["snapshot_bytes"],
        "snapshots_taken": fc["snapshots_taken"],
        "jit_retraces": c["jit_retraces"],
    }


def _run_query(reps):
    import numpy as np

    from deepflow_tpu.aggregator.window import WindowManager
    from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
    from deepflow_tpu.integration.dfstats import (
        DEEPFLOW_SYSTEM_DB,
        DEEPFLOW_SYSTEM_TABLE,
        LIVE_METRIC_FLOW_BYTES,
        PipelineLiveSource,
        ensure_system_table,
    )
    from deepflow_tpu.querier.live import LiveRegistry, QueryResultCache
    from deepflow_tpu.querier.promql import query_range
    from deepflow_tpu.storage.store import ColumnarStore

    store = ColumnarStore()
    ensure_system_table(store)
    reg = LiveRegistry()
    # a generously rate-limited snapshot: the cache serves the reps
    wm = WindowManager(WindowConfig(capacity=1 << 12, min_snapshot_interval=60.0))
    reg.register(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, PipelineLiveSource(wm))
    n = 512
    meters = np.zeros((FLOW_METER.num_fields, n), np.float32)
    meters[FLOW_METER.index("byte_tx")] = 64.0
    wm.ingest(
        np.full(n, T0, np.uint32),
        np.arange(n, dtype=np.uint32), np.arange(n, dtype=np.uint32),
        np.zeros((TAG_SCHEMA.num_fields, n), np.uint32), meters,
        np.ones(n, bool),
    )
    kw = dict(db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE, live=reg)

    def run(cache):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = query_range(store, LIVE_METRIC_FLOW_BYTES, T0, T0 + 1, 1,
                              cache=cache, **kw)
        return (time.perf_counter() - t0) / reps * 1e3, len(out)

    uncached_ms, series = run(False)
    cache = QueryResultCache(max_entries=64)
    cached_ms, _ = run(cache)
    cc = cache.get_counters()
    return {
        "series": series,
        "reps": reps,
        "uncached_ms": round(uncached_ms, 3),
        "cached_ms": round(cached_ms, 3),
        "speedup_cached": round(uncached_ms / max(cached_ms, 1e-6), 1),
        "cache": cc,
    }


def main():
    iters = int(os.environ.get("LIVEBENCH_ITERS", 48))
    snap_every = int(os.environ.get("LIVEBENCH_SNAP_EVERY", 4))
    reps = int(os.environ.get("LIVEBENCH_QUERY_REPS", 50))
    buckets = tuple(
        int(b) for b in os.environ.get("LIVEBENCH_BUCKETS", "256,512,1024").split(",")
    )
    try:
        off = _run_ingest(iters, buckets, 0)
        on = _run_ingest(iters, buckets, snap_every)
        query = _run_query(reps)
        rec = {
            "bench": "livebench",
            "iters": iters,
            "snap_every": snap_every,
            "ingest": {
                "off": off,
                "live": on,
                "overhead_pct": round(
                    (off["rec_s"] / max(on["rec_s"], 1e-9) - 1.0) * 100.0, 2
                ),
            },
            "query": query,
        }
    except Exception as e:  # parseable partial record, never a traceback
        rec = {"bench": "livebench", "partial": True, "error": repr(e)}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
