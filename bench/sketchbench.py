"""Sketch-tier A/B bench (ISSUE 8): exact-only vs +sketch-plane vs
+top-K through the windowed raw-doc ingest path, under a
high-cardinality generator (Zipf heavy flows + a uniform scan sweep —
the DDoS/scan shape that overflows the exact stash). A fourth
"topk_multisort" row (ISSUE 17) reruns the +top-K plane with
DEEPFLOW_SHARED_SORT=0, so every shape carries a shared-sort A/B
(`shared_sort_speedup` on the "topk" row; bench/sortbench.py is the
dedicated driver). A fifth "pool" row (ISSUE 20) reruns the +top-K
plane with the disaggregated sketch-memory pool ON — same accuracy
protocol, compared on resident HBM sketch bytes.

Measures, per (batch, stash) shape:
  * rec/s for the variants (the sketch tax on steady ingest);
  * HLL cardinality error of the closed window vs the true distinct
    count (acceptance: <1% at ≥1M distinct keys, hll_precision=14);
  * top-K heavy-hitter recall vs the true by-bytes top-K
    (acceptance: ≥0.95 at K=128, Zipf s=1.1);
  * exact-tier coverage (flushed rows / distinct keys) — the shed the
    sketch tier papers over;
  * `hbm_sketch_bytes` — the sketch tier's RESIDENT device bytes,
    read from live DeviceMemoryLedger rows (profiling/ledger.py), and
    `hbm_bytes_per_1pct_card` = bytes × cardinality-error-% (the cost
    of a percentage point of cardinality accuracy; lower is better).
    The "pool" row carries `density_vs_slab` = slab bytes / pool bytes
    at the same accuracy protocol (ISSUE 20 headline: ≥4×).

Protocol + committed CPU numbers: PERF.md §17 and §28 (on-chip
columns reserved; SKETCHBENCH_r02.json is the pooled run). Knobs:
SKETCHBENCH_SHAPES="batch:stash,...", SKETCHBENCH_BATCHES,
SKETCHBENCH_KEYS, SKETCHBENCH_TOPK, SKETCHBENCH_PRECISION. Emits one
JSON record on the last stdout line (bench_all.py c9 re-emits it)."""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepflow_tpu.aggregator.sketchplane import PoolConfig, SketchConfig  # noqa: E402
from deepflow_tpu.aggregator.window import WindowConfig, WindowManager  # noqa: E402
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA  # noqa: E402
from deepflow_tpu.ops.histogram import LogHistSpec  # noqa: E402

T0 = 1_700_000_000


def _shapes() -> list[tuple[int, int]]:
    env = os.environ.get("SKETCHBENCH_SHAPES")
    if env:
        return [tuple(int(x) for x in s.split(":")) for s in env.split(",")]
    # full protocol grid: {64k..1M} batch × {8k, 64k} stash
    return [(1 << 16, 1 << 13), (1 << 16, 1 << 16),
            (1 << 18, 1 << 13), (1 << 18, 1 << 16),
            (1 << 20, 1 << 13), (1 << 20, 1 << 16)]


class _KeyGen:
    """Zipf heavy flows over [0, n_keys) + a SEQUENTIAL scan sweep —
    every batch is half skewed traffic, half scanner walking the key
    space (the address-scan shape: guaranteed-high distinct count)."""

    def __init__(self, rng, n_keys, zipf_s):
        self.rng, self.n_keys, self.s = rng, n_keys, zipf_s
        self.cursor = 0

    def batch(self, n):
        half = n // 2
        z = self.rng.zipf(self.s, size=4 * half)
        z = z[z <= self.n_keys][:half].astype(np.uint64) - 1
        scan = (self.cursor + np.arange(n - len(z), dtype=np.uint64)) % self.n_keys
        self.cursor = int((self.cursor + len(scan)) % self.n_keys)
        keys = np.concatenate([z, scan])
        self.rng.shuffle(keys)
        return keys


def _doc_batch(keys: np.ndarray, t: int):
    n = len(keys)
    k_lo = (keys & 0xFFFFFFFF).astype(np.uint32)
    k_hi32 = (keys >> 32).astype(np.uint32)
    tags = np.zeros((TAG_SCHEMA.num_fields, n), np.uint32)
    tags[TAG_SCHEMA.index("ip0_w3")] = k_lo
    tags[TAG_SCHEMA.index("ip0_w2")] = k_hi32
    tags[TAG_SCHEMA.index("server_port")] = 443
    tags[TAG_SCHEMA.index("protocol")] = 6
    tags[TAG_SCHEMA.index("l3_epc_id1")] = (k_lo % np.uint32(7)).astype(np.uint32)
    meters = np.zeros((FLOW_METER.num_fields, n), np.float32)
    meters[FLOW_METER.index("byte_tx")] = 100.0
    meters[FLOW_METER.index("rtt_sum")] = 10.0
    meters[FLOW_METER.index("rtt_count")] = 1.0
    # injective 64-bit fingerprint of the key id — the doc key identity
    hi = (k_lo * np.uint32(2654435761)) ^ k_hi32
    lo = k_lo ^ np.uint32(0x9E3779B9) ^ (k_hi32 * np.uint32(40503))
    return (np.full(n, t, np.uint32), jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(tags), jnp.asarray(meters), np.ones(n, bool))


def _run_variant(variant, batch, stash, batches, n_keys, zipf_s, k_top,
                 precision):
    # "topk_multisort" is the ISSUE 17 A/B control: the same +top-K
    # plane with DEEPFLOW_SHARED_SORT=0 (the knob is read at dispatch
    # time, so flipping it between variants is honest within one
    # process). "pool" (ISSUE 20) is the same +top-K plane drawing from
    # the disaggregated sketch-memory pool — identical accuracy
    # protocol, compared on resident HBM bytes. Everything else about
    # those rows is the "topk" protocol.
    plane = "topk" if variant in ("topk", "topk_multisort", "pool") \
        else variant
    os.environ["DEEPFLOW_SHARED_SORT"] = (
        "0" if variant == "topk_multisort" else "1")
    sk = None
    if plane != "exact":
        sk = SketchConfig(
            num_groups=8, hll_precision=precision, cms_depth=4,
            cms_width=1 << 16,
            hist=LogHistSpec(bins=128, vmin=1.0, gamma=1.1),
            topk_rows=2 if plane == "topk" else 0,
            topk_cols=max(64, 1 << (max(k_top, 1) - 1).bit_length() + 3),
            pending=8,
            # topk_factor=2: the top-K lanes are a rounding error of
            # the arena (CMS/HLL dominate), so halving instead of
            # quartering them buys pre-promotion recall for free
            pool=PoolConfig(topk_factor=2) if variant == "pool" else None,
        )
    wm = WindowManager(WindowConfig(capacity=stash, delay=2, sketch=sk))
    gen = _KeyGen(np.random.default_rng(7), n_keys, zipf_s)
    key_stream, flushed = [], []
    # warmup batch compiles the fused step (excluded from timing; a
    # separate throwaway generator keeps the measured stream seeded)
    wk = _KeyGen(np.random.default_rng(1), n_keys, zipf_s).batch(
        min(batch, 1 << 14)
    )
    wm.ingest(*_doc_batch(wk, T0 - 100))
    wm.flush_all()

    t_ingest = 0.0
    for i in range(batches):
        keys = gen.batch(batch)
        key_stream.append(keys)
        b = _doc_batch(keys, T0)
        t0 = time.perf_counter()
        flushed += wm.ingest(*b)
        jax.block_until_ready(wm.acc.slot)
        t_ingest += time.perf_counter() - t0
    flushed += wm.flush_all()

    all_keys = np.concatenate(key_stream)
    true_distinct = len(np.unique(all_keys))
    f0 = next(f for f in flushed if f.window_idx == T0)
    exact_rows = f0.count
    rec = {
        "variant": variant,
        "rec_s": batch * batches / t_ingest if t_ingest else 0.0,
        "true_distinct": true_distinct,
        "exact_rows_flushed": int(exact_rows),
        "exact_coverage": float(exact_rows) / true_distinct,
        "stash_evictions": int(np.asarray(wm.state.dropped_overflow)),
    }
    if sk is not None and f0.sketches is not None:
        blk = f0.sketches
        est = blk.distinct()
        rec["hll_estimate"] = est
        rec["cardinality_error"] = abs(est - true_distinct) / true_distinct
        if plane == "topk":
            uniq, counts = np.unique(all_keys, return_counts=True)
            order = np.argsort(-counts, kind="stable")
            true_top = uniq[order[:k_top]]
            # match on the doc-key fingerprint the sketch stores — the
            # same identity flushed exact rows carry
            t_lo = (true_top & 0xFFFFFFFF).astype(np.uint32)
            t_hi32 = (true_top >> 32).astype(np.uint32)
            want = {
                (int((a * np.uint32(2654435761)) ^ b),
                 int(a ^ np.uint32(0x9E3779B9) ^ (b * np.uint32(40503))))
                for a, b in zip(t_lo, t_hi32)
            }
            got = blk.topk(k_top)
            have = {(t_["key_hi"], t_["key_lo"]) for t_ in got}
            rec["topk_recall"] = len(want & have) / max(1, k_top)
            rec["topk_returned"] = len(got)
    counters = wm.get_counters()
    rec["sketch_rows"] = counters["sketch_rows"]
    rec["sketch_shed"] = counters["sketch_shed"]
    if sk is not None:
        # resident sketch HBM from LIVE ledger rows (ISSUE 20): the
        # manager's device_planes() enumerate the actual buffers — the
        # pooled plane reports as sketch_pool_hot/_wide/_pending/_meta,
        # the slab plane as one "sketch" row; nothing is estimated
        from deepflow_tpu.profiling.ledger import DeviceMemoryLedger

        led = DeviceMemoryLedger()
        led.register("wm", wm)
        rec["hbm_sketch_bytes"] = sum(
            r["bytes"] for r in led.snapshot()
            if r["plane"].startswith("sketch")
        )
        if "cardinality_error" in rec:
            rec["hbm_bytes_per_1pct_card"] = round(
                rec["hbm_sketch_bytes"]
                * max(rec["cardinality_error"] * 100.0, 1e-3), 1)
        if sk.pool is not None:
            rec["pool_spill"] = counters["sketch_pool_spill"]
            rec["pool_promotions"] = counters["sketch_promotions"]
    return rec


def main():
    batches = int(os.environ.get("SKETCHBENCH_BATCHES", "4"))
    n_keys = int(os.environ.get("SKETCHBENCH_KEYS", str(1 << 20)))
    zipf_s = float(os.environ.get("SKETCHBENCH_ZIPF", "1.1"))
    k_top = int(os.environ.get("SKETCHBENCH_TOPK", "128"))
    precision = int(os.environ.get("SKETCHBENCH_PRECISION", "14"))
    rows = []
    err = None
    try:
        for batch, stash in _shapes():
            recs = {}
            for variant in ("exact", "sketch", "topk", "topk_multisort",
                            "pool"):
                r = _run_variant(variant, batch, stash, batches, n_keys,
                                 zipf_s, k_top, precision)
                r.update(batch=batch, stash=stash)
                recs[variant] = r
                rows.append(r)
                print(json.dumps(r), file=sys.stderr)
            # shared-sort A/B (ISSUE 17): one-pass topk vs the same
            # plane under the multi-sort oracle, same stream
            recs["topk"]["shared_sort_speedup"] = round(
                recs["topk"]["rec_s"]
                / max(recs["topk_multisort"]["rec_s"], 1e-9), 3)
            # pooled-memory density (ISSUE 20): resident sketch HBM of
            # the slab +top-K plane over the pooled one, same accuracy
            # protocol — the ≥4× headline, from live ledger rows
            slab_b = recs["topk"].get("hbm_sketch_bytes", 0)
            pool_b = recs["pool"].get("hbm_sketch_bytes", 0)
            if pool_b:
                recs["pool"]["density_vs_slab"] = round(slab_b / pool_b, 3)
                if "hbm_bytes_per_1pct_card" in recs["pool"]:
                    recs["pool"]["density_per_1pct_vs_slab"] = round(
                        recs["topk"].get("hbm_bytes_per_1pct_card", 0.0)
                        / max(recs["pool"]["hbm_bytes_per_1pct_card"],
                              1e-9), 3)
    except Exception as e:  # partial-JSON convention (bench.py stance)
        err = repr(e)
    out = {
        "bench": "sketchbench", "rows": rows,
        "n_keys": n_keys, "zipf_s": zipf_s, "k_top": k_top,
        "hll_precision": precision, "batches_per_variant": batches,
        "backend": jax.default_backend(),
    }
    if err:
        out["partial"] = True
        out["error"] = err
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
