#!/usr/bin/env python
"""Wire delivery plane A/B (ISSUE 19 acceptance): push fan-out latency
over REAL sockets, watchers × rules × hosts.

pushbench measures the in-process push plane (ONE eval, N callback
watchers). This bench extends it across the wire: H pipeline-host
stacks (store + bus + SubscriptionManager + `WirePublisher`), each
dialed into ONE `FleetSubscriptionRouter` over TCP, fan merged eval
envelopes out to W wire clients attached through the serving `WireHub`
(`open_stream`, the same face `GET /v1/watch` rides). Per grid cell:

  * **publish → all-W-watchers latency** (mean/p95 ms): host-side
    window-close publish until EVERY wire client's queue holds the
    merged envelope — eval + frame encode + socket + merge + fan-out.
    The acceptance shape: latency FLAT in W (fan-out is W bounded-queue
    appends off one merged eval; the wire/eval cost dominates and is
    paid ONCE), summarized as `latency_ratio_wmax_over_w1` per
    (hosts, rules) group.
  * **one upstream subscription** regardless of W (`upstream_subs`),
    evals == events per host (never × W), deliveries == merged × W,
    zero drops (drains keep up).
  * **rules ride along**: R host-side alert rules firing on the same
    events push `alert` frames up the same lane (`alerts_rx` counted);
    an alerts-topic wire client drains them.
  * **pinned**: the last merged envelope's per-host rows bit-exact vs
    each host's own `last_result` through `result_to_jsonable` — the
    wire never re-evaluates or re-shapes.

Usage: python bench/wirebench.py [repo_root]
Knobs: WIREBENCH_WATCHERS (default "1,10,100"), WIREBENCH_HOSTS
("1,2"), WIREBENCH_RULES ("0,4"), WIREBENCH_EVENTS (16). CPU-container
numbers; on-chip columns pending per the measurement-debt item
(PERF.md §27).
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)
sys.path.insert(0, root)

T0 = 1_700_000_000


class _HostStack:
    """One pipeline host: local store/bus/subs (+R alert rules) and a
    WirePublisher uplink into the bench router."""

    def __init__(self, idx, endpoint, rules):
        import numpy as np

        from deepflow_tpu.integration.dfstats import (
            DEEPFLOW_SYSTEM_DB,
            DEEPFLOW_SYSTEM_TABLE,
            ensure_system_table,
        )
        from deepflow_tpu.querier.events import QueryEventBus, WindowClosed
        from deepflow_tpu.querier.live import LiveRegistry
        from deepflow_tpu.querier.subscribe import SubscriptionManager
        from deepflow_tpu.storage.store import ColumnarStore
        from deepflow_tpu.wire import WirePublisher

        self.np = np
        self.db, self.table = DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE
        self.WindowClosed = WindowClosed
        self.host = f"h{idx}"
        self.store = ColumnarStore()
        ensure_system_table(self.store)
        self.bus = QueryEventBus(name=f"wirebench-{idx}")
        self.subs = SubscriptionManager(
            self.store, live=LiveRegistry(), cache=False, bus=self.bus,
            name=f"wirebench-{idx}",
        )
        self.alerts = None
        if rules:
            from deepflow_tpu.querier.alerts import AlertEngine, AlertRule

            self.alerts = AlertEngine(
                self.store, live=LiveRegistry(), bus=self.bus,
                name=f"wirebench-{idx}", log_sink=False,
            )
            for r in range(rules):
                self.alerts.add_rule(AlertRule(
                    name=f"rule{r}", query="m", comparator=">",
                    threshold=-1.0, for_s=0, lookback_s=2,
                ))
        self.pub = WirePublisher(endpoint, host=self.host,
                                 subscriptions=self.subs,
                                 alerts=self.alerts)

    def wait_subscribed(self, timeout_s=30.0):
        deadline = time.monotonic() + timeout_s
        while not self.pub.active_queries():
            if time.monotonic() > deadline:
                raise TimeoutError(f"{self.host}: no sub from router")
            time.sleep(0.005)
        return self.pub.active_queries()[0][1]

    def publish(self, t, v):
        np = self.np
        self.store.insert(self.db, self.table, {
            "time": np.asarray([t], np.uint32),
            "metric": np.asarray(["m"], object),
            "labels": np.asarray([""], object),
            "value": np.asarray([v], np.float64),
        })
        self.bus.publish(self.WindowClosed(self.db, self.table, t))

    def close(self):
        self.pub.close()
        self.subs.close()


def _run_cell(watchers, hosts, rules, events):
    from deepflow_tpu.querier.live import LiveRegistry
    from deepflow_tpu.querier.subscribe import SubscriptionManager
    from deepflow_tpu.storage.store import ColumnarStore
    from deepflow_tpu.wire import (
        FleetSubscriptionRouter,
        WireHub,
        result_to_jsonable,
    )

    router = FleetSubscriptionRouter(name=f"wb{watchers}x{hosts}").start()
    local = SubscriptionManager(ColumnarStore(), live=LiveRegistry(),
                                cache=False, name="wirebench-agg")
    hub = WireHub(local, router=router, name="wirebench")
    stacks, conns, alert_conn = [], [], None
    try:
        conns = [hub.open_stream(promql="m", span_s=4, maxlen=4 * events)
                 for _ in range(watchers)]
        if rules:
            alert_conn = hub.open_stream(alerts=True,
                                         maxlen=4 * events * rules * hosts)
        stacks = [_HostStack(i, router.endpoint, rules)
                  for i in range(hosts)]
        host_subs = [s.wait_subscribed() for s in stacks]
        assert router.get_counters()["upstream_subs"] == 1

        def wait_all(target, timeout_s=30.0):
            deadline = time.monotonic() + timeout_s
            while any(c.watcher.delivered < target for c in conns):
                if time.monotonic() > deadline:
                    raise TimeoutError("fan-out stalled")
                time.sleep(0)

        # warmup: one event per host faults every path in
        for i, s in enumerate(stacks):
            s.publish(T0 + i, 1.0)
        wait_all(hosts)

        lat = []
        t_start = time.perf_counter()
        for k in range(events):
            s = stacks[k % hosts]
            stamp = time.perf_counter()
            s.publish(T0 + hosts + k, float(k))
            wait_all(hosts + k + 1)
            lat.append(time.perf_counter() - stamp)
        elapsed = time.perf_counter() - t_start

        rc = router.get_counters()
        merged = rc["merged_evals"]
        # pinned: per-host wire rows == that host's own last eval
        env = None
        for c in conns[:1]:
            item = c.poll()
            while item is not None:
                env, item = item, c.poll()
        pinned = bool(env) and all(
            env["hosts"][s.host]["series"] == json.loads(
                json.dumps(result_to_jsonable(hs.last_result), default=str)
            )
            for s, hs in zip(stacks, host_subs)
        )
        alerts_drained = 0
        if alert_conn is not None:
            while alert_conn.poll() is not None:
                alerts_drained += 1
        lat.sort()
        return {
            "watchers": watchers,
            "hosts": hosts,
            "rules": rules,
            "events": events,
            "merged_evals": merged,
            "deliveries": rc["deliveries"],
            "upstream_subs": rc["upstream_subs"],
            "host_evals": [hs.evals for hs in host_subs],
            "drops": rc["drops"],
            "alerts_rx": rc["alerts_rx"],
            "alerts_drained": alerts_drained,
            "publish_to_all_watchers_ms_mean": round(
                sum(lat) / len(lat) * 1e3, 3),
            "publish_to_all_watchers_ms_p95": round(
                lat[int(0.95 * (len(lat) - 1))] * 1e3, 3),
            "deliveries_per_s": round(rc["deliveries"] / elapsed, 1),
            "pinned_bit_exact": pinned,
        }
    finally:
        for s in stacks:
            s.close()
        hub.close()
        local.close()
        router.stop()


def main():
    watcher_counts = [int(w) for w in os.environ.get(
        "WIREBENCH_WATCHERS", "1,10,100").split(",")]
    host_counts = [int(h) for h in os.environ.get(
        "WIREBENCH_HOSTS", "1,2").split(",")]
    rule_counts = [int(r) for r in os.environ.get(
        "WIREBENCH_RULES", "0,4").split(",")]
    events = int(os.environ.get("WIREBENCH_EVENTS", 16))
    try:
        from deepflow_tpu.utils.provenance import bench_provenance

        rows = [
            _run_cell(w, h, r, events)
            for h in host_counts for r in rule_counts
            for w in watcher_counts
        ]
        # the flatness summary the acceptance reads: max-W latency over
        # W=1 latency within each (hosts, rules) group
        ratios = {}
        for h in host_counts:
            for r in rule_counts:
                group = [x for x in rows
                         if x["hosts"] == h and x["rules"] == r]
                lo = min(group, key=lambda x: x["watchers"])
                hi = max(group, key=lambda x: x["watchers"])
                ratios[f"h{h}_r{r}"] = round(
                    hi["publish_to_all_watchers_ms_mean"]
                    / max(1e-9, lo["publish_to_all_watchers_ms_mean"]), 3)
        rec = {
            "bench": "wirebench",
            "events": events,
            "rows": rows,
            "latency_ratio_wmax_over_w1": ratios,
            "provenance": bench_provenance(),
        }
    except Exception as e:  # parseable partial record, never a traceback
        rec = {"bench": "wirebench", "partial": True, "error": repr(e)}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
