#!/usr/bin/env python
"""Mesh-scaling rows for BASELINE config 5 — the r4 verdict's demand
that c5 be a *mesh* statement, not a tunnel-latency measurement.

Runs the sharded pipeline on a virtual CPU mesh at 1/2/4/8 devices
(XLA_FLAGS=--xla_force_host_platform_device_count=8, the same
environment dryrun_multichip validates), at a FIXED per-device batch
(weak scaling, the pod-firehose shape), timing:

  * steady ingest cycles (step + amortized fold) — chained, no host
    round trip inside the loop; one measured fetch latency is
    subtracted from the window (PERF.md §7a recipe);
  * the *windowed* cadence — timestamps advance so every iteration
    closes a window through the fused `flush_range` batched drain
    (one totals fetch + one packed row-block fetch per advance,
    ISSUE 2) — the end-to-end rate the product ships through;
  * the collective window close (psum/pmax sketch merges over
    chip/host axes) separately, since that is the mesh-specific cost.

Prints one JSON line: {"rows": [{n_devices, ingest_rec_s,
windowed_rec_s, drain_ms, close_ms, ...}, ...]}. On any failure it
prints {"rows": [...partial...], "partial": true, "error": ...} and
exits 0 (bench.py convention — the harness always gets parseable
output). bench_all.py config5 shells out to this and embeds the rows
in PERF_ALL's c5 detail.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"  # this tool measures the CPU mesh only
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from deepflow_tpu.ingest.replay import SyntheticFlowGen  # noqa: E402
from deepflow_tpu.ops.histogram import LogHistSpec  # noqa: E402
from deepflow_tpu.parallel.mesh import make_mesh  # noqa: E402
from deepflow_tpu.parallel.sharded import (  # noqa: E402
    ShardedConfig,
    ShardedPipeline,
    ShardedWindowManager,
)


def _sync(wm):
    """Fetch ONE sketch element — the chained-sync fence (PERF.md §7a)."""
    return np.asarray(wm.sketches.hll.ravel()[:1])


def run(n_dev: int, per_dev: int, iters: int, fold_mode: str = "full") -> dict:
    mesh = make_mesh(n_dev, n_hosts=2 if n_dev >= 2 else 1)
    cfg = ShardedConfig(
        capacity_per_device=1 << 12,
        num_services=256,
        hll_precision=10,
        hist=LogHistSpec(bins=256, vmin=1.0, gamma=1.08),
        batch_unique_cap=1 << 13,
        fold_mode=fold_mode,
    )
    pipe = ShardedPipeline(mesh, cfg)
    wm = ShardedWindowManager(pipe)
    batch = per_dev * n_dev
    gen = SyntheticFlowGen(num_tuples=10_000, seed=4)
    t0s = 1_700_000_000

    # warm every compile path (step, fold, window_close, flush_range)
    for wt in (t0s, t0s + 60, t0s + 61, t0s + 65):
        fb = gen.flow_batch(batch, wt)
        wm.ingest(fb.tags, fb.meters, fb.valid)

    # one measured fetch to subtract from every chained window (§7a)
    _sync(wm)
    t0 = time.perf_counter()
    _sync(wm)
    fetch_base = time.perf_counter() - t0

    # steady ingest (one window, no closes inside the timed loop)
    batches = [gen.flow_batch(batch, t0s + 70) for _ in range(iters)]
    _sync(wm)
    t0 = time.perf_counter()
    for fb in batches:
        wm.ingest(fb.tags, fb.meters, fb.valid)
    _sync(wm)
    ingest_s = max(time.perf_counter() - t0 - fetch_base, 1e-9)
    ingest_rate = batch * iters / ingest_s

    # windowed cadence: every iteration advances time by one interval,
    # closing one window through the fused batched drain (flush_range)
    wbatches = [gen.flow_batch(batch, t0s + 80 + i) for i in range(iters)]
    _sync(wm)
    t0 = time.perf_counter()
    docs = 0
    for fb in wbatches:
        docs += sum(d.size for d in wm.ingest(fb.tags, fb.meters, fb.valid))
    _sync(wm)
    windowed_s = max(time.perf_counter() - t0 - fetch_base, 1e-9)
    windowed_rate = batch * iters / windowed_s
    # per-advance drain overhead = windowed minus steady, per iteration
    drain_ms = max(windowed_s - ingest_s, 0.0) / iters * 1e3

    # collective close alone: psum/pmax merges over the mesh axes
    t0 = time.perf_counter()
    closes = 4
    for _ in range(closes):
        wm.sketches, _gv, _pod = pipe.window_close(wm.sketches)
    _sync(wm)
    close_ms = (time.perf_counter() - t0 - fetch_base) / closes * 1e3

    row = {
        "n_devices": n_dev,
        "fold_mode": fold_mode,
        "per_device_batch": per_dev,
        "ingest_rec_s": round(ingest_rate, 1),
        "windowed_rec_s": round(windowed_rate, 1),
        "windowed_docs": docs,
        "drain_ms": round(drain_ms, 3),
        "close_ms": round(close_ms, 3),
        "fetch_base_ms": round(fetch_base * 1e3, 3),
    }
    try:  # stage attribution snapshot (ISSUE 3); tolerate its absence
        row["telemetry"] = wm.telemetry()
    except Exception as e:
        row["telemetry"] = None
        row["telemetry_error"] = repr(e)
    return row


def main():
    per_dev = int(os.environ.get("MESH_PER_DEV", 1 << 13))
    iters = int(os.environ.get("MESH_ITERS", 8))
    # fold-mode A/B (ISSUE 5): the windowed cadence's drain_ms is what
    # the incremental merge-fold attacks — emit before/after rows
    modes = [
        m for m in os.environ.get("MESH_FOLD_MODES", "full,merge").split(",") if m
    ]
    devices = [
        int(d) for d in os.environ.get("MESH_DEVICES", "1,2,4,8").split(",") if d
    ]
    rows = []
    try:
        for mode in modes:
            for n in devices:
                rows.append(run(n, per_dev, iters, fold_mode=mode))
        print(json.dumps({"rows": rows}), flush=True)
    except Exception as e:  # parseable partial record, never a traceback
        print(
            json.dumps({"rows": rows, "partial": True, "error": repr(e)}),
            flush=True,
        )


if __name__ == "__main__":
    main()
