#!/usr/bin/env python
"""Mesh-scaling rows for BASELINE config 5 — the r4 verdict's demand
that c5 be a *mesh* statement, not a tunnel-latency measurement.

Three recipes in one tool:

**MESH_PROCS=N1,N2,... (ISSUE 14)** — the multi-HOST recipe: for each
N, spawn N clean-env subprocesses (the dryrun_multichip pattern), each
one host of an N-process `jax.distributed` deployment
(MeshTopology.distributed, one shard group per process, fully-local
data path) running the §14 feeder-shaped workload — frames → queues →
FeederRuntime → ShardedFeedSink → windowed drains — against ITS group
only (key-hash routing already steered the agents there; the routing
itself is CI-pinned in tests/test_mesh_multiproc.py). Reports per-host
and AGGREGATE rec/s per process count plus the distributed bring-up
wall. Emits {"proc_rows": [...]} alongside (or instead of) the device
rows; MESHBENCH_r01.json holds the committed snapshot.

**MESH_REBALANCE=1 (ISSUE 15)** — the rebalance-pause protocol row
(PERF.md §24): a feeder-shaped shard group on the OLD owner's
standalone topology view is preloaded to a given state size and timed
at steady state, then handed over — `GroupRebalancer.release` (quiesce
→ manifest checkpoint → journal rotate) and `adopt`
(restore_sharded_state into a fresh manager under the NEW owner's
view) — with the pause decomposed into release/build/restore, the
first post-adopt pump (the cold manager's compile) reported
separately, and the per-step cadence walked until it re-enters 1.5× of
the pre-handover steady step (recovery-to-steady). One row per
MESH_REBALANCE_PRELOADS entry (state size sweep). Emits
{"rebalance_rows": [...]}.

**Default (device) recipe** — the single-process virtual CPU mesh at
1/2/4/8 devices (XLA_FLAGS=--xla_force_host_platform_device_count=8,
the same environment dryrun_multichip validates), at a FIXED
per-device batch (weak scaling, the pod-firehose shape), timing:

  * steady ingest cycles (step + amortized fold) — chained, no host
    round trip inside the loop; one measured fetch latency is
    subtracted from the window (PERF.md §7a recipe);
  * the *windowed* cadence — timestamps advance so every iteration
    closes a window through the fused `flush_range` batched drain
    (one totals fetch + one packed row-block fetch per advance,
    ISSUE 2) — the end-to-end rate the product ships through;
  * the collective window close (psum/pmax sketch merges over
    chip/host axes) separately, since that is the mesh-specific cost.

Prints one JSON line: {"rows": [{n_devices, ingest_rec_s,
windowed_rec_s, drain_ms, close_ms, ...}, ...]}. On any failure it
prints {"rows": [...partial...], "partial": true, "error": ...} and
exits 0 (bench.py convention — the harness always gets parseable
output). bench_all.py config5 shells out to this and embeds the rows
in PERF_ALL's c5 detail.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"  # this tool measures the CPU mesh only
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from deepflow_tpu.ingest.replay import SyntheticFlowGen  # noqa: E402
from deepflow_tpu.ops.histogram import LogHistSpec  # noqa: E402
from deepflow_tpu.parallel.mesh import make_mesh  # noqa: E402
from deepflow_tpu.parallel.sharded import (  # noqa: E402
    ShardedConfig,
    ShardedPipeline,
    ShardedWindowManager,
)


def _sync(wm):
    """Fetch ONE sketch element — the chained-sync fence (PERF.md §7a)."""
    return np.asarray(wm.sketches.hll.ravel()[:1])


def run(n_dev: int, per_dev: int, iters: int, fold_mode: str = "full") -> dict:
    mesh = make_mesh(n_dev, n_hosts=2 if n_dev >= 2 else 1)
    cfg = ShardedConfig(
        capacity_per_device=1 << 12,
        num_services=256,
        hll_precision=10,
        hist=LogHistSpec(bins=256, vmin=1.0, gamma=1.08),
        batch_unique_cap=1 << 13,
        fold_mode=fold_mode,
    )
    pipe = ShardedPipeline(mesh, cfg)
    wm = ShardedWindowManager(pipe)
    batch = per_dev * n_dev
    gen = SyntheticFlowGen(num_tuples=10_000, seed=4)
    t0s = 1_700_000_000

    # warm every compile path (step, fold, window_close, flush_range)
    for wt in (t0s, t0s + 60, t0s + 61, t0s + 65):
        fb = gen.flow_batch(batch, wt)
        wm.ingest(fb.tags, fb.meters, fb.valid)

    # one measured fetch to subtract from every chained window (§7a)
    _sync(wm)
    t0 = time.perf_counter()
    _sync(wm)
    fetch_base = time.perf_counter() - t0

    # steady ingest (one window, no closes inside the timed loop)
    batches = [gen.flow_batch(batch, t0s + 70) for _ in range(iters)]
    _sync(wm)
    t0 = time.perf_counter()
    for fb in batches:
        wm.ingest(fb.tags, fb.meters, fb.valid)
    _sync(wm)
    ingest_s = max(time.perf_counter() - t0 - fetch_base, 1e-9)
    ingest_rate = batch * iters / ingest_s

    # windowed cadence: every iteration advances time by one interval,
    # closing one window through the fused batched drain (flush_range)
    wbatches = [gen.flow_batch(batch, t0s + 80 + i) for i in range(iters)]
    _sync(wm)
    t0 = time.perf_counter()
    docs = 0
    for fb in wbatches:
        docs += sum(d.size for d in wm.ingest(fb.tags, fb.meters, fb.valid))
    _sync(wm)
    windowed_s = max(time.perf_counter() - t0 - fetch_base, 1e-9)
    windowed_rate = batch * iters / windowed_s
    # per-advance drain overhead = windowed minus steady, per iteration
    drain_ms = max(windowed_s - ingest_s, 0.0) / iters * 1e3

    # collective close alone: psum/pmax merges over the mesh axes
    t0 = time.perf_counter()
    closes = 4
    for _ in range(closes):
        wm.sketches, _gv, _pod = pipe.window_close(wm.sketches)
    _sync(wm)
    close_ms = (time.perf_counter() - t0 - fetch_base) / closes * 1e3

    row = {
        "n_devices": n_dev,
        "fold_mode": fold_mode,
        "per_device_batch": per_dev,
        "ingest_rec_s": round(ingest_rate, 1),
        "windowed_rec_s": round(windowed_rate, 1),
        "windowed_docs": docs,
        "drain_ms": round(drain_ms, 3),
        "close_ms": round(close_ms, 3),
        "fetch_base_ms": round(fetch_base * 1e3, 3),
    }
    try:  # stage attribution snapshot (ISSUE 3); tolerate its absence
        row["telemetry"] = wm.telemetry()
    except Exception as e:
        row["telemetry"] = None
        row["telemetry_error"] = repr(e)
    return row


# ---------------------------------------------------------------------------
# multi-process recipe (ISSUE 14)


def _proc_body(spec: dict) -> None:
    """One host of an N-process deployment (subprocess entry): real
    `jax.distributed` bring-up at N>1, one shard group, the §14
    feeder-shaped workload against it, one JSON result file."""
    import time as _time

    from deepflow_tpu.feeder import FeederConfig, encode_flowbatch_frames
    from deepflow_tpu.ingest.queues import PyOverwriteQueue
    from deepflow_tpu.parallel.topology import MeshTopology
    from deepflow_tpu.parallel.sharded import ShardedWindowManager

    nproc = spec["num_processes"]
    pid = spec["process_id"]
    t_init = _time.perf_counter()
    if nproc > 1:
        topo = MeshTopology.distributed(
            spec["coordinator"], nproc, pid,
            n_groups=nproc, devices_per_group=1,
        )
    else:
        topo = MeshTopology.single(n_groups=1, devices_per_group=1)
    init_s = _time.perf_counter() - t_init
    group = topo.owned_groups()[0]

    cfg = ShardedConfig(
        capacity_per_device=1 << 13,
        num_services=64,
        hll_precision=8,
        hist=LogHistSpec(bins=128, vmin=1.0, gamma=1.1),
    )
    wm = ShardedWindowManager(ShardedPipeline(topo, cfg, shard_group=group))
    queues = [PyOverwriteQueue(1 << 12) for _ in range(2)]
    buckets = (512, 1024, 2048)
    feeder = wm.make_feeder(
        queues, buckets, FeederConfig(frames_per_queue=16)
    )

    iters = spec["iters"]
    t0s = 1_700_000_000
    gen = SyntheticFlowGen(num_tuples=2000, seed=100 + pid)
    # pre-encode every step's frames (the probe times fan-in + decode +
    # coalesce + dispatch + windowed drains, not the generator); time
    # advances every 4 steps so windows close through the fused drain
    sizes = [buckets[i % len(buckets)] - (31 * i) % 128 for i in range(iters)]
    steps = [
        encode_flowbatch_frames(
            gen.flow_batch(n, t0s + 10 + i // 4),
            agent_id=pid * 64 + i, max_rows_per_frame=512,
        )
        for i, n in enumerate(sizes)
    ]
    # warm every bucket's compile path
    for b in buckets:
        for fr in encode_flowbatch_frames(
            gen.flow_batch(b, t0s), max_rows_per_frame=512
        ):
            queues[0].put(fr)
        feeder.pump()

    f0 = feeder.get_counters()
    docs = 0
    start = _time.perf_counter()
    for i, frames in enumerate(steps):
        for j, fr in enumerate(frames):
            queues[j % len(queues)].put(fr)
        docs += sum(d.size for d in feeder.pump())
    docs += sum(d.size for d in wm.drain())
    elapsed = _time.perf_counter() - start
    f1 = feeder.get_counters()
    records = f1["records_out"] - f0["records_out"]
    res = {
        "process_id": pid,
        "records": int(records),
        "elapsed_s": round(elapsed, 4),
        "rec_s": round(records / max(elapsed, 1e-9), 1),
        "init_s": round(init_s, 3),
        "flushed_docs": int(docs),
        "host_fetches": wm.get_counters()["host_fetches"],
    }
    from pathlib import Path

    from deepflow_tpu.parallel.hostproc import exit_after_barrier

    Path(spec["out"]).write_text(json.dumps(res))
    # shared done-file exit barrier (parallel/hostproc.py): process 0
    # hosts the coordination service and must outlive its peers; skip
    # the atexit shutdown barrier (results are already durable)
    exit_after_barrier(Path(spec["out"]).parent, pid, nproc)


def _spawn_proc_row(nproc: int, iters: int) -> dict:
    """Spawn nproc clean-env hosts, aggregate their rates."""
    import subprocess
    import tempfile
    from pathlib import Path

    from deepflow_tpu.parallel.topology import free_coordinator_port

    from deepflow_tpu.parallel.hostproc import clean_cpu_env

    d = Path(tempfile.mkdtemp(prefix=f"meshprocs{nproc}-"))
    coord = f"127.0.0.1:{free_coordinator_port()}"
    here = os.path.abspath(__file__)
    procs = []
    for pid in range(nproc):
        spec = {
            "num_processes": nproc, "process_id": pid,
            "coordinator": coord, "iters": iters,
            "out": str(d / f"res.p{pid}.json"),
        }
        procs.append(subprocess.Popen(
            [sys.executable, here, "--mesh-proc", json.dumps(spec)],
            env=clean_cpu_env(1), stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True,
        ))
    per_host = []
    try:
        for pid, p in enumerate(procs):
            try:
                _out, err = p.communicate(timeout=900)
            except subprocess.TimeoutExpired:
                p.kill()
                _out, err = p.communicate()
                raise RuntimeError(
                    f"mesh proc {pid}/{nproc} timed out:\n" + err[-2000:]
                )
            if p.returncode != 0:
                raise RuntimeError(
                    f"mesh proc {pid}/{nproc} rc={p.returncode}:\n"
                    + err[-2000:]
                )
            per_host.append(
                json.loads((d / f"res.p{pid}.json").read_text())
            )
    except Exception:
        # never leak live jax.distributed children (a wedged process 0
        # would also keep the coordinator port bound for the next row)
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise
    agg = round(sum(r["rec_s"] for r in per_host), 1)
    return {
        "n_processes": nproc,
        "aggregate_rec_s": agg,
        "per_host_rec_s": [r["rec_s"] for r in per_host],
        "records": sum(r["records"] for r in per_host),
        "init_s_max": max(r["init_s"] for r in per_host),
        "host_fetches": [r["host_fetches"] for r in per_host],
    }


def run_procs(proc_counts: list[int], iters: int,
              rows: list[dict] | None = None) -> list[dict]:
    """Appends each completed row into `rows` AS IT LANDS, so a later
    process count's failure still leaves the finished rows for the
    partial record (the bench.py contract)."""
    rows = [] if rows is None else rows
    base = None
    for n in proc_counts:
        row = _spawn_proc_row(n, iters)
        if base is None and row["n_processes"] == 1:
            base = row["aggregate_rec_s"]
        if base:
            row["scale_vs_1proc"] = round(row["aggregate_rec_s"] / base, 2)
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# rebalance-pause recipe (ISSUE 15)


def _rebalance_row(preload_steps: int, iters: int) -> dict:
    """One pause measurement at one state size, in a scratch dir that
    is removed afterward (the large-preload checkpoints are exactly
    the rows the state sweep makes big — repeated runs must not
    accumulate them in /tmp)."""
    import shutil
    import tempfile
    from pathlib import Path

    d = Path(tempfile.mkdtemp(prefix="meshreb-"))
    try:
        return _rebalance_row_in(preload_steps, iters, d)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _rebalance_row_in(preload_steps: int, iters: int, d) -> dict:
    """One pause measurement at one state size. Both topology views
    live in THIS process (MeshTopology.standalone — the protocol is
    control-plane only, so the pause does not depend on which process
    hosts which half), which keeps the row a protocol cost, not a
    process-spawn cost."""

    from deepflow_tpu.aggregator.checkpoint import save_sharded_state
    from deepflow_tpu.feeder import FeederConfig, encode_flowbatch_frames
    from deepflow_tpu.ingest.queues import PyOverwriteQueue
    from deepflow_tpu.parallel.rebalance import GroupRebalancer
    from deepflow_tpu.parallel.topology import MeshTopology

    group, old_pid, new_pid = 1, 1, 0
    cfg = ShardedConfig(
        capacity_per_device=1 << 13,
        num_services=64,
        hll_precision=8,
        hist=LogHistSpec(bins=128, vmin=1.0, gamma=1.1),
    )
    buckets = (512, 1024, 2048)
    t0s = 1_700_000_000
    gen = SyntheticFlowGen(num_tuples=2000, seed=41)
    ckpt = d / "handover.ckpt"

    def build(pid, topology=None):
        topo = topology if topology is not None else MeshTopology.standalone(
            pid, 2, n_groups=2, devices_per_group=1
        )
        wm = ShardedWindowManager(
            ShardedPipeline(topo, cfg, shard_group=group)
        )
        queues = [PyOverwriteQueue(1 << 12)]
        jdir = d / f"p{pid}"
        jdir.mkdir(exist_ok=True)
        feeder = wm.make_feeder(
            queues, buckets, FeederConfig(frames_per_queue=16),
            journal_dir=jdir,
        )
        return topo, wm, queues, feeder

    def step(queues, feeder, i):
        n = buckets[i % len(buckets)] - (31 * i) % 128
        for fr in encode_flowbatch_frames(
            gen.flow_batch(n, t0s + 10 + i // 4),
            agent_id=i, max_rows_per_frame=512,
        ):
            queues[0].put(fr)
        feeder.pump()
        return n

    old_topo, wm_old, queues_old, feeder_old = build(old_pid)
    # warm compiles, then preload to the target state size
    records = 0
    for i in range(preload_steps):
        records += step(queues_old, feeder_old, i)
    # steady cadence before the handover
    t0 = time.perf_counter()
    pre_records = sum(
        step(queues_old, feeder_old, preload_steps + i)
        for i in range(iters)
    )
    pre_s = time.perf_counter() - t0
    pre_step_s = pre_s / iters
    records += pre_records
    # the group state the checkpoint actually captures: everything fed
    # BEFORE the handover (recovery/post traffic is measurement-only)
    records_at_handover = records

    # -- the pause: release on the old owner ... -------------------------
    reb_old = GroupRebalancer(old_topo)
    plan = reb_old.plan(group, new_pid)
    t_pause = time.perf_counter()
    reb_old.release(
        plan, feeder=feeder_old,
        save=lambda extra: save_sharded_state(
            wm_old, ckpt, extra_meta=extra
        ),
    )
    release_ms = (time.perf_counter() - t_pause) * 1e3
    # -- ... adopt on the new owner --------------------------------------
    reb_new = GroupRebalancer(
        MeshTopology.standalone(new_pid, 2, n_groups=2, devices_per_group=1)
    )
    plan2 = reb_new.plan(group, new_pid)
    reb_new.claim(plan2)
    t1 = time.perf_counter()
    _topo, wm_new, queues_new, feeder_new = build(
        new_pid, topology=plan2.topology
    )
    build_ms = (time.perf_counter() - t1) * 1e3
    t1 = time.perf_counter()
    reb_new.adopt(plan2, swm=wm_new, ckpt_path=str(ckpt))
    restore_ms = (time.perf_counter() - t1) * 1e3
    pause_ms = (time.perf_counter() - t_pause) * 1e3

    # recovery: the first pump pays the fresh manager's compiles; walk
    # the cadence until a step lands back inside 1.5× the pre-handover
    # steady step
    t1 = time.perf_counter()
    records += step(queues_new, feeder_new, preload_steps + iters)
    first_pump_ms = (time.perf_counter() - t1) * 1e3
    recovery_steps = 1
    t_rec = time.perf_counter()
    for i in range(1, 4 * iters):
        t1 = time.perf_counter()
        records += step(queues_new, feeder_new, preload_steps + iters + i)
        recovery_steps += 1
        if time.perf_counter() - t1 <= 1.5 * pre_step_s:
            break
    recovery_ms = first_pump_ms + (time.perf_counter() - t_rec) * 1e3
    t0 = time.perf_counter()
    post_records = sum(
        step(queues_new, feeder_new, preload_steps + 5 * iters + i)
        for i in range(iters)
    )
    post_s = time.perf_counter() - t0
    return {
        "preload_steps": preload_steps,
        "records_at_handover": int(records_at_handover),
        "ckpt_bytes": int(os.path.getsize(ckpt)),
        "pause_ms": round(pause_ms, 2),
        "release_ms": round(release_ms, 2),
        "build_ms": round(build_ms, 2),
        "restore_ms": round(restore_ms, 2),
        "first_pump_ms": round(first_pump_ms, 2),
        "recovery_ms": round(recovery_ms, 2),
        "recovery_steps": recovery_steps,
        "pre_rec_s": round(pre_records / max(pre_s, 1e-9), 1),
        "post_rec_s": round(post_records / max(post_s, 1e-9), 1),
    }


def run_rebalance(preloads: list[int], iters: int,
                  rows: list[dict] | None = None) -> list[dict]:
    rows = [] if rows is None else rows
    for p in preloads:
        rows.append(_rebalance_row(p, iters))
    return rows


def main():
    reb_env = os.environ.get("MESH_REBALANCE", "")
    if reb_env:
        preloads = [
            int(p) for p in os.environ.get(
                "MESH_REBALANCE_PRELOADS", "8,32"
            ).split(",") if p
        ]
        iters = int(os.environ.get("MESHBENCH_ITERS", 24))
        rows = []
        try:
            run_rebalance(preloads, iters, rows)
            print(json.dumps({"rebalance_rows": rows}), flush=True)
        except Exception as e:  # parseable partial, never a traceback
            print(
                json.dumps({
                    "rebalance_rows": rows, "partial": True,
                    "error": repr(e),
                }),
                flush=True,
            )
        return
    proc_env = os.environ.get("MESH_PROCS", "")
    if proc_env:
        proc_counts = [int(p) for p in proc_env.split(",") if p]
        iters = int(os.environ.get("MESHBENCH_ITERS", 48))
        rows = []
        try:
            run_procs(proc_counts, iters, rows)
            print(json.dumps({"proc_rows": rows}), flush=True)
        except Exception as e:  # parseable partial, never a traceback
            print(
                json.dumps(
                    {"proc_rows": rows, "partial": True, "error": repr(e)}
                ),
                flush=True,
            )
        return
    per_dev = int(os.environ.get("MESH_PER_DEV", 1 << 13))
    iters = int(os.environ.get("MESH_ITERS", 8))
    # fold-mode A/B (ISSUE 5): the windowed cadence's drain_ms is what
    # the incremental merge-fold attacks — emit before/after rows
    modes = [
        m for m in os.environ.get("MESH_FOLD_MODES", "full,merge").split(",") if m
    ]
    devices = [
        int(d) for d in os.environ.get("MESH_DEVICES", "1,2,4,8").split(",") if d
    ]
    rows = []
    try:
        for mode in modes:
            for n in devices:
                rows.append(run(n, per_dev, iters, fold_mode=mode))
        print(json.dumps({"rows": rows}), flush=True)
    except Exception as e:  # parseable partial record, never a traceback
        print(
            json.dumps({"rows": rows, "partial": True, "error": repr(e)}),
            flush=True,
        )


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--mesh-proc":
        _proc_body(json.loads(sys.argv[2]))
    else:
        main()
