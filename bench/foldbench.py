#!/usr/bin/env python
"""Fold-stage microbench: full-sort fold vs incremental merge-fold
(ISSUE 5), the §7a chained-sync recipe.

The windowed advance is fold-dominated on the mesh path (PERF.md §12
drain_ms); the merge-fold replaces the O((S+A) log(S+A)) 3-key re-sort
of the whole stash+accumulator concat with an O(A log A) accumulator
sort + a rank-merge against the standing stash order
(aggregator/stash.py). This harness times three variants over the SAME
state at {stash_rows} × {acc_rows} grid points, threading the stash
through K iterations (chained — no host round trip inside the loop,
one measured fetch subtracted):

  full        _fold_impl          — the shipped full-sort oracle
  merge       _merge_fold_impl    — full-set rank-merge (capacity folds)
  merge_span  _merge_fold_impl hi — span-bounded advance fold (~1/4 of
                                    the acc's windows close)

A second section (ISSUE 17) A/Bs the SKETCH-plane fold in isolation:
`sketch_plane_step` with the per-hash-row multi-sort oracle vs the
one-pass shared sort, at FOLDBENCH_PLANE_ROWS row counts — emitted as
a separate `plane_rows` list so fold-row parsers are untouched.

Knobs: FOLDBENCH_SHAPES="S:A,S:A,..." (default
65536:8192,65536:65536,262144:8192,262144:65536,589824:8192,589824:65536,
2097152:8192,2097152:65536 — the ISSUE 5 grid), FOLDBENCH_ITERS (4),
FOLDBENCH_PLANE_ROWS (65536,262144), DEEPFLOW_MERGE_SCATTER=1 for the
scatter merged-order A/B (on-chip).

Prints ONE JSON line {"rows": [...]}; on failure a partial-but-
parseable record (bench.py convention). Full production schema
(TAG_SCHEMA × FLOW_METER) — the real fold payload widths.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from deepflow_tpu.aggregator.stash import (  # noqa: E402
    AccumState,
    _fold_impl,
    _merge_fold_impl,
    stash_fold,
    stash_init,
)
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA  # noqa: E402
from deepflow_tpu.ops.segment import SENTINEL_SLOT  # noqa: E402

SUM_COLS = tuple(int(i) for i in np.nonzero(FLOW_METER.sum_mask)[0])
MAX_COLS = tuple(int(i) for i in np.nonzero(FLOW_METER.max_mask)[0])
N_WINDOWS = 8  # live windows the synthetic stash spans


def _synthetic_acc(rng, cap, fill, key_space, t_cols, m_cols) -> AccumState:
    slot = np.full(cap, SENTINEL_SLOT, np.uint32)
    hi = np.zeros(cap, np.uint32)
    lo = np.zeros(cap, np.uint32)
    keys = rng.integers(0, key_space, fill).astype(np.uint64)
    slot[:fill] = (1 + keys % N_WINDOWS).astype(np.uint32)
    # spread keys over both 32-bit lanes like the real fingerprint
    hi[:fill] = (keys * np.uint64(2654435761) >> np.uint64(13)).astype(np.uint32)
    lo[:fill] = (keys * np.uint64(40503) + np.uint64(7)).astype(np.uint32)
    tags = np.zeros((t_cols, cap), np.uint32)
    tags[0, :fill] = keys.astype(np.uint32)
    meters = np.zeros((m_cols, cap), np.float32)
    meters[:, :fill] = rng.normal(size=(m_cols, fill)).astype(np.float32)
    return AccumState(
        slot=jnp.asarray(slot),
        key_hi=jnp.asarray(hi),
        key_lo=jnp.asarray(lo),
        tags=jnp.asarray(tags),
        meters=jnp.asarray(meters),
    )


def _chained(name, fn, state, acc, iters):
    t0 = time.perf_counter()
    state = fn(state, acc)
    _ = np.asarray(state.slot[:1])
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    _ = np.asarray(state.slot[:1])
    fetch = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _i in range(iters):
        state = fn(state, acc)
    _ = np.asarray(state.slot[:1])
    ms = (time.perf_counter() - t0 - fetch) / iters * 1e3
    print(
        f"  {name:12s} compile {compile_s:6.1f}s  steady {ms:9.2f} ms",
        file=sys.stderr, flush=True,
    )
    return ms


def run_shape(s_rows: int, a_rows: int, iters: int) -> dict:
    rng = np.random.default_rng(5)
    t_cols = TAG_SCHEMA.num_fields
    m_cols = FLOW_METER.num_fields
    # canonical stash at ~85% occupancy: one oracle fold of unique keys
    live = int(s_rows * 0.85)
    state = stash_init(s_rows, TAG_SCHEMA, FLOW_METER)
    seed_acc = _synthetic_acc(
        rng, live, live, key_space=live * 4, t_cols=t_cols, m_cols=m_cols
    )
    state, _ = stash_fold(state, seed_acc, FLOW_METER)
    # the benched acc: half its keys collide with stash keys
    acc = _synthetic_acc(
        rng, a_rows, a_rows, key_space=live * 4, t_cols=t_cols, m_cols=m_cols
    )

    # no donation: the SAME acc re-folds every iteration (steady-state
    # work — after the first fold the stash key set is stationary)
    full = jax.jit(lambda st, ac: _fold_impl(st, ac, SUM_COLS, MAX_COLS)[0])
    merge = jax.jit(
        lambda st, ac: _merge_fold_impl(
            st, ac, jnp.uint32(SENTINEL_SLOT), SUM_COLS, MAX_COLS
        )[0]
    )
    span_hi = jnp.uint32(1 + N_WINDOWS // 4)  # ~1/4 of windows close
    merge_span = jax.jit(
        lambda st, ac: _merge_fold_impl(st, ac, span_hi, SUM_COLS, MAX_COLS)[0]
    )

    print(f"stash={s_rows} acc={a_rows}", file=sys.stderr, flush=True)
    full_ms = _chained("full", full, state, acc, iters)
    merge_ms = _chained("merge", merge, state, acc, iters)
    span_ms = _chained("merge_span", merge_span, state, acc, iters)
    return {
        "stash_rows": s_rows,
        "acc_rows": a_rows,
        "live_rows": live,
        "iters": iters,
        "full_ms": round(full_ms, 3),
        "merge_ms": round(merge_ms, 3),
        "merge_span_ms": round(span_ms, 3),
        "speedup_full_vs_merge": round(full_ms / max(merge_ms, 1e-9), 3),
        "speedup_full_vs_span": round(full_ms / max(span_ms, 1e-9), 3),
        "merge_scatter": os.environ.get("DEEPFLOW_MERGE_SCATTER", "0") == "1",
    }


def run_plane_shape(n_rows: int, iters: int) -> dict:
    """Shared-sort A/B of the sketch-plane fold itself (ISSUE 17): the
    SAME batch through `sketch_plane_step` with the multi-sort oracle
    (shared_sort=False, one keyed sort per top-K hash row × phase) vs
    the one-pass rewrite (shared_sort=True, one sort total). This is
    the plane in isolation — bench/sortbench.py times it embedded in
    the full windowed ingest."""
    from deepflow_tpu.aggregator.sketchplane import (
        SketchConfig,
        sketch_init,
        sketch_plane_step,
    )
    from deepflow_tpu.ops.histogram import LogHistSpec

    cfg = SketchConfig(
        num_groups=8, hll_precision=14, cms_depth=4, cms_width=1 << 16,
        hist=LogHistSpec(bins=128, vmin=1.0, gamma=1.1),
        topk_rows=2, topk_cols=1024, pending=8,
    )
    rng = np.random.default_rng(9)
    base_w, close_w = jnp.uint32(10), jnp.uint32(11)
    keys = rng.integers(0, 1 << 20, n_rows).astype(np.uint64)
    lanes = dict(
        window=jnp.asarray(rng.integers(10, 12, n_rows).astype(np.uint32)),
        valid=jnp.asarray(np.ones(n_rows, bool)),
        group=jnp.asarray((keys % 8).astype(np.uint32)),
        client_hi=jnp.asarray((keys * np.uint64(2654435761)
                               >> np.uint64(13)).astype(np.uint32)),
        client_lo=jnp.asarray((keys * np.uint64(40503)).astype(np.uint32)),
        key_hi=jnp.asarray((keys >> np.uint64(1)).astype(np.uint32)),
        key_lo=jnp.asarray(keys.astype(np.uint32)),
        weight=jnp.asarray(
            rng.integers(1, 500, n_rows).astype(np.float32)),
        rtt=jnp.asarray(np.full(n_rows, 10.0, np.float32)),
        rtt_valid=jnp.asarray(np.ones(n_rows, bool)),
        id_a=jnp.asarray((keys ^ np.uint64(0x9E3779B9)).astype(np.uint32)),
        id_b=jnp.asarray((keys + np.uint64(7)).astype(np.uint32)),
    )

    def mk(shared: bool):
        def f(sk, **kw):
            return sketch_plane_step(
                sk, cfg.hist, base_w=base_w, close_w=close_w,
                shared_sort=shared, fused_sketch=False, **kw,
            )
        return jax.jit(f)

    row = {"plane_rows": n_rows, "iters": iters}
    for name, shared in (("plane_multisort", False), ("plane_onepass", True)):
        fn = mk(shared)
        sk = fn(sketch_init(cfg, 4), **lanes)
        _ = np.asarray(sk.rows)  # compile + settle
        t0 = time.perf_counter()
        for _i in range(iters):
            sk = fn(sk, **lanes)
        _ = np.asarray(sk.rows)
        ms = (time.perf_counter() - t0) / iters * 1e3
        row[f"{name}_ms"] = round(ms, 3)
        print(f"  {name:16s} steady {ms:9.2f} ms", file=sys.stderr,
              flush=True)
    row["speedup_multisort_vs_onepass"] = round(
        row["plane_multisort_ms"] / max(row["plane_onepass_ms"], 1e-9), 3)
    return row


def run_tier_shape(s_rows: int, a_rows: int, iters: int) -> dict:
    """Cascade tier-ring-fold A/B (ISSUE 20): `_ring_fold_impl` with
    the full [S+A] keyed sort (shared_sort=False, the pre-r20 shipped
    path) vs the shared-sort rank-merge that reuses the tier stash's
    dispatch-owned canonical order (shared_sort=True — sorts only the
    [A] ring). Both run over the SAME canonical tier stash + ring;
    the first iteration cross-checks bit-exactness before timing."""
    from deepflow_tpu.aggregator.cascade import _ring_fold_impl

    rng = np.random.default_rng(11)
    t_cols = TAG_SCHEMA.num_fields
    m_cols = FLOW_METER.num_fields
    live = int(s_rows * 0.85)
    tier = stash_init(s_rows, TAG_SCHEMA, FLOW_METER)
    seed_acc = _synthetic_acc(
        rng, live, live, key_space=live * 4, t_cols=t_cols, m_cols=m_cols
    )
    tier, _ = stash_fold(tier, seed_acc, FLOW_METER)  # canonical
    acc = _synthetic_acc(
        rng, a_rows, a_rows, key_space=live * 4, t_cols=t_cols, m_cols=m_cols
    )
    lanes = jnp.zeros((2,), jnp.uint32)

    def mk(shared: bool):
        return jax.jit(
            lambda st, ac: _ring_fold_impl(
                st, ac, lanes, SUM_COLS, MAX_COLS, shared_sort=shared
            )[0]
        )

    full_fn, shared_fn = mk(False), mk(True)
    a_state = full_fn(tier, acc)
    b_state = shared_fn(tier, acc)
    for f in ("slot", "key_hi", "key_lo", "tags", "meters", "valid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a_state, f)), np.asarray(getattr(b_state, f)),
            err_msg=f"tier ring fold shared-sort mismatch in {f}",
        )

    print(f"tier stash={s_rows} ring={a_rows}", file=sys.stderr, flush=True)
    full_ms = _chained("tier_full", full_fn, tier, acc, iters)
    shared_ms = _chained("tier_shared", shared_fn, tier, acc, iters)
    return {
        "tier_stash_rows": s_rows,
        "tier_ring_rows": a_rows,
        "iters": iters,
        "tier_full_ms": round(full_ms, 3),
        "tier_shared_ms": round(shared_ms, 3),
        "speedup_tier_full_vs_shared": round(
            full_ms / max(shared_ms, 1e-9), 3
        ),
        "shared_sort_default": os.environ.get(
            "DEEPFLOW_SHARED_SORT", "1") != "0",
    }


def main():
    default = (
        "65536:8192,65536:65536,262144:8192,262144:65536,"
        "589824:8192,589824:65536,2097152:8192,2097152:65536"
    )
    shapes = [
        tuple(int(v) for v in part.split(":"))
        for part in os.environ.get("FOLDBENCH_SHAPES", default).split(",")
        if part
    ]
    iters = int(os.environ.get("FOLDBENCH_ITERS", 4))
    plane_shapes = [
        int(v)
        for v in os.environ.get("FOLDBENCH_PLANE_ROWS", "65536,262144").split(",")
        if v
    ]
    tier_shapes = [
        tuple(int(v) for v in part.split(":"))
        for part in os.environ.get(
            "FOLDBENCH_TIER_SHAPES", "65536:8192,262144:65536").split(",")
        if part
    ]
    rows = []
    plane_rows = []
    tier_rows = []
    try:
        for s_rows, a_rows in shapes:
            rows.append(run_shape(s_rows, a_rows, iters))
        for n_rows in plane_shapes:
            plane_rows.append(run_plane_shape(n_rows, iters))
            print(json.dumps(plane_rows[-1]), file=sys.stderr, flush=True)
        for s_rows, a_rows in tier_shapes:
            tier_rows.append(run_tier_shape(s_rows, a_rows, iters))
            print(json.dumps(tier_rows[-1]), file=sys.stderr, flush=True)
        print(
            json.dumps({"rows": rows, "plane_rows": plane_rows,
                        "tier_rows": tier_rows,
                        "device": str(jax.devices()[0])}),
            flush=True,
        )
    except Exception as e:  # parseable partial record, never a traceback
        print(
            json.dumps({"rows": rows, "plane_rows": plane_rows,
                        "tier_rows": tier_rows,
                        "partial": True, "error": repr(e)}),
            flush=True,
        )


if __name__ == "__main__":
    main()
