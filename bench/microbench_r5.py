#!/usr/bin/env python
"""Round-5 append-stage bisection — run ON CHIP before any rewrite.

The r4 verdict: the append's per-raw-row linear work (~45 ns/record at
BATCH=2M) is the design floor. This harness bisects the append into
cumulative prefixes of the real pipeline graph and times each with the
chained-sync method (PERF.md §6: carry a scalar through K iterations,
one host fetch at the end), so successive deltas attribute time to:

  A  stack 25 tag cols + fingerprint64_t + slot
  B  + lax.sort((slot, hi, lo, iota))
  C  + head flags / segment-id cumsum
  D  + meter row-gather [N, 62] via perm
  E  + full-width segment_sum (num_segments=CAPU)
  F  + full-width segment_max
  G  = full batch_prereduce (adds head positions + tag gathers)
  H  = full append (prereduce + fanout + key fingerprint + accum write)

r6 variants (the levers that replaced D/E/F and part of A):
  2  A with the fingerprint folding dict columns directly (no stack)
  3  A with the PACKED-word fingerprint (datamodel/code.py plans)
  p  C + fused Pallas suffix reduce: the kernel gathers rows THROUGH
     the sort permutation (no standalone D gather pass at all)
  q  C + standalone row-gather (D) + pre-gathered Pallas suffix reduce
     (the r5 shipped shape) — q − p is the row-gather's residual cost

G/H always time the CURRENT production graph, so after the r6 rebuild
they include the packed fingerprint and (on TPU / forced pallas) the
fused kernel; compare p vs q and 3 vs A on-chip to attribute the wins.

Usage: python bench/microbench_r5.py [--batch 2097152] [--capu 32768]
                                     [--stages abcdefgh23pq]
Copy results into PERF.md.
"""

from __future__ import annotations

import argparse
import sys
import time
from functools import partial

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepflow_tpu.aggregator.fanout import FANOUT_LANES, FanoutConfig
from deepflow_tpu.aggregator.pipeline import batch_prereduce, make_ingest_step
from deepflow_tpu.aggregator.stash import accum_init, stash_init
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.ingest.replay import SyntheticFlowGen
from deepflow_tpu.ops.hashing import fingerprint64_t

SUM_COLS = np.nonzero(FLOW_METER.sum_mask)[0].astype(np.int32)
MAX_COLS = np.nonzero(FLOW_METER.max_mask)[0].astype(np.int32)


def _prep(tags, c):
    """Mix the carry into one tag column (bijective per iteration — the
    unique-key structure is preserved) and stack columns like the real
    pre-reduce does."""
    tags = dict(tags)
    tags["ip0_w3"] = tags["ip0_w3"] ^ c
    names = sorted(tags)
    tags_t = jnp.stack([jnp.asarray(tags[k], jnp.uint32) for k in names])
    slot = jnp.asarray(tags["timestamp"], jnp.uint32)
    return tags_t, slot


def stage_a(c, tags, meters, valid):
    tags_t, slot = _prep(tags, c)
    hi, lo = fingerprint64_t(tags_t)
    return c ^ hi[0] ^ lo[0] ^ slot[0]


def _sorted(c, tags, valid):
    tags_t, slot = _prep(tags, c)
    hi, lo = fingerprint64_t(tags_t)
    n = slot.shape[0]
    slot = jnp.where(valid, slot, jnp.uint32(0xFFFFFFFF))
    hi = jnp.where(valid, hi, jnp.uint32(0xFFFFFFFF))
    lo = jnp.where(valid, lo, jnp.uint32(0xFFFFFFFF))
    iota = jnp.arange(n, dtype=jnp.int32)
    return lax.sort((slot, hi, lo, iota), num_keys=3), tags_t


def stage_b(c, tags, meters, valid):
    (s_slot, s_hi, s_lo, perm), _ = _sorted(c, tags, valid)
    return c ^ s_hi[0] ^ s_lo[0] ^ jnp.uint32(perm[0])


def _segids(sorted_lanes):
    s_slot, s_hi, s_lo, perm = sorted_lanes
    n = s_slot.shape[0]
    head = jnp.concatenate(
        [jnp.ones((1,), bool),
         (s_slot[1:] != s_slot[:-1]) | (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1])]
    )
    live = s_slot != jnp.uint32(0xFFFFFFFF)
    seg_id = jnp.cumsum(head.astype(jnp.int32)) - 1
    seg_id = jnp.where(live, seg_id, n)
    num_seg = jnp.sum((head & live).astype(jnp.int32))
    return seg_id, num_seg


def stage_c(c, tags, meters, valid):
    lanes, _ = _sorted(c, tags, valid)
    seg_id, num_seg = _segids(lanes)
    return c ^ jnp.uint32(num_seg) ^ jnp.uint32(seg_id[-1])


def stage_d(c, tags, meters, valid):
    lanes, _ = _sorted(c, tags, valid)
    seg_id, num_seg = _segids(lanes)
    rows = jnp.take(meters, lanes[3], axis=0)  # [N, M]
    return c ^ jnp.uint32(num_seg) ^ rows[0, 0].astype(jnp.uint32)


def _stage_ef(c, tags, meters, valid, capu, with_max):
    lanes, _ = _sorted(c, tags, valid)
    seg_id, num_seg = _segids(lanes)
    rows = jnp.take(meters, lanes[3], axis=0)
    ps = jax.ops.segment_sum(rows, seg_id, num_segments=capu, indices_are_sorted=True)
    out = c ^ ps[0, 0].astype(jnp.uint32)
    if with_max:
        pm = jax.ops.segment_max(rows, seg_id, num_segments=capu, indices_are_sorted=True)
        out = out ^ pm[0, 0].astype(jnp.uint32)
    return out ^ jnp.uint32(num_seg)


def stage_v1(c, tags, meters, valid, capu):
    """Like F but segment_max over ONLY the 9 max-semantic lanes,
    gathered as a separate narrow [N, 9] matrix."""
    lanes, tags_t = _sorted(c, tags, valid)
    seg_id, num_seg = _segids(lanes)
    rows = jnp.take(meters, lanes[3], axis=0)
    ps = jax.ops.segment_sum(rows, seg_id, num_segments=capu, indices_are_sorted=True)
    max_rows = jnp.take(meters[:, MAX_COLS], lanes[3], axis=0)  # [N, 9]
    pm = jax.ops.segment_max(max_rows, seg_id, num_segments=capu, indices_are_sorted=True)
    return c ^ ps[0, 0].astype(jnp.uint32) ^ pm[0, 0].astype(jnp.uint32) ^ jnp.uint32(num_seg)


def stage_v2(c, tags, meters, valid):
    """Like A but fingerprint folds the dict columns directly — no
    [T, N] stack materialization."""
    from deepflow_tpu.ops.hashing import SEED_HI, SEED_LO, _fold

    tags = dict(tags)
    tags["ip0_w3"] = tags["ip0_w3"] ^ c
    names = sorted(tags)
    cols = [jnp.asarray(tags[k], jnp.uint32) for k in names]
    hi = _fold(cols, SEED_HI, jnp)
    lo = _fold(cols, SEED_LO, jnp)
    slot = jnp.asarray(tags["timestamp"], jnp.uint32)
    return c ^ hi[0] ^ lo[0] ^ slot[0]


def stage_v3(c, tags, meters, valid):
    """Like A but with the r6 packed-word fingerprint: bin-packed u32
    key words built once, both seeds fold ~23 words instead of 37."""
    from deepflow_tpu.datamodel.code import RAW_TAG_PACK, pack_tag_words
    from deepflow_tpu.ops.hashing import fingerprint64_words

    tags = dict(tags)
    tags["ip0_w3"] = tags["ip0_w3"] ^ c
    hi, lo = fingerprint64_words(pack_tag_words(tags, RAW_TAG_PACK, jnp))
    slot = jnp.asarray(tags["timestamp"], jnp.uint32)
    return c ^ hi[0] ^ lo[0] ^ slot[0]


def _stage_pallas(c, tags, meters, valid, capu, fused):
    """C + the Pallas suffix reduce. fused=True: the kernel gathers
    meter rows through the sort permutation (NO standalone row-gather
    stage); fused=False: the r5 shape (D's take, then the kernel)."""
    from deepflow_tpu.ops.segreduce_pallas import sorted_segment_sum_max

    lanes, _ = _sorted(c, tags, valid)
    seg_id, num_seg = _segids(lanes)
    first_pos = jnp.searchsorted(seg_id, jnp.arange(capu, dtype=jnp.int32))
    if fused:
        ps, pm = sorted_segment_sum_max(
            meters, seg_id, capu, first_pos, perm=lanes[3]
        )
    else:
        rows = jnp.take(meters, lanes[3], axis=0)
        ps, pm = sorted_segment_sum_max(rows, seg_id, capu, first_pos)
    return (c ^ ps[0, 0].astype(jnp.uint32) ^ pm[0, 0].astype(jnp.uint32)
            ^ jnp.uint32(num_seg))


def stage_g(c, tags, meters, valid, capu):
    tags = dict(tags)
    tags["ip0_w3"] = tags["ip0_w3"] ^ c
    r_tags, r_meters, r_valid, dropped = batch_prereduce(
        tags, meters, valid, 1, capu, SUM_COLS, MAX_COLS
    )
    return (c ^ r_tags["ip0_w3"][0] ^ r_meters[0, 0].astype(jnp.uint32)
            ^ jnp.uint32(dropped))


def chained(name, fn, iters=6):
    c = jnp.uint32(1)
    t0 = time.perf_counter()
    c = fn(c)
    _ = np.asarray(c)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter(); _ = np.asarray(c)
    fetch = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        c = fn(c)
    _ = np.asarray(c)
    ms = (time.perf_counter() - t0 - fetch) / iters * 1e3
    print(f"{name:44s} compile {compile_s:6.1f}s  steady {ms:9.2f} ms", flush=True)
    return ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 21)
    ap.add_argument("--capu", type=int, default=1 << 15)
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--stages", default="abcdefgh")
    args = ap.parse_args()
    N, CAPU = args.batch, args.capu

    gen = SyntheticFlowGen(num_tuples=10_000, seed=0)
    fb = gen.flow_batch(N, 1_700_000_000)
    tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
    meters = jnp.asarray(fb.meters)
    valid = jnp.asarray(fb.valid)
    print(f"batch={N} capu={CAPU} device={jax.devices()[0]}", flush=True)

    res = {}
    # jit wrappers hoisted ONCE — a fresh jit(lambda) per call would
    # recompile every iteration and time compiles, not kernels
    jit_a = jax.jit(stage_a)
    jit_b = jax.jit(stage_b)
    jit_c = jax.jit(stage_c)
    jit_d = jax.jit(stage_d)
    jit_e = jax.jit(partial(_stage_ef, capu=CAPU, with_max=False))
    jit_f = jax.jit(partial(_stage_ef, capu=CAPU, with_max=True))
    jit_g = jax.jit(partial(stage_g, capu=CAPU))
    jit_v1 = jax.jit(partial(stage_v1, capu=CAPU))
    jit_v2 = jax.jit(stage_v2)
    jit_v3 = jax.jit(stage_v3)
    jit_p = jax.jit(partial(_stage_pallas, capu=CAPU, fused=True))
    jit_q = jax.jit(partial(_stage_pallas, capu=CAPU, fused=False))
    stages = {
        "1": ("V1 narrow segment_max", lambda c: jit_v1(c, tags, meters, valid)),
        "2": ("V2 destacked fingerprint", lambda c: jit_v2(c, tags, meters, valid)),
        "3": ("V3 packed-word fingerprint", lambda c: jit_v3(c, tags, meters, valid)),
        "p": ("P fused-gather pallas reduce", lambda c: jit_p(c, tags, meters, valid)),
        "q": ("Q pregather pallas reduce", lambda c: jit_q(c, tags, meters, valid)),
        "a": ("A stack+fingerprint", lambda c: jit_a(c, tags, meters, valid)),
        "b": ("B +sort4", lambda c: jit_b(c, tags, meters, valid)),
        "c": ("C +segids", lambda c: jit_c(c, tags, meters, valid)),
        "d": ("D +meter row-gather", lambda c: jit_d(c, tags, meters, valid)),
        "e": ("E +segment_sum", lambda c: jit_e(c, tags, meters, valid)),
        "f": ("F +segment_max", lambda c: jit_f(c, tags, meters, valid)),
        "g": ("G full batch_prereduce", lambda c: jit_g(c, tags, meters, valid)),
    }
    for key, (name, fn) in stages.items():
        if key in args.stages:
            res[key] = chained(name, fn, args.iters)

    if "h" in args.stages:
        append_fn, _ = make_ingest_step(FanoutConfig(), interval=1, batch_unique_cap=CAPU)
        append = jax.jit(append_fn, donate_argnums=(0, 1))
        stride = FANOUT_LANES * CAPU
        state = stash_init(1 << 16, TAG_SCHEMA, FLOW_METER)
        acc = accum_init(2 * stride, TAG_SCHEMA, FLOW_METER)

        t0 = time.perf_counter()
        state, acc = append(state, acc, jnp.int32(0), tags, meters, valid)
        _ = np.asarray(state.dropped_overflow)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter(); _ = np.asarray(state.dropped_overflow)
        fetch = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(args.iters):
            state, acc = append(state, acc, jnp.int32(0), tags, meters, valid)
        _ = np.asarray(state.dropped_overflow)
        ms = (time.perf_counter() - t0 - fetch) / args.iters * 1e3
        print(f"{'H full append':44s} compile {compile_s:6.1f}s  steady {ms:9.2f} ms", flush=True)
        res["h"] = ms

    order = [k for k in "abcdefgh" if k in res]
    print("\ndeltas:")
    prev = 0.0
    for k in order:
        print(f"  {k}: {res[k] - prev:+8.2f} ms  (cum {res[k]:8.2f})")
        prev = res[k]
    if "h" in res:
        print(f"\nns/record at H: {res['h'] * 1e6 / N:.1f}")


if __name__ == "__main__":
    main()
