#!/usr/bin/env python
"""Windowed-path microbench (ISSUE 2 acceptance): steady L4Pipeline
ingest with one window close per batch — the end-to-end windowed rate
the product ships through (append + bookkeeping + flush + DocBatch
emission), NOT the raw append kernel rate.

Usage: python bench/winbench_probe.py [repo_root]   (default: parent)
Prints one JSON line {"rec_s", "docs", "batch", "iters"}.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)
sys.path.insert(0, root)

import numpy as np  # noqa: E402

from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig  # noqa: E402
from deepflow_tpu.aggregator.window import WindowConfig  # noqa: E402
from deepflow_tpu.ingest.replay import SyntheticFlowGen  # noqa: E402


def main():
    batch = int(os.environ.get("WINBENCH_BATCH", 1024))
    iters = int(os.environ.get("WINBENCH_ITERS", 60))
    wcfg = {"capacity": 1 << 14}
    if os.environ.get("WINBENCH_ASYNC") == "1":  # double-buffered drain
        wcfg["async_drain"] = True
    try:
        window = WindowConfig(**wcfg)
    except TypeError:  # pre-r7 WindowConfig has no async_drain
        window = WindowConfig(capacity=1 << 14)
    pipe = L4Pipeline(
        PipelineConfig(window=window, batch_size=batch)
    )
    gen = SyntheticFlowGen(num_tuples=2000, seed=0)
    t0 = 1_700_000_000
    # warm every compile path: first batch, steady, advance+flush
    for t in (t0, t0 + 1, t0 + 4, t0 + 5):
        pipe.ingest(gen.flow_batch(batch, t))
    # one window closes per timed batch (interval 1, delay 2)
    batches = [gen.flow_batch(batch, t0 + 10 + i) for i in range(iters)]
    start = time.perf_counter()
    docs = 0
    for fb in batches:
        docs += sum(db.size for db in pipe.ingest(fb))
    docs += sum(db.size for db in pipe.drain())
    elapsed = time.perf_counter() - start
    rec = {
        "rec_s": round(batch * iters / elapsed, 1),
        "docs": docs,
        "batch": batch,
        "iters": iters,
    }
    try:  # stage attribution (ISSUE 3): counter block + span summary
        rec["telemetry"] = pipe.telemetry()
    except Exception as e:  # pre-telemetry pipeline — record why, not crash
        rec["telemetry"] = None
        rec["telemetry_error"] = repr(e)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
