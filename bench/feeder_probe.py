#!/usr/bin/env python
"""Feeder-runtime microbench (ISSUE 4 acceptance): multi-queue fan-in →
shape-bucketed coalescing → the fused windowed step with a K-batch
counter ring — the full wire-to-window path the product ships through
(frame decode + bucket assembly + double-buffered upload + append +
flush), NOT the raw append kernel rate.

Usage: python bench/feeder_probe.py [repo_root]   (default: parent)
Prints one JSON line with rec_s, host-fetch-per-batch and shed/retrace
accounting. Knobs: FEEDER_ITERS, FEEDER_QUEUES, FEEDER_K,
FEEDER_BUCKETS (comma list). CPU-container numbers demonstrate the
host-overhead half only; on-chip columns are pending per the r6+r7
measurement-debt item (PERF.md §14).
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)
sys.path.insert(0, root)

from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig  # noqa: E402
from deepflow_tpu.aggregator.window import WindowConfig  # noqa: E402
from deepflow_tpu.feeder import (  # noqa: E402
    FeederConfig,
    FeederRuntime,
    PipelineFeedSink,
    encode_flowbatch_frames,
)
from deepflow_tpu.ingest.queues import PyOverwriteQueue  # noqa: E402
from deepflow_tpu.ingest.replay import SyntheticFlowGen  # noqa: E402


def main():
    iters = int(os.environ.get("FEEDER_ITERS", 48))
    n_queues = int(os.environ.get("FEEDER_QUEUES", 4))
    K = int(os.environ.get("FEEDER_K", 4))
    buckets = tuple(
        int(b) for b in os.environ.get("FEEDER_BUCKETS", "256,512,1024").split(",")
    )
    t0 = 1_700_000_000

    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 14, stats_ring=K),
        batch_size=buckets[-1], bucket_sizes=buckets,
    ))
    queues = [PyOverwriteQueue(1 << 12) for _ in range(n_queues)]
    feeder = FeederRuntime(
        queues, PipelineFeedSink(pipe),
        FeederConfig(frames_per_queue=16),
    )
    gen = SyntheticFlowGen(num_tuples=2000, seed=0)

    # pre-encode every step's frames: the probe times fan-in + decode +
    # coalesce + dispatch, not the synthetic generator
    sizes = [buckets[(i % len(buckets))] - (17 * i) % 64 for i in range(iters)]
    steps = []
    for i, n in enumerate(sizes):
        fb = gen.flow_batch(n, t0 + 10 + i // 4)
        steps.append(encode_flowbatch_frames(fb, agent_id=i, max_rows_per_frame=256))

    # warm every bucket's compile path
    for b in buckets:
        for fr in encode_flowbatch_frames(gen.flow_batch(b, t0), max_rows_per_frame=256):
            queues[0].put(fr)
        feeder.pump()

    c0 = pipe.get_counters()
    f0 = feeder.get_counters()
    docs = 0
    start = time.perf_counter()
    for i, frames in enumerate(steps):
        for j, fr in enumerate(frames):
            queues[j % n_queues].put(fr)
        docs += sum(db.size for db in feeder.pump())
    docs += sum(db.size for db in feeder.flush())
    docs += sum(db.size for db in pipe.drain())
    elapsed = time.perf_counter() - start

    c1 = pipe.get_counters()
    f1 = feeder.get_counters()
    records = f1["records_in"] - f0["records_in"]
    batches = f1["batches_out"] - f0["batches_out"]
    fetches = c1["host_fetches"] - c0["host_fetches"]
    rec = {
        "rec_s": round(records / elapsed, 1),
        "records": records,
        "batches": batches,
        "docs": docs,
        "iters": iters,
        "queues": n_queues,
        "stats_ring": K,
        "buckets": list(buckets),
        "host_fetches": fetches,
        "fetches_per_batch": round(fetches / max(batches, 1), 3),
        "window_advances": c1["window_advances"] - c0["window_advances"],
        "jit_retraces": c1["jit_retraces"],
        "jit_compiles": c1["jit_compiles"],
        "shed_records": f1["shed_records"],
        "pad_rows": f1["pad_rows"] - f0["pad_rows"],
    }
    try:  # stage attribution: counter block + span summaries
        rec["telemetry"] = pipe.telemetry()
        rec["feeder_telemetry"] = {
            "counters": f1,
            "spans": feeder.tracer.summary(),
        }
    except Exception as e:  # absence-tolerant (bench contract)
        rec["telemetry"] = None
        rec["telemetry_error"] = repr(e)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
