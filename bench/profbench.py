#!/usr/bin/env python
"""Device profiling plane overhead probe (ISSUE 12 acceptance): the
SAME wire-to-window feeder workload as bench/feeder_probe.py, run with
the profiling plane passive (it is always-on — registration +
span-histogram updates are unavoidable and included in BOTH sides)
versus with an AGGRESSIVE dashboard-rate consumer: every 4th pump (the
§19 livebench snapshot cadence) walks the HBM ledger + the pipeline's
span quantile face AND runs a full collector tick (tpu_hbm_*/span-p99
rows → deepflow_system + ProfileSnapshot publish on a bus). The
A/B isolates what *reading* the always-on plane costs steady-state
ingest; the acceptance bound is <2% with fetch parity (the parity
itself is CI-gated deterministically in
test_perf_gate.py::test_profiling_budget).

Also measured: the profile pull itself — `profile_snapshot()` without
analysis (the hot-path face), the first `analyze=True` pull (pays the
AOT lower+compile per bucket) and the cached repeat — the numbers
`GET /v1/profile/device` serves.

Usage: python bench/profbench.py [repo_root]   (default: parent)
Knobs: PROFBENCH_ITERS, PROFBENCH_BUCKETS (comma list).
Protocol + committed numbers: PERF.md §21, PROFBENCH_r01.json.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)
sys.path.insert(0, root)

from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig  # noqa: E402
from deepflow_tpu.aggregator.window import WindowConfig  # noqa: E402
from deepflow_tpu.feeder import (  # noqa: E402
    FeederConfig,
    FeederRuntime,
    PipelineFeedSink,
    encode_flowbatch_frames,
)
from deepflow_tpu.ingest.queues import PyOverwriteQueue  # noqa: E402
from deepflow_tpu.ingest.replay import SyntheticFlowGen  # noqa: E402


def run_mode(steps, buckets, profiled: bool):
    from deepflow_tpu.integration.dfstats import system_sink
    from deepflow_tpu.profiling import default_ledger, profile_tick_sink
    from deepflow_tpu.querier.events import QueryEventBus
    from deepflow_tpu.storage.store import ColumnarStore
    from deepflow_tpu.utils.stats import StatsCollector

    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 14, stats_ring=4),
        batch_size=buckets[-1], bucket_sizes=buckets,
    ))
    queues = [PyOverwriteQueue(1 << 12) for _ in range(4)]
    feeder = FeederRuntime(
        queues, PipelineFeedSink(pipe), FeederConfig(frames_per_queue=16),
    )
    col = bus = None
    if profiled:
        store = ColumnarStore()
        bus = QueryEventBus(name="profbench")
        col = StatsCollector()
        col.register("tpu_hbm", default_ledger)
        col.register("tpu_pipeline_spans", pipe.tracer)
        col.register("tpu_pipeline", pipe)
        col.add_sink(system_sink(store))
        col.add_sink(profile_tick_sink(bus))
    gen = SyntheticFlowGen(num_tuples=2000, seed=0)
    t0 = 1_700_000_000
    for b in buckets:  # warm every bucket's compile path
        for fr in encode_flowbatch_frames(gen.flow_batch(b, t0),
                                          max_rows_per_frame=256):
            queues[0].put(fr)
        feeder.pump()

    f0 = feeder.get_counters()
    start = time.perf_counter()
    for i, frames in enumerate(steps):
        for j, fr in enumerate(frames):
            queues[j % 4].put(fr)
        feeder.pump()
        if profiled and (i + 1) % 4 == 0:
            # the aggressive dashboard cadence (livebench's §19
            # snapshot-every-4-pumps framing): ledger walk + span
            # quantiles + the pipeline profile face + a full dogfood
            # tick (insert + ProfileSnapshot publish) every 4 batches
            default_ledger.get_counters()
            pipe.tracer.get_counters()
            pipe.profile_snapshot()
            col.tick(now=t0 + 10 + i // 4)
    feeder.flush()
    pipe.drain()
    elapsed = time.perf_counter() - start
    f1 = feeder.get_counters()
    records = f1["records_in"] - f0["records_in"]
    out = {
        "rec_s": round(records / elapsed, 1),
        "elapsed_s": round(elapsed, 4),
        "records": records,
        "host_fetches": pipe.get_counters()["host_fetches"],
        "jit_retraces": pipe.get_counters()["jit_retraces"],
    }
    if profiled:
        out["events_published"] = bus.get_counters()["events_published"]
        # the pull-path latencies the REST endpoint serves
        t = time.perf_counter()
        snap = pipe.profile_snapshot()
        out["pull_ms_no_analyze"] = round((time.perf_counter() - t) * 1e3, 3)
        t = time.perf_counter()
        full = pipe.profile_snapshot(analyze=True)
        out["pull_ms_first_analyze"] = round((time.perf_counter() - t) * 1e3, 1)
        t = time.perf_counter()
        pipe.profile_snapshot(analyze=True)
        out["pull_ms_cached_analyze"] = round((time.perf_counter() - t) * 1e3, 3)
        out["hbm_bytes"] = snap["hbm_bytes"]
        out["census"] = full["census"]
        out["span_p99_us"] = {
            k: v for k, v in pipe.tracer.get_counters().items()
            if k.endswith("p99_us")
        }
    return out


def main():
    iters = int(os.environ.get("PROFBENCH_ITERS", 48))
    buckets = tuple(
        int(b) for b in os.environ.get("PROFBENCH_BUCKETS", "256,512,1024").split(",")
    )
    gen = SyntheticFlowGen(num_tuples=2000, seed=0)
    t0 = 1_700_000_000
    sizes = [buckets[(i % len(buckets))] - (17 * i) % 64 for i in range(iters)]
    steps = [
        encode_flowbatch_frames(gen.flow_batch(n, t0 + 10 + i // 4),
                                agent_id=i, max_rows_per_frame=256)
        for i, n in enumerate(sizes)
    ]
    try:
        # throwaway full run (first-pipeline compile/alloc skew), then
        # INTERLEAVED median-of-3 per mode (the §18 cascadebench recipe
        # — this container's CPU is ±30% noisy, and a sequential A/B
        # bakes warmup drift into the sign of a small delta)
        run_mode(steps, buckets, False)
        runs = {False: [], True: []}
        for _ in range(3):
            for mode in (False, True):
                runs[mode].append(run_mode(steps, buckets, mode))

        def median(mode):
            return sorted(runs[mode], key=lambda r: r["rec_s"])[1]

        passive = median(False)
        profiled = median(True)
        rec = {
            "passive": passive,
            "profiled": {k: v for k, v in profiled.items()
                         if k not in ("census", "hbm_bytes", "span_p99_us")},
            "overhead_pct": round(
                (passive["rec_s"] / max(profiled["rec_s"], 1e-9) - 1.0) * 100, 2
            ),
            "fetch_parity": profiled["host_fetches"] == passive["host_fetches"],
            "pull": {
                k: profiled[k] for k in (
                    "pull_ms_no_analyze", "pull_ms_first_analyze",
                    "pull_ms_cached_analyze",
                )
            },
            "hbm_bytes": profiled["hbm_bytes"],
            "census": profiled["census"],
            "span_p99_us": profiled["span_p99_us"],
            "iters": iters,
            "buckets": list(buckets),
        }
    except Exception as e:  # partial-but-parseable (bench contract)
        rec = {"error": repr(e), "partial": True}
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
