#!/usr/bin/env python
"""Primitive throughput on the attached chip: sort vs scatter vs gather.

Decides the stash architecture (sort/segment vs hash/scatter). Timing is
tunnel-safe: every iteration is data-dependent on the previous one (the
measured op consumes a carry scalar), and the loop ends with a device_get
so async dispatch cannot hide execution. Run from repo root:

    python bench/microbench_kernels.py [--cpu]
"""

from __future__ import annotations

import sys
import time

if "--cpu" in sys.argv:
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def timeit(make_fn, iters=10, warmup=2):
    """make_fn() -> (fn, args). fn(carry, *args) -> new u32 carry scalar,
    chained so iteration i depends on i-1."""
    fn, args = make_fn()
    jfn = jax.jit(fn)
    carry = jnp.uint32(0)
    for _ in range(warmup):
        carry = jfn(carry, *args)
    _ = jax.device_get(carry)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry = jfn(carry, *args)
    _ = jax.device_get(carry)
    return (time.perf_counter() - t0) / iters


def main():
    print(f"platform={jax.devices()[0].platform}", flush=True)
    rng = np.random.default_rng(0)

    def report(name, n, t):
        print(f"{name:22s} n={n:>8}: {t*1e3:8.3f} ms  ({n/t/1e6:8.1f} M rows/s)", flush=True)

    for n in (1 << 17, 1 << 19, 1 << 21):
        a = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32))
        b = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32))
        c = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32))

        def mk_sort3():
            def f(carry, a, b, c):
                iota = jnp.arange(a.shape[0], dtype=jnp.int32)
                o = lax.sort((a ^ carry, b, c, iota), num_keys=3)
                return o[0][0] ^ jnp.uint32(o[3][0])

            return f, (a, b, c)

        report("sort3+iota", n, timeit(mk_sort3))

        def mk_sort1():
            def f(carry, a):
                iota = jnp.arange(a.shape[0], dtype=jnp.int32)
                o = lax.sort((a ^ carry, iota), num_keys=1)
                return o[0][0] ^ jnp.uint32(o[1][0])

            return f, (a,)

        report("sort1+iota", n, timeit(mk_sort1))

    S = 1 << 16
    for r in (1 << 16, 1 << 18, 1 << 20):
        idx = jnp.asarray(rng.integers(0, S, r, dtype=np.int32))
        vals = jnp.asarray(rng.random((r, 36), dtype=np.float32))
        sid = jnp.sort(idx)

        def mk_scatter_add():
            def f(carry, ix, v):
                tbl = jnp.zeros((S, 36), jnp.float32) + carry.astype(jnp.float32)
                tbl = tbl.at[ix].add(v)
                return tbl[0, 0].astype(jnp.uint32)

            return f, (idx, vals)

        report("scatter_add 36c", r, timeit(mk_scatter_add))

        def mk_gather40():
            tbl = jnp.asarray(rng.integers(0, 2**32, (S, 40), dtype=np.uint32))

            def f(carry, tb, ix):
                g = jnp.take(tb + carry, ix, axis=0)
                return g[0, 0]

            return f, (tbl, idx)

        report("gather 40c", r, timeit(mk_gather40))

        def mk_segsum():
            def f(carry, v, s):
                out = jax.ops.segment_sum(v + carry.astype(jnp.float32), s, num_segments=S)
                return out[0, 0].astype(jnp.uint32)

            return f, (vals, sid)

        report("segsum 36c sorted", r, timeit(mk_segsum))

        def mk_segscan():
            def f(carry, v, s):
                v = v + carry.astype(jnp.float32)
                n_ = v.shape[0]
                d = 1
                while d < n_:
                    same = jnp.concatenate([jnp.zeros((d,), bool), s[d:] == s[:-d]])
                    shifted = jnp.concatenate(
                        [jnp.zeros((d, v.shape[1]), v.dtype), v[:-d]]
                    )
                    v = v + jnp.where(same[:, None], shifted, 0)
                    d *= 2
                return v[0, 0].astype(jnp.uint32)

            return f, (vals, sid)

        report("segscan-shift 36c", r, timeit(mk_segscan))

        def mk_fingerprint():
            # column-major [C, r], the layout the pipeline actually
            # fingerprints (fingerprint64_t over key rows of [T, 4N])
            from deepflow_tpu.ops.hashing import fingerprint64_t

            tmat = jnp.asarray(rng.integers(0, 2**32, (30, r), dtype=np.uint32))

            def f(carry, tm):
                hi, lo = fingerprint64_t(tm + carry)
                return hi[0] ^ lo[0]

            return f, (tmat,)

        report("fingerprint_t 30c", r, timeit(mk_fingerprint))


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
