#!/usr/bin/env python
"""All five BASELINE.json configs + a measured CPU baseline, one JSON
line each (BASELINE.md:22-39; r3 verdict item #4). Run from repo root:

    python bench_all.py [--cpu] [--quick]

Configs:
  1 flow_metrics 1s rollup   — synthetic accumulated-flow replay, 10k
    5-tuples, amortized append/fold cadence (the bench.py number), plus
    the MEASURED CPU-oracle baseline on the identical stream; this
    config's vs line is device_rate / cpu_oracle_rate.
  2 L7 RED + t-digest        — request replay through the L7 path, RED
    meters + p50/p99 from the latency log-histogram t-digest.
  3 HLL cardinality          — 1M true client cardinality through the
    HLL plane; reports measured relative error (<1% required).
  4 CMS heavy hitters        — top-K endpoints by bytes via count-min,
    reports top-10 recall vs exact.
  5 pod-wide 1m rollup       — 64-agent firehose over the mesh pipeline
    with collective sketch merges (8-device CPU mesh when multichip
    hardware is absent; on the single TPU it degrades to a 1-device
    mesh, still through shard_map).

Output: one {"metric", "value", "unit", "vs_baseline"} JSON line per
config; also writes PERF_ALL.json with the full detail.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

if "--cpu" in sys.argv:
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as np

NORTH_STAR = 50e6

results = []


def emit(metric, value, unit, vs_baseline, **detail):
    line = {"metric": metric, "value": round(float(value), 4), "unit": unit,
            "vs_baseline": round(float(vs_baseline), 4)}
    print(json.dumps(line), flush=True)
    results.append({**line, **detail})


def _telemetry(obj):
    """Counter-block + span-summary snapshot for the aggregate JSON
    (ISSUE 3). None — never a crash — when the pipeline predates
    telemetry or the run died before the manager existed."""
    try:
        return obj.telemetry()
    except Exception:
        return None


def config1(quick: bool):
    import jax
    import jax.numpy as jnp

    from deepflow_tpu.aggregator.fanout import FANOUT_LANES, FanoutConfig
    from deepflow_tpu.aggregator.pipeline import make_ingest_step
    from deepflow_tpu.aggregator.stash import accum_init, stash_init
    from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    BATCH = 1 << 12 if quick else 1 << 20
    # cap must exceed per-batch uniques or the run sheds keys: 4096
    # draws from 10k tuples → ~3.3k uniques (quick); full batches hit
    # all ~10k+ (×2 windows) → 32k cap
    CAPU = 1 << 12 if quick else 1 << 15
    CAP = 1 << 16
    K = 2
    CYCLES = 2 if quick else 8

    gen = SyntheticFlowGen(num_tuples=10_000, seed=0)
    fb = gen.flow_batch(BATCH, 1_700_000_000)
    tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
    meters = jnp.asarray(fb.meters)
    valid = jnp.asarray(fb.valid)

    append_fn, fold_fn = make_ingest_step(
        FanoutConfig(), interval=1, batch_unique_cap=CAPU
    )
    append = jax.jit(append_fn, donate_argnums=(0, 1))
    fold = jax.jit(fold_fn, donate_argnums=(0, 1))
    stride = FANOUT_LANES * CAPU
    state = stash_init(CAP, TAG_SCHEMA, FLOW_METER)
    acc = accum_init(K * stride, TAG_SCHEMA, FLOW_METER)

    def cycle(state, acc):
        for k in range(K):
            state, acc = append(state, acc, jnp.int32(k * stride), tags, meters, valid)
        return fold(state, acc)

    # chained cycles + one true host-fetch sync (block_until_ready
    # returns early on the remote tunnel — PERF.md §6)
    state, acc = cycle(state, acc)
    _ = np.asarray(state.slot[:1])
    t0 = time.perf_counter(); _ = np.asarray(state.slot[:1])
    fetch_base = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(CYCLES):
        state, acc = cycle(state, acc)
    _ = np.asarray(state.slot[:1])
    dev_rate = BATCH * K * CYCLES / (time.perf_counter() - t0 - fetch_base)

    # CPU oracle baseline on the identical stream shape (the reference
    # publishes no numbers — BASELINE.md mandates measuring our own)
    from deepflow_tpu.oracle.numpy_oracle import oracle_l4_rollup

    n_oracle = min(BATCH, 4096)
    records = gen.records(n_oracle, 1_700_000_000)
    t0 = time.perf_counter()
    oracle_l4_rollup(records, config=FanoutConfig())
    cpu_rate = n_oracle / (time.perf_counter() - t0)

    emit("c1_flow_metrics_1s_rollup", dev_rate, "records/s", dev_rate / cpu_rate,
         cpu_oracle_rate=cpu_rate, north_star_frac=dev_rate / NORTH_STAR)


def config2(quick: bool):
    import jax
    import jax.numpy as jnp

    from deepflow_tpu.aggregator.fanout import FANOUT_LANES, FanoutConfig
    from deepflow_tpu.aggregator.pipeline import make_ingest_step
    from deepflow_tpu.aggregator.stash import accum_init, stash_init
    from deepflow_tpu.datamodel.schema import APP_METER, TAG_SCHEMA
    from deepflow_tpu.ops.histogram import LogHistSpec, loghist_update
    from deepflow_tpu.ops.tdigest import tdigest_from_loghist, tdigest_quantile

    BATCH = 1 << 12 if quick else 1 << 18
    CAPU = 1 << 11 if quick else 1 << 12  # ≥ 64 svc × 16 endpoint uniques
    total = 1 << 17 if quick else 1 << 21  # ~2M requests
    spec = LogHistSpec(bins=512, vmin=1.0, gamma=1.04)

    from deepflow_tpu.ingest.replay import SyntheticAppGen

    gen = SyntheticAppGen(num_services=64, endpoints_per_service=16, seed=1)
    draw = gen._draw(BATCH)
    fb = gen.app_batch(BATCH, 1_700_000_000, draw=draw)
    tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
    meters = jnp.asarray(fb.meters)
    valid = jnp.asarray(fb.valid)
    # the generator's true service id — NOT a port residue (a port-mod
    # binning can leave bins empty and record 0.0 percentiles)
    svc_id = jnp.asarray(draw[0].astype(np.int32))

    append_fn, fold_fn = make_ingest_step(
        FanoutConfig(), interval=1, app=True, batch_unique_cap=CAPU
    )
    append = jax.jit(append_fn, donate_argnums=(0, 1))
    fold = jax.jit(fold_fn, donate_argnums=(0, 1))
    doc_rows = FANOUT_LANES * CAPU
    K = 2
    state = stash_init(1 << 16, TAG_SCHEMA, APP_METER)
    acc = accum_init(K * doc_rows, TAG_SCHEMA, APP_METER)

    m_idx = APP_METER.index
    hist = jnp.zeros((64, spec.bins), jnp.int32)

    @jax.jit
    def upd_hist(hist, svc, meters, valid):
        rrt = meters[:, m_idx("rrt_sum")] / jnp.maximum(meters[:, m_idx("rrt_count")], 1.0)
        return loghist_update(hist, svc, rrt, valid & (meters[:, m_idx("rrt_count")] > 0), spec)

    # warm, then one true host-fetch sync (PERF.md §6)
    state, acc = append(state, acc, jnp.int32(0), tags, meters, valid)
    state, acc = fold(state, acc)
    hist = upd_hist(hist, svc_id, meters, valid)
    _ = np.asarray(state.slot[:1])
    t0 = time.perf_counter(); _ = np.asarray(state.slot[:1])
    fetch_base = time.perf_counter() - t0

    iters = max(1, total // BATCH)
    t0 = time.perf_counter()
    k = 0
    for i in range(iters):
        state, acc = append(state, acc, jnp.int32(k * doc_rows), tags, meters, valid)
        hist = upd_hist(hist, svc_id, meters, valid)
        k += 1
        if k == K:
            state, acc = fold(state, acc)
            k = 0
    _ = np.asarray(state.slot[:1])
    rate = BATCH * iters / (time.perf_counter() - t0 - fetch_base)

    # pooled distribution over ALL services (merge = histogram sum),
    # plus one per-service row as a spot check
    pooled = hist.sum(axis=0, keepdims=True)
    means, weights = tdigest_from_loghist(pooled, spec)
    p50, p99 = np.asarray(
        tdigest_quantile(means[0], weights[0], jnp.asarray([0.5, 0.99]))
    )
    svc0 = tdigest_from_loghist(hist[:1], spec)
    s_p50, s_p99 = np.asarray(
        tdigest_quantile(svc0[0][0], svc0[1][0], jnp.asarray([0.5, 0.99]))
    )
    # an empty-sketch regression must never be recordable again
    assert float(p99) > 0.0, "c2 pooled histogram is empty"
    assert float(s_p99) > 0.0, "c2 service-0 histogram is empty"
    emit("c2_l7_red_tdigest", rate, "requests/s", rate / NORTH_STAR,
         p50_us=float(p50), p99_us=float(p99),
         svc0_p50_us=float(s_p50), svc0_p99_us=float(s_p99))


def config3(quick: bool):
    import jax
    import jax.numpy as jnp

    from deepflow_tpu.ops.hashing import fingerprint64
    from deepflow_tpu.ops.hll import hll_estimate, hll_init, hll_update

    true_card = 1 << 17 if quick else 1_000_000
    BATCH = 1 << 16
    precision = 14
    rng = np.random.default_rng(2)
    state = hll_init(1, precision)
    upd = jax.jit(hll_update, donate_argnums=(0,))
    gid = jnp.zeros(BATCH, jnp.int32)
    v = jnp.ones(BATCH, bool)

    # stream 4x the cardinality in repeats (clients recur across windows)
    total = true_card * 4
    ids = rng.integers(0, true_card, total).astype(np.uint32)
    ids[:true_card] = np.arange(true_card, dtype=np.uint32)  # all present
    t0 = time.perf_counter()
    seen = 0
    for off in range(0, total, BATCH):
        chunk = ids[off : off + BATCH]
        if len(chunk) < BATCH:
            chunk = np.pad(chunk, (0, BATCH - len(chunk)))
        hi, lo = fingerprint64(jnp.asarray(chunk[:, None]))
        state = upd(state, gid, hi, lo, v)
        seen += len(chunk)
    est = float(np.asarray(hll_estimate(state))[0])
    dt = time.perf_counter() - t0
    rel_err = abs(est - true_card) / true_card
    emit("c3_hll_rel_err_at_1M", rel_err, "fraction", 1.0 if rel_err < 0.01 else 0.0,
         estimate=est, true_cardinality=true_card, update_rate=seen / dt)


def config4(quick: bool):
    import jax
    import jax.numpy as jnp

    from deepflow_tpu.ops.cms import cms_init, cms_query, cms_update
    from deepflow_tpu.ops.hashing import fingerprint64

    n_endpoints = 1 << 14  # 16-way tag group-by space
    BATCH = 1 << 16
    iters = 4 if quick else 16
    rng = np.random.default_rng(3)
    # zipf-ish endpoint popularity
    weights = 1.0 / np.arange(1, n_endpoints + 1) ** 1.2
    weights /= weights.sum()
    state = cms_init(depth=4, width=1 << 14)
    upd = jax.jit(cms_update, donate_argnums=(0,))
    truth = np.zeros(n_endpoints, np.int64)
    t0 = time.perf_counter()
    for _ in range(iters):
        eps = rng.choice(n_endpoints, BATCH, p=weights).astype(np.uint32)
        byte_w = rng.integers(100, 1500, BATCH).astype(np.int32)
        np.add.at(truth, eps, byte_w)
        hi, lo = fingerprint64(jnp.asarray(eps[:, None]))
        state = upd(state, hi, lo, jnp.asarray(byte_w), jnp.ones(BATCH, bool))
    jax.block_until_ready(state)
    rate = BATCH * iters / (time.perf_counter() - t0)

    all_ids = np.arange(n_endpoints, dtype=np.uint32)
    hi, lo = fingerprint64(jnp.asarray(all_ids[:, None]))
    est = np.asarray(cms_query(state, hi, lo))
    top_true = set(np.argsort(truth)[-10:].tolist())
    top_est = set(np.argsort(est)[-10:].tolist())
    recall = len(top_true & top_est) / 10.0
    emit("c4_cms_topk_endpoints", rate, "spans/s", recall, top10_recall=recall)


def config5(quick: bool):
    import jax

    from deepflow_tpu.ingest.replay import SyntheticFlowGen
    from deepflow_tpu.ops.histogram import LogHistSpec
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev, n_hosts=2 if n_dev % 2 == 0 and n_dev > 1 else 1)
    cfg = ShardedConfig(
        capacity_per_device=1 << 12,
        num_services=256,
        hll_precision=10,
        hist=LogHistSpec(bins=256, vmin=1.0, gamma=1.08),
        # ≥ E[uniques] of 32k draws from 10k tuples (~9.6k) so the run
        # sheds nothing
        batch_unique_cap=None if quick else 1 << 14,
    )
    pipe = ShardedPipeline(mesh, cfg)
    wm = ShardedWindowManager(pipe)

    per_dev = 1 << 10 if quick else 1 << 15
    batch = per_dev * n_dev  # "64-agent firehose" sharded over the mesh
    gen = SyntheticFlowGen(num_tuples=10_000, seed=4)
    t0s = 1_700_000_000
    # warm ALL the compile paths (step, window_close, fold, flush) —
    # the first advancing window pays them; timing must not
    for wt in (t0s, t0s + 60, t0s + 61, t0s + 65):
        fb = gen.flow_batch(batch, wt)
        wm.ingest(fb.tags, fb.meters, fb.valid)
    iters = 4 if quick else 12
    # pre-generate outside the timed loop — synthetic data creation is
    # not part of the pipeline under test
    batches = [gen.flow_batch(batch, t0s + 70 + i) for i in range(iters)]
    _ = np.asarray(wm.sketches.hll.ravel()[:1])  # true sync (PERF.md §6)
    t0 = time.perf_counter(); _ = np.asarray(wm.sketches.hll.ravel()[:1])
    fetch_base = time.perf_counter() - t0
    t0 = time.perf_counter()
    docs = 0
    for fb in batches:
        docs += sum(d.size for d in wm.ingest(fb.tags, fb.meters, fb.valid))
    _ = np.asarray(wm.sketches.hll.ravel()[:1])
    rate = batch * iters / (time.perf_counter() - t0 - fetch_base)

    # mesh scaling rows (1/2/4/8 virtual CPU devices, collective close
    # timed separately) — the r4 verdict's c5 fix: the headline above is
    # single-chip steady ingest; the mesh statement is this curve, run
    # in the same environment dryrun_multichip validates.
    scaling = []
    if not quick:
        import subprocess

        try:
            out = subprocess.run(
                [sys.executable, "bench/mesh_scaling.py"],
                capture_output=True, text=True, timeout=900,
                # fold-mode A/B at two device counts keeps the run inside
                # the timeout; the standalone tool defaults to the full
                # 1/2/4/8 × full/merge matrix
                env={**__import__("os").environ, "MESH_PER_DEV": str(1 << 13),
                     "MESH_ITERS": "8", "MESH_DEVICES": "1,4",
                     "MESH_FOLD_MODES": "full,merge"},
            )
            rec = json.loads(out.stdout.strip().splitlines()[-1])
            scaling = rec["rows"]
            if rec.get("partial"):  # mesh_scaling's partial-JSON convention
                scaling = scaling + [{"error": rec.get("error", "partial run")}]
        except Exception as e:
            scaling = [{"error": repr(e)}]
    emit("c5_pod_1m_rollup_mesh", rate, "records/s", rate / NORTH_STAR,
         n_devices=n_dev, flushed_docs=docs, mesh_scaling=scaling,
         telemetry=_telemetry(wm))


def config6(quick: bool):
    """Feeder runtime (ISSUE 4): wire-to-window rate through multi-queue
    fan-in + bucket coalescing + the K-batch counter ring. Runs
    bench/feeder_probe.py in a clean CPU subprocess (the probe pins
    JAX_PLATFORMS=cpu; on-chip columns pending, PERF.md §14) and
    re-emits its record; the vs line is host-fetches-per-batch — the
    lever this subsystem exists to push below 1."""
    import os
    import subprocess

    env = {**os.environ, "FEEDER_ITERS": "16" if quick else "48"}
    out = subprocess.run(
        [sys.executable, "bench/feeder_probe.py"],
        capture_output=True, text=True, timeout=900, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    emit("c6_feeder_wire_to_window", rec["rec_s"], "records/s",
         rec["fetches_per_batch"], **{
             k: rec[k] for k in (
                 "batches", "host_fetches", "stats_ring", "buckets",
                 "jit_retraces", "jit_compiles", "shed_records", "pad_rows",
             )
         }, telemetry=rec.get("telemetry"),
         feeder_telemetry=rec.get("feeder_telemetry"))


def config7(quick: bool):
    """Fold stage A/B (ISSUE 5): full-sort fold vs incremental
    merge-fold via bench/foldbench.py (chained-sync §7a recipe, real
    TAG_SCHEMA × FLOW_METER payload widths). The vs line is the
    full/merge speedup at the largest shape run; the span-bounded
    advance variant rides in the detail rows. Quick mode trims to one
    small shape; the full on-chip grid is the foldbench default
    (PERF.md §15)."""
    import os
    import subprocess

    shapes = (
        "65536:8192" if quick
        else "65536:8192,65536:65536,262144:8192,262144:65536"
    )
    env = {**os.environ, "FOLDBENCH_SHAPES": shapes,
           "FOLDBENCH_ITERS": "2" if quick else "4"}
    out = subprocess.run(
        [sys.executable, "bench/foldbench.py"],
        capture_output=True, text=True, timeout=900, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    rows = rec["rows"]
    if not rows:
        emit("c7_fold_full_vs_merge", 0, "error", 0,
             error=rec.get("error", "no rows"))
        return
    last = rows[-1]
    emit("c7_fold_full_vs_merge", last["merge_ms"], "ms/fold",
         last["speedup_full_vs_merge"], rows=rows,
         partial=rec.get("partial", False), error=rec.get("error"))


def config8(quick: bool):
    """Journal overhead A/B (ISSUE 6): the config6 feeder workload run
    journal-off vs journal-on (vs journal-on+fsync) via
    bench/journal_probe.py — the vs line is the buffered-journal
    overhead in percent (the crash-safety tax on steady-state ingest;
    protocol + committed numbers in PERF.md §16)."""
    import os
    import subprocess

    env = {**os.environ, "JOURNAL_ITERS": "16" if quick else "48"}
    out = subprocess.run(
        [sys.executable, "bench/journal_probe.py"],
        capture_output=True, text=True, timeout=1200, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if rec.get("partial"):
        emit("c8_journal_overhead", 0, "error", 0, error=rec.get("error"))
        return
    emit("c8_journal_overhead", rec["journal_on"]["rec_s"], "records/s",
         rec["overhead_pct"],
         overhead_fsync_pct=rec["overhead_fsync_pct"],
         journal_off=rec["journal_off"], journal_on=rec["journal_on"],
         journal_on_fsync=rec["journal_on_fsync"], buckets=rec["buckets"])


def config9(quick: bool):
    """Sketch tier A/B (ISSUE 8): exact-only vs +sketch-plane vs +top-K
    through the windowed raw-doc path under Zipf+scan traffic, via
    bench/sketchbench.py (protocol + committed numbers: PERF.md §17;
    the pooled-memory run is §28 / SKETCHBENCH_r02.json). The vs line
    is the top-K variant's heavy-hitter recall at the largest shape
    run; cardinality error, the exact tier's shed coverage and the
    ISSUE 20 pooled-memory density (`density_vs_slab` on the "pool"
    row, from live HBM ledger bytes) ride the detail rows. Quick mode
    trims to one small shape; the acceptance grid (1M-row batches,
    ≥1M distinct keys, K=128, Zipf s=1.1) is the standalone default."""
    import os
    import subprocess

    env = {**os.environ}
    if quick:
        env.update(SKETCHBENCH_SHAPES="65536:8192", SKETCHBENCH_BATCHES="2",
                   SKETCHBENCH_KEYS=str(1 << 18))
    out = subprocess.run(
        [sys.executable, "bench/sketchbench.py"],
        capture_output=True, text=True, timeout=3600, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    rows = rec["rows"]
    if not rows:
        emit("c9_sketch_tier", 0, "error", 0, error=rec.get("error", "no rows"))
        return
    topk_rows = [r for r in rows if r["variant"] == "topk"]
    last = topk_rows[-1] if topk_rows else rows[-1]
    pool_rows = [r for r in rows if r["variant"] == "pool"]
    emit("c9_sketch_tier", last["rec_s"], "records/s",
         last.get("topk_recall", 0.0), rows=rows,
         cardinality_error=last.get("cardinality_error"),
         exact_coverage=last.get("exact_coverage"),
         pool_density_vs_slab=(
             pool_rows[-1].get("density_vs_slab") if pool_rows else None),
         pool_topk_recall=(
             pool_rows[-1].get("topk_recall") if pool_rows else None),
         n_keys=rec["n_keys"], zipf_s=rec["zipf_s"], k_top=rec["k_top"],
         partial=rec.get("partial", False), error=rec.get("error"))


def config10(quick: bool):
    """Rollup cascade A/B (ISSUE 9): double-ingest vs cascade on the
    §14 feeder-shaped dual-granularity workload via
    bench/cascadebench.py (protocol + committed numbers: PERF.md §18,
    CASCADEBENCH_r01.json). The vs line is the cascade/double ingest
    speedup (acceptance ≥1.5× on the CPU grid); the long-range query
    A/B (1h span at 1s replay vs tier-selected 1m) rides the detail."""
    import os
    import subprocess

    env = {**os.environ}
    if quick:
        env.update(CASCADEBENCH_BATCHES="32", CASCADEBENCH_REPS="1")
    out = subprocess.run(
        [sys.executable, "bench/cascadebench.py"],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if rec.get("partial"):
        emit("c10_rollup_cascade", 0, "error", 0, error=rec.get("error"))
        return
    ing, q = rec["ingest"], rec["query"]
    emit("c10_rollup_cascade", ing["cascade"]["rec_s"], "records/s",
         ing["speedup_cascade_vs_double"],
         double=ing["double"], cascade=ing["cascade"],
         query_rows_ratio=q["rows_ratio"],
         query_speedup=q["speedup_tier_vs_replay"],
         batch=rec["batch"], n_batches=rec["n_batches"],
         tuples=rec["tuples"])


def config11(quick: bool):
    """Live read plane (ISSUE 10): snapshot overhead on the §14 feeder
    workload + cached vs uncached repeated-query latency via
    bench/livebench.py (protocol: PERF.md §19). The vs line is the
    result-cache speedup on the repeated dashboard query; the snapshot
    ingest overhead rides the detail."""
    import os
    import subprocess

    env = {**os.environ}
    if quick:
        env.update(LIVEBENCH_ITERS="16", LIVEBENCH_QUERY_REPS="20")
    out = subprocess.run(
        [sys.executable, "bench/livebench.py"],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if rec.get("partial"):
        emit("c11_live_read", 0, "error", 0, error=rec.get("error"))
        return
    q = rec["query"]
    emit("c11_live_read", q["cached_ms"], "ms/query",
         q["speedup_cached"],
         uncached_ms=q["uncached_ms"], series=q["series"],
         cache=q["cache"], ingest=rec["ingest"],
         snap_every=rec["snap_every"], iters=rec["iters"])


def config12(quick: bool):
    """Push query plane (ISSUE 11): dashboard-storm fan-out
    amplification + flush→watcher invalidation latency via
    bench/pushbench.py (protocol: PERF.md §20, committed numbers:
    PUSHBENCH_r01.json). The vs line is the amplification at the
    largest watcher count (acceptance ≥100× from ONE evaluation per
    event, results pinned bit-exact vs a fresh pull); evals/sec and
    the publish→delivery latency ride the detail rows."""
    import os
    import subprocess

    env = {**os.environ}
    if quick:
        env.update(PUSHBENCH_WATCHERS="1,100", PUSHBENCH_EVENTS="8",
                   PUSHBENCH_FLOWS="128")
    out = subprocess.run(
        [sys.executable, "bench/pushbench.py"],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if rec.get("partial"):
        emit("c12_push_plane", 0, "error", 0, error=rec.get("error"))
        return
    rows = rec["rows"]
    last = rows[-1]
    assert last["pinned_bit_exact"], "push-delivered result diverged from pull"
    emit("c12_push_plane", last["deliveries_per_s"], "deliveries/s",
         last["amplification"],
         evals_per_s=last["evals_per_s"],
         publish_to_last_watcher_ms=last["publish_to_last_watcher_ms"],
         watchers=last["watchers"], rows=rows, events=rec["events"],
         flows=rec["flows"])


def config13(quick: bool):
    """Device profiling plane (ISSUE 12): always-on ledger + census +
    span-quantile overhead on the §14 feeder workload via
    bench/profbench.py (protocol: PERF.md §21, committed numbers:
    PROFBENCH_r01.json). The vs line is the overhead percent under an
    aggressive every-4-pumps profiling consumer (acceptance <2% with
    fetch parity — parity itself is CI-gated deterministically); the
    profile pull latencies and per-bucket census rows ride the
    detail."""
    import os
    import subprocess

    env = {**os.environ}
    if quick:
        env.update(PROFBENCH_ITERS="16")
    out = subprocess.run(
        [sys.executable, "bench/profbench.py"],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if rec.get("partial"):
        emit("c13_device_profiling", 0, "error", 0, error=rec.get("error"))
        return
    emit("c13_device_profiling", rec["profiled"]["rec_s"], "records/s",
         rec["overhead_pct"],
         fetch_parity=rec["fetch_parity"], pull=rec["pull"],
         hbm_bytes=rec["hbm_bytes"], census=rec["census"],
         span_p99_us=rec["span_p99_us"],
         passive=rec["passive"], iters=rec["iters"])


def config14(quick: bool):
    """Window lineage tracing + freshness plane (ISSUE 13): passive vs
    traced A/B on the §14 feeder workload via bench/tracebench.py
    (protocol: PERF.md §22, committed numbers: TRACEBENCH_r01.json).
    The vs line is the overhead percent with the full lineage stack +
    an every-4-pumps consumer (fetch parity itself is CI-gated
    deterministically); span-row volume and the trace pull latencies
    ride the detail, on-chip columns reserved."""
    import os
    import subprocess

    env = {**os.environ}
    if quick:
        env.update(TRACEBENCH_ITERS="16")
    out = subprocess.run(
        [sys.executable, "bench/tracebench.py"],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if rec.get("partial"):
        emit("c14_window_lineage", 0, "error", 0, error=rec.get("error"))
        return
    emit("c14_window_lineage", rec["traced"]["rec_s"], "records/s",
         rec["overhead_pct"],
         fetch_parity=rec["fetch_parity"],
         span_rows_per_window=rec["traced"]["span_rows_per_window"],
         span_rows_per_1k_records=rec["traced"]["span_rows_per_1k_records"],
         pull_ms_live_assemble=rec["traced"]["pull_ms_live_assemble"],
         pull_ms_store_query=rec["traced"]["pull_ms_store_query"],
         passive=rec["passive"], iters=rec["iters"])


def config15(quick: bool):
    """Multi-host mesh scale-out (ISSUE 14): N-process jax.distributed
    deployments via bench/mesh_scaling.py MESH_PROCS — each host one
    shard group, key-hash-routed agents, fully-local data path — the
    aggregate rec/s statement the pod-scale ROADMAP item demanded
    (protocol: PERF.md §23, committed numbers: MESHBENCH_r01.json;
    acceptance: ≥1.7× aggregate at 2 processes)."""
    import os
    import subprocess

    env = {**os.environ, "MESH_PROCS": "1,2" if quick else "1,2,4"}
    if quick:
        env["MESHBENCH_ITERS"] = "16"
    out = subprocess.run(
        [sys.executable, "bench/mesh_scaling.py"],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if rec.get("partial"):
        emit("c15_multihost_mesh", 0, "error", 0, error=rec.get("error"))
        return
    rows = rec["proc_rows"]
    last = rows[-1]
    emit("c15_multihost_mesh", last["aggregate_rec_s"], "records/s",
         last.get("scale_vs_1proc", 0),
         n_processes=last["n_processes"],
         per_host_rec_s=last["per_host_rec_s"],
         init_s_max=last["init_s_max"], rows=rows)


def config16(quick: bool):
    """Rebalance-pause protocol (ISSUE 15): bench/mesh_scaling.py
    MESH_REBALANCE=1 — the shard-group handover pause (quiesce →
    manifest checkpoint → restore on the new owner) decomposed by
    phase, plus recovery-to-steady rate, swept over group state size
    (protocol + committed CPU numbers: PERF.md §24). The headline value
    is the largest-state row's pause; vs_baseline is post/pre steady
    rate — 1.0 means the flip left no lingering cost."""
    import os
    import subprocess

    env = {**os.environ, "MESH_REBALANCE": "1"}
    if quick:
        env["MESH_REBALANCE_PRELOADS"] = "8"
        env["MESHBENCH_ITERS"] = "8"
    out = subprocess.run(
        [sys.executable, "bench/mesh_scaling.py"],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    rows = rec.get("rebalance_rows", [])
    if rec.get("partial") or not rows:
        emit("c16_rebalance_pause", 0, "error", 0, error=rec.get("error"))
        return
    last = rows[-1]
    emit("c16_rebalance_pause", last["pause_ms"], "ms",
         last["post_rec_s"] / max(last["pre_rec_s"], 1e-9),
         ckpt_bytes=last["ckpt_bytes"], recovery_ms=last["recovery_ms"],
         first_pump_ms=last["first_pump_ms"], rows=rows)


def config17(quick: bool):
    """One-pass shared sort (ISSUE 17): bench/sortbench.py A/Bs the
    multi-sort oracle vs the shared-sort rewrite through the +top-K
    windowed ingest at the §17 shapes, with census-attributed
    sorts/dispatch and a bit-parity digest embedded (protocol +
    committed CPU numbers: PERF.md §25, SORTBENCH_r01.json; acceptance:
    ≥1.2× on the +topk shape with bit_parity true). The headline value
    is the last shape's one-pass rate; vs_baseline is its speedup over
    the multi-sort oracle on the same stream."""
    import os
    import subprocess

    env = {**os.environ}
    if quick:
        env["SORTBENCH_SHAPES"] = "65536:8192"
        env["SORTBENCH_BATCHES"] = "2"
    out = subprocess.run(
        [sys.executable, "bench/sortbench.py"],
        capture_output=True, text=True, timeout=3600, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    ones = [r for r in rec.get("rows", []) if r["mode"] == "onepass"]
    if rec.get("partial") or not ones:
        emit("c17_one_pass_sort", 0, "error", 0, error=rec.get("error"))
        return
    last = ones[-1]
    emit("c17_one_pass_sort", last["rec_s"], "records/s",
         last["speedup_vs_multisort"],
         batch=last["batch"], stash=last["stash"],
         bit_parity=last["bit_parity"],
         sorts_per_dispatch=rec["sorts_per_dispatch"], rows=rec["rows"])


def config18(quick: bool):
    """Fleet telemetry plane (ISSUE 18): bench/fleetbench.py A/Bs the
    §14 feeder workload passive vs with the full fleet export loop
    (collector tick → frame build/encode → TCP ship → aggregator merge)
    and sweeps the merged-read cost over hosts and over per-host sample
    volume (protocol: PERF.md §26; acceptance: ingest overhead within
    noise — fetch parity is CI-gated — and aggregator cost O(hosts),
    not O(samples)). The vs line is the ingest overhead percent."""
    import os
    import subprocess

    env = {**os.environ}
    if quick:
        env.update(FLEETBENCH_ITERS="16", FLEETBENCH_HOSTS="2,4")
    out = subprocess.run(
        [sys.executable, "bench/fleetbench.py"],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if rec.get("partial"):
        emit("c18_fleet_plane", 0, "error", 0, error=rec.get("error"))
        return
    emit("c18_fleet_plane", rec["fleet"]["rec_s"], "records/s",
         rec["overhead_pct"],
         frame_bytes_avg=rec["fleet"]["frame_bytes_avg"],
         hosts_rows=rec["hosts_rows"],
         per_host_ms_ratio=rec["per_host_ms_ratio"],
         samples_ratio=rec["samples_ratio"],
         frame_bytes_ratio=rec["frame_bytes_ratio"],
         merge_ms_ratio=rec["merge_ms_ratio"],
         passive=rec["passive"], iters=rec["iters"])


def config19(quick: bool):
    """Wire delivery plane (ISSUE 19): bench/wirebench.py fans merged
    eval envelopes from H socketed host publishers through the
    FleetSubscriptionRouter to W wire clients over a watchers × rules ×
    hosts grid (protocol: PERF.md §27; acceptance: publish→all-watchers
    latency FLAT in W — ONE upstream eval per event batch per query,
    fan-out is W bounded-queue appends — with per-host rows pinned
    bit-exact vs each host's own evaluation). The headline value is the
    largest cell's deliveries/s; the vs line is the worst
    max-W-over-W=1 latency ratio (1.0 == perfectly flat)."""
    import os
    import subprocess

    env = {**os.environ}
    if quick:
        env.update(WIREBENCH_EVENTS="8", WIREBENCH_WATCHERS="1,10",
                   WIREBENCH_HOSTS="1", WIREBENCH_RULES="0")
    out = subprocess.run(
        [sys.executable, "bench/wirebench.py"],
        capture_output=True, text=True, timeout=1800, env=env,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if rec.get("partial"):
        emit("c19_wire_fanout", 0, "error", 0, error=rec.get("error"))
        return
    big = max(rec["rows"], key=lambda r: r["watchers"] * r["hosts"])
    emit("c19_wire_fanout", big["deliveries_per_s"], "deliveries/s",
         max(rec["latency_ratio_wmax_over_w1"].values()),
         latency_ratio_wmax_over_w1=rec["latency_ratio_wmax_over_w1"],
         publish_to_all_watchers_ms_mean=big[
             "publish_to_all_watchers_ms_mean"],
         pinned_bit_exact=all(r["pinned_bit_exact"] for r in rec["rows"]),
         drops=sum(r["drops"] for r in rec["rows"]),
         upstream_subs=max(r["upstream_subs"] for r in rec["rows"]),
         rows=rec["rows"])


def main():
    from deepflow_tpu.utils.provenance import bench_provenance

    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--quick", action="store_true")
    args = p.parse_args()
    # provenance first (ISSUE 18 satellite): every bench JSON names the
    # commit, platform, and DEEPFLOW_* knob set it measured
    prov = bench_provenance()
    print(json.dumps({"provenance": prov}), flush=True)
    for fn in (config1, config2, config3, config4, config5, config6, config7,
               config8, config9, config10, config11, config12, config13,
               config14, config15, config16, config17, config18,
               config19):
        try:
            fn(args.quick)
        except Exception as e:  # one config must not kill the others
            emit(fn.__name__ + "_error", 0, "error", 0, error=repr(e))
    # quick/CPU smoke runs must never clobber the committed full-run
    # record the docs cite
    out = "PERF_ALL.json" if not (args.quick or args.cpu) else "PERF_ALL_QUICK.json"
    with open(out, "w") as f:
        json.dump({"provenance": prov, "results": results}, f, indent=1)


if __name__ == "__main__":
    main()
