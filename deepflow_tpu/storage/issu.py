"""In-service schema upgrade (ISSU) — the ckissu seat.

The reference migrates every ClickHouse table's schema on boot through a
versioned list of column adds/renames/retypes (ckissu.go:51,425: each
release carries its delta; the upgrader walks them from the store's
recorded version to current). Same protocol over the columnar store:

  * the store root carries a `schema_version` file;
  * MIGRATIONS is the ordered list of (version, Migration) deltas;
  * `upgrade()` applies every delta newer than the recorded version to
    all matching on-disk tables — updating the persisted TableSchema
    AND rewriting existing parts so old data satisfies the new schema
    (missing columns materialize with defaults; renamed columns carry
    their data over).

In-memory stores (no root) are always at head — create_table writes the
current schema, so upgrade() is a no-op.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
from pathlib import Path

import numpy as np

from .store import ColumnSpec, ColumnarStore, TableSchema

CURRENT_VERSION = 2


@dataclasses.dataclass(frozen=True)
class AddColumn:
    table_glob: str  # "db/table" glob, e.g. "flow_log/l7_flow_log"
    name: str
    dtype: str
    default: object = 0


@dataclasses.dataclass(frozen=True)
class RenameColumn:
    table_glob: str
    old: str
    new: str


# version → deltas applied when upgrading TO that version. Version 1 is
# the round-3 on-disk layout; version 2 added the trace columns the
# tracing plane introduced in round 4 (parent_span_id / x_request_id,
# flowlog/schema.py).
MIGRATIONS: list[tuple[int, list]] = [
    (
        2,
        [
            AddColumn("*/l7_flow_log", "parent_span_id", "U256", ""),
            AddColumn("*/l7_flow_log", "x_request_id", "U256", ""),
        ],
    ),
]


def _version_file(root: Path) -> Path:
    return root / "schema_version"


def read_version(root: str | Path) -> int:
    f = _version_file(Path(root))
    if not f.exists():
        return 0
    try:
        return int(f.read_text().strip())
    except ValueError:
        return 0


def upgrade(store: ColumnarStore, target: int = CURRENT_VERSION) -> dict:
    """Apply pending migrations to every on-disk table. Returns a report
    {applied: [version...], tables_changed: N}."""
    root = getattr(store, "root", None)
    if root is None:
        return {"applied": [], "tables_changed": 0}
    root = Path(root)
    if not root.exists():
        root.mkdir(parents=True, exist_ok=True)
    have = read_version(root)
    if have == 0 and not any(root.iterdir()):
        # fresh store: born at head
        _version_file(root).write_text(str(target))
        return {"applied": [], "tables_changed": 0}

    applied, changed = [], 0
    for version, deltas in MIGRATIONS:
        if version <= have or version > target:
            continue
        for delta in deltas:
            changed += _apply(store, delta)
        applied.append(version)
    _version_file(root).write_text(str(target))
    return {"applied": applied, "tables_changed": changed}


def _apply(store: ColumnarStore, delta) -> int:
    changed = 0
    for db in store.databases():
        for table in store.tables(db):
            if not fnmatch.fnmatch(f"{db}/{table}", delta.table_glob):
                continue
            schema = store.schema(db, table)
            if isinstance(delta, AddColumn):
                if delta.name in schema.column_names():
                    continue
                new_schema = TableSchema(
                    schema.name,
                    schema.columns + (ColumnSpec(delta.name, delta.dtype),),
                    partition_s=schema.partition_s,
                )
                _rewrite(store, db, table, new_schema,
                         add={delta.name: (delta.dtype, delta.default)})
            elif isinstance(delta, RenameColumn):
                if delta.old not in schema.column_names():
                    continue
                cols = tuple(
                    ColumnSpec(delta.new, c.dtype) if c.name == delta.old else c
                    for c in schema.columns
                )
                new_schema = TableSchema(schema.name, cols, partition_s=schema.partition_s)
                _rewrite(store, db, table, new_schema,
                         rename={delta.old: delta.new})
            changed += 1
    return changed


def _fix_part(data: dict, add, rename) -> dict:
    n = len(next(iter(data.values()))) if data else 0
    for name, (dtype, default) in (add or {}).items():
        if name not in data:
            data[name] = np.full(n, default, dtype=np.dtype(dtype))
    for old, new in (rename or {}).items():
        if old in data:
            data[new] = data.pop(old)
    return data


def _rewrite(store, db, table, new_schema, add=None, rename=None) -> None:
    """Swap the table's schema and rewrite every part (disk or memory)."""
    t = store._get(db, table)
    with store._lock:
        t.schema = new_schema
        if t.path is not None:
            (t.path / "schema.json").write_text(new_schema.to_json())
        mem_parts = {
            pid: [p for p in ps if not isinstance(p, Path)]
            for pid, ps in t.parts.items()
        }
        disk_parts = [p for ps in t.parts.values() for p in ps if isinstance(p, Path)]
        for ps in mem_parts.values():
            for p in ps:
                _fix_part(p, add, rename)
    for part in disk_parts:
        try:
            data = dict(np.load(part))
        except FileNotFoundError:
            continue
        data = _fix_part(data, add, rename)
        with open(part, "wb") as f:
            np.savez_compressed(f, **data)
