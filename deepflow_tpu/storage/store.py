"""Partitioned columnar table store — the ClickHouse seat.

The reference writes batched columnar blocks over the CK native protocol
into MergeTree tables partitioned by time, with org-id database prefixes
(`<org>_flow_metrics`, server/libs/ckdb/table.go:120) and TTL/partition
drops enforced by ckmonitor. This store keeps the same shape the TPU-host
way: a table is a directory of immutable columnar *parts* (one `.npz` per
flushed write batch, time-partitioned); scans mmap-load only the parts
overlapping the query range and concatenate columns. There is no
merge-on-read — rollups are the downsampler's job, matching the
reference's "docs are written as-is" stance (flow_metrics.go).

In-memory mode (root="") backs tests and the zero-dependency bring-up
path; the on-disk layout is `<root>/<db>/<table>/p<partition>_<seq>.npz`.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import re
import threading
from pathlib import Path

import numpy as np

_STORE_UIDS = itertools.count(1)

DEFAULT_ORG_ID = 1
_NAME_RE = re.compile(r"^[A-Za-z0-9_.]+$")


def org_db(base: str, org_id: int = DEFAULT_ORG_ID) -> str:
    """Org-aware database naming (ckdb/table.go:120 IsDefaultOrgID)."""
    if org_id in (0, DEFAULT_ORG_ID):
        return base
    return f"{org_id:04d}_{base}"


@dataclasses.dataclass(frozen=True)
class ColumnSpec:
    name: str
    # numpy dtype string: "u4", "f4", "i8", "U64"… — or "O" for a
    # variable-width string column (the ClickHouse String analogue:
    # values are never clipped to a fixed width; each on-disk part
    # stores them at that part's own max width)
    dtype: str


@dataclasses.dataclass(frozen=True)
class TableSchema:
    name: str
    columns: tuple[ColumnSpec, ...]
    time_column: str = "time"
    partition_s: int = 3600
    ttl_hours: int = 168
    version: int = 1

    def __post_init__(self):
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate columns in {self.name}")
        if self.time_column not in names:
            raise ValueError(f"{self.name}: missing time column {self.time_column}")

    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    def to_json(self) -> str:
        return json.dumps(
            {
                "name": self.name,
                "columns": [[c.name, c.dtype] for c in self.columns],
                "time_column": self.time_column,
                "partition_s": self.partition_s,
                "ttl_hours": self.ttl_hours,
                "version": self.version,
            }
        )

    @staticmethod
    def from_json(text: str) -> "TableSchema":
        d = json.loads(text)
        return TableSchema(
            name=d["name"],
            columns=tuple(ColumnSpec(n, t) for n, t in d["columns"]),
            time_column=d["time_column"],
            partition_s=d["partition_s"],
            ttl_hours=d["ttl_hours"],
            version=d.get("version", 1),
        )


def _load_part(chunk):
    """Load a part, tolerating concurrent drop_partition unlinks."""
    if not isinstance(chunk, Path):
        return chunk
    try:
        return np.load(chunk)
    except FileNotFoundError:
        return None


class _Table:
    def __init__(self, schema: TableSchema, path: Path | None):
        self.schema = schema
        self.path = path
        self.parts: dict[int, list] = {}  # partition → [np dict | Path]
        self.seq = 0
        # monotonically increasing write epoch (ISSUE 10): bumped on
        # every insert/drop so the querier's result cache can validate
        # an entry with one integer compare instead of re-scanning —
        # window close → flushed rows insert → epoch moves → stale
        self.mutations = 0


class ColumnarStore:
    """db → table → time-partitioned columnar parts."""

    def __init__(self, root: str | Path = ""):
        self.root = Path(root) if root else None
        self._dbs: dict[str, dict[str, _Table]] = {}
        self._lock = threading.Lock()
        # process-unique store identity: result-cache keys must never
        # collide across two stores (id() can be reused after GC —
        # same-looking mutation counts on a recycled address would
        # serve one store's cached rows for another's query)
        self.uid = next(_STORE_UIDS)
        # push query plane (ISSUE 11): optional mutation hook, called
        # (db, table, epoch) OUTSIDE the lock after every insert/drop —
        # querier/events.connect_store_events points it at a
        # QueryEventBus so a window close push-invalidates standing
        # queries the instant its flushed rows land
        self._mutation_hook = None
        # window lineage plane (ISSUE 13): optional scan hooks, called
        # (db, table, time_range) after every scan resolves its table —
        # tracing/lineage.connect_store_reads marks a flushed window's
        # first query (query.first hop) from here. A LIST (unlike the
        # single mutation hook): multiple trackers may watch different
        # tables of one store.
        self._scan_hooks: list = []
        if self.root is not None:
            self.root.mkdir(parents=True, exist_ok=True)
            self._load_existing()

    # -- bootstrap ------------------------------------------------------
    def _load_existing(self):
        for schema_file in self.root.glob("*/*/schema.json"):
            schema = TableSchema.from_json(schema_file.read_text())
            db = schema_file.parent.parent.name
            t = _Table(schema, schema_file.parent)
            for part in sorted(schema_file.parent.glob("p*_*.npz")):
                pid, seq = part.stem[1:].split("_")
                t.parts.setdefault(int(pid), []).append(part)
                t.seq = max(t.seq, int(seq) + 1)
            self._dbs.setdefault(db, {})[schema.name] = t

    # -- DDL ------------------------------------------------------------
    def create_table(self, db: str, schema: TableSchema) -> None:
        if not _NAME_RE.match(db) or not _NAME_RE.match(schema.name):
            raise ValueError(f"bad identifier {db!r}/{schema.name!r}")
        with self._lock:
            tables = self._dbs.setdefault(db, {})
            if schema.name in tables:
                return
            path = None
            if self.root is not None:
                path = self.root / db / schema.name
                path.mkdir(parents=True, exist_ok=True)
                (path / "schema.json").write_text(schema.to_json())
            tables[schema.name] = _Table(schema, path)

    def databases(self) -> list[str]:
        with self._lock:
            return sorted(self._dbs)

    def tables(self, db: str) -> list[str]:
        with self._lock:
            return sorted(self._dbs.get(db, {}))

    def schema(self, db: str, table: str) -> TableSchema:
        return self._get(db, table).schema

    def _get(self, db: str, table: str) -> _Table:
        with self._lock:
            try:
                return self._dbs[db][table]
            except KeyError:
                raise KeyError(f"no such table {db}.{table}") from None

    # -- DML ------------------------------------------------------------
    def insert(self, db: str, table: str, cols: dict[str, np.ndarray]) -> int:
        """Append one part per touched partition; returns rows written."""
        t = self._get(db, table)
        s = t.schema
        missing = [c.name for c in s.columns if c.name not in cols]
        if missing:
            raise ValueError(f"{db}.{table}: missing columns {missing}")
        n = len(cols[s.time_column])
        if n == 0:
            return 0
        arrs = {
            c.name: np.ascontiguousarray(cols[c.name], dtype=np.dtype(c.dtype))
            for c in s.columns
        }
        if any(len(a) != n for a in arrs.values()):
            raise ValueError(f"{db}.{table}: ragged columns")
        ts = arrs[s.time_column].astype(np.int64)
        pids = ts // s.partition_s
        unique_pids = [int(p) for p in np.unique(pids)]
        # reserve sequence numbers under the lock, compress/write outside
        # it (savez_compressed is the slow part — it must not serialize
        # unrelated tables' flushes or block scans), then publish
        with self._lock:
            seq0 = t.seq
            t.seq += len(unique_pids)
        written: list[tuple[int, object]] = []
        for i, pid in enumerate(unique_pids):
            sel = pids == pid
            part = {k: v[sel] for k, v in arrs.items()}
            if t.path is not None:
                f = t.path / f"p{pid}_{seq0 + i}.npz"
                # object (variable-width string) columns serialize as a
                # U<part-max> array — npz can't hold object arrays
                # without pickle, and per-part sizing keeps them
                # unclipped; load returns them as U<n>, which scan
                # concatenation promotes freely
                np.savez_compressed(
                    f,
                    **{
                        k: (v.astype(np.str_) if v.dtype == object else v)
                        for k, v in part.items()
                    },
                )
                written.append((pid, f))
            else:
                written.append((pid, part))
        with self._lock:
            for pid, part in written:
                t.parts.setdefault(pid, []).append(part)
            t.mutations += 1
            epoch = t.mutations
        self._notify_mutation(db, table, epoch)
        return n

    def set_mutation_hook(self, hook) -> None:
        """`hook(db, table, epoch)` fires after every insert/drop (None
        detaches). Called outside the store lock; exceptions are
        contained — a broken event plane must never fail a write."""
        self._mutation_hook = hook

    def _notify_mutation(self, db: str, table: str, epoch: int) -> None:
        hook = self._mutation_hook
        if hook is None:
            return
        try:
            hook(db, table, epoch)
        except Exception:
            logging.getLogger(__name__).debug(
                "store mutation hook failed for %s.%s (contained)",
                db, table, exc_info=True,
            )

    def add_scan_hook(self, hook) -> None:
        """`hook(db, table, time_range)` fires after every successful
        scan (exceptions contained — observability must never fail a
        read). The lineage plane's query.first seam (ISSUE 13)."""
        self._scan_hooks.append(hook)

    def remove_scan_hook(self, hook) -> None:
        if hook in self._scan_hooks:
            self._scan_hooks.remove(hook)

    def _notify_scan(self, db: str, table: str, time_range) -> None:
        for hook in list(self._scan_hooks):
            try:
                hook(db, table, time_range)
            except Exception:
                logging.getLogger(__name__).debug(
                    "store scan hook failed for %s.%s (contained)",
                    db, table, exc_info=True,
                )

    def scan(
        self,
        db: str,
        table: str,
        time_range: tuple[int, int] | None = None,
        columns: list[str] | None = None,
    ) -> dict[str, np.ndarray]:
        """Read columns across parts overlapping [t0, t1); row-filtered
        exactly on the time column."""
        t = self._get(db, table)
        s = t.schema
        names = columns if columns is not None else s.column_names()
        for nm in names:
            if nm not in s.column_names():
                raise KeyError(f"{db}.{table}: no column {nm}")
        read = list(dict.fromkeys(names + [s.time_column]))
        with self._lock:
            if time_range is None:
                pids = sorted(t.parts)
            else:
                p0 = time_range[0] // s.partition_s
                p1 = (time_range[1] - 1) // s.partition_s
                pids = sorted(p for p in t.parts if p0 <= p <= p1)
            chunks = [p for pid in pids for p in list(t.parts[pid])]
        cols: dict[str, list[np.ndarray]] = {nm: [] for nm in read}
        for chunk in chunks:
            data = _load_part(chunk)
            if data is None:  # partition dropped mid-scan
                continue
            ts = np.asarray(data[s.time_column])
            if time_range is not None:
                sel = (ts >= time_range[0]) & (ts < time_range[1])
                if not sel.any():
                    continue
                for nm in read:
                    cols[nm].append(np.asarray(data[nm])[sel])
            else:
                for nm in read:
                    cols[nm].append(np.asarray(data[nm]))
        empty = {
            c.name: np.empty(0, np.dtype(c.dtype)) for c in s.columns if c.name in read
        }
        out = {
            nm: (np.concatenate(cols[nm]) if cols[nm] else empty[nm]) for nm in names
        }
        if self._scan_hooks:
            # AFTER the read completed — a failed scan must not mark a
            # window as queried (add_scan_hook's contract)
            self._notify_scan(db, table, time_range)
        return out

    def row_count(self, db: str, table: str) -> int:
        t = self._get(db, table)
        with self._lock:
            chunks = [p for parts in t.parts.values() for p in parts]
        total = 0
        for chunk in chunks:
            data = _load_part(chunk)
            if data is None:
                continue
            total += len(np.asarray(data[t.schema.time_column]))
        return total

    # -- retention (ckmonitor hooks) ------------------------------------
    def partitions(self, db: str, table: str) -> list[int]:
        t = self._get(db, table)
        with self._lock:
            return sorted(t.parts)

    def part_count(self, db: str, table: str, pid: int) -> int:
        t = self._get(db, table)
        with self._lock:
            return len(t.parts.get(pid, []))

    def drop_partition(self, db: str, table: str, pid: int) -> None:
        t = self._get(db, table)
        with self._lock:
            for part in t.parts.pop(pid, []):
                if isinstance(part, Path):
                    part.unlink(missing_ok=True)
            t.mutations += 1
            epoch = t.mutations
        self._notify_mutation(db, table, epoch)

    def mutation_count(self, db: str, table: str) -> int:
        """Write epoch of one table (0 for a table that does not exist
        yet — its creation bumps nothing, but the first insert does).
        The querier's result cache validates entries against this: one
        int compare per lookup, no scan (ISSUE 10)."""
        with self._lock:
            t = self._dbs.get(db, {}).get(table)
            return 0 if t is None else t.mutations

    def disk_bytes(self, db: str | None = None) -> int:
        with self._lock:
            tabs = [
                t
                for d, ts in self._dbs.items()
                if db is None or d == db
                for t in ts.values()
            ]
            chunks = [p for t in tabs for parts in t.parts.values() for p in parts]
        total = 0
        for chunk in chunks:
            if isinstance(chunk, Path):
                total += chunk.stat().st_size if chunk.exists() else 0
            else:
                total += sum(a.nbytes for a in chunk.values())
        return total
