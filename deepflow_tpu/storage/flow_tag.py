"""SmartEncoding sidecar dictionary writers — the flow_tag analog.

The reference writes, alongside every data batch, dictionary rows per
(org, table, field_name, field_value) so string-valued tags stay
integer-encoded in the wide tables and the querier can enumerate /
translate values at query time (server/ingester/flow_tag/flow_tag_writer.go;
app_service_tag_writer.go:92). Both writers cache recently-written keys
and re-emit only after `cache_ttl_s`, matching FlowTagWriter's
cache-with-timeout dedup.

Tables (one per db, with a `table` column rather than per-table clones):
  flow_tag.custom_field        (time, table, field_name)
  flow_tag.custom_field_value  (time, table, field_name, field_value, count)
  flow_tag.app_service         (time, table, app_service, app_instance)
"""

from __future__ import annotations

import threading

import numpy as np

from .store import ColumnarStore, ColumnSpec, TableSchema
from .writer import TableWriter

FIELD_SCHEMA = TableSchema(
    "custom_field",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("table", "U64"),
        ColumnSpec("field_name", "U128"),
    ),
    partition_s=86400,
)

FIELD_VALUE_SCHEMA = TableSchema(
    "custom_field_value",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("table", "U64"),
        ColumnSpec("field_name", "U128"),
        ColumnSpec("field_value", "U256"),
        ColumnSpec("count", "u8"),
    ),
    partition_s=86400,
)

APP_SERVICE_SCHEMA = TableSchema(
    "app_service",
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("table", "U64"),
        ColumnSpec("app_service", "U256"),
        ColumnSpec("app_instance", "U256"),
    ),
    partition_s=86400,
)


class _CachedDictWriter:
    def __init__(self, writer: TableWriter, cache_ttl_s: float):
        self.writer = writer
        self.cache_ttl_s = cache_ttl_s
        self._cache: dict[tuple, float] = {}
        self._lock = threading.Lock()
        self.cache_hits = 0

    def emit(self, now: float, keys: list[tuple], rows_fn) -> int:
        """Write rows for keys not seen within the TTL; returns written."""
        fresh = []
        with self._lock:
            # prune expired entries so high-cardinality values (endpoints,
            # per-pod instances) don't grow the cache without bound
            if len(self._cache) > 1 << 20:
                self._cache = {
                    k: t for k, t in self._cache.items() if now - t < self.cache_ttl_s
                }
            for k in keys:
                last = self._cache.get(k)
                if last is not None and now - last < self.cache_ttl_s:
                    self.cache_hits += 1
                    continue
                self._cache[k] = now
                fresh.append(k)
        if fresh:
            self.writer.put(rows_fn(fresh))
        return len(fresh)


class FlowTagWriter:
    """Custom-field dictionary sidecar (flow_tag_writer.go analog)."""

    def __init__(
        self, store: ColumnarStore, db: str = "flow_tag", cache_ttl_s: float = 600.0
    ):
        self._fields = _CachedDictWriter(
            TableWriter(store, db, FIELD_SCHEMA, flush_interval_s=0.2), cache_ttl_s
        )
        self._values = _CachedDictWriter(
            TableWriter(store, db, FIELD_VALUE_SCHEMA, flush_interval_s=0.2), cache_ttl_s
        )

    def write(
        self,
        now: int,
        table: str,
        fields: dict[str, dict[str, int]],
    ) -> None:
        """fields: field_name → {field_value: count}. Value counts are
        summed per flush batch; the cache only gates re-emission."""
        self._fields.emit(
            now,
            [(table, f) for f in fields],
            lambda fresh: {
                "time": np.full(len(fresh), now, np.uint32),
                "table": np.array([t for t, _ in fresh]),
                "field_name": np.array([f for _, f in fresh]),
            },
        )
        vals = [(table, f, v, c) for f, vs in fields.items() for v, c in vs.items()]
        self._values.emit(
            now,
            [(t, f, v) for t, f, v, _ in vals],
            lambda fresh: _value_rows(now, {(t, f, v): c for t, f, v, c in vals}, fresh),
        )

    def flush(self):
        self._fields.writer.flush()
        self._values.writer.flush()


def _value_rows(now, counts, fresh):
    return {
        "time": np.full(len(fresh), now, np.uint32),
        "table": np.array([t for t, _, _ in fresh]),
        "field_name": np.array([f for _, f, _ in fresh]),
        "field_value": np.array([v for _, _, v in fresh]),
        "count": np.array([counts[k] for k in fresh], np.uint64),
    }


class AppServiceTagWriter:
    """app_service/app_instance sidecar (app_service_tag_writer.go:92)."""

    def __init__(
        self, store: ColumnarStore, db: str = "flow_tag", cache_ttl_s: float = 600.0
    ):
        self._w = _CachedDictWriter(
            TableWriter(store, db, APP_SERVICE_SCHEMA, flush_interval_s=0.2), cache_ttl_s
        )

    def write(self, now: int, table: str, pairs: list[tuple[str, str]]) -> None:
        self._w.emit(
            now,
            [(table, s, i) for s, i in pairs if s],
            lambda fresh: {
                "time": np.full(len(fresh), now, np.uint32),
                "table": np.array([t for t, _, _ in fresh]),
                "app_service": np.array([s for _, s, _ in fresh]),
                "app_instance": np.array([i for _, _, i in fresh]),
            },
        )

    def flush(self):
        self._w.writer.flush()
