"""Columnar telemetry store — the ClickHouse seat in the reference.

`store.py` is the table/partition engine (ckdb analog), `writer.py` the
batched ingest writer (ckwriter analog), `flow_tag.py` the SmartEncoding
sidecar dictionaries.
"""

from .store import ColumnarStore, ColumnSpec, TableSchema, org_db
from .writer import TableWriter

__all__ = ["ColumnarStore", "ColumnSpec", "TableSchema", "TableWriter", "org_db"]
