"""Batched table writer — the ckwriter analog.

`CKWriter.Put` queues rows, a per-queue goroutine batches them and flushes
on size or timeout, with retry and connection reset on failure
(server/ingester/pkg/ckwriter/ckwriter.go:481-636). `TableWriter` keeps
that contract against the columnar store: `put(cols)` enqueues a column
batch; the flusher thread coalesces batches and inserts one part per
flush, retrying on transient store errors; counters surface
write-ok/fail/retry like ckwriter's Countable (ckwriter.go:465-479).
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .. import chaos
from ..utils.stats import register_countable
from .store import ColumnarStore, TableSchema


class TableWriter:
    def __init__(
        self,
        store: ColumnarStore,
        db: str,
        schema: TableSchema,
        *,
        batch_size: int = 1 << 15,
        flush_interval_s: float = 1.0,
        queue_capacity: int = 256,
        retries: int = 3,
    ):
        store.create_table(db, schema)
        self.store = store
        self.db = db
        self.schema = schema
        self.batch_size = batch_size
        self.flush_interval_s = flush_interval_s
        self.retries = retries
        self._q: queue.Queue = queue.Queue(maxsize=queue_capacity)
        self.counters = {
            "write_ok": 0,
            "write_fail": 0,
            "retry": 0,
            "dropped_full": 0,
            "pending_rows": 0,
        }
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        # register the writer itself (weakly held → auto-deregistered);
        # stop() also deregisters explicitly for deterministic teardown
        self._stats_src = register_countable(
            "table_writer", self, db=db, table=schema.name
        )

    def get_counters(self):
        with self._lock:
            return dict(self.counters)

    # -- producer side --------------------------------------------------
    def put(self, cols: dict[str, np.ndarray]) -> bool:
        """Enqueue a column batch; sheds (and counts) when the queue is
        full — matching the reference's drop-not-block backpressure."""
        n = len(next(iter(cols.values()))) if cols else 0
        if n == 0:
            return True
        try:
            self._q.put_nowait(cols)
            with self._lock:
                self.counters["pending_rows"] += n
            return True
        except queue.Full:
            with self._lock:
                self.counters["dropped_full"] += n
            return False

    # -- flusher --------------------------------------------------------
    def _run(self):
        pending: list[dict[str, np.ndarray]] = []
        pending_rows = 0
        deadline = time.monotonic() + self.flush_interval_s
        while True:
            timeout = max(0.0, deadline - time.monotonic())
            try:
                item = self._q.get(timeout=timeout)
                pending.append(item)
                pending_rows += len(next(iter(item.values())))
            except queue.Empty:
                pass
            now = time.monotonic()
            if pending and (pending_rows >= self.batch_size or now >= deadline):
                self._flush(pending, pending_rows)
                pending, pending_rows = [], 0
            if now >= deadline:
                deadline = now + self.flush_interval_s
            if self._stop.is_set() and self._q.empty():
                if pending:
                    self._flush(pending, pending_rows)
                return

    def _flush(self, batches: list[dict[str, np.ndarray]], rows: int):
        names = self.schema.column_names()
        try:
            merged = {
                nm: np.concatenate([np.asarray(b[nm]) for b in batches]) for nm in names
            }
            for attempt in range(self.retries):
                try:
                    # chaos seam: storage write faults (SinkWriteError is
                    # an OSError, so injected failures exercise the real
                    # retry/fail-count path below)
                    chaos.maybe_fail(chaos.SITE_SINK_WRITE)
                    self.store.insert(self.db, self.schema.name, merged)
                    with self._lock:
                        self.counters["write_ok"] += rows
                        self.counters["pending_rows"] -= rows
                    return
                except OSError:
                    with self._lock:
                        self.counters["retry"] += 1
                    time.sleep(0.05 * (attempt + 1))
        except Exception:
            # malformed batch (missing/ragged columns) — count it as a
            # failed write; the flusher thread must survive any input
            pass
        with self._lock:
            self.counters["write_fail"] += rows
            self.counters["pending_rows"] -= rows

    def flush(self, timeout: float = 5.0) -> None:
        """Drain everything queued so far (tests / shutdown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.counters["pending_rows"] == 0 and self._q.empty():
                    return
            time.sleep(0.01)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout=timeout)
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)
