"""Batched table writer — the ckwriter analog.

`CKWriter.Put` queues rows, a per-queue goroutine batches them and flushes
on size or timeout, with retry and connection reset on failure
(server/ingester/pkg/ckwriter/ckwriter.go:481-636). `TableWriter` keeps
that contract against the columnar store: `put(cols)` enqueues a column
batch; the flusher thread coalesces batches and inserts one part per
flush, retrying on transient store errors; counters surface
write-ok/fail/retry like ckwriter's Countable (ckwriter.go:465-479).
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .. import chaos
from ..utils.stats import register_countable
from .store import ColumnarStore, TableSchema


class _WriterLiveSource:
    """LiveRegistry provider over a TableWriter's queued-but-unflushed
    batches (ISSUE 11 satellite, ROADMAP item (a)): the server-layer
    metrics writers' pending rows ARE the open span for their tables —
    a range query ending "now" sees rows the flusher has not landed
    yet, marked partial, and the flushed insert supersedes them (the
    mirror drops a batch BEFORE its insert, so a row is never served
    from both sides — transient invisibility between drop and insert
    is a bounded freshness gap, never a double count)."""

    def __init__(self, writer: "TableWriter"):
        self._writer = writer

    def __call__(self, lo: int, hi: int):
        w = self._writer
        with w._lock:
            pending = list(w._live_pending)
        if not pending:
            return None
        names = w.schema.column_names()
        tcol = w.schema.time_column
        parts = []
        for b in pending:
            try:
                ts = np.asarray(b[tcol], np.int64)
            except (KeyError, TypeError):
                continue
            sel = (ts >= lo) & (ts < hi)
            if sel.any():
                try:
                    parts.append({k: np.asarray(b[k])[sel] for k in names})
                except KeyError:  # malformed batch — the flusher counts it
                    continue
        if not parts:
            return None
        if len(parts) == 1:
            return parts[0]
        return {k: np.concatenate([p[k] for p in parts]) for k in names}

    def epoch(self) -> int:
        return self._writer._live_epoch

    def open_from(self) -> int | None:
        w = self._writer
        tcol = w.schema.time_column
        with w._lock:
            pending = list(w._live_pending)
        vals = [
            int(np.min(b[tcol])) for b in pending
            if tcol in b and len(np.atleast_1d(b[tcol]))
        ]
        return min(vals) if vals else None


class TableWriter:
    def __init__(
        self,
        store: ColumnarStore,
        db: str,
        schema: TableSchema,
        *,
        batch_size: int = 1 << 15,
        flush_interval_s: float = 1.0,
        queue_capacity: int = 256,
        retries: int = 3,
        live_registry=None,
    ):
        store.create_table(db, schema)
        self.store = store
        self.db = db
        self.schema = schema
        self.batch_size = batch_size
        self.flush_interval_s = flush_interval_s
        self.retries = retries
        self._q: queue.Queue = queue.Queue(maxsize=queue_capacity)
        self.counters = {
            "write_ok": 0,
            "write_fail": 0,
            "retry": 0,
            "dropped_full": 0,
            "pending_rows": 0,
        }
        self._lock = threading.Lock()
        # live read plane (ISSUE 11): the pending mirror tracks batches
        # from put() until the flusher hands them to the store; a
        # registered _WriterLiveSource serves them as open-span rows
        self._live_pending: list = []
        self._live_epoch = 0
        self._live_handle = None
        self._live_registry = live_registry
        if live_registry is not None:
            self._live_handle = live_registry.register(
                db, schema.name, _WriterLiveSource(self)
            )
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        # register the writer itself (weakly held → auto-deregistered);
        # stop() also deregisters explicitly for deterministic teardown
        self._stats_src = register_countable(
            "table_writer", self, db=db, table=schema.name
        )

    def get_counters(self):
        with self._lock:
            return dict(self.counters)

    # -- producer side --------------------------------------------------
    def put(self, cols: dict[str, np.ndarray]) -> bool:
        """Enqueue a column batch; sheds (and counts) when the queue is
        full — matching the reference's drop-not-block backpressure."""
        n = len(next(iter(cols.values()))) if cols else 0
        if n == 0:
            return True
        # mirror BEFORE the queue handoff: once the batch is in the
        # queue the flusher may retire-and-insert it at any moment, and
        # a mirror append landing after that retire pass would serve
        # the rows live forever ALONGSIDE their store copy (permanent
        # double count + mirror leak)
        if self._live_handle is not None:
            with self._lock:
                self._live_pending.append(cols)
                self._live_epoch += 1
        try:
            self._q.put_nowait(cols)
            with self._lock:
                self.counters["pending_rows"] += n
            return True
        except queue.Full:
            with self._lock:
                self.counters["dropped_full"] += n
                if self._live_handle is not None and self._live_pending:
                    # the batch never entered the pipeline — un-mirror it
                    self._live_pending = [
                        b for b in self._live_pending if b is not cols
                    ]
                    self._live_epoch += 1
            return False

    # -- flusher --------------------------------------------------------
    def _run(self):
        pending: list[dict[str, np.ndarray]] = []
        pending_rows = 0
        deadline = time.monotonic() + self.flush_interval_s
        while True:
            timeout = max(0.0, deadline - time.monotonic())
            try:
                item = self._q.get(timeout=timeout)
                pending.append(item)
                pending_rows += len(next(iter(item.values())))
            except queue.Empty:
                pass
            now = time.monotonic()
            if pending and (pending_rows >= self.batch_size or now >= deadline):
                self._flush(pending, pending_rows)
                pending, pending_rows = [], 0
            if now >= deadline:
                deadline = now + self.flush_interval_s
            if self._stop.is_set() and self._q.empty():
                if pending:
                    self._flush(pending, pending_rows)
                return

    def _flush(self, batches: list[dict[str, np.ndarray]], rows: int):
        names = self.schema.column_names()
        # retire the batches from the live mirror BEFORE the insert:
        # between retire and insert a query sees neither copy (a bounded
        # freshness gap — the rows "haven't arrived yet"); retiring
        # after would let one query see both and double-count in SQL
        # aggregates, the forbidden outcome
        with self._lock:
            if self._live_handle is not None and self._live_pending:
                ids = {id(b) for b in batches}
                self._live_pending = [
                    b for b in self._live_pending if id(b) not in ids
                ]
                self._live_epoch += 1
        try:
            merged = {
                nm: np.concatenate([np.asarray(b[nm]) for b in batches]) for nm in names
            }
            for attempt in range(self.retries):
                try:
                    # chaos seam: storage write faults (SinkWriteError is
                    # an OSError, so injected failures exercise the real
                    # retry/fail-count path below)
                    chaos.maybe_fail(chaos.SITE_SINK_WRITE)
                    self.store.insert(self.db, self.schema.name, merged)
                    with self._lock:
                        self.counters["write_ok"] += rows
                        self.counters["pending_rows"] -= rows
                    return
                except OSError:
                    with self._lock:
                        self.counters["retry"] += 1
                    time.sleep(0.05 * (attempt + 1))
        except Exception:
            # malformed batch (missing/ragged columns) — count it as a
            # failed write; the flusher thread must survive any input
            pass
        with self._lock:
            self.counters["write_fail"] += rows
            self.counters["pending_rows"] -= rows

    def flush(self, timeout: float = 5.0) -> None:
        """Drain everything queued so far (tests / shutdown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.counters["pending_rows"] == 0 and self._q.empty():
                    return
            time.sleep(0.01)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout=timeout)
        if self._live_handle is not None:
            self._live_registry.unregister(self._live_handle)
            self._live_handle = None
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)
