"""Store monitor — TTL expiry + disk-watermark priority drops.

The ckmonitor seat (server/ingester/ckmonitor/monitor.go:75-206): the
reference checks ClickHouse disk usage against a watermark and
force-drops the oldest partitions, lowest-priority tables first, until
usage falls below it; TTL expiry runs alongside. Same protocol over the
columnar store: `check()` enforces per-table TTLs, then while
`disk_bytes()` exceeds `max_bytes` walks the priority ladder dropping
each victim table's OLDEST partition (never the newest — that is the
live write head).
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path

from .store import ColumnarStore

_ORG_PREFIX = re.compile(r"^\d{4}_")  # org_db() prefixes non-default orgs

# drop order under disk pressure (lowest value drops first) — raw and
# log planes are sacrificed before aggregated metrics, matching the
# reference's priority list stance
DEFAULT_PRIORITIES = {
    "pcap": 0,
    "application_log": 1,
    "flow_log": 2,
    "profile": 3,
    "ext_metrics": 4,
    "deepflow_stats": 4,
    "prometheus": 5,
    "event": 6,
    "flow_metrics": 7,
}
_DEFAULT_PRIORITY = 5


@dataclasses.dataclass
class StoreMonitor:
    store: ColumnarStore
    max_bytes: int | None = None  # None = no watermark enforcement
    ttl_hours: dict = dataclasses.field(default_factory=dict)  # (db, table) → h
    priorities: dict = dataclasses.field(default_factory=lambda: dict(DEFAULT_PRIORITIES))

    def __post_init__(self):
        self.counters = {"ttl_dropped": 0, "watermark_dropped": 0, "checks": 0}

    def get_counters(self):
        return dict(self.counters)

    # -- TTL -------------------------------------------------------------
    def _expire_ttl(self, now: int) -> int:
        """Per-table TTLs: explicit overrides first, else the TTL the
        table's schema carries (every TableSchema has ttl_hours)."""
        dropped = 0
        for db in self.store.databases():
            for table in self.store.tables(db):
                try:
                    schema = self.store.schema(db, table)
                except KeyError:
                    continue
                hours = self.ttl_hours.get(
                    (db, table), getattr(schema, "ttl_hours", 0)
                )
                if not hours:
                    continue
                cutoff_pid = (now - hours * 3600) // schema.partition_s
                for pid in self.store.partitions(db, table):
                    if pid < cutoff_pid:
                        self.store.drop_partition(db, table, pid)
                        dropped += 1
        return dropped

    # -- watermark -------------------------------------------------------
    def _priority(self, db: str) -> int:
        base = _ORG_PREFIX.sub("", db)  # org-prefixed dbs share the base priority
        for key, pri in self.priorities.items():
            if base == key or base.startswith(key):
                return pri
        return _DEFAULT_PRIORITY

    def _victims(self):
        """(priority, oldest_pid, db, table) for every droppable table —
        tables with ≥2 partitions only, so the live head survives."""
        out = []
        for db in self.store.databases():
            pri = self._priority(db)
            for table in self.store.tables(db):
                pids = self.store.partitions(db, table)
                if len(pids) >= 2:
                    out.append((pri, pids[0], db, table))
        out.sort()
        return out

    def _partition_bytes(self, db: str, table: str, pid: int) -> int:
        t = self.store._get(db, table)
        with self.store._lock:
            parts = list(t.parts.get(pid, []))
        total = 0
        for p in parts:
            if isinstance(p, Path):
                try:
                    total += p.stat().st_size
                except OSError:
                    pass
            else:  # in-memory part: approximate array bytes
                total += sum(getattr(a, "nbytes", 0) for a in p.values())
        return total

    def _enforce_watermark(self) -> tuple[int, int]:
        """Returns (dropped, disk_bytes_after). disk_bytes() is a full
        stat() walk, so it runs ONCE; each drop subtracts the victim's
        measured size instead of re-walking."""
        if self.max_bytes is None:
            return 0, -1
        used = self.store.disk_bytes()
        dropped = 0
        while used > self.max_bytes:
            victims = self._victims()
            if not victims:
                break
            _pri, pid, db, table = victims[0]
            used -= self._partition_bytes(db, table, pid)
            self.store.drop_partition(db, table, pid)
            dropped += 1
        return dropped, used

    def check(self, now: int) -> dict:
        """One monitor pass; call from the server tick."""
        self.counters["checks"] += 1
        t = self._expire_ttl(now)
        w, used = self._enforce_watermark()
        self.counters["ttl_dropped"] += t
        self.counters["watermark_dropped"] += w
        return {"ttl_dropped": t, "watermark_dropped": w, "disk_bytes": used}
