"""Third-party trace imports: SkyWalking segments + Datadog traces.

The reference converts both formats into L7FlowLog spans inside the
flow_log decoder (decoder.go:289 handleSkyWalking, :338 handleDatadog;
converters under log_data/sw_import and log_data/dd_import). Same target
here: each import yields the OtelSpan shape the OTel lane already turns
into l7_flow_log rows + trace-tree spans, so every downstream plane
(tables, tracing, RED metrics) is shared.

Wire formats, from the public protocols:
  * SkyWalking: SegmentObject protobuf (skywalking-data-collect-protocol
    language-agent/Tracing.proto v3): traceId=1, traceSegmentId=2,
    spans=3[SpanObject], service=4, serviceInstance=5. SpanObject:
    spanId=1, parentSpanId=2 (i32, -1 = root), startTime=3 ms,
    endTime=4 ms, refs=5[SegmentReference{refType=1, traceId=2,
    parentTraceSegmentId=3, parentSpanId=4}], operationName=6, peer=7,
    spanType=8 (0 Entry/1 Exit/2 Local), spanLayer=9, componentId=10,
    isError=11, tags=12[KeyStringValuePair{key=1, value=2}].
  * Datadog: the MsgPack v0.4 trace payload is out of scope without a
    msgpack codec in-image; the JSON form (array of arrays of spans with
    trace_id/span_id/parent_id/service/name/resource/start/duration/
    error/meta) decodes natively and is what our collector accepts.
"""

from __future__ import annotations

import json

from .formats import OtelSpan, _iter_fields, _zigzag_free_i64


def _pb_str(v) -> str:
    return bytes(v).decode("utf-8", "replace")


def _parse_sw_span(buf: bytes) -> dict:
    s = {
        "span_id": 0, "parent_span_id": -1, "start_ms": 0, "end_ms": 0,
        "op": "", "is_error": False, "span_type": 0, "refs_parent": "",
        "peer": "", "tags": {},
    }
    for f, v in _iter_fields(buf):
        if f == 1:
            s["span_id"] = _zigzag_free_i64(v)
        elif f == 2:
            s["parent_span_id"] = _zigzag_free_i64(v)
        elif f == 3:
            s["start_ms"] = _zigzag_free_i64(v)
        elif f == 4:
            s["end_ms"] = _zigzag_free_i64(v)
        elif f == 5 and isinstance(v, (bytes, bytearray, memoryview)):
            # SegmentReference: parentTraceSegmentId=3 (string),
            # parentSpanId=4
            ref_seg, ref_span = "", -1
            for rf, rv in _iter_fields(bytes(v)):
                if rf == 3 and isinstance(rv, (bytes, bytearray, memoryview)):
                    ref_seg = _pb_str(rv)
                elif rf == 4 and not isinstance(rv, (bytes, bytearray, memoryview)):
                    ref_span = _zigzag_free_i64(rv)
            if ref_seg:
                s["refs_parent"] = f"{ref_seg}-{ref_span}"
        elif f == 6 and isinstance(v, (bytes, bytearray, memoryview)):
            s["op"] = _pb_str(v)
        elif f == 7 and isinstance(v, (bytes, bytearray, memoryview)):
            s["peer"] = _pb_str(v)
        elif f == 8:
            s["span_type"] = _zigzag_free_i64(v)
        elif f == 11:
            s["is_error"] = bool(_zigzag_free_i64(v))
        elif f == 12 and isinstance(v, (bytes, bytearray, memoryview)):
            k = val = ""
            for tf, tv in _iter_fields(bytes(v)):
                if tf == 1:
                    k = _pb_str(tv)
                elif tf == 2:
                    val = _pb_str(tv)
            if k:
                s["tags"][k] = val
    return s


def parse_skywalking_segment(data: bytes) -> list[OtelSpan]:
    """SegmentObject pb → OtelSpans (sw_import seat). Span ids are
    segment-scoped in SkyWalking, so wire ids are '<segment>-<span_id>';
    cross-segment parents come from SegmentReference."""
    trace_id = segment_id = service = instance = ""
    raw_spans = []
    try:
        for f, v in _iter_fields(data):
            if f == 1 and isinstance(v, (bytes, bytearray, memoryview)):
                trace_id = _pb_str(v)
            elif f == 2 and isinstance(v, (bytes, bytearray, memoryview)):
                segment_id = _pb_str(v)
            elif f == 3 and isinstance(v, (bytes, bytearray, memoryview)):
                raw_spans.append(_parse_sw_span(bytes(v)))
            elif f == 4 and isinstance(v, (bytes, bytearray, memoryview)):
                service = _pb_str(v)
            elif f == 5 and isinstance(v, (bytes, bytearray, memoryview)):
                instance = _pb_str(v)
    except Exception:
        return []
    if not trace_id or not raw_spans:
        return []
    out = []
    for s in raw_spans:
        if s["parent_span_id"] >= 0:
            parent = f"{segment_id}-{s['parent_span_id']}"
        else:
            parent = s["refs_parent"]  # cross-segment or root
        out.append(
            OtelSpan(
                service=service,
                name=s["op"],
                trace_id=trace_id,
                span_id=f"{segment_id}-{s['span_id']}",
                parent_span_id=parent,
                kind=3 if s["span_type"] == 1 else 2,  # Exit→client
                start_us=s["start_ms"] * 1000,
                end_us=s["end_ms"] * 1000,
                status_code=2 if s["is_error"] else 0,
                attributes={
                    **s["tags"],
                    **({"sw8.instance": instance} if instance else {}),
                    **({"net.peer.name": s["peer"]} if s["peer"] else {}),
                },
            )
        )
    return out


def parse_datadog_traces(data: bytes) -> list[OtelSpan]:
    """Datadog JSON trace payload → OtelSpans (dd_import seat)."""
    try:
        payload = json.loads(data)
    except (ValueError, UnicodeDecodeError):
        return []
    if not isinstance(payload, list):
        return []
    out = []
    for trace in payload:
        if not isinstance(trace, list):
            continue
        for sp in trace:
            if not isinstance(sp, dict):
                continue
            try:
                meta = sp.get("meta") or {}
                if not isinstance(meta, dict):
                    meta = {}
                start_ns = int(sp.get("start") or 0)
                dur_ns = int(sp.get("duration") or 0)
                out.append(
                    OtelSpan(
                        service=str(sp.get("service", "")),
                        name=str(sp.get("resource", sp.get("name", ""))),
                        trace_id=format(int(sp.get("trace_id") or 0), "032x"),
                        span_id=format(int(sp.get("span_id") or 0), "016x"),
                        parent_span_id=(
                            format(int(sp["parent_id"]), "016x")
                            if sp.get("parent_id")
                            else ""
                        ),
                        kind=3 if meta.get("span.kind") == "client" else 2,
                        start_us=start_ns // 1000,
                        end_us=(start_ns + dur_ns) // 1000,
                        status_code=2 if int(sp.get("error") or 0) else 0,
                        attributes={str(k): str(v) for k, v in meta.items()},
                    )
                )
            except (TypeError, ValueError):
                continue  # one malformed span must not drop its siblings
    return out
