"""Self-telemetry loop closure: StatsCollector → dfstats wire frames →
`deepflow_system` tables.

The reference serializes every component's counters as InfluxDB points
and ships them into its own ext_metrics pipeline as `deepflow_stats`
(server/libs/stats/stats.go:89-202). Two loops live here:

  * `stats_sink(sender)` — the wire loop: snapshots flow over DFSTATS
    frames into the deepflow_stats tables through the full ingest path
    (receiver → IntegrationIngester), queryable with the same SQL
    engine as everything else.
  * `system_sink(store)` — the dogfood loop (ISSUE 3): snapshots land
    directly in the store's `deepflow_system.deepflow_system` table in
    the prometheus-samples shape (time, metric, labels, value), so the
    framework's own querier answers questions about the framework —
    SQL (`SELECT value FROM deepflow_system.deepflow_system WHERE
    metric = 'tpu_pipeline_doc_in'`) and PromQL
    (`tpu_pipeline_doc_in{kind="L4Pipeline"}` with
    db="deepflow_system", table="deepflow_system") both work.

Influx line serialization follows the line-protocol typing rules:
integer fields keep their `{v}i` suffix (the reference's counters are
int-typed; coercing to float silently loses that), tag values escape
backslash/comma/equals/space, and non-finite floats are skipped — a
NaN field would poison the whole line at parse time.
"""

from __future__ import annotations

import math
import numbers
import re

import numpy as np

from ..ingest.sender import UniformSender
from ..storage.store import ColumnSpec, TableSchema
from ..utils.stats import StatsPoint


def _escape_tag(v: str) -> str:
    """Influx line-protocol tag-value escaping: backslash first, then
    the three structural characters (`,` `=` space)."""
    return (
        v.replace("\\", "\\\\")
        .replace(",", "\\,")
        .replace("=", "\\=")
        .replace(" ", "\\ ")
    )


def points_to_influx(points: list[StatsPoint]) -> str:
    lines = []
    for p in points:
        tags = "".join(f",{k}={_escape_tag(str(v))}" for k, v in p.tags)
        parts = []
        for k, v in p.fields.items():
            if isinstance(v, bool):
                v = int(v)
            if isinstance(v, numbers.Integral):
                parts.append(f"{k}={int(v)}i")  # keep influx int typing
            elif isinstance(v, numbers.Real):
                f = float(v)
                if math.isfinite(f):  # NaN/inf poison the line — skip
                    parts.append(f"{k}={f}")
        if not parts:
            continue
        lines.append(f"{p.module}{tags} {','.join(parts)} {int(p.timestamp * 1e9)}")
    return "\n".join(lines)


def stats_sink(sender: UniformSender):
    """→ a sink callable for StatsCollector.add_sink."""

    def sink(points: list[StatsPoint]) -> None:
        if not points:
            return
        text = points_to_influx(points)
        if text:
            sender.send([text.encode()])

    return sink


# ---------------------------------------------------------------------------
# deepflow_system: the dogfooded self-telemetry table (ISSUE 3). Same
# row shape as prometheus.samples so BOTH query engines read it: the
# SQL engine resolves `deepflow_system.deepflow_system` directly, and
# promql.query_instant/query_range accept db/table overrides.

DEEPFLOW_SYSTEM_DB = "deepflow_system"
DEEPFLOW_SYSTEM_TABLE = "deepflow_system"
# metric/labels are variable-width ("O", the ClickHouse-String analogue
# the store serializes per-part) — a fixed U<n> would silently clip a
# long packed label string, possibly mid-escape, and a PromQL selector
# would then match nothing with no error
DEEPFLOW_SYSTEM_SCHEMA = TableSchema(
    DEEPFLOW_SYSTEM_TABLE,
    (
        ColumnSpec("time", "u4"),
        ColumnSpec("metric", "O"),
        ColumnSpec("labels", "O"),
        ColumnSpec("value", "f8"),
    ),
)

_METRIC_SAN_RE = re.compile(r"[^a-zA-Z0-9_:]")


def system_metric_name(module: str, field: str) -> str:
    """`<module>_<field>` sanitized to the PromQL metric charset
    ([a-zA-Z_:][a-zA-Z0-9_:]*) — span fields like `stats.fetch.count`
    become `stats_fetch_count`."""
    return _METRIC_SAN_RE.sub("_", f"{module}_{field}")


def points_to_system_columns(
    points: list[StatsPoint], *, extra_tags: dict | None = None
) -> dict[str, np.ndarray]:
    """StatsPoints → deepflow_system columns, one row per (point, field).

    Values store as f8 — integer counters up to 2^53 round-trip
    bit-exactly (the acceptance test pins this). Non-finite and
    non-numeric fields are skipped, same stance as points_to_influx.

    `extra_tags` merge into every row's packed labels (winning on
    collision) — the fleet aggregator stamps `host`/`group` here so
    per-host attribution is a plain PromQL label selector."""
    from .formats import pack_tags

    extra = {k: str(v) for k, v in (extra_tags or {}).items()}
    time_col: list[int] = []
    metric: list[str] = []
    labels: list[str] = []
    value: list[float] = []
    for p in points:
        packed = pack_tags({**{k: str(v) for k, v in p.tags}, **extra})
        for fname, v in p.fields.items():
            if isinstance(v, bool):
                v = int(v)
            if not isinstance(v, numbers.Real):
                continue
            f = float(v)
            if not math.isfinite(f):
                continue
            time_col.append(int(p.timestamp))
            metric.append(system_metric_name(p.module, fname))
            labels.append(packed)
            value.append(f)
    return {
        "time": np.asarray(time_col, np.uint32),
        "metric": np.asarray(metric, dtype=object),
        "labels": np.asarray(labels, dtype=object),
        "value": np.asarray(value, np.float64),
    }


def ensure_system_table(store) -> None:
    store.create_table(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_SCHEMA)


def system_sink(store):
    """→ a StatsCollector sink writing snapshots straight into the
    store's deepflow_system table (no wire hop — this is the in-process
    dogfood path the bench/test stacks use; production stacks keep the
    DFSTATS wire loop as well)."""
    ensure_system_table(store)

    def sink(points: list[StatsPoint]) -> None:
        if not points:
            return
        cols = points_to_system_columns(points)
        if len(cols["time"]):
            store.insert(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, cols)

    return sink


# ---------------------------------------------------------------------------
# Sketch tier → deepflow_system (ISSUE 8). Closed-window sketch blocks
# (aggregator/sketchplane.WindowSketchBlock) land in the SAME
# prometheus-samples row shape, so distinct-count / quantile /
# heavy-hitter answers for a closed window are queryable through BOTH
# engines without flushing exact rows:
#   SQL:    SELECT value FROM deepflow_system.deepflow_system
#           WHERE metric = 'deepflow_sketch_distinct' AND time = <w>
#   PromQL: deepflow_sketch_distinct{service="3"}
#           topk(5, deepflow_sketch_top_bytes)  (querier/promql.py)

SKETCH_METRIC_DISTINCT = "deepflow_sketch_distinct"
SKETCH_METRIC_QUANTILE = "deepflow_sketch_rtt_quantile"
SKETCH_METRIC_TOPK = "deepflow_sketch_top_bytes"


def sketch_block_rows(
    block, interval: int, *, quantiles=(0.5, 0.95, 0.99), topk: int = 16
) -> list[tuple[int, str, dict, float]]:
    """One closed-window block → (time, metric, labels, value) rows.

    Per-service distinct counts (services whose HLL row saw data) and
    rtt quantiles, the window-level distinct count, and the inverted
    top-K heavy flows (one series per recovered key: the `key` label is
    the flow fingerprint, `ip`/`svc` carry the id-preview words)."""
    import jax.numpy as jnp

    from ..ops.tdigest import tdigest_quantile

    t = block.window * interval
    rows: list[tuple[int, str, dict, float]] = []
    rows.append((t, SKETCH_METRIC_DISTINCT, {"service": "all"}, block.distinct()))
    per_group = block.distinct_per_group()
    active = np.nonzero(block.hll.max(axis=1) > 0)[0]
    for g in active:
        g = int(g)
        rows.append(
            (t, SKETCH_METRIC_DISTINCT, {"service": str(g)}, float(per_group[g]))
        )
        # quantile rows only for services with actual latency samples —
        # an all-zero histogram (e.g. UDP-only traffic, rtt_count=0)
        # must produce NO series, not a fake 0 ms series. One t-digest
        # compression serves every requested quantile.
        if block.hist[g].sum() > 0:
            m, w = block.tdigest(g)
            qv = np.asarray(tdigest_quantile(
                jnp.asarray(m), jnp.asarray(w),
                jnp.asarray(list(quantiles), jnp.float32),
            ))
            for q, v in zip(quantiles, qv):
                rows.append(
                    (t, SKETCH_METRIC_QUANTILE,
                     {"service": str(g), "q": str(q)}, float(v))
                )
    for rank, hh in enumerate(block.topk(topk)):
        rows.append(
            (
                t, SKETCH_METRIC_TOPK,
                {
                    "key": f"{hh['key_hi']:08x}{hh['key_lo']:08x}",
                    "rank": str(rank),
                    "ip": str(hh["id_a"]),
                    "svc": str(hh["id_b"]),
                },
                float(hh["estimate"]),
            )
        )
    return rows


def sketch_rows_to_columns(rows) -> dict[str, np.ndarray]:
    from .formats import pack_tags

    return {
        "time": np.asarray([r[0] for r in rows], np.uint32),
        "metric": np.asarray([r[1] for r in rows], dtype=object),
        "labels": np.asarray([pack_tags(r[2]) for r in rows], dtype=object),
        "value": np.asarray([r[3] for r in rows], np.float64),
    }


def sketch_system_sink(store, interval: int = 1, *, bus=None, **row_kw):
    """→ a callable(blocks) writing closed-window sketch answers into
    deepflow_system — wire a pipeline's `pop_closed_sketches()` (or a
    ShardedWindowManager's) into it after every ingest/drain. With
    `bus` set (ISSUE 11), one WindowClosed/TierClosed batch publishes
    AFTER the insert, so heavy-hitter alert rules over the sketch
    plane's `topk()` lane re-evaluate the moment a window's sketch
    answers land."""
    ensure_system_table(store)

    def sink(blocks) -> None:
        import contextlib

        rows = []
        events = []
        for b in blocks:
            rows.extend(sketch_block_rows(b, interval, **row_kw))
            if bus is not None:
                from ..querier.events import TierClosed, WindowClosed

                t, i = b.window * interval, int(interval)
                events.append(
                    WindowClosed(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, t, i)
                    if i <= 1 else
                    TierClosed(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, t, i)
                )
        # one dispatch per sink call: the insert's StoreMutation joins
        # the data-timed close events in a single batch (bus.batch)
        with (bus.batch() if bus is not None else contextlib.nullcontext()):
            if rows:
                store.insert(
                    DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE,
                    sketch_rows_to_columns(rows),
                )
            if events and bus is not None:
                bus.publish(events)

    return sink


# ---------------------------------------------------------------------------
# Live read plane (ISSUE 10): the open-window overlay's dogfood
# adapters. Two kinds of live source plug into querier/live.LiveRegistry:
#
#   * `live_flow_source(pipeline_or_manager)` — per-flow rows of the
#     OPEN windows from `snapshot_open()`, in the prometheus-samples
#     shape (one `deepflow_flow_bytes{key=…}` series per stash row).
#     The SAME `flow_window_rows` builder serves a closed window's
#     flushed rows (`flow_window_sink`), so the live partial and the
#     post-flush value are bit-exact by construction once the window's
#     traffic stops — the acceptance pin in tests/test_live_read.py.
#   * `live_system_source(collector)` — the CURRENT counter values of
#     every registered Countable (StatsCollector.sample — no sink, no
#     store write), stamped at the query's upper time edge: feeder
#     health and device counter lanes answer at sub-`delay` latency
#     instead of waiting for the next collector tick. deepflow_tpu
#     observing itself in real time.

LIVE_METRIC_FLOW_BYTES = "deepflow_flow_bytes"


def flow_window_rows(
    f, *, metric: str = LIVE_METRIC_FLOW_BYTES, meter_col: int | None = None,
    meter_schema=None,
) -> list[tuple[int, str, dict, float]]:
    """One (open-partial OR flushed) window's rows → samples rows: a
    series per flow key (labels: the 64-bit fingerprint + window id),
    value = the chosen meter column (default byte_tx). Shared by the
    live source and the closed-window sink so the two emit identical
    values for identical window content."""
    if meter_col is None:
        from ..datamodel.schema import FLOW_METER

        meter_col = (meter_schema or FLOW_METER).index("byte_tx")
    rows = []
    for i in range(f.count):
        rows.append(
            (
                f.start_time, metric,
                {"key": f"{int(f.key_hi[i]):08x}{int(f.key_lo[i]):08x}",
                 "window": str(f.window_idx)},
                float(f.meters[i, meter_col]),
            )
        )
    return rows


class PipelineLiveSource:
    """LiveRegistry provider over an object exposing `snapshot_open()`
    (RollupPipeline, WindowManager, ShardedWindowManager): open-window
    partial rows in the samples shape. `epoch()` returns the snapshot
    seq — and may TAKE the (rate-limited) snapshot, so the result
    cache's live token names exactly the generation a subsequent
    evaluation reads.

    Two correctness/efficiency guards on top of the raw snapshot:

      * windows the manager has CLOSED since the (rate-limited)
        snapshot was cached are dropped at pull time, using the
        manager's host-side `start_window` (a plain int — no device
        read). A closed window's flushed rows are in (or en route to)
        the store; serving its stale partial alongside them would
        double-count in SQL aggregates, which have no per-series
        last-sample-wins dedup the way PromQL does.
      * rows are BUILT once per snapshot generation and cached; a
        range query's per-step pulls slice the prebuilt columns with a
        numpy time mask instead of rebuilding per-row label dicts
        O(steps × rows) times."""

    def __init__(self, owner, row_builder=flow_window_rows):
        self.owner = owner
        self.row_builder = row_builder
        self._built: tuple | None = None  # (seq, columns dict | None)

    def _open_lo(self):
        """The manager's CURRENT open-span start in seconds (host int;
        None = nothing ingested) — fresher than the cached snapshot."""
        wm = getattr(self.owner, "wm", self.owner)
        sw = getattr(wm, "start_window", None)
        if sw is None:
            return None
        interval = getattr(wm, "interval", None)
        if interval is None:
            interval = wm.config.interval
        return sw * interval

    def _columns(self):
        snap = self.owner.snapshot_open()
        if self._built is not None and self._built[0] == snap.seq:
            return self._built[1]
        rows = []
        for w in snap.windows:
            rows.extend(self.row_builder(w))
        cols = sketch_rows_to_columns(rows) if rows else None
        self._built = (snap.seq, cols)
        return cols

    def __call__(self, lo: int, hi: int):
        cols = self._columns()
        if cols is None:
            return None
        t = np.asarray(cols["time"], np.int64)
        open_lo = self._open_lo()
        # flushed supersedes: a window below the CURRENT open span has
        # closed since the snapshot — its flushed rows own the answer
        floor = lo if open_lo is None else max(lo, open_lo)
        sel = (t >= floor) & (t < hi)
        if not sel.any():
            return None
        if sel.all():
            return cols
        return {k: np.asarray(v)[sel] for k, v in cols.items()}

    def epoch(self) -> int:
        return self.owner.snapshot_open().seq

    def open_from(self):
        of = self.owner.snapshot_open().open_from
        open_lo = self._open_lo()
        if of is None or open_lo is None:
            return of
        return max(of, open_lo)


def live_flow_source(
    owner, *, db: str = DEEPFLOW_SYSTEM_DB, table: str = DEEPFLOW_SYSTEM_TABLE,
    registry=None, row_builder=flow_window_rows,
):
    """Register an open-window flow source for (db, table); returns
    (provider, handle) — pass the handle to registry.unregister at
    teardown."""
    from ..querier.live import default_live_registry

    reg = default_live_registry if registry is None else registry
    provider = PipelineLiveSource(owner, row_builder)
    return provider, reg.register(db, table, provider)


def flow_window_sink(store, *, bus=None, lineage=None, **row_kw):
    """→ callable(windows) writing CLOSED windows' rows through the
    same `flow_window_rows` builder the live source uses — window
    close = insert = store epoch bump = result-cache invalidation.
    With `bus` set (ISSUE 11), one WindowClosed batch publishes AFTER
    the insert (on top of the store's own StoreMutation hook, if
    connected): standing queries re-evaluate once per sink call with
    the closed windows' times as the event clock. With `lineage` set
    (ISSUE 13), each inserted window's store.insert hop records and
    its VISIBILITY freshness lag anchors here — the row just became
    queryable."""
    ensure_system_table(store)

    def sink(windows) -> None:
        import contextlib

        rows = []
        for f in windows:
            rows.extend(flow_window_rows(f, **row_kw))
        # bus.batch(): the insert's StoreMutation (mutation hook) and
        # the data-timed WindowClosed events below coalesce into ONE
        # dispatch — one evaluation per sink call, at the data time
        with (bus.batch() if bus is not None else contextlib.nullcontext()):
            if rows:
                store.insert(
                    DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE,
                    sketch_rows_to_columns(rows),
                )
            if bus is not None and windows:
                from ..querier.events import docbatch_events

                evs = docbatch_events(
                    windows, db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE
                )
                if evs:
                    bus.publish(evs)
        if lineage is not None and windows:
            # AFTER the insert: the visibility lag is "row queryable",
            # not "sink called" (partial snapshots never insert here,
            # and must never masquerade as post-flush visibility)
            lineage.note_store_insert(
                [(getattr(f, "interval", 0) or lineage.interval,
                  f.window_idx)
                 for f in windows if not getattr(f, "partial", False)]
            )

    return sink


LIVE_METRIC_WINDOW_ROWS = "deepflow_window_rows"


def docbatch_window_sink(store, *, interval: int = 1,
                         metric: str = LIVE_METRIC_WINDOW_ROWS,
                         bus=None, lineage=None):
    """→ callable(outputs) for CLOSED windows that arrive as writer
    DocBatches (RollupPipeline.ingest / ShardedWindowManager.ingest /
    pop_tier_docbatches): one summary row per window lands in
    deepflow_system (time = window start, labels {window, tier}, value
    = row count) — the minimal "this window is queryable" insert.
    Outputs may be DocBatches or (tier_interval_s, DocBatch) pairs
    (the cascade shape). With `lineage` set (ISSUE 13) each window's
    store.insert hop + VISIBILITY freshness lag anchor AFTER the
    insert; with `bus` set one WindowClosed/TierClosed batch publishes
    after it (the r15 contract)."""
    import contextlib

    ensure_system_table(store)

    def sink(outputs) -> None:
        rows = []
        items = []
        events = []
        for o in outputs:
            iv, db = o if isinstance(o, tuple) else (interval, o)
            if db.timestamp.shape[0] == 0:
                continue
            w = int(db.timestamp[0]) // iv
            rows.append((w * iv, metric,
                         {"window": str(w), "tier": f"{iv}s"},
                         float(db.timestamp.shape[0])))
            items.append((iv, w))
            if bus is not None:
                from ..querier.events import TierClosed, WindowClosed

                events.append(
                    WindowClosed(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE,
                                 w * iv, iv)
                    if iv <= interval else
                    TierClosed(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE,
                               w * iv, iv)
                )
        if not rows:
            return
        with (bus.batch() if bus is not None else contextlib.nullcontext()):
            store.insert(
                DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE,
                sketch_rows_to_columns(rows),
            )
            if events and bus is not None:
                bus.publish(events)
        if lineage is not None:
            # AFTER the insert — visibility means "row queryable now"
            lineage.note_store_insert(items)

    return sink


class SystemLiveSource:
    """LiveRegistry provider pulling the CURRENT Countable counters
    (collector.sample — no sinks, no store writes) stamped at the
    query's upper time edge."""

    def __init__(self, collector=None):
        from ..utils.stats import default_collector

        self.collector = default_collector if collector is None else collector
        self._pulls = 0

    def __call__(self, lo: int, hi: int):
        # stamp at the query's upper edge, clamped into the u32 time
        # column's range — an unbounded SQL range passes hi = 2^62 and
        # an unclamped stamp would overflow the dtype (and silently
        # drop the whole overlay via the registry's containment)
        t = int(max(min(lo, 0xFFFFFFFF), min(hi - 1, 0xFFFFFFFF)))
        points = self.collector.sample(now=float(t))
        self._pulls += 1
        cols = points_to_system_columns(points)
        return cols if len(cols["time"]) else None

    def epoch(self) -> int:
        # counters move continuously — every pull is a new generation,
        # so cached entries over live counters never serve stale values
        return self._pulls


def live_system_source(collector=None, *, registry=None):
    """Register the self-telemetry live source on
    deepflow_system.deepflow_system; returns (provider, handle)."""
    from ..querier.live import default_live_registry

    reg = default_live_registry if registry is None else registry
    provider = SystemLiveSource(collector)
    return provider, reg.register(
        DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, provider
    )
