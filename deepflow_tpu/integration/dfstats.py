"""Self-telemetry loop closure: StatsCollector → dfstats wire frames.

The reference serializes every component's counters as InfluxDB points
and ships them into its own ext_metrics pipeline as `deepflow_stats`
(server/libs/stats/stats.go:89-202). `stats_sink(sender)` is that loop
for this framework: attach it to a StatsCollector and counter snapshots
flow over DFSTATS frames into the deepflow_stats tables, queryable with
the same SQL engine as everything else.
"""

from __future__ import annotations

from ..ingest.sender import UniformSender
from ..utils.stats import StatsPoint


def points_to_influx(points: list[StatsPoint]) -> str:
    lines = []
    for p in points:
        tags = "".join(
            f",{k}={str(v).replace(' ', '_').replace(',', '_')}" for k, v in p.tags
        )
        fields = ",".join(
            f"{k}={float(v)}" for k, v in p.fields.items() if isinstance(v, (int, float))
        )
        if not fields:
            continue
        lines.append(f"{p.module}{tags} {fields} {int(p.timestamp * 1e9)}")
    return "\n".join(lines)


def stats_sink(sender: UniformSender):
    """→ a sink callable for StatsCollector.add_sink."""

    def sink(points: list[StatsPoint]) -> None:
        if not points:
            return
        text = points_to_influx(points)
        if text:
            sender.send([text.encode()])

    return sink
