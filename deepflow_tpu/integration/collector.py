"""Agent-side integration collector — HTTP intake → wire frames.

The reference agent runs an HTTP server accepting OTLP, Prometheus
remote-write, Telegraf/Influx, and Pyroscope pushes, wraps each body
into a `Sendable` and forwards it to the server unchanged
(agent/src/integration_collector.rs:94-230 — the agent does NOT decode;
decode happens in the server's ingesters). Same here: a threading HTTP
server with one route per source, forwarding raw bodies through the
per-type UniformSenders.

Endpoints (reference paths, integration_collector.rs routes):
  POST /v1/traces                  → OPENTELEMETRY
  POST /api/v1/prom/write          → PROMETHEUS (identity/gzip only —
                                     snappy is unavailable in-image, 415)
  POST /influxdb/api/v2/write      → TELEGRAF
  POST /api/v1/profile             → PROFILE ("svc\\0type\\0ts\\n" + folded)
"""

from __future__ import annotations

import gzip
import io
import threading
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..ingest.framing import MessageType
from ..ingest.sender import UniformSender
from ..utils.stats import register_countable

_ROUTES = {
    "/v1/traces": MessageType.OPENTELEMETRY,
    "/api/v1/prom/write": MessageType.PROMETHEUS,
    "/influxdb/api/v2/write": MessageType.TELEGRAF,
    "/api/v1/profile": MessageType.PROFILE,
    # SkyWalking SegmentObject pb (agent OAP route) and Datadog JSON
    # traces (integration_collector.rs SkyWalking/Datadog routes)
    "/v3/segment": MessageType.SKYWALKING,
    "/v0.4/traces": MessageType.DATADOG,
}

# request-size guards (the reference bounds bodies via hyper defaults;
# the bind is configurable so a bomb must not exhaust memory)
MAX_BODY_BYTES = 32 << 20
MAX_DECODED_BYTES = 128 << 20


class IntegrationCollector:
    def __init__(
        self,
        servers: list[tuple[str, int]],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        agent_id: int = 1,
        organization_id: int = 1,
    ):
        self.senders = {
            mt: UniformSender(
                servers,
                mt,
                agent_id=agent_id,
                organization_id=organization_id,
                prefer_native_queue=False,
            )
            for mt in set(_ROUTES.values())
        }
        self.counters = {"requests": 0, "bad_requests": 0, "bytes_in": 0}
        collector = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                mt = _ROUTES.get(self.path.split("?", 1)[0])
                if mt is None:
                    collector.counters["bad_requests"] += 1
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    collector.counters["bad_requests"] += 1
                    self.send_error(400, "bad Content-Length")
                    return
                if length < 0:
                    collector.counters["bad_requests"] += 1
                    self.send_error(400, "bad Content-Length")
                    return
                if length > MAX_BODY_BYTES:
                    collector.counters["bad_requests"] += 1
                    self.send_error(413, "body too large")
                    return
                body = self.rfile.read(length)
                enc = (self.headers.get("Content-Encoding") or "identity").lower()
                if enc == "gzip":
                    try:
                        # bounded streaming decompress — a gzip bomb must not
                        # expand past MAX_DECODED_BYTES in memory
                        d = gzip.GzipFile(fileobj=io.BytesIO(body))
                        body = d.read(MAX_DECODED_BYTES + 1)
                        if len(body) > MAX_DECODED_BYTES:
                            collector.counters["bad_requests"] += 1
                            self.send_error(413, "decoded body too large")
                            return
                    except (OSError, EOFError, zlib.error):
                        # truncated → EOFError; corrupt deflate → zlib.error
                        collector.counters["bad_requests"] += 1
                        self.send_error(400, "bad gzip body")
                        return
                elif enc == "snappy":
                    collector.counters["bad_requests"] += 1
                    self.send_error(415, "snappy unsupported; use identity or gzip")
                    return
                collector.counters["requests"] += 1
                collector.counters["bytes_in"] += len(body)
                collector.senders[mt].send([bytes(body)])
                self.send_response(204 if mt == MessageType.PROMETHEUS else 200)
                self.end_headers()

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        register_countable("integration_collector", self)

    def get_counters(self):
        return dict(self.counters)

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        for s in self.senders.values():
            s.close()
