"""Third-party telemetry integration: agent-side HTTP intake
(integration_collector.rs seat) and the wire decoders shared with the
server-side ingesters (ext_metrics / prometheus / profile / OTel).
"""
