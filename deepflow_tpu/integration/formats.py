"""Wire-format decoders for integration telemetry.

Four formats, each hand-rolled and dependency-free like the rest of the
codec layer:

* InfluxDB line protocol (Telegraf) — `integration_collector.rs`
  forwards raw lines; the server's ext_metrics decoder parses them
  (server/ingester/ext_metrics/decoder.go).
* Prometheus remote-write WriteRequest protobuf (prometheus/decoder).
  Snappy framing is NOT implemented (no snappy in the image) — senders
  must use Content-Encoding: identity or gzip; the HTTP layer gates it.
* OTLP ExportTraceServiceRequest protobuf subset — enough of
  opentelemetry.proto.trace.v1 to build l7_flow_log span rows
  (flow_log/decoder.go:244 OTel path).
* Pyroscope "folded" stacks text (profile/decoder).
"""

from __future__ import annotations

import dataclasses

from ..ingest.codec import (
    _get_varint,
    _iter_fields,
    _put_varint,
    pb_bytes as _pb_bytes,
    pb_fixed64 as _pb_fixed64,
    pb_str as _pb_str,
    pb_varint as _pb_varint,
)

# ---------------------------------------------------------------------------
# InfluxDB line protocol


@dataclasses.dataclass
class InfluxPoint:
    measurement: str
    tags: dict[str, str]
    fields: dict[str, float]
    timestamp_ns: int  # 0 = unset


def _split_escaped(s: str, sep: str) -> list[str]:
    out, cur, esc = [], [], False
    for ch in s:
        if esc:
            cur.append(ch)
            esc = False
        elif ch == "\\":
            esc = True
        elif ch == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def parse_influx_lines(text: str) -> tuple[list[InfluxPoint], int]:
    """→ (points, error_count). One bad line never kills the batch."""
    points, errors = [], 0
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            points.append(_parse_influx_line(line))
        except Exception:
            errors += 1
    return points, errors


def _parse_influx_line(line: str) -> InfluxPoint:
    # measurement[,tag=v...] field=v[,field=v...] [timestamp]
    # split on unescaped spaces into ≤3 parts
    parts, cur, esc, quoted = [], [], False, False
    for ch in line:
        if esc:
            cur.append(ch)
            esc = False
        elif ch == "\\":
            cur.append(ch)
            esc = True
        elif ch == '"':
            quoted = not quoted
            cur.append(ch)
        elif ch == " " and not quoted and len(parts) < 2:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    if len(parts) < 2:
        raise ValueError("missing fields")
    head = _split_escaped(parts[0], ",")
    measurement = head[0]
    if not measurement:
        raise ValueError("empty measurement")
    tags = {}
    for t in head[1:]:
        k, _, v = t.partition("=")
        if k:
            tags[k] = v
    fields: dict[str, float] = {}
    for f in _split_escaped(parts[1], ","):
        k, _, v = f.partition("=")
        if not k or v == "":
            raise ValueError(f"bad field {f!r}")
        if v.startswith('"'):
            continue  # string fields are not numeric metrics
        if v.endswith(("i", "u")):
            fields[k] = float(int(v[:-1]))
        elif v in ("t", "T", "true", "True"):
            fields[k] = 1.0
        elif v in ("f", "F", "false", "False"):
            fields[k] = 0.0
        else:
            fields[k] = float(v)
    if not fields:
        raise ValueError("no numeric fields")
    ts = int(parts[2]) if len(parts) > 2 and parts[2] else 0
    return InfluxPoint(measurement, tags, fields, ts)


# ---------------------------------------------------------------------------
# packed dynamic-tag strings (the CK map-column stand-in): values may
# contain ',' '=' '\' — escape on pack, unescape on parse, one pair of
# functions shared by the ingesters and the PromQL evaluator


def pack_tags(tags: dict[str, str]) -> str:
    def esc(s: str) -> str:
        return s.replace("\\", "\\\\").replace(",", "\\,").replace("=", "\\=")

    return ",".join(f"{esc(k)}={esc(v)}" for k, v in sorted(tags.items()))


def unpack_tags(packed: str) -> dict[str, str]:
    out: dict[str, str] = {}
    key, cur, esc_on = None, [], False
    for ch in packed:
        if esc_on:
            cur.append(ch)
            esc_on = False
        elif ch == "\\":
            esc_on = True
        elif ch == "=" and key is None:
            key = "".join(cur)
            cur = []
        elif ch == ",":
            if key is not None:
                out[key] = "".join(cur)
            key, cur = None, []
        else:
            cur.append(ch)
    if key is not None:
        out[key] = "".join(cur)
    return out


# ---------------------------------------------------------------------------
# Prometheus remote-write protobuf (prompb.WriteRequest)


@dataclasses.dataclass
class PromSeries:
    labels: dict[str, str]  # includes __name__
    samples: list[tuple[int, float]]  # (timestamp_ms, value)


def parse_remote_write(body: bytes) -> list[PromSeries]:
    """prompb: WriteRequest{timeseries=1}; TimeSeries{labels=1 Label
    {name=1,value=2}, samples=2 Sample{value=1 double, timestamp=2}}."""
    import struct

    series = []
    for field, ts_bytes in _iter_fields(body):
        if field != 1 or not isinstance(ts_bytes, (bytes, bytearray)):
            continue
        labels: dict[str, str] = {}
        samples: list[tuple[int, float]] = []
        for f2, v2 in _iter_fields(bytes(ts_bytes)):
            if f2 == 1 and isinstance(v2, (bytes, bytearray)):
                name = value = ""
                for f3, v3 in _iter_fields(bytes(v2)):
                    if f3 == 1:
                        name = bytes(v3).decode(errors="replace")
                    elif f3 == 2:
                        value = bytes(v3).decode(errors="replace")
                if name:
                    labels[name] = value
            elif f2 == 2 and isinstance(v2, (bytes, bytearray)):
                val = 0.0
                ts = 0
                for f3, v3 in _iter_fields(bytes(v2)):
                    if f3 == 1:  # fixed64 double
                        val = struct.unpack("<d", int(v3).to_bytes(8, "little"))[0]
                    elif f3 == 2:
                        ts = _zigzag_free_i64(v3)
                samples.append((ts, val))
        if labels:
            series.append(PromSeries(labels, samples))
    return series


def _zigzag_free_i64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def encode_remote_write(series: list[PromSeries]) -> bytes:
    """Test/SDK-side encoder for the same subset."""
    import struct

    from ..ingest.codec import _put_varint

    out = bytearray()
    for s in series:
        ts_buf = bytearray()
        for name, value in s.labels.items():
            lb = bytearray()
            _put_varint(lb, 1 << 3 | 2)
            _put_varint(lb, len(name.encode()))
            lb += name.encode()
            _put_varint(lb, 2 << 3 | 2)
            _put_varint(lb, len(value.encode()))
            lb += value.encode()
            _put_varint(ts_buf, 1 << 3 | 2)
            _put_varint(ts_buf, len(lb))
            ts_buf += lb
        for ts, val in s.samples:
            sb = bytearray()
            _put_varint(sb, 1 << 3 | 1)  # fixed64
            sb += struct.pack("<d", val)
            _put_varint(sb, 2 << 3 | 0)
            _put_varint(sb, ts & ((1 << 64) - 1))
            _put_varint(ts_buf, 2 << 3 | 2)
            _put_varint(ts_buf, len(sb))
            ts_buf += sb
        _put_varint(out, 1 << 3 | 2)
        _put_varint(out, len(ts_buf))
        out += ts_buf
    return bytes(out)


# ---------------------------------------------------------------------------
# OTLP trace protobuf subset


@dataclasses.dataclass
class OtelSpan:
    service: str
    name: str
    trace_id: str
    span_id: str
    parent_span_id: str
    kind: int  # 2=server, 3=client
    start_us: int
    end_us: int
    status_code: int  # 0 unset, 1 ok, 2 error
    attributes: dict[str, str]


def _any_value(buf: bytes) -> str:
    for f, v in _iter_fields(buf):
        if f == 1:
            return bytes(v).decode(errors="replace")
        if f == 2:
            return "true" if v else "false"
        if f == 3:
            return str(_zigzag_free_i64(v))
        if f == 4:
            import struct

            return str(struct.unpack("<d", int(v).to_bytes(8, "little"))[0])
    return ""


def _attributes(buf_list: list[bytes]) -> dict[str, str]:
    out = {}
    for kv in buf_list:
        key, val = "", ""
        try:
            for f, v in _iter_fields(kv):
                if f == 1:
                    key = bytes(v).decode(errors="replace")
                elif f == 2:
                    val = _any_value(bytes(v))
        except Exception:
            continue
        if key:
            out[key] = val
    return out


def parse_otlp_traces(body: bytes) -> list[OtelSpan]:
    """Malformed sub-messages are skipped, never raised — ingest frames
    are untrusted."""
    spans: list[OtelSpan] = []
    try:
        resource_spans = [bytes(v) for f, v in _iter_fields(body) if f == 1]
    except Exception:
        return spans
    for rs in resource_spans:
        service = ""
        scope_spans = []
        try:
            for f2, v2 in _iter_fields(rs):
                if f2 == 1:  # resource
                    attrs = [bytes(v3) for f3, v3 in _iter_fields(bytes(v2)) if f3 == 1]
                    service = _attributes(attrs).get("service.name", "")
                elif f2 == 2:
                    scope_spans.append(bytes(v2))
        except Exception:
            continue
        for ss in scope_spans:
            try:
                span_bufs = [bytes(v) for f, v in _iter_fields(ss) if f == 2]
            except Exception:
                continue
            for sb in span_bufs:
                s = _parse_span(service, sb)
                if s is not None:
                    spans.append(s)
    return spans


def _parse_span(service: str, buf: bytes) -> OtelSpan | None:
    s = OtelSpan(service, "", "", "", "", 0, 0, 0, 0, {})
    attrs = []
    try:
        for f3, v3 in _iter_fields(buf):
            if f3 == 1:
                s.trace_id = bytes(v3).hex()
            elif f3 == 2:
                s.span_id = bytes(v3).hex()
            elif f3 == 4:
                s.parent_span_id = bytes(v3).hex()
            elif f3 == 5:
                s.name = bytes(v3).decode(errors="replace")
            elif f3 == 6:
                s.kind = int(v3)
            elif f3 == 7:
                s.start_us = int(v3) // 1000
            elif f3 == 8:
                s.end_us = int(v3) // 1000
            elif f3 == 9:
                attrs.append(bytes(v3))
            elif f3 == 15:
                # Status: field 2 is `message` (string), field 3 is `code`
                # (opentelemetry/proto/trace/v1/trace.proto Status)
                for f4, v4 in _iter_fields(bytes(v3)):
                    if f4 == 3:
                        s.status_code = int(v4)
        s.attributes = _attributes(attrs)
        return s
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Pyroscope folded stacks


@dataclasses.dataclass
class ProfileSample:
    stack: str  # "a;b;c"
    value: int


def parse_folded(text: str) -> tuple[list[ProfileSample], int]:
    out, errors = [], 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, v = line.rpartition(" ")
        try:
            out.append(ProfileSample(stack, int(v)))
        except ValueError:
            errors += 1
    return out, errors


# ---------------------------------------------------------------------------
# OTLP encoders — the export half of the subsets parsed above
# (exporters/otlp_exporter/otlp_exporter.go builds the same messages via
# the generated SDK; here the encoder is the byte-level inverse of
# parse_otlp_traces / parse_otlp_metrics so round-trips are testable).


def _kv_str(key: str, value: str) -> bytes:
    av = bytearray()
    _pb_str(av, 1, value)  # AnyValue.string_value
    kv = bytearray()
    _pb_str(kv, 1, key)
    _pb_bytes(kv, 2, bytes(av))
    return bytes(kv)


def _resource_block(service: str) -> bytes:
    res = bytearray()
    _pb_bytes(res, 1, _kv_str("service.name", service))  # Resource.attributes
    return bytes(res)


def _hex_bytes(s: str) -> bytes:
    s = (s or "").strip()
    if len(s) % 2:
        s = "0" + s
    try:
        return bytes.fromhex(s)
    except ValueError:
        return b""


def encode_otlp_traces(spans: list[OtelSpan]) -> bytes:
    """OtelSpan rows → ExportTraceServiceRequest bytes (grouped by
    service into one ResourceSpans each)."""
    by_service: dict[str, list[OtelSpan]] = {}
    for s in spans:
        by_service.setdefault(s.service, []).append(s)
    out = bytearray()
    for service, group in by_service.items():
        ss = bytearray()  # ScopeSpans
        for s in group:
            sp = bytearray()
            _pb_bytes(sp, 1, _hex_bytes(s.trace_id))
            _pb_bytes(sp, 2, _hex_bytes(s.span_id))
            if s.parent_span_id:
                _pb_bytes(sp, 4, _hex_bytes(s.parent_span_id))
            _pb_str(sp, 5, s.name)
            if s.kind:
                _pb_varint(sp, 6, s.kind)
            _pb_fixed64(sp, 7, s.start_us * 1000)
            _pb_fixed64(sp, 8, s.end_us * 1000)
            for k, v in s.attributes.items():
                _pb_bytes(sp, 9, _kv_str(k, str(v)))
            if s.status_code:
                st = bytearray()
                _pb_varint(st, 3, s.status_code)
                _pb_bytes(sp, 15, bytes(st))
            _pb_bytes(ss, 2, bytes(sp))  # ScopeSpans.spans
        rs = bytearray()
        _pb_bytes(rs, 1, _resource_block(service))
        _pb_bytes(rs, 2, bytes(ss))  # ResourceSpans.scope_spans
        out2 = bytearray()
        _pb_bytes(out2, 1, bytes(rs))
        out += out2
    return bytes(out)


@dataclasses.dataclass
class OtlpMetricPoint:
    attributes: dict[str, str]
    time_ns: int
    value: float


@dataclasses.dataclass
class OtlpMetric:
    service: str
    name: str
    unit: str
    monotonic: bool  # True → Sum (cumulative counter), False → Gauge
    points: list[OtlpMetricPoint]


def encode_otlp_metrics(metrics: list[OtlpMetric]) -> bytes:
    """OtlpMetric rows → ExportMetricsServiceRequest bytes
    (opentelemetry.proto.metrics.v1: ResourceMetrics{resource,
    scope_metrics{metrics{name, unit, sum|gauge{data_points}}}})."""
    import struct

    by_service: dict[str, list[OtlpMetric]] = {}
    for m in metrics:
        by_service.setdefault(m.service, []).append(m)
    out = bytearray()
    for service, group in by_service.items():
        sm = bytearray()  # ScopeMetrics
        for m in group:
            mb = bytearray()
            _pb_str(mb, 1, m.name)
            if m.unit:
                _pb_str(mb, 3, m.unit)
            dps = bytearray()
            for p in m.points:
                dp = bytearray()
                for k, v in p.attributes.items():
                    _pb_bytes(dp, 7, _kv_str(k, str(v)))  # NumberDataPoint.attributes
                _pb_fixed64(dp, 3, p.time_ns)  # time_unix_nano
                _put_varint(dp, 4 << 3 | 1)  # as_double fixed64
                dp += struct.pack("<d", p.value)
                _pb_bytes(dps, 1, bytes(dp))
            if m.monotonic:
                _pb_varint(dps, 2, 2)  # AGGREGATION_TEMPORALITY_CUMULATIVE
                _pb_varint(dps, 3, 1)  # is_monotonic
                _pb_bytes(mb, 7, bytes(dps))  # Metric.sum
            else:
                _pb_bytes(mb, 5, bytes(dps))  # Metric.gauge
            _pb_bytes(sm, 2, bytes(mb))  # ScopeMetrics.metrics
        rm = bytearray()
        _pb_bytes(rm, 1, _resource_block(service))
        _pb_bytes(rm, 2, bytes(sm))
        out2 = bytearray()
        _pb_bytes(out2, 1, bytes(rm))
        out += out2
    return bytes(out)


def parse_otlp_metrics(body: bytes) -> list[OtlpMetric]:
    """Inverse subset of encode_otlp_metrics (round-trip pin + any
    future OTLP-metrics intake)."""
    import struct

    out: list[OtlpMetric] = []
    try:
        rms = [bytes(v) for f, v in _iter_fields(body) if f == 1]
    except Exception:
        return out
    for rm in rms:
        service = ""
        sms = []
        try:
            for f2, v2 in _iter_fields(rm):
                if f2 == 1:
                    attrs = [bytes(v3) for f3, v3 in _iter_fields(bytes(v2)) if f3 == 1]
                    service = _attributes(attrs).get("service.name", "")
                elif f2 == 2:
                    sms.append(bytes(v2))
        except Exception:
            continue
        for sm in sms:
            try:
                metric_bufs = [bytes(v) for f, v in _iter_fields(sm) if f == 2]
            except Exception:
                continue
            for mb in metric_bufs:
                name = unit = ""
                monotonic = False
                dp_parent = None
                try:
                    for f3, v3 in _iter_fields(mb):
                        if f3 == 1:
                            name = bytes(v3).decode(errors="replace")
                        elif f3 == 3:
                            unit = bytes(v3).decode(errors="replace")
                        elif f3 == 5:
                            dp_parent = bytes(v3)
                        elif f3 == 7:
                            dp_parent = bytes(v3)
                            monotonic = True
                except Exception:
                    continue
                points = []
                if dp_parent is not None:
                    try:
                        for f4, v4 in _iter_fields(dp_parent):
                            if f4 != 1:
                                continue
                            attrs, t_ns, val = [], 0, 0.0
                            for f5, v5 in _iter_fields(bytes(v4)):
                                if f5 == 7:
                                    attrs.append(bytes(v5))
                                elif f5 == 3:
                                    t_ns = int(v5)
                                elif f5 == 4:
                                    val = struct.unpack(
                                        "<d", int(v5).to_bytes(8, "little")
                                    )[0]
                            points.append(
                                OtlpMetricPoint(_attributes(attrs), t_ns, val)
                            )
                    except Exception:
                        pass
                out.append(OtlpMetric(service, name, unit, monotonic, points))
    return out
