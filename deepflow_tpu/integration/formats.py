"""Wire-format decoders for integration telemetry.

Four formats, each hand-rolled and dependency-free like the rest of the
codec layer:

* InfluxDB line protocol (Telegraf) — `integration_collector.rs`
  forwards raw lines; the server's ext_metrics decoder parses them
  (server/ingester/ext_metrics/decoder.go).
* Prometheus remote-write WriteRequest protobuf (prometheus/decoder).
  Snappy framing is NOT implemented (no snappy in the image) — senders
  must use Content-Encoding: identity or gzip; the HTTP layer gates it.
* OTLP ExportTraceServiceRequest protobuf subset — enough of
  opentelemetry.proto.trace.v1 to build l7_flow_log span rows
  (flow_log/decoder.go:244 OTel path).
* Pyroscope "folded" stacks text (profile/decoder).
"""

from __future__ import annotations

import dataclasses

from ..ingest.codec import _get_varint, _iter_fields

# ---------------------------------------------------------------------------
# InfluxDB line protocol


@dataclasses.dataclass
class InfluxPoint:
    measurement: str
    tags: dict[str, str]
    fields: dict[str, float]
    timestamp_ns: int  # 0 = unset


def _split_escaped(s: str, sep: str) -> list[str]:
    out, cur, esc = [], [], False
    for ch in s:
        if esc:
            cur.append(ch)
            esc = False
        elif ch == "\\":
            esc = True
        elif ch == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def parse_influx_lines(text: str) -> tuple[list[InfluxPoint], int]:
    """→ (points, error_count). One bad line never kills the batch."""
    points, errors = [], 0
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            points.append(_parse_influx_line(line))
        except Exception:
            errors += 1
    return points, errors


def _parse_influx_line(line: str) -> InfluxPoint:
    # measurement[,tag=v...] field=v[,field=v...] [timestamp]
    # split on unescaped spaces into ≤3 parts
    parts, cur, esc, quoted = [], [], False, False
    for ch in line:
        if esc:
            cur.append(ch)
            esc = False
        elif ch == "\\":
            cur.append(ch)
            esc = True
        elif ch == '"':
            quoted = not quoted
            cur.append(ch)
        elif ch == " " and not quoted and len(parts) < 2:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    if len(parts) < 2:
        raise ValueError("missing fields")
    head = _split_escaped(parts[0], ",")
    measurement = head[0]
    if not measurement:
        raise ValueError("empty measurement")
    tags = {}
    for t in head[1:]:
        k, _, v = t.partition("=")
        if k:
            tags[k] = v
    fields: dict[str, float] = {}
    for f in _split_escaped(parts[1], ","):
        k, _, v = f.partition("=")
        if not k or v == "":
            raise ValueError(f"bad field {f!r}")
        if v.startswith('"'):
            continue  # string fields are not numeric metrics
        if v.endswith(("i", "u")):
            fields[k] = float(int(v[:-1]))
        elif v in ("t", "T", "true", "True"):
            fields[k] = 1.0
        elif v in ("f", "F", "false", "False"):
            fields[k] = 0.0
        else:
            fields[k] = float(v)
    if not fields:
        raise ValueError("no numeric fields")
    ts = int(parts[2]) if len(parts) > 2 and parts[2] else 0
    return InfluxPoint(measurement, tags, fields, ts)


# ---------------------------------------------------------------------------
# packed dynamic-tag strings (the CK map-column stand-in): values may
# contain ',' '=' '\' — escape on pack, unescape on parse, one pair of
# functions shared by the ingesters and the PromQL evaluator


def pack_tags(tags: dict[str, str]) -> str:
    def esc(s: str) -> str:
        return s.replace("\\", "\\\\").replace(",", "\\,").replace("=", "\\=")

    return ",".join(f"{esc(k)}={esc(v)}" for k, v in sorted(tags.items()))


def unpack_tags(packed: str) -> dict[str, str]:
    out: dict[str, str] = {}
    key, cur, esc_on = None, [], False
    for ch in packed:
        if esc_on:
            cur.append(ch)
            esc_on = False
        elif ch == "\\":
            esc_on = True
        elif ch == "=" and key is None:
            key = "".join(cur)
            cur = []
        elif ch == ",":
            if key is not None:
                out[key] = "".join(cur)
            key, cur = None, []
        else:
            cur.append(ch)
    if key is not None:
        out[key] = "".join(cur)
    return out


# ---------------------------------------------------------------------------
# Prometheus remote-write protobuf (prompb.WriteRequest)


@dataclasses.dataclass
class PromSeries:
    labels: dict[str, str]  # includes __name__
    samples: list[tuple[int, float]]  # (timestamp_ms, value)


def parse_remote_write(body: bytes) -> list[PromSeries]:
    """prompb: WriteRequest{timeseries=1}; TimeSeries{labels=1 Label
    {name=1,value=2}, samples=2 Sample{value=1 double, timestamp=2}}."""
    import struct

    series = []
    for field, ts_bytes in _iter_fields(body):
        if field != 1 or not isinstance(ts_bytes, (bytes, bytearray)):
            continue
        labels: dict[str, str] = {}
        samples: list[tuple[int, float]] = []
        for f2, v2 in _iter_fields(bytes(ts_bytes)):
            if f2 == 1 and isinstance(v2, (bytes, bytearray)):
                name = value = ""
                for f3, v3 in _iter_fields(bytes(v2)):
                    if f3 == 1:
                        name = bytes(v3).decode(errors="replace")
                    elif f3 == 2:
                        value = bytes(v3).decode(errors="replace")
                if name:
                    labels[name] = value
            elif f2 == 2 and isinstance(v2, (bytes, bytearray)):
                val = 0.0
                ts = 0
                for f3, v3 in _iter_fields(bytes(v2)):
                    if f3 == 1:  # fixed64 double
                        val = struct.unpack("<d", int(v3).to_bytes(8, "little"))[0]
                    elif f3 == 2:
                        ts = _zigzag_free_i64(v3)
                samples.append((ts, val))
        if labels:
            series.append(PromSeries(labels, samples))
    return series


def _zigzag_free_i64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def encode_remote_write(series: list[PromSeries]) -> bytes:
    """Test/SDK-side encoder for the same subset."""
    import struct

    from ..ingest.codec import _put_varint

    out = bytearray()
    for s in series:
        ts_buf = bytearray()
        for name, value in s.labels.items():
            lb = bytearray()
            _put_varint(lb, 1 << 3 | 2)
            _put_varint(lb, len(name.encode()))
            lb += name.encode()
            _put_varint(lb, 2 << 3 | 2)
            _put_varint(lb, len(value.encode()))
            lb += value.encode()
            _put_varint(ts_buf, 1 << 3 | 2)
            _put_varint(ts_buf, len(lb))
            ts_buf += lb
        for ts, val in s.samples:
            sb = bytearray()
            _put_varint(sb, 1 << 3 | 1)  # fixed64
            sb += struct.pack("<d", val)
            _put_varint(sb, 2 << 3 | 0)
            _put_varint(sb, ts & ((1 << 64) - 1))
            _put_varint(ts_buf, 2 << 3 | 2)
            _put_varint(ts_buf, len(sb))
            ts_buf += sb
        _put_varint(out, 1 << 3 | 2)
        _put_varint(out, len(ts_buf))
        out += ts_buf
    return bytes(out)


# ---------------------------------------------------------------------------
# OTLP trace protobuf subset


@dataclasses.dataclass
class OtelSpan:
    service: str
    name: str
    trace_id: str
    span_id: str
    parent_span_id: str
    kind: int  # 2=server, 3=client
    start_us: int
    end_us: int
    status_code: int  # 0 unset, 1 ok, 2 error
    attributes: dict[str, str]


def _any_value(buf: bytes) -> str:
    for f, v in _iter_fields(buf):
        if f == 1:
            return bytes(v).decode(errors="replace")
        if f == 2:
            return "true" if v else "false"
        if f == 3:
            return str(_zigzag_free_i64(v))
        if f == 4:
            import struct

            return str(struct.unpack("<d", int(v).to_bytes(8, "little"))[0])
    return ""


def _attributes(buf_list: list[bytes]) -> dict[str, str]:
    out = {}
    for kv in buf_list:
        key, val = "", ""
        try:
            for f, v in _iter_fields(kv):
                if f == 1:
                    key = bytes(v).decode(errors="replace")
                elif f == 2:
                    val = _any_value(bytes(v))
        except Exception:
            continue
        if key:
            out[key] = val
    return out


def parse_otlp_traces(body: bytes) -> list[OtelSpan]:
    """Malformed sub-messages are skipped, never raised — ingest frames
    are untrusted."""
    spans: list[OtelSpan] = []
    try:
        resource_spans = [bytes(v) for f, v in _iter_fields(body) if f == 1]
    except Exception:
        return spans
    for rs in resource_spans:
        service = ""
        scope_spans = []
        try:
            for f2, v2 in _iter_fields(rs):
                if f2 == 1:  # resource
                    attrs = [bytes(v3) for f3, v3 in _iter_fields(bytes(v2)) if f3 == 1]
                    service = _attributes(attrs).get("service.name", "")
                elif f2 == 2:
                    scope_spans.append(bytes(v2))
        except Exception:
            continue
        for ss in scope_spans:
            try:
                span_bufs = [bytes(v) for f, v in _iter_fields(ss) if f == 2]
            except Exception:
                continue
            for sb in span_bufs:
                s = _parse_span(service, sb)
                if s is not None:
                    spans.append(s)
    return spans


def _parse_span(service: str, buf: bytes) -> OtelSpan | None:
    s = OtelSpan(service, "", "", "", "", 0, 0, 0, 0, {})
    attrs = []
    try:
        for f3, v3 in _iter_fields(buf):
            if f3 == 1:
                s.trace_id = bytes(v3).hex()
            elif f3 == 2:
                s.span_id = bytes(v3).hex()
            elif f3 == 4:
                s.parent_span_id = bytes(v3).hex()
            elif f3 == 5:
                s.name = bytes(v3).decode(errors="replace")
            elif f3 == 6:
                s.kind = int(v3)
            elif f3 == 7:
                s.start_us = int(v3) // 1000
            elif f3 == 8:
                s.end_us = int(v3) // 1000
            elif f3 == 9:
                attrs.append(bytes(v3))
            elif f3 == 15:
                # Status: field 2 is `message` (string), field 3 is `code`
                # (opentelemetry/proto/trace/v1/trace.proto Status)
                for f4, v4 in _iter_fields(bytes(v3)):
                    if f4 == 3:
                        s.status_code = int(v4)
        s.attributes = _attributes(attrs)
        return s
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Pyroscope folded stacks


@dataclasses.dataclass
class ProfileSample:
    stack: str  # "a;b;c"
    value: int


def parse_folded(text: str) -> tuple[list[ProfileSample], int]:
    out, errors = [], 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, v = line.rpartition(" ")
        try:
            out.append(ProfileSample(stack, int(v)))
        except ValueError:
            errors += 1
    return out, errors
