"""DFPUSH frame — the wire delivery plane's cross-host push unit.

The fleet plane (r21) ships telemetry SUMMARIES host → aggregator on
DFSTATS; this lane ships *query results and alert notifications* the
same way — host-local subscription evaluations pushed upstream so ONE
eval per host per event batch can fan out to N wire clients on the
aggregator, instead of N clients each pulling every host.

One frame = one control or data message, compact JSON over the
existing framed-TCP ABI (`ingest/framing.py`, 19-byte flow header,
deflate/zstd body) with `msg_type = DFPUSH` (21 — this build's
extension of the reference registry, which ends at DATADOG=20). The
lane is DUPLEX over one dialed socket, unlike the one-way DFSTATS
lane: the router sends control frames down the same connection the
host pushes results up.

Frame kinds:

  * `hello`  — host → router on (re)connect: names the host; the
    router answers by (re)sending one `sub` per active distinct query,
    so reconnect resumes the subscription set with no host-side state.
  * `sub`    — router → host: subscribe this normalized query spec
    (`body` = spec dict); `query_id` is the router-assigned identity
    every later frame carries.
  * `unsub`  — router → host: the last wire watcher for the query is
    gone; drop the host-local subscription.
  * `result` — host → router: one subscription evaluation. `seq` is a
    per-(host, query) monotone counter — delivery is at-least-once
    across reconnects (the publisher retains the unacked frame,
    HandoffSender stance), so the router dedups on `(host, query_id,
    seq)`. `body` = {"now", "partial", "series"}.
  * `alert`  — host → router: one alert-engine notification dict; the
    router fans it to every `alerts=1` wire watcher.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

from ..ingest.framing import (
    FlowHeader,
    MessageType,
    best_encoder,
    decompress_body,
    encode_frame,
    split_messages,
)

#: the push lane's message type — see MessageType.DFPUSH in framing.py
PUSH_MSG_TYPE = MessageType.DFPUSH

PUSH_FRAME_VERSION = 1

PUSH_KINDS = ("hello", "sub", "unsub", "result", "alert")


@dataclasses.dataclass(frozen=True)
class PushFrame:
    """One DFPUSH message (decoded form)."""

    kind: str  # one of PUSH_KINDS
    host: str = ""  # sending host (hello/result/alert)
    query_id: str = ""  # router-assigned query identity (sub/unsub/result)
    seq: int = 0  # per-(host, query) result sequence (result)
    body: dict = dataclasses.field(default_factory=dict)


def normalize_query_spec(spec: dict) -> tuple:
    """Canonical dedup key for a wire query spec: whitespace-collapsed
    query text + the evaluation parameters that change the answer.
    "ONE upstream subscription per distinct query fleet-wide" rides on
    this — `rate(x[1m])` and ` rate(x[1m]) ` are the same question."""
    kind = str(spec.get("kind", "promql"))
    if kind not in ("promql", "sql"):
        raise ValueError(f"unknown wire query kind {kind!r}")
    query = " ".join(str(spec.get("query", "")).split())
    if not query:
        raise ValueError("wire query spec has no query text")
    return (
        kind,
        query,
        str(spec.get("db", "deepflow_system")),
        str(spec.get("table", "deepflow_system")),
        int(spec.get("span_s", 60)),
        int(spec.get("step", 1)),
        int(spec.get("lookback_s", 300)),
    )


def query_id_for(key: tuple) -> str:
    """Stable short id for a normalized spec key — the wire name every
    sub/result frame carries (content-derived, so two routers agree)."""
    digest = hashlib.sha1(json.dumps(list(key)).encode()).hexdigest()
    return "q" + digest[:12]


def spec_from_key(key: tuple) -> dict:
    """Inverse of normalize_query_spec — the dict shipped in `sub`."""
    kind, query, db, table, span_s, step, lookback_s = key
    return {
        "kind": kind, "query": query, "db": db, "table": table,
        "span_s": span_s, "step": step, "lookback_s": lookback_s,
    }


def encode_push_frame(frame: PushFrame, *, agent_id: int = 0,
                      encoder: int | None = None) -> bytes:
    """PushFrame → one wire frame (header + compressed JSON body)."""
    if frame.kind not in PUSH_KINDS:
        raise ValueError(f"unknown push frame kind {frame.kind!r}")
    body = json.dumps(
        {
            "v": PUSH_FRAME_VERSION,
            "kind": frame.kind,
            "host": frame.host,
            "qid": frame.query_id,
            "seq": int(frame.seq),
            "body": frame.body,
        },
        separators=(",", ":"),
    ).encode()
    enc = best_encoder() if encoder is None else encoder
    return encode_frame(
        FlowHeader(msg_type=int(PUSH_MSG_TYPE), agent_id=agent_id),
        [body], encoder=enc,
    )


def decode_push_frame(header: FlowHeader, body: bytes) -> PushFrame:
    """(header, body) from a FrameReassembler → PushFrame. Raises
    ValueError on a wrong message type or version — both ends count
    these as decode errors, never silently skip."""
    if header.msg_type != int(PUSH_MSG_TYPE):
        raise ValueError(f"not a push frame: msg_type={header.msg_type}")
    (msg,) = split_messages(decompress_body(body, header.encoder))
    obj = json.loads(msg)
    if obj.get("v") != PUSH_FRAME_VERSION:
        raise ValueError(f"unknown push frame version {obj.get('v')!r}")
    kind = str(obj.get("kind", ""))
    if kind not in PUSH_KINDS:
        raise ValueError(f"unknown push frame kind {kind!r}")
    return PushFrame(
        kind=kind,
        host=str(obj.get("host", "")),
        query_id=str(obj.get("qid", "")),
        seq=int(obj.get("seq", 0)),
        body=dict(obj.get("body", {})),
    )


__all__ = [
    "PUSH_MSG_TYPE",
    "PUSH_FRAME_VERSION",
    "PUSH_KINDS",
    "PushFrame",
    "normalize_query_spec",
    "query_id_for",
    "spec_from_key",
    "encode_push_frame",
    "decode_push_frame",
]
