"""FleetSubscriptionRouter — ONE upstream eval per query, N wire clients.

The aggregator-side half of the cross-host push fan-out (ISSUE 19).
Pipeline hosts dial in (`publisher.WirePublisher`), say `hello`, and
the router subscribes each of them to every distinct query any wire
client is watching — exactly ONE `sub` per normalized query per host,
no matter how many clients watch it. Hosts push `result` frames (one
per local subscription eval, i.e. one per event batch) and the router
merges the per-host rows and fans the merged envelope out to N bounded
watcher queues. Fan-out cost is O(evals), never O(watchers × hosts).

Semantics:

  * **Dedup by normalized query** — `frame.normalize_query_spec`;
    the first watcher creates the entry (and the upstream subs), the
    last watcher's departure tears both down (`unsub` broadcast, no
    orphaned upstream evals).
  * **At-least-once upstream** — the publisher retains the in-flight
    frame across reconnects (HandoffSender stance), so the router
    dedups redelivery on `(host, query_id, seq)` (counted
    `dup_results`).
  * **Flushed supersedes partial** — a host's PARTIAL result for data
    time `now` never replaces a flushed result it already delivered
    for the same `now` (counted `partial_superseded`, no fan-out: the
    merged view did not move).
  * **Counted staleness** — a host connection dropping marks that
    host's rows stale in every entry and delivers a staleness notice
    to every watcher (counted, never silent); `hello` from the same
    host recovers it and re-sends the active subscription set, so
    reconnect resumes with no host-side bookkeeping.

Watchers are the EXISTING `querier.subscribe.Watcher` bounded queues —
same drop/lease/reap machinery as the local push plane; `reap()` here
covers router watchers the way `SubscriptionManager.reap()` covers
local ones. Countable face: `tpu_wire_router`.
"""

from __future__ import annotations

import socket
import threading
import time

from ..ingest.framing import FrameReassembler
from ..querier.subscribe import DEFAULT_WATCHER_QUEUE, Watcher
from ..utils.stats import register_countable
from .frame import (
    PushFrame,
    decode_push_frame,
    encode_push_frame,
    normalize_query_spec,
    query_id_for,
    spec_from_key,
)


class _RouterEntry:
    """One distinct query fleet-wide: its wire watchers + per-host
    latest-result state. `hosts[h]` = {"seq", "now", "partial",
    "series", "stale"}."""

    __slots__ = ("key", "query_id", "spec", "watchers", "hosts",
                 "merged_seq", "upstream_results", "deliveries", "drops",
                 "dup_results", "partial_superseded")

    def __init__(self, key: tuple):
        self.key = key
        self.query_id = query_id_for(key)
        self.spec = spec_from_key(key)
        self.watchers: list[Watcher] = []
        self.hosts: dict[str, dict] = {}
        self.merged_seq = 0
        self.upstream_results = 0
        self.deliveries = 0
        self.drops = 0
        self.dup_results = 0
        self.partial_superseded = 0


class _HostConn:
    __slots__ = ("host", "sock", "wlock", "connected", "last_seen",
                 "results", "hellos")

    def __init__(self, host: str, sock):
        self.host = host
        self.sock = sock
        self.wlock = threading.Lock()
        self.connected = True
        self.last_seen = 0.0
        self.results = 0
        self.hellos = 0


class FleetSubscriptionRouter:
    """TCP listener for WirePublisher uplinks + the fleet-wide
    subscription table. Start with `.start()`; wire clients attach via
    `watch(spec)` (usually through `hub.WireHub`)."""

    def __init__(self, *, host: str = "127.0.0.1", port: int = 0,
                 clock=time.time, name: str = "router"):
        self.host = host
        self.port = port
        self.name = name
        self._clock = clock
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._running = False
        self._lock = threading.Lock()  # entries/hosts maps + counters
        # serializes fan-out and watcher-list mutation (the
        # SubscriptionManager._eval_lock stance: an unguarded
        # check-then-remove pair double-reaps under concurrency)
        self._fan_lock = threading.RLock()
        self._entries: dict[tuple, _RouterEntry] = {}
        self._by_qid: dict[str, _RouterEntry] = {}
        self._hosts: dict[str, _HostConn] = {}
        self._alert_cbs: list = []
        self.counters = {
            "connections": 0,
            "hellos": 0,
            "frames_rx": 0,
            "decode_errors": 0,
            "results_rx": 0,
            "dup_results": 0,
            "unknown_results": 0,
            "partial_superseded": 0,
            "merged_evals": 0,
            "deliveries": 0,
            "drops": 0,
            "alerts_rx": 0,
            "alert_cb_errors": 0,
            "upstream_subs": 0,
            "upstream_unsubs": 0,
            "control_tx": 0,
            "control_errors": 0,
            "hosts_lost": 0,
            "hosts_recovered": 0,
            "staleness_notices": 0,
            "watchers_reaped": 0,
        }
        self._stats_src = register_countable("tpu_wire_router", self, name=name)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "FleetSubscriptionRouter":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(32)
        s.settimeout(0.5)
        self._sock = s
        self.port = s.getsockname()[1]
        self._running = True
        t = threading.Thread(target=self._accept_loop,
                             name=f"wire-router-{self.name}", daemon=True)
        t.start()
        self._threads.append(t)
        return self

    @property
    def endpoint(self) -> tuple[str, int]:
        return (self.host, self.port)

    def stop(self) -> None:
        self._running = False
        with self._lock:
            conns = list(self._hosts.values())
        for hc in conns:
            try:
                hc.sock.close()
            except OSError:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    # -- wire client face (the hub calls these) --------------------------
    def watch(self, spec: dict, *, maxlen: int = DEFAULT_WATCHER_QUEUE,
              lease_s: float | None = None) -> tuple[_RouterEntry, Watcher]:
        """Attach one wire watcher to the (deduped) entry for `spec`;
        the FIRST watcher for a distinct query broadcasts the upstream
        `sub` — later ones just join the fan-out."""
        key = normalize_query_spec(spec)
        broadcast = None
        with self._fan_lock:
            with self._lock:
                entry = self._entries.get(key)
                if entry is None:
                    entry = _RouterEntry(key)
                    self._entries[key] = entry
                    self._by_qid[entry.query_id] = entry
                    self.counters["upstream_subs"] += 1
                    broadcast = entry
            w = Watcher(None, maxlen=maxlen, lease_s=lease_s)
            entry.watchers.append(w)
        if broadcast is not None:
            self._broadcast_sub(broadcast)
        return entry, w

    def unwatch(self, entry: _RouterEntry, watcher: Watcher) -> None:
        """Detach one watcher; the LAST one tears the entry down and
        unsubscribes the fleet (no orphaned upstream evals)."""
        teardown = False
        with self._fan_lock:
            if watcher in entry.watchers:
                entry.watchers.remove(watcher)
            if not entry.watchers:
                with self._lock:
                    if self._entries.get(entry.key) is entry:
                        del self._entries[entry.key]
                        self._by_qid.pop(entry.query_id, None)
                        self.counters["upstream_unsubs"] += 1
                        teardown = True
        if teardown:
            frame = PushFrame(kind="unsub", query_id=entry.query_id)
            for hc in self._live_conns():
                self._send_control(hc, frame)

    def reap(self, now_monotonic: float | None = None) -> int:
        """Remove router watchers whose lease lapsed (same stance as
        SubscriptionManager.reap); empty entries unsubscribe upstream."""
        now = time.monotonic() if now_monotonic is None else now_monotonic
        reaped = 0
        with self._fan_lock:
            entries = list(self._entries.values())
            expired = [
                (e, w) for e in entries for w in list(e.watchers)
                if w.expired(now)
            ]
            for e, w in expired:
                self.unwatch(e, w)
                reaped += 1
        if reaped:
            self._count("watchers_reaped", reaped)
        return reaped

    def on_alert(self, cb) -> None:
        """Register a callback for remote `alert` frames (the hub fans
        them to its `alerts=1` wire watchers)."""
        with self._lock:
            self._alert_cbs.append(cb)

    # -- read faces ------------------------------------------------------
    def hosts(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "host": hc.host,
                    "connected": hc.connected,
                    "last_seen": hc.last_seen,
                    "results": hc.results,
                    "hellos": hc.hellos,
                }
                for hc in self._hosts.values()
            ]

    def entries(self) -> list[dict]:
        with self._fan_lock:
            return [
                {
                    "query_id": e.query_id,
                    "kind": e.spec["kind"],
                    "query": e.spec["query"],
                    "watchers": len(e.watchers),
                    "hosts": len(e.hosts),
                    "upstream_results": e.upstream_results,
                    "merged_seq": e.merged_seq,
                    "deliveries": e.deliveries,
                    "drops": e.drops,
                    "dup_results": e.dup_results,
                    "partial_superseded": e.partial_superseded,
                }
                for e in self._entries.values()
            ]

    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["queries"] = len(self._entries)
            out["hosts"] = len(self._hosts)
            out["hosts_connected"] = sum(
                1 for hc in self._hosts.values() if hc.connected
            )
        with self._fan_lock:
            out["watchers"] = sum(
                len(e.watchers) for e in self._by_qid.values()
            )
        return out

    # -- control plane (router → host) -----------------------------------
    def _live_conns(self) -> list[_HostConn]:
        with self._lock:
            return [hc for hc in self._hosts.values() if hc.connected]

    def _broadcast_sub(self, entry: _RouterEntry) -> None:
        frame = PushFrame(kind="sub", query_id=entry.query_id,
                          body=dict(entry.spec))
        for hc in self._live_conns():
            self._send_control(hc, frame)

    def _send_control(self, hc: _HostConn, frame: PushFrame) -> None:
        buf = encode_push_frame(frame)
        try:
            with hc.wlock:
                hc.sock.sendall(buf)
            self._count("control_tx")
        except OSError:
            # the conn loop owns disconnect bookkeeping; reconnect
            # re-sends the whole active set on hello anyway
            self._count("control_errors")

    # -- uplink (host → router) ------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(0.5)
            self._count("connections")
            t = threading.Thread(
                target=self._conn_loop, args=(conn, addr),
                name=f"wire-router-conn-{addr[1]}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _conn_loop(self, conn: socket.socket, addr) -> None:
        reasm = FrameReassembler()
        hc: _HostConn | None = None
        try:
            while self._running:
                try:
                    chunk = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not chunk:
                    break
                for header, body in reasm.feed(chunk):
                    self._count("frames_rx")
                    try:
                        frame = decode_push_frame(header, body)
                    except (ValueError, KeyError, TypeError):
                        self._count("decode_errors")
                        continue
                    hc = self._dispatch(frame, conn, hc)
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if hc is not None:
                self._host_lost(hc, conn)

    def _dispatch(self, frame: PushFrame, conn, hc: _HostConn | None):
        if frame.kind == "hello":
            return self._on_hello(frame, conn)
        if hc is None:
            # results before hello: identity unknown — count, drop
            self._count("decode_errors")
            return None
        hc.last_seen = self._clock()
        if frame.kind == "result":
            hc.results += 1
            self._on_result(hc.host, frame)
        elif frame.kind == "alert":
            self._on_alert_frame(hc.host, frame)
        else:
            self._count("decode_errors")
        return hc

    def _on_hello(self, frame: PushFrame, conn) -> _HostConn:
        host = frame.host or "?"
        with self._lock:
            prev = self._hosts.get(host)
            hc = _HostConn(host, conn)
            hc.hellos = (prev.hellos if prev else 0) + 1
            hc.results = prev.results if prev else 0
            hc.last_seen = self._clock()
            self._hosts[host] = hc
            recovered = prev is not None
            self.counters["hellos"] += 1
            if recovered:
                self.counters["hosts_recovered"] += 1
        if prev is not None and prev.sock is not conn:
            try:
                prev.sock.close()
            except OSError:
                pass
        # (re)send the active subscription set: reconnect resumes with
        # zero host-side state
        with self._lock:
            entries = list(self._entries.values())
        for entry in entries:
            self._send_control(hc, PushFrame(
                kind="sub", query_id=entry.query_id, body=dict(entry.spec)
            ))
        return hc

    def _host_lost(self, hc: _HostConn, conn) -> None:
        """Connection gone: mark stale + notify watchers (counted)."""
        with self._lock:
            cur = self._hosts.get(hc.host)
            if cur is not hc:
                return  # a newer hello superseded this conn already
            hc.connected = False
            self.counters["hosts_lost"] += 1
        notice_base = {"type": "staleness", "host": hc.host}
        with self._fan_lock:
            entries = [
                e for e in self._entries.values() if hc.host in e.hosts
            ]
            for e in entries:
                e.hosts[hc.host]["stale"] = True
                notice = dict(notice_base)
                notice["query_id"] = e.query_id
                n = drops = 0
                for w in list(e.watchers):
                    d0 = w.dropped
                    w.deliver(notice, None)
                    drops += w.dropped - d0
                    n += 1
                if n:
                    self._count("staleness_notices", n)
                e.drops += drops
                if drops:
                    self._count("drops", drops)

    def _on_result(self, host: str, frame: PushFrame) -> None:
        with self._lock:
            entry = self._by_qid.get(frame.query_id)
        if entry is None:
            self._count("unknown_results")
            return
        body = frame.body
        now = int(body.get("now", 0))
        partial = bool(body.get("partial", False))
        with self._fan_lock:
            hs = entry.hosts.get(host)
            if hs is not None and frame.seq <= hs["seq"]:
                # at-least-once redelivery across a reconnect
                entry.dup_results += 1
                self._count("dup_results")
                return
            if (hs is not None and partial and not hs["partial"]
                    and now <= hs["now"]):
                # flushed supersedes partial: the merged view did not
                # move — record the seq (it IS consumed) and skip
                hs["seq"] = frame.seq
                entry.partial_superseded += 1
                self._count("partial_superseded")
                return
            entry.hosts[host] = {
                "seq": frame.seq,
                "now": now,
                "partial": partial,
                "series": body.get("series"),
                "stale": False,
            }
            entry.upstream_results += 1
            self._count("results_rx")
            self._fan_out(entry)

    def _fan_out(self, entry: _RouterEntry) -> None:
        """Build ONE merged envelope from the entry's per-host state and
        deliver it to every watcher (called under _fan_lock)."""
        entry.merged_seq += 1
        hosts = {
            h: {
                "seq": hs["seq"],
                "now": hs["now"],
                "partial": hs["partial"],
                "stale": hs["stale"],
                "series": hs["series"],
            }
            for h, hs in entry.hosts.items()
        }
        merged = []
        for h in sorted(hosts):
            series = hosts[h]["series"]
            if isinstance(series, list):
                for s in series:
                    if isinstance(s, dict):
                        s = dict(s)
                        metric = dict(s.get("metric") or {})
                        metric["host"] = h
                        s["metric"] = metric
                    merged.append(s)
        envelope = {
            "type": "result",
            "query_id": entry.query_id,
            "kind": entry.spec["kind"],
            "query": entry.spec["query"],
            "seq": entry.merged_seq,
            "now": max((hs["now"] for hs in hosts.values()), default=0),
            "hosts": hosts,
            "merged": merged,
        }
        self._count("merged_evals")
        delivered = drops = 0
        for w in list(entry.watchers):
            d0 = w.dropped
            w.deliver(envelope, None)
            drops += w.dropped - d0
            delivered += 1
        entry.deliveries += delivered
        entry.drops += drops
        self._count("deliveries", delivered)
        if drops:
            self._count("drops", drops)

    def _on_alert_frame(self, host: str, frame: PushFrame) -> None:
        self._count("alerts_rx")
        event = dict(frame.body)
        event.setdefault("host", host)
        with self._lock:
            cbs = list(self._alert_cbs)
        for cb in cbs:
            try:
                cb(event)
            except Exception:
                self._count("alert_cb_errors")


__all__ = ["FleetSubscriptionRouter"]
