"""Wire delivery plane (ISSUE 19) — the million-watcher product surface.

The push plane (r15) evaluates once per event batch and fans out to N
bounded watcher queues; the lease machinery (r16) reaps abandoned
ones; the handoff transport (r19) moves framed bytes between hosts
at-least-once; the fleet plane (r21) proved the host→aggregator
fan-in shape. This package is the layer that turns all of that into a
surface dashboards actually connect to:

  * `frame`     — the DFPUSH lane: control (`hello`/`sub`/`unsub`) and
    data (`result`/`alert`) frames on the existing framed-TCP ABI.
  * `hub`       — `WireHub`: SSE streams off the RestServer
    (`GET /v1/watch`), a framed-TCP `WireListener`, and in-process
    streams, all mapped onto the EXISTING bounded `Watcher` queues
    (per-client flow control, lease renewal on delivery, counted
    drops/reaps/disconnects).
  * `router`    — `FleetSubscriptionRouter`: ONE upstream subscription
    per distinct query fleet-wide; merges per-host results
    (flushed-supersedes-partial, at-least-once dedup, counted
    staleness) and fans the merged eval to N wire clients.
  * `publisher` — `WirePublisher`: the pipeline host's duplex uplink —
    answers the router's control plane with local subscriptions and
    pushes every eval (and alert notification) upstream.

Watcher count scales with aggregator processes; fan-out cost stays
O(evals), never O(watchers × hosts).
"""

from .frame import (
    PUSH_FRAME_VERSION,
    PUSH_MSG_TYPE,
    PushFrame,
    decode_push_frame,
    encode_push_frame,
    normalize_query_spec,
    query_id_for,
)
from .hub import DEFAULT_LEASE_S, WireConnection, WireHub, WireListener
from .publisher import WirePublisher, result_to_jsonable
from .router import FleetSubscriptionRouter

__all__ = [
    "PUSH_FRAME_VERSION",
    "PUSH_MSG_TYPE",
    "PushFrame",
    "decode_push_frame",
    "encode_push_frame",
    "normalize_query_spec",
    "query_id_for",
    "DEFAULT_LEASE_S",
    "WireConnection",
    "WireHub",
    "WireListener",
    "WirePublisher",
    "result_to_jsonable",
    "FleetSubscriptionRouter",
]
