"""WirePublisher — the pipeline host's DFPUSH uplink.

One duplex framed-TCP connection host → `FleetSubscriptionRouter`:
control frames (`sub`/`unsub`) flow DOWN it, results and alert
notifications flow UP it. The send side is the HandoffSender stance
verbatim — bounded PyOverwriteQueue (overflow = counted shed, the only
loss point), the in-flight frame retained across reconnects
(at-least-once; the router dedups on seq), capped decorrelated-jitter
backoff, and the `chaos.SITE_WIRE_SEND` seam so tests script transport
loss deterministically.

A `sub` frame creates ONE local subscription on the host's EXISTING
`SubscriptionManager` with a callback watcher that encodes each
evaluation as a `result` frame — so the host evaluates once per event
batch (the r15 coalescing pin) no matter how many wire clients watch
the query on the aggregator, and the local drop/lease machinery is
reused unchanged. `unsub` tears the local subscription down (unless
other local watchers still hold it). Countable face:
`tpu_wire_publisher`.
"""

from __future__ import annotations

import select
import socket
import threading
import time

from .. import chaos
from ..ingest.framing import FrameReassembler
from ..ingest.queues import PyOverwriteQueue
from ..utils.retry import RetryPolicy, decorrelated_rng
from ..utils.stats import register_countable
from .frame import PushFrame, decode_push_frame, encode_push_frame

_RECONNECT = RetryPolicy(base_delay_s=0.05, max_delay_s=2.0, jitter=0.5)
_BACKOFF_CAP_ATTEMPT = 16


def result_to_jsonable(result):
    """One subscription eval result → the JSON shape shipped in a
    `result` frame body. PromQL range results are already list[dict];
    SQL `QueryResult`s become {"columns", "rows"}. The ORACLE in the
    2-process pin records the same shape, so bit-exact comparison is a
    plain == on parsed JSON."""
    if result is None:
        return None
    if isinstance(result, (list, tuple)):
        return [dict(s) if isinstance(s, dict) else s for s in result]
    cols = getattr(result, "columns", None)
    rows = getattr(result, "rows", None)
    if cols is not None and rows is not None:
        return {
            "columns": list(cols),
            "rows": [list(r) for r in rows],
        }
    return result


def _has_partial(payload) -> bool:
    if isinstance(payload, list):
        return any(
            isinstance(s, dict) and s.get("partial") for s in payload
        )
    return False


class WirePublisher:
    """Dial a router, answer its subscription control plane, push every
    local eval upstream. `seq_base` exists for process generations: a
    respawned host must start ABOVE its predecessor's sequence space or
    the router's at-least-once dedup would eat its first results."""

    def __init__(self, endpoint: tuple[str, int], *, host: str,
                 subscriptions, alerts=None, capacity: int = 1024,
                 seq_base: int = 0, connect_timeout_s: float = 5.0,
                 name: str | None = None):
        self.endpoint = (endpoint[0], int(endpoint[1]))
        self.host = host
        self._subs = subscriptions
        self._alerts = alerts
        self._alert_sink = None
        self._queue = PyOverwriteQueue(capacity)
        self._seq = seq_base
        self._seq_lock = threading.Lock()
        self.connect_timeout_s = connect_timeout_s
        self._lock = threading.Lock()
        #: query_id -> (sub, watcher) — the local half of each router sub
        self._active: dict[str, tuple] = {}
        self._inflight = 0
        self._sock: socket.socket | None = None
        self._running = True
        self._rng = decorrelated_rng(hash(host) & 0x7FFFFFFF)
        self.counters = {
            "hellos": 0,
            "tx_frames": 0,
            "tx_bytes": 0,
            "shed_frames": 0,
            "send_errors": 0,
            "reconnects": 0,
            "control_rx": 0,
            "control_errors": 0,
            "dup_subs": 0,
            "results_built": 0,
            "alerts_tx": 0,
        }
        if alerts is not None:
            self._alert_sink = alerts.add_sink(
                self._on_alert, name=f"wire:{host}"
            )
        self._stats_src = register_countable(
            "tpu_wire_publisher", self, host=host, name=name or host
        )
        self._thread = threading.Thread(
            target=self._run, name=f"wire-pub-{host}", daemon=True
        )
        self._thread.start()

    # -- public faces ----------------------------------------------------
    def active_queries(self) -> list[tuple]:
        """[(query_id, Subscription)] — the test oracle attaches here."""
        with self._lock:
            return [(qid, sw[0]) for qid, sw in self._active.items()]

    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["subs_active"] = len(self._active)
        out["queue_depth"] = len(self._queue)
        out["queue_shed"] = self._queue.overwritten
        return out

    def flush(self, timeout_s: float = 30.0) -> bool:
        """Block until the outbound queue is drained ONTO the wire (the
        HandoffSender fence): queue empty AND no frame in flight."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not len(self._queue) and not self._inflight:
                return True
            time.sleep(0.002)
        return False

    def close(self, drain_timeout_s: float = 5.0) -> None:
        self.flush(drain_timeout_s)
        self._running = False
        self._thread.join(timeout=10.0)
        shed = len(self._queue) + self._inflight
        if shed:
            self._count("shed_frames", shed)
        self._queue.close()
        if self._alert_sink is not None:
            # the engine prunes detached sinks; flagging it is the
            # supported detach path (no remove_sink face)
            self._alert_sink.detached = True
            self._alert_sink = None
        with self._lock:
            active = list(self._active.values())
            self._active.clear()
        for sub, w in active:
            sub.unwatch(w)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)

    # -- counters --------------------------------------------------------
    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    def _enqueue(self, buf: bytes) -> None:
        before = self._queue.overwritten
        self._queue.put(buf)
        shed = self._queue.overwritten - before
        if shed:
            self._count("shed_frames", shed)

    # -- alert lane ------------------------------------------------------
    def _on_alert(self, event: dict) -> None:
        self._enqueue(encode_push_frame(PushFrame(
            kind="alert", host=self.host, body=dict(event)
        )))
        self._count("alerts_tx")

    # -- control plane ---------------------------------------------------
    def _on_control(self, frame: PushFrame) -> None:
        self._count("control_rx")
        if frame.kind == "sub":
            self._on_sub(frame)
        elif frame.kind == "unsub":
            self._on_unsub(frame)
        else:
            self._count("control_errors")

    def _on_sub(self, frame: PushFrame) -> None:
        qid = frame.query_id
        with self._lock:
            if qid in self._active:
                self.counters["dup_subs"] += 1
                return
        spec = frame.body

        def cb(result, sub):
            with self._seq_lock:
                self._seq += 1
                seq = self._seq
            payload = result_to_jsonable(result)
            body = {
                "now": int(getattr(sub, "last_now", 0) or 0),
                "partial": _has_partial(payload),
                "series": payload,
            }
            self._enqueue(encode_push_frame(PushFrame(
                kind="result", host=self.host, query_id=qid,
                seq=seq, body=body,
            )))
            self._count("results_built")

        try:
            if spec.get("kind") == "sql":
                sub, w = self._subs.subscribe_sql(
                    spec["query"], callback=cb
                )
            else:
                sub, w = self._subs.subscribe_promql(
                    spec["query"],
                    span_s=int(spec.get("span_s", 60)),
                    step=int(spec.get("step", 1)),
                    db=spec.get("db", "deepflow_system"),
                    table=spec.get("table", "deepflow_system"),
                    lookback_s=int(spec.get("lookback_s", 300)),
                    callback=cb,
                )
        except Exception:
            self._count("control_errors")
            return
        with self._lock:
            self._active[qid] = (sub, w)

    def _on_unsub(self, frame: PushFrame) -> None:
        with self._lock:
            pair = self._active.pop(frame.query_id, None)
        if pair is None:
            return
        sub, w = pair
        sub.unwatch(w)
        if not sub.watchers:
            # no other local consumer holds this query — drop it so it
            # stops evaluating (cache-warming mode is opt-in, not a leak)
            self._subs.unsubscribe(sub)

    # -- uplink thread ---------------------------------------------------
    def _connect(self) -> bool:
        try:
            s = socket.create_connection(
                self.endpoint, timeout=self.connect_timeout_s
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.sendall(encode_push_frame(PushFrame(
                kind="hello", host=self.host
            )))
        except OSError:
            return False
        self._sock = s
        self._count("hellos")
        return True

    def _disconnect(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _run(self) -> None:
        attempt = 1
        pending: bytes | None = None
        reasm = FrameReassembler()
        while self._running or pending is not None or len(self._queue):
            if self._sock is None:
                if not self._connect():
                    self._count("send_errors")
                    if not self._running:
                        self._count("shed_frames", 1 if pending else 0)
                        self._inflight = 0
                        return
                    time.sleep(_RECONNECT.delay(attempt, self._rng))
                    attempt = min(attempt + 1, _BACKOFF_CAP_ATTEMPT)
                    continue
                reasm = FrameReassembler()  # new stream, new framing
                attempt = 1
            # control plane: drain whatever the router pushed down
            try:
                r, _, _ = select.select([self._sock], [], [], 0)
                if r:
                    chunk = self._sock.recv(1 << 16)
                    if not chunk:
                        raise ConnectionResetError("router closed uplink")
                    for header, body in reasm.feed(chunk):
                        try:
                            self._on_control(decode_push_frame(header, body))
                        except (ValueError, KeyError, TypeError):
                            self._count("control_errors")
            except OSError:
                self._count("reconnects")
                self._disconnect()
                continue
            if pending is None:
                got = self._queue.gets(1, timeout_ms=5)
                if not got:
                    if not self._running:
                        return
                    continue
                pending = got[0]
                self._inflight = 1
            try:
                # the scripted-loss seam: an injected fault here behaves
                # exactly like a broken pipe (reconnect + resend)
                chaos.maybe_fail(chaos.SITE_WIRE_SEND)
                self._sock.sendall(pending)
                self._count("tx_frames")
                self._count("tx_bytes", len(pending))
                pending = None
                self._inflight = 0
            except Exception:
                # at-least-once: the in-flight frame stays pending
                # across the reconnect
                self._count("send_errors")
                self._count("reconnects")
                self._disconnect()
                time.sleep(_RECONNECT.delay(attempt, self._rng))
                attempt = min(attempt + 1, _BACKOFF_CAP_ATTEMPT)


__all__ = ["WirePublisher", "result_to_jsonable"]
