"""WireHub — the wire delivery plane's serving half (ISSUE 19).

Maps each wire client — an SSE stream off the RestServer
(`GET /v1/watch?...`), a framed-TCP connection (`WireListener`), or an
in-process stream (`open_stream`) — onto ONE bounded `Watcher` queue
from the EXISTING push plane. Nothing new is invented for flow
control, drops, or liveness:

  connection → queue → lease state machine

  * OPEN     — `open_stream` attaches a queue-mode `Watcher` to the
    query's (deduped) subscription: local `SubscriptionManager` for
    `scope=local`, `FleetSubscriptionRouter` entry for fleet queries,
    the hub's alert topic for `alerts=1`. Queue bounds ARE the
    per-client flow control: a slow client drops ITS OWN oldest
    results (counted on its watcher), never a sibling's.
  * DELIVER  — the serve loop polls with `renew=False` (the pop proves
    nothing about the client) and renews the lease only after a
    successful socket write — delivery IS the heartbeat; idle streams
    renew on successful `: hb` keepalive writes instead.
  * LAPSE    — a client that vanished mid-silence stops renewing; the
    manager/router/hub `reap()` removes the watcher after `lease_s`
    (counted) and the serve loop notices and ends. A client that
    vanished mid-WRITE is caught immediately (BrokenPipe/
    ConnectionReset contained + counted, never kills the handler
    thread) and unwatched on the spot — lease lapse is the backstop
    for silently-wedged transports, not the common path.
  * CLOSE    — `close_conn` detaches the watcher from whatever it was
    attached to; no orphaned queues (the queue dies with the watcher,
    and a fleet entry whose last watcher left unsubscribes upstream).

Countable face: `tpu_wire` — aggregate counters plus per-connection
rows via `connections()` (surfaced on `GET /v1/wire` and, as skew
lanes, in `GET /v1/fleet/skew`).
"""

from __future__ import annotations

import itertools
import json
import select
import socket
import threading
import time

from ..querier.subscribe import DEFAULT_WATCHER_QUEUE, Watcher
from ..utils.stats import register_countable
from .frame import PushFrame, decode_push_frame, encode_push_frame
from .publisher import result_to_jsonable

DEFAULT_LEASE_S = 30.0

_conn_ids = itertools.count(1)


class WireConnection:
    """One wire client: a Watcher plus the detach recipe for whatever
    plane it is attached to."""

    __slots__ = ("id", "transport", "topic", "query", "query_id",
                 "watcher", "opened", "closed", "_detach")

    def __init__(self, *, transport: str, topic: str, query: str,
                 query_id: str, watcher: Watcher, detach):
        self.id = next(_conn_ids)
        self.transport = transport  # "sse" | "tcp" | "local"
        self.topic = topic  # "promql" | "sql" | "alerts"
        self.query = query
        self.query_id = query_id
        self.watcher = watcher
        self.opened = time.monotonic()
        self.closed = False
        self._detach = detach

    def poll(self):
        """Pop WITHOUT renewing — only a successful write renews."""
        return self.watcher.poll(renew=False)

    def renew(self) -> None:
        self.watcher.renew()


class WireHub:
    def __init__(self, subscriptions, *, alerts=None, router=None,
                 bus=None, lease_s: float | None = DEFAULT_LEASE_S,
                 maxlen: int = DEFAULT_WATCHER_QUEUE, name: str = "wire"):
        self._subs = subscriptions
        self._alerts = alerts
        self.router = router
        self._bus = bus
        self.lease_s = lease_s
        self.maxlen = maxlen
        self.name = name
        self._lock = threading.Lock()
        self._conns: dict[int, WireConnection] = {}
        self._alert_watchers: list[Watcher] = []
        self._closing = False
        self.counters = {
            "connections_total": 0,
            "sse_connections": 0,
            "tcp_connections": 0,
            "deliveries": 0,
            "drops": 0,
            "heartbeats": 0,
            "disconnects": 0,
            "mid_write_disconnects": 0,
            "reaps": 0,
            "alerts_delivered": 0,
            "alerts_dropped": 0,
            "open_errors": 0,
        }
        self._alert_sink = None
        if alerts is not None:
            from ..querier.alerts import wire_notification_sink

            self._alert_sink = alerts.add_sink(
                wire_notification_sink(self), name=f"wire:{name}"
            )
        if router is not None:
            router.on_alert(self.deliver_alert)
        self._stats_src = register_countable("tpu_wire", self, name=name)

    # -- stream lifecycle ------------------------------------------------
    def open_stream(self, *, promql: str | None = None,
                    sql: str | None = None, alerts: bool = False,
                    scope: str = "auto", span_s: int = 60, step: int = 1,
                    db: str = "deepflow_system", table: str = "deepflow_system",
                    lookback_s: int = 300, maxlen: int | None = None,
                    lease_s: float | None = None,
                    transport: str = "local") -> WireConnection:
        """Attach one wire client; returns the connection. Exactly one
        of promql/sql/alerts selects the topic. `scope="fleet"` (or
        "auto" with a router attached) rides the FleetSubscriptionRouter
        — ONE upstream subscription per distinct query fleet-wide;
        `scope="local"` evaluates on this process's store."""
        maxlen = self.maxlen if maxlen is None else int(maxlen)
        lease = self.lease_s if lease_s is None else lease_s
        if sum(x is not None and x != "" for x in (promql, sql)) + bool(alerts) != 1:
            raise ValueError(
                "exactly one of promql=, sql=, alerts=1 selects the topic"
            )
        if alerts:
            w = Watcher(None, maxlen=maxlen, lease_s=lease)
            with self._lock:
                self._alert_watchers.append(w)

            def detach():
                with self._lock:
                    if w in self._alert_watchers:
                        self._alert_watchers.remove(w)

            conn = WireConnection(
                transport=transport, topic="alerts", query="alerts",
                query_id="", watcher=w, detach=detach,
            )
        else:
            kind = "promql" if promql is not None else "sql"
            query = promql if promql is not None else sql
            fleet = self.router is not None and scope != "local"
            if scope == "fleet" and self.router is None:
                raise ValueError("no fleet router on this server")
            if fleet and kind == "sql":
                if scope == "fleet":
                    raise ValueError(
                        "sql subscriptions are local-only; fleet scope "
                        "takes promql"
                    )
                fleet = False  # auto: sql falls back to the local store
            if fleet:
                spec = {"kind": kind, "query": query, "db": db,
                        "table": table, "span_s": span_s, "step": step,
                        "lookback_s": lookback_s}
                entry, w = self.router.watch(
                    spec, maxlen=maxlen, lease_s=lease
                )
                detach = lambda: self.router.unwatch(entry, w)  # noqa: E731
                qid = entry.query_id
            else:
                if kind == "sql":
                    sub, w = self._subs.subscribe_sql(
                        query, queue=True, maxlen=maxlen, lease_s=lease
                    )
                else:
                    sub, w = self._subs.subscribe_promql(
                        query, span_s=int(span_s), step=int(step), db=db,
                        table=table, lookback_s=int(lookback_s),
                        queue=True, maxlen=maxlen, lease_s=lease,
                    )
                qid = ""

                def detach(sub=sub, w=w):
                    sub.unwatch(w)
                    if not sub.watchers:
                        # a transient dashboard client must not leave a
                        # standing eval behind (cache-warming subs are
                        # registered deliberately, not by disconnect)
                        self._subs.unsubscribe(sub)

            conn = WireConnection(
                transport=transport, topic=kind, query=query,
                query_id=qid, watcher=w, detach=detach,
            )
        with self._lock:
            self._conns[conn.id] = conn
            self.counters["connections_total"] += 1
            if transport == "sse":
                self.counters["sse_connections"] += 1
            elif transport == "tcp":
                self.counters["tcp_connections"] += 1
        return conn

    def close_conn(self, conn: WireConnection, *, reason: str = "close") -> None:
        with self._lock:
            if conn.closed:
                return
            conn.closed = True
            self._conns.pop(conn.id, None)
            # fold the departing connection's drops into the lifetime
            # total (open connections report theirs via open_dropped)
            self.counters["drops"] += conn.watcher.dropped
            if reason == "disconnect":
                self.counters["disconnects"] += 1
            elif reason == "lease":
                self.counters["reaps"] += 1
        try:
            conn._detach()
        except Exception:
            pass

    def reap(self, now_monotonic: float | None = None) -> int:
        """Lease sweep for everything the hub owns: alert-topic
        watchers, fleet router watchers (via router.reap), and stream
        records whose watcher lapsed. Local-subscription watchers are
        ALSO reaped by SubscriptionManager.reap — this pass closes the
        hub's connection record for them."""
        now = time.monotonic() if now_monotonic is None else now_monotonic
        reaped = 0
        with self._lock:
            conns = list(self._conns.values())
        for conn in conns:
            if conn.watcher.expired(now):
                self.close_conn(conn, reason="lease")
                reaped += 1
        with self._lock:
            expired = [w for w in self._alert_watchers if w.expired(now)]
            for w in expired:
                self._alert_watchers.remove(w)
                # not conn-tracked (open_stream alert watchers are); a
                # bare expired alert watcher still counts as a reap
                self.counters["reaps"] += 1
                reaped += 1
        if self.router is not None:
            self.router.reap(now)
        return reaped

    def close(self) -> None:
        self._closing = True
        if self._alert_sink is not None:
            self._alert_sink.detached = True
            self._alert_sink = None
        with self._lock:
            conns = list(self._conns.values())
        for conn in conns:
            self.close_conn(conn)
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)

    # -- alert topic -----------------------------------------------------
    def deliver_alert(self, event: dict) -> None:
        """Fan one alert notification to every alerts-topic watcher
        (local engine sink AND remote `alert` frames land here)."""
        with self._lock:
            watchers = list(self._alert_watchers)
        delivered = dropped = 0
        for w in watchers:
            d0 = w.dropped
            w.deliver(dict(event), None)
            dropped += w.dropped - d0
            delivered += 1
        with self._lock:
            self.counters["alerts_delivered"] += delivered
            self.counters["alerts_dropped"] += dropped
        if self._bus is not None:
            from ..querier.events import AlertFired

            labels = event.get("labels") or {}
            self._bus.publish(AlertFired(
                rule=str(event.get("rule", "?")),
                state=str(event.get("state", "?")),
                value=float(event.get("value") or 0.0),
                labels=tuple(sorted(labels.items())),
                time=event.get("time"),
            ))

    # -- read faces ------------------------------------------------------
    def connections(self) -> list[dict]:
        now = time.monotonic()
        with self._lock:
            conns = list(self._conns.values())
        return [
            {
                "id": c.id,
                "transport": c.transport,
                "topic": c.topic,
                "query": c.query,
                "query_id": c.query_id,
                "delivered": c.watcher.delivered,
                "dropped": c.watcher.dropped,
                "queue_depth": len(c.watcher.queue or ()),
                "lease_s": c.watcher.lease_s,
                "age_s": round(now - c.opened, 3),
                "expired": c.watcher.expired(now),
            }
            for c in conns
        ]

    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            conns = list(self._conns.values())
            out["alert_watchers"] = len(self._alert_watchers)
        out["connections_open"] = len(conns)
        # the skew lanes fleet/skew scans for (per-host wire imbalance):
        # live per-connection sums ride the same names as the totals
        out["open_delivered"] = sum(c.watcher.delivered for c in conns)
        out["open_dropped"] = sum(c.watcher.dropped for c in conns)
        return out

    # -- SSE serving -----------------------------------------------------
    def serve_sse(self, h, q: dict) -> None:
        """Serve `GET /v1/watch` on a RestServer handler `h` with query
        params `q`. Chunked-style SSE: `data: <json>\\n\\n` per result,
        `: hb\\n\\n` keepalives, until the client disconnects, the
        lease lapses, `max_events` is reached, or the hub closes."""
        try:
            conn = self.open_stream(
                promql=q.get("promql"),
                sql=q.get("sql"),
                alerts=(q.get("alerts") or "0") not in ("0", "", "false"),
                scope=q.get("scope", "auto"),
                span_s=int(q.get("span_s") or 60),
                step=int(q.get("step") or 1),
                db=q.get("db") or "deepflow_system",
                table=q.get("table") or "deepflow_system",
                lookback_s=int(q.get("lookback_s") or 300),
                maxlen=int(q["maxlen"]) if q.get("maxlen") else None,
                lease_s=float(q["lease_s"]) if q.get("lease_s") else None,
                transport="sse",
            )
        except ValueError as e:
            with self._lock:
                self.counters["open_errors"] += 1
            h._json({"error": str(e)}, 400)
            return
        max_events = int(q.get("max_events") or 0)
        heartbeat_s = float(q.get("heartbeat_s") or 5.0)
        poll_s = 0.02
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.send_header("X-Accel-Buffering", "no")
        h.end_headers()
        sent = 0
        last_write = time.monotonic()
        reason = "disconnect"
        try:
            while True:
                if self._closing or conn.closed:
                    reason = "close" if not conn.closed else "lease"
                    break
                item = conn.poll()
                if item is None:
                    now = time.monotonic()
                    if now - last_write >= heartbeat_s:
                        h.wfile.write(b": hb\n\n")
                        h.wfile.flush()
                        conn.renew()
                        last_write = now
                        with self._lock:
                            self.counters["heartbeats"] += 1
                    time.sleep(poll_s)
                    continue
                payload = json.dumps(
                    result_to_jsonable(item), default=str
                ).encode()
                h.wfile.write(b"data: " + payload + b"\n\n")
                h.wfile.flush()
                # a successful write IS the client's heartbeat
                conn.renew()
                last_write = time.monotonic()
                sent += 1
                with self._lock:
                    self.counters["deliveries"] += 1
                if max_events and sent >= max_events:
                    reason = "close"
                    break
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client vanished mid-write: contained and counted — the
            # handler thread survives; the watcher detaches on the spot
            # (lease lapse is only the backstop for wedged transports)
            with self._lock:
                self.counters["mid_write_disconnects"] += 1
            reason = "disconnect"
        finally:
            self.close_conn(conn, reason=reason)


class WireListener:
    """The framed-TCP variant of the SSE lane (the UniformSender/
    handoff stance): a client connects, sends ONE `sub` PushFrame whose
    body is an open_stream spec, and receives `result` frames (body =
    {"payload": ...}) with `hello` keepalives — same watcher queue,
    lease, drop, and containment semantics as SSE."""

    def __init__(self, hub: WireHub, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.hub = hub
        self.host = host
        self.port = port
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._running = False

    def start(self) -> "WireListener":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(32)
        s.settimeout(0.5)
        self._sock = s
        self.port = s.getsockname()[1]
        self._running = True
        t = threading.Thread(target=self._accept_loop,
                             name="wire-listener", daemon=True)
        t.start()
        self._threads.append(t)
        return self

    @property
    def endpoint(self) -> tuple[str, int]:
        return (self.host, self.port)

    def stop(self) -> None:
        self._running = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f"wire-tcp-{addr[1]}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, sock: socket.socket) -> None:
        from ..ingest.framing import FrameReassembler

        hub = self.hub
        reasm = FrameReassembler()
        stream = None
        try:
            sock.settimeout(5.0)
            sub = None
            while sub is None:
                chunk = sock.recv(1 << 16)
                if not chunk:
                    return
                for header, body in reasm.feed(chunk):
                    frame = decode_push_frame(header, body)
                    if frame.kind == "sub":
                        sub = frame
                        break
            spec = sub.body
            stream = hub.open_stream(
                promql=spec.get("promql"),
                sql=spec.get("sql"),
                alerts=bool(spec.get("alerts")),
                scope=spec.get("scope", "auto"),
                span_s=int(spec.get("span_s") or 60),
                step=int(spec.get("step") or 1),
                db=spec.get("db") or "deepflow_system",
                table=spec.get("table") or "deepflow_system",
                lookback_s=int(spec.get("lookback_s") or 300),
                maxlen=spec.get("maxlen"),
                lease_s=spec.get("lease_s"),
                transport="tcp",
            )
            sock.setblocking(True)
            seq = 0
            last_write = time.monotonic()
            heartbeat_s = float(spec.get("heartbeat_s") or 5.0)
            reason = "disconnect"
            while self._running:
                if hub._closing or stream.closed:
                    reason = "close" if not stream.closed else "lease"
                    break
                r, _, _ = select.select([sock], [], [], 0)
                if r:
                    chunk = sock.recv(1 << 16)
                    if not chunk:
                        break  # client closed cleanly
                    for header, body in reasm.feed(chunk):
                        frame = decode_push_frame(header, body)
                        if frame.kind == "unsub":
                            reason = "close"
                            raise StopIteration
                item = stream.poll()
                if item is None:
                    now = time.monotonic()
                    if now - last_write >= heartbeat_s:
                        sock.sendall(encode_push_frame(
                            PushFrame(kind="hello")
                        ))
                        stream.renew()
                        last_write = now
                        with hub._lock:
                            hub.counters["heartbeats"] += 1
                    time.sleep(0.02)
                    continue
                seq += 1
                sock.sendall(encode_push_frame(PushFrame(
                    kind="result", query_id=stream.query_id, seq=seq,
                    body={"payload": result_to_jsonable(item)},
                )))
                stream.renew()
                last_write = time.monotonic()
                with hub._lock:
                    hub.counters["deliveries"] += 1
            hub.close_conn(stream, reason=reason)
            stream = None
        except StopIteration:
            hub.close_conn(stream, reason="close")
            stream = None
        except (BrokenPipeError, ConnectionResetError, OSError, ValueError):
            with hub._lock:
                hub.counters["mid_write_disconnects"] += 1
        finally:
            if stream is not None:
                hub.close_conn(stream, reason="disconnect")
            try:
                sock.close()
            except OSError:
                pass


__all__ = ["WireHub", "WireConnection", "WireListener", "DEFAULT_LEASE_S"]
