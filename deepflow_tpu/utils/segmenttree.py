"""Immutable interval index — the server/libs/segmenttree seat.

The reference builds an immutable segment tree over value ranges for
querier-side lookups (libs/segmenttree). The numpy-native equivalent is
a sorted-endpoint index answering the same queries without pointer
chasing, vectorized over query batches:

  * stab(points)   → which intervals contain each point
  * query(lo, hi)  → indices of intervals overlapping [lo, hi]

Build once (immutable), query many — the same usage contract.
"""

from __future__ import annotations

import numpy as np


class IntervalIndex:
    def __init__(self, starts, ends):
        """Intervals [starts[i], ends[i]] (inclusive), any order."""
        self.starts = np.asarray(starts, np.int64)
        self.ends = np.asarray(ends, np.int64)
        if self.starts.shape != self.ends.shape:
            raise ValueError("starts/ends shape mismatch")
        if (self.ends < self.starts).any():
            raise ValueError("interval with end < start")
        self._by_start = np.argsort(self.starts, kind="stable")
        self._sorted_starts = self.starts[self._by_start]
        # running max of ends in start order: the classic augmented-tree
        # invariant flattened — intervals before position i can only
        # overlap x if max_end[:i] >= x
        self._max_end = (
            np.maximum.accumulate(self.ends[self._by_start])
            if len(self.starts)
            else np.empty(0, np.int64)
        )

    def __len__(self) -> int:
        return len(self.starts)

    def query(self, lo: int, hi: int) -> np.ndarray:
        """Indices (original order) of intervals overlapping [lo, hi]."""
        if not len(self):
            return np.empty(0, np.int64)
        # candidates: start <= hi
        k = int(np.searchsorted(self._sorted_starts, hi, side="right"))
        if k == 0:
            return np.empty(0, np.int64)
        cand = self._by_start[:k]
        hit = self.ends[cand] >= lo
        return np.sort(cand[hit])

    def stab(self, points) -> list[np.ndarray]:
        """For each point, the indices of intervals containing it."""
        return [self.query(int(p), int(p)) for p in np.asarray(points).ravel()]

    def coverage(self, points) -> np.ndarray:
        """[N] count of intervals containing each point (vectorized)."""
        pts = np.asarray(points, np.int64)
        if not len(self):
            return np.zeros(len(pts), np.int64)
        starts = np.sort(self.starts)
        ends = np.sort(self.ends)
        started = np.searchsorted(starts, pts, side="right")
        ended = np.searchsorted(ends, pts, side="left")
        return started - ended
