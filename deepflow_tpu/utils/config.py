"""Server configuration — one YAML file → typed per-module configs.

The reference's server reads a single `/etc/server.yaml` into per-module
`config.Config` structs with yaml tags + validation
(server/ingester/config/config.go); the agent adds a dynamic layer pushed
over gRPC. Here every module config is a frozen dataclass with defaults;
`load_config` overlays a YAML mapping (unknown keys are collected and
reported, not silently dropped) and validates ranges.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any

import yaml


class ConfigError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class ReceiverConfig:
    host: str = "127.0.0.1"
    tcp_port: int = 20033
    udp_port: int = 20033


@dataclasses.dataclass(frozen=True)
class IngesterConfig:
    n_decoders: int = 2
    queue_capacity: int = 1 << 16
    batch_size: int = 256
    disable_second_write: bool = False
    prefer_native: bool = True
    # flow_log per-second throttle (ingester.flow_log throttler; 0 = off)
    l4_throttle: int = 50000
    l7_throttle: int = 50000


@dataclasses.dataclass(frozen=True)
class StorageConfig:
    root: str = ""  # "" = in-memory store
    partition_s: int = 3600
    ttl_hours: int = 168
    writer_batch_size: int = 1 << 15
    writer_flush_s: float = 1.0
    # disk watermark for ckmonitor-style priority drops (0 = unlimited)
    max_disk_bytes: int = 0


@dataclasses.dataclass(frozen=True)
class AggregatorConfig:
    stash_capacity: int = 1 << 16
    batch_size: int = 4096
    window_delay_s: int = 2  # quadruple_generator delay_seconds analog
    second_enabled: bool = True
    minute_enabled: bool = True


@dataclasses.dataclass(frozen=True)
class SketchConfig:
    num_services: int = 1 << 10
    hll_precision: int = 14
    cms_depth: int = 4
    cms_width: int = 1 << 16
    hist_bins: int = 128
    hist_vmin: float = 1.0
    hist_gamma: float = 1.08


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet telemetry fan-in (deepflow_tpu/fleet): when enabled the
    server runs a FleetAggregator listener and the REST /v1/fleet pane
    goes live; hosts point their FleetSink at (listen_host,
    listen_port)."""

    enabled: bool = False
    listen_host: str = "127.0.0.1"
    listen_port: int = 0  # 0 = ephemeral (tests); fixed in production
    # host quiet longer than this is EXPIRED from merged views (counted,
    # last-seen stamp retained on the hosts pane)
    expiry_s: float = 60.0


@dataclasses.dataclass(frozen=True)
class WireConfig:
    """Wire delivery plane (deepflow_tpu/wire, ISSUE 19). The SSE lane
    (`GET /v1/watch`) is always on when `enabled` — it rides the
    existing RestServer. `tcp_*` gates the framed-TCP variant listener;
    `router_*` gates the aggregator-side FleetSubscriptionRouter that
    pipeline hosts' WirePublishers dial into."""

    enabled: bool = True
    lease_s: float = 30.0  # default watcher lease for wire clients
    queue_maxlen: int = 64  # default per-client bounded queue
    tcp_enabled: bool = False
    tcp_host: str = "127.0.0.1"
    tcp_port: int = 0  # 0 = ephemeral (tests); fixed in production
    router_enabled: bool = False
    router_host: str = "127.0.0.1"
    router_port: int = 0


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    receiver: ReceiverConfig = ReceiverConfig()
    ingester: IngesterConfig = IngesterConfig()
    storage: StorageConfig = StorageConfig()
    aggregator: AggregatorConfig = AggregatorConfig()
    sketch: SketchConfig = SketchConfig()
    fleet: FleetConfig = FleetConfig()
    wire: WireConfig = WireConfig()
    region_id: int = 0
    log_level: str = "info"
    # exporter sink specs (exporters/config seat): list of mappings,
    # each {"kind": "kafka"|"otlp"|"prom_rw"|"jsonl", ...kind kwargs,
    # "data_sources": [table prefixes]} — built by
    # server.main.build_exporters at boot
    exporters: tuple = ()
    # path to a YAML/JSON alert-rules file (querier/alerts.py
    # save_rules/load_rules shape) loaded at boot — rules survive a
    # restart; a malformed file fails the boot LOUDLY (ISSUE 13
    # satellite / ROADMAP r15 leftover)
    alert_rules: str = ""


def _overlay(cls, defaults, data: dict[str, Any], path: str, unknown: list[str]):
    kwargs = {}
    fields = {f.name: f for f in dataclasses.fields(cls)}
    for key, value in data.items():
        if key not in fields:
            unknown.append(f"{path}{key}")
            continue
        cur = getattr(defaults, key)
        if value is None:
            # explicit YAML null (`key:` with no value) keeps the default
            continue
        if dataclasses.is_dataclass(cur):
            if not isinstance(value, dict):
                raise ConfigError(f"{path}{key}: expected mapping")
            kwargs[key] = _overlay(type(cur), cur, value, f"{path}{key}.", unknown)
        else:
            if isinstance(cur, tuple) and isinstance(value, list):
                value = tuple(value)  # YAML sequences arrive as lists
            if cur is not None and not isinstance(
                value, (type(cur), int) if isinstance(cur, float) else type(cur)
            ):
                raise ConfigError(
                    f"{path}{key}: expected {type(cur).__name__}, got {type(value).__name__}"
                )
            kwargs[key] = type(cur)(value) if cur is not None else value
    return dataclasses.replace(defaults, **kwargs)


def _validate(cfg: ServerConfig) -> None:
    checks = [
        (cfg.ingester.n_decoders >= 1, "ingester.n_decoders must be >= 1"),
        (cfg.storage.partition_s >= 1, "storage.partition_s must be >= 1"),
        (cfg.aggregator.stash_capacity > 0, "aggregator.stash_capacity must be > 0"),
        (1 <= cfg.sketch.hll_precision <= 18, "sketch.hll_precision out of range [1,18]"),
        (cfg.sketch.hist_gamma > 1.0, "sketch.hist_gamma must be > 1"),
        (0 <= cfg.receiver.tcp_port <= 65535, "receiver.tcp_port out of range"),
        (cfg.fleet.expiry_s > 0, "fleet.expiry_s must be > 0"),
        (0 <= cfg.fleet.listen_port <= 65535, "fleet.listen_port out of range"),
        (cfg.wire.lease_s > 0, "wire.lease_s must be > 0"),
        (cfg.wire.queue_maxlen >= 1, "wire.queue_maxlen must be >= 1"),
        (0 <= cfg.wire.tcp_port <= 65535, "wire.tcp_port out of range"),
        (0 <= cfg.wire.router_port <= 65535, "wire.router_port out of range"),
    ]
    for ok, msg in checks:
        if not ok:
            raise ConfigError(msg)


def load_config(source: str | Path | dict | None = None) -> tuple[ServerConfig, list[str]]:
    """Build a ServerConfig from a YAML file path, mapping, or None
    (pure defaults). Returns (config, unknown_keys)."""
    if source is None:
        data: dict = {}
    elif isinstance(source, dict):
        data = source
    else:
        text = Path(source).read_text()
        data = yaml.safe_load(text) or {}
        if not isinstance(data, dict):
            raise ConfigError("top-level config must be a mapping")
    unknown: list[str] = []
    cfg = _overlay(ServerConfig, ServerConfig(), data, "", unknown)
    _validate(cfg)
    return cfg, unknown
