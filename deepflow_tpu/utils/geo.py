"""IP geolocation — the server/libs/geo seat.

The reference ships a compiled IP→(region, province, ISP) table used by
flow-log enrichment (server/libs/geo). Same shape here: a CIDR table →
two sorted u32 arrays → vectorized `np.searchsorted` lookups, so a
whole column geolocates in one call. The built-in table covers the
special-use ranges every deployment needs (RFC 1918/6598/3927, loopback,
multicast); production tables load via `GeoTable.from_cidrs` with
operator data (the reference's table is a licensed database, not
shippable).
"""

from __future__ import annotations

import ipaddress

import numpy as np

UNKNOWN = 0

# built-in labels (id 0 reserved for unknown/public)
BUILTIN_LABELS = {
    0: "public",
    1: "private-10",
    2: "private-172",
    3: "private-192",
    4: "loopback",
    5: "link-local",
    6: "cgn-100.64",
    7: "multicast",
}

_BUILTIN_CIDRS = [
    ("10.0.0.0/8", 1),
    ("172.16.0.0/12", 2),
    ("192.168.0.0/16", 3),
    ("127.0.0.0/8", 4),
    ("169.254.0.0/16", 5),
    ("100.64.0.0/10", 6),
    ("224.0.0.0/4", 7),
]


class GeoTable:
    """DISJOINT sorted-interval IPv4 lookup: starts[i] ≤ ip ≤ ends[i] →
    ids[i]. Build via from_cidrs, which flattens arbitrary (nested /
    overlapping) CIDRs into disjoint ranges with most-specific-wins —
    the shape real geo tables have (a province /24 carved from an ISP
    /16 must not shadow the rest of the /16)."""

    def __init__(self, starts: np.ndarray, ends: np.ndarray, ids: np.ndarray,
                 labels: dict[int, str]):
        order = np.argsort(starts)
        self.starts = starts[order]
        self.ends = ends[order]
        self.ids = ids[order]
        self.labels = dict(labels)

    @classmethod
    def from_cidrs(cls, cidrs: list[tuple[str, int]],
                   labels: dict[int, str] | None = None) -> "GeoTable":
        nets = []
        for cidr, gid in cidrs:
            net = ipaddress.ip_network(cidr)
            nets.append(
                (int(net.network_address), int(net.broadcast_address),
                 net.prefixlen, gid)
            )
        # flatten: sweep over boundary points; within each elementary
        # segment the longest-prefix (most specific) covering net wins
        points = sorted({p for s, e, _l, _g in nets for p in (s, e + 1)})
        starts, ends, ids = [], [], []
        for lo, hi_excl in zip(points, points[1:]):
            best = None
            for s, e, plen, gid in nets:
                if s <= lo and hi_excl - 1 <= e:
                    if best is None or plen > best[0]:
                        best = (plen, gid)
            if best is not None:
                # merge with the previous segment when contiguous + same id
                if starts and ids[-1] == best[1] and ends[-1] == lo - 1:
                    ends[-1] = hi_excl - 1
                else:
                    starts.append(lo)
                    ends.append(hi_excl - 1)
                    ids.append(best[1])
        return cls(
            np.asarray(starts, np.uint32),
            np.asarray(ends, np.uint32),
            np.asarray(ids, np.uint32),
            labels or dict(BUILTIN_LABELS),
        )

    @classmethod
    def builtin(cls) -> "GeoTable":
        return cls.from_cidrs(_BUILTIN_CIDRS)

    def lookup(self, ips: np.ndarray) -> np.ndarray:
        """[N] u32 IPv4 → [N] u32 geo ids (UNKNOWN when no range hits)."""
        ips = np.asarray(ips, np.uint32)
        if len(self.starts) == 0:
            return np.zeros(ips.shape, np.uint32)
        idx = np.searchsorted(self.starts, ips, side="right") - 1
        idx_c = np.clip(idx, 0, len(self.starts) - 1)
        hit = (idx >= 0) & (ips <= self.ends[idx_c])
        return np.where(hit, self.ids[idx_c], np.uint32(UNKNOWN))

    def label(self, gid: int) -> str:
        return self.labels.get(int(gid), "public")
