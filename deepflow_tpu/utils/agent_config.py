"""Agent-config migration — the server/agent_config/migrator.go seat.

The reference carries agent YAML across schema generations: the old
flat trident keys and the current nested sections both upgrade to one
canonical shape via `upgrade_from` annotations (migrator.go:42
newUpgrader; migrator_conv.go rename tables). Same job here, targeting
this build's flat dynamic-config schema (the dict
Agent.apply_dynamic_config consumes): operators can feed either an
old-generation flat YAML or a current nested one, and group-config
pushes normalize on the way in (TrisolarisService.set_group_config).

Unknown keys pass through untouched (agents ignore what they don't
know); every rename is reported in the notes so operators see exactly
what the migrator did.
"""

from __future__ import annotations

# old/foreign dotted path → canonical flat key. Left side matches both
# generations of the reference schema (flat trident keys and the nested
# 6.6+ sections); right side is this build's AgentConfig field space.
_RENAMES = {
    # identity / control plane
    "vtap_id": "agent_id",
    # declaration order matters: within one target the OLDER generation
    # comes first so the newer alias wins conflicts (pass-1 invariant)
    "controller_ips": "servers",
    "global.communication.controller_ip": "servers",
    # resource shape
    "flow_count_limit": "flow_capacity",
    "processors.flow_log.tunning.concurrent_flow_limit": "flow_capacity",
    "batch_size": "batch_size",
    # throttles
    "l4_log_collect_nps_threshold": "l4_log_throttle",
    "processors.flow_log.throttles.l4_throttle": "l4_log_throttle",
    # capture plane
    "tap_interface_regex": "capture_interface_regex",
    "inputs.cbpf.af_packet.interface_regex": "capture_interface_regex",
    "capture_bpf": "capture_filter",
    "inputs.cbpf.af_packet.extra_bpf_filter": "capture_filter",
    # transport
    "compressor_socket_type": "compression",
    "outputs.flow_log.compression": "compression",
    # policy
    "flow_acls": "acls",
}


def _flatten(doc: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in doc.items():
        path = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict) and path not in _RENAMES:
            out.update(_flatten(v, path))
        else:
            out[path] = v
    return out


def migrate_agent_config(doc: dict) -> tuple[dict, list[str]]:
    """Normalize an agent config of any supported generation into the
    flat canonical schema. Returns (config, notes); notes record every
    rename applied (migrator.go's 'has been upgraded to' warnings)."""
    flat = _flatten(doc or {})
    out: dict = {}
    notes: list[str] = []
    # pass 1: renamed aliases, walked in _RENAMES declaration order —
    # within one canonical target the older-generation key is declared
    # first, so when BOTH generations appear the newer alias wins
    # deterministically (never YAML key order)
    for path, target in _RENAMES.items():
        if path not in flat:
            continue
        value = flat[path]
        if target in out and out[target] != value:
            notes.append(f"conflict on {target!r}: newer alias {path!r} wins")
        out[target] = value
        if target != path:
            notes.append(f"{path!r} upgraded to {target!r}")
    # pass 2: canonical / unknown keys — an explicit canonical key
    # deterministically WINS over any leftover alias (dict order must
    # never decide which value an agent receives)
    for path, value in flat.items():
        if path in _RENAMES:
            continue
        if path in out and out[path] != value:
            notes.append(f"canonical {path!r} overrides a renamed alias")
        out[path] = value
    return out, notes
