"""Host stage-span tracer for the pipeline's self-telemetry plane.

The reference attributes latency per pipeline stage by shipping every
component's counters through its own stats pipeline (stats.go:89-202);
what it cannot see — and what the TPU build critically needs — is where
a *host-driven* batch spends its wall time: dispatching the fused jit
step, blocking on the stats fetch, advancing the window (fold + flush
dispatch), draining packed flush rows, saving checkpoints. This module
is that seam: a monotonic-clock span recorder with a fixed vocabulary
of stage names, cheap enough to stay always-on (two perf_counter calls
per span), exposing three faces:

  * `summary()` — per-stage count/total/max/last aggregates for bench
    JSON snapshots (BENCH files carry stage attribution);
  * `get_counters()` — a flat Countable field map so the tracer
    registers on `utils/stats.StatsCollector` like any component and
    its aggregates dogfood into the `deepflow_system` table;
  * `export_otlp(exporter)` — drains the recent-span ring through the
    EXISTING OTLP exporter path (server/exporters.OtlpExporter's
    l7_flow_log traces lane), so pipeline stages show up as spans in
    whatever trace backend the exporter points at — including our own
    IntegrationCollector round-trip.

`JitCacheMonitor` rides along: retrace/compile counters for one jitted
callable, read from the pjit cache size — the CI gate asserts ZERO
retraces across steady-state same-shape ingest so a shape leak (the
silent compile-per-batch failure mode) trips loudly.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from contextlib import contextmanager

import numpy as np

# The pipeline stage vocabulary (explicit names, ISSUE 3). Everything
# the window managers emit uses these; ad-hoc names are allowed but the
# docs/tests pin this set.
SPAN_INGEST_DISPATCH = "ingest.dispatch"  # fused jit step dispatch (async — host-side cost)
SPAN_STATS_FETCH = "stats.fetch"  # the ONE per-batch device→host stats sync
SPAN_WINDOW_ADVANCE = "window.advance"  # fold + flush_range dispatch on window close
# fold dispatch alone (capacity-triggered AND the advance's span fold) —
# nested inside window.advance when the advance fires it, so the
# fold-dominated share of drain_ms is attributable on its own (ISSUE 5;
# this is the lane the merge-fold exists to shrink)
SPAN_WINDOW_FOLD = "window.fold"
SPAN_FLUSH_DRAIN = "flush.drain"  # packed flush fetch + per-window split
SPAN_CHECKPOINT_SAVE = "checkpoint.save"  # window-state snapshot to .npz
# live read plane (ISSUE 10): pull-only open-window snapshot reads and
# result-cache lookups — separate names so a live dashboard's read
# latency is attributable on its own instead of hiding in flush.drain
SPAN_QUERY_SNAPSHOT = "query.snapshot"  # snapshot_open: fold + 2-fetch read
SPAN_QUERY_CACHE = "query.cache"  # result-cache lookup (hit or miss)

# Feeder-runtime stages (ISSUE 4) — emitted by feeder/runtime.py on its
# own tracer; NOT in PIPELINE_SPAN_NAMES (a pipeline can run feederless,
# and the pinned vocabulary must stay satisfiable by a bare pipeline).
SPAN_FEEDER_DRAIN = "feeder.drain"  # queue gets + frame decode
SPAN_FEEDER_COALESCE = "feeder.coalesce"  # bucket assembly + pad
SPAN_FEEDER_DISPATCH = "feeder.dispatch"  # staged batch → sink ingest

# Push query plane (ISSUE 11) — emitted by querier/subscribe.py and
# querier/alerts.py on their own tracers; also not pipeline vocabulary
# (a pipeline can run with no standing queries). One span per
# subscription/rule evaluation, so fan-out latency (flush → watcher
# delivery) is attributable separately from the pull path's
# query.snapshot/query.cache lanes.
SPAN_SUBSCRIPTION_EVAL = "subscribe.eval"  # one shared eval serving N watchers
SPAN_ALERT_EVAL = "alert.eval"  # rule query + state-machine step

PIPELINE_SPAN_NAMES = (
    SPAN_INGEST_DISPATCH,
    SPAN_STATS_FETCH,
    SPAN_WINDOW_ADVANCE,
    SPAN_WINDOW_FOLD,
    SPAN_FLUSH_DRAIN,
    SPAN_CHECKPOINT_SAVE,
    SPAN_QUERY_SNAPSHOT,
    SPAN_QUERY_CACHE,
)


@dataclasses.dataclass(frozen=True)
class SpanRecord:
    name: str
    start_s: float  # wall-clock epoch seconds (for export timestamps)
    duration_us: int  # monotonic-clock measured
    # window-lineage context (ISSUE 13): when a stage span belongs to a
    # window's lineage trace, these carry the DERIVED ids
    # (tracing/lineage.window_trace_id — the window id IS the context)
    # and export_otlp emits them instead of synthesizing singleton ids;
    # `window` is the per-window correlation key ("<idx>@<interval>s").
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""
    window: str = ""


@dataclasses.dataclass(frozen=True)
class SpanHistSpec:
    """Log-binned per-stage latency histogram geometry (ISSUE 12) — the
    numpy twin of ops/histogram.LogHistSpec (same bin(v) =
    floor(log_gamma(v / vmin)) algebra, same (gamma-1)/(gamma+1)
    relative-error bound), kept jax-free so the tracer stays importable
    from host-only components (agent, querier threads). The default
    covers 1 µs .. ~640 s at ≤1% relative error in 1024 i64 bins
    (8 KB per stage)."""

    bins: int = 1024
    vmin: float = 1.0  # µs; durations at/below land in bin 0
    gamma: float = 1.02

    def bin(self, duration_us: float) -> int:
        import math

        v = max(float(duration_us), self.vmin)
        b = int(math.floor(math.log(v / self.vmin) / math.log(self.gamma)))
        return min(max(b, 0), self.bins - 1)

    def centers(self) -> np.ndarray:
        return self.vmin * np.power(
            float(self.gamma), np.arange(self.bins, dtype=np.float64) + 0.5
        )


def loghist_quantiles_np(
    hist: np.ndarray, spec: SpanHistSpec, qs: tuple[float, ...]
) -> np.ndarray:
    """Pure-numpy quantiles over one [bins] log-histogram — the same
    cumsum + rank-threshold walk as ops/histogram.loghist_quantiles,
    evaluated host-side so the Countable face never dispatches to a
    device. Returns zeros for an empty histogram (no fake series)."""
    cum = np.cumsum(hist.astype(np.float64))
    total = cum[-1]
    if total <= 0:
        return np.zeros(len(qs))
    centers = spec.centers()
    out = np.empty(len(qs))
    for i, q in enumerate(qs):
        idx = int(np.searchsorted(cum, q * total, side="left"))
        out[i] = centers[min(idx, spec.bins - 1)]
    return out


#: the quantiles the Countable face exports per stage (deepflow_system
#: metric names: <module>_<stage>_p50_us / _p95_us / _p99_us — the lanes
#: span-latency alert rules key on, ISSUE 12)
SPAN_QUANTILES = (0.5, 0.95, 0.99)


class _Agg:
    __slots__ = ("count", "total_us", "max_us", "last_us", "hist")

    def __init__(self, bins: int):
        self.count = 0
        self.total_us = 0
        self.max_us = 0
        self.last_us = 0
        # per-stage log-histogram (ISSUE 12): updated together with the
        # scalar aggregates — callers hold the tracer lock, so the
        # read-modify-write on the bin counter cannot lose updates under
        # concurrent feeder-pump + query threads
        self.hist = np.zeros(bins, np.int64)

    def add(self, dur_us: int, bin_idx: int) -> None:
        self.count += 1
        self.total_us += dur_us
        self.last_us = dur_us
        if dur_us > self.max_us:
            self.max_us = dur_us
        self.hist[bin_idx] += 1


class SpanTracer:
    """Monotonic-clock stage spans: aggregates + per-stage log-histograms
    always, ring for export."""

    def __init__(self, service: str = "deepflow_tpu.pipeline", ring_size: int = 2048,
                 hist_spec: SpanHistSpec = SpanHistSpec()):
        self.service = service
        self.hist_spec = hist_spec
        self._ring: deque[SpanRecord] = deque(maxlen=ring_size)
        self._agg: dict[str, _Agg] = {}
        self._lock = threading.Lock()
        self._seq = 0

    @contextmanager
    def span(self, name: str):
        wall = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, int((time.perf_counter() - t0) * 1e6), start_s=wall)

    def record(self, name: str, duration_us: int, start_s: float | None = None,
               *, trace_id: str = "", span_id: str = "",
               parent_span_id: str = "", window: str = ""):
        """Record a pre-measured span — for stages whose work is split
        across non-contiguous host sections (e.g. the sharded advance:
        sketch close before the append, fold after) that must count as
        ONE logical span so cross-path stage attribution compares.
        Optional trace/parent ids + the per-window correlation key ride
        into the export ring (ISSUE 13: lineage-context stage spans)."""
        rec = SpanRecord(name, time.time() if start_s is None else start_s,
                         int(duration_us), trace_id=trace_id, span_id=span_id,
                         parent_span_id=parent_span_id, window=window)
        # the bin is computed outside the lock (pure math), but EVERY
        # aggregate mutation — scalar lanes and the histogram counter —
        # happens under the tracer lock: record() runs concurrently from
        # feeder-pump and query threads, and an unlocked += on the
        # histogram would silently lose samples (ISSUE 12 satellite,
        # pinned by tests/test_profiling.py::test_span_tracer_threaded).
        bin_idx = self.hist_spec.bin(rec.duration_us)
        with self._lock:
            self._ring.append(rec)
            agg = self._agg.get(name)
            if agg is None:
                agg = self._agg[name] = _Agg(self.hist_spec.bins)
            agg.add(rec.duration_us, bin_idx)

    # -- read faces -----------------------------------------------------
    def summary(self) -> dict[str, dict]:
        """Per-stage aggregates, JSON-able (the bench snapshot shape) —
        now with the log-histogram quantiles (ISSUE 12), so BENCH files
        carry p50/p95/p99 stage attribution next to count/avg/max."""
        with self._lock:
            out = {}
            for name, a in sorted(self._agg.items()):
                qv = loghist_quantiles_np(a.hist, self.hist_spec, SPAN_QUANTILES)
                out[name] = {
                    "count": a.count,
                    "total_us": a.total_us,
                    "avg_us": round(a.total_us / a.count, 1) if a.count else 0.0,
                    "max_us": a.max_us,
                    "last_us": a.last_us,
                    **{
                        f"p{int(q * 100)}_us": round(float(v), 1)
                        for q, v in zip(SPAN_QUANTILES, qv)
                    },
                }
            return out

    def hist_dump(self) -> dict[str, list[list[int]]]:
        """stage → nonzero (bin, count) pairs — the same compact shape
        `FreshnessTracker.hist_dump` emits, so span latency histograms
        ride the fleet frame and merge bin-for-bin across hosts
        (histograms add; quantile summaries don't)."""
        with self._lock:
            return {
                name: [
                    [int(b), int(a.hist[b])]
                    for b in np.nonzero(a.hist)[0]
                ]
                for name, a in sorted(self._agg.items())
            }

    def quantiles(
        self, name: str, qs: tuple[float, ...] = SPAN_QUANTILES
    ) -> np.ndarray | None:
        """Per-stage latency quantiles (µs) from the log-histogram —
        pure numpy, no device access. None when the stage never ran."""
        with self._lock:
            a = self._agg.get(name)
            hist = None if a is None else a.hist.copy()
        if hist is None:
            return None
        return loghist_quantiles_np(hist, self.hist_spec, qs)

    def tdigest(self, name: str, compression: int = 64):
        """(means, weights) centroid export of one stage's latency
        histogram — the same loghist→t-digest compression the r12
        sketch blocks use (ops/tdigest.tdigest_from_loghist). Dispatches
        the jitted compressor on a tiny fixed-size array: OFF the
        Countable face, for wire/bench export only. None when the stage
        never ran."""
        with self._lock:
            a = self._agg.get(name)
            hist = None if a is None else a.hist.copy()
        if hist is None:
            return None
        import jax.numpy as jnp  # lazy: the tracer itself stays jax-free

        from ..ops.histogram import LogHistSpec
        from ..ops.tdigest import tdigest_from_loghist

        spec = LogHistSpec(bins=self.hist_spec.bins, vmin=self.hist_spec.vmin,
                           gamma=self.hist_spec.gamma)
        m, w = tdigest_from_loghist(
            jnp.asarray(hist[None, :], jnp.int32), spec, compression=compression
        )
        return np.asarray(m[0]), np.asarray(w[0])

    def get_counters(self) -> dict[str, int | float]:
        """Countable face: flat `<stage>.count/.total_us/.max_us` fields
        plus the log-histogram p50/p95/p99 lanes (ISSUE 12) — dogfooded
        via integration/dfstats into deepflow_system, where
        `ingest.dispatch.p99_us` becomes the
        `tpu_pipeline_spans_ingest_dispatch_p99_us` metric a span-latency
        alert rule keys on. Pure numpy, fetch-free, safe from a ticking
        collector thread."""
        with self._lock:
            aggs = [(name, a.count, a.total_us, a.max_us, a.hist.copy())
                    for name, a in sorted(self._agg.items())]
        out: dict[str, int | float] = {}
        for name, count, total_us, max_us, hist in aggs:
            out[f"{name}.count"] = count
            out[f"{name}.total_us"] = total_us
            out[f"{name}.max_us"] = max_us
            qv = loghist_quantiles_np(hist, self.hist_spec, SPAN_QUANTILES)
            for q, v in zip(SPAN_QUANTILES, qv):
                out[f"{name}.p{int(q * 100)}_us"] = round(float(v), 1)
        return out

    def recent(self, name: str | None = None) -> list[SpanRecord]:
        with self._lock:
            recs = list(self._ring)
        if name is not None:
            recs = [r for r in recs if r.name == name]
        return recs

    def drain(self) -> list[SpanRecord]:
        """Pop and return the ring (export-once semantics)."""
        with self._lock:
            recs = list(self._ring)
            self._ring.clear()
        return recs

    # -- OTLP export ------------------------------------------------------
    def export_otlp(self, exporter, *, table: str = "l7_flow_log") -> int:
        """Drain the span ring through an exporter's traces lane.

        Builds l7_flow_log-shaped columns (app_service/endpoint/
        start_time/response_duration + trace ids) and hands them to
        `exporter.export(table, cols)` — the same path every other
        trace row takes (server/exporters.OtlpExporter turns each row
        into an OTel span). Returns the span count exported."""
        recs = self.drain()
        if not recs:
            return 0
        with self._lock:
            seq0 = self._seq
            self._seq += len(recs)
        n = len(recs)
        cols = {
            "time": np.asarray([int(r.start_s) for r in recs], np.uint32),
            "start_time": np.asarray([int(r.start_s) for r in recs], np.uint32),
            "response_duration": np.asarray(
                [r.duration_us for r in recs], np.uint32
            ),
            "app_service": np.asarray([self.service] * n),
            # the window correlation key (when set) suffixes the
            # endpoint so per-window stage spans stay distinguishable
            # in the trace backend
            "endpoint": np.asarray(
                [f"{r.name}:{r.window}" if r.window else r.name for r in recs]
            ),
            # records carrying lineage context keep their DERIVED ids;
            # plain stage spans synthesize singleton ids as before
            "trace_id": np.asarray(
                [r.trace_id or f"{seq0 + i + 1:032x}"
                 for i, r in enumerate(recs)]
            ),
            "span_id": np.asarray(
                [r.span_id or f"{seq0 + i + 1:016x}"
                 for i, r in enumerate(recs)]
            ),
            "parent_span_id": np.asarray([r.parent_span_id for r in recs]),
        }
        exporter.export(table, cols)
        return n


class JitCacheMonitor:
    """Compile/retrace counters for ONE jitted callable.

    Reads the pjit executable-cache size (`fn._cache_size()`): the first
    `expected_compiles` entries are expected compiles (one per declared
    input shape — a shape-bucketed feeder legitimately compiles the
    fused step once per bucket), every further entry is a RETRACE — a
    shape/dtype/static-arg leak recompiling what steady state should
    reuse. `poll()` is cheap (no device sync); call it after each
    dispatch. Degrades to zeros on jax builds without the cache probe.
    """

    def __init__(self, fn=None, expected_compiles: int = 1):
        self._fn = fn
        self._size = 0
        self.expected_compiles = max(1, int(expected_compiles))
        self.compiles = 0
        self.retraces = 0
        # poll() runs from the ingest loop AND a ticking StatsCollector
        # thread (the pipeline registers itself); the read-modify-write
        # on _size must not double-count one cache growth
        self._lock = threading.Lock()

    def attach(self, fn) -> None:
        """Point at a (new) jitted callable; cumulative counts survive."""
        with self._lock:
            self._fn = fn
            self._size = 0

    def poll(self) -> tuple[int, int]:
        """→ (compiles, retraces), updated from the current cache size."""
        with self._lock:
            if self._fn is not None:
                try:
                    size = int(self._fn._cache_size())
                except Exception:  # pragma: no cover - probe-less jax build
                    size = self._size
                grew = size - self._size
                while grew > 0 and self.compiles < self.expected_compiles:
                    self.compiles += 1
                    grew -= 1
                if grew > 0:
                    self.retraces += grew
                self._size = size
            return self.compiles, self.retraces

    def get_counters(self) -> dict[str, int]:
        self.poll()
        return {"jit_compiles": self.compiles, "jit_retraces": self.retraces}
