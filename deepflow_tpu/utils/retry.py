"""Bounded retry with exponential backoff + jitter — the transient-
failure policy shared by the device dispatch, host-fetch and transport
paths (ISSUE 6).

The reference retries transient infrastructure errors everywhere it
talks to something that can hiccup (ckwriter reconnect+retry,
uniform_sender failover, grpc session redial) and treats everything
else as fatal-but-contained. This module is that policy as one
function: classify, back off exponentially with jitter (decorrelated
retries — N feeders must not re-dial a recovering device in lockstep),
give up after a bounded number of attempts.

Retrying a DEVICE dispatch is only sound when the failure pre-empted
the call: the fused steps donate their accumulator buffers, so an
error thrown mid-execution leaves the donated input consumed. The
transient classification therefore covers admission-time failures —
RESOURCE_EXHAUSTED-style allocator rejections, queue-full, timeouts —
plus the chaos module's injected faults (which always fire before the
real call); a mid-flight device loss is NOT transient and surfaces to
the containment layer (feeder degraded mode) instead. Because the
runtime reports both kinds through message substrings, there are TWO
classifiers: is_transient (fetch/transport — no donation, the broad
marker set applies) and is_dispatch_transient (donated-buffer
dispatch — admission-time codes only).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import random
import time

_rng_seq = itertools.count()


def decorrelated_rng(tag: int) -> random.Random:
    """Jitter rng for one retrying instance: seeded from a caller tag,
    the pid and a process-wide instance counter, so N managers (or N
    processes) backing off against one recovering device never share a
    jitter stream — identical streams re-dial in lockstep, the exact
    thundering herd the jitter exists to break."""
    return random.Random((tag << 40) ^ (os.getpid() << 20) ^ next(_rng_seq))

# Substrings of runtime error text treated as transient. XLA runtime
# errors carry their absl status code in the message; these are the
# codes that mean "the device/tunnel may accept the same call shortly".
TRANSIENT_ERROR_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
)


class TransientError(Exception):
    """Failures that are retryable by construction (admission-time:
    the operation never started). The chaos module's transient fault
    classes subclass this."""


def is_transient(exc: BaseException) -> bool:
    """The shared retry classification: our TransientError taxonomy,
    plus runtime errors whose status code says try-again. For
    donated-buffer DISPATCH calls use is_dispatch_transient instead."""
    if isinstance(exc, TransientError):
        return True
    if isinstance(exc, Exception):
        msg = str(exc)
        return any(m in msg for m in TRANSIENT_ERROR_MARKERS)
    return False


# Dispatch-only markers: UNAVAILABLE/ABORTED can be a MID-FLIGHT
# device loss, after the step consumed its donated accumulator — a
# retry would then fail on a deleted array and mask the real error.
# Only codes that by construction reject the call at admission time
# (allocator/queue rejections, deadline before launch) are safe.
DISPATCH_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "DEADLINE_EXCEEDED",
)


def is_dispatch_transient(exc: BaseException) -> bool:
    """Admission-time-only classification for the donated-buffer
    dispatch paths: our TransientError taxonomy (the chaos seam fires
    before the real call) plus admission-time status codes. The fetch
    path keeps the broader is_transient — a blown fetch deadline
    leaves the device handle valid."""
    if isinstance(exc, TransientError):
        return True
    if isinstance(exc, Exception):
        msg = str(exc)
        return any(m in msg for m in DISPATCH_TRANSIENT_MARKERS)
    return False


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """attempts = TOTAL tries (1 = no retry). Delay for retry k
    (k=1..attempts-1) is min(base * multiplier**(k-1), max) scaled by a
    uniform jitter in [1-jitter, 1]."""

    attempts: int = 4
    base_delay_s: float = 0.005
    max_delay_s: float = 0.5
    multiplier: float = 2.0
    jitter: float = 0.5

    def delay(self, attempt: int, rng: random.Random) -> float:
        # clamp the exponent before exponentiating: callers feed
        # unbounded failstreaks in here (serve()'s crash-loop guard),
        # and float ** raises OverflowError past ~2.0**1024 — the
        # min() with max_delay_s saturates the result long before 64
        # doublings for any sane policy, so the cap never changes it
        d = min(self.base_delay_s * self.multiplier ** min(attempt - 1, 64),
                self.max_delay_s)
        return d * (1.0 - self.jitter * rng.random())


def retry_call(
    fn,
    policy: RetryPolicy = RetryPolicy(),
    *,
    classify=is_transient,
    on_retry=None,
    rng: random.Random | None = None,
    sleep=time.sleep,
):
    """Call `fn()`; on a transient failure, back off and retry up to
    policy.attempts total tries. Non-transient errors (and BaseException
    kill-points from the chaos harness) propagate immediately —
    containment above this layer decides what survives. `on_retry(k,
    exc)` fires before each retry so owners can count them."""
    rng = rng if rng is not None else random
    last = None
    for attempt in range(1, max(1, policy.attempts) + 1):
        try:
            return fn()
        except Exception as exc:
            if attempt > policy.attempts - 1 or not classify(exc):
                raise
            last = exc
            if on_retry is not None:
                on_retry(attempt, exc)
            sleep(policy.delay(attempt, rng))
    raise last  # pragma: no cover - loop always returns or raises
