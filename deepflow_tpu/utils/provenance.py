"""Bench provenance stamp (ISSUE 18 satellite).

Every bench JSON embeds the exact config it measured: git SHA (+dirty
flag), platform identity, and a snapshot of the `DEEPFLOW_*` env knobs
(plus the JAX platform pin) — so a PERF.md column is attributable to a
commit and a knob set instead of "whatever the box had that day".
"""

from __future__ import annotations


def bench_provenance() -> dict:
    import os
    import platform
    import subprocess
    import time

    sha = None
    dirty = None
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            timeout=10, cwd=here,
        ).stdout.strip() or None
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain"], capture_output=True,
            text=True, timeout=10, cwd=here,
        ).stdout.strip())
    except Exception:
        pass  # benches must run from an exported tree too
    out = {
        "git_sha": sha,
        "git_dirty": dirty,
        "time": int(time.time()),
        "platform": {
            "python": platform.python_version(),
            "system": platform.system(),
            "release": platform.release(),
            "machine": platform.machine(),
            "cpus": os.cpu_count(),
        },
        # the knob snapshot: every DEEPFLOW_* flag (shared-sort, fused
        # sketch, merge-scatter, …) plus the backend pin — the flip
        # decisions PERF.md tracks hinge on exactly these
        "env": {
            k: v for k, v in sorted(os.environ.items())
            if k.startswith("DEEPFLOW_") or k == "JAX_PLATFORMS"
        },
    }
    try:
        import jax
        import jaxlib

        out["platform"]["jax"] = jax.__version__
        out["platform"]["jaxlib"] = jaxlib.__version__
    except Exception:
        pass
    return out
