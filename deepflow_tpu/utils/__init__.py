"""Cross-cutting utilities: self-telemetry counters and config."""
