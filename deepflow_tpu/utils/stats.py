"""Self-telemetry counter registry — the universal Countable pattern.

Every component in the reference registers a `RefCountable`/`Countable`
with a stats collector that periodically snapshots counters and ships them
as `deepflow_stats` points into its own ext_metrics pipeline
(server/libs/stats/stats.go:89-202; agent/src/utils/stats.rs). This module
is the framework-wide twin: components expose `get_counters()` dicts; the
collector holds *weak* references (a dropped component unregisters itself,
the RefCountable semantics), ticks on an interval, and hands batched
`StatsPoint`s to pluggable sinks — in-memory ring for the debug tap, and
the ext_metrics ingester once it exists.

Counter naming follows the reference convention: a point per (module,
tags) with an integer/float field map.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import weakref
from collections import deque
from typing import Callable, Mapping, Protocol, runtime_checkable

_log = logging.getLogger(__name__)


@runtime_checkable
class Countable(Protocol):
    def get_counters(self) -> Mapping[str, int | float]: ...


@dataclasses.dataclass(frozen=True)
class StatsPoint:
    timestamp: float
    module: str
    tags: tuple[tuple[str, str], ...]
    fields: dict[str, int | float]


class CounterSource:
    """One registered countable: weakly held, tagged."""

    __slots__ = ("module", "tags", "_ref", "_fn", "failures", "cooldown",
                 "suppressed", "lock")

    def __init__(self, module: str, tags: dict[str, str], countable):
        self.module = module
        self.tags = tuple(sorted(tags.items()))
        self.failures = 0  # consecutive get_counters() exceptions
        self.cooldown = 0  # ticks to skip before the next re-probe
        self.suppressed = False  # entered backoff (warning already logged)
        # guards the failure/cooldown/suppressed bookkeeping: the tick
        # thread and pull-path sample() callers (live queries, the fleet
        # exporter) race on the same source, and unlocked check-then-act
        # would lose failure counts or double-count recoveries
        self.lock = threading.Lock()
        if callable(countable) and not isinstance(countable, Countable):
            # plain closures can't be weakly bound to a component lifetime;
            # hold them strongly (caller owns deregistration)
            self._ref = None
            self._fn = countable
        else:
            self._ref = weakref.ref(countable)
            self._fn = None

    def dead(self) -> bool:
        """Weakly-bound component already collected (callable sources
        are owner-deregistered, never dead)."""
        return self._ref is not None and self._ref() is None

    def sample(self) -> Mapping[str, int | float] | None:
        if self._fn is not None:
            return self._fn()
        obj = self._ref()
        if obj is None:
            return None
        return obj.get_counters()


class StatsCollector:
    """Periodic counter snapshotter with pluggable sinks.

    `register(module, countable, **tags)` — countable is either an object
    with `get_counters()` (weakly referenced; auto-deregistered when the
    component is garbage collected) or a zero-arg callable returning the
    counter map (strongly held; `deregister` to remove).
    """

    # consecutive sample failures before a source enters backoff
    # (warning logged once on entry)
    MAX_SOURCE_FAILURES = 3
    # re-probe backoff cap, in ticks: a broken source is probed at
    # 1, 2, 4, … up to this many ticks apart — never dropped for good
    # (ISSUE 6: a component that recovers, e.g. after a device comes
    # back, must resume reporting without a process restart)
    MAX_BACKOFF_TICKS = 64

    def __init__(self, interval_s: float = 10.0, ring_size: int = 4096):
        self.interval_s = interval_s
        self.n_source_errors = 0  # total get_counters() exceptions seen
        self.n_source_recoveries = 0  # sources that came back from backoff
        self.n_sink_errors = 0  # sink callback exceptions (contained)
        self._sources: list[CounterSource] = []
        self._sinks: list[Callable[[list[StatsPoint]], None]] = []
        self._ring: deque[StatsPoint] = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- registry -------------------------------------------------------
    def register(self, module: str, countable, **tags: str) -> CounterSource:
        src = CounterSource(module, tags, countable)
        with self._lock:
            # prune dead weakrefs here too: components auto-register at
            # construction (pipelines, exporters), so a process that
            # never ticks must not grow the source list unboundedly
            self._sources = [s for s in self._sources if not s.dead()]
            self._sources.append(src)
        return src

    def deregister(self, src: CounterSource) -> None:
        with self._lock:
            if src in self._sources:
                self._sources.remove(src)

    def add_sink(self, sink: Callable[[list[StatsPoint]], None]) -> None:
        with self._lock:
            self._sinks.append(sink)

    def remove_sink(self, sink: Callable[[list[StatsPoint]], None]) -> None:
        """Detach a sink (a stopped server's ProfileSnapshot publisher
        must not keep firing events on a bus nobody drains)."""
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    # -- ticking --------------------------------------------------------
    def sample(
        self, now: float | None = None, *, _advance_backoff: bool = False
    ) -> list[StatsPoint]:
        """Snapshot every live source once WITHOUT sinking or ringing —
        the pull-time read the live query plane uses (ISSUE 10:
        integration/dfstats.live_system_source answers a query at
        sub-tick latency from the CURRENT counters; writing those rows
        through the sinks would turn every query into a store insert).
        Shares tick()'s failure accounting, but only tick() ADVANCES
        the backoff clock (`_advance_backoff`): a broken source's
        capped-exponential re-probe spacing is measured in collector
        ticks, and dashboard-rate pulls must neither drain it in
        seconds nor hammer the broken source on the query path — while
        backing off, pulls skip it without touching the cooldown."""
        now = time.time() if now is None else now
        points: list[StatsPoint] = []
        with self._lock:
            sources = list(self._sources)
        dead: list[CounterSource] = []
        for src in sources:
            if src.dead():
                dead.append(src)
                continue
            with src.lock:
                if src.cooldown > 0:  # backing off — skip this round
                    if _advance_backoff:
                        src.cooldown -= 1
                    continue
            try:
                fields = src.sample()
            except Exception:
                with self._lock:
                    self.n_source_errors += 1
                with src.lock:
                    src.failures += 1
                    failures = src.failures
                    entered_backoff = False
                    if failures >= self.MAX_SOURCE_FAILURES:
                        src.cooldown = min(
                            1 << (failures - self.MAX_SOURCE_FAILURES),
                            self.MAX_BACKOFF_TICKS,
                        )
                        if not src.suppressed:
                            src.suppressed = True
                            entered_backoff = True
                if entered_backoff:
                    _log.warning(
                        "stats source %s%s backing off after %d "
                        "consecutive sample errors (re-probed with "
                        "capped exponential spacing)",
                        src.module, dict(src.tags) or "", failures,
                        exc_info=True,
                    )
                continue
            with src.lock:
                recovered = src.suppressed
                failures = src.failures
                src.suppressed = False
                src.failures = 0
                src.cooldown = 0
            if recovered:  # came back from backoff
                with self._lock:
                    self.n_source_recoveries += 1
                _log.warning(
                    "stats source %s%s recovered after %d consecutive "
                    "sample errors", src.module, dict(src.tags) or "",
                    failures,
                )
            if fields is None:  # component died → auto-deregister
                dead.append(src)
                continue
            if fields:
                points.append(StatsPoint(now, src.module, src.tags, dict(fields)))
        with self._lock:
            if dead:
                self._sources = [s for s in self._sources if s not in dead]
        return points

    def tick(self, now: float | None = None) -> list[StatsPoint]:
        """`sample()` + sinks + ring (also called by the thread).

        Samples run outside the lock (a callback may register/deregister)
        and are exception-guarded — one broken component must not kill
        self-telemetry for the rest. Failures are COUNTED
        (`n_source_errors`); a source that fails MAX_SOURCE_FAILURES
        times in a row enters capped-exponential BACKOFF (one warning
        log) and keeps being re-probed at 1, 2, 4, …, MAX_BACKOFF_TICKS
        tick spacing instead of being dropped — a component whose
        dependency comes back (a reconnected store, a recovered device)
        resumes reporting, with the recovery counted and logged once
        (`n_source_recoveries`). Sink callbacks are guarded the same
        way (`n_sink_errors`): a broken export loop must not kill the
        collector thread.
        """
        points = self.sample(now, _advance_backoff=True)
        with self._lock:
            sinks = list(self._sinks)
            self._ring.extend(points)
        for sink in sinks:
            try:
                sink(points)
            except Exception:
                with self._lock:
                    self.n_sink_errors += 1
                _log.warning("stats sink %r failed; points dropped for "
                             "this tick", sink, exc_info=True)
        return points

    def recent(self, module: str | None = None) -> list[StatsPoint]:
        with self._lock:
            pts = list(self._ring)
        if module is not None:
            pts = [p for p in pts if p.module == module]
        return pts

    # -- thread ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval_s + 1)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()


# Default process-wide collector, mirroring the reference's package-level
# RegisterCountable entry points (stats.go:89).
default_collector = StatsCollector()


def register_countable(module: str, countable, **tags: str) -> CounterSource:
    return default_collector.register(module, countable, **tags)
