"""PromQL subset over the prometheus.samples table — the app/prometheus
seat (the reference compiles PromQL onto its CK engine; we evaluate
directly).

Supported:  [agg by (l1, l2)] (metric{label="v", label!="v"})
            and rate(metric{...}[Ns])  inside the aggregation,
            topk(k, metric{...}) / bottomk(k, metric{...}) — the
            heavy-hitter surface the sketch tier feeds (ISSUE 8:
            topk(5, deepflow_sketch_top_bytes) ranks the invertible
            sketch's recovered flows without any exact-row scan)
Instant queries: evaluate at time `t` with a lookback window (last
sample per series wins, Prometheus staleness semantics simplified).
Range queries: query_range evaluates the instant expression at each
step over [start, end] and returns per-series value arrays — the
/api/v1/query_range shape.
"""

from __future__ import annotations

import re

import numpy as np

from ..storage.store import ColumnarStore

_QUERY_RE = re.compile(
    r"^\s*(?:(?P<agg>sum|avg|max|min|count)\s*(?:by\s*\((?P<by>[^)]*)\)\s*)?\(\s*)?"
    r"(?:(?P<topk>topk|bottomk)\s*\(\s*(?P<k>\d+)\s*,\s*)?"
    r"(?:(?P<rate>rate)\s*\(\s*)?"
    r"(?P<metric>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<matchers>[^}]*)\})?"
    r"(?:\[(?P<range>\d+)(?P<range_unit>[smh])\])?"
    r"(?:\s*\))?(?:\s*\))?(?:\s*\))?\s*$"
)

_UNIT_S = {"s": 1, "m": 60, "h": 3600}


class PromQLError(ValueError):
    pass


def _parse_matchers(text: str | None) -> list[tuple[str, str, str]]:
    out = []
    if not text:
        return out
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.match(r'^([a-zA-Z_][a-zA-Z0-9_]*)\s*(=~|!=|=)\s*"([^"]*)"$', part)
        if not m:
            raise PromQLError(f"bad matcher {part!r}")
        out.append((m.group(1), m.group(2), m.group(3)))
    return out


from ..integration.formats import unpack_tags as _label_dict


def query_instant(
    store: ColumnarStore,
    query: str,
    t: int,
    *,
    lookback_s: int = 300,
    db: str = "prometheus",
    table: str = "samples",
) -> list[dict]:
    """→ [{"labels": {...}, "value": float}] — instant vector at time t.

    `db`/`table` default to the remote-write store; pass
    db="deepflow_system", table="deepflow_system" to evaluate over the
    framework's own dogfooded telemetry (integration/dfstats) — the
    table shares the samples row shape by construction."""
    m = _QUERY_RE.match(query)
    if not m:
        raise PromQLError(f"unsupported query {query!r}")
    if query.count("(") != query.count(")"):
        # the regex's optional close-paren groups would otherwise let a
        # typo ("topk(5, m" / "sum(m))") parse and silently answer
        raise PromQLError(f"unbalanced parentheses in {query!r}")
    agg = m.group("agg")
    by = [s.strip() for s in (m.group("by") or "").split(",") if s.strip()]
    is_rate = bool(m.group("rate"))
    window = (
        int(m.group("range")) * _UNIT_S[m.group("range_unit")]
        if m.group("range")
        else lookback_s
    )
    matchers = _parse_matchers(m.group("matchers"))
    if is_rate and not m.group("range"):
        raise PromQLError("rate() needs a [range]")

    cols = store.scan(db, table, time_range=(t - window, t + 1))
    sel = cols["metric"] == m.group("metric")
    labels_packed = cols["labels"]
    rows = np.nonzero(sel)[0]
    series: dict[str, list[tuple[int, float]]] = {}
    for i in rows:
        packed = str(labels_packed[i])
        lab = _label_dict(packed)
        keep = True
        for name, op, val in matchers:
            have = lab.get(name, "")
            if op == "=" and have != val:
                keep = False
            elif op == "!=" and have == val:
                keep = False
            elif op == "=~" and not re.fullmatch(val, have):
                keep = False
        if keep:
            series.setdefault(packed, []).append(
                (int(cols["time"][i]), float(cols["value"][i]))
            )

    # per-series instant value
    per_series: dict[str, float] = {}
    for packed, samples in series.items():
        samples.sort()
        if is_rate:
            if len(samples) < 2:
                continue
            dt = samples[-1][0] - samples[0][0]
            # counter-reset correction (Prometheus extrapolatedRate): a
            # decrease means the counter restarted from ~0, so the true
            # increase across the reset is the new value itself
            dv = 0.0
            for (_, prev), (_, cur) in zip(samples, samples[1:]):
                dv += cur - prev if cur >= prev else cur
            per_series[packed] = dv / dt if dt > 0 else 0.0
        else:
            per_series[packed] = samples[-1][1]

    if m.group("topk"):
        # topk/bottomk(k, inner): keep the k extreme series, then fall
        # through to an (optional) outer aggregation over the survivors
        k = int(m.group("k"))
        sign = -1.0 if m.group("topk") == "topk" else 1.0
        keep = sorted(per_series.items(), key=lambda kv: (sign * kv[1], kv[0]))[:k]
        per_series = dict(keep)
        if agg is None:
            # rank order, not label order — the whole point of topk
            return [{"labels": _label_dict(p), "value": v} for p, v in keep]

    if agg is None:
        return [
            {"labels": _label_dict(p), "value": v} for p, v in sorted(per_series.items())
        ]
    groups: dict[tuple, list[float]] = {}
    for packed, v in per_series.items():
        lab = _label_dict(packed)
        key = tuple((b, lab.get(b, "")) for b in by)
        groups.setdefault(key, []).append(v)
    out = []
    for key, vals in sorted(groups.items()):
        if agg == "sum":
            v = sum(vals)
        elif agg == "avg":
            v = sum(vals) / len(vals)
        elif agg == "max":
            v = max(vals)
        elif agg == "min":
            v = min(vals)
        else:
            v = float(len(vals))
        out.append({"labels": dict(key), "value": v})
    return out


def query_range(
    store: ColumnarStore,
    query: str,
    start: int,
    end: int,
    step: int,
    *,
    lookback_s: int = 300,
    db: str = "prometheus",
    table: str = "samples",
) -> list[dict]:
    """Matrix result: [{"labels": {...}, "values": [[t, v], ...]}] — the
    /api/v1/query_range evaluation (each step is an instant evaluation,
    which is exactly Prometheus's range-query semantics)."""
    if step <= 0:
        raise PromQLError("step must be positive")
    if end < start:
        raise PromQLError("end < start")
    series: dict[tuple, dict] = {}
    for t in range(start, end + 1, step):
        for row in query_instant(
            store, query, t, lookback_s=lookback_s, db=db, table=table
        ):
            key = tuple(sorted(row["labels"].items()))
            s = series.get(key)
            if s is None:
                s = series[key] = {"labels": row["labels"], "values": []}
            s["values"].append([t, row["value"]])
    return [series[k] for k in sorted(series)]
