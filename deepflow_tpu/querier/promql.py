"""PromQL subset over the prometheus.samples table — the app/prometheus
seat (the reference compiles PromQL onto its CK engine; we evaluate
directly).

Supported:  [agg by (l1, l2)] (metric{label="v", label!="v"})
            and rate(metric{...}[Ns])  inside the aggregation,
            topk(k, metric{...}) / bottomk(k, metric{...}) — the
            heavy-hitter surface the sketch tier feeds (ISSUE 8:
            topk(5, deepflow_sketch_top_bytes) ranks the invertible
            sketch's recovered flows without any exact-row scan)
Instant queries: evaluate at time `t` with a lookback window (last
sample per series wins, Prometheus staleness semantics simplified).
Range queries: query_range evaluates the instant expression at each
step over [start, end] and returns per-series value arrays — the
/api/v1/query_range shape.

Live read plane (ISSUE 10): when a LiveRegistry (querier/live.py)
carries a provider for (db, table), both entry points merge the
provider's open-window partial rows with the flushed scan — a range
query ending "now" returns rows from the currently OPEN window. Any
result row whose value used a live sample carries `"partial": True`
(Prometheus result-marker style; absent otherwise), and because
flushed rows supersede a window's partials, the same query returns
identical values unmarked once the window closes (pinned bit-exact in
tests/test_live_read.py). Results are cached through
live.QueryResultCache keyed on (query, db, table, time args) and
validated against (store write epoch, live snapshot generation) — the
repeated-dashboard path costs a dict lookup until a window closes or a
new snapshot lands.
"""

from __future__ import annotations

import re

import numpy as np

from ..storage.store import ColumnarStore
from .live import (
    LiveRegistry,
    QueryResultCache,
    cache_token,
    default_live_registry,
    default_query_cache,
)

_QUERY_RE = re.compile(
    r"^\s*(?:(?P<agg>sum|avg|max|min|count)\s*(?:by\s*\((?P<by>[^)]*)\)\s*)?\(\s*)?"
    r"(?:(?P<topk>topk|bottomk)\s*\(\s*(?P<k>\d+)\s*,\s*)?"
    r"(?:(?P<rate>rate)\s*\(\s*)?"
    r"(?P<metric>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<matchers>[^}]*)\})?"
    r"(?:\[(?P<range>\d+)(?P<range_unit>[smh])\])?"
    r"(?:\s*\))?(?:\s*\))?(?:\s*\))?\s*$"
)

_UNIT_S = {"s": 1, "m": 60, "h": 3600}


class PromQLError(ValueError):
    pass


def _parse_matchers(text: str | None) -> list[tuple[str, str, str]]:
    out = []
    if not text:
        return out
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.match(r'^([a-zA-Z_][a-zA-Z0-9_]*)\s*(=~|!=|=)\s*"([^"]*)"$', part)
        if not m:
            raise PromQLError(f"bad matcher {part!r}")
        out.append((m.group(1), m.group(2), m.group(3)))
    return out


from ..integration.formats import unpack_tags as _label_dict


def _row(labels: dict, value: float, partial: bool) -> dict:
    """One result row; `partial` is present ONLY when True (the live
    marker must not change the shape of flushed-only results)."""
    out = {"labels": labels, "value": value}
    if partial:
        out["partial"] = True
    return out


def query_instant(
    store: ColumnarStore,
    query: str,
    t: int,
    *,
    lookback_s: int = 300,
    db: str = "prometheus",
    table: str = "samples",
    live: "LiveRegistry | None" = None,
) -> list[dict]:
    """→ [{"labels": {...}, "value": float}] — instant vector at time t.

    `db`/`table` default to the remote-write store; pass
    db="deepflow_system", table="deepflow_system" to evaluate over the
    framework's own dogfooded telemetry (integration/dfstats) — the
    table shares the samples row shape by construction. `live` (default:
    the process-wide registry) supplies open-window partial rows; rows
    whose value used one carry `"partial": True`."""
    m = _QUERY_RE.match(query)
    if not m:
        raise PromQLError(f"unsupported query {query!r}")
    if query.count("(") != query.count(")"):
        # the regex's optional close-paren groups would otherwise let a
        # typo ("topk(5, m" / "sum(m))") parse and silently answer
        raise PromQLError(f"unbalanced parentheses in {query!r}")
    agg = m.group("agg")
    by = [s.strip() for s in (m.group("by") or "").split(",") if s.strip()]
    is_rate = bool(m.group("rate"))
    window = (
        int(m.group("range")) * _UNIT_S[m.group("range_unit")]
        if m.group("range")
        else lookback_s
    )
    matchers = _parse_matchers(m.group("matchers"))
    if is_rate and not m.group("range"):
        raise PromQLError("rate() needs a [range]")

    cols = store.scan(db, table, time_range=(t - window, t + 1))
    n_store = len(cols["time"]) if cols else 0
    is_live = np.zeros(n_store, bool)
    reg = default_live_registry if live is None else live
    if reg.has(db, table):
        # open-window overlay: live partial rows join the flushed scan.
        # Flushed rows for the same (series, time) supersede at the
        # last-sample-wins stage below (live rows sort FIRST on time
        # ties via the is_live sort key), so a window that closed
        # between snapshot and query never double-reports.
        lv = reg.columns(db, table, t - window, t + 1)
        if lv is not None and all(
            k in lv for k in ("time", "metric", "labels", "value")
        ):
            lt = np.asarray(lv["time"], np.int64)
            sel_t = (lt >= t - window) & (lt < t + 1)
            if sel_t.any():
                cols = {
                    k: np.concatenate(
                        [np.asarray(cols[k]), np.asarray(lv[k])[sel_t]]
                    )
                    for k in ("time", "metric", "labels", "value")
                }
                is_live = np.r_[is_live, np.ones(int(sel_t.sum()), bool)]

    sel = cols["metric"] == m.group("metric")
    labels_packed = cols["labels"]
    rows = np.nonzero(sel)[0]
    series: dict[str, list[tuple[int, int, float]]] = {}
    for i in rows:
        packed = str(labels_packed[i])
        lab = _label_dict(packed)
        keep = True
        for name, op, val in matchers:
            have = lab.get(name, "")
            if op == "=" and have != val:
                keep = False
            elif op == "!=" and have == val:
                keep = False
            elif op == "=~" and not re.fullmatch(val, have):
                keep = False
        if keep:
            series.setdefault(packed, []).append(
                # sort key (time, rank) with rank 0 = live, 1 = flushed:
                # on a time tie the FLUSHED sample sorts last and wins
                # the instant value (flushed supersedes partials)
                (int(cols["time"][i]), 0 if is_live[i] else 1,
                 float(cols["value"][i]))
            )

    # per-series instant value (+ whether a live sample produced it)
    per_series: dict[str, float] = {}
    partials: dict[str, bool] = {}
    for packed, samples in series.items():
        samples.sort()
        if is_rate:
            if len(samples) < 2:
                continue
            dt = samples[-1][0] - samples[0][0]
            # counter-reset correction (Prometheus extrapolatedRate): a
            # decrease means the counter restarted from ~0, so the true
            # increase across the reset is the new value itself
            dv = 0.0
            for (_, _, prev), (_, _, cur) in zip(samples, samples[1:]):
                dv += cur - prev if cur >= prev else cur
            per_series[packed] = dv / dt if dt > 0 else 0.0
            partials[packed] = any(rank == 0 for _, rank, _ in samples)
        else:
            per_series[packed] = samples[-1][2]
            partials[packed] = samples[-1][1] == 0

    if m.group("topk"):
        # topk/bottomk(k, inner): keep the k extreme series, then fall
        # through to an (optional) outer aggregation over the survivors
        k = int(m.group("k"))
        sign = -1.0 if m.group("topk") == "topk" else 1.0
        keep = sorted(per_series.items(), key=lambda kv: (sign * kv[1], kv[0]))[:k]
        per_series = dict(keep)
        if agg is None:
            # rank order, not label order — the whole point of topk
            return [
                _row(_label_dict(p), v, partials.get(p, False)) for p, v in keep
            ]

    if agg is None:
        return [
            _row(_label_dict(p), v, partials.get(p, False))
            for p, v in sorted(per_series.items())
        ]
    groups: dict[tuple, list[float]] = {}
    group_partial: dict[tuple, bool] = {}
    for packed, v in per_series.items():
        lab = _label_dict(packed)
        key = tuple((b, lab.get(b, "")) for b in by)
        groups.setdefault(key, []).append(v)
        group_partial[key] = group_partial.get(key, False) or partials.get(
            packed, False
        )
    out = []
    for key, vals in sorted(groups.items()):
        if agg == "sum":
            v = sum(vals)
        elif agg == "avg":
            v = sum(vals) / len(vals)
        elif agg == "max":
            v = max(vals)
        elif agg == "min":
            v = min(vals)
        else:
            v = float(len(vals))
        out.append(_row(dict(key), v, group_partial[key]))
    return out


def query_range(
    store: ColumnarStore,
    query: str,
    start: int,
    end: int,
    step: int,
    *,
    lookback_s: int = 300,
    db: str = "prometheus",
    table: str = "samples",
    live: "LiveRegistry | None" = None,
    cache: "QueryResultCache | None | bool" = None,
) -> list[dict]:
    """Matrix result: [{"labels": {...}, "values": [[t, v], ...]}] — the
    /api/v1/query_range evaluation (each step is an instant evaluation,
    which is exactly Prometheus's range-query semantics).

    A range ending "now" includes the currently open window's partial
    rows via the live overlay; any series that used one carries
    `"partial": True`. Results cache through `cache` (default: the
    process-wide live.default_query_cache; False disables) keyed on
    (query, db, table, start, end, step) and validated against the
    (store write epoch, live snapshot generation) token — the repeated
    dashboard is a dict lookup until a window closes or a new snapshot
    lands, at which point the stale entry is dropped (counted) and
    recomputed."""
    if step <= 0:
        raise PromQLError("step must be positive")
    if end < start:
        raise PromQLError("end < start")
    reg = default_live_registry if live is None else live
    if cache is None or cache is True:
        c = default_query_cache
    elif cache is False:
        c = None
    else:
        c = cache
    key = token = None
    if c is not None:
        key = ("promql_range", query, db, table, start, end, step,
               lookback_s, getattr(store, "uid", id(store)))
        # token BEFORE evaluation: a pipeline provider's epoch() may
        # take the rate-limited snapshot, so the generation the token
        # names is the one the evaluation below reads
        token = cache_token(store, db, table, reg)
        hit = c.lookup(key, token)
        if hit is not None:
            return hit
    series: dict[tuple, dict] = {}
    for t in range(start, end + 1, step):
        for row in query_instant(
            store, query, t, lookback_s=lookback_s, db=db, table=table,
            live=reg,
        ):
            skey = tuple(sorted(row["labels"].items()))
            s = series.get(skey)
            if s is None:
                s = series[skey] = {"labels": row["labels"], "values": []}
            s["values"].append([t, row["value"]])
            if row.get("partial"):
                s["partial"] = True
    out = [series[k] for k in sorted(series)]
    if c is not None:
        c.store(key, token, out)
    return out
