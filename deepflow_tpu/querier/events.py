"""Push-mode query plane, layer 1 (ISSUE 11): the QueryEventBus.

The r14 live plane is pull-only: the result cache discovers staleness
lazily, one token compare per lookup, and every dashboard client
re-evaluates its own query. The reference server's querier exists to
feed Grafana panels and alert rules — *continuous* consumers — so the
interesting moment is not "a query arrived" but "the data a standing
query watches just changed". This module gives that moment a type:

  * **Typed events** — `WindowClosed` (a 1s window's flushed rows left
    the device), `TierClosed` (a cascade 1m/1h window closed),
    `SnapshotAdvanced` (a new open-window snapshot generation landed),
    `StoreMutation` (a flushed insert/drop bumped a table's write
    epoch). Every event names its (db, table), so consumers filter
    with one tuple compare.
  * **QueryEventBus** — a bounded in-process pub/sub fan-out. Handlers
    receive the WHOLE publish batch in one call (`handler(events)`), so
    a drain that closes K windows produces ONE delivery — the
    coalescing surface subscriptions and alert rules build on (K
    closes → one evaluation). Publishing from inside a handler is
    legal: re-entrant events append to a bounded pending queue (drops
    counted) and drain in the same outer dispatch, never recursing.

Failure stance (the drain must never stall): a handler that raises is
counted (`handler_errors`); after `MAX_HANDLER_FAILURES` consecutive
failures it is DETACHED (counted, logged once) rather than retried
forever. Publish itself never raises. Counters register as a Countable
(`tpu_query_events`), so bus health dogfoods into `deepflow_system`
like every other component.

Layer-1 consumers wired here:

  * `connect_store_events(store, bus)` — the ColumnarStore's mutation
    hook → `StoreMutation` events: a window close (flushed insert)
    becomes a push the instant it lands, instead of a lazy token
    mismatch at the next lookup.
  * `live.QueryResultCache.attach_bus(bus)` — push invalidation: the
    cache drops a mutated (db, table)'s entries EAGERLY at event time
    (`push_invalidations` lane). The per-lookup token compare stays as
    the correctness backstop (`stale_invalidations` lane) — stale-row-
    never-served remains pinned bit-exact whether or not events flow.

The process-wide `default_event_bus` mirrors `default_live_registry` /
`default_query_cache` and arrives pre-attached to the default cache: a
process that never publishes keeps today's pull-only behavior bit-for-
bit; the first connected store makes invalidation push-mode with no
further wiring.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
from collections import deque

from ..utils.stats import register_countable

_log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# the event vocabulary


@dataclasses.dataclass(frozen=True)
class WindowClosed:
    """A 1s window closed: its flushed rows left (or are leaving) the
    device — any standing query over (db, table) is stale."""

    db: str
    table: str
    time: int  # window start, seconds
    interval: int = 1


@dataclasses.dataclass(frozen=True)
class TierClosed:
    """A cascade tier window (1m/1h/…) closed."""

    db: str
    table: str
    time: int
    interval: int


@dataclasses.dataclass(frozen=True)
class SnapshotAdvanced:
    """A new open-window snapshot generation is readable — live
    partials moved even though nothing flushed."""

    db: str
    table: str
    seq: int


@dataclasses.dataclass(frozen=True)
class StoreMutation:
    """A table's write epoch moved (insert or partition drop)."""

    db: str
    table: str
    epoch: int


@dataclasses.dataclass(frozen=True)
class ProfileSnapshot:
    """A profiling sample tick landed (ISSUE 12): the device memory
    ledger / span-quantile rows for (db, table) moved — span-latency
    alert rules and standing profile dashboards re-evaluate. `time` is
    the tick's sample timestamp (the rows' own time column), so
    evaluations run at DATA time like every other event; None falls
    back to the consumer's last data time, like SnapshotAdvanced."""

    db: str
    table: str
    seq: int
    time: int | None = None


@dataclasses.dataclass(frozen=True)
class AlertFired:
    """An alert rule transitioned (ISSUE 19): published by the wire
    hub's alert sink so in-process consumers can ride the same moment
    remote dashboards see. Deliberately carries NO `db` attribute —
    subscription/alert routing keys on (db, table), and an alert
    transition must not re-trigger query evaluation (that way lies a
    feedback loop: eval → alert → event → eval)."""

    rule: str
    state: str
    value: float
    labels: tuple = ()
    time: int | None = None


QUERY_EVENT_TYPES = (
    WindowClosed, TierClosed, SnapshotAdvanced, StoreMutation,
    ProfileSnapshot, AlertFired,
)


def event_time(ev) -> int | None:
    """Best event-plane clock for an event (None when it carries no
    time) — subscription/alert evaluation uses the batch max as `now`
    so `for`-durations advance on DATA time, deterministically."""
    t = getattr(ev, "time", None)
    if t is None:
        return None
    return int(t) + int(getattr(ev, "interval", 1) or 1)


# ---------------------------------------------------------------------------
# the bus


class _Handler:
    __slots__ = ("fn", "name", "failures", "detached")

    def __init__(self, fn, name: str):
        self.fn = fn
        self.name = name
        self.failures = 0  # consecutive
        self.detached = False


class QueryEventBus:
    """Bounded in-process event fan-out; batch-preserving delivery."""

    # consecutive handler failures before detachment — a broken
    # subscriber must not tax every future drain with a raise+catch
    MAX_HANDLER_FAILURES = 8

    def __init__(self, *, max_pending: int = 4096, name: str = "default"):
        self.name = name
        self.max_pending = max_pending
        self._handlers: list[_Handler] = []
        self._pending: deque = deque()
        self._lock = threading.RLock()
        self._dispatching = False
        self.counters = {
            "events_published": 0,
            "events_dropped": 0,
            "batches": 0,
            "handler_errors": 0,
            "handlers_detached": 0,
        }
        register_countable("tpu_query_events", self, name=name)

    # -- registry --------------------------------------------------------
    def subscribe(self, handler, *, name: str = "?") -> _Handler:
        """`handler(events: list)` gets every publish batch in one
        call; returns a handle for `unsubscribe`."""
        h = _Handler(handler, name)
        with self._lock:
            self._handlers.append(h)
        return h

    def unsubscribe(self, handle: _Handler) -> None:
        with self._lock:
            if handle in self._handlers:
                self._handlers.remove(handle)

    # -- publish ---------------------------------------------------------
    def publish(self, events) -> int:
        """Deliver a batch (or one event) to every handler; returns the
        number of events accepted. Never raises; re-entrant publishes
        queue into the bounded pending deque and drain in the OUTER
        dispatch — one logical batch per drain, no recursion."""
        if dataclasses.is_dataclass(events):
            events = [events]
        events = [e for e in events if e is not None]
        if not events:
            return 0
        with self._lock:
            accepted = 0
            for e in events:
                if len(self._pending) >= self.max_pending:
                    self.counters["events_dropped"] += 1
                    continue
                self._pending.append(e)
                accepted += 1
            self.counters["events_published"] += accepted
            if self._dispatching:
                # a publish from inside a handler (or from another
                # thread mid-drain): the draining caller owns delivery
                return accepted
            self._dispatching = True
        self._drain()
        return accepted

    @contextlib.contextmanager
    def batch(self):
        """Coalesce every publish inside the context into ONE dispatch
        at exit. The close-and-insert shape needs this: a sink's
        `store.insert` fires the mutation hook's StoreMutation and the
        sink then publishes its data-timed WindowClosed — without the
        context that is two dispatches per close (two evaluations, a
        drop-rewarm-drop cache bounce, and the first eval has no data
        time); inside it, both land in one batch, evaluated once at
        the data time. Re-entrant: inside an active dispatch (or a
        nested batch) it is a no-op — the outer drain owns delivery."""
        with self._lock:
            nested = self._dispatching
            self._dispatching = True
        try:
            yield self
        finally:
            if not nested:
                self._drain()

    def _drain(self) -> None:
        """Deliver pending batches until empty. The emptiness check and
        the `_dispatching` clear happen under ONE lock acquisition: a
        concurrent publisher either appends while the flag is up (this
        loop sees it) or after the clear (it drains itself) — an event
        can never strand between a finishing drainer and a publisher
        that deferred to it."""
        while True:
            with self._lock:
                if not self._pending:
                    self._dispatching = False
                    return
                batch = list(self._pending)
                self._pending.clear()
                self.counters["batches"] += 1
                handlers = [h for h in self._handlers if not h.detached]
            try:
                self._dispatch(batch, handlers)
            except BaseException:
                with self._lock:  # never leave the bus wedged
                    self._dispatching = False
                raise

    def _dispatch(self, batch: list, handlers: list) -> None:
        for h in handlers:
            try:
                h.fn(batch)
            except Exception:
                with self._lock:
                    self.counters["handler_errors"] += 1
                h.failures += 1
                if h.failures >= self.MAX_HANDLER_FAILURES:
                    h.detached = True
                    with self._lock:
                        self.counters["handlers_detached"] += 1
                        if h in self._handlers:
                            self._handlers.remove(h)
                    _log.exception(
                        "event bus %s: handler %s detached after %d "
                        "consecutive failures",
                        self.name, h.name, h.failures,
                    )
                else:
                    _log.debug(
                        "event bus %s: handler %s raised (contained)",
                        self.name, h.name, exc_info=True,
                    )
            else:
                h.failures = 0

    # -- countable face --------------------------------------------------
    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["handlers"] = len(self._handlers)
            out["pending"] = len(self._pending)
        return out


# ---------------------------------------------------------------------------
# store → bus wiring


def connect_store_events(store, bus: QueryEventBus):
    """Point a ColumnarStore's mutation hook at the bus: every insert /
    partition drop publishes a `StoreMutation` for its (db, table).
    Returns the hook so callers can detach (`store.set_mutation_hook
    (None)`)."""

    def hook(db: str, table: str, epoch: int) -> None:
        bus.publish(StoreMutation(db, table, int(epoch)))

    store.set_mutation_hook(hook)
    return hook


def docbatch_events(outputs, *, db: str, table: str) -> list:
    """Flushed pipeline outputs → WindowClosed/TierClosed events, one
    per distinct (window start, interval). Accepts the two flushed
    shapes the window controllers emit — DocBatch (timestamp array,
    optional tier `interval_s`) and FlushedWindow (start_time) — and
    skips anything it cannot read; the event hook must never be the
    thing that breaks a drain."""
    seen: dict[tuple[int, int], None] = {}
    for o in outputs:
        try:
            interval = int(
                getattr(o, "interval_s", None) or getattr(o, "interval", 1) or 1
            )
            st = getattr(o, "start_time", None)
            if st is None:
                ts = getattr(o, "timestamp", None)
                if ts is None or not len(ts):
                    continue
                st = int(ts[0]) // interval * interval
            seen.setdefault((int(st), interval), None)
        except Exception:
            continue
    return [
        WindowClosed(db, table, t, i) if i <= 1 else TierClosed(db, table, t, i)
        for (t, i) in seen
    ]


#: process-wide default bus, mirroring live.default_live_registry /
#: live.default_query_cache — and pre-attached to the default cache, so
#: the first `connect_store_events` makes invalidation push-mode with
#: no further wiring (nothing changes until something publishes).
default_event_bus = QueryEventBus()

from .live import default_query_cache  # noqa: E402  (import-cycle-free: live imports nothing from here)

default_query_cache.attach_bus(default_event_bus)
