"""Logical metric registry — the querier's metric expansion layer.

The reference maps user-facing metric names onto storage-column
expressions per table family (querier/engine/clickhouse/metrics/: e.g.
`rtt` expands to Sum(rtt_sum)/Sum(rtt_count), `packet` to
Sum(packet_tx)+Sum(packet_rx)); the engine substitutes these before
building SQL. Same idea here: derived metrics are SQL snippets parsed
with our own parser and substituted into the query AST.

`db_descriptions`-style catalogs: `list_metrics(table)` enumerates both
raw meter columns and derived names so the CLI can surface them.
"""

from __future__ import annotations

from ..datamodel.schema import APP_METER, FLOW_METER, USAGE_METER, MeterSchema
from .sqlparse import _Parser

# family → derived metric name → expression snippet over storage columns
_FLOW_DERIVED = {
    "packet": "Sum(packet_tx) + Sum(packet_rx)",
    "byte": "Sum(byte_tx) + Sum(byte_rx)",
    "l3_byte": "Sum(l3_byte_tx) + Sum(l3_byte_rx)",
    "l4_byte": "Sum(l4_byte_tx) + Sum(l4_byte_rx)",
    "rtt_avg": "Sum(rtt_sum) / Sum(rtt_count)",
    "rtt_client_avg": "Sum(rtt_client_sum) / Sum(rtt_client_count)",
    "rtt_server_avg": "Sum(rtt_server_sum) / Sum(rtt_server_count)",
    "srt_avg": "Sum(srt_sum) / Sum(srt_count)",
    "art_avg": "Sum(art_sum) / Sum(art_count)",
    "rrt_avg": "Sum(rrt_sum) / Sum(rrt_count)",
    "cit_avg": "Sum(cit_sum) / Sum(cit_count)",
    "retrans": "Sum(retrans_tx) + Sum(retrans_rx)",
    "retrans_ratio": "(Sum(retrans_tx) + Sum(retrans_rx)) / (Sum(packet_tx) + Sum(packet_rx))",
    "error": "Sum(client_rst_flow) + Sum(server_rst_flow)",
    "l7_error": "Sum(l7_client_error) + Sum(l7_server_error)",
}

# NOTE: derived names must not shadow raw storage columns — expansion is
# by name, and `SELECT request` must mean the raw column, not Sum(request).
_APP_DERIVED = {
    "rrt_avg": "Sum(rrt_sum) / Sum(rrt_count)",
    "error": "Sum(client_error) + Sum(server_error)",
    "error_ratio": "(Sum(client_error) + Sum(server_error)) / Sum(response)",
    "client_error_ratio": "Sum(client_error) / Sum(response)",
    "server_error_ratio": "Sum(server_error) / Sum(response)",
}

_USAGE_DERIVED = {
    "packet": "Sum(packet_tx) + Sum(packet_rx)",
    "byte": "Sum(byte_tx) + Sum(byte_rx)",
}

_FAMILY_METER: dict[str, tuple[MeterSchema, dict[str, str]]] = {
    "network": (FLOW_METER, _FLOW_DERIVED),
    "network_map": (FLOW_METER, _FLOW_DERIVED),
    "application": (APP_METER, _APP_DERIVED),
    "application_map": (APP_METER, _APP_DERIVED),
    "traffic_policy": (USAGE_METER, _USAGE_DERIVED),
}


# shadowing guard: a derived name that matched a raw column would make
# `SELECT <col>` silently aggregate
for _meter, _derived in _FAMILY_METER.values():
    _clash = set(_derived) & set(_meter.field_names())
    assert not _clash, f"derived metrics shadow raw columns: {_clash}"


def _family(table: str) -> str | None:
    base = table.replace(".", "_")
    for fam in sorted(_FAMILY_METER, key=len, reverse=True):
        if base == fam or base.startswith(fam + "_"):
            return fam
    return None


def derived_metrics(table: str) -> dict[str, str]:
    fam = _family(table)
    return _FAMILY_METER[fam][1] if fam else {}


def list_metrics(table: str) -> dict[str, str]:
    """name → kind ("counter"/"gauge"/"derived") for the catalogs."""
    fam = _family(table)
    out: dict[str, str] = {}
    if fam:
        meter, derived = _FAMILY_METER[fam]
        for f in meter.fields:
            out[f.name] = "counter" if f.op.value == "sum" else "gauge"
        for name in derived:
            out[name] = "derived"
    return out


def expand(table: str, name: str):
    """Derived metric name → parsed expression AST, or None."""
    snippet = derived_metrics(table).get(name)
    if snippet is None:
        return None
    return _Parser(snippet).parse_expr()


# ---------------------------------------------------------------------------
# Metric types + counter-aware operator sets (metrics/const.go
# METRICS_TYPE_* and METRICS_TYPE_UNLAY_FUNCTIONS). The type drives
# Avg's expansion (Counter_Avg / Delay_Avg / plain AVG) and the
# ignore-zero treatment of delay metrics (view/function.go *If(x>0)).

import re as _re

COUNTER = "counter"
GAUGE = "gauge"
BOUNDED_GAUGE = "bounded_gauge"
DELAY = "delay"
PERCENTAGE = "percentage"
QUOTIENT = "quotient"

# delay family: rtt/srt/art/rrt/cit/tls_rtt with side/stat suffixes —
# everything except the _count lanes (those are counters)
_DELAY_RE = _re.compile(
    r"^(tls_)?(rtt|srt|art|rrt|cit)(_client|_server)?(_max|_sum|_avg)?$"
)

TYPE_OPERATORS = {
    COUNTER: ("Sum", "Avg", "AAvg", "Max", "Min", "PerSecond", "Percentile", "Stddev"),
    GAUGE: ("Avg", "AAvg", "Max", "Min", "Percentile", "Stddev"),
    BOUNDED_GAUGE: ("Avg", "AAvg", "Max", "Min", "Last", "Percentile", "PercentileExact"),
    DELAY: ("Avg", "AAvg", "Max", "Min", "Last", "Spread", "Rspread",
            "Percentile", "PercentileExact", "Apdex"),
    PERCENTAGE: ("Avg",),
    QUOTIENT: ("Avg",),
}


def metric_type(table: str, name: str) -> str | None:
    """Semantic type of a raw or derived metric column, or None for an
    unknown/tag column."""
    if name.endswith("_ratio"):
        return PERCENTAGE
    if _DELAY_RE.match(name) or name in ("response_duration",):
        return DELAY
    if name == "direction_score":
        return BOUNDED_GAUGE
    if name in ("flow_load",):
        return GAUGE
    fam = _family(table)
    if fam:
        meter, derived = _FAMILY_METER[fam]
        if name in derived:
            return QUOTIENT
        if name in meter.field_names():
            f = next(f for f in meter.fields if f.name == name)
            return COUNTER if f.op.value == "sum" else GAUGE
        return None
    # log tables: numeric counters vs delays handled by the regex above
    if name in _LOG_ROW_DERIVED or name.endswith(("_tx", "_rx", "_count")) or name in (
        "syn_count", "synack_count"
    ):
        return COUNTER
    return None


def is_delay(table: str, name: str) -> bool:
    return metric_type(table, name) == DELAY


# row-level derived metrics — substituted INSIDE aggregate arguments
# (clickhouse_test.go: `Sum(byte)` → SUM(byte_tx+byte_rx), `byte` on a
# log table → byte_tx+byte_rx, `Sum(log_count)` → SUM(1))
_TRAFFIC_ROW = {
    "byte": "byte_tx + byte_rx",
    "packet": "packet_tx + packet_rx",
    "l3_byte": "l3_byte_tx + l3_byte_rx",
    "l4_byte": "l4_byte_tx + l4_byte_rx",
    "retrans": "retrans_tx + retrans_rx",
    "zero_win": "zero_win_tx + zero_win_rx",
}
_LOG_ROW_DERIVED = {**_TRAFFIC_ROW, "total_byte": "total_byte_tx + total_byte_rx",
                    "total_packet": "total_packet_tx + total_packet_rx",
                    "log_count": "1"}
_APP_ROW = {"error": "client_error + server_error", "log_count": "1"}


def row_derived(table: str) -> dict[str, str]:
    base = table.replace(".", "_")
    if base.startswith("l4_flow_log") or base.startswith("l7_flow_log"):
        return _LOG_ROW_DERIVED if base.startswith("l4") else _APP_ROW
    fam = _family(table)
    if fam in ("network", "network_map", "traffic_policy"):
        return _TRAFFIC_ROW
    if fam in ("application", "application_map"):
        return _APP_ROW
    return {}


def expand_row(table: str, name: str):
    """Row-level derived name → AST (usable inside aggregates)."""
    snippet = row_derived(table).get(name)
    if snippet is None:
        return None
    return _Parser(snippet).parse_expr()


def datasource_interval(table: str) -> int:
    """Storage granularity from the table name (network_1m → 60s) —
    Counter_Avg's divisor (view/function.go GetInterval)."""
    base = table.replace(".", "_")
    for suffix, ival in (("_1d", 86400), ("_1h", 3600), ("_1m", 60), ("_1s", 1)):
        if base.endswith(suffix):
            return ival
    return 1


# ---------------------------------------------------------------------------
# db_descriptions-style catalogs (querier/db_descriptions/) — generated
# from the schemas instead of shipped as flat files.


def metric_catalog(table: str, store_schema=None) -> list[dict]:
    """One row per queryable metric: name, type, unit, operators."""
    out = []
    seen = set()

    def add(name, mtype, category):
        if name in seen or mtype is None:
            return
        seen.add(name)
        unit = ""
        if _DELAY_RE.match(name) or name.endswith("_avg") or name == "response_duration":
            unit = "us"
        elif "byte" in name:
            unit = "byte"
        out.append({
            "name": name,
            "type": mtype,
            "unit": unit,
            "category": category,
            "operators": list(TYPE_OPERATORS.get(mtype, ("Sum",))),
        })

    fam = _family(table)
    if fam:
        meter, derived = _FAMILY_METER[fam]
        for f in meter.fields:
            add(f.name, metric_type(table, f.name), "meter")
        for name in derived:
            add(name, metric_type(table, name) or QUOTIENT, "derived")
    for name in row_derived(table):
        add(name, COUNTER, "derived")
    if store_schema is not None:
        # raw numeric columns of the concrete table (log tables have no
        # meter schema; their f4 lanes are metrics)
        for c in store_schema.columns:
            t = metric_type(table, c.name)
            if c.dtype.startswith("f") or t is not None:
                add(c.name, t or GAUGE, "meter")
    return out


def tag_catalog(table: str, store_schema=None) -> list[dict]:
    """One row per queryable tag: name, data type, enumerability —
    from the storage schema when given, else the static tag schema."""
    from ..datamodel.schema import TAG_SCHEMA

    metric_names = {m["name"] for m in metric_catalog(table, store_schema)}
    out = []
    if store_schema is not None:
        for c in store_schema.columns:
            if c.name in metric_names or c.name == "time":
                continue
            kind = "string" if c.dtype.startswith("U") else "int"
            out.append({"name": c.name, "type": kind,
                        "client_server": c.name.endswith(("_0", "_1"))})
    else:
        for f in TAG_SCHEMA.fields:
            out.append({"name": f.name, "type": "int", "client_server": False})
    return out
