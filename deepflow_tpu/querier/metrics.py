"""Logical metric registry — the querier's metric expansion layer.

The reference maps user-facing metric names onto storage-column
expressions per table family (querier/engine/clickhouse/metrics/: e.g.
`rtt` expands to Sum(rtt_sum)/Sum(rtt_count), `packet` to
Sum(packet_tx)+Sum(packet_rx)); the engine substitutes these before
building SQL. Same idea here: derived metrics are SQL snippets parsed
with our own parser and substituted into the query AST.

`db_descriptions`-style catalogs: `list_metrics(table)` enumerates both
raw meter columns and derived names so the CLI can surface them.
"""

from __future__ import annotations

from ..datamodel.schema import APP_METER, FLOW_METER, USAGE_METER, MeterSchema
from .sqlparse import _Parser

# family → derived metric name → expression snippet over storage columns
_FLOW_DERIVED = {
    "packet": "Sum(packet_tx) + Sum(packet_rx)",
    "byte": "Sum(byte_tx) + Sum(byte_rx)",
    "l3_byte": "Sum(l3_byte_tx) + Sum(l3_byte_rx)",
    "l4_byte": "Sum(l4_byte_tx) + Sum(l4_byte_rx)",
    "rtt_avg": "Sum(rtt_sum) / Sum(rtt_count)",
    "rtt_client_avg": "Sum(rtt_client_sum) / Sum(rtt_client_count)",
    "rtt_server_avg": "Sum(rtt_server_sum) / Sum(rtt_server_count)",
    "srt_avg": "Sum(srt_sum) / Sum(srt_count)",
    "art_avg": "Sum(art_sum) / Sum(art_count)",
    "rrt_avg": "Sum(rrt_sum) / Sum(rrt_count)",
    "cit_avg": "Sum(cit_sum) / Sum(cit_count)",
    "retrans": "Sum(retrans_tx) + Sum(retrans_rx)",
    "retrans_ratio": "(Sum(retrans_tx) + Sum(retrans_rx)) / (Sum(packet_tx) + Sum(packet_rx))",
    "error": "Sum(client_rst_flow) + Sum(server_rst_flow)",
    "l7_error": "Sum(l7_client_error) + Sum(l7_server_error)",
}

# NOTE: derived names must not shadow raw storage columns — expansion is
# by name, and `SELECT request` must mean the raw column, not Sum(request).
_APP_DERIVED = {
    "rrt_avg": "Sum(rrt_sum) / Sum(rrt_count)",
    "error": "Sum(client_error) + Sum(server_error)",
    "error_ratio": "(Sum(client_error) + Sum(server_error)) / Sum(response)",
    "client_error_ratio": "Sum(client_error) / Sum(response)",
    "server_error_ratio": "Sum(server_error) / Sum(response)",
}

_USAGE_DERIVED = {
    "packet": "Sum(packet_tx) + Sum(packet_rx)",
    "byte": "Sum(byte_tx) + Sum(byte_rx)",
}

_FAMILY_METER: dict[str, tuple[MeterSchema, dict[str, str]]] = {
    "network": (FLOW_METER, _FLOW_DERIVED),
    "network_map": (FLOW_METER, _FLOW_DERIVED),
    "application": (APP_METER, _APP_DERIVED),
    "application_map": (APP_METER, _APP_DERIVED),
    "traffic_policy": (USAGE_METER, _USAGE_DERIVED),
}


# shadowing guard: a derived name that matched a raw column would make
# `SELECT <col>` silently aggregate
for _meter, _derived in _FAMILY_METER.values():
    _clash = set(_derived) & set(_meter.field_names())
    assert not _clash, f"derived metrics shadow raw columns: {_clash}"


def _family(table: str) -> str | None:
    base = table.replace(".", "_")
    for fam in sorted(_FAMILY_METER, key=len, reverse=True):
        if base == fam or base.startswith(fam + "_"):
            return fam
    return None


def derived_metrics(table: str) -> dict[str, str]:
    fam = _family(table)
    return _FAMILY_METER[fam][1] if fam else {}


def list_metrics(table: str) -> dict[str, str]:
    """name → kind ("counter"/"gauge"/"derived") for the catalogs."""
    fam = _family(table)
    out: dict[str, str] = {}
    if fam:
        meter, derived = _FAMILY_METER[fam]
        for f in meter.fields:
            out[f.name] = "counter" if f.op.value == "sum" else "gauge"
        for name in derived:
            out[name] = "derived"
    return out


def expand(table: str, name: str):
    """Derived metric name → parsed expression AST, or None."""
    snippet = derived_metrics(table).get(name)
    if snippet is None:
        return None
    return _Parser(snippet).parse_expr()
