"""Query-time tag translation — the dictGet seat.

The reference decodes SmartEncoding integer tags back to names at query
time via ClickHouse dictionaries materialized by tagrecorder
(`dictGet('flow_tag.pod_map', ...)`, tag/translation.go:95-150). Here
the same dictionaries live as `flow_tag.<kind>_map` tables in the store
(written by the controller's tagrecorder); `Translator.translate` loads
a map lazily, caches it, and gathers names for an id column. Enum-coded
columns (tap_side, protocol…) translate from static tables.
"""

from __future__ import annotations

import numpy as np

# tag column (or its _0/_1 sided variants) → dictionary table kind
_COLUMN_DICT = {
    "pod_id": "pod",
    "pod_node_id": "pod_node",
    "pod_ns_id": "pod_ns",
    "pod_group_id": "pod_group",
    "pod_cluster_id": "pod_cluster",
    "region_id": "region",
    "az_id": "az",
    "subnet_id": "subnet",
    "host_id": "host",
    "l3_device_id": "device",
    "l3_epc_id": "l3_epc",
    "gprocess_id": "gprocess",
    "auto_service_id": "auto_service",
    "auto_instance_id": "auto_instance",
}

_ENUMS = {
    "tap_side": {0: "rest", 1: "c", 2: "s", 9: "c-nd", 10: "s-nd", 17: "c-hv", 18: "s-hv",
                 33: "c-gw", 34: "s-gw", 41: "c-p", 42: "s-p", 49: "c-app", 50: "s-app", 48: "app"},
    "protocol": {0: "unknown", 1: "icmp", 6: "tcp", 17: "udp"},
    "signal_source": {0: "packet", 1: "xflow", 3: "ebpf", 4: "otel"},
}

FLOW_TAG_DB = "flow_tag"

# -- datasource tier selection (ISSUE 9) ------------------------------------
# The rollup cascade maintains bounded 1m/1h tiers alongside the 1s
# tables; the datasource manager materializes more. A range query whose
# step is coarse should read the COARSEST tier whose resolution
# satisfies the step instead of replaying 1s rows — a month at 1h
# resolution is ~720 tier rows per series, not 2.6M second rows. The
# querier routes a BARE family name ("network") through here; an
# explicit granularity ("network.1s") stays pinned.

TIER_SUFFIX_S = {"1s": 1, "1m": 60, "1h": 3600, "1d": 86400}


def select_datasource_tier(
    available: dict[str, int], step: int | None,
    live_tables: frozenset[str] | set[str] = frozenset(),
) -> str | None:
    """Pick a table from `available` ({table_name: interval_s}).

    The coarsest tier whose interval both fits within and divides
    `step` wins (divisibility keeps output buckets aligned with tier
    rows — a 90s step over a 1m tier would split tier rows across
    buckets). step None (no interval grouping) reads the finest tier:
    detail queries must not silently coarsen. A step FINER than every
    available tier returns None — answering a 30s-bucket query from
    60s rows would produce a silently wrong series, so the caller's
    no-such-table error is the correct outcome.

    `live_tables` (ISSUE 10): tables with a registered open-window live
    source. When the query's range touches the open span (the engine
    only passes a non-empty set then), a LIVE-covered tier that
    satisfies the step beats a coarser tier without coverage — the
    coarser rows would silently miss the freshest `delay` seconds that
    the live overlay exists to serve. Among live-covered fits the
    FINEST wins (it has the freshest open windows)."""
    if not available:
        return None
    by_interval = sorted(available.items(), key=lambda kv: kv[1])
    if step is None:
        return by_interval[0][0]
    if by_interval[0][1] > step:
        return None  # even the finest tier is coarser than the step
    fits = [
        (name, s) for name, s in by_interval if s <= step and step % s == 0
    ]
    if fits and live_tables:
        live_fits = [(name, s) for name, s in fits if name in live_tables]
        if live_fits:
            return live_fits[0][0]
    return (fits[-1] if fits else by_interval[0])[0]


class Translator:
    def __init__(self, store):
        self.store = store
        self._cache: dict[str, dict[int, str]] = {}

    def _load_map(self, kind: str) -> dict[int, str]:
        m = self._cache.get(kind)
        if m is not None:
            return m
        m = {}
        table = f"{kind}_map"
        try:
            cols = self.store.scan(FLOW_TAG_DB, table, columns=["id", "name"])
            m = {int(i): str(s) for i, s in zip(cols["id"], cols["name"])}
        except KeyError:
            pass  # dictionary not materialized (no controller) → ids pass through
        self._cache[kind] = m
        return m

    def invalidate(self, kind: str | None = None) -> None:
        if kind is None:
            self._cache.clear()
        else:
            self._cache.pop(kind, None)

    # ch_pod_k8s_label / _annotation / _env lookups — the
    # `k8s.label.<key>` custom-tag seat (tag/translation.go dictGet on
    # flow_tag.pod_k8s_label_map)
    _K8S_TABLES = {
        "label": "pod_k8s_label_map",
        "annotation": "pod_k8s_annotation_map",
        "env": "pod_k8s_env_map",
    }

    def _load_kv(self, table: str) -> dict[tuple[int, str], str]:
        cache_key = f"kv:{table}"
        m = self._cache.get(cache_key)
        if m is not None:
            return m
        m = {}
        try:
            cols = self.store.scan(FLOW_TAG_DB, table, columns=["id", "key", "value"])
            m = {
                (int(i), str(k)): str(v)
                for i, k, v in zip(cols["id"], cols["key"], cols["value"])
            }
        except KeyError:
            pass
        self._cache[cache_key] = m
        return m

    def k8s_meta(self, kind: str, key: str, pod_ids: np.ndarray) -> np.ndarray:
        """Pod ids → the value of one label/annotation/env key ('' when
        absent)."""
        table = self._K8S_TABLES[kind]
        m = self._load_kv(table)
        return np.array([m.get((int(v), key), "") for v in pod_ids])

    def translate(self, table: str, column: str, ids: np.ndarray) -> np.ndarray:
        base = column[:-2] if column.endswith(("_0", "_1")) else column
        if base in _ENUMS:
            enum = _ENUMS[base]
            return np.array([enum.get(int(v), str(int(v))) for v in ids])
        kind = _COLUMN_DICT.get(base)
        if kind is None:
            return np.array([str(int(v)) for v in ids])
        m = self._load_map(kind)
        return np.array([m.get(int(v), str(int(v))) for v in ids])
