"""SQL subset parser for the query engine.

The reference parses DeepFlow-SQL with sqlparser and walks the AST into
a ClickHouse view tree (clickhouse.go:1007-1423 TransSelect/TransWhere/
TransFrom/TransGroupBy). We target our own executor instead of CK SQL,
so the parser stops at a plain expression AST:

    SELECT expr [AS alias], ...
    FROM table
    [WHERE expr] [GROUP BY expr, ...] [ORDER BY expr [ASC|DESC], ...]
    [LIMIT n] [OFFSET n]

Expressions: identifiers (optionally quoted with `backticks`), int/float
/'string' literals, function calls, unary -/NOT, binary */%//, +-, com-
parisons, IN (...), AND, OR. Pratt precedence climbing, ~150 lines.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any


class SQLError(ValueError):
    pass


# -- AST --------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Ident:
    name: str


@dataclasses.dataclass(frozen=True)
class Literal:
    value: Any


@dataclasses.dataclass(frozen=True)
class Func:
    name: str  # lowercased
    args: tuple


@dataclasses.dataclass(frozen=True)
class BinOp:
    op: str
    left: Any
    right: Any


@dataclasses.dataclass(frozen=True)
class UnaryOp:
    op: str  # "-" | "not"
    operand: Any


@dataclasses.dataclass(frozen=True)
class InList:
    expr: Any
    values: tuple
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class SelectItem:
    expr: Any
    alias: str | None


@dataclasses.dataclass(frozen=True)
class Show:
    """SHOW tables | SHOW metrics FROM t | SHOW tags FROM t — the
    db_descriptions introspection statements (querier/engine/clickhouse
    ShowSqlParse handles `show tags/metrics from ...`)."""

    what: str  # "tables" | "metrics" | "tags"
    table: str | None


@dataclasses.dataclass(frozen=True)
class Query:
    select: tuple[SelectItem, ...]
    table: str
    where: Any | None
    group_by: tuple
    order_by: tuple  # of (expr, "asc"|"desc")
    limit: int | None
    offset: int
    having: Any | None = None


# -- lexer ------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    \s*(?:
      (?P<num>\d+\.\d+|\.\d+|\d+)
    | (?P<str>'(?:[^'\\]|\\.)*')
    | (?P<qid>`[^`]+`)
    | (?P<id>[A-Za-z_][A-Za-z0-9_.]*)
    | (?P<op><>|!=|<=|>=|=|<|>|\(|\)|,|\*|/|%|\+|-)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "from", "where", "group", "order", "by", "limit", "offset",
    "as", "and", "or", "not", "in", "asc", "desc", "having", "show",
}


def _lex(text: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m or m.end() == pos:
            if text[pos:].strip():
                raise SQLError(f"bad token at: {text[pos:pos+20]!r}")
            break
        pos = m.end()
        if m.lastgroup == "num":
            out.append(("num", m.group("num")))
        elif m.lastgroup == "str":
            out.append(("str", m.group("str")[1:-1].replace("\\'", "'")))
        elif m.lastgroup == "qid":
            out.append(("id", m.group("qid")[1:-1]))
        elif m.lastgroup == "id":
            word = m.group("id")
            if word.lower() in _KEYWORDS:
                out.append(("kw", word.lower()))
            else:
                out.append(("id", word))
        else:
            out.append(("op", m.group("op")))
    out.append(("eof", ""))
    return out


# -- parser -----------------------------------------------------------------

_PRECEDENCE = {
    "or": 1,
    "and": 2,
    "=": 4, "!=": 4, "<>": 4, "<": 4, ">": 4, "<=": 4, ">=": 4, "in": 4,
    "+": 5, "-": 5,
    "*": 6, "/": 6, "%": 6,
}


class _Parser:
    def __init__(self, text: str):
        self.toks = _lex(text)
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, kind, value=None):
        k, v = self.next()
        if k != kind or (value is not None and v != value):
            raise SQLError(f"expected {value or kind}, got {v!r}")
        return v

    def accept(self, kind, value=None):
        k, v = self.peek()
        if k == kind and (value is None or v == value):
            self.i += 1
            return True
        return False

    # expressions ------------------------------------------------------
    def parse_expr(self, min_prec: int = 0):
        left = self._parse_unary()
        while True:
            k, v = self.peek()
            op = v if (k == "op" and v in _PRECEDENCE) else (
                v if (k == "kw" and v in ("and", "or", "in")) else None
            )
            negated = False
            if op is None and k == "kw" and v == "not":
                # NOT IN — decide before consuming anything, so a
                # precedence break leaves both tokens for the outer level
                nk, nv = self.toks[self.i + 1]
                if nk == "kw" and nv == "in":
                    op, negated = "in", True
                else:
                    break
            if op is None or _PRECEDENCE[op] < min_prec:
                break
            if negated:
                self.next()  # NOT
            self.next()
            if op == "in":
                self.expect("op", "(")
                vals = [self._parse_value()]
                while self.accept("op", ","):
                    vals.append(self._parse_value())
                self.expect("op", ")")
                left = InList(left, tuple(vals), negated)
                continue
            right = self.parse_expr(_PRECEDENCE[op] + 1)
            left = BinOp("!=" if op == "<>" else op, left, right)
        return left

    def _parse_value(self):
        k, v = self.next()
        if k == "num":
            return Literal(float(v) if "." in v else int(v))
        if k == "str":
            return Literal(v)
        raise SQLError(f"expected literal, got {v!r}")

    def _parse_unary(self):
        k, v = self.peek()
        if k == "op" and v == "-":
            self.next()
            return UnaryOp("-", self._parse_unary())
        if k == "kw" and v == "not":
            # SQL precedence: NOT binds looser than comparisons, so
            # `NOT a = 1` is NOT(a = 1) — parse the operand at the
            # precedence level just above AND
            self.next()
            return UnaryOp("not", self.parse_expr(_PRECEDENCE["and"] + 1))
        return self._parse_primary()

    def _parse_primary(self):
        k, v = self.next()
        if k == "num":
            return Literal(float(v) if "." in v else int(v))
        if k == "str":
            return Literal(v)
        if k == "op" and v == "(":
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        if k == "op" and v == "*":
            return Ident("*")
        if k == "id":
            if self.accept("op", "("):
                args = []
                if not self.accept("op", ")"):
                    args.append(self.parse_expr())
                    while self.accept("op", ","):
                        args.append(self.parse_expr())
                    self.expect("op", ")")
                return Func(v.lower(), tuple(args))
            return Ident(v)
        raise SQLError(f"unexpected token {v!r}")

    # statement --------------------------------------------------------
    def parse_query(self) -> Query | Show:
        if self.accept("kw", "show"):
            what = self.expect("id").lower()
            if what not in ("tables", "metrics", "tags"):
                raise SQLError(f"SHOW {what!r}: expected tables/metrics/tags")
            table = None
            if self.accept("kw", "from"):
                table = self.expect("id")
            if self.peek()[0] != "eof":
                raise SQLError(f"trailing input: {self.peek()[1]!r}")
            if what != "tables" and table is None:
                raise SQLError(f"SHOW {what} needs FROM <table>")
            if what == "tables" and table is not None:
                raise SQLError("SHOW tables takes no FROM clause")
            return Show(what, table)
        self.expect("kw", "select")
        items = [self._select_item()]
        while self.accept("op", ","):
            items.append(self._select_item())
        self.expect("kw", "from")
        table = self.expect("id")
        where = None
        if self.accept("kw", "where"):
            where = self.parse_expr()
        group_by: list = []
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by.append(self.parse_expr())
            while self.accept("op", ","):
                group_by.append(self.parse_expr())
        having = None
        if self.accept("kw", "having"):
            having = self.parse_expr()
        order_by: list = []
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            while True:
                e = self.parse_expr()
                direction = "asc"
                if self.accept("kw", "desc"):
                    direction = "desc"
                elif self.accept("kw", "asc"):
                    pass
                order_by.append((e, direction))
                if not self.accept("op", ","):
                    break
        limit = None
        offset = 0
        if self.accept("kw", "limit"):
            limit = int(self.expect("num"))
        if self.accept("kw", "offset"):
            offset = int(self.expect("num"))
        if self.peek()[0] != "eof":
            raise SQLError(f"trailing input: {self.peek()[1]!r}")
        return Query(
            select=tuple(items),
            table=table,
            where=where,
            group_by=tuple(group_by),
            order_by=tuple(order_by),
            limit=limit,
            offset=offset,
            having=having,
        )

    def _select_item(self) -> SelectItem:
        e = self.parse_expr()
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("id")
        return SelectItem(e, alias)


def parse(text: str) -> Query:
    return _Parser(text).parse_query()
