"""Live query plane (ISSUE 10) — open-window overlay + result cache.

The querier (SQL + PromQL) historically read only FLUSHED stores: every
open window was invisible until it closed, so the freshest `delay`
seconds of telemetry — exactly what a live dashboard wants — were a
blind spot. This module closes it with two host-side pieces:

  * **LiveRegistry** — (db, table) → live-row providers. A provider is
    a callable `(lo, hi) → columns dict | None` returning table-shaped
    rows for the open span (typically backed by
    `RollupPipeline.snapshot_open()` / `ShardedWindowManager
    .snapshot_open()` through the adapters in integration/dfstats.py,
    or by a pull of StatsCollector counters). Both query engines
    consult the registry when a query's time range touches the open
    span and merge the partial rows in, marked `partial=True` in
    results — flushed rows always SUPERSEDE a window's partials, so
    once a window closes the same query returns the identical values
    unmarked (the consistency pin in tests/test_live_read.py).
    Optional provider faces: `.epoch()` — a monotonically increasing
    int identifying the snapshot generation backing the rows (the
    result cache's live token; pipeline adapters return the
    OpenSnapshot seq, so the cache stays hot between rate-limited
    snapshots) — and `.open_from()` — the first open second (None =
    nothing open), used by datasource tier selection to keep
    live-covered tiers preferred for range queries ending "now".

  * **QueryResultCache** — the repeated-dashboard path: an LRU map
    keyed on (engine, query, db, table, time args), validated per
    lookup against a token of (store write epoch, live epoch). A
    window close inserts flushed rows → the store epoch moves → the
    stale entry is dropped (counted as an invalidation) and recomputed;
    between mutations and snapshots, the same dashboard query is a
    dict lookup. Bounded (LRU, configurable entries) so a dashboard
    storm of distinct queries cannot grow host memory without bound;
    hit/miss/invalidation/eviction counters expose as a Countable —
    queryable through the same SQL/PromQL engines it accelerates.

Both pieces are PULL-only and entirely off the ingest path: nothing
here runs unless a query does, and the device reads behind the
providers are rate-limited at the snapshot layer
(`WindowConfig.min_snapshot_interval`).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from ..utils.spans import SPAN_QUERY_CACHE, SpanTracer
from ..utils.stats import register_countable


class LiveRegistry:
    """(db, table) → live-row providers for the open-window overlay."""

    def __init__(self):
        self._providers: dict[tuple[str, str], list] = {}
        self._lock = threading.Lock()
        self._reg_seq = 0  # registration churn feeds the epoch too

    def register(self, db: str, table: str, provider) -> tuple:
        """Add a provider; returns a handle for `unregister`."""
        key = (db, table)
        with self._lock:
            self._providers.setdefault(key, []).append(provider)
            self._reg_seq += 1
        return (key, provider)

    def unregister(self, handle: tuple) -> None:
        key, provider = handle
        with self._lock:
            lst = self._providers.get(key, [])
            if provider in lst:
                lst.remove(provider)
                self._reg_seq += 1
            if not lst:
                self._providers.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._providers.clear()
            self._reg_seq += 1

    def has(self, db: str, table: str) -> bool:
        with self._lock:
            return bool(self._providers.get((db, table)))

    def live_tables(self, db: str) -> set[str]:
        with self._lock:
            return {t for (d, t), ps in self._providers.items() if d == db and ps}

    def epoch(self, db: str, table: str) -> int:
        """Live-data generation token for (db, table): changes whenever
        a provider's snapshot generation moves or the provider set
        does. NOTE: a pipeline-backed provider's epoch() may take the
        (rate-limited) snapshot itself, so the token identifies the
        exact generation the subsequent evaluation will read."""
        with self._lock:
            providers = list(self._providers.get((db, table), ()))
            seq = self._reg_seq
        tok = seq
        for p in providers:
            ep = getattr(p, "epoch", None)
            if ep is not None:
                tok = tok * 1_000_003 + int(ep())
        return tok

    def open_from(self, db: str, table: str) -> int | None:
        """Earliest open second any provider serves (None = nothing
        open / no provider exposes it)."""
        with self._lock:
            providers = list(self._providers.get((db, table), ()))
        vals = []
        for p in providers:
            of = getattr(p, "open_from", None)
            if of is not None:
                v = of()
                if v is not None:
                    vals.append(int(v))
        return min(vals) if vals else None

    def columns(self, db: str, table: str, lo: int, hi: int):
        """Merged live rows for [lo, hi): one columns dict (or None).
        Provider failures are contained — a broken live source must
        degrade the query to flushed-only, never break it."""
        with self._lock:
            providers = list(self._providers.get((db, table), ()))
        parts = []
        for p in providers:
            try:
                cols = p(lo, hi)
            except Exception:
                continue
            if cols is not None and len(next(iter(cols.values()), ())):
                parts.append(cols)
        if not parts:
            return None
        if len(parts) == 1:
            return parts[0]
        # only columns EVERY provider serves concatenate — a provider
        # missing one must degrade that column's overlay, not raise
        keys = set(parts[0])
        for p in parts[1:]:
            keys &= set(p)
        return {k: np.concatenate([p[k] for p in parts]) for k in sorted(keys)}


#: process-wide default, mirroring utils.stats.default_collector — the
#: engines fall back to it when no registry is passed explicitly, so an
#: empty registry keeps today's flushed-only behavior bit-for-bit.
default_live_registry = LiveRegistry()


class QueryResultCache:
    """LRU result cache keyed on (query, db, table, window args).

    `lookup(key, token)` → cached value or None; `store(key, token,
    value)` inserts. A token mismatch on lookup drops the stale entry
    (counted: `invalidations` — the window-close path) and reports a
    miss; insertion beyond `max_entries` evicts the least recently
    used (counted: `evictions`). Thread-safe; the cached value is
    returned by reference — treat results as immutable.

    Push mode (ISSUE 11): `attach_bus(bus)` subscribes the cache to a
    `events.QueryEventBus` — any WindowClosed / TierClosed /
    SnapshotAdvanced / StoreMutation event drops the named (db, table)'s
    entries EAGERLY at event time instead of waiting for the next
    lookup's token compare. The two paths count into separate lanes —
    `push_invalidations` (event-driven) vs `stale_invalidations` (the
    lazy per-lookup backstop) — with `invalidations` kept as their sum,
    so the push plane's coverage is observable: in a fully event-wired
    process the stale lane sits at ~0 and every non-zero tick of it
    names a mutation path that bypassed the bus. The token compare
    itself is never retired — it is the correctness backstop that keeps
    stale-row-never-served pinned bit-exact whether or not events flow."""

    def __init__(self, max_entries: int = 256, *, tracer: SpanTracer | None = None):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.max_entries = max_entries
        self.tracer = tracer if tracer is not None else SpanTracer(
            service="deepflow_tpu.querier"
        )
        self._map: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self._buses: list = []  # attached event buses (handles kept alive)
        self._rewarm = None
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.push_invalidations = 0
        self.stale_invalidations = 0
        self.rewarmed = 0
        self.evictions = 0

    def lookup(self, key, token):
        with self.tracer.span(SPAN_QUERY_CACHE):
            with self._lock:
                entry = self._map.get(key)
                if entry is not None:
                    e_token, value = entry
                    if e_token == token:
                        self._map.move_to_end(key)
                        self.hits += 1
                        return value
                    # stale — a window closed (store epoch moved) or a
                    # newer snapshot landed (live epoch moved) and no
                    # push event beat this lookup to the entry: the
                    # lazy-epoch backstop lane
                    del self._map[key]
                    self.invalidations += 1
                    self.stale_invalidations += 1
                self.misses += 1
                return None

    def store(self, key, token, value) -> None:
        with self._lock:
            self._map[key] = (token, value)
            self._map.move_to_end(key)
            while len(self._map) > self.max_entries:
                self._map.popitem(last=False)
                self.evictions += 1

    def invalidate(
        self, db: str | None = None, table: str | None = None,
        *, push: bool = False,
    ) -> int:
        """Drop entries whose key names (db, table) — every key the
        engines build carries them at fixed positions 2/3; None drops
        everything. Returns the number invalidated. `push=True` counts
        into the event-driven lane (attach_bus uses it); the default
        counts the manual/lazy lane."""
        with self._lock:
            if db is None and table is None:
                drop = list(self._map)
                self._map.clear()
            else:
                drop = [
                    k for k in self._map
                    if (db is None or (len(k) > 2 and k[2] == db))
                    and (table is None or (len(k) > 3 and k[3] == table))
                ]
                for k in drop:
                    del self._map[k]
            n = len(drop)
            self.invalidations += n
            if push:
                self.push_invalidations += n
            else:
                self.stale_invalidations += n
            rewarm = self._rewarm
        if push and rewarm is not None and drop:
            # optional re-warm: hand the dropped keys to the hook (a
            # SubscriptionManager re-evaluating its standing queries is
            # the usual warmer); contained — a broken warmer must not
            # break the event path
            try:
                self.rewarmed += rewarm(drop)
            except Exception:
                pass
        return n

    def attach_bus(self, bus, *, rewarm=None):
        """Subscribe to an `events.QueryEventBus`: every event naming a
        (db, table) push-invalidates its entries. Idempotent per bus.
        `rewarm(keys) -> int` optionally re-computes hot entries right
        after a push drop (returns how many it warmed)."""
        if rewarm is not None:
            self._rewarm = rewarm
        with self._lock:
            if any(b is bus for b, _ in self._buses):
                return None

        def on_events(events) -> None:
            seen = set()
            for e in events:
                db = getattr(e, "db", None)
                table = getattr(e, "table", None)
                if db is None or table is None or (db, table) in seen:
                    continue
                seen.add((db, table))
                self.invalidate(db, table, push=True)

        handle = bus.subscribe(on_events, name="query_cache")
        with self._lock:
            self._buses.append((bus, handle))
        return handle

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def get_counters(self) -> dict:
        """Countable face — dogfoods into deepflow_system like every
        other component, so cache health is queryable via SQL and
        PromQL (tpu_query_cache_hits{...}); the push vs stale lanes
        (tpu_query_cache_push_invalidations / ..._stale_invalidations)
        make the event plane's invalidation coverage observable."""
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "push_invalidations": self.push_invalidations,
                "stale_invalidations": self.stale_invalidations,
                "rewarmed": self.rewarmed,
                "evictions": self.evictions,
                "entries": len(self._map),
                "max_entries": self.max_entries,
            }


#: process-wide default result cache (the engines use it unless told
#: otherwise), registered as a Countable at import — the reference's
#: RegisterCountable-at-construction stance.
default_query_cache = QueryResultCache(max_entries=256)
register_countable("tpu_query_cache", default_query_cache)


def cache_token(store, db: str, table: str, live: LiveRegistry | None) -> tuple:
    """The validation token both engines stamp on cached entries:
    (store write epoch, live generation). Any flushed insert — a
    window close — or a new live snapshot changes it."""
    mut = store.mutation_count(db, table) if hasattr(store, "mutation_count") else -1
    lep = live.epoch(db, table) if live is not None else 0
    return (mut, lep)
