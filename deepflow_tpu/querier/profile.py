"""Flame-graph service — the querier/profile seat.

The reference builds flame trees from `in_process_profile` rows
(server/querier/profile/). `flame_tree` folds stack rows into the
nested {name, self_value, total_value, children} shape flamegraph UIs
consume; `query_flame` runs the scan + filter through the store.
"""

from __future__ import annotations

import numpy as np

from ..storage.store import ColumnarStore


def flame_tree(stacks: list[str], values: list[int]) -> dict:
    root = {"name": "root", "self_value": 0, "total_value": 0, "children": {}}
    for stack, value in zip(stacks, values):
        node = root
        node["total_value"] += value
        for frame in stack.split(";"):
            child = node["children"].get(frame)
            if child is None:
                child = node["children"][frame] = {
                    "name": frame,
                    "self_value": 0,
                    "total_value": 0,
                    "children": {},
                }
            child["total_value"] += value
            node = child
        node["self_value"] += value

    def finish(node):
        node["children"] = [finish(c) for c in node["children"].values()]
        return node

    return finish(root)


def query_flame(
    store: ColumnarStore,
    *,
    app_service: str,
    time_range: tuple[int, int] | None = None,
    event_type: str | None = None,
    db: str = "profile",
) -> dict:
    cols = store.scan(db, "in_process_profile", time_range=time_range)
    sel = cols["app_service"] == app_service
    if event_type is not None:
        sel &= cols["profile_event_type"] == event_type
    return flame_tree(
        [str(s) for s in cols["stack"][sel]],
        [int(v) for v in np.asarray(cols["value"])[sel]],
    )
