"""Query plane: DeepFlow-SQL subset engine over the columnar store —
the server/querier seat (engine/clickhouse/clickhouse.go:117) — plus
the push-mode layers (ISSUE 11): QueryEventBus (events.py), query
subscriptions (subscribe.py), and the alerting rule engine (alerts.py).
"""

from .engine import QueryEngine

__all__ = [
    "QueryEngine",
    "QueryEventBus",
    "SubscriptionManager",
    "AlertEngine",
    "AlertRule",
]


def __getattr__(name):  # lazy: keep bare-engine imports light
    if name == "QueryEventBus":
        from .events import QueryEventBus

        return QueryEventBus
    if name == "SubscriptionManager":
        from .subscribe import SubscriptionManager

        return SubscriptionManager
    if name in ("AlertEngine", "AlertRule"):
        from . import alerts

        return getattr(alerts, name)
    raise AttributeError(name)
