"""Query plane: DeepFlow-SQL subset engine over the columnar store —
the server/querier seat (engine/clickhouse/clickhouse.go:117).
"""

from .engine import QueryEngine

__all__ = ["QueryEngine"]
