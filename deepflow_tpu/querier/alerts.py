"""Push-mode query plane, layer 3 (ISSUE 11): the alerting rule engine.

The reference server's querier serves exactly two consumers — Grafana
dashboards and alert rules. Subscriptions (subscribe.py) are the
dashboard half; an alert rule is the same machinery with a comparator
and a threshold bolted on: a standing query re-evaluated on push
events, whose RESULT feeds a small per-rule state machine instead of a
websocket.

Rule = query (PromQL instant — including `topk()` / distinct /
quantile queries the sketch plane answers — or SQL) + comparator +
threshold + `for`-duration. State is kept PER SERIES (label set) since
ISSUE 12 — Prometheus semantics: one service's latency series can fire
while its siblings stay inactive; the rule-level faces report the
worst series. States:

    inactive ──breach──▶ pending ──held for ≥ for_s──▶ firing
       ▲                    │                            │
       └────no breach───────┘                       no breach
                                                         ▼
    resolved ◀───────────────────────────────────────────┘
       └──breach──▶ pending  (flap suppression: a re-fire after a
                              resolve walks the FULL pending ladder
                              again — a flapping series cannot ring
                              the pager at event rate)

Time is the event plane's DATA time (`events.event_time` batch max),
so `for`-durations advance deterministically under replay and tests;
`tick(now)` drives the same evaluation from a wall clock for processes
whose tables go quiet (a pending rule must still mature to firing when
traffic stops precisely because it stopped).

Transitions notify pluggable sinks: `log_notification_sink` (always
available), arbitrary callbacks, and `otlp_notification_sink(exporter)`
— alert events ride the same exporter traces lane the span tracer uses,
so a firing rule shows up in the trace backend next to the pipeline
stages that produced it. A raising sink is counted and DETACHED after
`MAX_SINK_FAILURES` consecutive failures; it never stalls the drain.

Dogfood: the engine registers as a Countable (`tpu_alert_rules`), with
per-rule state codes and transition counts as flat lanes — rule states
are queryable via SQL and PromQL
(`tpu_alert_rules_rule_<name>_state_code`) like every other component.
"""

from __future__ import annotations

import dataclasses
import logging
import re
import threading
import time

from ..utils.spans import SPAN_ALERT_EVAL, SpanTracer
from ..utils.stats import register_countable
from .events import QueryEventBus, event_time

_log = logging.getLogger(__name__)

STATE_INACTIVE = "inactive"
STATE_PENDING = "pending"
STATE_FIRING = "firing"
STATE_RESOLVED = "resolved"

#: stable numeric codes for the dogfood lanes (SQL/PromQL-queryable)
STATE_CODES = {
    STATE_INACTIVE: 0,
    STATE_PENDING: 1,
    STATE_FIRING: 2,
    STATE_RESOLVED: 3,
}

_COMPARATORS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    "==": lambda v, t: v == t,
    "!=": lambda v, t: v != t,
}

_NAME_SAN_RE = re.compile(r"[^a-zA-Z0-9_]")


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One rule spec. `engine` picks evaluation: "promql" runs
    `query_instant` at the event time over (db, table) and compares
    EVERY returned series against the threshold — state is kept PER
    LABEL SET (Prometheus semantics, ISSUE 12 satellite: one series of
    a rule can fire while its siblings stay inactive); "sql" executes
    the statement and compares the first numeric cell of the first row
    (one anonymous series). A series with no data this evaluation is no
    breach (a silent series resolves rather than pages), and an
    inactive series that stops reporting leaves the state map — label
    churn cannot grow it unboundedly (plus a hard cap, counted)."""

    name: str
    query: str
    comparator: str  # one of > >= < <= == !=
    threshold: float
    for_s: int = 0
    engine: str = "promql"  # "promql" | "sql"
    db: str = "deepflow_system"
    table: str = "deepflow_system"
    lookback_s: int = 300
    labels: tuple[tuple[str, str], ...] = ()

    def __post_init__(self):
        if self.comparator not in _COMPARATORS:
            raise ValueError(f"unknown comparator {self.comparator!r}")
        if self.engine not in ("promql", "sql"):
            raise ValueError(f"unknown engine {self.engine!r}")
        if self.for_s < 0:
            raise ValueError("for_s must be >= 0")


#: worst-state ordering for the rule-level rollup faces (state(),
#: list_rules, the dogfood state-code lane)
_SEVERITY = {STATE_INACTIVE: 0, STATE_RESOLVED: 1, STATE_PENDING: 2,
             STATE_FIRING: 3}


def worst_state(states) -> str:
    """Worst alert state in `states` under the severity ordering —
    the fleet aggregator's per-rule cross-host rollup (one firing host
    makes the fleet rule firing). Unknown states rank below inactive
    rather than raising: a newer host must not crash an older pane."""
    worst = STATE_INACTIVE
    rank = -1
    for s in states:
        r = _SEVERITY.get(s, -1)
        if r > rank:
            rank, worst = r, s
    return worst


# -- rule persistence (ISSUE 13 satellite / ROADMAP r15 leftover) --------
#
# Rules serialize to/from plain mappings so a YAML or JSON config file
# round-trips an engine's rule set across restarts. Parsing is LOUD:
# a malformed rule raises ValueError naming the entry and the field —
# a typo'd comparator must fail the boot, not silently drop the page.

_RULE_REQUIRED = ("name", "query", "comparator", "threshold")
_RULE_OPTIONAL = {
    "for_s": int, "engine": str, "db": str, "table": str,
    "lookback_s": int, "labels": None,
}


def rule_to_dict(rule: AlertRule) -> dict:
    d = dataclasses.asdict(rule)
    d["labels"] = dict(rule.labels)
    return d


def rule_from_dict(d, *, where: str = "rule") -> AlertRule:
    if not isinstance(d, dict):
        raise ValueError(f"{where}: expected a mapping, got {type(d).__name__}")
    unknown = set(d) - set(_RULE_REQUIRED) - set(_RULE_OPTIONAL)
    if unknown:
        raise ValueError(f"{where}: unknown keys {sorted(unknown)}")
    for k in _RULE_REQUIRED:
        if k not in d:
            raise ValueError(f"{where}: missing required key {k!r}")
    kw = dict(d)
    try:
        kw["threshold"] = float(kw["threshold"])
        for k in ("for_s", "lookback_s"):
            if k in kw:
                kw[k] = int(kw[k])
        labels = kw.pop("labels", None)
        if labels is not None:
            if not isinstance(labels, dict):
                raise ValueError("labels must be a mapping")
            kw["labels"] = tuple(sorted(
                (str(k), str(v)) for k, v in labels.items()
            ))
        rule = AlertRule(**kw)
    except (TypeError, ValueError) as exc:
        raise ValueError(f"{where}: {exc}") from exc
    return rule


def load_rules_file(path) -> list[AlertRule]:
    """Parse a YAML/JSON rules file → validated AlertRules. The file is
    either a list of rule mappings or {"rules": [...]}; EVERY rule is
    validated before any is returned (atomic — a malformed entry fails
    the whole load loudly)."""
    import json
    from pathlib import Path

    import yaml

    p = Path(path)
    text = p.read_text()
    try:
        data = (json.loads(text) if p.suffix == ".json"
                else yaml.safe_load(text))
    except Exception as exc:
        raise ValueError(f"alert rules file {p}: unparseable: {exc}") from exc
    if isinstance(data, dict):
        data = data.get("rules", None)
    if not isinstance(data, list):
        raise ValueError(
            f"alert rules file {p}: expected a list of rules (or a "
            "mapping with a 'rules' list)"
        )
    rules = [
        rule_from_dict(d, where=f"{p.name} rule #{i}")
        for i, d in enumerate(data)
    ]
    names = [r.name for r in rules]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"alert rules file {p}: duplicate names {sorted(dupes)}")
    return rules


def save_rules_file(path, rules: list[AlertRule]) -> None:
    """Write rules as YAML (or JSON for a .json path) — the exact shape
    `load_rules_file` reads back."""
    import json
    from pathlib import Path

    import yaml

    p = Path(path)
    doc = {"rules": [rule_to_dict(r) for r in rules]}
    if p.suffix == ".json":
        p.write_text(json.dumps(doc, indent=2))
    else:
        p.write_text(yaml.safe_dump(doc, sort_keys=False))


class _SeriesState:
    """One label set's state machine (Prometheus keys alert state by
    series, not by rule)."""

    __slots__ = ("labels", "state", "pending_since", "fired_before",
                 "last_value", "last_transition", "transitions",
                 "last_partial", "last_seen")

    def __init__(self, labels: dict | None = None):
        self.labels = dict(labels or {})
        self.state = STATE_INACTIVE
        self.pending_since: int | None = None
        self.fired_before = False
        self.last_value: float | None = None
        self.last_transition = 0
        self.transitions = 0
        self.last_partial = False
        self.last_seen = 0  # event time of the last eval WITH data


class _RuleState:
    """Per-rule bookkeeping: the series map + rule-level eval counters.
    Bounded: beyond MAX_SERIES new label sets are counted-dropped (the
    held-buffer stance everywhere else in the tree); inactive series
    that stop reporting are garbage-collected each evaluation."""

    MAX_SERIES = 512

    __slots__ = ("series", "last_eval", "evals", "eval_errors",
                 "last_partial", "series_dropped", "_transitions_base",
                 "_last_transition_base")

    def __init__(self):
        self.series: dict[tuple, _SeriesState] = {}
        self.last_eval = 0
        self.evals = 0
        self.eval_errors = 0
        self.last_partial = False
        self.series_dropped = 0
        self._transitions_base = 0  # transitions of GC'd series
        self._last_transition_base = 0  # newest transition of GC'd series

    def worst(self) -> _SeriesState | None:
        """The most severe series (ties: larger value) — the rule-level
        rollup the single-state faces report."""
        best = None
        for ss in self.series.values():
            if best is None:
                best = ss
                continue
            key = (_SEVERITY[ss.state], ss.last_value or 0.0)
            bkey = (_SEVERITY[best.state], best.last_value or 0.0)
            if key > bkey:
                best = ss
        return best

    @property
    def state(self) -> str:
        w = self.worst()
        return STATE_INACTIVE if w is None else w.state

    @property
    def transitions(self) -> int:
        return self._transitions_base + sum(
            ss.transitions for ss in self.series.values()
        )


def log_notification_sink(event: dict) -> None:
    """The always-on default notification lane."""
    _log.warning(
        "ALERT %s: rule %r value=%s threshold %s %s (t=%s)",
        event["state"], event["rule"], event["value"], event["comparator"],
        event["threshold"], event["time"],
    )


def otlp_notification_sink(exporter, *, table: str = "l7_flow_log"):
    """→ a sink shipping alert transitions through an exporter's traces
    lane (the same path utils/spans.export_otlp uses), one span per
    transition: app_service = deepflow_tpu.alerts, endpoint = rule
    name, response_duration = the for-duration the rule held."""
    import numpy as np

    seq = {"n": 0}

    def sink(event: dict) -> None:
        seq["n"] += 1
        i = seq["n"]
        cols = {
            "time": np.asarray([int(event["time"])], np.uint32),
            "start_time": np.asarray([int(event["time"])], np.uint32),
            "response_duration": np.asarray(
                [int(event.get("held_s", 0)) * 1_000_000], np.uint32
            ),
            "app_service": np.asarray(["deepflow_tpu.alerts"]),
            "endpoint": np.asarray([f"{event['rule']}:{event['state']}"]),
            "trace_id": np.asarray([f"{i:032x}"]),
            "span_id": np.asarray([f"{i:016x}"]),
            "parent_span_id": np.asarray([""]),
        }
        exporter.export(table, cols)

    return sink


def wire_notification_sink(hub):
    """→ a sink fanning alert transitions to the wire hub's `alerts=1`
    watchers (ISSUE 19) — the wire twin of otlp_notification_sink: a
    firing rule reaches every connected `/v1/watch?alerts=1` stream
    (and, through the hub's bus hook, any in-process AlertFired
    consumer) without polling."""

    def sink(event: dict) -> None:
        hub.deliver_alert(dict(event))

    return sink


class _Sink:
    __slots__ = ("fn", "name", "failures", "detached")

    def __init__(self, fn, name):
        self.fn = fn
        self.name = name
        self.failures = 0
        self.detached = False


class AlertEngine:
    """Rules over one store, evaluated on push events (and `tick`)."""

    MAX_SINK_FAILURES = 4
    # a RESOLVED series that stops reporting is kept this many seconds
    # (event time) for flap-memory/visibility, then GC'd like an
    # inactive one — without this, churned series that once fired
    # (per-pod incident labels) occupy MAX_SERIES slots forever and
    # eventually block NEW series from ever alerting
    RESOLVED_RETENTION_S = 900

    def __init__(self, store, *, live=None, cache=None,
                 bus: QueryEventBus | None = None,
                 tracer: SpanTracer | None = None, name: str = "alerts",
                 log_sink: bool = True):
        from .live import default_live_registry

        self.store = store
        self.live = default_live_registry if live is None else live
        self.cache = cache
        self.tracer = tracer if tracer is not None else SpanTracer(
            service="deepflow_tpu.alerts"
        )
        self.name = name
        self._rules: dict[str, tuple[AlertRule, _RuleState]] = {}
        self._sinks: list[_Sink] = []
        self._lock = threading.Lock()
        # serializes rule evaluation + state transitions: bus dispatch
        # (a writer-flusher or feeder thread) and Server.tick run
        # concurrently, and an unguarded pending_since read racing a
        # transition's None-out would crash (int - None) or double-fire.
        # RLock, separate from _lock: _notify takes _lock inside.
        self._eval_lock = threading.RLock()
        self.counters = {
            "evals": 0,
            "eval_errors": 0,
            "notifications": 0,
            "sink_errors": 0,
            "sinks_detached": 0,
            "transitions": 0,
        }
        if log_sink:
            self.add_sink(log_notification_sink, name="log")
        self._bus = bus
        self._bus_handle = None
        if bus is not None:
            self._bus_handle = bus.subscribe(self.on_events, name=f"alerts:{name}")
        self._stats_src = register_countable("tpu_alert_rules", self, name=name)

    def close(self) -> None:
        """Detach from the bus AND the stats collector — a stopped
        engine on a shared bus must not keep firing rules against its
        (possibly stopped) store, nor keep dogfooding frozen counters
        next to a successor with the same name tag."""
        if self._bus is not None and self._bus_handle is not None:
            self._bus.unsubscribe(self._bus_handle)
            self._bus_handle = None
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)

    # -- registry --------------------------------------------------------
    def add_rule(self, rule: AlertRule) -> None:
        with self._lock:
            if rule.name in self._rules:
                raise ValueError(f"duplicate rule {rule.name!r}")
            self._rules[rule.name] = (rule, _RuleState())

    def remove_rule(self, name: str) -> None:
        with self._lock:
            self._rules.pop(name, None)

    # -- persistence (ISSUE 13 satellite: rules survive a restart) --------
    def save_rules(self, path) -> int:
        """Serialize every registered rule to a YAML/JSON file (shape:
        {"rules": [...]}). Returns the rule count. Per-series STATES are
        deliberately not persisted: they rebuild from evaluations after
        a restart (the for-ladder restarts from the next breach — a
        restart must not resurrect a stale pager state)."""
        with self._lock:
            rules = [r for r, _ in self._rules.values()]
        save_rules_file(path, rules)
        return len(rules)

    def load_rules(self, path, *, replace: bool = False) -> int:
        """Load + register rules from a YAML/JSON file. The WHOLE file
        validates before any rule registers (atomic); malformed entries
        raise ValueError naming the entry and field. With
        `replace=False` (default) a name collision with a live rule is
        an error — silently shadowing an active pager rule is worse
        than failing the load. Each loaded rule starts with FRESH
        per-series states; the next evaluations rebuild them."""
        rules = load_rules_file(path)
        with self._lock:
            if not replace:
                clash = [r.name for r in rules if r.name in self._rules]
                if clash:
                    raise ValueError(
                        f"alert rules file {path}: rules already "
                        f"registered: {clash} (load_rules(replace=True) "
                        "to replace them)"
                    )
            for r in rules:
                self._rules[r.name] = (r, _RuleState())
        return len(rules)

    def add_sink(self, fn, *, name: str = "?") -> _Sink:
        s = _Sink(fn, name)
        with self._lock:
            self._sinks.append(s)
        return s

    # -- evaluation ------------------------------------------------------
    def on_events(self, events) -> None:
        """Bus handler: ONE evaluation per matching rule per batch —
        K window closes in one drain cost one rule evaluation."""
        with self._lock:
            rules = list(self._rules.values())
        if not rules:
            return
        now = max((t for t in (event_time(e) for e in events) if t is not None),
                  default=None)
        touched = {
            (getattr(e, "db", None), getattr(e, "table", None)) for e in events
        }
        for rule, st in rules:
            if (rule.db, rule.table) in touched:
                self._evaluate(rule, st, now)

    def tick(self, now: int | None = None, *, all_rules: bool = False) -> None:
        """Wall-clock evaluation — the quiet-table path: a pending rule
        matures to firing (and a firing one resolves) even when no
        event arrives because traffic stopped. Only PENDING and FIRING
        rules evaluate by default: an inactive/resolved rule can only
        change on a breach, which requires new data, which publishes an
        event — re-running every rule's query per tick would be the
        per-poll cost the push plane exists to retire (`all_rules=True`
        restores the sweep for event-less deployments). Unlike the
        event path, `now=None` here resolves to the WALL clock — the
        whole point of the tick is that real time kept moving."""
        now = int(time.time()) if now is None else int(now)
        with self._lock:
            rules = list(self._rules.values())
        for rule, st in rules:
            # st.state iterates the series map — _eval_lock, like every
            # other series read (the bus thread mutates it mid-eval)
            with self._eval_lock:
                wanted = all_rules or st.state in (STATE_PENDING, STATE_FIRING)
            if wanted:
                self._evaluate(rule, st, now)

    def evaluate_rule(self, name: str, *, now: int | None = None):
        with self._lock:
            rule, st = self._rules[name]
        return self._evaluate(rule, st, now)

    def _query_series(
        self, rule: AlertRule, now: int
    ) -> list[tuple[tuple, dict, float, bool]]:
        """→ [(series_key, labels, value, partial)] — one entry per
        returned series (Prometheus alert semantics: every label set
        gets its own state machine). SQL rules produce one anonymous
        series from the first numeric cell; no rows → empty list (no
        data → no breach for every known series)."""
        if rule.engine == "promql":
            from .promql import query_instant

            rows = query_instant(
                self.store, rule.query, int(now), lookback_s=rule.lookback_s,
                db=rule.db, table=rule.table, live=self.live,
            )
            return [
                (tuple(sorted(r["labels"].items())), r["labels"],
                 float(r["value"]), bool(r.get("partial")))
                for r in rows
            ]
        from .engine import QueryEngine

        engine = QueryEngine(self.store, live=self.live, cache=False)
        res = engine.execute(rule.query)
        if not res.rows:
            return []
        for c in res.columns:
            try:
                return [((), {}, float(res.values[c][0]), bool(res.partial))]
            except (TypeError, ValueError):
                continue
        return []

    def _evaluate(self, rule: AlertRule, st: _RuleState, now: int | None):
        # now=None (an event batch with no data-timed event, e.g. pure
        # SnapshotAdvanced/ProfileSnapshot): re-evaluate at the rule's
        # LAST data time — under replay the wall clock is far from the
        # data and would silently resolve a firing rule over an empty
        # range
        with self._eval_lock:
            if now is None:
                now = st.last_eval or int(time.time())
            now = int(now)
            try:
                with self.tracer.span(SPAN_ALERT_EVAL):
                    series = self._query_series(rule, now)
            except Exception:
                st.eval_errors += 1
                with self._lock:
                    self.counters["eval_errors"] += 1
                return st.state
            st.evals += 1
            st.last_eval = now
            st.last_partial = any(p for *_, p in series)
            with self._lock:
                self.counters["evals"] += 1
            seen: set[tuple] = set()
            for key, labels, value, partial in series:
                ss = st.series.get(key)
                if ss is None:
                    if len(st.series) >= st.MAX_SERIES:
                        st.series_dropped += 1
                        continue
                    ss = st.series[key] = _SeriesState(labels)
                seen.add(key)
                ss.last_value = value
                ss.last_partial = partial
                ss.last_seen = now
                breach = _COMPARATORS[rule.comparator](value, rule.threshold)
                self._transition(rule, ss, breach, now)
            # series with no data this evaluation: no breach (a silent
            # series resolves rather than pages) — then GC so label
            # churn cannot poison the bounded map: inactive ones leave
            # immediately, RESOLVED ones after RESOLVED_RETENTION_S of
            # silence (they hold only flap memory by then — left
            # forever, 512 churned once-fired series would permanently
            # block every NEW label set from alerting)
            for key, ss in list(st.series.items()):
                if key in seen:
                    continue
                ss.last_value = None
                self._transition(rule, ss, False, now)
                if ss.state == STATE_INACTIVE or (
                    ss.state == STATE_RESOLVED
                    and now - max(ss.last_seen, ss.last_transition)
                    >= self.RESOLVED_RETENTION_S
                ):
                    st._transitions_base += ss.transitions
                    st._last_transition_base = max(
                        st._last_transition_base, ss.last_transition
                    )
                    del st.series[key]
            return st.state

    def _transition(self, rule: AlertRule, ss: _SeriesState, breach: bool,
                    now: int) -> str:
        old = ss.state
        if breach:
            if ss.state in (STATE_INACTIVE, STATE_RESOLVED):
                ss.state = STATE_PENDING
                ss.pending_since = now
            if ss.state == STATE_PENDING and now - ss.pending_since >= rule.for_s:
                ss.state = STATE_FIRING
        else:
            if ss.state == STATE_PENDING:
                # never matured: fall back quietly, no notification
                ss.state = STATE_RESOLVED if ss.fired_before else STATE_INACTIVE
                ss.pending_since = None
            elif ss.state == STATE_FIRING:
                ss.state = STATE_RESOLVED
                ss.pending_since = None
        if ss.state != old:
            ss.transitions += 1
            ss.last_transition = now
            with self._lock:
                self.counters["transitions"] += 1
            if ss.state == STATE_FIRING:
                ss.fired_before = True
                self._notify(rule, ss, STATE_FIRING, now)
            elif ss.state == STATE_RESOLVED and old == STATE_FIRING:
                self._notify(rule, ss, STATE_RESOLVED, now)
        return ss.state

    def _notify(self, rule: AlertRule, ss: _SeriesState, state: str, now: int):
        event = {
            "rule": rule.name,
            "state": state,
            "value": ss.last_value,
            "comparator": rule.comparator,
            "threshold": rule.threshold,
            "time": now,
            "held_s": (now - ss.pending_since) if ss.pending_since else 0,
            "partial": ss.last_partial,
            # rule labels + the firing series' own label set — a pager
            # line names WHICH series fired, not just which rule
            "labels": {**dict(rule.labels), **ss.labels},
        }
        with self._lock:
            sinks = [s for s in self._sinks if not s.detached]
            self.counters["notifications"] += 1
        for s in sinks:
            try:
                s.fn(event)
            except Exception:
                s.failures += 1
                with self._lock:
                    self.counters["sink_errors"] += 1
                if s.failures >= self.MAX_SINK_FAILURES:
                    s.detached = True
                    with self._lock:
                        self.counters["sinks_detached"] += 1
                        if s in self._sinks:
                            self._sinks.remove(s)
                    _log.exception(
                        "alert engine %s: notification sink %s detached "
                        "after %d consecutive failures",
                        self.name, s.name, s.failures,
                    )
            else:
                s.failures = 0

    # -- read faces ------------------------------------------------------
    # Series maps are mutated by _evaluate under _eval_lock (inserts on
    # new label sets, deletes on GC); every reader that ITERATES one —
    # state()'s worst() rollup, list_rules, series_states, the
    # Countable face a ticking collector thread samples — must hold
    # _eval_lock too, or a concurrent evaluation turns the read into
    # "dictionary changed size during iteration". Lock order: _lock is
    # only ever taken INSIDE _eval_lock (never the reverse), so the
    # readers take _lock first standalone, release it, then _eval_lock.

    def state(self, name: str) -> str:
        with self._lock:
            st = self._rules[name][1]
        with self._eval_lock:
            return st.state

    def series_states(self, name: str) -> list[dict]:
        """Per-series detail for one rule (the Prometheus /api/v1/rules
        alerts shape): one row per tracked label set."""
        with self._lock:
            _, st = self._rules[name]
        with self._eval_lock:
            series = list(st.series.values())
        return [
            {
                "labels": dict(ss.labels),
                "state": ss.state,
                "value": ss.last_value,
                "partial": ss.last_partial,
                "transitions": ss.transitions,
                "last_transition": ss.last_transition,
            }
            for ss in series
        ]

    def list_rules(self) -> list[dict]:
        """The dfctl listing: one row per rule — the worst series'
        state/value as the rule-level rollup, per-series detail in
        `series`."""
        with self._lock:
            rules = list(self._rules.values())
        out = []
        for r, st in rules:
            with self._eval_lock:
                out.append(self._rule_row(r, st))
        return out

    def _rule_row(self, r: AlertRule, st: _RuleState) -> dict:
        worst = st.worst()
        return {
                "name": r.name,
                "query": r.query,
                "condition": f"{r.comparator} {r.threshold}",
                "for_s": r.for_s,
                "state": st.state,
                "value": None if worst is None else worst.last_value,
                "partial": st.last_partial,
                "evals": st.evals,
                "transitions": st.transitions,
                # GC'd series fold their newest transition into the
                # base so the rule-level stamp never regresses to 0
                # while transitions stays > 0
                "last_transition": max(
                    max((ss.last_transition for ss in st.series.values()),
                        default=0),
                    st._last_transition_base,
                ),
                "series": [
                    {
                        "labels": dict(ss.labels),
                        "state": ss.state,
                        "value": ss.last_value,
                        "transitions": ss.transitions,
                    }
                    for ss in st.series.values()
                ],
            }

    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            rules = list(self._rules.values())
        out["rules"] = len(rules)
        with self._eval_lock:
            # rule-level rollups (a rule counts as firing when ANY of
            # its series fires) + the total tracked-series accounting —
            # all series-map iteration, hence under the eval lock (the
            # collector tick thread samples this mid-evaluation)
            out["firing"] = sum(st.state == STATE_FIRING for _, st in rules)
            out["pending"] = sum(st.state == STATE_PENDING for _, st in rules)
            out["series"] = sum(len(st.series) for _, st in rules)
            out["series_dropped"] = sum(st.series_dropped for _, st in rules)
            for r, st in rules:
                slug = _NAME_SAN_RE.sub("_", r.name)
                out[f"rule_{slug}_state_code"] = STATE_CODES[st.state]
                out[f"rule_{slug}_transitions"] = st.transitions
                out[f"rule_{slug}_firing_series"] = sum(
                    ss.state == STATE_FIRING for ss in st.series.values()
                )
        return out
