"""Push-mode query plane, layer 3 (ISSUE 11): the alerting rule engine.

The reference server's querier serves exactly two consumers — Grafana
dashboards and alert rules. Subscriptions (subscribe.py) are the
dashboard half; an alert rule is the same machinery with a comparator
and a threshold bolted on: a standing query re-evaluated on push
events, whose RESULT feeds a small per-rule state machine instead of a
websocket.

Rule = query (PromQL instant — including `topk()` / distinct /
quantile queries the sketch plane answers — or SQL) + comparator +
threshold + `for`-duration. States:

    inactive ──breach──▶ pending ──held for ≥ for_s──▶ firing
       ▲                    │                            │
       └────no breach───────┘                       no breach
                                                         ▼
    resolved ◀───────────────────────────────────────────┘
       └──breach──▶ pending  (flap suppression: a re-fire after a
                              resolve walks the FULL pending ladder
                              again — a flapping series cannot ring
                              the pager at event rate)

Time is the event plane's DATA time (`events.event_time` batch max),
so `for`-durations advance deterministically under replay and tests;
`tick(now)` drives the same evaluation from a wall clock for processes
whose tables go quiet (a pending rule must still mature to firing when
traffic stops precisely because it stopped).

Transitions notify pluggable sinks: `log_notification_sink` (always
available), arbitrary callbacks, and `otlp_notification_sink(exporter)`
— alert events ride the same exporter traces lane the span tracer uses,
so a firing rule shows up in the trace backend next to the pipeline
stages that produced it. A raising sink is counted and DETACHED after
`MAX_SINK_FAILURES` consecutive failures; it never stalls the drain.

Dogfood: the engine registers as a Countable (`tpu_alert_rules`), with
per-rule state codes and transition counts as flat lanes — rule states
are queryable via SQL and PromQL
(`tpu_alert_rules_rule_<name>_state_code`) like every other component.
"""

from __future__ import annotations

import dataclasses
import logging
import re
import threading
import time

from ..utils.spans import SPAN_ALERT_EVAL, SpanTracer
from ..utils.stats import register_countable
from .events import QueryEventBus, event_time

_log = logging.getLogger(__name__)

STATE_INACTIVE = "inactive"
STATE_PENDING = "pending"
STATE_FIRING = "firing"
STATE_RESOLVED = "resolved"

#: stable numeric codes for the dogfood lanes (SQL/PromQL-queryable)
STATE_CODES = {
    STATE_INACTIVE: 0,
    STATE_PENDING: 1,
    STATE_FIRING: 2,
    STATE_RESOLVED: 3,
}

_COMPARATORS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    "==": lambda v, t: v == t,
    "!=": lambda v, t: v != t,
}

_NAME_SAN_RE = re.compile(r"[^a-zA-Z0-9_]")


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One rule spec. `engine` picks evaluation: "promql" runs
    `query_instant` at the event time over (db, table) and compares the
    MAX series value (so `topk(k, m)`-shaped heavy-hitter rules compare
    the biggest recovered flow); "sql" executes the statement and
    compares the first numeric cell of the first row. No data → no
    breach (a silent series resolves rather than pages)."""

    name: str
    query: str
    comparator: str  # one of > >= < <= == !=
    threshold: float
    for_s: int = 0
    engine: str = "promql"  # "promql" | "sql"
    db: str = "deepflow_system"
    table: str = "deepflow_system"
    lookback_s: int = 300
    labels: tuple[tuple[str, str], ...] = ()

    def __post_init__(self):
        if self.comparator not in _COMPARATORS:
            raise ValueError(f"unknown comparator {self.comparator!r}")
        if self.engine not in ("promql", "sql"):
            raise ValueError(f"unknown engine {self.engine!r}")
        if self.for_s < 0:
            raise ValueError("for_s must be >= 0")


class _RuleState:
    __slots__ = ("state", "pending_since", "fired_before", "last_value",
                 "last_eval", "last_transition", "transitions", "evals",
                 "eval_errors", "last_partial")

    def __init__(self):
        self.state = STATE_INACTIVE
        self.pending_since: int | None = None
        self.fired_before = False
        self.last_value: float | None = None
        self.last_eval = 0
        self.last_transition = 0
        self.transitions = 0
        self.evals = 0
        self.eval_errors = 0
        self.last_partial = False


def log_notification_sink(event: dict) -> None:
    """The always-on default notification lane."""
    _log.warning(
        "ALERT %s: rule %r value=%s threshold %s %s (t=%s)",
        event["state"], event["rule"], event["value"], event["comparator"],
        event["threshold"], event["time"],
    )


def otlp_notification_sink(exporter, *, table: str = "l7_flow_log"):
    """→ a sink shipping alert transitions through an exporter's traces
    lane (the same path utils/spans.export_otlp uses), one span per
    transition: app_service = deepflow_tpu.alerts, endpoint = rule
    name, response_duration = the for-duration the rule held."""
    import numpy as np

    seq = {"n": 0}

    def sink(event: dict) -> None:
        seq["n"] += 1
        i = seq["n"]
        cols = {
            "time": np.asarray([int(event["time"])], np.uint32),
            "start_time": np.asarray([int(event["time"])], np.uint32),
            "response_duration": np.asarray(
                [int(event.get("held_s", 0)) * 1_000_000], np.uint32
            ),
            "app_service": np.asarray(["deepflow_tpu.alerts"]),
            "endpoint": np.asarray([f"{event['rule']}:{event['state']}"]),
            "trace_id": np.asarray([f"{i:032x}"]),
            "span_id": np.asarray([f"{i:016x}"]),
            "parent_span_id": np.asarray([""]),
        }
        exporter.export(table, cols)

    return sink


class _Sink:
    __slots__ = ("fn", "name", "failures", "detached")

    def __init__(self, fn, name):
        self.fn = fn
        self.name = name
        self.failures = 0
        self.detached = False


class AlertEngine:
    """Rules over one store, evaluated on push events (and `tick`)."""

    MAX_SINK_FAILURES = 4

    def __init__(self, store, *, live=None, cache=None,
                 bus: QueryEventBus | None = None,
                 tracer: SpanTracer | None = None, name: str = "alerts",
                 log_sink: bool = True):
        from .live import default_live_registry

        self.store = store
        self.live = default_live_registry if live is None else live
        self.cache = cache
        self.tracer = tracer if tracer is not None else SpanTracer(
            service="deepflow_tpu.alerts"
        )
        self.name = name
        self._rules: dict[str, tuple[AlertRule, _RuleState]] = {}
        self._sinks: list[_Sink] = []
        self._lock = threading.Lock()
        # serializes rule evaluation + state transitions: bus dispatch
        # (a writer-flusher or feeder thread) and Server.tick run
        # concurrently, and an unguarded pending_since read racing a
        # transition's None-out would crash (int - None) or double-fire.
        # RLock, separate from _lock: _notify takes _lock inside.
        self._eval_lock = threading.RLock()
        self.counters = {
            "evals": 0,
            "eval_errors": 0,
            "notifications": 0,
            "sink_errors": 0,
            "sinks_detached": 0,
            "transitions": 0,
        }
        if log_sink:
            self.add_sink(log_notification_sink, name="log")
        self._bus = bus
        self._bus_handle = None
        if bus is not None:
            self._bus_handle = bus.subscribe(self.on_events, name=f"alerts:{name}")
        self._stats_src = register_countable("tpu_alert_rules", self, name=name)

    def close(self) -> None:
        """Detach from the bus AND the stats collector — a stopped
        engine on a shared bus must not keep firing rules against its
        (possibly stopped) store, nor keep dogfooding frozen counters
        next to a successor with the same name tag."""
        if self._bus is not None and self._bus_handle is not None:
            self._bus.unsubscribe(self._bus_handle)
            self._bus_handle = None
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)

    # -- registry --------------------------------------------------------
    def add_rule(self, rule: AlertRule) -> None:
        with self._lock:
            if rule.name in self._rules:
                raise ValueError(f"duplicate rule {rule.name!r}")
            self._rules[rule.name] = (rule, _RuleState())

    def remove_rule(self, name: str) -> None:
        with self._lock:
            self._rules.pop(name, None)

    def add_sink(self, fn, *, name: str = "?") -> _Sink:
        s = _Sink(fn, name)
        with self._lock:
            self._sinks.append(s)
        return s

    # -- evaluation ------------------------------------------------------
    def on_events(self, events) -> None:
        """Bus handler: ONE evaluation per matching rule per batch —
        K window closes in one drain cost one rule evaluation."""
        with self._lock:
            rules = list(self._rules.values())
        if not rules:
            return
        now = max((t for t in (event_time(e) for e in events) if t is not None),
                  default=None)
        touched = {
            (getattr(e, "db", None), getattr(e, "table", None)) for e in events
        }
        for rule, st in rules:
            if (rule.db, rule.table) in touched:
                self._evaluate(rule, st, now)

    def tick(self, now: int | None = None, *, all_rules: bool = False) -> None:
        """Wall-clock evaluation — the quiet-table path: a pending rule
        matures to firing (and a firing one resolves) even when no
        event arrives because traffic stopped. Only PENDING and FIRING
        rules evaluate by default: an inactive/resolved rule can only
        change on a breach, which requires new data, which publishes an
        event — re-running every rule's query per tick would be the
        per-poll cost the push plane exists to retire (`all_rules=True`
        restores the sweep for event-less deployments). Unlike the
        event path, `now=None` here resolves to the WALL clock — the
        whole point of the tick is that real time kept moving."""
        now = int(time.time()) if now is None else int(now)
        with self._lock:
            rules = list(self._rules.values())
        for rule, st in rules:
            if all_rules or st.state in (STATE_PENDING, STATE_FIRING):
                self._evaluate(rule, st, now)

    def evaluate_rule(self, name: str, *, now: int | None = None):
        with self._lock:
            rule, st = self._rules[name]
        return self._evaluate(rule, st, now)

    def _query_value(self, rule: AlertRule, now: int) -> tuple[float | None, bool]:
        """→ (value, partial): the scalar the comparator sees, and
        whether a live open-window partial produced it."""
        if rule.engine == "promql":
            from .promql import query_instant

            rows = query_instant(
                self.store, rule.query, int(now), lookback_s=rule.lookback_s,
                db=rule.db, table=rule.table, live=self.live,
            )
            if not rows:
                return None, False
            best = max(rows, key=lambda r: r["value"])
            return float(best["value"]), any(r.get("partial") for r in rows)
        from .engine import QueryEngine

        engine = QueryEngine(self.store, live=self.live, cache=False)
        res = engine.execute(rule.query)
        if not res.rows:
            return None, False
        for c in res.columns:
            try:
                return float(res.values[c][0]), res.partial
            except (TypeError, ValueError):
                continue
        return None, res.partial

    def _evaluate(self, rule: AlertRule, st: _RuleState, now: int | None):
        # now=None (an event batch with no data-timed event, e.g. pure
        # SnapshotAdvanced): re-evaluate at the rule's LAST data time —
        # under replay the wall clock is far from the data and would
        # silently resolve a firing rule over an empty range
        with self._eval_lock:
            if now is None:
                now = st.last_eval or int(time.time())
            now = int(now)
            try:
                with self.tracer.span(SPAN_ALERT_EVAL):
                    value, partial = self._query_value(rule, now)
            except Exception:
                st.eval_errors += 1
                with self._lock:
                    self.counters["eval_errors"] += 1
                return st.state
            st.evals += 1
            st.last_eval = now
            st.last_value = value
            st.last_partial = partial
            with self._lock:
                self.counters["evals"] += 1
            breach = value is not None and _COMPARATORS[rule.comparator](
                value, rule.threshold
            )
            return self._transition(rule, st, breach, now)

    def _transition(self, rule: AlertRule, st: _RuleState, breach: bool,
                    now: int) -> str:
        old = st.state
        if breach:
            if st.state in (STATE_INACTIVE, STATE_RESOLVED):
                st.state = STATE_PENDING
                st.pending_since = now
            if st.state == STATE_PENDING and now - st.pending_since >= rule.for_s:
                st.state = STATE_FIRING
        else:
            if st.state == STATE_PENDING:
                # never matured: fall back quietly, no notification
                st.state = STATE_RESOLVED if st.fired_before else STATE_INACTIVE
                st.pending_since = None
            elif st.state == STATE_FIRING:
                st.state = STATE_RESOLVED
                st.pending_since = None
        if st.state != old:
            st.transitions += 1
            st.last_transition = now
            with self._lock:
                self.counters["transitions"] += 1
            if st.state == STATE_FIRING:
                st.fired_before = True
                self._notify(rule, st, STATE_FIRING, now)
            elif st.state == STATE_RESOLVED and old == STATE_FIRING:
                self._notify(rule, st, STATE_RESOLVED, now)
        return st.state

    def _notify(self, rule: AlertRule, st: _RuleState, state: str, now: int):
        event = {
            "rule": rule.name,
            "state": state,
            "value": st.last_value,
            "comparator": rule.comparator,
            "threshold": rule.threshold,
            "time": now,
            "held_s": (now - st.pending_since) if st.pending_since else 0,
            "partial": st.last_partial,
            "labels": dict(rule.labels),
        }
        with self._lock:
            sinks = [s for s in self._sinks if not s.detached]
            self.counters["notifications"] += 1
        for s in sinks:
            try:
                s.fn(event)
            except Exception:
                s.failures += 1
                with self._lock:
                    self.counters["sink_errors"] += 1
                if s.failures >= self.MAX_SINK_FAILURES:
                    s.detached = True
                    with self._lock:
                        self.counters["sinks_detached"] += 1
                        if s in self._sinks:
                            self._sinks.remove(s)
                    _log.exception(
                        "alert engine %s: notification sink %s detached "
                        "after %d consecutive failures",
                        self.name, s.name, s.failures,
                    )
            else:
                s.failures = 0

    # -- read faces ------------------------------------------------------
    def state(self, name: str) -> str:
        with self._lock:
            return self._rules[name][1].state

    def list_rules(self) -> list[dict]:
        """The dfctl listing: one row per rule with its live state."""
        with self._lock:
            rules = list(self._rules.values())
        return [
            {
                "name": r.name,
                "query": r.query,
                "condition": f"{r.comparator} {r.threshold}",
                "for_s": r.for_s,
                "state": st.state,
                "value": st.last_value,
                "partial": st.last_partial,
                "evals": st.evals,
                "transitions": st.transitions,
                "last_transition": st.last_transition,
            }
            for r, st in rules
        ]

    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            rules = list(self._rules.values())
        out["rules"] = len(rules)
        out["firing"] = sum(st.state == STATE_FIRING for _, st in rules)
        out["pending"] = sum(st.state == STATE_PENDING for _, st in rules)
        for r, st in rules:
            slug = _NAME_SAN_RE.sub("_", r.name)
            out[f"rule_{slug}_state_code"] = STATE_CODES[st.state]
            out[f"rule_{slug}_transitions"] = st.transitions
        return out
