"""Query executor — the CHEngine seat (clickhouse.go:117 ExecuteQuery).

The reference translates DeepFlow-SQL to ClickHouse SQL and lets CK
execute; here the engine *is* the executor, running directly over the
columnar store: partition-pruned scans (time-range conjuncts hoisted
from WHERE), vectorized row filters, group-by via factorized keys +
`jax.ops.segment_*` reductions (the same segment machinery as the
ingest hot path), derived-metric expansion (metrics.py), and query-time
tag translation (translation.py — the dictGet seat).

Aggregate functions: Sum Max Min Avg Count Uniq. Scalar helpers:
interval(time, N) → N-second bucket (toStartOfInterval analog),
name(col) → dictionary translation of a tag id column.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np

from .metrics import (
    datasource_interval,
    expand,
    expand_row,
    is_delay,
    list_metrics,
    metric_type,
)
from .sqlparse import (
    BinOp,
    Func,
    Ident,
    InList,
    Literal,
    Query,
    Show,
    SQLError,
    UnaryOp,
    parse,
)
from .translation import Translator

# row→group reducers (view/function.go FUNCTION_*)
_AGG_FUNCS = {
    "sum", "max", "min", "avg", "aavg", "count", "uniq", "uniqexact",
    "countdistinct", "percentile", "percentileexact", "stddev", "spread",
    "rspread", "apdex", "last", "any", "topk", "histogram",
}
# group-level math wrappers that force aggregation even over bare columns
_AGG_WRAPPERS = {"persecond", "percentage", "derivative", "nonnegativederivative"}


@dataclasses.dataclass
class Result:
    columns: list[str]
    values: dict[str, np.ndarray]
    # live read plane (ISSUE 10): True when open-window partial rows
    # from a registered live source contributed to this result — the
    # values for the open span may still grow until the window closes
    # and its flushed rows supersede the partials (stale=false,
    # partial=true in the reference's result-marker terms)
    partial: bool = False

    @property
    def rows(self) -> int:
        return len(next(iter(self.values.values()))) if self.values else 0

    def to_dicts(self) -> list[dict]:
        return [
            {c: self.values[c][i].item() if hasattr(self.values[c][i], "item") else self.values[c][i] for c in self.columns}
            for i in range(self.rows)
        ]


class QueryEngine:
    def __init__(self, store, translator: Translator | None = None,
                 *, live=None, cache=None):
        from .live import default_live_registry, default_query_cache

        self.store = store
        self.translator = translator or Translator(store)
        # live read plane (ISSUE 10): open-window overlay providers and
        # the repeated-dashboard result cache (None = process defaults;
        # cache=False disables caching for this engine)
        self.live = default_live_registry if live is None else live
        if cache is None or cache is True:
            self.cache = default_query_cache
        elif cache is False:
            self.cache = None
        else:
            self.cache = cache

    # -- public ---------------------------------------------------------
    def resolve_query_table(self, sql: str) -> tuple[str, str]:
        """The (db, table) a statement will read, without executing it —
        the push plane's event-routing key: a SQL subscription resolves
        once here and then re-evaluates only when events name its
        table (querier/subscribe.py)."""
        q = parse(sql)
        if isinstance(q, Show):
            raise SQLError("SHOW statements have no subscribable table")
        trange = _time_range(q.where) if q.where is not None else None
        return self._resolve_table(q.table, step=_requested_step(q), trange=trange)

    def execute(self, sql: str) -> Result:
        q = parse(sql)
        if isinstance(q, Show):
            return self._run_show(q)
        # the hoisted time range drives partition pruning, the live
        # overlay AND live-aware tier selection — computed before
        # resolution (it reads only the raw WHERE AST)
        trange = _time_range(q.where) if q.where is not None else None
        db, table = self._resolve_table(
            q.table, step=_requested_step(q), trange=trange
        )
        key = token = None
        if self.cache is not None:
            from .live import cache_token

            key = ("sql", sql, db, table, getattr(self.store, "uid", id(self.store)))
            # token BEFORE evaluation: a pipeline provider's epoch() may
            # take the rate-limited snapshot the evaluation then reads
            token = cache_token(self.store, db, table, self.live)
            hit = self.cache.lookup(key, token)
            if hit is not None:
                return hit
        res = self._execute_resolved(q, db, table, trange)
        if self.cache is not None:
            self.cache.store(key, token, res)
        return res

    def _execute_resolved(self, q: Query, db: str, table: str, trange) -> Result:
        schema = self.store.schema(db, table)
        colnames = set(schema.column_names())

        # expand derived metrics in select/order (WHERE stays raw columns)
        # output names come from the pre-expansion AST (rrt_avg stays
        # "rrt_avg", not its Sum()/Sum() expansion)
        q = dataclasses.replace(
            q,
            select=tuple(
                dataclasses.replace(
                    it,
                    expr=self._expand(table, it.expr),
                    alias=it.alias or _expr_name(it.expr),
                )
                for it in q.select
            ),
            # ORDER BY keeps the pre-expansion expr: resolution first
            # matches select-output names, then expands for evaluation
            order_by=tuple(q.order_by),
            having=self._expand(table, q.having) if q.having is not None else None,
        )

        # GROUP BY / HAVING may name a select alias ("group by time_120",
        # "having cnt > 5", clickhouse_test.go:60) — substitute the
        # aliased expression
        alias_map = {it.alias: it.expr for it in q.select
                     if it.alias and it.alias not in colnames}
        q = dataclasses.replace(
            q,
            group_by=tuple(
                alias_map[e.name]
                if isinstance(e, Ident) and e.name in alias_map
                else self._expand(table, e)
                for e in q.group_by
            ),
            having=(_subst_aliases(q.having, alias_map)
                    if q.having is not None else None),
        )

        has_agg = bool(q.group_by) or q.having is not None or any(
            _has_aggregate(it.expr) for it in q.select
        )

        aliases = {it.alias for it in q.select if it.alias}
        needed = set()
        for it in q.select:
            _collect_idents(it.expr, needed)
        for e in q.group_by:
            _collect_idents(e, needed)
        for e, _ in q.order_by:
            _collect_idents(self._expand(table, e), needed)
        if q.where is not None:
            _collect_idents(q.where, needed)
        if q.having is not None:
            _collect_idents(q.having, needed)
        if has_agg:
            # Last/Derivative/Counter_Avg need the time axis
            needed.add(schema.time_column)
        star = "*" in needed
        needed.discard("*")
        # ORDER BY may reference select output names; real columns stay
        needed -= aliases - colnames
        unknown = needed - colnames
        if unknown:
            raise SQLError(f"unknown columns for {table}: {sorted(unknown)}")

        if star:
            scan_cols = None  # SELECT * reads everything
        elif needed:
            scan_cols = sorted(needed)
        else:
            scan_cols = [schema.time_column]  # SELECT Count(): cheapest column
        cols = self.store.scan(db, table, time_range=trange, columns=scan_cols)
        n = len(next(iter(cols.values()))) if cols else 0

        # open-window overlay (ISSUE 10): append live partial rows when
        # a provider is registered and serves every scanned column. The
        # WHERE mask applies to them identically; the result is marked
        # partial iff any live row survived it.
        n_store = n
        if self.live.has(db, table) and cols:
            lo, hi = trange if trange is not None else (0, 1 << 62)
            lv = self.live.columns(db, table, lo, hi)
            if lv is not None and all(k in lv for k in cols):
                lt = np.asarray(lv[schema.time_column], np.int64)
                sel = (lt >= lo) & (lt < hi)
                if sel.any():
                    cols = {
                        k: np.concatenate(
                            [np.asarray(cols[k]), np.asarray(lv[k])[sel]]
                        )
                        for k in cols
                    }
                    n = n_store + int(sel.sum())

        ctx = _EvalCtx(cols, n, table, self.translator)
        mask = None
        if q.where is not None:
            mask = np.asarray(ctx.eval(q.where), bool)
            ctx = ctx.masked(mask)
        partial = bool(
            mask[n_store:].any() if mask is not None else n > n_store
        )

        if has_agg:
            res = self._run_aggregate(q, ctx, table, schema, trange)
        else:
            res = self._run_plain(q, ctx, schema)
        res.partial = partial
        return res

    # -- helpers --------------------------------------------------------
    def _touches_open(self, db: str, table: str, trange) -> bool:
        """Does a query over `trange` reach into the open span a live
        provider serves? Unbounded upper ranges always do; bounded ones
        only when they extend past the provider's first open second."""
        if not self.live.has(db, table):
            return False
        if trange is None or trange[1] >= (1 << 61):
            return True
        of = self.live.open_from(db, table)
        return of is not None and trange[1] > of

    def _resolve_table(
        self, name: str, step: int | None = None, trange=None
    ) -> tuple[str, str]:
        # accept db.table / table.granularity / bare table
        cand = name.replace(".", "_")
        parts = name.split(".", 1)
        for db in self.store.databases():
            if parts[0] == db and len(parts) == 2:
                t = parts[1].replace(".", "_")
                if t in self.store.tables(db):
                    return db, t
            if cand in self.store.tables(db):
                return db, cand
        # tier selection (ISSUE 9): a BARE family name ("network")
        # resolves to the coarsest granularity table that satisfies the
        # query's interval step, so month-scale range queries read the
        # cascade's bounded 1m/1h tiers instead of replaying 1s rows.
        # Explicit granularities ("network.1s") never reroute — they
        # resolved above. ISSUE 10: when the range touches the open
        # span, a LIVE-covered tier beats a coarser one without
        # coverage (the coarser rows would miss the freshest seconds
        # the overlay exists to serve).
        from .translation import TIER_SUFFIX_S, select_datasource_tier

        for db in self.store.databases():
            avail = {}
            for suffix, s in TIER_SUFFIX_S.items():
                t = f"{cand}_{suffix}"
                if t in self.store.tables(db):
                    avail[t] = s
            live_set = {
                t for t in avail if self._touches_open(db, t, trange)
            }
            pick = select_datasource_tier(avail, step, live_tables=live_set)
            if pick is not None:
                return db, pick
        raise SQLError(f"no such table {name!r}")

    def _expand(self, table: str, expr, in_agg: bool = False):
        if isinstance(expr, Ident):
            if not in_agg:
                sub = expand(table, expr.name)
                if sub is not None:
                    return sub
            # row-level derived (Sum(byte) → SUM(byte_tx + byte_rx), and
            # bare `byte` on log tables)
            sub = expand_row(table, expr.name)
            if sub is not None:
                return sub
        elif isinstance(expr, BinOp):
            return BinOp(expr.op, self._expand(table, expr.left, in_agg),
                         self._expand(table, expr.right, in_agg))
        elif isinstance(expr, UnaryOp):
            return UnaryOp(expr.op, self._expand(table, expr.operand, in_agg))
        elif isinstance(expr, Func) and expr.name in _AGG_FUNCS:
            return Func(expr.name, tuple(self._expand(table, a, True) for a in expr.args))
        elif isinstance(expr, Func):
            return Func(expr.name, tuple(self._expand(table, a, in_agg) for a in expr.args))
        return expr

    def _run_plain(self, q: Query, ctx: "_EvalCtx", schema) -> Result:
        items = []
        for it in q.select:
            if isinstance(it.expr, Ident) and it.expr.name == "*":
                items += [(c, Ident(c)) for c in schema.column_names() if c in ctx.cols]
            else:
                items.append((it.alias or _expr_name(it.expr), it.expr))
        values = {name: np.asarray(ctx.eval(e)) for name, e in items}
        values = {k: (np.broadcast_to(v, (ctx.n,)) if v.ndim == 0 else v) for k, v in values.items()}
        # ORDER BY resolves select output names first, then raw columns
        order = [
            (values[_expr_name(e)] if _expr_name(e) in values else np.asarray(ctx.eval(e)), d)
            for e, d in q.order_by
        ]
        idx = _order_index(order, ctx.n)
        idx = idx[q.offset : None if q.limit is None else q.offset + q.limit]
        return Result([n for n, _ in items], {k: v[idx] for k, v in values.items()})

    def _run_aggregate(self, q: Query, ctx: "_EvalCtx", table: str,
                       schema=None, trange=None) -> Result:
        # group keys → factorized codes
        key_names = [_expr_name(e) for e in q.group_by]
        key_arrays = [np.asarray(ctx.eval(e)) for e in q.group_by]
        if key_arrays:
            codes = [np.unique(a, return_inverse=True) for a in key_arrays]
            stacked = np.stack([c[1] for c in codes], axis=1)
            uniq_rows, gid = np.unique(stacked, axis=0, return_inverse=True)
            ngroups = uniq_rows.shape[0]
            key_values = {
                name: codes[j][0][uniq_rows[:, j]] for j, name in enumerate(key_names)
            }
        else:
            gid = np.zeros(ctx.n, np.int64)
            ngroups = 1
            key_values = {}

        # time axis for Derivative/PerSecond/Counter_Avg: the group key
        # built from interval(time, N) (or bare time), plus the partition
        # id formed by every OTHER group key
        group_interval = None
        time_key = None
        for e in q.group_by:
            nm = _expr_name(e)
            if isinstance(e, Func) and e.name == "interval" and len(e.args) == 2:
                group_interval = int(e.args[1].value)
                time_key = nm
            elif isinstance(e, Ident) and e.name == (schema.time_column if schema else "time"):
                time_key = nm
        if time_key is not None and len(key_names) > 1:
            others = [j for j, nm in enumerate(key_names) if nm != time_key]
            partition = np.unique(uniq_rows[:, others], axis=0, return_inverse=True)[1]
        else:
            partition = np.zeros(ngroups, np.int64)
        env = _AggEnv(
            table=table,
            ds_interval=datasource_interval(table),
            trange=trange,
            group_interval=group_interval,
            time_column=schema.time_column if schema else "time",
            group_times=(None if time_key is None
                         else np.asarray(key_values[time_key], np.int64)),
            partition=partition,
        )
        agg_ctx = _AggCtx(ctx, gid, ngroups, env)

        items = [(it.alias or _expr_name(it.expr), it.expr) for it in q.select]
        values: dict[str, np.ndarray] = {}
        for name, e in items:
            if name in key_values:
                values[name] = key_values[name]
            elif _expr_name(e) in key_values:  # aliased group expr
                values[name] = key_values[_expr_name(e)]
            else:
                v = np.asarray(agg_ctx.eval(e))
                values[name] = np.broadcast_to(v, (ngroups,)) if v.ndim == 0 else v

        keep = np.ones(ngroups, bool)
        if q.having is not None:
            keep = np.broadcast_to(
                np.asarray(agg_ctx.eval(q.having), bool), (ngroups,)
            )
        order = []
        for e, d in q.order_by:
            nm = _expr_name(e)
            if nm in values:
                order.append((values[nm], d))
            elif nm in key_values:
                order.append((key_values[nm], d))
            else:
                order.append((np.asarray(agg_ctx.eval(self._expand(table, e))), d))
        idx = _order_index(order, ngroups)
        idx = idx[keep[idx]]
        idx = idx[q.offset : None if q.limit is None else q.offset + q.limit]
        return Result([n for n, _ in items], {k: np.asarray(v)[idx] for k, v in values.items()})

    def _run_show(self, q: Show) -> Result:
        """SHOW tables / metrics / tags — catalog rows as a result set."""
        if q.what == "tables":
            rows = [
                {"db": db, "table": t}
                for db in sorted(self.store.databases())
                for t in sorted(self.store.tables(db))
            ]
            cols = ["db", "table"]
        else:
            # resolve db-qualified names the way SELECT does, and make
            # unknown tables error instead of returning an empty catalog
            _, bare = self._resolve_table(q.table)
            cat = self.catalogs(bare)
            rows = cat["metrics"] if q.what == "metrics" else cat["tags"]
            rows = [
                {k: (", ".join(v) if isinstance(v, list) else v)
                 for k, v in r.items()}
                for r in rows
            ]
            cols = list(rows[0].keys()) if rows else ["name"]
        values = {
            c: np.asarray([r.get(c, "") for r in rows]) for c in cols
        }
        return Result(cols, values)

    def catalogs(self, table: str) -> dict:
        """db_descriptions seat: tag + metric catalogs for one table."""
        from .metrics import metric_catalog, tag_catalog

        schema = None
        try:
            db, t = self._resolve_table(table)
            schema = self.store.schema(db, t)
        except (SQLError, KeyError):
            pass
        return {
            "table": table,
            "metrics": metric_catalog(table, schema),
            "tags": tag_catalog(table, schema),
        }

    def metrics(self, table: str) -> dict[str, str]:
        return list_metrics(table)


# -- evaluation contexts ----------------------------------------------------


class _EvalCtx:
    """Row-level vectorized evaluation over scanned columns."""

    def __init__(self, cols: dict[str, np.ndarray], n: int, table: str, translator):
        self.cols = cols
        self.n = n
        self.table = table
        self.translator = translator

    def masked(self, mask: np.ndarray) -> "_EvalCtx":
        return _EvalCtx(
            {k: v[mask] for k, v in self.cols.items()},
            int(mask.sum()),
            self.table,
            self.translator,
        )

    def eval(self, e):
        if isinstance(e, Literal):
            return e.value
        if isinstance(e, Ident):
            if e.name not in self.cols:
                raise SQLError(f"unknown column {e.name!r}")
            return self.cols[e.name]
        if isinstance(e, UnaryOp):
            v = self.eval(e.operand)
            return ~np.asarray(v, bool) if e.op == "not" else -np.asarray(v)
        if isinstance(e, InList):
            v = np.asarray(self.eval(e.expr))
            vals = [x.value for x in e.values]
            if v.dtype.kind in "US":
                m = np.isin(v, np.asarray(vals, dtype=v.dtype))
            else:
                m = np.isin(v, np.asarray(vals))
            return ~m if e.negated else m
        if isinstance(e, BinOp):
            l, r = self.eval(e.left), self.eval(e.right)
            return _binop(e.op, l, r)
        if isinstance(e, Func):
            return self._func(e)
        raise SQLError(f"cannot evaluate {e!r}")

    def _func(self, e: Func):
        if e.name == "interval":
            if len(e.args) != 2 or not isinstance(e.args[1], Literal):
                raise SQLError("interval(col, seconds)")
            v = np.asarray(self.eval(e.args[0]), np.int64)
            step = int(e.args[1].value)
            return (v // step * step).astype(np.uint32)
        if e.name == "name":
            if len(e.args) != 1 or not isinstance(e.args[0], Ident):
                raise SQLError("name(tag_column)")
            col = e.args[0].name
            return self.translator.translate(self.table, col, np.asarray(self.eval(e.args[0])))
        if e.name in ("k8s_label", "k8s_annotation", "k8s_env"):
            # k8s_label(pod_id_col, 'key') → per-row label value (the
            # reference's `k8s.label.<key>` custom tag)
            if len(e.args) != 2 or not isinstance(e.args[1], Literal):
                raise SQLError(f"{e.name}(pod_id_column, 'key')")
            ids = np.asarray(self.eval(e.args[0]))
            return self.translator.k8s_meta(
                e.name.removeprefix("k8s_"), str(e.args[1].value), ids
            )
        if e.name in _AGG_FUNCS or e.name in _AGG_WRAPPERS:
            raise SQLError(f"aggregate {e.name}() outside aggregation context")
        raise SQLError(f"unknown function {e.name!r}")


@dataclasses.dataclass
class _AggEnv:
    """Time/typing context the group-level functions need (the view
    layer's Time struct, function.go GetInterval)."""

    table: str
    ds_interval: int
    trange: tuple[int, int] | None  # [lo, hi) from WHERE
    group_interval: int | None  # interval(time, N) step in GROUP BY
    time_column: str
    group_times: np.ndarray | None  # [ngroups] time bucket per group
    partition: np.ndarray  # [ngroups] series id from non-time group keys


class _AggCtx:
    """Aggregate evaluation: aggregates reduce rows → groups, everything
    above them is per-group arithmetic. Delay-type metrics get the
    reference's ignore-zero treatment (AVGIf/MAXIf(x > 0)); Avg on a
    counter divides the range sum by range/ds-interval (Counter_Avg)."""

    def __init__(self, row_ctx: _EvalCtx, gid: np.ndarray, ngroups: int,
                 env: _AggEnv | None = None):
        self.row = row_ctx
        self.gid = gid
        self.ngroups = ngroups
        self.env = env or _AggEnv("", 1, None, None, "time", None,
                                  np.zeros(ngroups, np.int64))

    def eval(self, e):
        if isinstance(e, Literal):
            return e.value
        if isinstance(e, Func) and e.name in _AGG_FUNCS:
            return self._agg(e)
        if isinstance(e, Func) and e.name in _AGG_WRAPPERS:
            return self._wrapper(e)
        if isinstance(e, BinOp):
            return _binop(e.op, self.eval(e.left), self.eval(e.right))
        if isinstance(e, UnaryOp):
            v = self.eval(e.operand)
            return ~np.asarray(v, bool) if e.op == "not" else -np.asarray(v)
        if isinstance(e, Func):
            raise SQLError(f"scalar function {e.name}() above aggregates is unsupported")
        if isinstance(e, Ident):
            raise SQLError(
                f"column {e.name!r} must appear in GROUP BY or inside an aggregate"
            )
        raise SQLError(f"cannot evaluate {e!r}")

    # -- helpers ---------------------------------------------------------
    def _masked_gid(self, v: np.ndarray, mask: np.ndarray) -> np.ndarray:
        """Rows failing `mask` get an out-of-range gid → dropped."""
        return np.where(mask, self.gid, self.ngroups)

    def _delay_arg(self, a) -> bool:
        return isinstance(a, Ident) and is_delay(self.env.table, a.name)

    def _sum(self, v, gid=None):
        return np.asarray(jax.ops.segment_sum(
            v.astype(np.float64), self.gid if gid is None else gid,
            self.ngroups + 1)[: self.ngroups])

    def _mean(self, v, gid):
        s = self._sum(v, gid)
        c = self._sum(np.ones_like(v, np.float64), gid)
        return s / np.maximum(c, 1)

    def _minmax(self, v, gid, fn):
        r = np.asarray(fn(v.astype(np.float64), gid, self.ngroups + 1)[: self.ngroups])
        return np.where(np.isfinite(r), r, 0.0)

    def _n_intervals(self) -> float:
        """Counter_Avg divisor: how many datasource rows one output
        bucket spans (GetInterval, view/function.go:866-885)."""
        env = self.env
        ds = max(1, env.ds_interval)
        if env.group_interval:
            return max(1.0, env.group_interval / ds)
        if env.trange is not None and env.trange[1] < (1 << 61):
            lo, hi = env.trange
            return max(1.0, (hi - lo) / ds)
        t = self.row.cols.get(env.time_column)
        if t is not None and len(t):
            return max(1.0, (float(np.max(t)) - float(np.min(t))) / ds + 1)
        return 1.0

    def _series_seconds(self) -> float:
        env = self.env
        if env.group_interval:
            return float(env.group_interval)
        if env.trange is not None and env.trange[1] < (1 << 61):
            return float(max(1, env.trange[1] - env.trange[0]))
        return float(max(1, env.ds_interval))

    # -- group-level math wrappers --------------------------------------
    def _wrapper(self, e: Func):
        if e.name == "persecond":
            if len(e.args) != 1:
                raise SQLError("PerSecond() takes one argument")
            inner = self._auto_agg(e.args[0])
            return np.asarray(self.eval(inner)) / self._series_seconds()
        if e.name == "percentage":
            if not 1 <= len(e.args) <= 2:
                raise SQLError("Percentage() takes one or two arguments")
            a = np.asarray(self.eval(self._auto_agg(e.args[0])), np.float64)
            b = (np.asarray(self.eval(self._auto_agg(e.args[1])), np.float64)
                 if len(e.args) == 2 else np.float64(1.0))
            return np.divide(a, b, out=np.zeros(np.broadcast(a, b).shape),
                             where=np.asarray(b) != 0) * 100.0
        # nonNegativeDerivative over the time axis, partitioned by the
        # other group keys (view/function.go NonNegativeDerivativeFunction)
        if len(e.args) != 1:
            raise SQLError("Derivative() takes one argument")
        env = self.env
        if env.group_times is None:
            raise SQLError("Derivative() needs interval(time, N) or time in GROUP BY")
        v = np.asarray(self.eval(self._auto_agg(e.args[0])), np.float64)
        v = np.broadcast_to(v, (self.ngroups,))
        t = env.group_times
        out = np.zeros(self.ngroups, np.float64)
        order = np.lexsort((t, env.partition))
        sp, st, sv = env.partition[order], t[order], v[order]
        same = np.concatenate([[False], sp[1:] == sp[:-1]])
        dt = np.maximum(np.concatenate([[1], st[1:] - st[:-1]]), 1)
        d = np.concatenate([[0.0], sv[1:] - sv[:-1]]) / dt
        out[order] = np.where(same, np.maximum(d, 0.0), 0.0)
        return out

    def _auto_agg(self, a):
        """Bare column/row expr inside a wrapper defaults to Sum —
        PerSecond(byte) ≡ PerSecond(Sum(byte))."""
        return a if _has_aggregate(a) else Func("sum", (a,))

    # -- aggregates ------------------------------------------------------
    def _agg(self, e: Func):
        if e.name == "count":
            return self._sum(np.ones(len(self.gid), np.float64))
        if e.name in ("percentile", "percentileexact"):
            # Percentile(col, p) — CK quantile analog, per group
            if len(e.args) != 2:
                raise SQLError("percentile() takes (column, p)")
            v = np.asarray(self.row.eval(e.args[0])).astype(np.float64)
            p = float(np.asarray(self.row.eval(e.args[1])).reshape(-1)[0])
            if not 0 <= p <= 100:
                raise SQLError(f"percentile p out of range: {p}")
            gid = (self._masked_gid(v, v > 0)
                   if self._delay_arg(e.args[0]) else self.gid)
            out = np.zeros(self.ngroups, np.float64)
            order = np.argsort(gid, kind="stable")
            sg = gid[order]
            sv = v[order]
            starts = np.searchsorted(sg, np.arange(self.ngroups))
            ends = np.searchsorted(sg, np.arange(self.ngroups) + 1)
            for g in range(self.ngroups):
                if ends[g] > starts[g]:
                    out[g] = np.percentile(sv[starts[g]:ends[g]], p)
            return out
        if e.name == "apdex":
            # Apdex(delay, T): (satisfied + tolerating/2) / total over
            # x > 0, in [0, 1] (view/function.go ApdexFunction)
            if len(e.args) != 2:
                raise SQLError("Apdex() takes (column, threshold)")
            v = np.asarray(self.row.eval(e.args[0])).astype(np.float64)
            thr = float(np.asarray(self.row.eval(e.args[1])).reshape(-1)[0])
            pos = v > 0
            gid = self._masked_gid(v, pos)
            sat = self._sum((pos & (v <= thr)).astype(np.float64))
            tol = self._sum((pos & (v > thr) & (v <= 4 * thr)).astype(np.float64))
            tot = self._sum(pos.astype(np.float64))
            return np.divide(sat + tol / 2, tot, out=np.zeros_like(tot), where=tot > 0)
        if e.name == "topk":
            if len(e.args) != 2:
                raise SQLError("TopK() takes (column, k)")
            v = np.asarray(self.row.eval(e.args[0]))
            k = int(np.asarray(self.row.eval(e.args[1])).reshape(-1)[0])
            return self._per_group_json(
                v, lambda vals: [x.item() if hasattr(x, "item") else x
                                 for x, _ in _top_frequent(vals, k)])
        if e.name == "histogram":
            if len(e.args) != 2:
                raise SQLError("Histogram() takes (column, bins)")
            v = np.asarray(self.row.eval(e.args[0])).astype(np.float64)
            bins = int(np.asarray(self.row.eval(e.args[1])).reshape(-1)[0])

            def hist(vals):
                vals = vals[vals > 0]
                if not len(vals):
                    return []
                cnt, edges = np.histogram(vals, bins=max(1, bins))
                return [[float(edges[i]), float(edges[i + 1]), int(cnt[i])]
                        for i in range(len(cnt))]

            return self._per_group_json(v, hist)
        if len(e.args) != 1:
            raise SQLError(f"{e.name}() takes one argument")
        v = np.asarray(self.row.eval(e.args[0]))
        if e.name in ("uniq", "uniqexact", "countdistinct"):
            pairs = np.stack([self.gid, np.unique(v, return_inverse=True)[1]], axis=1)
            uniq = np.unique(pairs, axis=0)
            return np.bincount(uniq[:, 0], minlength=self.ngroups).astype(np.float64)
        if e.name == "any":
            first = self._minmax(np.arange(len(v), dtype=np.float64), self.gid,
                                 jax.ops.segment_min).astype(np.int64)
            return v[np.clip(first, 0, max(0, len(v) - 1))] if len(v) else v
        if e.name == "last":
            # argMax(x, time) (FUNCTION_LAST)
            t = self.row.cols.get(self.env.time_column)
            key = (np.asarray(t, np.float64) if t is not None
                   else np.arange(len(v), dtype=np.float64))
            order = np.lexsort((key, self.gid))
            sg = self.gid[order]
            starts = np.searchsorted(sg, np.arange(self.ngroups))
            ends = np.searchsorted(sg, np.arange(self.ngroups) + 1)
            res = np.zeros(self.ngroups, v.dtype if v.dtype.kind != "U" else object)
            for g in range(self.ngroups):
                if ends[g] > starts[g]:
                    res[g] = v[order[ends[g] - 1]]
            return res
        v = v.astype(np.float64)
        delay = self._delay_arg(e.args[0])
        gid = self._masked_gid(v, v > 0) if delay else self.gid
        if e.name == "sum":
            return self._sum(v)
        if e.name == "aavg":
            return self._mean(v, gid)
        if e.name == "avg":
            # Counter_Avg only for counter metrics (incl. expressions
            # whose every leaf column is a counter, e.g. the expanded
            # byte_tx + byte_rx); anything untyped averages arithmetically
            leaves: set = set()
            _collect_idents(e.args[0], leaves)
            types = {metric_type(self.env.table, n) for n in leaves}
            if leaves and types == {"counter"}:
                # Counter_Avg: sum over the range / expected row count
                return self._sum(v) / self._n_intervals()
            return self._mean(v, gid)  # Delay_Avg seat: AVGIf(x, x>0)
        if e.name == "max":
            return self._minmax(v, gid, jax.ops.segment_max)
        if e.name == "min":
            return self._minmax(v, gid, jax.ops.segment_min)
        if e.name == "spread":
            return (self._minmax(v, gid, jax.ops.segment_max)
                    - self._minmax(v, gid, jax.ops.segment_min))
        if e.name == "rspread":
            mx = self._minmax(v, gid, jax.ops.segment_max) + 1e-15
            mn = self._minmax(v, gid, jax.ops.segment_min) + 1e-15
            return mx / mn
        if e.name == "stddev":
            m = self._mean(v, gid)
            m2 = self._mean(v * v, gid)
            return np.sqrt(np.maximum(m2 - m * m, 0.0))
        raise SQLError(f"unknown aggregate {e.name!r}")

    def _per_group_json(self, v: np.ndarray, fn) -> np.ndarray:
        import json as _json

        order = np.argsort(self.gid, kind="stable")
        sg = self.gid[order]
        starts = np.searchsorted(sg, np.arange(self.ngroups))
        ends = np.searchsorted(sg, np.arange(self.ngroups) + 1)
        out = np.empty(self.ngroups, object)
        for g in range(self.ngroups):
            out[g] = _json.dumps(fn(v[order[starts[g]:ends[g]]]))
        return out


def _subst_aliases(e, alias_map: dict):
    if isinstance(e, Ident) and e.name in alias_map:
        return alias_map[e.name]
    if isinstance(e, BinOp):
        return BinOp(e.op, _subst_aliases(e.left, alias_map),
                     _subst_aliases(e.right, alias_map))
    if isinstance(e, UnaryOp):
        return UnaryOp(e.op, _subst_aliases(e.operand, alias_map))
    if isinstance(e, Func):
        return Func(e.name, tuple(_subst_aliases(a, alias_map) for a in e.args))
    if isinstance(e, InList):
        return InList(_subst_aliases(e.expr, alias_map), e.values, e.negated)
    return e


def _top_frequent(vals: np.ndarray, k: int):
    uniq, counts = np.unique(vals, return_counts=True)
    order = np.argsort(-counts, kind="stable")[: max(0, k)]
    return [(uniq[i], int(counts[i])) for i in order]


# -- small shared helpers ---------------------------------------------------


def _order_index(order: list[tuple[np.ndarray, str]], n: int) -> np.ndarray:
    """Stable multi-key sort index; strings factorize to codes so DESC
    is a plain negation for every key type."""
    idx = np.arange(n)
    for arr, direction in reversed(order):
        arr = np.asarray(arr)
        if arr.dtype.kind in "US":
            arr = np.unique(arr, return_inverse=True)[1]
        key = -arr.astype(np.float64) if direction == "desc" else arr
        idx = idx[np.argsort(key[idx], kind="stable")]
    return idx


def _binop(op: str, l, r):
    if op == "and":
        return np.asarray(l, bool) & np.asarray(r, bool)
    if op == "or":
        return np.asarray(l, bool) | np.asarray(r, bool)
    if op in ("+", "-", "*", "/", "%"):
        l = np.asarray(l, np.float64) if not np.isscalar(l) else l
        r = np.asarray(r, np.float64) if not np.isscalar(r) else r
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            return np.divide(l, r, out=np.zeros(np.broadcast(l, r).shape), where=np.asarray(r) != 0)
        return np.mod(l, r)
    # comparisons — strings compare as strings
    larr, rarr = np.asarray(l), np.asarray(r)
    if larr.dtype.kind in "US" or rarr.dtype.kind in "US":
        larr, rarr = larr.astype(str), rarr.astype(str)
    else:
        larr, rarr = larr.astype(np.float64), rarr.astype(np.float64)
    return {
        "=": larr == rarr,
        "!=": larr != rarr,
        "<": larr < rarr,
        ">": larr > rarr,
        "<=": larr <= rarr,
        ">=": larr >= rarr,
    }[op]


def _collect_idents(e, out: set):
    if isinstance(e, Ident):
        out.add(e.name)
    elif isinstance(e, BinOp):
        _collect_idents(e.left, out)
        _collect_idents(e.right, out)
    elif isinstance(e, UnaryOp):
        _collect_idents(e.operand, out)
    elif isinstance(e, InList):
        _collect_idents(e.expr, out)
    elif isinstance(e, Func):
        for a in e.args:
            _collect_idents(a, out)


def _has_aggregate(e) -> bool:
    if isinstance(e, Func):
        return (e.name in _AGG_FUNCS or e.name in _AGG_WRAPPERS
                or any(_has_aggregate(a) for a in e.args))
    if isinstance(e, BinOp):
        return _has_aggregate(e.left) or _has_aggregate(e.right)
    if isinstance(e, UnaryOp):
        return _has_aggregate(e.operand)
    return False


def _expr_name(e) -> str:
    if isinstance(e, Ident):
        return e.name
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, Func):
        return f"{e.name}({', '.join(_expr_name(a) for a in e.args)})"
    if isinstance(e, BinOp):
        return f"{_expr_name(e.left)} {e.op} {_expr_name(e.right)}"
    if isinstance(e, UnaryOp):
        return f"{e.op}{_expr_name(e.operand)}"
    if isinstance(e, InList):
        return f"{_expr_name(e.expr)} in (...)"
    return str(e)


def _requested_step(q: Query) -> int | None:
    """The query's time-bucket step from a GROUP BY interval(time, N)
    (pre-expansion AST; GROUP BY may name a select alias of the
    interval expression) — the tier-selection input: a query bucketing
    at ≥60s never needs sub-minute rows."""
    aliases = {it.alias: it.expr for it in q.select if it.alias}
    for e in q.group_by:
        if isinstance(e, Ident):
            e = aliases.get(e.name, e)
        if (
            isinstance(e, Func)
            and e.name == "interval"
            and len(e.args) == 2
            and isinstance(e.args[1], Literal)
        ):
            try:
                return int(e.args[1].value)
            except (TypeError, ValueError):
                return None
    return None


def _time_range(where) -> tuple[int, int] | None:
    """Hoist time >=/>/<=/< conjuncts (AND chains only) for partition
    pruning; the full WHERE still runs as a row mask."""
    lo, hi = None, None

    def walk(e):
        nonlocal lo, hi
        if isinstance(e, BinOp) and e.op == "and":
            walk(e.left)
            walk(e.right)
            return
        if (
            isinstance(e, BinOp)
            and isinstance(e.left, Ident)
            and e.left.name == "time"
            and isinstance(e.right, Literal)
        ):
            v = int(e.right.value)
            if e.op in (">=", ">"):
                lo = v if lo is None else max(lo, v)
            elif e.op == "<":
                hi = v if hi is None else min(hi, v)
            elif e.op == "<=":
                hi = v + 1 if hi is None else min(hi, v + 1)
            elif e.op == "=":
                lo = v if lo is None else max(lo, v)
                hi = v + 1 if hi is None else min(hi, v + 1)

    walk(where)
    if lo is None and hi is None:
        return None
    return (lo or 0, hi if hi is not None else 1 << 62)
