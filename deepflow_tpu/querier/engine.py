"""Query executor — the CHEngine seat (clickhouse.go:117 ExecuteQuery).

The reference translates DeepFlow-SQL to ClickHouse SQL and lets CK
execute; here the engine *is* the executor, running directly over the
columnar store: partition-pruned scans (time-range conjuncts hoisted
from WHERE), vectorized row filters, group-by via factorized keys +
`jax.ops.segment_*` reductions (the same segment machinery as the
ingest hot path), derived-metric expansion (metrics.py), and query-time
tag translation (translation.py — the dictGet seat).

Aggregate functions: Sum Max Min Avg Count Uniq. Scalar helpers:
interval(time, N) → N-second bucket (toStartOfInterval analog),
name(col) → dictionary translation of a tag id column.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np

from .metrics import expand, list_metrics
from .sqlparse import BinOp, Func, Ident, InList, Literal, Query, SQLError, UnaryOp, parse
from .translation import Translator

_AGG_FUNCS = {"sum", "max", "min", "avg", "count", "uniq", "percentile"}


@dataclasses.dataclass
class Result:
    columns: list[str]
    values: dict[str, np.ndarray]

    @property
    def rows(self) -> int:
        return len(next(iter(self.values.values()))) if self.values else 0

    def to_dicts(self) -> list[dict]:
        return [
            {c: self.values[c][i].item() if hasattr(self.values[c][i], "item") else self.values[c][i] for c in self.columns}
            for i in range(self.rows)
        ]


class QueryEngine:
    def __init__(self, store, translator: Translator | None = None):
        self.store = store
        self.translator = translator or Translator(store)

    # -- public ---------------------------------------------------------
    def execute(self, sql: str) -> Result:
        q = parse(sql)
        db, table = self._resolve_table(q.table)
        schema = self.store.schema(db, table)
        colnames = set(schema.column_names())

        # expand derived metrics in select/order (WHERE stays raw columns)
        # output names come from the pre-expansion AST (rrt_avg stays
        # "rrt_avg", not its Sum()/Sum() expansion)
        q = dataclasses.replace(
            q,
            select=tuple(
                dataclasses.replace(
                    it,
                    expr=self._expand(table, it.expr),
                    alias=it.alias or _expr_name(it.expr),
                )
                for it in q.select
            ),
            # ORDER BY keeps the pre-expansion expr: resolution first
            # matches select-output names, then expands for evaluation
            order_by=tuple(q.order_by),
        )

        aliases = {it.alias for it in q.select if it.alias}
        needed = set()
        for it in q.select:
            _collect_idents(it.expr, needed)
        for e in q.group_by:
            _collect_idents(e, needed)
        for e, _ in q.order_by:
            _collect_idents(self._expand(table, e), needed)
        if q.where is not None:
            _collect_idents(q.where, needed)
        star = "*" in needed
        needed.discard("*")
        # ORDER BY may reference select output names; real columns stay
        needed -= aliases - colnames
        unknown = needed - colnames
        if unknown:
            raise SQLError(f"unknown columns for {table}: {sorted(unknown)}")

        trange = _time_range(q.where) if q.where is not None else None
        if star:
            scan_cols = None  # SELECT * reads everything
        elif needed:
            scan_cols = sorted(needed)
        else:
            scan_cols = [schema.time_column]  # SELECT Count(): cheapest column
        cols = self.store.scan(db, table, time_range=trange, columns=scan_cols)
        n = len(next(iter(cols.values()))) if cols else 0
        ctx = _EvalCtx(cols, n, table, self.translator)

        mask = None
        if q.where is not None:
            mask = np.asarray(ctx.eval(q.where), bool)
            ctx = ctx.masked(mask)

        has_agg = bool(q.group_by) or any(
            _has_aggregate(it.expr) for it in q.select
        )
        if has_agg:
            return self._run_aggregate(q, ctx, table)
        return self._run_plain(q, ctx, schema)

    # -- helpers --------------------------------------------------------
    def _resolve_table(self, name: str) -> tuple[str, str]:
        # accept db.table / table.granularity / bare table
        cand = name.replace(".", "_")
        parts = name.split(".", 1)
        for db in self.store.databases():
            if parts[0] == db and len(parts) == 2:
                t = parts[1].replace(".", "_")
                if t in self.store.tables(db):
                    return db, t
            if cand in self.store.tables(db):
                return db, cand
        raise SQLError(f"no such table {name!r}")

    def _expand(self, table: str, expr):
        if isinstance(expr, Ident):
            sub = expand(table, expr.name)
            if sub is not None:
                return sub
        elif isinstance(expr, BinOp):
            return BinOp(expr.op, self._expand(table, expr.left), self._expand(table, expr.right))
        elif isinstance(expr, UnaryOp):
            return UnaryOp(expr.op, self._expand(table, expr.operand))
        elif isinstance(expr, Func) and expr.name not in _AGG_FUNCS:
            return Func(expr.name, tuple(self._expand(table, a) for a in expr.args))
        return expr

    def _run_plain(self, q: Query, ctx: "_EvalCtx", schema) -> Result:
        items = []
        for it in q.select:
            if isinstance(it.expr, Ident) and it.expr.name == "*":
                items += [(c, Ident(c)) for c in schema.column_names() if c in ctx.cols]
            else:
                items.append((it.alias or _expr_name(it.expr), it.expr))
        values = {name: np.asarray(ctx.eval(e)) for name, e in items}
        values = {k: (np.broadcast_to(v, (ctx.n,)) if v.ndim == 0 else v) for k, v in values.items()}
        # ORDER BY resolves select output names first, then raw columns
        order = [
            (values[_expr_name(e)] if _expr_name(e) in values else np.asarray(ctx.eval(e)), d)
            for e, d in q.order_by
        ]
        idx = _order_index(order, ctx.n)
        idx = idx[q.offset : None if q.limit is None else q.offset + q.limit]
        return Result([n for n, _ in items], {k: v[idx] for k, v in values.items()})

    def _run_aggregate(self, q: Query, ctx: "_EvalCtx", table: str) -> Result:
        # group keys → factorized codes
        key_names = [_expr_name(e) for e in q.group_by]
        key_arrays = [np.asarray(ctx.eval(e)) for e in q.group_by]
        if key_arrays:
            codes = [np.unique(a, return_inverse=True) for a in key_arrays]
            stacked = np.stack([c[1] for c in codes], axis=1)
            uniq_rows, gid = np.unique(stacked, axis=0, return_inverse=True)
            ngroups = uniq_rows.shape[0]
            key_values = {
                name: codes[j][0][uniq_rows[:, j]] for j, name in enumerate(key_names)
            }
        else:
            gid = np.zeros(ctx.n, np.int64)
            ngroups = 1
            key_values = {}
        agg_ctx = _AggCtx(ctx, gid, ngroups)

        items = [(it.alias or _expr_name(it.expr), it.expr) for it in q.select]
        values: dict[str, np.ndarray] = {}
        for name, e in items:
            if name in key_values:
                values[name] = key_values[name]
            elif _expr_name(e) in key_values:  # aliased group expr
                values[name] = key_values[_expr_name(e)]
            else:
                v = np.asarray(agg_ctx.eval(e))
                values[name] = np.broadcast_to(v, (ngroups,)) if v.ndim == 0 else v
        order = []
        for e, d in q.order_by:
            nm = _expr_name(e)
            if nm in values:
                order.append((values[nm], d))
            elif nm in key_values:
                order.append((key_values[nm], d))
            else:
                order.append((np.asarray(agg_ctx.eval(self._expand(table, e))), d))
        idx = _order_index(order, ngroups)
        idx = idx[q.offset : None if q.limit is None else q.offset + q.limit]
        return Result([n for n, _ in items], {k: np.asarray(v)[idx] for k, v in values.items()})

    def metrics(self, table: str) -> dict[str, str]:
        return list_metrics(table)


# -- evaluation contexts ----------------------------------------------------


class _EvalCtx:
    """Row-level vectorized evaluation over scanned columns."""

    def __init__(self, cols: dict[str, np.ndarray], n: int, table: str, translator):
        self.cols = cols
        self.n = n
        self.table = table
        self.translator = translator

    def masked(self, mask: np.ndarray) -> "_EvalCtx":
        return _EvalCtx(
            {k: v[mask] for k, v in self.cols.items()},
            int(mask.sum()),
            self.table,
            self.translator,
        )

    def eval(self, e):
        if isinstance(e, Literal):
            return e.value
        if isinstance(e, Ident):
            if e.name not in self.cols:
                raise SQLError(f"unknown column {e.name!r}")
            return self.cols[e.name]
        if isinstance(e, UnaryOp):
            v = self.eval(e.operand)
            return ~np.asarray(v, bool) if e.op == "not" else -np.asarray(v)
        if isinstance(e, InList):
            v = np.asarray(self.eval(e.expr))
            vals = [x.value for x in e.values]
            if v.dtype.kind in "US":
                m = np.isin(v, np.asarray(vals, dtype=v.dtype))
            else:
                m = np.isin(v, np.asarray(vals))
            return ~m if e.negated else m
        if isinstance(e, BinOp):
            l, r = self.eval(e.left), self.eval(e.right)
            return _binop(e.op, l, r)
        if isinstance(e, Func):
            return self._func(e)
        raise SQLError(f"cannot evaluate {e!r}")

    def _func(self, e: Func):
        if e.name == "interval":
            if len(e.args) != 2 or not isinstance(e.args[1], Literal):
                raise SQLError("interval(col, seconds)")
            v = np.asarray(self.eval(e.args[0]), np.int64)
            step = int(e.args[1].value)
            return (v // step * step).astype(np.uint32)
        if e.name == "name":
            if len(e.args) != 1 or not isinstance(e.args[0], Ident):
                raise SQLError("name(tag_column)")
            col = e.args[0].name
            return self.translator.translate(self.table, col, np.asarray(self.eval(e.args[0])))
        if e.name in _AGG_FUNCS:
            raise SQLError(f"aggregate {e.name}() outside aggregation context")
        raise SQLError(f"unknown function {e.name!r}")


class _AggCtx:
    """Aggregate evaluation: aggregates reduce rows → groups, everything
    above them is per-group arithmetic."""

    def __init__(self, row_ctx: _EvalCtx, gid: np.ndarray, ngroups: int):
        self.row = row_ctx
        self.gid = gid
        self.ngroups = ngroups

    def eval(self, e):
        if isinstance(e, Literal):
            return e.value
        if isinstance(e, Func) and e.name in _AGG_FUNCS:
            return self._agg(e)
        if isinstance(e, BinOp):
            return _binop(e.op, self.eval(e.left), self.eval(e.right))
        if isinstance(e, UnaryOp):
            v = self.eval(e.operand)
            return ~np.asarray(v, bool) if e.op == "not" else -np.asarray(v)
        if isinstance(e, Func):
            raise SQLError(f"scalar function {e.name}() above aggregates is unsupported")
        if isinstance(e, Ident):
            raise SQLError(
                f"column {e.name!r} must appear in GROUP BY or inside an aggregate"
            )
        raise SQLError(f"cannot evaluate {e!r}")

    def _agg(self, e: Func):
        if e.name == "count":
            return np.asarray(
                jax.ops.segment_sum(np.ones(len(self.gid), np.float32), self.gid, self.ngroups)
            )
        if e.name == "percentile":
            # Percentile(col, p) — CK quantile analog, per group
            if len(e.args) != 2:
                raise SQLError("percentile() takes (column, p)")
            v = np.asarray(self.row.eval(e.args[0])).astype(np.float64)
            p = float(np.asarray(self.row.eval(e.args[1])).reshape(-1)[0])
            if not 0 <= p <= 100:
                raise SQLError(f"percentile p out of range: {p}")
            out = np.zeros(self.ngroups, np.float64)
            order = np.argsort(self.gid, kind="stable")
            sg = self.gid[order]
            sv = v[order]
            starts = np.searchsorted(sg, np.arange(self.ngroups))
            ends = np.searchsorted(sg, np.arange(self.ngroups) + 1)
            for g in range(self.ngroups):
                if ends[g] > starts[g]:
                    out[g] = np.percentile(sv[starts[g]:ends[g]], p)
            return out
        if len(e.args) != 1:
            raise SQLError(f"{e.name}() takes one argument")
        v = np.asarray(self.row.eval(e.args[0]))
        if e.name == "uniq":
            pairs = np.stack([self.gid, np.unique(v, return_inverse=True)[1]], axis=1)
            uniq = np.unique(pairs, axis=0)
            return np.bincount(uniq[:, 0], minlength=self.ngroups).astype(np.float64)
        v = v.astype(np.float32)
        if e.name == "sum":
            return np.asarray(jax.ops.segment_sum(v, self.gid, self.ngroups))
        if e.name == "avg":
            s = np.asarray(jax.ops.segment_sum(v, self.gid, self.ngroups))
            c = np.asarray(
                jax.ops.segment_sum(np.ones_like(v), self.gid, self.ngroups)
            )
            return s / np.maximum(c, 1)
        if e.name == "max":
            r = np.asarray(jax.ops.segment_max(v, self.gid, self.ngroups))
            return np.where(np.isfinite(r), r, 0.0)
        if e.name == "min":
            r = np.asarray(jax.ops.segment_min(v, self.gid, self.ngroups))
            return np.where(np.isfinite(r), r, 0.0)
        raise SQLError(f"unknown aggregate {e.name!r}")


# -- small shared helpers ---------------------------------------------------


def _order_index(order: list[tuple[np.ndarray, str]], n: int) -> np.ndarray:
    """Stable multi-key sort index; strings factorize to codes so DESC
    is a plain negation for every key type."""
    idx = np.arange(n)
    for arr, direction in reversed(order):
        arr = np.asarray(arr)
        if arr.dtype.kind in "US":
            arr = np.unique(arr, return_inverse=True)[1]
        key = -arr.astype(np.float64) if direction == "desc" else arr
        idx = idx[np.argsort(key[idx], kind="stable")]
    return idx


def _binop(op: str, l, r):
    if op == "and":
        return np.asarray(l, bool) & np.asarray(r, bool)
    if op == "or":
        return np.asarray(l, bool) | np.asarray(r, bool)
    if op in ("+", "-", "*", "/", "%"):
        l = np.asarray(l, np.float64) if not np.isscalar(l) else l
        r = np.asarray(r, np.float64) if not np.isscalar(r) else r
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            return np.divide(l, r, out=np.zeros(np.broadcast(l, r).shape), where=np.asarray(r) != 0)
        return np.mod(l, r)
    # comparisons — strings compare as strings
    larr, rarr = np.asarray(l), np.asarray(r)
    if larr.dtype.kind in "US" or rarr.dtype.kind in "US":
        larr, rarr = larr.astype(str), rarr.astype(str)
    else:
        larr, rarr = larr.astype(np.float64), rarr.astype(np.float64)
    return {
        "=": larr == rarr,
        "!=": larr != rarr,
        "<": larr < rarr,
        ">": larr > rarr,
        "<=": larr <= rarr,
        ">=": larr >= rarr,
    }[op]


def _collect_idents(e, out: set):
    if isinstance(e, Ident):
        out.add(e.name)
    elif isinstance(e, BinOp):
        _collect_idents(e.left, out)
        _collect_idents(e.right, out)
    elif isinstance(e, UnaryOp):
        _collect_idents(e.operand, out)
    elif isinstance(e, InList):
        _collect_idents(e.expr, out)
    elif isinstance(e, Func):
        for a in e.args:
            _collect_idents(a, out)


def _has_aggregate(e) -> bool:
    if isinstance(e, Func):
        return e.name in _AGG_FUNCS or any(_has_aggregate(a) for a in e.args)
    if isinstance(e, BinOp):
        return _has_aggregate(e.left) or _has_aggregate(e.right)
    if isinstance(e, UnaryOp):
        return _has_aggregate(e.operand)
    return False


def _expr_name(e) -> str:
    if isinstance(e, Ident):
        return e.name
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, Func):
        return f"{e.name}({', '.join(_expr_name(a) for a in e.args)})"
    if isinstance(e, BinOp):
        return f"{_expr_name(e.left)} {e.op} {_expr_name(e.right)}"
    if isinstance(e, UnaryOp):
        return f"{e.op}{_expr_name(e.operand)}"
    if isinstance(e, InList):
        return f"{_expr_name(e.expr)} in (...)"
    return str(e)


def _time_range(where) -> tuple[int, int] | None:
    """Hoist time >=/>/<=/< conjuncts (AND chains only) for partition
    pruning; the full WHERE still runs as a row mask."""
    lo, hi = None, None

    def walk(e):
        nonlocal lo, hi
        if isinstance(e, BinOp) and e.op == "and":
            walk(e.left)
            walk(e.right)
            return
        if (
            isinstance(e, BinOp)
            and isinstance(e.left, Ident)
            and e.left.name == "time"
            and isinstance(e.right, Literal)
        ):
            v = int(e.right.value)
            if e.op in (">=", ">"):
                lo = v if lo is None else max(lo, v)
            elif e.op == "<":
                hi = v if hi is None else min(hi, v)
            elif e.op == "<=":
                hi = v + 1 if hi is None else min(hi, v + 1)
            elif e.op == "=":
                lo = v if lo is None else max(lo, v)
                hi = v + 1 if hi is None else min(hi, v + 1)

    walk(where)
    if lo is None and hi is None:
        return None
    return (lo or 0, hi if hi is not None else 1 << 62)
