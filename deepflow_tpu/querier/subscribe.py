"""Push-mode query plane, layer 2 (ISSUE 11): query subscriptions.

A dashboard storm is thousands of clients asking the SAME question at
the same cadence. The r14 result cache collapsed the *recompute* cost
(81× on the repeated read, PERF.md §19) but every client still polls;
this module inverts the flow: a PromQL/SQL query registers ONCE, the
`events.QueryEventBus` tells the manager when its (db, table) moved,
the manager re-evaluates against the live overlay ONE time and fans
the result out to N watchers. N dashboards cost one evaluation per
data change, not one evaluation per client per poll tick.

Shape:

  * `SubscriptionManager.subscribe_promql(query, span_s=, step=)` — a
    range query pinned to "now": each evaluation runs `query_range`
    over `[now - span_s, now]` where `now` is the event batch's data
    time (`events.event_time` max; wall clock only when no event
    carries one), so results are deterministic under replay.
    `subscribe_sql(sql)` — the SQL is evaluated as written; its
    (db, table) is resolved once at subscribe time for event routing.
  * **Dedup**: identical query specs share ONE Subscription — a second
    `subscribe_*` call with the same spec just adds a watcher.
  * **Watchers**: `sub.watch(callback)` or `sub.watch()` (queue mode:
    a bounded deque the client drains; overflow drops the OLDEST
    result, counted — a slow websocket must not hold results for the
    fast ones). A callback that raises is counted and DETACHED after
    `MAX_WATCHER_FAILURES` consecutive failures — it never stalls the
    drain that published the event.
  * **Coalescing**: handlers receive the whole publish batch, so K
    window closes in one drain mark the subscription dirty K times but
    evaluate ONCE (`coalesced_events` counts the K−1 savings).

Every evaluation runs under `SPAN_SUBSCRIPTION_EVAL` on the manager's
tracer; the manager registers as a Countable (`tpu_query_subscriptions`)
so fan-out amplification (deliveries/evals) is queryable via SQL and
PromQL like every other lane. Evaluations go through the shared result
cache, so a subscription doubles as the cache re-warmer: the entry a
push event just dropped is recomputed by the one subscription eval and
every plain pull after it hits.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils.spans import SPAN_SUBSCRIPTION_EVAL, SpanTracer
from ..utils.stats import register_countable
from .events import QueryEventBus, event_time

DEFAULT_WATCHER_QUEUE = 64


class Watcher:
    """One consumer of a subscription's evaluations: callback mode
    (`callback(result, subscription)`) or queue mode (bounded deque,
    client drains with `poll()`).

    Lease (ISSUE 12 satellite): with `lease_s` set the watcher must
    renew within that many seconds — `poll()` renews implicitly (an
    actively-draining queue dashboard never expires), a SUCCESSFUL
    callback delivery renews too (callback mode has no poll; accepting
    the delivery is its heartbeat), and `renew()` renews explicitly
    (the wire layer calls it per client heartbeat). A watcher that
    misses its lease is REAPED by the manager (counted,
    `watchers_reaped`): an abandoned dashboard client stops holding a
    bounded queue — and its share of fan-out work — forever. lease_s
    None (default) never expires, today's behavior."""

    MAX_WATCHER_FAILURES = 4

    __slots__ = ("callback", "queue", "delivered", "dropped", "errors",
                 "_failstreak", "detached", "lease_s", "last_renew")

    def __init__(self, callback=None, *, maxlen: int = DEFAULT_WATCHER_QUEUE,
                 lease_s: float | None = None):
        self.callback = callback
        self.queue: deque | None = None if callback is not None else deque(
            maxlen=max(1, maxlen)
        )
        self.delivered = 0
        self.dropped = 0
        self.errors = 0
        self._failstreak = 0
        self.detached = False
        self.lease_s = lease_s
        self.last_renew = time.monotonic()

    def renew(self) -> None:
        """Refresh the lease (client liveness heartbeat)."""
        self.last_renew = time.monotonic()

    def expired(self, now_monotonic: float | None = None) -> bool:
        if self.lease_s is None:
            return False
        now = time.monotonic() if now_monotonic is None else now_monotonic
        return now - self.last_renew > self.lease_s

    def deliver(self, result, sub) -> bool:
        if self.callback is not None:
            try:
                self.callback(result, sub)
            except Exception:
                self.errors += 1
                self._failstreak += 1
                if self._failstreak >= self.MAX_WATCHER_FAILURES:
                    self.detached = True
                return False
            self._failstreak = 0
            self.delivered += 1
            # a callback that keeps ACCEPTING deliveries is alive — it
            # has no poll() to renew through, so successful delivery IS
            # its heartbeat (queue mode must NOT renew here: the queue
            # fills whether or not anyone drains it — only poll() proves
            # a queue client exists)
            self.renew()
            return True
        if len(self.queue) == self.queue.maxlen:
            self.dropped += 1  # deque drops the OLDEST on append
        self.queue.append(result)
        self.delivered += 1
        return True

    def poll(self, *, renew: bool = True):
        """Queue mode: pop the oldest pending result (None = empty).
        Polling renews the lease by default — an actively-draining
        client is by definition alive. The WIRE plane passes
        `renew=False`: there the server-side delivery loop polls on the
        client's behalf, so the pop itself proves nothing about the
        client — only a successful socket write does, and the wire lane
        calls `renew()` explicitly after one (a disconnected client's
        lease must lapse even while the server keeps polling)."""
        if renew:
            self.renew()
        if self.queue is None or not self.queue:
            return None
        return self.queue.popleft()


class Subscription:
    """One registered query + its watcher set; evaluation is owned by
    the manager (one eval per event batch, shared by every watcher)."""

    def __init__(self, key: tuple, kind: str, query: str, db: str, table: str,
                 evaluate):
        self.key = key
        self.kind = kind  # "promql" | "sql"
        self.query = query
        self.db = db
        self.table = table
        self._evaluate = evaluate  # (now:int) -> result
        self.watchers: list[Watcher] = []
        self.evals = 0
        self.eval_errors = 0
        self.deliveries = 0
        self.coalesced_events = 0
        self.last_eval_us = 0
        self.last_now = 0
        self.last_result = None

    def watch(self, callback=None, *, maxlen: int = DEFAULT_WATCHER_QUEUE,
              lease_s: float | None = None) -> Watcher:
        w = Watcher(callback, maxlen=maxlen, lease_s=lease_s)
        self.watchers.append(w)
        return w

    def unwatch(self, watcher: Watcher) -> None:
        if watcher in self.watchers:
            self.watchers.remove(watcher)


class SubscriptionManager:
    """Standing queries over one store, evaluated on push events."""

    def __init__(self, store, *, live=None, cache=None, bus: QueryEventBus | None = None,
                 tracer: SpanTracer | None = None, name: str = "subs"):
        from .live import default_live_registry, default_query_cache

        self.store = store
        self.live = default_live_registry if live is None else live
        self.cache = default_query_cache if cache is None else (
            None if cache is False else cache
        )
        self.tracer = tracer if tracer is not None else SpanTracer(
            service="deepflow_tpu.subscribe"
        )
        self.name = name
        self._subs: dict[tuple, Subscription] = {}
        self._lock = threading.Lock()
        self.counters = {
            "event_batches": 0,
            "evals": 0,
            "eval_errors": 0,
            "deliveries": 0,
            "coalesced_events": 0,
            "watcher_drops": 0,
            "watcher_errors": 0,
            "watchers_detached": 0,
            "watchers_reaped": 0,
        }
        # serializes evaluation + fan-out: bus dispatch is single-
        # threaded by the bus itself, but the public evaluate() may be
        # called from any thread concurrently with it
        self._eval_lock = threading.RLock()
        self._bus = bus
        self._bus_handle = None
        if bus is not None:
            self._bus_handle = bus.subscribe(self.on_events, name=f"subs:{name}")
        self._stats_src = register_countable(
            "tpu_query_subscriptions", self, name=name
        )

    def close(self) -> None:
        """Detach from the bus AND the stats collector — a stopped
        manager on a shared bus must not keep evaluating against its
        (possibly stopped) store, nor keep dogfooding frozen counters
        next to a successor with the same name tag."""
        if self._bus is not None and self._bus_handle is not None:
            self._bus.unsubscribe(self._bus_handle)
            self._bus_handle = None
        from ..utils.stats import default_collector

        default_collector.deregister(self._stats_src)

    # -- registration ----------------------------------------------------
    def subscribe_promql(
        self, query: str, *, span_s: int, step: int, db: str, table: str,
        lookback_s: int = 300, callback=None, queue: bool = False,
        maxlen: int = DEFAULT_WATCHER_QUEUE, lease_s: float | None = None,
    ) -> tuple[Subscription, Watcher]:
        """Register (or join — dedup) a now-anchored PromQL range query;
        returns (subscription, watcher). Pass `callback` for push
        delivery or `queue=True` for a pollable bounded queue; neither
        registers a bare subscription (evaluations still run and park
        in `last_result` — the cache-warming mode). `lease_s` gives the
        watcher a renewal lease (poll()/renew()); miss it and `reap()`
        removes the watcher, counted."""
        from .promql import query_range

        key = ("promql", query, db, table, int(span_s), int(step), int(lookback_s))

        def evaluate(now: int):
            return query_range(
                self.store, query, int(now) - int(span_s), int(now), int(step),
                lookback_s=lookback_s, db=db, table=table, live=self.live,
                cache=self.cache if self.cache is not None else False,
            )

        return self._register(key, "promql", query, db, table, evaluate,
                              callback, queue, maxlen, lease_s)

    def subscribe_sql(
        self, sql: str, *, callback=None, queue: bool = False,
        maxlen: int = DEFAULT_WATCHER_QUEUE, lease_s: float | None = None,
    ) -> tuple[Subscription, Watcher]:
        """Register (or join) a SQL query, evaluated as written. Its
        (db, table) resolves once here — event routing filters on it."""
        from .engine import QueryEngine

        engine = QueryEngine(self.store, live=self.live,
                             cache=self.cache if self.cache is not None else False)
        db, table = engine.resolve_query_table(sql)
        key = ("sql", sql, db, table)

        def evaluate(now: int):
            return engine.execute(sql)

        return self._register(key, "sql", sql, db, table, evaluate,
                              callback, queue, maxlen, lease_s)

    def _register(self, key, kind, query, db, table, evaluate,
                  callback, queue, maxlen, lease_s=None):
        with self._lock:
            sub = self._subs.get(key)
            if sub is None:
                sub = Subscription(key, kind, query, db, table, evaluate)
                self._subs[key] = sub
        watcher = None
        if callback is not None or queue:
            watcher = sub.watch(callback, maxlen=maxlen, lease_s=lease_s)
        return sub, watcher

    def reap(self, now_monotonic: float | None = None) -> int:
        """Remove watchers whose lease expired (ISSUE 12 satellite):
        an abandoned dashboard client — websocket gone, tab closed —
        stops holding its bounded queue and its share of the fan-out.
        Counted (`watchers_reaped`, queryable like every lane); runs
        before every event-batch evaluation and from Server.tick."""
        now = time.monotonic() if now_monotonic is None else now_monotonic
        reaped = 0
        with self._lock:
            subs = list(self._subs.values())
        # watcher-list mutation is serialized on the eval lock like
        # every other path that touches it (_evaluate_locked's detach
        # loop) — reap() runs concurrently from the Server.tick thread
        # and the bus thread, and an unguarded check-then-remove pair
        # would double-remove the same expired watcher (ValueError out
        # of whichever thread loses the race, double-counted reaps)
        with self._eval_lock:
            for sub in subs:
                for w in [w for w in sub.watchers if w.expired(now)]:
                    sub.unwatch(w)
                    reaped += 1
        if reaped:
            with self._lock:
                self.counters["watchers_reaped"] += reaped
        return reaped

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            self._subs.pop(sub.key, None)

    # -- evaluation ------------------------------------------------------
    def on_events(self, events) -> None:
        """Bus handler: ONE evaluation per dirty subscription per batch
        regardless of how many events touched it (the coalescing pin).
        Expired leases reap first — a dead client must not receive (or
        drop) this batch's delivery."""
        self.reap()
        with self._lock:
            subs = list(self._subs.values())
            self.counters["event_batches"] += 1
        if not subs:
            return
        now = max((t for t in (event_time(e) for e in events) if t is not None),
                  default=None)
        touched: dict[tuple, int] = {}
        for e in events:
            db = getattr(e, "db", None)
            table = getattr(e, "table", None)
            if db is None:
                continue
            touched[(db, table)] = touched.get((db, table), 0) + 1
        for sub in subs:
            n = touched.get((sub.db, sub.table), 0)
            if n == 0:
                continue
            sub.coalesced_events += n - 1
            with self._lock:
                self.counters["coalesced_events"] += n - 1
            self.evaluate(sub, now=now)

    def evaluate(self, sub: Subscription, *, now: int | None = None):
        """Evaluate one subscription once and fan the result out to its
        watchers; returns the result (None on eval failure — counted,
        contained). `now=None` — an event batch with no data-timed
        event (e.g. pure SnapshotAdvanced) — re-evaluates at the LAST
        data time the subscription saw, not the wall clock: under
        replay the wall is far from the data and an eval there would
        silently answer over an empty range (falls back to the wall
        only when no data time was ever seen)."""
        with self._eval_lock:
            return self._evaluate_locked(sub, now)

    def _evaluate_locked(self, sub: Subscription, now: int | None):
        if now is None:
            now = sub.last_now or int(time.time())
        now = int(now)
        t0 = time.perf_counter()
        try:
            with self.tracer.span(SPAN_SUBSCRIPTION_EVAL):
                result = sub._evaluate(now)
        except Exception:
            sub.eval_errors += 1
            with self._lock:
                self.counters["eval_errors"] += 1
            return None
        sub.last_eval_us = int((time.perf_counter() - t0) * 1e6)
        sub.last_now = now
        sub.last_result = result
        sub.evals += 1
        with self._lock:
            self.counters["evals"] += 1
        detached = []
        for w in list(sub.watchers):
            drops0, errs0 = w.dropped, w.errors
            ok = w.deliver(result, sub)
            with self._lock:
                self.counters["watcher_drops"] += w.dropped - drops0
                self.counters["watcher_errors"] += w.errors - errs0
                if ok:
                    self.counters["deliveries"] += 1
            sub.deliveries += int(ok)
            if w.detached:
                detached.append(w)
        for w in detached:
            sub.unwatch(w)
            with self._lock:
                self.counters["watchers_detached"] += 1
        return result

    # -- read faces ------------------------------------------------------
    def list_subscriptions(self) -> list[dict]:
        """The dfctl listing: one row per active subscription."""
        with self._lock:
            subs = list(self._subs.values())
        return [
            {
                "kind": s.kind,
                "query": s.query,
                "db": s.db,
                "table": s.table,
                "watchers": len(s.watchers),
                "evals": s.evals,
                "eval_errors": s.eval_errors,
                "deliveries": s.deliveries,
                "coalesced_events": s.coalesced_events,
                "last_eval_us": s.last_eval_us,
                "last_now": s.last_now,
            }
            for s in subs
        ]

    def get_counters(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["subscriptions"] = len(self._subs)
            out["watchers"] = sum(len(s.watchers) for s in self._subs.values())
        # the amplification lane the bench/gate pin: deliveries per eval
        out["amplification_x100"] = int(
            out["deliveries"] * 100 / max(1, out["evals"])
        )
        return out
