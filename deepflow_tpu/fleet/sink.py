"""Host side of the fleet plane: frame builder + wire sink.

`FleetExporter` turns one collector tick's points plus the host's
summary faces (freshness/span `hist_dump()`, alert rule states, HBM
ledger rows, census scalars) into one `FleetFrame`. Everything it
reads is host-side arithmetic over already-maintained state — no
device fetch, no store access — so attaching the sink cannot move the
ingest fetch budget (CI-gated by
test_perf_gate::test_fleet_export_budget).

`FleetSink` is the `StatsCollector.add_sink` face: each tick it
encodes one frame and queues it on a `HandoffSender` pointed at the
aggregator — the r19 framed-TCP stance verbatim (bounded overwrite
queue, capped-exponential reconnect with jitter, at-least-once across
reconnects, counted shed when the aggregator stays unreachable, the
`handoff.send` chaos seam for scripted transport faults). A dead
aggregator therefore costs the host one queue slot per tick, never a
blocked tick thread.
"""

from __future__ import annotations

import threading
import time

from ..utils.stats import StatsPoint, register_countable
from .frame import FleetFrame, encode_fleet_frame

#: the single-peer id a sink's sender routes to (the aggregator)
AGGREGATOR_PEER = 0


class FleetExporter:
    """Builds per-tick fleet frames from a host's telemetry faces.

    Every face is optional and guarded: a broken face is skipped and
    counted (`face_errors`), never allowed to kill the tick — the
    collector's own sink guard would otherwise drop the WHOLE frame
    for one bad census pull.
    """

    def __init__(self, host: str, *, group: str = "", epoch: int = 0,
                 collector=None, hist_faces=None, alerts=None,
                 ledger=None, census=None, clock=time.time):
        self.host = str(host)
        self.group = str(group)
        self.epoch = int(epoch)
        self._collector = collector
        #: {face name: object with .hist_dump()} — freshness trackers,
        #: span tracers; merged across hosts bin-for-bin by name.lane
        self._hist_faces = dict(hist_faces or {})
        self._alerts = alerts
        self._ledger = ledger
        self._census = census
        self._clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self.counters = {
            "frames_built": 0, "frame_bytes": 0, "face_errors": 0,
        }

    def set_epoch(self, epoch: int) -> None:
        """Topology epoch flips stamp subsequent frames (the aggregator
        keys staleness decisions on (host, epoch))."""
        self.epoch = int(epoch)

    def add_hist_face(self, name: str, face) -> None:
        self._hist_faces[str(name)] = face

    def get_counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self.counters)

    # -- frame assembly --------------------------------------------------
    def _guard(self, fn, default):
        try:
            return fn()
        except Exception:
            with self._lock:
                self.counters["face_errors"] += 1
            return default

    def build(self, points=None, now: float | None = None) -> FleetFrame:
        """One frame from `points` (a collector tick's StatsPoints) or,
        when None, a fresh fetch-free `collector.sample()` pull."""
        now = self._clock() if now is None else now
        if points is None:
            collector = self._collector
            if collector is None:
                from ..utils.stats import default_collector as collector
            points = self._guard(lambda: collector.sample(now), [])
        pts = tuple(
            (p.timestamp, p.module, {k: v for k, v in p.tags},
             dict(p.fields))
            for p in points
            if isinstance(p, StatsPoint)
        )
        hists = {}
        for name, face in self._hist_faces.items():
            dump = self._guard(face.hist_dump, None)
            if dump:
                hists[name] = dump
        alerts = ()
        if self._alerts is not None:
            alerts = self._guard(
                lambda: tuple(
                    {
                        "name": r["name"], "state": r["state"],
                        "value": r["value"],
                        "transitions": r["transitions"],
                    }
                    for r in self._alerts.list_rules()
                ),
                (),
            )
        hbm = ()
        if self._ledger is not None:
            hbm = self._guard(lambda: tuple(self._ledger.snapshot()), ())
        census = {}
        if self._census is not None:
            # scalars only (get_counters) — snapshot(analyze=True) may
            # COMPILE and belongs on the profile pull, never per tick
            census = self._guard(lambda: dict(self._census.get_counters()), {})
        with self._lock:
            seq = self._seq
            self._seq += 1
            self.counters["frames_built"] += 1
        return FleetFrame(
            host=self.host, group=self.group, epoch=self.epoch,
            seq=seq, timestamp=float(now), points=pts, hists=hists,
            alerts=alerts, hbm=hbm, census=census,
        )

    def encode(self, points=None, now: float | None = None) -> bytes:
        raw = encode_fleet_frame(self.build(points=points, now=now))
        with self._lock:
            self.counters["frame_bytes"] += len(raw)
        return raw


class FleetSink:
    """`StatsCollector` sink → one fleet frame per tick over the wire.

    Attach with `collector.add_sink(sink)`; detach + drain with
    `close()`. Loss is never silent: an unreachable aggregator sheds
    frames COUNTED on the sender (`tpu_handoff_sender.shed_frames`)
    and on this sink (`send_errors`).
    """

    def __init__(self, endpoint: tuple[str, int], exporter: FleetExporter,
                 *, sender=None, queue_capacity: int = 1 << 10):
        from ..ingest.handoff import HandoffSender

        self.exporter = exporter
        self._sender = sender if sender is not None else HandoffSender(
            {AGGREGATOR_PEER: endpoint}, queue_capacity=queue_capacity
        )
        self._lock = threading.Lock()
        self.counters = {"frames_sent": 0, "bytes_sent": 0, "send_errors": 0}
        self._stats_src = register_countable(
            "tpu_fleet_sink", self, host=exporter.host
        )

    def __call__(self, points) -> None:
        from ..ingest.handoff import HandoffUnreachable

        raw = self.exporter.encode(points=points)
        try:
            self._sender.send(AGGREGATOR_PEER, raw)
        except HandoffUnreachable:
            # the sender already counted the shed; keep a sink-local
            # error lane so the HOST's pane shows its own export health
            with self._lock:
                self.counters["send_errors"] += 1
            return
        with self._lock:
            self.counters["frames_sent"] += 1
            self.counters["bytes_sent"] += len(raw)

    def get_counters(self) -> dict[str, int]:
        with self._lock:
            out = dict(self.counters)
        out.update(
            {f"export_{k}": v for k, v in self.exporter.get_counters().items()}
        )
        return out

    def flush(self, timeout_s: float = 30.0) -> bool:
        """Fence: every queued frame written to the aggregator's socket
        (tests pin merged state only after this returns True)."""
        return self._sender.flush(timeout_s)

    def close(self, drain_timeout_s: float = 5.0) -> None:
        from ..utils.stats import default_collector

        self._sender.close(drain_timeout_s)
        default_collector.deregister(self._stats_src)
