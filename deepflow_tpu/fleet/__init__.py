"""Fleet telemetry plane — cross-host observability fan-in.

Hosts export one compact summary frame per collector tick
(`FleetExporter` + `FleetSink`, framed-TCP on the handoff stance); a
`FleetAggregator` merges them in the summary domain and serves one
queryable pane (store rows with host/group labels, merged log-hists,
worst-rolled alerts, skew surfaces, REST + dfctl).
"""

from .aggregator import DEFAULT_RATE_FIELD, FleetAggregator
from .frame import (
    FLEET_MSG_TYPE,
    FRAME_VERSION,
    FleetFrame,
    decode_fleet_frame,
    encode_fleet_frame,
)
from .sink import AGGREGATOR_PEER, FleetExporter, FleetSink

__all__ = [
    "AGGREGATOR_PEER",
    "DEFAULT_RATE_FIELD",
    "FLEET_MSG_TYPE",
    "FRAME_VERSION",
    "FleetAggregator",
    "FleetExporter",
    "FleetFrame",
    "FleetSink",
    "decode_fleet_frame",
    "encode_fleet_frame",
]
