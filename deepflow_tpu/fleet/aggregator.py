"""FleetAggregator — cross-host telemetry fan-in with one queryable pane.

A TCP listener (same reassembly stance as `HandoffReceiver`) receives
one `FleetFrame` per host per tick and merges IN THE SUMMARY DOMAIN:

  * counters — latest cumulative sample per host, summed across live
    hosts at read time, keyed by (module, tags-minus-host, field); the
    per-host rows additionally land in a fleet-level `deepflow_system`
    store with `host`/`group` labels, so the EXISTING SQL + PromQL
    queriers, subscriptions and alert engine serve fleet-wide queries
    unchanged,
  * log-hists — sparse `(bin, count)` dumps summed bin-for-bin across
    hosts (histograms add; quantile summaries don't — the r12/r16
    algebra), pinned BIT-EXACT against the per-host-dump oracle by the
    mesh proof,
  * alert states — worst-rolled-up per rule across hosts
    (`querier.alerts.worst_state` severity ordering).

Staleness is explicit, never silent: each host carries a last-seen
stamp; a host quiet past `expiry_s` EXPIRES — excluded from every
merged view with the exclusion COUNTED (`stale_drops`, one per read
that skipped it; `hosts_expired` on the transition) and its last-seen
stamp still served on the `hosts()` pane. A frame from an expired
host recovers it (counted).

Built-in skew surfaces ride the Countable face (`tpu_fleet`) and the
REST `GET /v1/fleet/{health,hosts,skew}` pane:
  * freshness-lag skew — max−min of per-host current lag,
  * HBM imbalance — max−min (and max/mean) of per-host ledger bytes,
  * rate divergence — max−min of per-group ingest rate, measured from
    consecutive frames' cumulative counters.

Aggregator work per tick is O(hosts × lanes), independent of how many
raw samples each host ingested — `bench/fleetbench.py` pins that.
"""

from __future__ import annotations

import socket
import threading
import time

from ..ingest.framing import FrameReassembler
from ..utils.stats import StatsPoint, register_countable
from .frame import decode_fleet_frame

#: counter field used for the per-group rate-divergence surface
DEFAULT_RATE_FIELD = "flow_in"


class _HostState:
    __slots__ = (
        "host", "groups", "epoch", "seq", "last_seen", "frame_ts",
        "frames", "points", "hists", "alerts", "hbm", "census",
        "expired", "rate_prev", "rates",
    )

    def __init__(self, host: str):
        self.host = host
        self.groups: set[str] = set()
        self.epoch = 0
        self.seq = -1
        self.last_seen = 0.0
        self.frame_ts = 0.0
        self.frames = 0
        self.points: tuple = ()
        self.hists: dict = {}
        self.alerts: tuple = ()
        self.hbm: tuple = ()
        self.census: dict = {}
        self.expired = False
        # per-group (t, cumulative value) for the rate surface
        self.rate_prev: dict[str, tuple[float, float]] = {}
        self.rates: dict[str, float] = {}


def _counter_key(module: str, tags: dict, field: str) -> str:
    """Canonical merged-counter key: host label stripped (that is the
    merge axis), remaining tags packed in sorted order."""
    from ..integration.formats import pack_tags

    rest = {k: str(v) for k, v in tags.items() if k != "host"}
    return f"{module}{{{pack_tags(rest)}}}.{field}"


class FleetAggregator:
    """Receive, merge, store and expose fleet telemetry."""

    def __init__(self, *, host: str = "127.0.0.1", port: int = 0,
                 store=None, bus=None, expiry_s: float = 60.0,
                 clock=time.time, rate_field: str = DEFAULT_RATE_FIELD,
                 autoregister: bool = True):
        self.host = host
        self.port = port
        self.store = store
        self.bus = bus
        self.expiry_s = float(expiry_s)
        self.clock = clock
        self.rate_field = rate_field
        self._hosts: dict[str, _HostState] = {}
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._running = False
        self.counters = {
            "frames_rx": 0, "bytes_rx": 0, "bad_frames": 0,
            "decode_errors": 0, "conns": 0, "store_rows": 0,
            "store_errors": 0, "hosts_expired": 0, "hosts_recovered": 0,
            "stale_drops": 0,
        }
        if store is not None:
            from ..integration.dfstats import ensure_system_table

            ensure_system_table(store)
        self._stats_src = (
            register_countable("tpu_fleet", self) if autoregister else None
        )

    # -- wire ------------------------------------------------------------
    def endpoint(self) -> tuple[str, int]:
        """The (host, port) every host's FleetSink dials."""
        return (self.host, self.port)

    def start(self) -> "FleetAggregator":
        self._running = True
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        self.port = s.getsockname()[1]
        s.listen(64)
        s.settimeout(0.5)  # close() does not wake accept() on Linux
        self._sock = s
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._running = False
        if self._stats_src is not None:
            from ..utils.stats import default_collector

            default_collector.deregister(self._stats_src)
            self._stats_src = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in list(self._threads):
            t.join(timeout=2)

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(0.5)
            self._count("conns")
            with self._lock:
                self._conns.add(conn)
                self._threads = [t for t in self._threads if t.is_alive()]
            t = threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True
            )
            t.start()
            with self._lock:
                self._threads.append(t)

    def _conn_loop(self, conn: socket.socket) -> None:
        asm = FrameReassembler()
        seen_bad = 0
        try:
            while self._running:
                try:
                    chunk = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                if not chunk:
                    return
                for header, body in asm.feed(chunk):
                    nbytes = header.frame_size
                    try:
                        frame = decode_fleet_frame(header, body)
                    except Exception:
                        # counted, never fatal to the conn: one corrupt
                        # frame must not take down the fleet pane
                        self._count("decode_errors")
                        continue
                    self.ingest(frame, nbytes=nbytes)
                if asm.bad_frames != seen_bad:
                    self._count("bad_frames", asm.bad_frames - seen_bad)
                    seen_bad = asm.bad_frames
        except OSError:
            return
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    # -- merge -----------------------------------------------------------
    def ingest(self, frame, *, nbytes: int = 0) -> None:
        """Merge one decoded frame (also the in-process test seam).
        Frames carry CUMULATIVE faces, so per-host state is
        last-frame-wins; cross-host summation happens at read time."""
        now = self.clock()
        with self._lock:
            st = self._hosts.get(frame.host)
            if st is None:
                st = self._hosts[frame.host] = _HostState(frame.host)
            if st.expired:
                st.expired = False
                self.counters["hosts_recovered"] += 1
            st.last_seen = now
            st.frame_ts = frame.timestamp
            st.epoch = frame.epoch
            st.seq = frame.seq
            st.frames += 1
            if frame.group:
                st.groups.add(frame.group)
            st.points = frame.points
            # hist faces are cumulative too: replace per face, keep
            # faces a sparser later frame did not mention (a quiet lane
            # still counts in the merge)
            for face, lanes in frame.hists.items():
                st.hists[face] = lanes
            if frame.alerts:
                st.alerts = frame.alerts
            st.hbm = frame.hbm
            if frame.census:
                st.census = frame.census
            self.counters["frames_rx"] += 1
            self.counters["bytes_rx"] += nbytes
            self._update_rates(st, frame)
        if self.store is not None:
            self._store_frame(frame)

    def _update_rates(self, st: _HostState, frame) -> None:
        """Per-group ingest rate from consecutive cumulative counters
        (under self._lock)."""
        for ts, _module, tags, fields in frame.points:
            if self.rate_field not in fields:
                continue
            group = str(tags.get("group", frame.group or ""))
            val = float(fields[self.rate_field])
            prev = st.rate_prev.get(group)
            if prev is not None and ts > prev[0]:
                st.rates[group] = (val - prev[1]) / (ts - prev[0])
            st.rate_prev[group] = (float(ts), val)

    def _store_frame(self, frame) -> None:
        """Per-host counter rows → the fleet deepflow_system table, with
        host/group labels packed into the standard labels column — the
        existing SQL/PromQL/alert planes read them with zero changes."""
        from ..integration.dfstats import (
            DEEPFLOW_SYSTEM_DB,
            DEEPFLOW_SYSTEM_TABLE,
            points_to_system_columns,
        )

        extra = {"host": frame.host}
        if frame.group:
            extra["group"] = frame.group
        points = [
            StatsPoint(ts, module, tuple(sorted(
                (str(k), str(v)) for k, v in tags.items()
            )), dict(fields))
            for ts, module, tags, fields in frame.points
        ]
        if not points:
            return
        try:
            cols = points_to_system_columns(points, extra_tags=extra)
            n = len(cols["time"])
            if n:
                self.store.insert(
                    DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, cols
                )
                self._count("store_rows", n)
        except Exception:
            self._count("store_errors")

    # -- staleness -------------------------------------------------------
    def _live(self, now: float) -> list[_HostState]:
        """Live hosts, with expiry transitions + stale skips COUNTED
        (call under self._lock)."""
        live = []
        for st in self._hosts.values():
            if now - st.last_seen > self.expiry_s:
                if not st.expired:
                    st.expired = True
                    self.counters["hosts_expired"] += 1
                # a read is happening and this host's data is being
                # withheld — that is the "no silent stale reads" lane
                self.counters["stale_drops"] += 1
                continue
            live.append(st)
        return live

    # -- merged read faces ----------------------------------------------
    def merged_counters(self, now: float | None = None) -> dict:
        """Cross-host counter sums keyed `module{tags}.field` (host
        label stripped — it is the merge axis). Bit-exact: int sums
        stay ints."""
        now = self.clock() if now is None else now
        with self._lock:
            live = self._live(now)
            rows = [(st.points,) for st in live]
        out: dict[str, int | float] = {}
        for (points,) in rows:
            for _ts, module, tags, fields in points:
                for field, v in fields.items():
                    if not isinstance(v, (int, float)) or isinstance(v, bool):
                        continue
                    key = _counter_key(module, tags, field)
                    out[key] = out.get(key, 0) + v
        return out

    def merged_hists(self, now: float | None = None) -> dict:
        """Cross-host log-hist sums, `face.lane` → sorted nonzero
        [[bin, count], ...] — the same shape `hist_dump()` emits, so a
        fleet-level quantile read uses the identical algebra."""
        now = self.clock() if now is None else now
        with self._lock:
            live = self._live(now)
            dumps = [dict(st.hists) for st in live]
        acc: dict[str, dict[int, int]] = {}
        for hists in dumps:
            for face, lanes in hists.items():
                for lane, pairs in lanes.items():
                    tgt = acc.setdefault(f"{face}.{lane}", {})
                    for b, c in pairs:
                        tgt[int(b)] = tgt.get(int(b), 0) + int(c)
        return {
            key: [[b, tgt[b]] for b in sorted(tgt)]
            for key, tgt in sorted(acc.items())
        }

    def merged_alerts(self, now: float | None = None) -> list[dict]:
        """Per-rule worst state across live hosts (the fleet rollup)."""
        from ..querier.alerts import worst_state

        now = self.clock() if now is None else now
        with self._lock:
            live = self._live(now)
            rows = [(st.host, st.alerts) for st in live]
        rules: dict[str, dict] = {}
        for host, alerts in rows:
            for a in alerts:
                r = rules.setdefault(
                    a["name"], {"name": a["name"], "hosts": {}}
                )
                r["hosts"][host] = {
                    "state": a["state"], "value": a.get("value"),
                    "transitions": a.get("transitions", 0),
                }
        out = []
        for name in sorted(rules):
            r = rules[name]
            r["state"] = worst_state(
                h["state"] for h in r["hosts"].values()
            )
            out.append(r)
        return out

    # -- panes -----------------------------------------------------------
    def hosts(self, now: float | None = None) -> list[dict]:
        """Per-host roster: last-seen stamp always served, stale flagged
        loudly instead of dropped."""
        now = self.clock() if now is None else now
        with self._lock:
            self._live(now)  # refresh expiry transitions (counted)
            states = list(self._hosts.values())
            rows = [
                {
                    "host": st.host,
                    "groups": sorted(st.groups),
                    "epoch": st.epoch,
                    "frames": st.frames,
                    "last_seen": st.last_seen,
                    "age_s": round(max(now - st.last_seen, 0.0), 3),
                    "stale": st.expired,
                    "hbm_bytes": sum(
                        int(r.get("bytes", 0)) for r in st.hbm
                    ),
                    "census": dict(st.census),
                }
                for st in sorted(states, key=lambda s: s.host)
            ]
        return rows

    def skew(self, now: float | None = None) -> dict:
        """The built-in cross-host imbalance surfaces."""
        now = self.clock() if now is None else now
        with self._lock:
            live = self._live(now)
            lag = {}
            hbm = {}
            rates: dict[str, float] = {}
            wire_drops: dict[str, int] = {}
            wire_deliveries: dict[str, int] = {}
            for st in live:
                worst = 0.0
                wd = wdel = 0
                for _ts, module, _tags, fields in st.points:
                    if module.startswith("tpu_wire"):
                        # slow-consumer imbalance (ISSUE 19 satellite):
                        # every wire face — hub, router, publisher —
                        # reports drop/delivery lanes; summing them per
                        # host makes a host whose clients shed visible
                        # fleet-wide next to the lag/HBM skew lanes
                        for field, v in fields.items():
                            if not isinstance(v, (int, float)):
                                continue
                            if field in ("drops", "open_dropped",
                                         "shed_frames", "alerts_dropped"):
                                wd += int(v)
                            elif field in ("deliveries", "open_delivered"):
                                wdel += int(v)
                    if "freshness" not in module:
                        continue
                    for field, v in fields.items():
                        if field.endswith("_lag_ms") and isinstance(
                            v, (int, float)
                        ):
                            worst = max(worst, float(v))
                lag[st.host] = worst
                wire_drops[st.host] = wd
                wire_deliveries[st.host] = wdel
                hbm[st.host] = sum(int(r.get("bytes", 0)) for r in st.hbm)
                for g, r in st.rates.items():
                    rates[g] = rates.get(g, 0.0) + r
        def spread(d):
            return (max(d.values()) - min(d.values())) if d else 0.0
        hbm_mean = (sum(hbm.values()) / len(hbm)) if hbm else 0.0
        return {
            "hosts": len(lag),
            "freshness_lag_skew_ms": round(spread(lag), 3),
            "per_host_lag_ms": {h: round(v, 3) for h, v in lag.items()},
            "hbm_imbalance_bytes": int(spread(hbm)),
            "hbm_imbalance_ratio": round(
                (max(hbm.values()) / hbm_mean) if hbm_mean else 0.0, 4
            ),
            "per_host_hbm_bytes": hbm,
            "rate_divergence": round(spread(rates), 3),
            "per_group_rate": {g: round(r, 3) for g, r in rates.items()},
            "wire_drop_skew": int(spread(wire_drops)),
            "per_host_wire_drops": wire_drops,
            "per_host_wire_deliveries": wire_deliveries,
        }

    def health(self, now: float | None = None) -> dict:
        now = self.clock() if now is None else now
        with self._lock:
            live = self._live(now)
            n_hosts = len(self._hosts)
            n_live = len(live)
            last_rx = max(
                (st.last_seen for st in self._hosts.values()), default=0.0
            )
            c = dict(self.counters)
        alerts = self.merged_alerts(now)
        firing = sum(a["state"] == "firing" for a in alerts)
        return {
            "status": "ok" if n_live else "empty",
            "hosts": n_hosts,
            "live": n_live,
            "stale": n_hosts - n_live,
            "frames_rx": c["frames_rx"],
            "bytes_rx": c["bytes_rx"],
            "decode_errors": c["decode_errors"],
            "store_rows": c["store_rows"],
            "last_rx_age_s": round(max(now - last_rx, 0.0), 3)
            if last_rx else None,
            "rules": len(alerts),
            "rules_firing": firing,
        }

    # -- Countable --------------------------------------------------------
    def get_counters(self) -> dict[str, int | float]:
        """The `tpu_fleet` dogfood face: rx/merge accounting plus the
        skew gauges — pure summary math, fetch-free."""
        now = self.clock()
        sk = self.skew(now)
        with self._lock:
            out = dict(self.counters)
            n_hosts = len(self._hosts)
            n_stale = sum(st.expired for st in self._hosts.values())
        out["hosts"] = n_hosts
        out["hosts_stale"] = n_stale
        out["freshness_lag_skew_ms"] = sk["freshness_lag_skew_ms"]
        out["hbm_imbalance_bytes"] = sk["hbm_imbalance_bytes"]
        out["rate_divergence"] = sk["rate_divergence"]
        out["wire_drop_skew"] = sk["wire_drop_skew"]
        return out
