"""Fleet telemetry frame — the cross-host observability wire unit.

One compact frame per collector tick per host, carrying that host's
telemetry in the SUMMARY domain (the 2503.13515 stance: merge
sketches/log-hists, never raw samples):

  * counter samples — the tick's `StatsPoint`s (module, tags, fields),
  * log-hist dumps — nonzero `(bin, count)` pairs from the existing
    `hist_dump()` faces (freshness tiers, span stages) — histograms
    add bin-for-bin across hosts; quantile summaries don't,
  * alert series states — rule name + worst state + value, so the
    aggregator can worst-roll-up per rule fleet-wide,
  * HBM ledger rows + census summary — the per-host device-memory and
    compile-pressure pane,

all tagged `(host, shard_group, epoch)` so the merged store rows keep
per-host attribution as plain PromQL labels.

The wire format is the existing framed-TCP ABI (`ingest/framing.py`):
a 19-byte flow header with `msg_type = DFSTATS` (the reference's
self-telemetry lane) over one deflate/zstd-compressed JSON message —
so `FrameReassembler`, the codec negotiation, and the handoff
transport all apply unchanged. JSON keeps ints exact (the bit-exact
merge pin rides on that) and the compressor makes "compact" true in
practice: a frame is dominated by sparse hist pairs, not samples.
"""

from __future__ import annotations

import dataclasses
import json

from ..ingest.framing import (
    FlowHeader,
    MessageType,
    best_encoder,
    compress_body,
    decompress_body,
    encode_frame,
    split_messages,
)

#: the fleet lane's message type — DFSTATS is the reference's
#: self-telemetry msg_type, which is exactly what this frame carries
FLEET_MSG_TYPE = MessageType.DFSTATS

FRAME_VERSION = 1


@dataclasses.dataclass(frozen=True)
class FleetFrame:
    """One host's per-tick telemetry summary (decoded form)."""

    host: str
    group: str  # "" = host-wide (multi-group hosts tag per point)
    epoch: int
    seq: int
    timestamp: float
    #: ((timestamp, module, {tag: value}, {field: number}), ...)
    points: tuple = ()
    #: {face: {lane: [[bin, count], ...]}} — sparse log-hist dumps
    hists: dict = dataclasses.field(default_factory=dict)
    #: ({"name", "state", "value", "transitions"}, ...) per alert rule
    alerts: tuple = ()
    #: HBM ledger snapshot rows (profiling/ledger.py shape)
    hbm: tuple = ()
    #: census summary scalars (profiling/census.py get_counters shape)
    census: dict = dataclasses.field(default_factory=dict)


def encode_fleet_frame(frame: FleetFrame, *, agent_id: int = 0,
                       encoder: int | None = None) -> bytes:
    """FleetFrame → one wire frame (header + compressed JSON body)."""
    body = json.dumps(
        {
            "v": FRAME_VERSION,
            "host": frame.host,
            "group": frame.group,
            "epoch": int(frame.epoch),
            "seq": int(frame.seq),
            "t": frame.timestamp,
            "points": [
                [ts, module, tags, fields]
                for (ts, module, tags, fields) in frame.points
            ],
            "hists": frame.hists,
            "alerts": list(frame.alerts),
            "hbm": list(frame.hbm),
            "census": frame.census,
        },
        separators=(",", ":"),
    ).encode()
    enc = best_encoder() if encoder is None else encoder
    return encode_frame(
        FlowHeader(msg_type=int(FLEET_MSG_TYPE), agent_id=agent_id),
        [body], encoder=enc,
    )


def decode_fleet_frame(header: FlowHeader, body: bytes) -> FleetFrame:
    """(header, body) from a FrameReassembler → FleetFrame. Raises
    ValueError on a wrong message type or version — the aggregator
    counts these as decode errors, never silently skips."""
    if header.msg_type != int(FLEET_MSG_TYPE):
        raise ValueError(
            f"not a fleet frame: msg_type={header.msg_type}"
        )
    (msg,) = split_messages(decompress_body(body, header.encoder))
    obj = json.loads(msg)
    if obj.get("v") != FRAME_VERSION:
        raise ValueError(f"unknown fleet frame version {obj.get('v')!r}")
    return FleetFrame(
        host=str(obj["host"]),
        group=str(obj.get("group", "")),
        epoch=int(obj.get("epoch", 0)),
        seq=int(obj.get("seq", 0)),
        timestamp=float(obj.get("t", 0.0)),
        points=tuple(
            (p[0], p[1], p[2], p[3]) for p in obj.get("points", ())
        ),
        hists={
            str(face): {
                str(lane): [[int(b), int(c)] for b, c in pairs]
                for lane, pairs in lanes.items()
            }
            for face, lanes in obj.get("hists", {}).items()
        },
        alerts=tuple(obj.get("alerts", ())),
        hbm=tuple(obj.get("hbm", ())),
        census=dict(obj.get("census", {})),
    )


__all__ = [
    "FLEET_MSG_TYPE",
    "FRAME_VERSION",
    "FleetFrame",
    "encode_fleet_frame",
    "decode_fleet_frame",
    "compress_body",  # re-exported for bench/diagnostics symmetry
]
