"""Device-resident FlowMap — the agent's flow-generation hot loop.

The reference's `FlowMap::inject_meta_packet` (flow_generator/
flow_map.rs:710) probes a host hash map per packet, runs a per-packet
TCP state machine (flow_state.rs) and TcpPerf RTT estimation
(perf/tcp.rs), and a 1s `inject_flush_ticker` (flow_map.rs:555) emits
`TaggedFlow`s. The TPU shape replaces per-packet probing with the same
sort→segment machinery as every other hot loop in this framework:

  * the flow table is a `LogStashState` over the FLOW_STATE schema
    (slot pinned to 0 — no windowing; the 5-tuple is the key),
  * a packet batch becomes flow-row updates (canonicalized endpoint
    pair + per-direction conditional columns) merged in one sort,
  * `tick(now)` is a jit step that computes per-flow TCP state from
    accumulated flag/time aggregates, closes flows (FIN/RST/timeout),
    emits per-second delta rows (L4_FLOW_LOG schema) compacted on
    device, and zeroes the delta counters.

Documented deviations from the sequential reference (conformance tests
pin these semantics):
  * TCP state derives from cumulative per-direction flag sets, not
    packet order — SYN→SYN+ACK→FIN/RST transitions are order-free, so
    flow accounting matches; mid-stream anomalies (e.g. data-before-
    handshake) are not distinguished.
  * RTT: client = t(first SYN+ACK) − t(first SYN); server = t(first
    pure ACK from the SYN side) − t(first SYN+ACK). TcpPerf's
    continuous per-ACK srt/art tracking is approximated by the
    handshake estimate.
  * Retransmissions: within a batch, exact duplicate (flow, dir, seq,
    len) data segments; across batches, a host-side per-flow
    high-water mark (seq_end per direction, the TcpPerf SeqSegment
    seat) flags data segments ending at or below bytes already seen in
    an earlier batch (tcp.rs retrans detection on seq < expected).
    Partial overlaps straddling the mark are missed; reordering within
    one batch is never false-flagged (golden-pinned against the
    reference's xiangdao-retrans.result at batch_size 1 and whole-pcap).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..flowlog.aggr import FlowLogBatch, LogStashState, log_stash_init, log_stash_merge
from ..flowlog.schema import L4_FLOW_LOG, LogOp, LogSchema, LogField
from ..ops.hashing import fingerprint64
from ..ops.segment import SENTINEL_SLOT
from ..utils.stats import register_countable
from .packet import TCP_ACK, TCP_FIN, TCP_RST, TCP_SYN, PacketBatch

_ABSENT = 0xFFFFFFFF  # MIN-lane identity for "time never seen"


def _i(name, op=LogOp.FIRST):
    return LogField(name, op, "int")


def _n(name, op=LogOp.SUM):
    return LogField(name, op, "num")


FLOW_STATE = LogSchema(
    "flow_state",
    key=(
        "is_ipv6",
        "ep0_w0", "ep0_w1", "ep0_w2", "ep0_w3",
        "ep1_w0", "ep1_w1", "ep1_w2", "ep1_w3",
        "ep0_port", "ep1_port", "protocol",
    ),
    fields=tuple(
        [
            _i("is_ipv6"),
            *[_i(f"ep{s}_w{w}") for s in (0, 1) for w in range(4)],
            _i("ep0_port"),
            _i("ep1_port"),
            _i("protocol"),
            _i("tunnel_type"),
            _i("start_time", LogOp.MIN),
            _i("last_seen", LogOp.MAX),
            _i("flags_d0", LogOp.OR),  # d0 = packets sent by ep0
            _i("flags_d1", LogOp.OR),
            _i("syn_time", LogOp.MIN),  # _ABSENT when unseen
            _i("synack_time", LogOp.MIN),
            _i("ack_time_d0", LogOp.MIN),  # first pure-ACK per direction
            _i("ack_time_d1", LogOp.MIN),
            _i("syn_dir", LogOp.OR),  # bit0: ep0 sent SYN, bit1: ep1
            _i("emitted", LogOp.OR),  # set by tick() after first emission
            # dispatcher orientation (dispatcher.py): which endpoints
            # terminate locally (L2End), and the tap the flow rode
            _i("l2_end_ep0", LogOp.OR),
            _i("l2_end_ep1", LogOp.OR),
            _i("tap_type", LogOp.MAX),  # one tap per flow; MAX merges idempotently
            # delta counters (zeroed by tick() after each emission)
            _n("packet_d0"),
            _n("packet_d1"),
            _n("byte_d0"),
            _n("byte_d1"),
            _n("l4_byte_d0"),
            _n("l4_byte_d1"),
            _n("syn_count"),
            _n("synack_count"),
            _n("retrans_d0"),
            _n("retrans_d1"),
            # lifetime totals (never reset)
            _n("total_packet_d0"),
            _n("total_packet_d1"),
            _n("total_byte_d0"),
            _n("total_byte_d1"),
        ]
    ),
)

_II = FLOW_STATE.int_index
_NI = FLOW_STATE.num_index

# flow states (flow_state.rs FlowState, condensed)
STATE_OPENING = 1
STATE_ESTABLISHED = 2
STATE_CLOSING = 3
STATE_CLOSED = 4

# close types (flow.rs CloseType, condensed)
CLOSE_NONE = 0
CLOSE_FIN = 1
CLOSE_CLIENT_RST = 2
CLOSE_SERVER_RST = 3
CLOSE_TIMEOUT = 5


@dataclasses.dataclass(frozen=True)
class FlowTimeouts:
    """flow timeout config (agent config flow.flow_timeout analog)."""

    opening: int = 5
    established: int = 300
    closing: int = 35


# ---------------------------------------------------------------------------
# packet batch → flow-row updates (pure function of PacketBatch columns)


def packets_to_flow_rows(
    p: PacketBatch, seq_tracker: dict | None = None, orient=None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """PacketBatch → (ints [N, Ki], nums [N, Kn], valid) FLOW_STATE rows.

    Endpoint canonicalization: ep0 is the lexicographically smaller
    (ip, port); dir=1 when the sender is ep1. Both directions of one
    connection land on the same key, like FlowMapKey's symmetric hash.
    """
    n = p.size
    src_key = [p.ip_src[:, w].astype(np.uint64) for w in range(4)] + [p.port_src.astype(np.uint64)]
    dst_key = [p.ip_dst[:, w].astype(np.uint64) for w in range(4)] + [p.port_dst.astype(np.uint64)]
    swap = np.zeros(n, bool)
    decided = np.zeros(n, bool)
    for s, d in zip(src_key, dst_key):
        gt = ~decided & (s > d)
        lt = ~decided & (s < d)
        swap |= gt
        decided |= gt | lt
    d1 = swap  # sender is ep1

    ints = np.zeros((n, len(FLOW_STATE.ints)), np.uint32)
    nums = np.zeros((n, len(FLOW_STATE.nums)), np.float32)

    ints[:, _II("is_ipv6")] = p.is_ipv6
    for w in range(4):
        ints[:, _II(f"ep0_w{w}")] = np.where(d1, p.ip_dst[:, w], p.ip_src[:, w])
        ints[:, _II(f"ep1_w{w}")] = np.where(d1, p.ip_src[:, w], p.ip_dst[:, w])
    ints[:, _II("ep0_port")] = np.where(d1, p.port_dst, p.port_src)
    ints[:, _II("ep1_port")] = np.where(d1, p.port_src, p.port_dst)
    ints[:, _II("protocol")] = p.protocol
    ints[:, _II("tunnel_type")] = p.tunnel_type
    ints[:, _II("start_time")] = p.timestamp_s
    ints[:, _II("last_seen")] = p.timestamp_s
    ints[:, _II("flags_d0")] = np.where(~d1, p.tcp_flags, 0)
    ints[:, _II("flags_d1")] = np.where(d1, p.tcp_flags, 0)

    f = p.tcp_flags
    is_syn = (f & TCP_SYN != 0) & (f & TCP_ACK == 0)
    is_synack = (f & TCP_SYN != 0) & (f & TCP_ACK != 0)
    pure_ack = (f == TCP_ACK) & (p.payload_len == 0)
    # handshake clocks run in µs (mod 2^32) so RTTs keep microsecond
    # resolution like the reference's TcpPerf (perf/tcp.rs works on
    # 64-bit µs Timestamps); the 71-minute wrap only matters if a
    # handshake straddles it — u32 subtraction still yields the right
    # difference then, only the MIN merge order could pick the later
    # timestamp (documented approximation)
    ts_us32 = (
        p.timestamp_s.astype(np.uint64) * np.uint64(1_000_000)
        + p.timestamp_us.astype(np.uint64)
    ).astype(np.uint32)
    ints[:, _II("syn_time")] = np.where(is_syn, ts_us32, _ABSENT)
    ints[:, _II("synack_time")] = np.where(is_synack, ts_us32, _ABSENT)
    ints[:, _II("ack_time_d0")] = np.where(pure_ack & ~d1, ts_us32, _ABSENT)
    ints[:, _II("ack_time_d1")] = np.where(pure_ack & d1, ts_us32, _ABSENT)
    ints[:, _II("syn_dir")] = np.where(is_syn, np.where(d1, 2, 1), 0)

    if orient is not None:
        tap, end_src, end_dst = orient
        ints[:, _II("tap_type")] = tap
        # src/dst are packet-relative; fold onto the canonical ep0/ep1
        ints[:, _II("l2_end_ep0")] = np.where(d1, end_dst, end_src)
        ints[:, _II("l2_end_ep1")] = np.where(d1, end_src, end_dst)
    else:
        # no dispatcher: the historical local single-host stance —
        # everything terminates here (tap_side resolves to the client
        # view, matching the pre-mode behavior)
        ints[:, _II("tap_type")] = 3  # TAP_CLOUD
        ints[:, _II("l2_end_ep0")] = 1
        ints[:, _II("l2_end_ep1")] = 1

    one = np.ones(n, np.float32)
    nums[:, _NI("packet_d0")] = np.where(~d1, one, 0)
    nums[:, _NI("packet_d1")] = np.where(d1, one, 0)
    nums[:, _NI("byte_d0")] = np.where(~d1, p.packet_len, 0)
    nums[:, _NI("byte_d1")] = np.where(d1, p.packet_len, 0)
    nums[:, _NI("l4_byte_d0")] = np.where(~d1, p.payload_len, 0)
    nums[:, _NI("l4_byte_d1")] = np.where(d1, p.payload_len, 0)
    nums[:, _NI("syn_count")] = is_syn
    nums[:, _NI("synack_count")] = is_synack
    nums[:, _NI("total_packet_d0")] = nums[:, _NI("packet_d0")]
    nums[:, _NI("total_packet_d1")] = nums[:, _NI("packet_d1")]
    nums[:, _NI("total_byte_d0")] = nums[:, _NI("byte_d0")]
    nums[:, _NI("total_byte_d1")] = nums[:, _NI("byte_d1")]

    # within-batch retransmission detection: an exact duplicate
    # (flow, dir, seq, len) data segment is a resend. Plain reordering of
    # disjoint ranges is NOT flagged (an arrival-order prefix-max scheme
    # would false-positive on any reordered link); partial-overlap
    # retransmits are missed — documented approximation
    key_mat = ints[:, FLOW_STATE.key_cols]
    hi, lo = fingerprint64(key_mat, xp=np)
    is_data = (p.protocol == 6) & (p.payload_len > 0)
    order = np.lexsort((p.payload_len, p.seq, d1.astype(np.int64), lo, hi))
    same = np.zeros(n, bool)
    if n > 1:
        cols = [hi, lo, d1.astype(np.uint32), p.seq, p.payload_len]
        eq = np.ones(n - 1, bool)
        for c in cols:
            cs = c[order]
            eq &= cs[1:] == cs[:-1]
        same[1:] = eq
    retrans = np.zeros(n, bool)
    retrans[order] = same & is_data[order]

    if seq_tracker is not None and n:
        # the seq-list pass processes packets in arrival order, so it
        # subsumes the within-batch duplicate rule above
        retrans = _seq_list_retrans(
            seq_tracker, hi, lo, d1, p.seq, p.payload_len, is_data
        )
    nums[:, _NI("retrans_d0")] = retrans & ~d1
    nums[:, _NI("retrans_d1")] = retrans & d1

    return ints, nums, p.valid.copy()


SEQ_LIST_MAX_LEN = 16  # perf/tcp.rs:80


def _seq_list_retrans(tracker: dict, hi, lo, d1, seq, plen, is_data):
    """Per-(flow, dir) seen-byte interval lists — the TcpPerf seq_list
    (perf/tcp.rs:84, is_retrans_segment:266): a data segment whose whole
    range was already transmitted is a retransmission. Sequential in
    arrival order (duplicates inside one batch count too), carried
    across batches via `tracker`. Sequence wrap is handled by storing
    intervals as signed offsets from the flow's first-seen seq; at 16
    intervals the two oldest merge (the reference merges at the tail,
    tcp.rs:330). Partial overlaps are NOT flagged (the reference splits
    and counts only fully-seen ranges the same way)."""
    n = hi.shape[0]
    out = np.zeros(n, bool)
    idx = np.nonzero(is_data)[0]
    for i in idx:
        key = (int(hi[i]), int(lo[i]), int(d1[i]))
        s32 = int(seq[i])
        ln = int(plen[i])
        # pop + reinsert on EVERY touch: dict order then approximates
        # LRU, so the overflow eviction in FlowMap.inject (which deletes
        # the oldest-quarter of keys) sheds idle flows, not the
        # long-lived active ones whose cross-batch retrans detection
        # matters most (ADVICE.md #3 — update-in-place left dict order
        # at insertion time, evicting exactly the wrong entries).
        ent = tracker.pop(key, None)
        if ent is None:
            anchor = s32
            ivals: list[list[int]] = []
        else:
            anchor, ivals = ent
        # wrap-tolerant signed offset from the anchor
        s = ((s32 - anchor + (1 << 31)) & 0xFFFFFFFF) - (1 << 31)
        e = s + ln
        covered = any(a <= s and e <= b for a, b in ivals)
        if covered:
            out[i] = True
            tracker[key] = (anchor, ivals)  # refresh recency on hit too
            continue
        # insert + merge (list stays sorted and disjoint; adjacency
        # merges so contiguous transmissions form one range)
        before = [iv for iv in ivals if iv[1] < s]
        after = [iv for iv in ivals if iv[0] > e]
        for a, b in ivals:
            if not (b < s or a > e):
                s, e = min(a, s), max(b, e)
        merged = before + [(s, e)] + after
        if len(merged) > SEQ_LIST_MAX_LEN:
            merged[0] = (merged[0][0], merged[1][1])
            del merged[1]
        tracker[key] = (anchor, merged)
    return out


# ---------------------------------------------------------------------------
# tick kernel: state classification, close, emission, delta reset


@dataclasses.dataclass(frozen=True)
class _TickCfg:
    opening: int
    established: int
    closing: int

    def __hash__(self):  # static jit arg
        return hash((self.opening, self.established, self.closing))


def _flow_tick_impl(state: LogStashState, now, cfg: _TickCfg):
    ints, nums = state.ints, state.nums
    valid = state.valid

    def icol(name):
        return ints[:, _II(name)]

    def ncol(name):
        return nums[:, _NI(name)]

    f0, f1 = icol("flags_d0"), icol("flags_d1")
    fboth = f0 | f1
    is_tcp = icol("protocol") == 6
    syn_seen = (fboth & TCP_SYN) != 0
    synack = icol("synack_time") != jnp.uint32(_ABSENT)
    fin0 = (f0 & TCP_FIN) != 0
    fin1 = (f1 & TCP_FIN) != 0
    rst = (fboth & TCP_RST) != 0

    tcp_state = jnp.where(
        synack & syn_seen,
        jnp.where(fin0 & fin1, STATE_CLOSED, jnp.where(fin0 | fin1, STATE_CLOSING, STATE_ESTABLISHED)),
        jnp.where(syn_seen, STATE_OPENING, STATE_ESTABLISHED),  # mid-stream pickup
    )
    tcp_state = jnp.where(is_tcp, tcp_state, STATE_ESTABLISHED)

    # guard the u32 subtraction: capture clocks can run ahead of the
    # tick clock, and a wrapped idle would timeout-close live flows
    last_seen = icol("last_seen")
    idle = jnp.where(last_seen >= now, jnp.uint32(0), now - last_seen)
    timeout_s = jnp.where(
        tcp_state == STATE_OPENING,
        cfg.opening,
        jnp.where(tcp_state == STATE_ESTABLISHED, cfg.established, cfg.closing),
    )
    timed_out = valid & (idle >= timeout_s)
    done = valid & is_tcp & ((fin0 & fin1) | rst)
    closing_flow = done | timed_out

    # close_type: RST attribution by which side reset; FIN; timeout.
    # client = SYN sender; without a handshake, the lower port is taken
    # as the server (the reference's port-number heuristic)
    syn_dir = icol("syn_dir")
    client_is_ep1 = jnp.where(
        syn_dir != 0,
        (syn_dir & 1) == 0,
        icol("ep0_port") < icol("ep1_port"),
    )
    rst0 = (f0 & TCP_RST) != 0
    server_rst = jnp.where(client_is_ep1, rst0, (f1 & TCP_RST) != 0)
    close_type = jnp.where(
        rst,
        jnp.where(server_rst, CLOSE_SERVER_RST, CLOSE_CLIENT_RST),
        jnp.where(fin0 & fin1, CLOSE_FIN, CLOSE_TIMEOUT),
    )
    close_type = jnp.where(closing_flow, close_type, CLOSE_NONE)

    active = valid & (ncol("packet_d0") + ncol("packet_d1") > 0)
    emit = active | closing_flow

    # RTT in µs (handshake lanes carry the µs-mod-2^32 clock; matches
    # the reference's µs TcpPerf, perf/tcp.rs)
    syn_t, synack_t = icol("syn_time"), icol("synack_time")
    ack_t = jnp.where(client_is_ep1, icol("ack_time_d1"), icol("ack_time_d0"))
    absent = jnp.uint32(_ABSENT)
    # wrap-tolerant ordering: the u32 µs difference is the true RTT as
    # long as it lands under 2^31 (handshakes are short), so a clock
    # wrap between SYN and SYN-ACK still measures correctly
    d_cli = synack_t - syn_t
    d_srv = ack_t - synack_t
    # handshake legs are bounded (5 min in µs): rejects both nonsense
    # orderings and the post-wrap pure-ACK displacing the handshake ACK
    # in the MIN lane on flows that live across a 71-min clock wrap
    bound = jnp.uint32(300_000_000)
    have_cli = (syn_t != absent) & (synack_t != absent) & (d_cli < bound)
    have_srv = (synack_t != absent) & (ack_t != absent) & (d_srv < bound)
    rtt_client = jnp.where(have_cli, d_cli, 0)
    rtt_server = jnp.where(have_srv, d_srv, 0)

    out = {
        "close": closing_flow,
        "tcp_state": tcp_state.astype(jnp.uint32),
        "close_type": close_type.astype(jnp.uint32),
        "client_is_ep1": client_is_ep1,
        "rtt_client": rtt_client.astype(jnp.uint32),
        "rtt_server": rtt_server.astype(jnp.uint32),
        "new_flow": (icol("emitted") == 0) & emit,
        "ints": ints,
        "nums": nums,
        "count": jnp.sum(emit.astype(jnp.int32)),
    }
    # compact emitted rows to the prefix (host copies O(emitted))
    order = jnp.argsort(jnp.where(emit, 0, 1), stable=True)
    for k in ("tcp_state", "close_type", "client_is_ep1", "rtt_client", "rtt_server", "new_flow"):
        out[k] = jnp.take(out[k], order, axis=0)
    out["ints"] = jnp.take(ints, order, axis=0)
    out["nums"] = jnp.take(nums, order, axis=0)

    # post-emission state: closed flows leave; emitted flows zero their
    # delta lanes and set `emitted`
    delta_cols = np.array(
        [_NI(c) for c in (
            "packet_d0", "packet_d1", "byte_d0", "byte_d1", "l4_byte_d0",
            "l4_byte_d1", "syn_count", "synack_count", "retrans_d0", "retrans_d1",
        )],
        np.int32,
    )
    new_nums = nums.at[:, delta_cols].set(
        jnp.where(emit[:, None], 0.0, nums[:, delta_cols])
    )
    new_ints = ints.at[:, _II("emitted")].set(
        jnp.where(emit, jnp.uint32(1), ints[:, _II("emitted")])
    )
    new_valid = valid & ~closing_flow
    new_state = dataclasses.replace(
        state,
        ints=new_ints,
        nums=new_nums,
        valid=new_valid,
        slot=jnp.where(new_valid, state.slot, jnp.uint32(SENTINEL_SLOT)),
    )
    return new_state, out


_flow_tick = jax.jit(_flow_tick_impl, static_argnames=("cfg",), donate_argnums=(0,))


# ---------------------------------------------------------------------------
# host driver


class FlowMap:
    """inject packets, tick every second, emit L4_FLOW_LOG delta rows."""

    def __init__(
        self,
        *,
        capacity: int = 1 << 16,
        batch_size: int = 1 << 12,
        timeouts: FlowTimeouts = FlowTimeouts(),
        agent_id: int = 1,
        dispatcher=None,
    ):
        self.capacity = capacity
        self.batch_size = batch_size
        self.timeouts = timeouts
        self.agent_id = agent_id
        self.dispatcher = dispatcher
        self.state = log_stash_init(capacity, FLOW_STATE)
        # host-side per-(flow, dir) seq interval lists for cross-batch
        # retrans detection; bounded. Entries move to the dict tail on
        # every touch (_seq_list_retrans pop+reinsert), so the
        # oldest-quarter eviction below approximates LRU — idle flows
        # go first, active long-lived flows keep their seq history
        self.seq_tracker: dict = {}
        self.seq_tracker_cap = max(1024, 4 * capacity)
        self.counters = {"packets_in": 0, "invalid_packets": 0, "flows_emitted": 0, "flows_closed": 0}
        register_countable("flow_map", self)

    def get_counters(self):
        c = dict(self.counters)
        c["dropped_overflow"] = int(np.asarray(self.state.dropped_overflow))
        c["occupancy"] = int(np.asarray(self.state.valid).sum())
        return c

    def inject(self, p: PacketBatch, orient=None) -> None:
        if orient is None and self.dispatcher is not None:
            orient = self.dispatcher.orient(p)
        ints, nums, valid = packets_to_flow_rows(p, self.seq_tracker, orient)
        if len(self.seq_tracker) > self.seq_tracker_cap:
            import itertools

            # dict head = least-recently-touched (pop+reinsert in
            # _seq_list_retrans); drop a quarter, and always at least
            # enough to get back under the cap
            n_evict = max(
                len(self.seq_tracker) - self.seq_tracker_cap,
                self.seq_tracker_cap // 4,
            )
            for k in list(itertools.islice(iter(self.seq_tracker), n_evict)):
                del self.seq_tracker[k]
        n = ints.shape[0]
        if n > self.batch_size:
            raise ValueError(f"packet batch {n} > batch_size {self.batch_size}")
        pad = self.batch_size - n
        ints = np.pad(ints, ((0, pad), (0, 0)))
        # padded MIN lanes must hold the identity, not 0
        for c in ("syn_time", "synack_time", "ack_time_d0", "ack_time_d1", "start_time"):
            ints[n:, _II(c)] = _ABSENT if c != "start_time" else 0
        nums = np.pad(nums, ((0, pad), (0, 0)))
        valid = np.pad(valid, (0, pad))
        self.counters["packets_in"] += int(valid.sum())
        self.counters["invalid_packets"] += int((~p.valid).sum())

        key_mat = ints[:, FLOW_STATE.key_cols]
        hi, lo = fingerprint64(key_mat, xp=np)
        self.state = log_stash_merge(
            self.state,
            jnp.zeros(self.batch_size, jnp.uint32),  # slot 0: keyed purely by 5-tuple
            jnp.asarray(hi),
            jnp.asarray(lo),
            jnp.asarray(ints),
            jnp.asarray(nums),
            jnp.asarray(valid),
            FLOW_STATE,
        )

    def tick(self, now: int) -> FlowLogBatch:
        """1s flush ticker: emit per-second TaggedFlow deltas + closes."""
        cfg = _TickCfg(self.timeouts.opening, self.timeouts.established, self.timeouts.closing)
        self.state, raw = _flow_tick(self.state, np.uint32(now), cfg)
        n = int(raw["count"])
        self.counters["flows_emitted"] += n
        # closed flows release their seq-tracker entries — without this,
        # churn would evict still-active flows' marks (FIFO backstop)
        # while dead keys lingered
        if n and self.seq_tracker:
            closed = np.asarray(raw["close"][:n]).astype(bool)
            if closed.any():
                fi = np.asarray(raw["ints"][:n])[closed]
                hi, lo = fingerprint64(fi[:, FLOW_STATE.key_cols], xp=np)
                for h, l in zip(hi, lo):
                    for d in (0, 1):
                        self.seq_tracker.pop((int(h), int(l), d), None)
        emitted = _emission_to_l4_rows(
            {k: np.asarray(v[:n]) for k, v in raw.items() if k != "count"},
            n,
            now,
            self.agent_id,
        )
        self.counters["flows_closed"] += int(np.asarray(raw["close"]).sum())
        return emitted

    def drain(self, now: int) -> FlowLogBatch:
        """Force-close everything (shutdown): emit with timeout close."""
        saved = self.timeouts
        self.timeouts = FlowTimeouts(opening=0, established=0, closing=0)
        try:
            return self.tick(now)
        finally:
            self.timeouts = saved


def _emission_to_l4_rows(raw: dict, n: int, now: int, agent_id: int) -> FlowLogBatch:
    """Tick output → L4_FLOW_LOG rows: client side becomes side 0."""
    s = L4_FLOW_LOG
    ints_out = np.zeros((n, len(s.ints)), np.uint32)
    nums_out = np.zeros((n, len(s.nums)), np.float32)
    if n == 0:
        return FlowLogBatch(s, ints_out, nums_out, np.ones(0, bool))
    fi = raw["ints"]
    fn = raw["nums"]
    cli1 = raw["client_is_ep1"].astype(bool)
    ii, ni = s.int_index, s.num_index

    key_mat = fi[:, FLOW_STATE.key_cols]
    hi, lo = fingerprint64(key_mat, xp=np)
    ints_out[:, ii("flow_id_hi")] = hi
    ints_out[:, ii("flow_id_lo")] = lo
    ints_out[:, ii("agent_id")] = agent_id
    ints_out[:, ii("is_ipv6")] = fi[:, _II("is_ipv6")]
    for w in range(4):
        ep0, ep1 = fi[:, _II(f"ep0_w{w}")], fi[:, _II(f"ep1_w{w}")]
        ints_out[:, ii(f"ip0_w{w}")] = np.where(cli1, ep1, ep0)
        ints_out[:, ii(f"ip1_w{w}")] = np.where(cli1, ep0, ep1)
    p0, p1 = fi[:, _II("ep0_port")], fi[:, _II("ep1_port")]
    ints_out[:, ii("client_port")] = np.where(cli1, p1, p0)
    ints_out[:, ii("server_port")] = np.where(cli1, p0, p1)
    ints_out[:, ii("protocol")] = fi[:, _II("protocol")]
    # dispatcher orientation → tap_type + tap_side (TapSide::from(L2End),
    # document.rs): client-local → c(1), server-local → s(2), both → 1
    # (the reference reports the client view), neither → rest(0)
    tap = fi[:, _II("tap_type")]
    ints_out[:, ii("tap_type")] = np.where(tap > 0, tap, 3)
    e0 = fi[:, _II("l2_end_ep0")].astype(bool)
    e1 = fi[:, _II("l2_end_ep1")].astype(bool)
    cli_end = np.where(cli1, e1, e0)
    srv_end = np.where(cli1, e0, e1)
    ints_out[:, ii("tap_side")] = np.where(
        cli_end, 1, np.where(srv_end, 2, 0)
    )
    ints_out[:, ii("signal_source")] = 0
    ints_out[:, ii("start_time")] = fi[:, _II("start_time")]
    ints_out[:, ii("end_time")] = now
    ints_out[:, ii("status")] = 1
    ints_out[:, ii("close_type")] = raw["close_type"]
    ints_out[:, ii("state")] = raw["tcp_state"]
    new = raw["new_flow"].astype(bool)
    ints_out[:, ii("is_new_flow")] = new
    fl0, fl1 = fi[:, _II("flags_d0")], fi[:, _II("flags_d1")]
    ints_out[:, ii("tcp_flags_bit_0")] = np.where(cli1, fl1, fl0)
    ints_out[:, ii("tcp_flags_bit_1")] = np.where(cli1, fl0, fl1)

    def dmap(base):
        a = fn[:, _NI(f"{base}_d0")]
        b = fn[:, _NI(f"{base}_d1")]
        return np.where(cli1, b, a), np.where(cli1, a, b)

    for src, (tx, rx) in (
        ("packet", dmap("packet")),
        ("byte", dmap("byte")),
        ("l4_byte", dmap("l4_byte")),
        ("retrans", dmap("retrans")),
        ("total_packet", dmap("total_packet")),
        ("total_byte", dmap("total_byte")),
    ):
        nums_out[:, ni(f"{src}_tx")] = tx
        nums_out[:, ni(f"{src}_rx")] = rx
    nums_out[:, ni("syn_count")] = fn[:, _NI("syn_count")]
    nums_out[:, ni("synack_count")] = fn[:, _NI("synack_count")]
    # handshake RTT is stamped once, on the flow's first emission —
    # re-stamping every second would weight RTT stats by flow lifetime
    nums_out[:, ni("rtt")] = np.where(
        new, (raw["rtt_client"] + raw["rtt_server"]).astype(np.float32), 0
    )
    nums_out[:, ni("rtt_client_max")] = np.where(new, raw["rtt_client"], 0)
    nums_out[:, ni("rtt_server_max")] = np.where(new, raw["rtt_server"], 0)
    return FlowLogBatch(s, ints_out, nums_out, np.ones(n, bool))
