"""eBPF socket-data bridge — the ebpf_dispatcher seat.

The reference's eBPF plane captures syscall-level socket payloads in
kernel C (socket_trace.bpf.c), and `ebpf_dispatcher.rs` synthesizes
MetaPackets from them so the same FlowMap/L7 machinery processes kernel
events and wire packets alike — with SignalSource::EBPF, which the L4
metric plane skips (quadruple_generator.rs:420-423; our fanout gate).

Kernel eBPF itself cannot exist in this container; this module is the
*userspace half*: it accepts socket-data events (the fields the
reference's tracer emits per syscall: pid, 5-tuple, direction, capture
sequence, payload bytes, µs timestamp) and synthesizes the [N, SNAP]
buffer + PacketBatch the L7Engine consumes — payloads enter protocol
inference/parsing exactly like wire capture, but rows carry no L4
meters and are tagged SignalSource.EBPF downstream.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..datamodel.code import SignalSource
from .packet import PacketBatch


@dataclasses.dataclass
class SocketDataEvent:
    """One eBPF socket read/write capture (socket_trace.bpf.c output)."""

    pid: int
    ip_src: int  # IPv4 u32 (local side)
    ip_dst: int
    port_src: int
    port_dst: int
    protocol: int  # 6 tcp / 17 udp
    direction: int  # 0 egress (write/send), 1 ingress (read/recv)
    payload: bytes
    timestamp_us: int
    cap_seq: int = 0  # tracer capture sequence (ordering)


def events_to_batch(
    events: list[SocketDataEvent], snap: int = 1 << 10
) -> tuple[np.ndarray, PacketBatch]:
    """Socket events → (payload buffer, PacketBatch) for L7Engine.process.

    The synthesized rows look like payload-bearing packets with zero L2/
    L3 headroom: payload_off=0, payload in the buffer row, 5-tuple from
    the socket (ingress events swap src/dst so the tuple is always the
    sender's view, like the reference's MetaPacket synthesis).
    """
    events = sorted(events, key=lambda e: (e.timestamp_us, e.cap_seq))
    n = len(events)
    buf = np.zeros((n, snap), np.uint8)
    z = np.zeros(n, np.uint32)
    ip_src = np.zeros((n, 4), np.uint32)
    ip_dst = np.zeros((n, 4), np.uint32)
    sport = np.zeros(n, np.uint32)
    dport = np.zeros(n, np.uint32)
    proto = np.zeros(n, np.uint32)
    plen = np.zeros(n, np.uint32)
    ts_s = np.zeros(n, np.uint32)
    ts_us = np.zeros(n, np.uint32)
    for i, e in enumerate(events):
        pl = e.payload[:snap]
        buf[i, : len(pl)] = np.frombuffer(pl, np.uint8)
        plen[i] = len(pl)
        src, dst = (e.ip_src, e.ip_dst), (e.port_src, e.port_dst)
        if e.direction == 1:  # ingress: sender is the remote side
            ip_src[i, 3], ip_dst[i, 3] = e.ip_dst, e.ip_src
            sport[i], dport[i] = e.port_dst, e.port_src
        else:
            ip_src[i, 3], ip_dst[i, 3] = e.ip_src, e.ip_dst
            sport[i], dport[i] = e.port_src, e.port_dst
        proto[i] = e.protocol
        ts_s[i] = e.timestamp_us // 1_000_000
        ts_us[i] = e.timestamp_us % 1_000_000
    p = PacketBatch(
        timestamp_s=ts_s,
        timestamp_us=ts_us,
        is_ipv6=z.copy(),
        ip_src=ip_src,
        ip_dst=ip_dst,
        port_src=sport,
        port_dst=dport,
        protocol=proto,
        tcp_flags=z.copy(),
        seq=z.copy(),
        ack=z.copy(),
        payload_len=plen,
        payload_off=z.copy(),
        packet_len=plen.copy(),
        tunnel_type=z.copy(),
        valid=np.ones(n, bool),
    )
    return buf, p


class EbpfDispatcher:
    """Feeds socket events into an L7Engine; emitted rows are re-tagged
    SignalSource.EBPF on both the log ints and the AppMeter tags (the
    fanout gate then keeps them off the L4 metric plane)."""

    def __init__(self, l7_engine):
        self.l7 = l7_engine
        self.counters = {"events_in": 0, "sessions_out": 0}

    def process(self, events: list[SocketDataEvent]):
        from ..flowlog.schema import L7_FLOW_LOG

        self.counters["events_in"] += len(events)
        buf, p = events_to_batch(events)
        log_batch, app_batch = self.l7.process(buf, p)
        sig = int(SignalSource.EBPF)
        if log_batch.size:
            log_batch.ints[:, L7_FLOW_LOG.int_index("signal_source")] = sig
        if app_batch.valid.any():
            app_batch.tags["signal_source"][:] = sig
        self.counters["sessions_out"] += log_batch.size
        return log_batch, app_batch


@dataclasses.dataclass
class PerfStackSample:
    """One perf/on-CPU stack capture (perf_profiler.c ring output):
    raw user-space return addresses, leaf first."""

    pid: int
    stack: list  # of int addresses
    weight: int = 1  # sample count (or off-CPU µs, etc.)


class ContinuousProfiler:
    """The perf_profiler.c userspace loop: raw stack samples →
    symbolized folded aggregation per window → PROFILE frames through
    the given sender (the same wire shape the /api/v1/profile HTTP
    intake ships, so the server's flame plane needs nothing new)."""

    def __init__(self, sender=None, *, app_service: str = "",
                 event_type: str = "cpu", interval_s: float = 10.0):
        from .symbolizer import ProfileAggregator

        self.agg = ProfileAggregator(
            app_service=app_service, event_type=event_type
        )
        self.sender = sender
        self.interval_s = interval_s
        self._last_flush = 0.0
        self.counters = {"frames_sent": 0}

    def observe(self, samples: list[PerfStackSample]) -> None:
        for s in samples:
            self.agg.observe(s.pid, s.stack, s.weight)

    def maybe_flush(self, now: float, timestamp: int | None = None) -> bytes | None:
        """Interval-driven flush for poll loops: emits only when
        `interval_s` elapsed since the last frame."""
        if now - self._last_flush < self.interval_s:
            return None
        self._last_flush = now
        return self.flush(int(timestamp if timestamp is not None else now))

    def flush(self, timestamp: int) -> bytes | None:
        frame = self.agg.flush(timestamp)
        if frame is not None and self.sender is not None:
            self.sender.send(frame)
            self.counters["frames_sent"] += 1
        return frame
