"""Vectorized packet header parsing — the dispatcher/recv_engine seat.

The reference's dispatcher parses packets one at a time into MetaPacket
structs (agent/src/dispatcher/, agent/src/common/meta_packet.rs). Here a
capture batch is a [N, SNAP] u8 matrix and the whole parse is
data-parallel gathers/compares over it: per-row header offsets are
*data* (index vectors), not control flow, so one pass handles a mixed
batch of VLAN/no-VLAN, v4/v6, TCP/UDP packets. The output SoA feeds the
device FlowMap directly.

Covered: Ethernet + up to two 802.1Q VLAN tags, IPv4 (options via IHL),
IPv6 (fixed header), TCP (flags/seq/ack/payload via data-offset), UDP,
ICMP, and one vectorized decap level covering the reference's overlay
set (dispatcher decap): VXLAN (UDP :4789 → inner Ethernet), IPIP
(proto 4/41 → inner IP), GRE (proto 47 → inner IP), and ERSPAN II/III
over GRE (→ inner Ethernet).
Unknown ethertypes/protocols yield valid=False rows, never errors —
capture streams contain garbage by design.
"""

from __future__ import annotations

import dataclasses

import numpy as np

ETH_IPV4 = 0x0800
ETH_IPV6 = 0x86DD
ETH_VLAN = 0x8100
ETH_QINQ = 0x88A8
PROTO_ICMP = 1
PROTO_TCP = 6
PROTO_UDP = 17
PROTO_GRE = 47
VXLAN_PORT = 4789

TCP_FIN = 0x01
TCP_SYN = 0x02
TCP_RST = 0x04
TCP_PSH = 0x08
TCP_ACK = 0x10


@dataclasses.dataclass
class PacketBatch:
    """Parsed MetaPacket columns (SoA)."""

    timestamp_s: np.ndarray  # [N] u32 epoch seconds
    timestamp_us: np.ndarray  # [N] u32 microseconds within the second
    is_ipv6: np.ndarray  # [N] u32 0/1
    ip_src: np.ndarray  # [N, 4] u32 words (v4 in word 3)
    ip_dst: np.ndarray  # [N, 4] u32
    port_src: np.ndarray  # [N] u32
    port_dst: np.ndarray  # [N] u32
    protocol: np.ndarray  # [N] u32
    tcp_flags: np.ndarray  # [N] u32
    seq: np.ndarray  # [N] u32
    ack: np.ndarray  # [N] u32
    payload_len: np.ndarray  # [N] u32 (L4 payload bytes)
    payload_off: np.ndarray  # [N] u32 (offset of the L4 payload in the snap)
    packet_len: np.ndarray  # [N] u32 (on-wire length incl. L2)
    tunnel_type: np.ndarray  # [N] u32 (0 none, 1 vxlan, 2 ipip, 3 gre, 4 erspan)
    valid: np.ndarray  # [N] bool
    # outer-frame L2 identity for the dispatcher modes: low 32 bits of
    # the MACs (the reference's vm-mac set keys on to_lower_32b,
    # mirror_mode_dispatcher.rs:103) + the outer VLAN id (analyzer-mode
    # tap_type mapping). Zeros when absent.
    mac_src_lo: np.ndarray | None = None  # [N] u32
    mac_dst_lo: np.ndarray | None = None  # [N] u32
    vlan_id: np.ndarray | None = None  # [N] u32

    def __post_init__(self):
        n = self.valid.shape[0]
        for f in ("mac_src_lo", "mac_dst_lo", "vlan_id"):
            if getattr(self, f) is None:
                setattr(self, f, np.zeros(n, np.uint32))

    @property
    def size(self) -> int:
        return self.valid.shape[0]


def _u8(buf, off):
    return buf[np.arange(buf.shape[0]), off].astype(np.uint32)


def _u16(buf, off):
    return _u8(buf, off) << 8 | _u8(buf, off + 1)


def _u32(buf, off):
    return _u16(buf, off) << 16 | _u16(buf, off + 2)


@dataclasses.dataclass
class _Headers:
    ok: np.ndarray
    is_v6: np.ndarray
    proto: np.ndarray
    ip_src: np.ndarray
    ip_dst: np.ndarray
    sport: np.ndarray
    dport: np.ndarray
    seq: np.ndarray
    ack: np.ndarray
    flags: np.ndarray
    payload: np.ndarray
    payload_off: np.ndarray
    is_udp: np.ndarray
    l4_off: np.ndarray


def _parse_headers(
    buf: np.ndarray, lengths: np.ndarray, l2_off: np.ndarray,
    l3_off: np.ndarray | None = None,
) -> _Headers:
    """Rows parse from an Ethernet header at l2_off; rows whose l3_off
    is ≥ 0 instead start straight at an IP header (IPIP / GRE-delivered
    inner packets carry no inner Ethernet) — version nibble decides
    v4/v6 there."""
    n, snap = buf.shape
    # clamp the L2 start so every fixed-offset read stays in the snap
    # (inner VXLAN offsets are data-driven); rows whose true headers
    # don't fit are rejected by the `fits` gate below
    fits = l2_off + 54 <= snap
    l2_off = np.minimum(l2_off, snap - 54).astype(np.int64)
    # -- L2: ethertype with up to two VLAN tags
    et = _u16(buf, l2_off + 12)
    off = (l2_off + 14).astype(np.int64)
    for _ in range(2):
        is_vlan = (et == ETH_VLAN) | (et == ETH_QINQ)
        et = np.where(is_vlan, _u16(buf, np.minimum(off + 2, snap - 2).astype(np.int64)), et)
        off = np.where(is_vlan, off + 4, off)

    if l3_off is not None:
        use3 = np.asarray(l3_off) >= 0
        l3_c = np.minimum(np.maximum(l3_off, 0), snap - 41).astype(np.int64)
        ver = _u8(buf, l3_c) >> 4
        et = np.where(
            use3,
            np.where(ver == 6, ETH_IPV6, np.where(ver == 4, ETH_IPV4, 0)),
            et,
        )
        off = np.where(use3, l3_c, off)
        # +41 matches the snap-41 clamp: an IP header the clamp would
        # shift is rejected, not parsed one byte early
        fits = np.where(use3, np.asarray(l3_off) + 41 <= snap, fits)

    v4 = et == ETH_IPV4
    v6 = et == ETH_IPV6
    off_c = np.minimum(off, snap - 41).astype(np.int64)  # clamp: v6 header reach

    # -- L3
    ihl = (_u8(buf, off_c) & 0x0F).astype(np.int64) * 4
    proto = np.where(v4, _u8(buf, off_c + 9), np.where(v6, _u8(buf, off_c + 6), 0))
    l4_off = np.where(v4, off_c + ihl, off_c + 40)

    src4 = _u32(buf, off_c + 12)
    dst4 = _u32(buf, off_c + 16)
    ip_src = np.zeros((n, 4), np.uint32)
    ip_dst = np.zeros((n, 4), np.uint32)
    for w in range(4):
        ip_src[:, w] = np.where(v6, _u32(buf, off_c + 8 + 4 * w), np.where(v4 & (w == 3), src4, 0))
        ip_dst[:, w] = np.where(v6, _u32(buf, off_c + 24 + 4 * w), np.where(v4 & (w == 3), dst4, 0))

    ip_total = np.where(v4, _u16(buf, off_c + 2), _u16(buf, off_c + 4) + 40)

    # -- L4
    is_tcp = proto == PROTO_TCP
    is_udp = proto == PROTO_UDP
    l4_c = np.minimum(l4_off, snap - 20).astype(np.int64)
    sport = _u16(buf, l4_c)
    dport = _u16(buf, l4_c + 2)
    seq = _u32(buf, l4_c + 4)
    ackn = _u32(buf, l4_c + 8)
    doff = (_u8(buf, l4_c + 12) >> 4).astype(np.int64) * 4
    flags = _u8(buf, l4_c + 13)
    l4_hdr = np.where(is_tcp, doff, np.where(is_udp, 8, 0))
    payload = ip_total.astype(np.int64) - (l4_off - off_c) - l4_hdr
    # ICMP keeps the whole message (type byte onward) as its payload so
    # the PING parser sees the echo header (ping.rs ICMP seat)
    payload = np.where(is_tcp | is_udp | (proto == PROTO_ICMP), np.maximum(payload, 0), 0)

    ok = fits & (v4 | v6) & (lengths >= 34) & (l4_off + np.where(is_tcp, 20, 8) <= snap)
    return _Headers(
        ok=ok,
        is_v6=v6,
        proto=proto.astype(np.uint32),
        ip_src=ip_src,
        ip_dst=ip_dst,
        sport=np.where(is_tcp | is_udp, sport, 0).astype(np.uint32),
        dport=np.where(is_tcp | is_udp, dport, 0).astype(np.uint32),
        seq=np.where(is_tcp, seq, 0).astype(np.uint32),
        ack=np.where(is_tcp, ackn, 0).astype(np.uint32),
        flags=np.where(is_tcp, flags, 0).astype(np.uint32),
        payload=payload.astype(np.uint32),
        payload_off=(l4_c + l4_hdr).astype(np.uint32),
        is_udp=is_udp,
        l4_off=l4_off,
    )


def parse_packets(
    buf: np.ndarray, lengths: np.ndarray, ts_s: np.ndarray, ts_us: np.ndarray | None = None
) -> PacketBatch:
    """[N, SNAP] u8 capture matrix → PacketBatch columns, with one
    vectorized decap pass over VXLAN / IPIP / GRE / ERSPAN II+III (the
    same header stage re-run at per-row inner offsets)."""
    buf = np.asarray(buf, np.uint8)
    n, snap = buf.shape
    if snap < 54:
        raise ValueError(f"snap {snap} too small: need >= 54 header bytes")
    lengths = np.asarray(lengths, np.uint32)
    zero_off = np.zeros(n, np.int64)

    outer = _parse_headers(buf, lengths, zero_off)
    h = outer
    tunnel = np.zeros(n, np.uint32)

    # -- one vectorized decap level: VXLAN / IPIP / GRE / ERSPAN-over-GRE
    # (the reference's decap set, dispatcher/mod.rs; deeper nesting is a
    # second pass nobody's traffic needs at the capture edge)
    is_vxlan = outer.ok & outer.is_udp & (outer.dport == VXLAN_PORT)
    is_ipip = outer.ok & ((outer.proto == 4) | (outer.proto == 41))
    is_gre = outer.ok & (outer.proto == PROTO_GRE)
    l4c = np.minimum(outer.l4_off, snap - 4).astype(np.int64)
    gre_flags = _u16(buf, l4c)
    gre_proto = _u16(buf, np.minimum(l4c + 2, snap - 2).astype(np.int64))
    # base 4 bytes + checksum(+reserved) 4 + key 4 + sequence 4
    gre_len = (
        4
        + 4 * ((gre_flags >> 15) & 1)
        + 4 * ((gre_flags >> 13) & 1)
        + 4 * ((gre_flags >> 12) & 1)
    ).astype(np.int64)
    gre_ip = is_gre & ((gre_proto == ETH_IPV4) | (gre_proto == ETH_IPV6))
    erspan2 = is_gre & (gre_proto == 0x88BE)  # ERSPAN type II: 8-byte hdr
    erspan3 = is_gre & (gre_proto == 0x22EB)  # ERSPAN type III: 12 bytes
    # type III O bit (LSB of the header's last byte) appends an 8-byte
    # platform-specific subheader before the inner Ethernet
    ers3_last = _u8(
        buf, np.minimum(outer.l4_off + gre_len + 11, snap - 1).astype(np.int64)
    )
    ers3_extra = np.where(erspan3 & ((ers3_last & 1) == 1), 8, 0).astype(np.int64)

    minus1 = np.full(n, -1, np.int64)
    inner_l2 = np.where(
        is_vxlan,
        outer.l4_off + 8 + 8,  # UDP + VXLAN hdr
        np.where(
            erspan2,
            outer.l4_off + gre_len + 8,
            np.where(erspan3, outer.l4_off + gre_len + 12 + ers3_extra, minus1),
        ),
    ).astype(np.int64)
    inner_l3 = np.where(
        is_ipip, outer.l4_off, np.where(gre_ip, outer.l4_off + gre_len, minus1)
    ).astype(np.int64)

    want_inner = (inner_l2 >= 0) | (inner_l3 >= 0)
    if want_inner.any():
        inner = _parse_headers(
            buf, lengths, np.maximum(inner_l2, 0), l3_off=inner_l3
        )
        sel = want_inner & inner.ok
        tunnel = np.where(
            sel & is_vxlan, 1,
            np.where(sel & is_ipip, 2,
                     np.where(sel & gre_ip, 3,
                              np.where(sel & (erspan2 | erspan3), 4, 0))),
        ).astype(np.uint32)

        def pick(o, i):
            return np.where(sel[:, None] if o.ndim == 2 else sel, i, o)

        h = _Headers(
            **{
                f.name: pick(getattr(outer, f.name), getattr(inner, f.name))
                for f in dataclasses.fields(_Headers)
            }
        )

    # outer-frame L2 identity (offset 0: dst mac, 6: src mac; the VLAN
    # id sits after ethertype 0x8100/0x88a8 when tagged)
    outer_et = _u16(buf, np.full(n, 12, np.int64))
    vlan_id = np.where(
        (outer_et == ETH_VLAN) | (outer_et == ETH_QINQ),
        _u16(buf, np.full(n, 14, np.int64)) & 0x0FFF,
        0,
    ).astype(np.uint32)

    return PacketBatch(
        timestamp_s=np.asarray(ts_s, np.uint32),
        timestamp_us=np.asarray(
            ts_us if ts_us is not None else np.zeros(n), np.uint32
        ),
        is_ipv6=h.is_v6.astype(np.uint32),
        ip_src=h.ip_src,
        ip_dst=h.ip_dst,
        port_src=h.sport,
        port_dst=h.dport,
        protocol=h.proto,
        tcp_flags=h.flags,
        seq=h.seq,
        ack=h.ack,
        payload_len=h.payload,
        payload_off=h.payload_off,
        packet_len=lengths,
        tunnel_type=tunnel,
        valid=h.ok,
        mac_dst_lo=_u32(buf, np.full(n, 2, np.int64)),
        mac_src_lo=_u32(buf, np.full(n, 8, np.int64)),
        vlan_id=vlan_id,
    )


# ---------------------------------------------------------------------------
# packet crafting (tests / synthetic capture)


def craft_tcp(
    src_ip: int,
    dst_ip: int,
    sport: int,
    dport: int,
    *,
    flags: int = TCP_ACK,
    seq: int = 0,
    ack: int = 0,
    payload: bytes = b"",
    vlan: int | None = None,
    mac_src: int = 0x020000000002,
    mac_dst: int = 0x020000000001,
) -> bytes:
    eth = mac_dst.to_bytes(6, "big") + mac_src.to_bytes(6, "big")
    if vlan is not None:
        eth += (0x8100).to_bytes(2, "big") + vlan.to_bytes(2, "big")
    eth += (0x0800).to_bytes(2, "big")
    tcp = (
        sport.to_bytes(2, "big")
        + dport.to_bytes(2, "big")
        + seq.to_bytes(4, "big")
        + ack.to_bytes(4, "big")
        + bytes([5 << 4, flags])
        + (65535).to_bytes(2, "big")
        + b"\x00\x00\x00\x00"
    )
    total = 20 + len(tcp) + len(payload)
    ip = (
        bytes([0x45, 0])
        + total.to_bytes(2, "big")
        + b"\x00\x00\x40\x00\x40"
        + bytes([PROTO_TCP])
        + b"\x00\x00"
        + src_ip.to_bytes(4, "big")
        + dst_ip.to_bytes(4, "big")
    )
    return eth + ip + tcp + payload


def craft_udp(src_ip: int, dst_ip: int, sport: int, dport: int, payload: bytes = b"") -> bytes:
    eth = b"\x02\x00\x00\x00\x00\x01\x02\x00\x00\x00\x00\x02" + (0x0800).to_bytes(2, "big")
    udp = (
        sport.to_bytes(2, "big")
        + dport.to_bytes(2, "big")
        + (8 + len(payload)).to_bytes(2, "big")
        + b"\x00\x00"
    )
    total = 20 + 8 + len(payload)
    ip = (
        bytes([0x45, 0])
        + total.to_bytes(2, "big")
        + b"\x00\x00\x40\x00\x40"
        + bytes([PROTO_UDP])
        + b"\x00\x00"
        + src_ip.to_bytes(4, "big")
        + dst_ip.to_bytes(4, "big")
    )
    return eth + ip + udp + payload


def craft_icmp(src_ip: int, dst_ip: int, icmp: bytes) -> bytes:
    """IPv4 frame carrying a raw ICMP message (echo header + data)."""
    eth = b"\x02\x00\x00\x00\x00\x01\x02\x00\x00\x00\x00\x02" + (0x0800).to_bytes(2, "big")
    total = 20 + len(icmp)
    ip = (
        bytes([0x45, 0])
        + total.to_bytes(2, "big")
        + b"\x00\x00\x40\x00\x40"
        + bytes([PROTO_ICMP])
        + b"\x00\x00"
        + src_ip.to_bytes(4, "big")
        + dst_ip.to_bytes(4, "big")
    )
    return eth + ip + icmp


def craft_vxlan(outer_src: int, outer_dst: int, vni: int, inner: bytes) -> bytes:
    vxlan = bytes([0x08, 0, 0, 0]) + vni.to_bytes(3, "big") + b"\x00"
    return craft_udp(outer_src, outer_dst, 54321, VXLAN_PORT, vxlan + inner)


def to_batch(
    packets: list[bytes], ts_s: list[int], ts_us: list[int] | None = None, snap: int = 192
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Raw packet list → (buf [N, snap] u8, lengths, ts_s, ts_us)."""
    n = len(packets)
    buf = np.zeros((n, snap), np.uint8)
    lengths = np.zeros(n, np.uint32)
    for i, p in enumerate(packets):
        lengths[i] = len(p)
        b = p[:snap]
        buf[i, : len(b)] = np.frombuffer(b, np.uint8)
    us = np.asarray(ts_us if ts_us is not None else [0] * n, np.uint32)
    return buf, lengths, np.asarray(ts_s, np.uint32), us


@dataclasses.dataclass(frozen=True)
class CaptureFilter:
    """Vectorized capture filter — the dispatcher's BPF seat.

    The reference compiles operator BPF expressions into the kernel
    socket (dispatcher/recv_engine BPF filters); here the same common
    predicates evaluate as one mask over the parsed batch. Empty tuples
    mean "no constraint"; `exclude_*` wins over includes (classic
    "not port 22" usage).
    """

    protocols: tuple = ()  # allowed IP protocol numbers
    ports: tuple = ()  # allowed ports (either side)
    hosts: tuple = ()  # allowed IPv4 addresses (either side, u32)
    exclude_ports: tuple = ()
    exclude_hosts: tuple = ()

    def mask(self, p: PacketBatch) -> np.ndarray:
        m = np.ones(p.size, bool)
        v4 = p.is_ipv6 == 0  # host filters carry IPv4 values; word-3
        # comparison against a v6 address's low word would be a false hit
        if self.protocols:
            m &= np.isin(p.protocol, np.asarray(self.protocols, np.uint32))
        if self.ports:
            allow = np.asarray(self.ports, np.uint32)
            m &= np.isin(p.port_src, allow) | np.isin(p.port_dst, allow)
        if self.hosts:
            allow = np.asarray(self.hosts, np.uint32)
            m &= v4 & (np.isin(p.ip_src[:, 3], allow) | np.isin(p.ip_dst[:, 3], allow))
        if self.exclude_ports:
            deny = np.asarray(self.exclude_ports, np.uint32)
            m &= ~(np.isin(p.port_src, deny) | np.isin(p.port_dst, deny))
        if self.exclude_hosts:
            deny = np.asarray(self.exclude_hosts, np.uint32)
            m &= ~(
                v4 & (np.isin(p.ip_src[:, 3], deny) | np.isin(p.ip_dst[:, 3], deny))
            )
        return m

    def apply(self, p: PacketBatch) -> PacketBatch:
        p.valid = p.valid & self.mask(p)
        return p
