"""Agent data plane: vectorized packet parsing (dispatcher seat) and the
device-resident FlowMap (flow_generator seat) — the TPU rebuild of
agent/src/dispatcher + agent/src/flow_generator.
"""
