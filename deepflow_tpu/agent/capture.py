"""Live packet capture — the dispatcher's AF_PACKET seat.

The reference's recv_engine captures via AF_PACKET/af-xdp ring maps
(agent/src/dispatcher/recv_engine/af_packet). This build keeps the
same seat with a plain AF_PACKET SOCK_RAW socket: frames accumulate
into the fixed [N, snap] u8 batches the vectorized parser consumes and
ship to `Agent.step` on size or time. No ring mmap — the vectorized
batch parse downstream is where this design spends its complexity
budget; the capture loop just moves bytes.

Root/CAP_NET_RAW required (same as the reference's dispatcher).
"""

from __future__ import annotations

import socket
import time

import numpy as np

ETH_P_ALL = 0x0003


class AfPacketCapture:
    def __init__(self, interface: str = "lo", *, snap: int = 192,
                 batch_size: int = 4096, flush_ms: int = 200):
        self.interface = interface
        self.snap = snap
        self.batch_size = batch_size
        self.flush_ms = flush_ms
        self._sock = socket.socket(
            socket.AF_PACKET, socket.SOCK_RAW, socket.htons(ETH_P_ALL)
        )
        self._sock.bind((interface, 0))
        self._sock.settimeout(0.05)
        self.counters = {"frames": 0, "bytes": 0, "truncated": 0}
        self._running = True

    def close(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass

    def batches(self, *, duration_s: float | None = None):
        """Yield (buf [N, snap] u8, lengths, ts_s, ts_us) batches until
        closed (or for `duration_s`). Partial batches flush on the
        flush_ms deadline so quiet interfaces still make progress."""
        deadline = None if duration_s is None else time.time() + duration_s
        frames: list[tuple[bytes, int]] = []  # (snap-truncated bytes, wire len)
        stamps: list[float] = []
        flush_at = time.time() + self.flush_ms / 1e3
        while self._running and (deadline is None or time.time() < deadline):
            try:
                data = self._sock.recv(1 << 16)
                now = time.time()
                self.counters["frames"] += 1
                self.counters["bytes"] += len(data)
                if len(data) > self.snap:
                    self.counters["truncated"] += 1
                # keep the ORIGINAL length: packet_len feeds flow byte
                # meters; the snap only bounds parse bytes (to_batch
                # makes the same distinction for replay — not reused
                # here because it needs full frames retained, and a live
                # source must bound buffered bytes at snap per frame)
                if not frames:
                    # arm the deadline from the FIRST frame of a batch,
                    # or an idle gap longer than flush_ms would flush
                    # every subsequent packet as its own 1-frame batch
                    flush_at = now + self.flush_ms / 1e3
                frames.append((data[: self.snap], len(data)))
                stamps.append(now)
            except socket.timeout:
                pass
            except OSError:
                break  # still flush what was captured before the error
            if frames and (len(frames) >= self.batch_size or time.time() >= flush_at):
                yield self._pack(frames, stamps)
                frames, stamps = [], []
        if frames:
            yield self._pack(frames, stamps)

    def _pack(self, frames: list[tuple[bytes, int]], stamps: list[float]):
        return _pack_frames(self.snap, frames, stamps)


def _pack_frames(snap: int, frames: list[tuple[bytes, int]], stamps: list[float]):
    n = len(frames)
    buf = np.zeros((n, snap), np.uint8)
    lengths = np.zeros((n,), np.uint32)
    for i, (fr, wire_len) in enumerate(frames):
        buf[i, : len(fr)] = np.frombuffer(fr, np.uint8)
        lengths[i] = wire_len
    ts = np.asarray(stamps)
    ts_s = ts.astype(np.uint32)
    ts_us = ((ts - ts_s) * 1e6).astype(np.uint32)
    return buf, lengths, ts_s, ts_us


# ---------------------------------------------------------------------------
# TPACKET_V3 ring capture — the reference's af_packet recv_engine
# (dispatcher/recv_engine/af_packet/tpacket.rs): the kernel writes
# frames into an mmap'd block ring and hands whole blocks to userspace,
# amortizing the syscall per BLOCK instead of per packet. Pure
# socket+mmap+struct — no libpcap.

import mmap as _mmap
import select as _select
import struct as _struct

SOL_PACKET = 263
PACKET_RX_RING = 5
PACKET_VERSION = 10
TPACKET_V3 = 2
TP_STATUS_KERNEL = 0
TP_STATUS_USER = 1


class AfPacketRingCapture:
    """Block-ring flavor of AfPacketCapture (same batches() shape).

    Ring geometry follows the reference's defaults scaled down: block
    retirement (`retire_ms`) bounds latency on quiet links the way
    flush_ms does for the plain socket."""

    def __init__(self, interface: str = "lo", *, snap: int = 192,
                 batch_size: int = 4096, block_size: int = 1 << 18,
                 block_count: int = 8, retire_ms: int = 100):
        self.interface = interface
        self.snap = snap
        self.batch_size = batch_size
        self.block_size = block_size
        self.block_count = block_count
        self._sock = socket.socket(
            socket.AF_PACKET, socket.SOCK_RAW, socket.htons(ETH_P_ALL)
        )
        self._sock.setsockopt(SOL_PACKET, PACKET_VERSION, TPACKET_V3)
        # tpacket_req3: block_size, block_nr, frame_size, frame_nr,
        # retire_blk_tov, sizeof_priv, feature_req_word
        frame_size = 1 << 11
        req = _struct.pack(
            "IIIIIII", block_size, block_count, frame_size,
            block_size // frame_size * block_count, retire_ms, 0, 0,
        )
        self._sock.setsockopt(SOL_PACKET, PACKET_RX_RING, req)
        self._sock.bind((interface, 0))
        self._ring = _mmap.mmap(
            self._sock.fileno(), block_size * block_count,
            _mmap.MAP_SHARED, _mmap.PROT_READ | _mmap.PROT_WRITE,
        )
        self._next_block = 0
        self.counters = {"frames": 0, "bytes": 0, "truncated": 0, "blocks": 0}
        self._running = True

    def close(self) -> None:
        self._running = False
        try:
            self._ring.close()
        except (OSError, ValueError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    # -- block walk ------------------------------------------------------
    def _drain_block(self, frames: list, stamps: list) -> bool:
        """Consume the next ring block if the kernel released it."""
        base = self._next_block * self.block_size
        ring = self._ring
        # tpacket_block_desc: version, offset_to_priv, then
        # tpacket_hdr_v1 {block_status, num_pkts, offset_to_first_pkt,…}
        status, = _struct.unpack_from("I", ring, base + 8)
        if not status & TP_STATUS_USER:
            return False
        num_pkts, first_off = _struct.unpack_from("II", ring, base + 12)
        off = base + first_off
        for _ in range(num_pkts):
            (next_off, tp_sec, tp_nsec, tp_snaplen, tp_len, _tp_status,
             tp_mac) = _struct.unpack_from("IIIIIIH", ring, off)
            data = bytes(ring[off + tp_mac: off + tp_mac + min(tp_snaplen, self.snap)])
            self.counters["frames"] += 1
            self.counters["bytes"] += tp_len
            if tp_snaplen > self.snap:
                self.counters["truncated"] += 1
            frames.append((data, tp_len))
            stamps.append(tp_sec + tp_nsec / 1e9)
            if not next_off:
                break
            off += next_off
        # release the block back to the kernel
        _struct.pack_into("I", ring, base + 8, TP_STATUS_KERNEL)
        self._next_block = (self._next_block + 1) % self.block_count
        self.counters["blocks"] += 1
        return True

    def batches(self, *, duration_s: float | None = None):
        """Yield (buf [N, snap] u8, lengths, ts_s, ts_us) batches —
        one per retired ring block group (same contract as
        AfPacketCapture.batches)."""
        deadline = None if duration_s is None else time.time() + duration_s
        frames: list[tuple[bytes, int]] = []
        stamps: list[float] = []
        poll = _select.poll()
        poll.register(self._sock.fileno(), _select.POLLIN)
        while self._running and (deadline is None or time.time() < deadline):
            drained = False
            try:
                while self._drain_block(frames, stamps):
                    drained = True
                    if len(frames) >= self.batch_size:
                        break
            except (OSError, ValueError):
                break  # concurrent close(): flush what was drained
            if drained and frames:
                # a block can hold more than batch_size packets — the
                # downstream batch parser has a fixed shape, so yield
                # in batch_size slices
                for i in range(0, len(frames), self.batch_size):
                    yield _pack_frames(
                        self.snap, frames[i:i + self.batch_size],
                        stamps[i:i + self.batch_size],
                    )
                frames, stamps = [], []
                continue
            if not drained:
                try:
                    poll.poll(50)  # retire_blk_tov bounds the wait
                except OSError:
                    break
        # reachable only via the break paths (mid-drain close)
        for i in range(0, len(frames), self.batch_size):
            yield _pack_frames(
                self.snap, frames[i:i + self.batch_size],
                stamps[i:i + self.batch_size],
            )
