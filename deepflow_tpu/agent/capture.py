"""Live packet capture — the dispatcher's AF_PACKET seat.

The reference's recv_engine captures via AF_PACKET/af-xdp ring maps
(agent/src/dispatcher/recv_engine/af_packet). This build keeps the
same seat with a plain AF_PACKET SOCK_RAW socket: frames accumulate
into the fixed [N, snap] u8 batches the vectorized parser consumes and
ship to `Agent.step` on size or time. No ring mmap — the vectorized
batch parse downstream is where this design spends its complexity
budget; the capture loop just moves bytes.

Root/CAP_NET_RAW required (same as the reference's dispatcher).
"""

from __future__ import annotations

import socket
import time

import numpy as np

ETH_P_ALL = 0x0003


class AfPacketCapture:
    def __init__(self, interface: str = "lo", *, snap: int = 192,
                 batch_size: int = 4096, flush_ms: int = 200):
        self.interface = interface
        self.snap = snap
        self.batch_size = batch_size
        self.flush_ms = flush_ms
        self._sock = socket.socket(
            socket.AF_PACKET, socket.SOCK_RAW, socket.htons(ETH_P_ALL)
        )
        self._sock.bind((interface, 0))
        self._sock.settimeout(0.05)
        self.counters = {"frames": 0, "bytes": 0, "truncated": 0}
        self._running = True

    def close(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass

    def batches(self, *, duration_s: float | None = None):
        """Yield (buf [N, snap] u8, lengths, ts_s, ts_us) batches until
        closed (or for `duration_s`). Partial batches flush on the
        flush_ms deadline so quiet interfaces still make progress."""
        deadline = None if duration_s is None else time.time() + duration_s
        frames: list[tuple[bytes, int]] = []  # (snap-truncated bytes, wire len)
        stamps: list[float] = []
        flush_at = time.time() + self.flush_ms / 1e3
        while self._running and (deadline is None or time.time() < deadline):
            try:
                data = self._sock.recv(1 << 16)
                now = time.time()
                self.counters["frames"] += 1
                self.counters["bytes"] += len(data)
                if len(data) > self.snap:
                    self.counters["truncated"] += 1
                # keep the ORIGINAL length: packet_len feeds flow byte
                # meters; the snap only bounds parse bytes (to_batch
                # makes the same distinction for replay — not reused
                # here because it needs full frames retained, and a live
                # source must bound buffered bytes at snap per frame)
                if not frames:
                    # arm the deadline from the FIRST frame of a batch,
                    # or an idle gap longer than flush_ms would flush
                    # every subsequent packet as its own 1-frame batch
                    flush_at = now + self.flush_ms / 1e3
                frames.append((data[: self.snap], len(data)))
                stamps.append(now)
            except socket.timeout:
                pass
            except OSError:
                break  # still flush what was captured before the error
            if frames and (len(frames) >= self.batch_size or time.time() >= flush_at):
                yield self._pack(frames, stamps)
                frames, stamps = [], []
        if frames:
            yield self._pack(frames, stamps)

    def _pack(self, frames: list[tuple[bytes, int]], stamps: list[float]):
        n = len(frames)
        buf = np.zeros((n, self.snap), np.uint8)
        lengths = np.zeros((n,), np.uint32)
        for i, (fr, wire_len) in enumerate(frames):
            buf[i, : len(fr)] = np.frombuffer(fr, np.uint8)
            lengths[i] = wire_len
        ts = np.asarray(stamps)
        ts_s = ts.astype(np.uint32)
        ts_us = ((ts - ts_s) * 1e6).astype(np.uint32)
        return buf, lengths, ts_s, ts_us
