"""Possible-host activity map — the utils/possible_host.rs seat.

The reference keeps an LRU of hosts recently seen ORIGINATING traffic
(PossibleHost, capacity-bounded) and consults it when deciding
`is_active_host` for endpoints that platform data doesn't know —
inactive endpoints get their IPs zeroed/aggregated in the doc fanout
(collector.rs get_single_tagger inactive handling). Scalar LRU probing
doesn't vectorize, so this build uses a fixed open-addressing table of
hashed-ip slots with epoch stamps: batch add + batch membership are a
handful of numpy gathers, and aging is free (a slot is live iff its
stamp is within the lease).

Collisions can only FALSELY mark a host active (shared slot), never
inactive — the same failure direction as the reference's LRU dropping
old entries, and harmless: activity is an aggregation hint, not a
correctness bit.
"""

from __future__ import annotations

import numpy as np

_MIX = np.uint64(0x9E3779B97F4A7C15)


def _hash_ips(ip_words: np.ndarray) -> np.ndarray:
    """[N, 4] u32 ip words → [N] u64 keys (splitmix-style fold)."""
    h = np.zeros(ip_words.shape[0], np.uint64)
    for w in range(ip_words.shape[1]):
        h = (h ^ ip_words[:, w].astype(np.uint64)) * _MIX
        h ^= h >> np.uint64(29)
    return h


class PossibleHostTable:
    def __init__(self, *, capacity_pow: int = 18, probes: int = 2,
                 lease_s: int = 300):
        self.mask = (1 << capacity_pow) - 1
        self.probes = probes
        self.lease_s = lease_s
        self.keys = np.zeros(1 << capacity_pow, np.uint64)
        self.stamp = np.zeros(1 << capacity_pow, np.int64)  # 0 = never
        self.counters = {"added": 0, "evicted": 0}

    def _slots(self, keys: np.ndarray, p: int) -> np.ndarray:
        # probe p reads a different 16-bit window of the 64-bit key
        return (keys >> np.uint64(16 * p)).astype(np.int64) & self.mask

    def add_keys(self, keys: np.ndarray, now_s: int) -> None:
        """Mark pre-hashed hosts active at `now_s`."""
        if not len(keys):
            return
        self.counters["added"] += int(len(keys))
        live = self.stamp > now_s - self.lease_s
        for p in range(self.probes):
            slots = self._slots(keys, p)
            ours = self.keys[slots] == keys
            free = ~live[slots]
            take = ours | free
            w = slots[take]
            self.counters["evicted"] += int((free & ~ours & (self.stamp[slots] > 0))[take].sum())
            self.keys[w] = keys[take]
            self.stamp[w] = now_s
            # slots claimed THIS call are live for later probes, or a
            # probe-1 placement could overwrite a probe-0 write and
            # falsely deactivate a host added in the same batch
            live[w] = True
            keys = keys[~take]
            if not len(keys):
                break
        else:
            # all probes occupied by other live hosts: overwrite probe 0
            # (newest-wins, the LRU-evict analog)
            slots = self._slots(keys, 0)
            self.keys[slots] = keys
            self.stamp[slots] = now_s
            self.counters["evicted"] += len(keys)

    def check_keys(self, keys: np.ndarray, now_s: int) -> np.ndarray:
        hit = np.zeros(len(keys), bool)
        fresh = self.stamp > now_s - self.lease_s
        for p in range(self.probes):
            slots = self._slots(keys, p)
            hit |= (self.keys[slots] == keys) & fresh[slots]
        return hit

    def add(self, ip_words: np.ndarray, now_s: int, sel: np.ndarray | None = None) -> None:
        """Mark hosts as active at `now_s`. ip_words [N, 4] u32."""
        keys = _hash_ips(ip_words)
        self.add_keys(keys[sel] if sel is not None else keys, now_s)

    def check(self, ip_words: np.ndarray, now_s: int) -> np.ndarray:
        """[N, 4] ip words → [N] bool: seen originating traffic within
        the lease."""
        return self.check_keys(_hash_ips(ip_words), now_s)
