"""Agent daemon composition — the trident.rs wiring seat.

The reference's trident.rs builds, per capture engine: dispatcher →
FlowMap → {QuadrupleGenerator/Collector, FlowAggr, L7 log} chains, one
UniformSender per output type, config sync, and self-monitoring
(trident.rs:1748-1781 lists every sender). This composes the same
pipeline graph from this package's pieces:

  packet source (pcap replay / crafted batches; live capture has no
  seat in this container) → parse_packets → FlowMap (L4 state) +
  L7Engine (protocol logs) → per-second tick:
    * L4 emissions → DualGranularityPipeline (1s+1m metric docs)
      → METRICS sender
    * L4 emissions → minute FlowAggr → TAGGEDFLOW sender
    * L7 sessions → PROTOCOLLOG sender + L7 AppMeter pipeline → METRICS
  plus AgentSyncClient (config/platform/NTP/upgrade) and the stats loop
  shipping DFSTATS.

`Agent.run_pcap()` is the replay driver; `step()` is the injectable
unit tests drive.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..aggregator.fanout import FanoutConfig
from ..aggregator.pipeline import DualGranularityPipeline, L7Pipeline, PipelineConfig
from ..aggregator.window import WindowConfig
from ..datamodel.batch import FlowBatch
from ..datamodel.code import DocumentFlag
from ..flowlog.aggr import MinuteAggr, ThrottlingQueue
from ..flowlog.codec import encode_rows
from ..ingest.codec import encode_docbatch
from ..ingest.framing import MessageType
from ..ingest.sender import UniformSender
from .dispatcher import Dispatcher, DispatcherConfig
from ..utils.stats import StatsCollector
from .bridge import emissions_to_flow_batch
from .flow_map import FlowMap, FlowTimeouts
from .policy import (
    ACTION_DROP,
    ACTION_PCAP,
    PolicyLabeler,
    PolicyMeterAggregator,
    pcap_frames,
)
from .possible import PossibleHostTable
from .l7.engine import L7Engine
from .packet import CaptureFilter, parse_packets


@dataclasses.dataclass(frozen=True)
class AgentConfig:
    agent_id: int = 1
    organization_id: int = 1
    servers: tuple = (("127.0.0.1", 20033),)
    flow_capacity: int = 1 << 14
    batch_size: int = 1 << 12
    l4_log_throttle: int = 10_000
    compression: str | int = "auto"
    metrics_window: WindowConfig = WindowConfig(capacity=1 << 14)
    # dispatcher BPF seat: evaluated as one vectorized mask per batch
    capture_filter: CaptureFilter | None = None
    # policy plane (labeler.rs seat): ACLs in priority order; DROP
    # removes packets pre-FlowMap, PCAP ships RAW_PCAP frames
    acls: tuple = ()
    # possible-host activity tracking (utils/possible_host.rs seat):
    # when on, is_active_host comes from observed traffic instead of
    # the all-active default, enabling inactive-IP aggregation
    track_host_activity: bool = False
    # dispatcher flavor (dispatcher/mod.rs DispatcherFlavor): local /
    # mirror / analyzer orientation — see agent/dispatcher.py
    dispatcher: DispatcherConfig | None = None


def _compact(buf: np.ndarray, p, retain: np.ndarray):
    """Drop rows from the capture batch (capture-filter / policy-drop
    compaction): slice the snap buffer and every PacketBatch field."""
    return buf[retain], dataclasses.replace(
        p, **{f.name: getattr(p, f.name)[retain] for f in dataclasses.fields(p)}
    )


class Agent:
    def __init__(self, config: AgentConfig = AgentConfig(), *, senders=None):
        c = config
        self.config = c
        self.dispatcher = (
            Dispatcher(c.dispatcher) if c.dispatcher is not None else None
        )
        self.flow_map = FlowMap(
            capacity=c.flow_capacity, batch_size=c.batch_size,
            agent_id=c.agent_id, dispatcher=self.dispatcher,
        )
        self.l7 = L7Engine(agent_id=c.agent_id)
        fanout = FanoutConfig(agent_id=c.agent_id)
        pipe_cfg = PipelineConfig(
            fanout=fanout, window=c.metrics_window, batch_size=c.batch_size
        )
        self.metrics = DualGranularityPipeline(pipe_cfg)
        self.l7_metrics = L7Pipeline(pipe_cfg)
        self.flow_aggr = MinuteAggr(batch_size=4 * c.batch_size)
        self.l4_throttle = ThrottlingQueue(c.l4_log_throttle)

        self._default_senders = senders is None
        if senders is not None:
            self.senders = senders  # test seam: {msg_type: sender-like}
        else:
            self.senders = {
                mt: UniformSender(
                    list(c.servers),
                    mt,
                    agent_id=c.agent_id,
                    organization_id=c.organization_id,
                    compression=c.compression,
                )
                for mt in (
                    MessageType.METRICS,
                    MessageType.TAGGEDFLOW,
                    MessageType.PROTOCOLLOG,
                    MessageType.AGENT_LOG,
                )
                + ((MessageType.RAW_PCAP,) if c.acls else ())
            }
        self.policy = PolicyLabeler(list(c.acls)) if c.acls else None
        self.policy_meters = (
            PolicyMeterAggregator(agent_id=c.agent_id) if c.acls else None
        )
        self.possible_hosts = PossibleHostTable() if c.track_host_activity else None
        self.counters = {
            "batches": 0, "packets": 0, "docs_sent": 0, "logs_sent": 0,
            "packets_filtered": 0, "packets_dropped_policy": 0, "pcap_sent": 0,
        }

    # -- pipeline step ---------------------------------------------------
    def step(self, buf: np.ndarray, lengths, ts_s, ts_us) -> None:
        """One capture batch through the whole graph."""
        p = parse_packets(buf, lengths, ts_s, ts_us)
        if self.config.capture_filter is not None:
            keep = self.config.capture_filter.mask(p)
            filtered = p.valid & ~keep
            if filtered.any():
                # drop filtered rows from the batch entirely — FlowMap's
                # invalid_packets counter must keep meaning "capture
                # garbage", not operator policy
                self.counters["packets_filtered"] += int(filtered.sum())
                buf, p = _compact(buf, p, ~filtered)
        if self.policy is not None:
            acl_id, action = self.policy.match(p)
            self.policy_meters.update(p, acl_id, action, self.policy.last_forward)
            pcap_idx = np.nonzero(action == ACTION_PCAP)[0]
            if pcap_idx.size:
                frames = pcap_frames(buf, p, pcap_idx, acl_id)
                if self._send(MessageType.RAW_PCAP, frames):
                    self.counters["pcap_sent"] += len(frames)
            dropped = action == ACTION_DROP
            if dropped.any():
                self.counters["packets_dropped_policy"] += int(dropped.sum())
                buf, p = _compact(buf, p, ~dropped)
        self.counters["batches"] += 1
        self.counters["packets"] += int(p.valid.sum())
        self.flow_map.inject(p)

        # L7: protocol logs + RED metrics from the same packets
        log_batch, app_batch = self.l7.process(buf, p)
        if log_batch.size:
            self._send(MessageType.PROTOCOLLOG, encode_rows(log_batch))
            self.counters["logs_sent"] += log_batch.size
        if app_batch.valid.any():
            for db in self.l7_metrics.ingest(app_batch):
                self._send_docs(db, self.l7_metrics.flags)

        # L4 tick at the batch's max second: emissions feed metrics + logs
        now = int(np.max(np.asarray(ts_s))) if len(np.asarray(ts_s)) else 0
        if self.policy_meters is not None:
            usage = self.policy_meters.flush(now)
            if usage is not None:
                # traffic_policy docs are minute-granularity (NONE =
                # not PER_SECOND; since ISSUE 9 the dual pipeline has
                # no separate minute sub-pipeline to borrow flags from)
                self._send_docs(usage, DocumentFlag.NONE)
        emissions = self.flow_map.tick(now)
        if emissions.size:
            self._ingest_l4(emissions)
            for sampled in self.l4_throttle.drain():
                self._send(MessageType.TAGGEDFLOW, encode_rows(sampled))

    def _ingest_l4(self, emissions) -> None:
        """Emission rows → dual-granularity metric docs + minute flow
        logs. Chunked: a drain tick can emit more rows than one pipeline
        batch (the stash flushes whole windows at once)."""
        fb = emissions_to_flow_batch(emissions, possible=self.possible_hosts)
        bs = self.config.batch_size
        for off in range(0, fb.size, bs):
            chunk = FlowBatch(
                tags={k: v[off : off + bs] for k, v in fb.tags.items()},
                meters=fb.meters[off : off + bs],
                valid=fb.valid[off : off + bs],
            )
            for flags, db in self.metrics.ingest(chunk):
                self._send_docs(db, flags)
        for minute_batch in self.flow_aggr.ingest(emissions):
            self.l4_throttle.put(minute_batch)

    def _send_docs(self, db, flags) -> None:
        msgs = encode_docbatch(db, flags=int(flags))
        self._send(MessageType.METRICS, msgs)
        self.counters["docs_sent"] += db.size

    def _send(self, mt: MessageType, msgs: list[bytes]) -> bool:
        s = self.senders.get(mt)
        if s is not None and msgs:
            s.send(msgs)
            return True
        return False

    def apply_dynamic_config(self, cfg: dict) -> None:
        """Apply a trisolaris-pushed dynamic config overlay. Today the
        live-reloadable knobs are the ACL table ("acls": FlowAcl dicts —
        the reference's flow_acls push) and the l4 log throttle."""
        from .policy import acls_from_config

        if "acls" in cfg:
            acls = acls_from_config(cfg["acls"])
            self.policy = PolicyLabeler(list(acls)) if acls else None
            if acls and self.policy_meters is None:
                self.policy_meters = PolicyMeterAggregator(agent_id=self.config.agent_id)
            # a pushed PCAP ACL needs the RAW_PCAP lane even though the
            # static config had none (default sender set is acl-gated)
            if (
                acls
                and self._default_senders
                and MessageType.RAW_PCAP not in self.senders
            ):
                c = self.config
                self.senders[MessageType.RAW_PCAP] = UniformSender(
                    list(c.servers), MessageType.RAW_PCAP,
                    agent_id=c.agent_id, organization_id=c.organization_id,
                    compression=c.compression,
                )
            self.counters["config_reloads"] = self.counters.get("config_reloads", 0) + 1
        if "l4_log_throttle" in cfg:
            self.l4_throttle.throttle = int(cfg["l4_log_throttle"])

    def ship_log(self, line: str, severity: int = 6) -> None:
        """Forward one agent log line to the server's AGENT_LOG lane
        (droplet-message type 18 → application_log table); RFC 3164
        <PRI> prefix carries the severity."""
        self._send(MessageType.AGENT_LOG, [f"<{8 + severity}>{line}".encode()])

    # -- drivers ---------------------------------------------------------
    def run_live(self, interface: str = "lo", *, duration_s: float | None = None,
                 snap: int = 192, ring: bool = False) -> dict:
        """Live AF_PACKET capture → the same graph as replay (the
        dispatcher seat when the container grants CAP_NET_RAW).
        `ring=True` uses the TPACKET_V3 mmap block ring (the
        recv_engine/af_packet fast path) instead of per-packet recv."""
        from .capture import AfPacketCapture, AfPacketRingCapture

        cls = AfPacketRingCapture if ring else AfPacketCapture
        cap = cls(
            interface, snap=snap, batch_size=self.config.batch_size
        )
        try:
            for buf, lengths, ts_s, ts_us in cap.batches(duration_s=duration_s):
                self.step(buf, lengths, ts_s, ts_us)
        finally:
            cap.close()
        # drain like run_pcap: open flows + buffered windows must flush
        # when a bounded capture ends, or the session tail is lost
        stats = self.drain()
        return dict(stats, capture=dict(cap.counters))

    def run_pcap(self, path, *, batch_size: int | None = None) -> dict:
        """Replay a capture file through the graph (the dispatcher seat —
        this container has no live AF_PACKET/XDP; replay is the source)."""
        from .pcap import pcap_batches

        for buf, lengths, ts_s, ts_us in pcap_batches(
            path, batch_size=batch_size or self.config.batch_size
        ):
            self.step(buf, lengths, ts_s, ts_us)
        return self.drain()

    def drain(self) -> dict:
        """Flush everything (shutdown): final tick far in the future,
        pipeline drains, sender close left to the caller."""
        emissions = self.flow_map.tick(1 << 31)
        if emissions.size:
            self._ingest_l4(emissions)
        if self.policy_meters is not None:
            usage = self.policy_meters.flush(1 << 31)
            if usage is not None:
                # minute-granularity, same flag stance as the tick path
                self._send_docs(usage, DocumentFlag.NONE)
        for flags, db in self.metrics.drain():
            self._send_docs(db, flags)
        for db in self.l7_metrics.drain():
            self._send_docs(db, self.l7_metrics.flags)
        for batch in self.flow_aggr.drain():
            self.l4_throttle.put(batch)
        for sampled in self.l4_throttle.drain():
            self._send(MessageType.TAGGEDFLOW, encode_rows(sampled))
        return dict(self.counters)

    def close(self) -> None:
        for s in self.senders.values():
            if hasattr(s, "close"):
                s.close()
