"""Classic pcap file reader/writer — the replay driver's capture source.

The reference replays `.pcap` fixtures through its parsers for golden
tests (agent/resources/test/**.pcap, SURVEY §4); this module gives the
TPU build the same replay path: read a capture file into the [N, SNAP]
u8 batch the vectorized parser consumes. Writer included so tests can
author fixtures without external tooling. Supports the classic format
(magic 0xA1B2C3D4, µs resolution; byte-swapped and ns variants read).
"""

from __future__ import annotations

import struct
from pathlib import Path

import numpy as np

MAGIC_US = 0xA1B2C3D4
MAGIC_NS = 0xA1B23C4D
# "modified" pcap (Alexey Kuznetzov's patched libpcap): classic layout
# with 8 extra per-record bytes (ifindex u32, protocol u16, pkt_type u8,
# pad u8) after the standard 16-byte record header
MAGIC_MODIFIED = 0xA1B2CD34
LINKTYPE_ETHERNET = 1


def write_pcap(path: str | Path, packets: list[tuple[int, int, bytes]]) -> None:
    """packets: (ts_sec, ts_usec, frame_bytes)."""
    with open(path, "wb") as f:
        f.write(struct.pack("<IHHiIII", MAGIC_US, 2, 4, 0, 0, 65535, LINKTYPE_ETHERNET))
        for sec, usec, data in packets:
            f.write(struct.pack("<IIII", sec, usec, len(data), len(data)))
            f.write(data)


def read_pcap(path: str | Path) -> list[tuple[int, int, bytes]]:
    data = Path(path).read_bytes()
    if len(data) < 24:
        raise ValueError("truncated pcap: no global header")
    (magic,) = struct.unpack_from("<I", data, 0)
    if magic in (MAGIC_US, MAGIC_NS, MAGIC_MODIFIED):
        endian = "<"
    elif magic in (struct.unpack(">I", struct.pack("<I", m))[0]
                   for m in (MAGIC_US, MAGIC_NS, MAGIC_MODIFIED)):
        endian = ">"
        (magic,) = struct.unpack_from(">I", data, 0)
    else:
        raise ValueError(f"bad pcap magic {magic:#x}")
    ns = magic == MAGIC_NS
    extra = 8 if magic == MAGIC_MODIFIED else 0
    out = []
    off = 24
    while off + 16 + extra <= len(data):
        sec, frac, incl, _orig = struct.unpack_from(f"{endian}IIII", data, off)
        off += 16 + extra
        if off + incl > len(data):
            break  # truncated trailing record
        out.append((sec, frac // 1000 if ns else frac, data[off : off + incl]))
        off += incl
    return out


def pcap_batches(path: str | Path, batch_size: int = 4096, snap: int = 192):
    """Yield (buf, lengths, ts_s, ts_us) parse batches from a capture."""
    from .packet import to_batch

    packets = read_pcap(path)
    for i in range(0, len(packets), batch_size):
        chunk = packets[i : i + batch_size]
        yield to_batch(
            [p[2] for p in chunk],
            [p[0] for p in chunk],
            [p[1] for p in chunk],
            snap=snap,
        )
