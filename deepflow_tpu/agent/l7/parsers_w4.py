"""L7 parsers, wave 4: SofaRPC (Bolt), bRPC, Tars, SOME/IP, Pulsar,
OpenWire, ZMTP, Oracle TNS, ICMP Ping.

Behavioral peers of protocol_logs/rpc/{sofa_rpc.rs, brpc.rs, tars.rs,
some_ip.rs}, mq/{pulsar.rs, openwire.rs, zmtp.rs}, sql/oracle.rs and
ping.rs; wire layouts from the public protocol specs (Bolt, brpc RPC
spec, Tars JCE, AUTOSAR SOME/IP, Pulsar BaseCommand, ActiveMQ OpenWire,
ZMTP/3.x, Oracle TNS, RFC 792).
"""

from __future__ import annotations

from ...datamodel.code import L7Protocol
from .parsers import (
    MSG_REQUEST,
    MSG_RESPONSE,
    STATUS_CLIENT_ERROR,
    STATUS_OK,
    STATUS_SERVER_ERROR,
    L7Message,
)

# ---------------------------------------------------------------------------
# SofaRPC / Bolt v1+v2 (rpc/sofa_rpc.rs) — header:
#   proto(1) [v2: ver1(1)] type(1) cmdcode(2) ver2(1) reqid(4) codec(1)
#   [v2: switch(1)] (req: timeout(4) | resp: status(2))
#   classlen(2) headerlen(2) contentlen(4) classname[classlen] header...

_BOLT_TYPE_RESP = 0
_BOLT_TYPE_REQ = 1
_BOLT_TYPE_ONEWAY = 2
_BOLT_CMD_HEARTBEAT = 0
_BOLT_CMD_REQ = 1
_BOLT_CMD_RESP = 2


def _bolt_header(payload: bytes):
    if len(payload) < 20:
        return None
    proto = payload[0]
    if proto == 1:
        off = 1
    elif proto == 2:
        off = 2  # ver1 byte
    else:
        return None
    typ = payload[off]
    cmd = int.from_bytes(payload[off + 1 : off + 3], "big")
    if typ not in (_BOLT_TYPE_RESP, _BOLT_TYPE_REQ, _BOLT_TYPE_ONEWAY):
        return None
    if cmd not in (_BOLT_CMD_HEARTBEAT, _BOLT_CMD_REQ, _BOLT_CMD_RESP):
        return None
    req_id = int.from_bytes(payload[off + 4 : off + 8], "big")
    p = off + 9  # past ver2, reqid, codec
    if proto == 2:
        p += 1  # switch byte
    # exact per-variant minimum: truncated tail slices would silently
    # decode as 0 through int.from_bytes, misparsing len fields
    body_off = p + (2 if typ == _BOLT_TYPE_RESP else 4) + 8
    if len(payload) < body_off:
        return None
    resp_status = 0
    if typ == _BOLT_TYPE_RESP:
        resp_status = int.from_bytes(payload[p : p + 2], "big")
        p += 2
    else:
        p += 4  # timeout
    class_len = int.from_bytes(payload[p : p + 2], "big")
    hdr_len = int.from_bytes(payload[p + 2 : p + 4], "big")
    content_len = int.from_bytes(payload[p + 4 : p + 8], "big")
    body = p + 8
    if class_len > 4096 or hdr_len > 65535 or content_len > (1 << 26):
        return None
    return typ, cmd, req_id, resp_status, class_len, hdr_len, body


def check_sofarpc(payload: bytes, port: int = 0) -> bool:
    h = _bolt_header(payload)
    if h is None:
        return False
    typ, cmd, _rid, _st, class_len, hdr_len, body = h
    # codec byte is always set on real Bolt frames (1=hessian, 11/12 =
    # protobuf/json); 0 rejects the all-zero lookalikes
    codec_off = (1 if payload[0] == 1 else 2) + 8
    if payload[codec_off] == 0:
        return False
    if cmd == _BOLT_CMD_HEARTBEAT:
        # heartbeats carry no class/header/content at all
        return class_len == 0 and hdr_len == 0 and len(payload) <= body
    # requests carry a java class name; cheap sanity on its bytes
    name = payload[body : body + class_len]
    return class_len == 0 or all(0x20 < b < 0x7F for b in name)


def _bolt_kv_headers(buf: bytes) -> dict:
    """Bolt string headers: repeated [len(4) key][len(4) value]."""
    out, p = {}, 0
    while p + 8 <= len(buf):
        klen = int.from_bytes(buf[p : p + 4], "big")
        if p + 4 + klen + 4 > len(buf):
            break
        key = buf[p + 4 : p + 4 + klen].decode(errors="replace")
        p += 4 + klen
        vlen = int.from_bytes(buf[p : p + 4], "big")
        if p + 4 + vlen > len(buf):
            break
        val = buf[p + 4 : p + 4 + vlen].decode(errors="replace")
        p += 4 + vlen
        out[key] = val
    return out


def parse_sofarpc(payload: bytes) -> L7Message | None:
    h = _bolt_header(payload)
    if h is None:
        return None
    typ, cmd, req_id, resp_status, class_len, hdr_len, body = h
    if typ in (_BOLT_TYPE_REQ, _BOLT_TYPE_ONEWAY):
        hdrs = _bolt_kv_headers(payload[body + class_len : body + class_len + hdr_len])
        service = hdrs.get("sofa_head_target_service") or hdrs.get(
            "service", ""
        )
        method = hdrs.get("sofa_head_method_name", "")
        return L7Message(
            protocol=L7Protocol.SOFARPC,
            msg_type=MSG_REQUEST,
            request_type="heartbeat" if cmd == _BOLT_CMD_HEARTBEAT else "call",
            request_resource=service,
            endpoint=f"{service}/{method}" if method else service,
            request_id=req_id,
        )
    # response: status 0 ok; 8 = client-side error band (bolt spec)
    status = STATUS_OK
    if resp_status != 0:
        status = STATUS_CLIENT_ERROR if resp_status == 8 else STATUS_SERVER_ERROR
    return L7Message(
        protocol=L7Protocol.SOFARPC,
        msg_type=MSG_RESPONSE,
        request_id=req_id,
        status=status,
        status_code=resp_status,
    )


# ---------------------------------------------------------------------------
# bRPC "standard" protocol (rpc/brpc.rs) — "PRPC" + body_size(4) +
# meta_size(4) + RpcMeta protobuf (request{service,method}, response
# {error_code}, correlation_id).


def _pb_fields(buf: bytes):
    """Minimal protobuf walk → yields (field_no, wire_type, value)."""
    p = 0
    while p < len(buf):
        tag = 0
        shift = 0
        while p < len(buf):
            b = buf[p]
            tag |= (b & 0x7F) << shift
            p += 1
            shift += 7
            if not b & 0x80:
                break
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            v = 0
            shift = 0
            while p < len(buf):
                b = buf[p]
                v |= (b & 0x7F) << shift
                p += 1
                shift += 7
                if not b & 0x80:
                    break
            yield field, wt, v
        elif wt == 2:  # length-delimited
            ln = 0
            shift = 0
            while p < len(buf):
                b = buf[p]
                ln |= (b & 0x7F) << shift
                p += 1
                shift += 7
                if not b & 0x80:
                    break
            yield field, wt, buf[p : p + ln]
            p += ln
        elif wt == 1:
            yield field, wt, buf[p : p + 8]
            p += 8
        elif wt == 5:
            yield field, wt, buf[p : p + 4]
            p += 4
        else:
            return


def check_brpc(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 12 or payload[:4] != b"PRPC":
        return False
    meta_size = int.from_bytes(payload[8:12], "big")
    return meta_size <= int.from_bytes(payload[4:8], "big")


def parse_brpc(payload: bytes) -> L7Message | None:
    if len(payload) < 12 or payload[:4] != b"PRPC":
        return None
    meta_size = int.from_bytes(payload[8:12], "big")
    meta = payload[12 : 12 + meta_size]
    service = method = ""
    corr_id = None
    err_code = 0
    is_resp = False
    for field, wt, val in _pb_fields(meta):
        if field == 1 and wt == 2:  # RpcRequestMeta
            for f2, w2, v2 in _pb_fields(val):
                if f2 == 1 and w2 == 2:
                    service = v2.decode(errors="replace")
                elif f2 == 2 and w2 == 2:
                    method = v2.decode(errors="replace")
        elif field == 2 and wt == 2:  # RpcResponseMeta
            is_resp = True
            for f2, w2, v2 in _pb_fields(val):
                if f2 == 1 and w2 == 0:
                    err_code = v2
        elif field == 4 and wt == 0:  # correlation_id
            corr_id = val
    if is_resp:
        return L7Message(
            protocol=L7Protocol.BRPC,
            msg_type=MSG_RESPONSE,
            request_id=corr_id,
            status=STATUS_SERVER_ERROR if err_code else STATUS_OK,
            status_code=err_code,
        )
    return L7Message(
        protocol=L7Protocol.BRPC,
        msg_type=MSG_REQUEST,
        request_type=method,
        request_resource=service,
        endpoint=f"{service}/{method}" if service else method,
        request_id=corr_id,
    )


# ---------------------------------------------------------------------------
# Tars (rpc/tars.rs) — packet: len(4) + JCE-encoded RequestPacket:
#   tag1 iVersion(short) tag2 cPacketType(byte) tag3 iMessageType(int)
#   tag4 iRequestId(int) tag5 sServantName(str) tag6 sFuncName(str)
# response: tag5 iRet(int) on version>=3 … we read the low tags only.

_JCE_INT8, _JCE_INT16, _JCE_INT32, _JCE_INT64 = 0, 1, 2, 3
_JCE_STRING1, _JCE_STRING4 = 6, 7
_JCE_ZERO = 12


def _jce_fields(buf: bytes, limit: int = 8):
    """Yield (tag, value) for the leading flat JCE fields. Tolerates
    truncation (TCP segmentation can cut a stream on any byte): a field
    whose bytes are missing simply ends the walk."""
    p = 0
    n = len(buf)
    while p < n and limit > 0:
        head = buf[p]
        tag, typ = head >> 4, head & 0x0F
        p += 1
        if tag == 0xF:
            if p >= n:
                return
            tag = buf[p]
            p += 1
        if typ == _JCE_INT8:
            if p >= n:
                return
            yield tag, buf[p]
            p += 1
        elif typ == _JCE_INT16:
            yield tag, int.from_bytes(buf[p : p + 2], "big")
            p += 2
        elif typ == _JCE_INT32:
            yield tag, int.from_bytes(buf[p : p + 4], "big")
            p += 4
        elif typ == _JCE_INT64:
            yield tag, int.from_bytes(buf[p : p + 8], "big")
            p += 8
        elif typ == _JCE_STRING1:
            if p >= n:
                return
            ln = buf[p]
            yield tag, buf[p + 1 : p + 1 + ln]
            p += 1 + ln
        elif typ == _JCE_STRING4:
            ln = int.from_bytes(buf[p : p + 4], "big")
            yield tag, buf[p + 4 : p + 4 + ln]
            p += 4 + ln
        elif typ == _JCE_ZERO:
            yield tag, 0
        else:
            return
        limit -= 1


_TARS_VERSIONS = (1, 2, 3)


def check_tars(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 8:
        return False
    pkt_len = int.from_bytes(payload[:4], "big")
    if pkt_len < 8 or pkt_len > (1 << 24):
        return False
    fields = dict(_jce_fields(payload[4:], limit=2))
    return fields.get(1) in _TARS_VERSIONS and fields.get(2, 0) in (0, 1)


def parse_tars(payload: bytes) -> L7Message | None:
    if len(payload) < 8:
        return None
    fields = dict(_jce_fields(payload[4:], limit=8))
    if fields.get(1) not in _TARS_VERSIONS:
        return None
    servant = fields.get(5, b"")
    func = fields.get(6, b"")
    if isinstance(servant, bytes) and servant:
        # RequestPacket: tag4 iRequestId, tag5 sServantName, tag6 sFuncName
        servant_s = servant.decode(errors="replace")
        func_s = func.decode(errors="replace") if isinstance(func, bytes) else ""
        return L7Message(
            protocol=L7Protocol.TARS,
            msg_type=MSG_REQUEST,
            version=str(fields.get(1)),
            request_type=func_s,
            request_resource=servant_s,
            endpoint=f"{servant_s}/{func_s}" if func_s else servant_s,
            request_id=fields.get(4),
        )
    # ResponsePacket: tag3 iRequestId, tag4 iMessageType, tag5 iRet
    ret = fields.get(5, 0) if isinstance(fields.get(5), int) else 0
    if ret >= 1 << 31:  # JCE ints are signed
        ret -= 1 << 32
    return L7Message(
        protocol=L7Protocol.TARS,
        msg_type=MSG_RESPONSE,
        version=str(fields.get(1)),
        request_id=fields.get(3),
        status=STATUS_OK if ret == 0 else STATUS_SERVER_ERROR,
        status_code=ret,
    )


# ---------------------------------------------------------------------------
# SOME/IP (rpc/some_ip.rs) — 16-byte header:
#   service_id(2) method_id(2) length(4) client_id(2) session_id(2)
#   proto_ver(1)=1 iface_ver(1) msg_type(1) return_code(1)

_SOMEIP_TYPES = {
    0x00: "REQUEST",
    0x01: "REQUEST_NO_RETURN",
    0x02: "NOTIFICATION",
    0x80: "RESPONSE",
    0x81: "ERROR",
    0x20: "TP_REQUEST",
    0x21: "TP_REQUEST_NO_RETURN",
    0x23: "TP_NOTIFICATION",
    0xA0: "TP_RESPONSE",
    0xA1: "TP_ERROR",
}


def check_someip(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 16:
        return False
    length = int.from_bytes(payload[4:8], "big")
    proto_ver = payload[12]
    msg_type = payload[14]
    return proto_ver == 1 and msg_type in _SOMEIP_TYPES and length >= 8


def parse_someip(payload: bytes) -> L7Message | None:
    if not check_someip(payload):
        return None
    service_id = int.from_bytes(payload[0:2], "big")
    method_id = int.from_bytes(payload[2:4], "big")
    session_id = int.from_bytes(payload[10:12], "big")
    msg_type = payload[14]
    ret = payload[15]
    is_resp = bool(msg_type & 0x80)
    return L7Message(
        protocol=L7Protocol.SOME_IP,
        msg_type=MSG_RESPONSE if is_resp else MSG_REQUEST,
        request_type=_SOMEIP_TYPES[msg_type],
        request_resource=str(service_id),
        endpoint=f"{service_id}/{method_id:#06x}",
        request_id=session_id,
        status=STATUS_SERVER_ERROR if ret not in (0, 1) else STATUS_OK,
        status_code=ret,
    )


# ---------------------------------------------------------------------------
# Pulsar (mq/pulsar.rs) — frame: total_size(4) command_size(4) +
# BaseCommand protobuf {type enum = field 1 varint}.

_PULSAR_CMDS = {
    2: "CONNECT", 3: "CONNECTED", 4: "SUBSCRIBE", 5: "PRODUCER",
    6: "SEND", 7: "SEND_RECEIPT", 8: "SEND_ERROR", 9: "MESSAGE",
    10: "ACK", 11: "FLOW", 12: "UNSUBSCRIBE", 13: "SUCCESS",
    14: "ERROR", 15: "CLOSE_PRODUCER", 16: "CLOSE_CONSUMER",
    17: "PRODUCER_SUCCESS", 18: "PING", 19: "PONG",
    21: "PARTITIONED_METADATA", 22: "PARTITIONED_METADATA_RESPONSE",
    23: "LOOKUP", 24: "LOOKUP_RESPONSE",
}
# broker→client command types (pair as responses)
_PULSAR_RESP = {3, 7, 8, 9, 13, 14, 17, 19, 22, 24}


def check_pulsar(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 9:
        return False
    total = int.from_bytes(payload[:4], "big")
    cmd_size = int.from_bytes(payload[4:8], "big")
    if cmd_size + 4 > total or total > (1 << 26):
        return False
    # field 1 (type) may legally appear after other BaseCommand fields
    for field, wt, val in _pb_fields(payload[8 : 8 + cmd_size]):
        if field == 1 and wt == 0:
            return val in _PULSAR_CMDS
    return False


def parse_pulsar(payload: bytes) -> L7Message | None:
    if len(payload) < 9:
        return None
    cmd_size = int.from_bytes(payload[4:8], "big")
    cmd_type = None
    for field, wt, val in _pb_fields(payload[8 : 8 + cmd_size]):
        if field == 1 and wt == 0:
            cmd_type = val
            break
    name = _PULSAR_CMDS.get(cmd_type)
    if name is None:
        return None
    return L7Message(
        protocol=L7Protocol.PULSAR,
        msg_type=MSG_RESPONSE if cmd_type in _PULSAR_RESP else MSG_REQUEST,
        request_type=name,
        status=STATUS_SERVER_ERROR if name in ("SEND_ERROR", "ERROR") else STATUS_OK,
    )


# ---------------------------------------------------------------------------
# OpenWire / ActiveMQ (mq/openwire.rs) — frame: length(4) dtype(1)…
# WIREFORMAT_INFO (1) carries the b"ActiveMQ" magic.

_OPENWIRE_TYPES = {
    1: "WIREFORMAT_INFO", 2: "BROKER_INFO", 3: "CONNECTION_INFO",
    4: "SESSION_INFO", 5: "CONSUMER_INFO", 6: "PRODUCER_INFO",
    7: "TRANSACTION_INFO", 8: "DESTINATION_INFO", 9: "REMOVE_SUBSCRIPTION_INFO",
    10: "KEEP_ALIVE_INFO", 11: "SHUTDOWN_INFO", 12: "REMOVE_INFO",
    14: "CONTROL_COMMAND", 15: "FLUSH_COMMAND", 16: "CONNECTION_ERROR",
    21: "MESSAGE_DISPATCH", 22: "MESSAGE_ACK", 23: "ACTIVEMQ_MESSAGE",
    24: "ACTIVEMQ_BYTES_MESSAGE", 25: "ACTIVEMQ_MAP_MESSAGE",
    26: "ACTIVEMQ_OBJECT_MESSAGE", 27: "ACTIVEMQ_STREAM_MESSAGE",
    28: "ACTIVEMQ_TEXT_MESSAGE", 30: "RESPONSE", 31: "EXCEPTION_RESPONSE",
    32: "DATA_RESPONSE", 34: "INTEGER_RESPONSE",
}
_OPENWIRE_RESP = {16, 21, 30, 31, 32, 34}


def check_openwire(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 5:
        return False
    length = int.from_bytes(payload[:4], "big")
    dtype = payload[4]
    if dtype == 1:  # WireFormatInfo: magic follows the dtype byte
        return payload[5:13] == b"ActiveMQ"
    return dtype in _OPENWIRE_TYPES and 1 <= length <= (1 << 26) and port == 61616


def parse_openwire(payload: bytes) -> L7Message | None:
    if len(payload) < 5:
        return None
    dtype = payload[4]
    name = _OPENWIRE_TYPES.get(dtype)
    if name is None:
        return None
    return L7Message(
        protocol=L7Protocol.OPENWIRE,
        msg_type=MSG_RESPONSE if dtype in _OPENWIRE_RESP else MSG_REQUEST,
        request_type=name,
        status=STATUS_SERVER_ERROR if dtype in (16, 31) else STATUS_OK,
    )


# ---------------------------------------------------------------------------
# ZMTP 3.x (mq/zmtp.rs) — greeting: 0xFF pad(8) 0x7F major(1) minor(1)
# mechanism(20, NUL-padded) as-server(1) filler(31); then frames:
# flags(1: MORE|LONG|COMMAND) size(1 or 8) body.

_ZMTP_MECHANISMS = (b"NULL", b"PLAIN", b"CURVE", b"GSSAPI")


def check_zmtp(payload: bytes, port: int = 0) -> bool:
    if len(payload) >= 12 and payload[0] == 0xFF and payload[9] == 0x7F:
        if payload[10] != 3:
            return False
        mech = payload[12:32].rstrip(b"\x00") if len(payload) >= 32 else b""
        return len(payload) < 32 or mech in _ZMTP_MECHANISMS
    # command frame: flags(1) size(1 short / 8 long) name_len(1) name…
    if len(payload) >= 4 and payload[0] in (0x04, 0x06):
        if payload[0] == 0x06 and len(payload) < 11:
            return False
        name_len = payload[2] if payload[0] == 0x04 else payload[9]
        off = 3 if payload[0] == 0x04 else 10
        name = payload[off : off + name_len]
        return name in (b"READY", b"ERROR", b"SUBSCRIBE", b"CANCEL", b"PING", b"PONG", b"HELLO", b"WELCOME", b"INITIATE")
    return False


def parse_zmtp(payload: bytes) -> L7Message | None:
    # a flow greeting-classified as ZMTP later delivers arbitrary
    # (possibly truncated) frames — never raise, just skip them
    if not check_zmtp(payload):
        return None
    if payload[0] == 0xFF:  # greeting
        mech = (
            payload[12:32].rstrip(b"\x00").decode(errors="replace")
            if len(payload) >= 32
            else ""
        )
        return L7Message(
            protocol=L7Protocol.ZMTP,
            msg_type=MSG_REQUEST,
            version=f"3.{payload[11]}" if len(payload) > 11 else "3",
            request_type="greeting",
            request_resource=mech,
        )
    name_len = payload[2] if payload[0] == 0x04 else payload[9]
    off = 3 if payload[0] == 0x04 else 10
    name = payload[off : off + name_len].decode(errors="replace")
    return L7Message(
        protocol=L7Protocol.ZMTP,
        msg_type=MSG_RESPONSE
        if name in ("WELCOME", "PONG", "ERROR")
        else MSG_REQUEST,
        request_type=name,
        status=STATUS_SERVER_ERROR if name == "ERROR" else STATUS_OK,
    )


# ---------------------------------------------------------------------------
# Oracle TNS (sql/oracle.rs) — packet: length(2) checksum(2) type(1)
# flags(1) header_checksum(2). Type 1=CONNECT 2=ACCEPT 4=REFUSE 6=DATA
# 11=RESEND 12=MARKER.

_TNS_TYPES = {
    1: "CONNECT", 2: "ACCEPT", 3: "ACK", 4: "REFUSE", 5: "REDIRECT",
    6: "DATA", 7: "NULL", 9: "ABORT", 11: "RESEND", 12: "MARKER",
    13: "ATTENTION", 14: "CONTROL",
}
_TNS_RESP = {2, 4, 5, 11}


def check_oracle(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 8:
        return False
    pkt_len = int.from_bytes(payload[:2], "big")
    ptype = payload[4]
    if ptype not in _TNS_TYPES:
        return False
    if ptype == 1:  # CONNECT carries "(DESCRIPTION=" connect data
        return b"(DESCRIPTION=" in payload or b"(CONNECT_DATA=" in payload
    return pkt_len == len(payload) or port == 1521


def parse_oracle(payload: bytes) -> L7Message | None:
    if len(payload) < 8:
        return None
    ptype = payload[4]
    name = _TNS_TYPES.get(ptype)
    if name is None:
        return None
    service = ""
    if ptype == 1:
        i = payload.find(b"SERVICE_NAME=")
        if i >= 0:
            j = payload.find(b")", i)
            service = payload[i + 13 : j].decode(errors="replace")
    return L7Message(
        protocol=L7Protocol.ORACLE,
        msg_type=MSG_RESPONSE if ptype in _TNS_RESP else MSG_REQUEST,
        request_type=name,
        request_domain=service,
        status=STATUS_SERVER_ERROR if ptype in (4, 9) else STATUS_OK,
    )


# ---------------------------------------------------------------------------
# Ping (ping.rs) — ICMP echo: type(1)=8 req /0 reply, code(1)=0,
# checksum(2), id(2), seq(2). The dispatcher hands the ICMP message as
# the "payload" for IPPROTO_ICMP flows.


def _inet_checksum(buf: bytes) -> int:
    if len(buf) % 2:
        buf += b"\x00"
    s = sum(int.from_bytes(buf[i : i + 2], "big") for i in range(0, len(buf), 2))
    while s >> 16:
        s = (s & 0xFFFF) + (s >> 16)
    return ~s & 0xFFFF


def check_ping(payload: bytes, port: int = 0) -> bool:
    # only reachable from the engine's ICMP branch (never probed against
    # TCP/UDP payloads), so no checksum requirement: snap-truncated echo
    # payloads must still classify
    return len(payload) >= 8 and payload[0] in (0, 8) and payload[1] == 0


def parse_ping(payload: bytes) -> L7Message | None:
    if not check_ping(payload):
        return None
    icmp_type = payload[0]
    ident = int.from_bytes(payload[4:6], "big")
    seq = int.from_bytes(payload[6:8], "big")
    return L7Message(
        protocol=L7Protocol.PING,
        msg_type=MSG_REQUEST if icmp_type == 8 else MSG_RESPONSE,
        request_type="echo",
        # one logical "request" per (id, seq) pair — rpc-style pairing
        request_id=(ident << 16) | seq,
    )
