"""L7 protocol inference, parsing, and session pairing — the
protocol_logs seat (agent/src/flow_generator/protocol_logs/).
"""

from .parsers import L7Message, infer_protocol, parse_payload
from .engine import L7Engine

__all__ = ["L7Message", "infer_protocol", "parse_payload", "L7Engine"]
