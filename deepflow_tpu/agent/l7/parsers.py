"""L7 protocol parsers: HTTP/1.x, DNS, Redis (RESP), MySQL.

The reference implements 20+ parsers behind `L7ProtocolParserInterface`
(protocol_logs/mod.rs): each exposes a cheap `check_payload` probe used
for per-flow protocol inference, and a full parse producing request/
response records with RED fields. Same structure here, host-side —
byte-string protocol parsing is irreducibly sequential per message, so
it stays on CPU feeding the device pipelines (exactly where the
reference runs it). SQL text is obfuscated before leaving the parser
(sql_obfuscate.rs stance: literals never reach storage).

Parsers cited: http.rs, dns.rs, redis.rs, mysql.rs under
/root/reference/agent/src/flow_generator/protocol_logs/.
"""

from __future__ import annotations

import dataclasses
import re

from ...datamodel.code import L7Protocol

MSG_REQUEST = 0
MSG_RESPONSE = 1

# L7ResponseStatus (protocol_logs/pb_adapter.rs semantics, condensed)
STATUS_OK = 1
STATUS_CLIENT_ERROR = 3
STATUS_SERVER_ERROR = 4


@dataclasses.dataclass
class L7Message:
    protocol: int
    msg_type: int  # MSG_REQUEST / MSG_RESPONSE
    version: str = ""
    request_type: str = ""  # method / command / qtype
    request_domain: str = ""  # host / db / query name
    request_resource: str = ""  # path / statement / key
    endpoint: str = ""  # normalized resource
    # pairing id (DNS txid…). None = protocol has no ids (FIFO pairing);
    # 0 is a VALID id — DNS txids may legitimately be zero
    request_id: int | None = None
    status: int = STATUS_OK
    status_code: int = 0
    # distributed-tracing context carried in protocol headers
    # (traceparent / B3 / sw8 — http.rs ON_HEADER trace extraction);
    # lets packet-observed spans join instrumented traces
    trace_id: str = ""
    span_id: str = ""


# ---------------------------------------------------------------------------
# HTTP/1.x (http.rs)

_HTTP_METHODS = (
    b"GET ", b"POST ", b"PUT ", b"DELETE ", b"HEAD ", b"OPTIONS ",
    b"PATCH ", b"TRACE ", b"CONNECT ",
)
_N_PATH_SEGMENTS = 2  # endpoint = first two path segments (http.rs endpoint trim)


def check_http(payload: bytes) -> bool:
    return payload.startswith(_HTTP_METHODS) or payload.startswith(b"HTTP/1.")


def trace_context_from_header(name: str, value: str) -> tuple[str, str]:
    """One trace header → (trace_id, span_id); empty strings when the
    header carries no usable context. Supported generations mirror
    http.rs: W3C `traceparent`, Zipkin B3 (`x-b3-traceid` /
    `x-b3-spanid`), SkyWalking `sw8` (base64 segments)."""
    name = name.lower()
    if name == "traceparent":
        parts = value.split("-")
        if (
            len(parts) >= 3
            and len(parts[1]) == 32
            and len(parts[2]) == 16
            # W3C-invalid all-zero trace and parent ids
            and set(parts[1]) != {"0"}
            and set(parts[2]) != {"0"}
            and all(c in "0123456789abcdef" for c in parts[1] + parts[2])
        ):
            return parts[1], parts[2]
    elif name == "x-b3-traceid":
        v = value.strip().lower()
        if len(v) in (16, 32) and all(c in "0123456789abcdef" for c in v):
            return v, ""
    elif name == "x-b3-spanid":
        v = value.strip().lower()
        if len(v) == 16 and all(c in "0123456789abcdef" for c in v):
            return "", v
    elif name == "sw8":
        # 1-<b64(trace id)>-<b64(segment id)>-<span idx>-…
        import base64

        parts = value.split("-")
        if len(parts) >= 4:
            try:
                tid = base64.b64decode(parts[1] + "=" * (-len(parts[1]) % 4)).decode()
                seg = base64.b64decode(parts[2] + "=" * (-len(parts[2]) % 4)).decode()
                return tid, f"{seg}-{parts[3]}"
            except Exception:
                return "", ""
    return "", ""


def _merge_trace(trace: tuple[str, str], new: tuple[str, str]) -> tuple[str, str]:
    return (trace[0] or new[0], trace[1] or new[1])


TRACE_HEADERS = ("traceparent", "x-b3-traceid", "x-b3-spanid", "sw8")
_TRACE_HEADERS_B = tuple(h.encode() for h in TRACE_HEADERS)


def trace_from_headers(get) -> tuple[str, str]:
    """(trace_id, span_id) from a header lookup callable `get(name) ->
    value | None` — the one shared walk over every supported trace
    generation (HTTP/1 lines, HTTP/2 hpack maps, and Dubbo attachments
    all feed this)."""
    trace = ("", "")
    for name in TRACE_HEADERS:
        v = get(name)
        if v:
            trace = _merge_trace(trace, trace_context_from_header(name, v))
    return trace


def parse_http(payload: bytes) -> L7Message | None:
    try:
        head, _, _ = payload.partition(b"\r\n\r\n")
        lines = head.split(b"\r\n")
        first = lines[0]
        if first.startswith(b"HTTP/1."):
            parts = first.split(b" ", 2)
            code = int(parts[1]) if len(parts) > 1 and parts[1].isdigit() else 0
            status = (
                STATUS_CLIENT_ERROR
                if 400 <= code < 500
                else STATUS_SERVER_ERROR if code >= 500 else STATUS_OK
            )
            return L7Message(
                protocol=L7Protocol.HTTP1,
                msg_type=MSG_RESPONSE,
                version=first[5:8].decode(errors="replace"),
                status=status,
                status_code=code,
            )
        for m in _HTTP_METHODS:
            if first.startswith(m):
                method = m.strip().decode()
                parts = first.split(b" ", 2)
                uri = parts[1].decode(errors="replace") if len(parts) > 1 else ""
                version = (
                    parts[2][5:8].decode(errors="replace") if len(parts) > 2 else ""
                )
                host = ""
                hdrs: dict[bytes, bytes] = {}
                for ln in lines[1:]:
                    k, _, v = ln.partition(b":")
                    key = k.strip().lower()
                    if key == b"host":
                        host = v.strip().decode(errors="replace")
                    elif key in _TRACE_HEADERS_B:
                        hdrs.setdefault(key, v.strip())
                trace = trace_from_headers(
                    lambda n: (hdrs.get(n.encode()) or b"").decode(errors="replace")
                )
                path = uri.split("?", 1)[0]
                endpoint = endpoint_from_path(path, _N_PATH_SEGMENTS)
                return L7Message(
                    protocol=L7Protocol.HTTP1,
                    msg_type=MSG_REQUEST,
                    version=version,
                    request_type=method,
                    request_domain=host,
                    request_resource=path,
                    endpoint=endpoint,
                    trace_id=trace[0],
                    span_id=trace[1],
                )
        return None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# DNS (dns.rs) — UDP payload

_QTYPES = {1: "A", 2: "NS", 5: "CNAME", 6: "SOA", 12: "PTR", 15: "MX", 16: "TXT", 28: "AAAA", 33: "SRV"}


def _dns_check_raw(payload: bytes, port: int) -> bool:
    if len(payload) < 12:
        return False
    qd = int.from_bytes(payload[4:6], "big")
    opcode_ok = (payload[2] >> 3) & 0xF in (0, 1, 2)
    return (port == 53 or 1 <= qd <= 4) and opcode_ok and qd >= 1


def _dns_tcp_strip(payload: bytes, port: int = 0) -> bytes:
    """DNS over TCP prefixes the message with a u16 length (RFC 1035
    §4.2.2; dns.rs handles both transports). Only strip when the raw
    bytes do NOT already form a plausible DNS message — a UDP query
    whose txid happens to equal len-2 must not lose its first bytes."""
    if _dns_check_raw(payload, port):
        return payload
    if len(payload) >= 14 and int.from_bytes(payload[:2], "big") == len(payload) - 2:
        return payload[2:]
    return payload


def check_dns(payload: bytes, port: int = 0) -> bool:
    return _dns_check_raw(_dns_tcp_strip(payload, port), port)


def parse_dns(payload: bytes) -> L7Message | None:
    try:
        payload = _dns_tcp_strip(payload)
        if len(payload) < 12:
            return None
        txid = int.from_bytes(payload[0:2], "big")
        flags = int.from_bytes(payload[2:4], "big")
        is_resp = bool(flags & 0x8000)
        rcode = flags & 0xF
        # parse the first question name
        labels = []
        off = 12
        while off < len(payload):
            ln = payload[off]
            if ln == 0:
                off += 1
                break
            if ln >= 0xC0 or off + 1 + ln > len(payload):  # compression in QD is invalid
                return None
            labels.append(payload[off + 1 : off + 1 + ln].decode(errors="replace"))
            off += 1 + ln
        qtype = int.from_bytes(payload[off : off + 2], "big") if off + 2 <= len(payload) else 0
        name = ".".join(labels)
        if is_resp:
            status = (
                STATUS_OK
                if rcode == 0
                else STATUS_CLIENT_ERROR if rcode == 3 else STATUS_SERVER_ERROR
            )
            return L7Message(
                protocol=L7Protocol.DNS,
                msg_type=MSG_RESPONSE,
                request_id=txid,
                request_domain=name,
                status=status,
                status_code=rcode,
            )
        return L7Message(
            protocol=L7Protocol.DNS,
            msg_type=MSG_REQUEST,
            request_id=txid,
            request_type=_QTYPES.get(qtype, str(qtype)),
            request_domain=name,
            request_resource=name,
            endpoint=name,
        )
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Redis RESP (redis.rs)


def check_redis(payload: bytes) -> bool:
    return len(payload) >= 4 and payload[:1] in (b"*", b"+", b"-", b"$", b":") and b"\r\n" in payload[:64]


def parse_redis(payload: bytes) -> L7Message | None:
    try:
        first = payload[:1]
        if first == b"*":  # request: array of bulk strings
            lines = payload.split(b"\r\n")
            # lines: *N, $len, CMD, $len, arg...
            if len(lines) < 3 or not lines[1].startswith(b"$"):
                return None
            cmd = lines[2].decode(errors="replace").upper()
            args = [
                lines[i].decode(errors="replace")
                for i in range(4, min(len(lines), 8), 2)
                if i < len(lines) and not lines[i].startswith((b"$", b"*"))
            ]
            return L7Message(
                protocol=L7Protocol.REDIS,
                msg_type=MSG_REQUEST,
                request_type=cmd,
                request_resource=" ".join([cmd] + args[:1]),
                endpoint=cmd,
            )
        if first == b"-":  # error reply
            return L7Message(
                protocol=L7Protocol.REDIS,
                msg_type=MSG_RESPONSE,
                status=STATUS_SERVER_ERROR,
                request_resource=payload[1:].split(b"\r\n")[0].decode(errors="replace"),
            )
        if first in (b"+", b"$", b":"):
            return L7Message(protocol=L7Protocol.REDIS, msg_type=MSG_RESPONSE)
        return None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# MySQL (mysql.rs) — classic protocol, header [len u24 LE][seq u8]

_COM_QUERY = 0x03
_COM_STMT_PREPARE = 0x16
_COM_STMT_EXECUTE = 0x17
_COM_NAMES = {0x01: "COM_QUIT", 0x03: "COM_QUERY", 0x0E: "COM_PING", 0x16: "COM_STMT_PREPARE", 0x17: "COM_STMT_EXECUTE"}

_SQL_STR = re.compile(r"'(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\"")
_SQL_NUM = re.compile(r"\b\d+(?:\.\d+)?\b")


def obfuscate_sql(stmt: str) -> str:
    """Literal stripping (sql/sql_obfuscate.rs): values never stored."""
    stmt = _SQL_STR.sub("?", stmt)
    return _SQL_NUM.sub("?", stmt)


def _mysql_greeting(payload: bytes) -> bool:
    """Server handshake v10: [len u24][seq=0][0x0a]["x.y.z\\0"…] — the
    signature mysql.rs uses to classify off-port flows (the server
    greets first, so this is the first payload the probe sees)."""
    if len(payload) < 7 or payload[3] != 0 or payload[4] != 0x0A:
        return False
    nul = payload.find(b"\x00", 5, 5 + 24)
    if nul < 0:
        return False
    ver = payload[5:nul]
    return bool(ver) and all(0x20 < b < 0x7F for b in ver) and ver[0:1].isdigit()


def check_mysql(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 5:
        return False
    ln = int.from_bytes(payload[0:3], "little")
    if not 0 < ln <= len(payload) - 4:
        return False
    return port == 3306 or _mysql_greeting(payload)


def parse_mysql(payload: bytes) -> L7Message | None:
    try:
        if len(payload) < 5:
            return None
        seq = payload[3]
        cmd = payload[4]
        if seq == 0 and cmd in _COM_NAMES:  # request
            stmt = ""
            if cmd in (_COM_QUERY, _COM_STMT_PREPARE):
                stmt = obfuscate_sql(payload[5:].decode(errors="replace"))
            verb = stmt.split(" ", 1)[0].upper() if stmt else _COM_NAMES[cmd]
            return L7Message(
                protocol=L7Protocol.MYSQL,
                msg_type=MSG_REQUEST,
                request_type=verb,
                request_resource=stmt,
                endpoint=verb,
            )
        if cmd == 0x00 and seq > 0:  # OK packet
            return L7Message(protocol=L7Protocol.MYSQL, msg_type=MSG_RESPONSE)
        if cmd == 0xFF and seq > 0:  # ERR packet
            code = int.from_bytes(payload[5:7], "little") if len(payload) >= 7 else 0
            status = STATUS_CLIENT_ERROR if 1000 <= code < 2000 else STATUS_SERVER_ERROR
            return L7Message(
                protocol=L7Protocol.MYSQL,
                msg_type=MSG_RESPONSE,
                status=status,
                status_code=code,
            )
        ln = int.from_bytes(payload[0:3], "little")
        if seq == 1 and 0x01 <= cmd <= 0xFA and ln <= 9:
            # resultset reply: the FIRST response packet (seq=1) is a tiny
            # lenenc column count — SELECTs answer with these, not OK
            # packets (mysql.rs resultset handling). seq==1 + length≤9
            # excludes multi-packet request continuations and row packets
            return L7Message(protocol=L7Protocol.MYSQL, msg_type=MSG_RESPONSE)
        return None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# registry + inference (the check_payload trial loop, protocol_logs/mod.rs)

_PARSERS: list[tuple[int, object, object]] = [
    (L7Protocol.HTTP1, check_http, parse_http),
    (L7Protocol.DNS, check_dns, parse_dns),
    (L7Protocol.REDIS, check_redis, parse_redis),
    (L7Protocol.MYSQL, check_mysql, parse_mysql),
]

_PORT_HINTS = {
    53: L7Protocol.DNS,
    3306: L7Protocol.MYSQL,
    6379: L7Protocol.REDIS,
    443: L7Protocol.TLS,
    5432: L7Protocol.POSTGRESQL,
    9092: L7Protocol.KAFKA,
    27017: L7Protocol.MONGODB,
    20880: L7Protocol.DUBBO,
    1883: L7Protocol.MQTT,
    11211: L7Protocol.MEMCACHED,
    4222: L7Protocol.NATS,
    5672: L7Protocol.AMQP,
    6650: L7Protocol.PULSAR,
    61616: L7Protocol.OPENWIRE,
    1521: L7Protocol.ORACLE,
    12200: L7Protocol.SOFARPC,
    30490: L7Protocol.SOME_IP,
    30509: L7Protocol.SOME_IP,
}


def register_parser(protocol: int, check, parse) -> None:
    """Extension seat (the reference's L7ProtocolParserInterface registry,
    protocol_logs/mod.rs impl_protocol_parser!)."""
    for i, (p, _c, _p) in enumerate(_PARSERS):
        if p == protocol:
            _PARSERS[i] = (protocol, check, parse)
            return
    _PARSERS.append((protocol, check, parse))


def infer_protocol(payload: bytes, server_port: int = 0) -> int:
    """Try each parser's cheap probe; port hints break ties first."""
    hint = _PORT_HINTS.get(server_port)
    ordered = sorted(_PARSERS, key=lambda p: 0 if p[0] == hint else 1)
    for proto, check, _ in ordered:
        try:
            if check.__code__.co_argcount > 1:  # port-aware probes
                if check(payload, server_port):
                    return proto
            elif check(payload):
                return proto
        except Exception:
            continue
    return L7Protocol.UNKNOWN


def endpoint_from_path(path: str, n_segments: int = 2) -> str:
    """Endpoint = first n path segments, query stripped (the http.rs
    endpoint trim; shared by HTTP/1 and HTTP/2)."""
    bare = path.split("?", 1)[0]
    segs = [s for s in bare.split("/") if s]
    return "/" + "/".join(segs[:n_segments])


def parse_payload(protocol: int, payload: bytes, ctx=None) -> L7Message | None:
    """Dispatch to the protocol's parser. `ctx` is per-flow parser state
    (today: the HTTP/2 connection's Hpack dynamic table) handed to
    parsers that declare a second positional argument."""
    for proto, _, parse in _PARSERS:
        if proto == protocol:
            if ctx is not None and parse.__code__.co_argcount > 1:
                return parse(payload, ctx)
            return parse(payload)
    return None


def _register_wave2() -> None:
    """Wave-2 parsers live in sibling modules; importing here keeps the
    single registry while avoiding a cyclic import at module top."""
    from . import parsers_ext as ext
    from .http2 import check_http2, parse_http2

    register_parser(L7Protocol.HTTP2, check_http2, parse_http2)
    register_parser(L7Protocol.TLS, ext.check_tls, ext.parse_tls)
    register_parser(L7Protocol.POSTGRESQL, ext.check_postgresql, ext.parse_postgresql)
    register_parser(L7Protocol.MONGODB, ext.check_mongodb, ext.parse_mongodb)
    register_parser(L7Protocol.DUBBO, ext.check_dubbo, ext.parse_dubbo)
    from . import parsers_mq as mq
    from . import parsers_rpc as rpc

    register_parser(L7Protocol.FASTCGI, rpc.check_fastcgi, rpc.parse_fastcgi)
    register_parser(L7Protocol.ROCKETMQ, rpc.check_rocketmq, rpc.parse_rocketmq)
    register_parser(L7Protocol.MQTT, mq.check_mqtt, mq.parse_mqtt)
    register_parser(L7Protocol.MEMCACHED, mq.check_memcached, mq.parse_memcached)
    register_parser(L7Protocol.NATS, mq.check_nats, mq.parse_nats)
    register_parser(L7Protocol.AMQP, mq.check_amqp, mq.parse_amqp)
    # kafka last: its request heuristic is the loosest (mq/kafka.rs also
    # orders bespoke-magic protocols before it)
    register_parser(L7Protocol.KAFKA, ext.check_kafka, ext.parse_kafka)


def _register_wave4() -> None:
    """Wave 4: the remaining reference parsers (rpc/mq/sql/ping.rs).
    All have strict magics, so they slot in ahead of kafka's loose
    heuristic; ping goes last (its only guard is the ICMP checksum)."""
    from . import parsers_w4 as w4

    kafka = next(p for p in _PARSERS if p[0] == L7Protocol.KAFKA)
    _PARSERS.remove(kafka)
    register_parser(L7Protocol.SOFARPC, w4.check_sofarpc, w4.parse_sofarpc)
    register_parser(L7Protocol.BRPC, w4.check_brpc, w4.parse_brpc)
    register_parser(L7Protocol.TARS, w4.check_tars, w4.parse_tars)
    register_parser(L7Protocol.SOME_IP, w4.check_someip, w4.parse_someip)
    register_parser(L7Protocol.PULSAR, w4.check_pulsar, w4.parse_pulsar)
    register_parser(L7Protocol.OPENWIRE, w4.check_openwire, w4.parse_openwire)
    register_parser(L7Protocol.ZMTP, w4.check_zmtp, w4.parse_zmtp)
    register_parser(L7Protocol.ORACLE, w4.check_oracle, w4.parse_oracle)
    _PARSERS.append(kafka)
    # PING parses only ICMP flows; the engine dispatches those directly
    # (engine._one_packet), so its probe never fires on TCP/UDP payloads
    register_parser(L7Protocol.PING, lambda p, port=0: False, w4.parse_ping)


_register_wave2()
_register_wave4()

# GRPC rides the HTTP2 parser (content-type dispatch); parse_payload on
# GRPC must resolve too
from .http2 import parse_http2 as _p2  # noqa: E402

register_parser(L7Protocol.GRPC, lambda p, port=0: False, _p2)
