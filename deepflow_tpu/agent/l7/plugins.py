"""Custom L7 protocol plugins — the Wasm / shared-object plugin seat.

The reference loads operator-supplied protocol parsers as Wasm modules
or shared objects (agent/src/plugin/, ~4.9k LoC) exposing the same
check/parse interface as built-ins. The Python-native equivalent: a
plugin directory of modules each declaring

    PROTOCOL  = <int id>        # a datamodel.code.L7Protocol value or
                                # a custom id ≥ 200
    def check_payload(payload: bytes, port: int = 0) -> bool
    def parse_payload(payload: bytes) -> parsers.L7Message | None

`load_plugins(dir)` imports every module and registers it into the
shared parser registry (parsers.register_parser — the same seat the
wave-2 parsers use), so plugin protocols flow through inference, the
L7 engine, flow logs, and RED metrics with zero further wiring.
"""

from __future__ import annotations

import importlib.util
import sys
from pathlib import Path

from .parsers import register_parser

# operator protocol ids live above every built-in (l7_protocol.rs
# reserves the custom range the same way)
CUSTOM_PROTOCOL_BASE = 200


def load_plugins(plugin_dir: str | Path) -> list[tuple[int, str]]:
    """Import and register every plugin; returns [(protocol_id, name)].

    A broken plugin is skipped (one bad operator module must not take
    down the agent), mirroring the reference's plugin-load error stance.
    """
    loaded = []
    d = Path(plugin_dir)
    if not d.is_dir():
        return loaded
    for path in sorted(d.glob("*.py")):
        name = f"deepflow_l7_plugin_{path.stem}"
        try:
            spec = importlib.util.spec_from_file_location(name, path)
            mod = importlib.util.module_from_spec(spec)
            sys.modules[name] = mod
            spec.loader.exec_module(mod)
            proto = int(mod.PROTOCOL)
            check = mod.check_payload
            parse = mod.parse_payload
        except Exception:
            sys.modules.pop(name, None)
            continue
        register_parser(proto, check, parse)
        loaded.append((proto, path.stem))
    return loaded
