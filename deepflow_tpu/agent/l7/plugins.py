"""Custom L7 protocol plugins — the Wasm / shared-object plugin seat.

The reference loads operator-supplied protocol parsers as Wasm modules
or shared objects (agent/src/plugin/, ~4.9k LoC) exposing the same
check/parse interface as built-ins. The Python-native equivalent: a
plugin directory of modules each declaring

    PROTOCOL  = <int id>        # a datamodel.code.L7Protocol value or
                                # a custom id ≥ 200
    def check_payload(payload: bytes, port: int = 0) -> bool
    def parse_payload(payload: bytes) -> parsers.L7Message | None

`load_plugins(dir)` imports every module and registers it into the
shared parser registry (parsers.register_parser — the same seat the
wave-2 parsers use), so plugin protocols flow through inference, the
L7 engine, flow logs, and RED metrics with zero further wiring.
"""

from __future__ import annotations

import ctypes
import importlib.util
import sys
from pathlib import Path

from .parsers import register_parser

# operator protocol ids live above every built-in (l7_protocol.rs
# reserves the custom range the same way)
CUSTOM_PROTOCOL_BASE = 200


def load_plugins(plugin_dir: str | Path) -> list[tuple[int, str]]:
    """Import and register every plugin; returns [(protocol_id, name)].

    Python modules (*.py) and native shared objects (*.so, the C ABI
    below) register through the same seat. A broken plugin is skipped
    (one bad operator module must not take down the agent), mirroring
    the reference's plugin-load error stance.
    """
    loaded = []
    d = Path(plugin_dir)
    if not d.is_dir():
        return loaded
    for path in sorted(d.glob("*.py")):
        name = f"deepflow_l7_plugin_{path.stem}"
        try:
            spec = importlib.util.spec_from_file_location(name, path)
            mod = importlib.util.module_from_spec(spec)
            sys.modules[name] = mod
            spec.loader.exec_module(mod)
            proto = int(mod.PROTOCOL)
            check = mod.check_payload
            parse = mod.parse_payload
        except Exception:
            sys.modules.pop(name, None)
            continue
        if proto < CUSTOM_PROTOCOL_BASE:
            continue  # a plugin must never shadow a built-in parser
        register_parser(proto, check, parse)
        loaded.append((proto, path.stem))
    for path in sorted(d.glob("*.so")):
        try:
            proto, check, parse = _load_so_plugin(path)
        except Exception:
            continue
        if proto < CUSTOM_PROTOCOL_BASE:
            continue
        register_parser(proto, check, parse)
        loaded.append((proto, path.stem))
    return loaded


# ---------------------------------------------------------------------------
# native shared-object plugin ABI (the reference's plugin/shared_obj
# seat, agent/src/plugin/shared_obj/: operators compile a C parser once
# and every agent loads it). Contract — three exported symbols:
#
#   int df_protocol(void);
#       // protocol id (>= 200 for custom protocols)
#   int df_check(const unsigned char *payload, int len, int port);
#       // 1 when the payload is this protocol
#   int df_parse(const unsigned char *payload, int len,
#                struct df_l7_info *out);
#       // 1 on success, filling `out`:
#   struct df_l7_info {
#       int  msg_type;         // 0 request / 1 response / 2 session
#       int  status;           // 1 ok / 3 client err / 4 server err
#       int  status_code;
#       unsigned int request_id;
#       char request_type[64];     // NUL-terminated
#       char request_resource[256];
#       char request_domain[256];
#       char endpoint[256];
#   };


class _DfL7Info(ctypes.Structure):
    _fields_ = [
        ("msg_type", ctypes.c_int),
        ("status", ctypes.c_int),
        ("status_code", ctypes.c_int),
        ("request_id", ctypes.c_uint),
        ("request_type", ctypes.c_char * 64),
        ("request_resource", ctypes.c_char * 256),
        ("request_domain", ctypes.c_char * 256),
        ("endpoint", ctypes.c_char * 256),
    ]


def _load_so_plugin(path: Path):
    from .parsers import MSG_REQUEST, MSG_RESPONSE, L7Message

    lib = ctypes.CDLL(str(path))
    lib.df_protocol.restype = ctypes.c_int
    lib.df_check.restype = ctypes.c_int
    lib.df_check.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.df_parse.restype = ctypes.c_int
    lib.df_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(_DfL7Info)
    ]
    proto = int(lib.df_protocol())

    def check(payload: bytes, port: int = 0) -> bool:
        return bool(lib.df_check(payload, len(payload), int(port)))

    def parse(payload: bytes):
        info = _DfL7Info()
        if not lib.df_parse(payload, len(payload), ctypes.byref(info)):
            return None
        # 2 (session) pairs like a request that already carries its
        # response status — the engine's FIFO pairing closes it
        mt = MSG_RESPONSE if int(info.msg_type) == 1 else MSG_REQUEST
        return L7Message(
            protocol=proto,
            msg_type=mt,
            status=int(info.status) or 1,
            status_code=int(info.status_code),
            request_id=int(info.request_id),
            request_type=info.request_type.decode(errors="replace"),
            request_resource=info.request_resource.decode(errors="replace"),
            request_domain=info.request_domain.decode(errors="replace"),
            endpoint=info.endpoint.decode(errors="replace"),
        )

    return proto, check, parse
