"""L7 parsers, wave 2: TLS, Kafka, PostgreSQL, MongoDB, Dubbo.

Behavioral peers of the reference parsers (protocol_logs/{tls.rs,
mq/kafka.rs, sql/postgresql.rs, sql/mongo.rs, rpc/dubbo.rs}); all wire
layouts implemented from the public protocol specs. Each exposes the
same (check, parse) pair as parsers.py and registers into its registry.
"""

from __future__ import annotations

import struct

from ...datamodel.code import L7Protocol
from .parsers import (
    MSG_REQUEST,
    MSG_RESPONSE,
    STATUS_CLIENT_ERROR,
    STATUS_OK,
    STATUS_SERVER_ERROR,
    L7Message,
    obfuscate_sql,
)

# ---------------------------------------------------------------------------
# TLS (tls.rs) — record layer + ClientHello SNI / ServerHello version

_TLS_HANDSHAKE = 22
_CLIENT_HELLO = 1
_SERVER_HELLO = 2
_TLS_VERSIONS = {0x0301: "1.0", 0x0302: "1.1", 0x0303: "1.2", 0x0304: "1.3"}


def check_tls(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 6:
        return False
    typ, maj, mi = payload[0], payload[1], payload[2]
    return typ in (20, 21, 22, 23) and maj == 3 and mi <= 4 and (
        typ != _TLS_HANDSHAKE or payload[5] in (_CLIENT_HELLO, _SERVER_HELLO)
    )


def _hello_fields(body: bytes) -> tuple[int, str]:
    """(legacy_version, sni) from a ClientHello body; best effort."""
    try:
        ver = int.from_bytes(body[0:2], "big")
        off = 2 + 32  # version + random
        sid_len = body[off]
        off += 1 + sid_len
        cs_len = int.from_bytes(body[off : off + 2], "big")
        off += 2 + cs_len
        comp_len = body[off]
        off += 1 + comp_len
        if off + 2 > len(body):
            return ver, ""
        ext_len = int.from_bytes(body[off : off + 2], "big")
        off += 2
        end = min(off + ext_len, len(body))
        while off + 4 <= end:
            etype = int.from_bytes(body[off : off + 2], "big")
            elen = int.from_bytes(body[off + 2 : off + 4], "big")
            off += 4
            if etype == 0 and off + 5 <= len(body):  # server_name
                # list_len u16, type u8, name_len u16
                name_len = int.from_bytes(body[off + 3 : off + 5], "big")
                return ver, body[off + 5 : off + 5 + name_len].decode(errors="replace")
            off += elen
        return ver, ""
    except (IndexError, struct.error):
        return 0, ""


def parse_tls(payload: bytes) -> L7Message | None:
    try:
        if payload[0] != _TLS_HANDSHAKE:
            return None
        hs_type = payload[5]
        body = payload[9 : 9 + int.from_bytes(payload[6:9], "big")]
        if hs_type == _CLIENT_HELLO:
            ver, sni = _hello_fields(body)
            return L7Message(
                protocol=L7Protocol.TLS,
                msg_type=MSG_REQUEST,
                version=_TLS_VERSIONS.get(ver, ""),
                request_type="ClientHello",
                request_domain=sni,
                request_resource=sni,
                endpoint=sni,
            )
        if hs_type == _SERVER_HELLO:
            ver = int.from_bytes(body[0:2], "big") if len(body) >= 2 else 0
            return L7Message(
                protocol=L7Protocol.TLS,
                msg_type=MSG_RESPONSE,
                version=_TLS_VERSIONS.get(ver, ""),
                request_type="ServerHello",
            )
        return None
    except (IndexError, struct.error):
        return None


# ---------------------------------------------------------------------------
# Kafka (mq/kafka.rs) — [size u32][api_key u16][api_ver u16][corr u32]
#                       [client_id u16-prefixed]

# api_key -> (name, max request version): the version cap is the request/
# response discriminator — a "request" whose version exceeds its API's
# ceiling is a response whose correlation id happened to alias the field
# (kafka.rs keeps per-flow session state for the same purpose).
_KAFKA_APIS = {
    0: ("Produce", 11), 1: ("Fetch", 17), 2: ("ListOffsets", 9),
    3: ("Metadata", 13), 8: ("OffsetCommit", 9), 9: ("OffsetFetch", 9),
    10: ("FindCoordinator", 6), 11: ("JoinGroup", 9), 12: ("Heartbeat", 4),
    13: ("LeaveGroup", 5), 14: ("SyncGroup", 5), 15: ("DescribeGroups", 5),
    16: ("ListGroups", 5), 17: ("SaslHandshake", 1), 18: ("ApiVersions", 4),
    19: ("CreateTopics", 7), 20: ("DeleteTopics", 6),
    36: ("SaslAuthenticate", 2),
}
_KAFKA_MAX_API = 74


def check_kafka(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 12:
        return False
    size = int.from_bytes(payload[0:4], "big")
    api_key = int.from_bytes(payload[4:6], "big")
    api_ver = int.from_bytes(payload[6:8], "big")
    entry = _KAFKA_APIS.get(api_key)
    req_ok = size + 4 >= len(payload) and entry is not None and api_ver <= entry[1]
    return (port == 9092 and size > 0) or req_ok


def parse_kafka(payload: bytes, ctx: dict | None = None) -> L7Message | None:
    """`ctx` is the flow's parser state (kafka.rs keeps the same): a
    response frame is just [size][correlation_id][body], so matching it
    to an outstanding request's correlation id is the only reliable
    request/response discriminator."""
    try:
        if len(payload) < 8:
            return None
        # response-first: a correlation id matching an outstanding
        # request beats the loose api_key heuristic — but only for
        # packets NOT traveling in the request direction (low api
        # words alias low sequential corr ids otherwise)
        corr = int.from_bytes(payload[4:8], "big")
        if ctx is not None and corr in ctx.get("pending", {}):
            req_dir = ctx.get("req_dir")
            if req_dir is None or ctx.get("dir") != req_dir:
                ctx["pending"].pop(corr, None)
                return L7Message(
                    protocol=L7Protocol.KAFKA,
                    msg_type=MSG_RESPONSE,
                    request_id=corr,
                )
        api_key = int.from_bytes(payload[4:6], "big")
        api_ver = int.from_bytes(payload[6:8], "big")
        entry = _KAFKA_APIS.get(api_key)
        looks_req = entry is not None and api_ver <= entry[1] and len(payload) >= 12
        known_req_dir = None if ctx is None else ctx.get("req_dir")
        blocked = (
            looks_req
            and known_req_dir is not None
            and ctx.get("dir") is not None
            and ctx["dir"] != known_req_dir
        )
        if blocked:
            # a request-looking frame traveling in the RESPONSE
            # direction is usually a response whose corr words alias an
            # api header (retransmit/evicted/duplicate) — but repeated
            # contradictions mean req_dir itself was seeded wrong
            # (capture started mid-stream on an aliasing response), so
            # two strikes flip it and the frame registers as a request
            ctx["contra"] = ctx.get("contra", 0) + 1
            if ctx["contra"] >= 2:
                ctx["req_dir"] = ctx["dir"]
                ctx["contra"] = 0
                ctx.get("pending", {}).clear()
                blocked = False
        if looks_req and not blocked:
            corr = int.from_bytes(payload[8:12], "big")
            if ctx is not None:
                ctx["contra"] = 0
                if ctx.get("req_dir") is None:
                    ctx["req_dir"] = ctx.get("dir")
                pending = ctx.setdefault("pending", {})
                pending[corr] = None
                while len(pending) > 64:  # engine's _MAX_PENDING stance
                    pending.pop(next(iter(pending)))
            name = entry[0]
            return L7Message(
                protocol=L7Protocol.KAFKA,
                msg_type=MSG_REQUEST,
                version=str(api_ver),
                request_type=name,
                request_resource="",
                endpoint=name,
                request_id=corr,
            )
        # stateless fallback: [size][correlation_id], nothing request-like
        return L7Message(
            protocol=L7Protocol.KAFKA,
            msg_type=MSG_RESPONSE,
            request_id=corr,
        )
    except (IndexError, struct.error):
        return None


# ---------------------------------------------------------------------------
# PostgreSQL (sql/postgresql.rs) — typed messages ['Q' len sql...], etc.

_PG_REQ = {b"Q": "QUERY", b"P": "PARSE", b"B": "BIND", b"E": "EXECUTE", b"F": "FASTPATH"}
_PG_RESP_OK = (b"C", b"T", b"D", b"Z", b"1", b"2", b"n", b"s")
# CommandComplete tags (command word leads); used to disambiguate the
# 'C' byte from the frontend Close message (both use the tag)
_PG_COMPLETE_TAGS = (
    b"SELECT", b"INSERT", b"UPDATE", b"DELETE", b"BEGIN", b"COMMIT",
    b"ROLLBACK", b"FETCH", b"COPY", b"CREATE", b"DROP", b"ALTER", b"SET",
    b"MOVE", b"TRUNCATE",
)
# ErrorResponse field-type bytes (severity/code lead in practice)
_PG_ERR_FIELDS = b"SVC"


def _pg_is_error_response(payload: bytes) -> bool:
    """'E' is both frontend Execute and backend ErrorResponse; the error
    body is field-structured ([type u8][cstr]...) while Execute is
    [portal cstr][maxrows i32]."""
    body = payload[5:]
    return bool(body) and body[0:1] in (b"S", b"V") and b"\x00" in body


def _pg_wellformed(payload: bytes) -> bool:
    """Byte stream starts with a plausible [type][len u32 BE] message
    chain. Continuation segments of a large result set are raw row bytes
    whose accidental first byte may alias a type code, but their "length"
    is random — requiring the chain to land exactly on a boundary (or
    run past the segment only on its FINAL message) rejects them."""
    off = 0
    n = len(payload)
    msgs = 0
    while off < n:
        if off + 5 > n:
            return msgs > 0  # trailing partial header after valid msgs
        ln = int.from_bytes(payload[off + 1 : off + 5], "big")
        if ln < 4 or ln > 1 << 24:
            return False
        if off + 1 + ln > n:
            # a message larger than the captured segment is legitimate
            # (big DataRow spanning TCP segments) — but only as the
            # stream's FINAL message; random continuation "lengths"
            # rarely land in [4, 16M]
            return True
        off += 1 + ln
        msgs += 1
        if msgs >= 4:  # enough evidence
            return True
    return off == n


def check_postgresql(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 5:
        return False
    if payload[0:1] in _PG_REQ or payload[0:1] in (b"R", b"S", b"K", b"C", b"T", b"E"):
        ln = int.from_bytes(payload[1:5], "big")
        return 4 <= ln <= 1 << 24 and (port == 5432 or ln <= len(payload) + 16)
    # startup message: len u32, protocol 3.0 = 196608
    ln = int.from_bytes(payload[0:4], "big")
    proto = int.from_bytes(payload[4:8], "big") if len(payload) >= 8 else 0
    return proto in (196608, 80877103) and ln <= 1 << 16


def parse_postgresql(payload: bytes) -> L7Message | None:
    try:
        t = payload[0:1]
        if t == b"E" and not _pg_is_error_response(payload):
            return L7Message(
                protocol=L7Protocol.POSTGRESQL,
                msg_type=MSG_REQUEST,
                request_type="EXECUTE",
                endpoint="EXECUTE",
            )
        if t == b"C" and not payload[5:].split(b"\x00", 1)[0].startswith(
            _PG_COMPLETE_TAGS
        ):
            # frontend Close ('S'/'P' + name), not CommandComplete
            return L7Message(
                protocol=L7Protocol.POSTGRESQL,
                msg_type=MSG_REQUEST,
                request_type="CLOSE",
                endpoint="CLOSE",
            )
        if t == b"Q" or t == b"P":
            body = payload[5:]
            if t == b"P":  # Parse: statement name \0 query \0
                _, _, body = body.partition(b"\x00")
            sql = body.split(b"\x00", 1)[0].decode(errors="replace")
            stmt = obfuscate_sql(sql)
            verb = stmt.split(" ", 1)[0].upper() if stmt else _PG_REQ[t]
            return L7Message(
                protocol=L7Protocol.POSTGRESQL,
                msg_type=MSG_REQUEST,
                request_type=verb,
                request_resource=stmt,
                endpoint=verb,
            )
        if t == b"C":  # CommandComplete ("SELECT 1\0")
            tag = payload[5:].split(b"\x00", 1)[0].decode(errors="replace")
            return L7Message(
                protocol=L7Protocol.POSTGRESQL,
                msg_type=MSG_RESPONSE,
                request_resource=tag,
            )
        if t == b"E":  # ErrorResponse: fields [code u8][str \0]...
            severity, code = "", ""
            off = 5
            while off < len(payload) and payload[off] != 0:
                f = payload[off : off + 1]
                end = payload.index(b"\x00", off + 1)
                val = payload[off + 1 : end].decode(errors="replace")
                if f == b"S":
                    severity = val
                elif f == b"C":
                    code = val
                off = end + 1
            status = (
                STATUS_CLIENT_ERROR
                if code.startswith(("42", "22", "23"))  # syntax/data/integrity
                else STATUS_SERVER_ERROR
            )
            return L7Message(
                protocol=L7Protocol.POSTGRESQL,
                msg_type=MSG_RESPONSE,
                status=status,
                request_resource=f"{severity} {code}".strip(),
            )
        if t in _PG_RESP_OK and _pg_wellformed(payload):
            return L7Message(protocol=L7Protocol.POSTGRESQL, msg_type=MSG_RESPONSE)
        return None
    except (IndexError, ValueError):
        return None


# ---------------------------------------------------------------------------
# MongoDB (sql/mongo.rs) — wire header [len i32 LE][req id][responseTo][op]

_OP_MSG = 2013
_OP_QUERY = 2004
_OP_REPLY = 1
_MONGO_OPS = {_OP_MSG, _OP_QUERY, _OP_REPLY, 2001, 2002, 2005, 2006, 2007, 2010, 2011, 2012}
_MONGO_CMDS = (
    "find", "insert", "update", "delete", "aggregate", "count", "distinct",
    "findAndModify", "getMore", "hello", "isMaster", "ping", "saslStart",
    "saslContinue", "listCollections", "listDatabases", "create", "drop",
)


def check_mongodb(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 16:
        return False
    ln = int.from_bytes(payload[0:4], "little")
    op = int.from_bytes(payload[12:16], "little")
    return op in _MONGO_OPS and 16 <= ln <= 48 << 20 and (port == 27017 or ln <= len(payload) + 64)


def _bson_first_key(doc: bytes) -> str:
    """First element name of a BSON document (the command verb)."""
    if len(doc) < 6:
        return ""
    # [len i32][etype u8][name \0]...
    end = doc.find(b"\x00", 5)
    if end < 0:
        return ""
    return doc[5:end].decode(errors="replace")


def parse_mongodb(payload: bytes) -> L7Message | None:
    try:
        if len(payload) < 16:
            return None
        req_id = int.from_bytes(payload[4:8], "little")
        response_to = int.from_bytes(payload[8:12], "little")
        op = int.from_bytes(payload[12:16], "little")
        is_resp = response_to != 0 or op == _OP_REPLY
        cmd = ""
        if op == _OP_MSG and len(payload) > 21:
            # [flags u32][section kind u8][BSON doc]
            cmd = _bson_first_key(payload[21:])
        elif op == _OP_QUERY:
            # [flags u32][fullCollectionName \0][skip][ret][BSON]
            end = payload.find(b"\x00", 20)
            if end > 0:
                cmd = payload[20:end].decode(errors="replace")
        if is_resp:
            return L7Message(
                protocol=L7Protocol.MONGODB,
                msg_type=MSG_RESPONSE,
                request_id=response_to or req_id,
            )
        known = cmd in _MONGO_CMDS or "." in cmd
        return L7Message(
            protocol=L7Protocol.MONGODB,
            msg_type=MSG_REQUEST,
            request_type=cmd if known or cmd else f"op_{op}",
            request_resource=cmd,
            endpoint=cmd,
            request_id=req_id,
        )
    except (IndexError, struct.error):
        return None


# ---------------------------------------------------------------------------
# Dubbo (rpc/dubbo.rs) — magic 0xdabb header + hessian2 body strings

_DUBBO_MAGIC = b"\xda\xbb"
_FLAG_REQUEST = 0x80
_FLAG_EVENT = 0x20


def check_dubbo(payload: bytes, port: int = 0) -> bool:
    return len(payload) >= 16 and payload[:2] == _DUBBO_MAGIC


def _hessian_attachment(body: bytes, key: str) -> str:
    """Value of a string-keyed attachment in a Dubbo request body: the
    attachments map encodes keys as hessian2 short strings (1-byte
    length), so the exact byte pattern [len][key] locates it; the value
    is read with the same short/medium string rules _hessian_strings
    handles. Used for the trace-context attachments (sw8/traceparent —
    dubbo.rs pulls the same keys)."""
    marker = bytes([len(key)]) + key.encode()
    i = body.find(marker)
    if i < 0:
        return ""
    off = i + len(marker)
    if off >= len(body):
        return ""
    ln = body[off]
    if 0x30 <= ln <= 0x33 and off + 1 < len(body):  # medium string
        ln = ((ln - 0x30) << 8) + body[off + 1]
        off += 2
    elif ln < 0x20:
        off += 1
    else:
        return ""
    if off + ln > len(body):
        return ""
    return body[off : off + ln].decode(errors="replace")


def _hessian_strings(body: bytes, limit: int = 4) -> list[str]:
    """Leading hessian2-encoded short strings ("2.0.2", service, version,
    method). Short strings are length-prefixed with 0x00-0x1f."""
    out = []
    off = 0
    while off < len(body) and len(out) < limit:
        ln = body[off]
        if 0x30 <= ln <= 0x33 and off + 1 < len(body):  # medium string
            ln = ((ln - 0x30) << 8) + body[off + 1]
            off += 2
        elif ln < 0x20:
            off += 1
        else:
            break
        if off + ln > len(body):
            break
        out.append(body[off : off + ln].decode(errors="replace"))
        off += ln
    return out


def parse_dubbo(payload: bytes) -> L7Message | None:
    try:
        if payload[:2] != _DUBBO_MAGIC or len(payload) < 16:
            return None
        flags = payload[2]
        status = payload[3]
        req_id = int.from_bytes(payload[4:12], "big")
        body = payload[16:]
        if flags & _FLAG_REQUEST:
            if flags & _FLAG_EVENT:
                return L7Message(
                    protocol=L7Protocol.DUBBO,
                    msg_type=MSG_REQUEST,
                    request_type="heartbeat",
                    request_id=req_id,
                )
            strs = _hessian_strings(body)
            # [dubbo version, service, service version, method]
            service = strs[1] if len(strs) > 1 else ""
            method = strs[3] if len(strs) > 3 else ""
            from .parsers import trace_from_headers

            trace = trace_from_headers(lambda n: _hessian_attachment(body, n))
            return L7Message(
                protocol=L7Protocol.DUBBO,
                msg_type=MSG_REQUEST,
                version=strs[0] if strs else "",
                request_type=method,
                request_domain=service,
                request_resource=f"{service}.{method}" if service else method,
                endpoint=service,
                request_id=req_id,
                trace_id=trace[0],
                span_id=trace[1],
            )
        # Dubbo status registry: 20 OK; client-side faults: 30
        # CLIENT_TIMEOUT, 40 BAD_REQUEST, 90 CLIENT_ERROR; server-side:
        # 31 SERVER_TIMEOUT, 50 BAD_RESPONSE, 60 SERVICE_NOT_FOUND,
        # 70 SERVICE_ERROR, 80 SERVER_ERROR
        st = (
            STATUS_OK
            if status == 20
            else STATUS_CLIENT_ERROR
            if status in (30, 40, 90)
            else STATUS_SERVER_ERROR
        )
        return L7Message(
            protocol=L7Protocol.DUBBO,
            msg_type=MSG_RESPONSE,
            status=st,
            status_code=status,
            request_id=req_id,
        )
    except (IndexError, struct.error):
        return None
