"""HTTP/2 + gRPC parser — frames, HPACK header decode, gRPC detection.

Mirrors the behavior of the reference's h2 path (protocol_logs/http.rs:
the HTTP/2 branch parses HEADERS frames via an HPACK decoder, detects
gRPC from the content-type, maps :path to the request resource and
grpc-status/:status to the response status). Implementation is from the
public RFC specs, not the reference code:

  * RFC 9113 frame layout: [len u24 BE][type u8][flags u8][stream u31].
  * RFC 7541 HPACK: static table, dynamic table (append semantics),
    indexed / literal header fields, integer prefix coding, and the
    spec's canonical Huffman code (the packed table below is the RFC
    7541 Appendix B data).

The per-flow parser is stateless across packets except for the optional
`Hpack` dynamic table a caller may thread through a connection.
"""

from __future__ import annotations

import dataclasses

from ...datamodel.code import L7Protocol
from .parsers import (
    MSG_REQUEST,
    MSG_RESPONSE,
    STATUS_CLIENT_ERROR,
    STATUS_OK,
    STATUS_SERVER_ERROR,
    L7Message,
)

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

FRAME_DATA = 0x0
FRAME_HEADERS = 0x1
FRAME_SETTINGS = 0x4

# -- RFC 7541 Appendix B Huffman code, packed as "code_hex:length" ------
_HUFF_PACKED = "1ff8:13,7fffd8:23,fffffe2:28,fffffe3:28,fffffe4:28,fffffe5:28,fffffe6:28,fffffe7:28,fffffe8:28,ffffea:24,3ffffffc:30,fffffe9:28,fffffea:28,3ffffffd:30,fffffeb:28,fffffec:28,fffffed:28,fffffee:28,fffffef:28,ffffff0:28,ffffff1:28,ffffff2:28,3ffffffe:30,ffffff3:28,ffffff4:28,ffffff5:28,ffffff6:28,ffffff7:28,ffffff8:28,ffffff9:28,ffffffa:28,ffffffb:28,14:6,3f8:10,3f9:10,ffa:12,1ff9:13,15:6,f8:8,7fa:11,3fa:10,3fb:10,f9:8,7fb:11,fa:8,16:6,17:6,18:6,0:5,1:5,2:5,19:6,1a:6,1b:6,1c:6,1d:6,1e:6,1f:6,5c:7,fb:8,7ffc:15,20:6,ffb:12,3fc:10,1ffa:13,21:6,5d:7,5e:7,5f:7,60:7,61:7,62:7,63:7,64:7,65:7,66:7,67:7,68:7,69:7,6a:7,6b:7,6c:7,6d:7,6e:7,6f:7,70:7,71:7,72:7,fc:8,73:7,fd:8,1ffb:13,7fff0:19,1ffc:13,3ffc:14,22:6,7ffd:15,3:5,23:6,4:5,24:6,5:5,25:6,26:6,27:6,6:5,74:7,75:7,28:6,29:6,2a:6,7:5,2b:6,76:7,2c:6,8:5,9:5,2d:6,77:7,78:7,79:7,7a:7,7b:7,7ffe:15,7fc:11,3ffd:14,1ffd:13,ffffffc:28,fffe6:20,3fffd2:22,fffe7:20,fffe8:20,3fffd3:22,3fffd4:22,3fffd5:22,7fffd9:23,3fffd6:22,7fffda:23,7fffdb:23,7fffdc:23,7fffdd:23,7fffde:23,ffffeb:24,7fffdf:23,ffffec:24,ffffed:24,3fffd7:22,7fffe0:23,ffffee:24,7fffe1:23,7fffe2:23,7fffe3:23,7fffe4:23,1fffdc:21,3fffd8:22,7fffe5:23,3fffd9:22,7fffe6:23,7fffe7:23,ffffef:24,3fffda:22,1fffdd:21,fffe9:20,3fffdb:22,3fffdc:22,7fffe8:23,7fffe9:23,1fffde:21,7fffea:23,3fffdd:22,3fffde:22,fffff0:24,1fffdf:21,3fffdf:22,7fffeb:23,7fffec:23,1fffe0:21,1fffe1:21,3fffe0:22,1fffe2:21,7fffed:23,3fffe1:22,7fffee:23,7fffef:23,fffea:20,3fffe2:22,3fffe3:22,3fffe4:22,7ffff0:23,3fffe5:22,3fffe6:22,7ffff1:23,3ffffe0:26,3ffffe1:26,fffeb:20,7fff1:19,3fffe7:22,7ffff2:23,3fffe8:22,1ffffec:25,3ffffe2:26,3ffffe3:26,3ffffe4:26,7ffffde:27,7ffffdf:27,3ffffe5:26,fffff1:24,1ffffed:25,7fff2:19,1fffe3:21,3ffffe6:26,7ffffe0:27,7ffffe1:27,3ffffe7:26,7ffffe2:27,fffff2:24,1fffe4:21,1fffe5:21,3ffffe8:26,3ffffe9:26,ffffffd:28,7ffffe3:27,7ffffe4:27,7ffffe5:27,fffec:20,fffff3:24,fffed:20,1fffe6:21,3fffe9:22,1fffe7:21,1fffe8:21,7ffff3:23,3fffea:22,3fffeb:22,1ffffee:25,1ffffef:25,fffff4:24,fffff5:24,3ffffea:26,7ffff4:23,3ffffeb:26,7ffffe6:27,3ffffec:26,3ffffed:26,7ffffe7:27,7ffffe8:27,7ffffe9:27,7ffffea:27,7ffffeb:27,ffffffe:28,7ffffec:27,7ffffed:27,7ffffee:27,7ffffef:27,7fffff0:27,3ffffee:26,3fffffff:30"  # noqa: E501

# decode map: (code, length) -> symbol; walked bit-by-bit
_HUFF_DECODE: dict[tuple[int, int], int] = {}
for _sym, _entry in enumerate(_HUFF_PACKED.split(",")):
    _c, _l = _entry.split(":")
    _HUFF_DECODE[(int(_c, 16), int(_l))] = _sym


def huffman_decode(data: bytes) -> str:
    out = []
    code = 0
    length = 0
    for byte in data:
        for bit in range(7, -1, -1):
            code = (code << 1) | ((byte >> bit) & 1)
            length += 1
            sym = _HUFF_DECODE.get((code, length))
            if sym is not None:
                if sym == 256:  # EOS in data is an error; stop
                    return "".join(out)
                out.append(chr(sym))
                code = 0
                length = 0
            elif length > 30:
                return "".join(out)  # malformed
    return "".join(out)


# -- RFC 7541 Appendix A static table (name, value) ---------------------
STATIC_TABLE = [
    (":authority", ""), (":method", "GET"), (":method", "POST"),
    (":path", "/"), (":path", "/index.html"), (":scheme", "http"),
    (":scheme", "https"), (":status", "200"), (":status", "204"),
    (":status", "206"), (":status", "304"), (":status", "400"),
    (":status", "404"), (":status", "500"), ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"), ("accept-language", ""),
    ("accept-ranges", ""), ("accept", ""), ("access-control-allow-origin", ""),
    ("age", ""), ("allow", ""), ("authorization", ""), ("cache-control", ""),
    ("content-disposition", ""), ("content-encoding", ""),
    ("content-language", ""), ("content-length", ""), ("content-location", ""),
    ("content-range", ""), ("content-type", ""), ("cookie", ""), ("date", ""),
    ("etag", ""), ("expect", ""), ("expires", ""), ("from", ""), ("host", ""),
    ("if-match", ""), ("if-modified-since", ""), ("if-none-match", ""),
    ("if-range", ""), ("if-unmodified-since", ""), ("last-modified", ""),
    ("link", ""), ("location", ""), ("max-forwards", ""),
    ("proxy-authenticate", ""), ("proxy-authorization", ""), ("range", ""),
    ("referer", ""), ("refresh", ""), ("retry-after", ""), ("server", ""),
    ("set-cookie", ""), ("strict-transport-security", ""),
    ("transfer-encoding", ""), ("user-agent", ""), ("vary", ""), ("via", ""),
    ("www-authenticate", ""),
]


class Hpack:
    """Minimal HPACK decoder state (dynamic table, append-at-front)."""

    def __init__(self, max_entries: int = 256):
        self.dynamic: list[tuple[str, str]] = []
        self.max_entries = max_entries

    def _lookup(self, idx: int) -> tuple[str, str]:
        if 1 <= idx <= len(STATIC_TABLE):
            return STATIC_TABLE[idx - 1]
        d = idx - len(STATIC_TABLE) - 1
        if 0 <= d < len(self.dynamic):
            return self.dynamic[d]
        return ("", "")

    def _insert(self, name: str, value: str) -> None:
        self.dynamic.insert(0, (name, value))
        del self.dynamic[self.max_entries:]

    def decode(self, block: bytes) -> list[tuple[str, str]]:
        """HPACK header block → [(name, value)]; best-effort on damage."""
        headers = []
        i = 0
        n = len(block)

        def read_int(prefix_bits: int) -> int:
            nonlocal i
            mask = (1 << prefix_bits) - 1
            v = block[i] & mask
            i += 1
            if v < mask:
                return v
            shift = 0
            while i < n:
                b = block[i]
                i += 1
                v += (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            return v

        def read_str() -> str:
            nonlocal i
            if i >= n:
                return ""
            huff = bool(block[i] & 0x80)
            ln = read_int(7)
            raw = block[i : i + ln]
            i += ln
            return huffman_decode(raw) if huff else raw.decode("utf-8", "replace")

        while i < n:
            b = block[i]
            if b & 0x80:  # indexed field
                idx = read_int(7)
                headers.append(self._lookup(idx))
            elif b & 0x40:  # literal with incremental indexing
                idx = read_int(6)
                name = self._lookup(idx)[0] if idx else read_str()
                value = read_str()
                self._insert(name, value)
                headers.append((name, value))
            elif b & 0x20:  # dynamic table size update
                read_int(5)
            else:  # literal without indexing / never indexed (prefix 4)
                idx = read_int(4)
                name = self._lookup(idx)[0] if idx else read_str()
                value = read_str()
                headers.append((name, value))
        return headers


@dataclasses.dataclass
class H2Frame:
    type: int
    flags: int
    stream_id: int
    payload: bytes


def iter_frames(payload: bytes):
    """Yield frames from a packet payload (preface skipped if present)."""
    off = 0
    if payload.startswith(PREFACE):
        off = len(PREFACE)
    n = len(payload)
    while off + 9 <= n:
        ln = int.from_bytes(payload[off : off + 3], "big")
        typ = payload[off + 3]
        flags = payload[off + 4]
        stream = int.from_bytes(payload[off + 5 : off + 9], "big") & 0x7FFFFFFF
        body = payload[off + 9 : off + 9 + ln]
        if typ > 0x9 or ln > 1 << 20:  # not an h2 stream after all
            return
        yield H2Frame(typ, flags, stream, body)
        off += 9 + ln


def check_http2(payload: bytes, port: int = 0) -> bool:
    if payload.startswith(PREFACE):
        return True
    # standalone frame heuristic: valid type + sane length + SETTINGS or
    # HEADERS near the front (the reference's h2c sniff in http.rs)
    if len(payload) < 9:
        return False
    ln = int.from_bytes(payload[0:3], "big")
    typ = payload[3]
    if typ == FRAME_SETTINGS:
        return ln % 6 == 0 and ln <= 1024
    return typ == FRAME_HEADERS and ln <= len(payload)


_N_PATH_SEGMENTS = 2


def parse_http2(payload: bytes, hpack: Hpack | None = None) -> L7Message | None:
    """First HEADERS frame in the payload → request/response message.

    gRPC: content-type application/grpc → protocol GRPC, endpoint =
    /package.Service/Method from :path, grpc-status maps onto status.
    """
    hp = hpack or Hpack()
    try:
        return _parse_http2_inner(payload, hp)
    except Exception:
        return None


def _parse_http2_inner(payload: bytes, hp: Hpack) -> L7Message | None:
    for fr in iter_frames(payload):
        if fr.type != FRAME_HEADERS:
            continue
        body = fr.payload
        pad = body[0] if fr.flags & 0x8 and body else 0
        off = 1 if fr.flags & 0x8 else 0
        if fr.flags & 0x20:  # PRIORITY fields
            off += 5
        block = body[off : len(body) - pad if pad else len(body)]
        headers = dict(hp.decode(block))
        if not headers:
            return None
        is_grpc = headers.get("content-type", "").startswith("application/grpc")
        proto = L7Protocol.GRPC if is_grpc else L7Protocol.HTTP2
        if ":method" in headers:  # request
            from .parsers import endpoint_from_path, trace_from_headers

            path = headers.get(":path", "")
            bare = path.split("?", 1)[0]
            # gRPC paths are exactly /package.Service/Method — the
            # 2-segment trim keeps them whole
            endpoint = endpoint_from_path(bare, _N_PATH_SEGMENTS)
            trace = trace_from_headers(headers.get)
            return L7Message(
                protocol=proto,
                msg_type=MSG_REQUEST,
                version="2",
                request_type=headers[":method"],
                request_domain=headers.get(":authority", headers.get("host", "")),
                request_resource=bare,
                endpoint=endpoint,
                request_id=fr.stream_id,
                trace_id=trace[0],
                span_id=trace[1],
            )
        if ":status" in headers or "grpc-status" in headers:
            grpc_status = headers.get("grpc-status")
            raw_code = headers.get(":status") or "0"
            code = int(raw_code) if raw_code.isdigit() else 0
            if grpc_status is not None and grpc_status.isdigit():
                g = int(grpc_status)
                status = STATUS_OK if g == 0 else STATUS_SERVER_ERROR
                code = g if g else code
            else:
                status = (
                    STATUS_CLIENT_ERROR
                    if 400 <= code < 500
                    else STATUS_SERVER_ERROR if code >= 500 else STATUS_OK
                )
            return L7Message(
                protocol=proto,
                msg_type=MSG_RESPONSE,
                version="2",
                status=status,
                status_code=code,
                request_id=fr.stream_id,
            )
        return None  # trailers-only or damaged
    return None
