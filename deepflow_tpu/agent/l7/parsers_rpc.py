"""L7 parsers, wave 4: FastCGI + RocketMQ.

Behavioral peers of protocol_logs/rpc/fastcgi.rs and mq/rocketmq.rs;
wire layouts from the public protocol specs:

  * FastCGI: 8-byte records [version=1][type][requestId u16]
    [contentLength u16][paddingLength][reserved]; BEGIN_REQUEST=1 opens,
    PARAMS=4 carries name-value pairs (REQUEST_METHOD / REQUEST_URI),
    STDOUT=6 carries the response head ("Status: NNN"), END_REQUEST=3.
  * RocketMQ remoting: [frame len u32][header meta u32: serializer in
    the top byte, JSON header length in the low 24 bits][JSON header]
    [body]. Header fields: code, flag (bit0 = response), opaque
    (correlation id), language, version, extFields{topic, consumerGroup,
    queueId...}, remark.
"""

from __future__ import annotations

import json

from ...datamodel.code import L7Protocol
from .parsers import (
    MSG_REQUEST,
    MSG_RESPONSE,
    STATUS_CLIENT_ERROR,
    STATUS_OK,
    STATUS_SERVER_ERROR,
    L7Message,
)

# ---------------------------------------------------------------------------
# FastCGI

_FCGI_BEGIN = 1
_FCGI_END = 3
_FCGI_PARAMS = 4
_FCGI_STDOUT = 6
_FCGI_TYPES = set(range(1, 12))


def _fcgi_records(payload: bytes):
    off = 0
    while off + 8 <= len(payload):
        version, rtype = payload[off], payload[off + 1]
        req_id = int.from_bytes(payload[off + 2 : off + 4], "big")
        clen = int.from_bytes(payload[off + 4 : off + 6], "big")
        plen = payload[off + 6]
        if version != 1 or rtype not in _FCGI_TYPES:
            return
        if rtype == 1 and clen != 8:  # spec: BEGIN_REQUEST body is exactly 8B
            return
        yield rtype, req_id, payload[off + 8 : off + 8 + clen]
        off += 8 + clen + plen


def _fcgi_params(content: bytes) -> dict:
    out = {}
    off = 0
    n = len(content)
    while off < n:
        lens = []
        for _ in range(2):
            if off >= n:
                return out
            ln = content[off]
            if ln >> 7:
                ln = int.from_bytes(content[off : off + 4], "big") & 0x7FFFFFFF
                off += 4
            else:
                off += 1
            lens.append(ln)
        k = content[off : off + lens[0]]
        v = content[off + lens[0] : off + lens[0] + lens[1]]
        off += lens[0] + lens[1]
        out[k.decode(errors="replace")] = v.decode(errors="replace")
    return out


def check_fastcgi(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 8 or payload[0] != 1:
        return False
    return payload[1] in _FCGI_TYPES and (
        port == 9000 or next(_fcgi_records(payload), None) is not None
    )


def parse_fastcgi(payload: bytes) -> L7Message | None:
    try:
        method = uri = ""
        req_id = None
        saw_req = saw_resp = False
        status = STATUS_OK
        code = 0
        for rtype, rid, content in _fcgi_records(payload):
            req_id = rid
            if rtype in (_FCGI_BEGIN, _FCGI_PARAMS):
                saw_req = True
                if rtype == _FCGI_PARAMS and content:
                    params = _fcgi_params(content)
                    method = params.get("REQUEST_METHOD", method)
                    uri = params.get("REQUEST_URI", params.get("SCRIPT_NAME", uri))
            elif rtype in (_FCGI_STDOUT, _FCGI_END):
                saw_resp = True
                if rtype == _FCGI_STDOUT and content.startswith(b"Status:"):
                    head = content.split(b"\r\n", 1)[0][7:].strip()
                    digits = head.split(b" ", 1)[0]
                    if digits.isdigit():
                        code = int(digits)
                        status = (
                            STATUS_CLIENT_ERROR
                            if 400 <= code < 500
                            else STATUS_SERVER_ERROR if code >= 500 else STATUS_OK
                        )
        if saw_req and not saw_resp:
            from .parsers import endpoint_from_path

            return L7Message(
                protocol=L7Protocol.FASTCGI,
                msg_type=MSG_REQUEST,
                request_type=method,
                request_resource=uri,
                endpoint=endpoint_from_path(uri) if uri else "",
                request_id=req_id,
            )
        if saw_resp:
            return L7Message(
                protocol=L7Protocol.FASTCGI,
                msg_type=MSG_RESPONSE,
                status=status,
                status_code=code,
                request_id=req_id,
            )
        return None
    except (IndexError, ValueError):
        return None


# ---------------------------------------------------------------------------
# RocketMQ

_ROCKETMQ_CODES = {
    10: "SEND_MESSAGE", 11: "PULL_MESSAGE", 12: "QUERY_MESSAGE",
    14: "QUERY_CONSUMER_OFFSET", 15: "UPDATE_CONSUMER_OFFSET",
    34: "HEART_BEAT", 35: "UNREGISTER_CLIENT", 36: "CONSUMER_SEND_MSG_BACK",
    38: "GET_CONSUMER_LIST_BY_GROUP", 105: "GET_ROUTEINFO_BY_TOPIC",
    310: "SEND_MESSAGE_V2", 320: "SEND_BATCH_MESSAGE",
}
_ROCKETMQ_RESP = {0: "SUCCESS", 1: "SYSTEM_ERROR", 2: "SYSTEM_BUSY",
                  3: "REQUEST_CODE_NOT_SUPPORTED"}


def check_rocketmq(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 12:
        return False
    total = int.from_bytes(payload[0:4], "big")
    meta = int.from_bytes(payload[4:8], "big")
    hlen = meta & 0xFFFFFF
    serializer = meta >> 24
    # only the JSON serializer (0) is parseable below; accepting the
    # binary one would pin flows to a protocol that then never parses
    # (and its loose shape swallows SofaRPC/Bolt frames)
    return (
        4 <= total <= 1 << 25
        and serializer == 0
        and 2 <= hlen
        and hlen + 4 <= total
        and payload[8:9] == b"{"
    )


def parse_rocketmq(payload: bytes) -> L7Message | None:
    try:
        meta = int.from_bytes(payload[4:8], "big")
        hlen = meta & 0xFFFFFF
        if meta >> 24 != 0:  # ROCKETMQ (binary) headers: code+flag only
            return None
        header = json.loads(payload[8 : 8 + hlen])
        code = int(header.get("code", 0))
        flag = int(header.get("flag", 0))
        opaque = int(header.get("opaque", 0))
        ext = header.get("extFields") or {}
        topic = str(ext.get("topic", ext.get("b", "")))
        group = str(ext.get("consumerGroup", ext.get("group", ext.get("a", ""))))
        if flag & 1:  # response
            rstatus = STATUS_OK if code == 0 else STATUS_SERVER_ERROR
            return L7Message(
                protocol=L7Protocol.ROCKETMQ,
                msg_type=MSG_RESPONSE,
                request_type=_ROCKETMQ_RESP.get(code, str(code)),
                status=rstatus,
                status_code=code,
                request_id=opaque,
            )
        name = _ROCKETMQ_CODES.get(code, str(code))
        return L7Message(
            protocol=L7Protocol.ROCKETMQ,
            msg_type=MSG_REQUEST,
            request_type=name,
            request_domain=group,
            request_resource=topic,
            endpoint=topic or name,
            request_id=opaque,
        )
    except (IndexError, ValueError, TypeError):
        return None
