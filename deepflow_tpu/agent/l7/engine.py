"""L7 engine: per-flow protocol inference, request/response pairing,
and dual emission (request logs + RED metrics).

Mirrors the reference composition: protocol_logs parsers emit
AppProtoLogs entries with per-flow RRT tracked by pairing requests to
responses (protocol_logs/perf/ rrt caches keyed by request_id/stream);
the same events feed the AppMeter path via L7QuadrupleGenerator. Here
`process()` consumes a parsed PacketBatch (+ its snap buffer for
payload slices), keeps per-flow inference and pending-request state,
and returns (L7_FLOW_LOG rows for the PROTOCOLLOG wire, AppMeter
FlowBatch for the L7 metrics pipeline).

Pairing: DNS/MySQL match on request_id (txid / seq window), HTTP/Redis
FIFO per flow (HTTP/1 has no ids; pipelining pairs in order). Pending
requests older than `session_timeout_s` emit as timeout sessions —
the reference's rrt-cache timeout semantics.
"""

from __future__ import annotations

import dataclasses
from collections import deque

import numpy as np

from ...datamodel.batch import FLOW_RECORD_TAG_FIELDS, FlowBatch
from ...datamodel.code import Direction, L7Protocol, SignalSource
from ...datamodel.schema import APP_METER
from ...flowlog.aggr import FlowLogBatch
from ...flowlog.schema import L7_FLOW_LOG
from ..packet import PROTO_ICMP, PROTO_TCP, PROTO_UDP, PacketBatch
from .parsers import (
    MSG_REQUEST,
    MSG_RESPONSE,
    STATUS_CLIENT_ERROR,
    STATUS_OK,
    STATUS_SERVER_ERROR,
    L7Message,
    infer_protocol,
    parse_payload,
)

STATUS_TIMEOUT = 5
_M = APP_METER.index

# l7 log type column (l7_flow_log.go type)
TYPE_REQUEST = 0
TYPE_RESPONSE = 1
TYPE_SESSION = 2


# the wire codec's endpoint hash — ONE hash per endpoint string across
# the packet path and the wire-decode path, or per-endpoint series split
from ...ingest.codec import _hash_str


@dataclasses.dataclass
class _Pending:
    msg: L7Message
    ts_us: int
    row: dict  # flow identity fields


@dataclasses.dataclass
class _FlowL7:
    protocol: int = L7Protocol.UNKNOWN
    tries: int = 0
    pending: deque = dataclasses.field(default_factory=deque)
    by_id: dict = dataclasses.field(default_factory=dict)
    last_seen_us: int = 0
    # per-direction parser state (HTTP/2 HPACK dynamic tables — each
    # side of the connection keeps its own, RFC 7541 §2.2)
    parser_ctx: dict = dataclasses.field(default_factory=dict)


_MAX_INFER_TRIES = 8  # reference: bounded per-flow inference attempts
_MAX_PENDING = 64


class L7Engine:
    def __init__(self, *, agent_id: int = 1, session_timeout_s: int = 30):
        self.agent_id = agent_id
        self.session_timeout_s = session_timeout_s
        self._flows: dict[tuple, _FlowL7] = {}
        self.counters = {
            "payloads_in": 0,
            "inferred": 0,
            "sessions": 0,
            "timeouts": 0,
            "parse_miss": 0,
        }

    # -- main entry -----------------------------------------------------
    def process(self, buf: np.ndarray, p: PacketBatch) -> tuple[FlowLogBatch, FlowBatch]:
        """One capture batch → (l7 log rows, AppMeter records)."""
        sessions: list[dict] = []
        buf = np.asarray(buf, np.uint8)
        idx = np.nonzero(
            p.valid
            & (p.payload_len > 0)
            & ((p.protocol == PROTO_TCP) | (p.protocol == PROTO_UDP) | (p.protocol == PROTO_ICMP))
        )[0]
        for i in idx:
            self._one_packet(buf, p, int(i), sessions)
        # session-timeout sweep on the batch's max clock
        if p.size:
            now_us = int(p.timestamp_s.max()) * 1_000_000
            self._sweep_timeouts(now_us, sessions)
        return self._emit(sessions)

    def _flow_key(self, p: PacketBatch, i: int) -> tuple[tuple, int]:
        """→ (canonical flow key, flow-relative direction of packet i):
        direction 0 = the packet's source is the key's low endpoint.
        Derived here because the src tuple is already in hand — callers
        must not rebuild it per protocol."""
        a = (tuple(int(w) for w in p.ip_src[i]), int(p.port_src[i]))
        b = (tuple(int(w) for w in p.ip_dst[i]), int(p.port_dst[i]))
        lo, hi = (a, b) if a <= b else (b, a)
        return (lo, hi, int(p.protocol[i])), 0 if a == lo else 1

    def _one_packet(self, buf, p: PacketBatch, i: int, sessions: list) -> None:
        self.counters["payloads_in"] += 1
        off = int(p.payload_off[i])
        end = min(off + int(p.payload_len[i]), buf.shape[1])
        payload = buf[i, off:end].tobytes()
        if not payload:
            return
        key, d = self._flow_key(p, i)
        fl = self._flows.get(key)
        if fl is None:
            fl = self._flows[key] = _FlowL7()
        fl.last_seen_us = int(p.timestamp_s[i]) * 1_000_000 + int(p.timestamp_us[i])

        sport, dport = int(p.port_src[i]), int(p.port_dst[i])
        if fl.protocol == L7Protocol.UNKNOWN:
            if fl.tries >= _MAX_INFER_TRIES:
                return
            fl.tries += 1
            if int(p.protocol[i]) == PROTO_ICMP:
                # ICMP never enters the TCP/UDP probe chain: echo frames
                # go straight to PING, everything else stays UNKNOWN
                from .parsers_w4 import check_ping

                if not check_ping(payload):
                    return
                proto = L7Protocol.PING
            else:
                proto = infer_protocol(payload, dport) or infer_protocol(payload, sport)
            if proto == L7Protocol.UNKNOWN:
                return
            fl.protocol = proto
            self.counters["inferred"] += 1

        ctx = None
        if fl.protocol in (L7Protocol.HTTP2, L7Protocol.GRPC):
            from .http2 import Hpack

            ctx = fl.parser_ctx.setdefault(d, Hpack())
        elif fl.protocol == L7Protocol.KAFKA:
            # correlation-id bookkeeping: responses are only
            # recognizable against outstanding requests (kafka.rs
            # keeps the same per-flow session state). The packet's
            # flow-relative direction rides along so a request whose
            # api words alias a pending corr can't be taken for a
            # response.
            ctx = fl.parser_ctx.setdefault("kafka", {})
            ctx["dir"] = d
        msg = parse_payload(fl.protocol, payload, ctx)
        if msg is None:
            self.counters["parse_miss"] += 1
            return
        # parser-level refinement: HTTP/2 flows carrying
        # content-type application/grpc become GRPC for the whole flow
        if msg.protocol not in (fl.protocol, L7Protocol.UNKNOWN):
            fl.protocol = msg.protocol
        ts_us = int(p.timestamp_s[i]) * 1_000_000 + int(p.timestamp_us[i])
        ident = {
            "is_ipv6": int(p.is_ipv6[i]),
            **{f"ip{0}_w{w}": int(p.ip_src[i, w]) for w in range(4)},
            **{f"ip{1}_w{w}": int(p.ip_dst[i, w]) for w in range(4)},
            "client_port": sport,
            "server_port": dport,
            "protocol": int(p.protocol[i]),
            "l7_protocol": fl.protocol,
        }
        if msg.msg_type == MSG_REQUEST:
            if len(fl.pending) >= _MAX_PENDING:
                evicted = fl.pending.popleft()
                if evicted.msg.request_id is not None:  # keep by_id in sync
                    fl.by_id.pop(evicted.msg.request_id, None)
            entry = _Pending(msg, ts_us, ident)
            fl.pending.append(entry)
            if msg.request_id is not None:
                fl.by_id[msg.request_id] = entry
        else:
            if 100 <= msg.status_code < 200:
                # informational (100 Continue): not a final response —
                # pairing on it would orphan the real one
                return
            entry = None
            if msg.request_id is not None and msg.request_id in fl.by_id:
                entry = fl.by_id.pop(msg.request_id)
                try:
                    fl.pending.remove(entry)
                except ValueError:
                    pass
            elif msg.request_id is None and fl.pending:
                entry = fl.pending.popleft()
                if entry.msg.request_id is not None:
                    fl.by_id.pop(entry.msg.request_id, None)
            self.counters["sessions"] += 1
            if entry is None:
                # orphan response: the packet flows server→client, so the
                # identity must be swapped to keep ip0/client_port = client
                swapped = {
                    **ident,
                    **{f"ip0_w{w}": ident[f"ip1_w{w}"] for w in range(4)},
                    **{f"ip1_w{w}": ident[f"ip0_w{w}"] for w in range(4)},
                    "client_port": ident["server_port"],
                    "server_port": ident["client_port"],
                }
                sessions.append(
                    {**swapped, "req": None, "resp": msg, "ts_us": ts_us, "rrt_us": 0}
                )
            else:
                sessions.append(
                    {
                        **entry.row,
                        "req": entry.msg,
                        "resp": msg,
                        "ts_us": ts_us,
                        "req_ts_us": entry.ts_us,
                        "rrt_us": max(0, ts_us - entry.ts_us),
                    }
                )

    def _sweep_timeouts(self, now_us: int, sessions: list) -> None:
        limit = self.session_timeout_s * 1_000_000
        for key, fl in list(self._flows.items()):
            while fl.pending and now_us - fl.pending[0].ts_us > limit:
                entry = fl.pending.popleft()
                if entry.msg.request_id is not None:
                    fl.by_id.pop(entry.msg.request_id, None)
                self.counters["timeouts"] += 1
                sessions.append(
                    {
                        **entry.row,
                        "req": entry.msg,
                        "resp": None,
                        "ts_us": entry.ts_us,
                        "req_ts_us": entry.ts_us,
                        "rrt_us": 0,
                    }
                )
            # evict idle flows (inferred or not) — per-flow L7 state must
            # not outlive the connection
            if not fl.pending and now_us - fl.last_seen_us > 2 * limit:
                del self._flows[key]

    # -- emission -------------------------------------------------------
    def _emit(self, sessions: list[dict]) -> tuple[FlowLogBatch, FlowBatch]:
        s = L7_FLOW_LOG
        n = len(sessions)
        ints = np.zeros((n, len(s.ints)), np.uint32)
        nums = np.zeros((n, len(s.nums)), np.float32)
        strs = {f.name: [""] * n for f in s.strs}
        tags = {f: np.zeros(n, np.uint32) for f in FLOW_RECORD_TAG_FIELDS}
        meters = np.zeros((n, APP_METER.num_fields), np.float32)
        ii = s.int_index

        for r, sess in enumerate(sessions):
            req: L7Message | None = sess["req"]
            resp: L7Message | None = sess["resp"]
            head = req or resp
            timeout = resp is None
            status = STATUS_TIMEOUT if timeout else resp.status
            sec = sess["ts_us"] // 1_000_000
            for f in ("is_ipv6", "client_port", "server_port", "protocol", "l7_protocol"):
                ints[r, ii(f)] = sess[f]
            for side in (0, 1):
                for w in range(4):
                    ints[r, ii(f"ip{side}_w{w}")] = sess[f"ip{side}_w{w}"]
            ints[r, ii("agent_id")] = self.agent_id
            ints[r, ii("type")] = (
                TYPE_SESSION if req and resp else TYPE_REQUEST if req else TYPE_RESPONSE
            )
            # ids/codes are pairing cookies, not quantities — mask into
            # the u32 columns (bRPC correlation ids are 64-bit varints,
            # Tars iRet is signed)
            ints[r, ii("request_id")] = ((head.request_id or 0) if head else 0) & 0xFFFFFFFF
            ints[r, ii("status")] = status
            ints[r, ii("status_code")] = (resp.status_code if resp else 0) & 0xFFFFFFFF
            ints[r, ii("start_time")] = sess.get("req_ts_us", sess["ts_us"]) // 1_000_000
            ints[r, ii("end_time")] = sec
            ints[r, ii("response_duration")] = sess["rrt_us"]
            ints[r, ii("tap_side")] = 1
            if req:
                strs["request_type"][r] = req.request_type
                strs["request_domain"][r] = req.request_domain
                strs["request_resource"][r] = req.request_resource
                strs["endpoint"][r] = req.endpoint
                # header-carried trace context (traceparent/B3/sw8):
                # packet spans join instrumented traces through the
                # same l7_flow_log columns the OTel lane fills
                strs["trace_id"][r] = req.trace_id
                strs["span_id"][r] = req.span_id
            if resp and resp.request_resource and resp.status in (
                STATUS_CLIENT_ERROR,
                STATUS_SERVER_ERROR,
            ):
                strs["response_exception"][r] = resp.request_resource

            # AppMeter record (fill_l7_stats inputs)
            tags["timestamp"][r] = sec
            tags["agent_id"][r] = self.agent_id
            tags["signal_source"][r] = int(SignalSource.PACKET)
            for w in range(4):
                tags[f"ip0_w{w}"][r] = sess[f"ip0_w{w}"]
                tags[f"ip1_w{w}"][r] = sess[f"ip1_w{w}"]
            tags["is_ipv6"][r] = sess["is_ipv6"]
            tags["protocol"][r] = sess["protocol"]
            tags["server_port"][r] = sess["server_port"]
            tags["l7_protocol"][r] = sess["l7_protocol"]
            tags["endpoint_hash"][r] = _hash_str(req.endpoint if req else "")
            tags["direction0"][r] = int(Direction.CLIENT_TO_SERVER)
            tags["direction1"][r] = int(Direction.SERVER_TO_CLIENT)
            tags["is_active_host0"][r] = 1
            tags["is_active_host1"][r] = 1
            tags["is_active_service"][r] = 1
            meters[r, _M("request")] = 1 if req else 0
            meters[r, _M("response")] = 1 if resp else 0
            if sess["rrt_us"]:
                meters[r, _M("rrt_max")] = sess["rrt_us"]
                meters[r, _M("rrt_sum")] = sess["rrt_us"]
                meters[r, _M("rrt_count")] = 1
            meters[r, _M("client_error")] = status == STATUS_CLIENT_ERROR
            meters[r, _M("server_error")] = status == STATUS_SERVER_ERROR
            meters[r, _M("timeout")] = status == STATUS_TIMEOUT

        log_batch = FlowLogBatch(s, ints, nums, np.ones(n, bool), strs)
        app_batch = FlowBatch(tags=tags, meters=meters, valid=np.ones(n, bool))
        return log_batch, app_batch
