"""L7 parsers, wave 3: MQTT, memcached, NATS, AMQP.

Behavioral peers of protocol_logs/mq/{mqtt.rs, nats.rs, amqp.rs} and
sql/memcached.rs; wire layouts from the public protocol specs.
"""

from __future__ import annotations

from ...datamodel.code import L7Protocol
from .parsers import (
    MSG_REQUEST,
    MSG_RESPONSE,
    STATUS_CLIENT_ERROR,
    STATUS_OK,
    STATUS_SERVER_ERROR,
    L7Message,
)

# ---------------------------------------------------------------------------
# MQTT (mq/mqtt.rs) — fixed header: [type:4|flags:4][remaining varint]

_MQTT_TYPES = {
    1: "CONNECT", 2: "CONNACK", 3: "PUBLISH", 4: "PUBACK", 5: "PUBREC",
    6: "PUBREL", 7: "PUBCOMP", 8: "SUBSCRIBE", 9: "SUBACK",
    10: "UNSUBSCRIBE", 11: "UNSUBACK", 12: "PINGREQ", 13: "PINGRESP",
    14: "DISCONNECT",
}
# control packets the broker sends (pair as responses)
_MQTT_RESP = {2, 4, 5, 7, 9, 11, 13}


def _mqtt_varint(buf: bytes, off: int) -> tuple[int, int]:
    v = shift = 0
    while off < len(buf) and shift <= 21:
        b = buf[off]
        v |= (b & 0x7F) << shift
        off += 1
        shift += 7
        if not b & 0x80:
            return v, off
    return -1, off


def check_mqtt(payload: bytes, port: int = 0) -> bool:
    if len(payload) < 2:
        return False
    ptype = payload[0] >> 4
    if ptype not in _MQTT_TYPES:
        return False
    ln, hdr_end = _mqtt_varint(payload, 1)
    if ln < 0:
        return False
    if ptype == 1:  # CONNECT carries the protocol name
        name_len = int.from_bytes(payload[hdr_end : hdr_end + 2], "big")
        name = payload[hdr_end + 2 : hdr_end + 2 + name_len]
        return name in (b"MQTT", b"MQIsdp")
    return port == 1883 or hdr_end + ln == len(payload)


def parse_mqtt(payload: bytes) -> L7Message | None:
    try:
        ptype = payload[0] >> 4
        name = _MQTT_TYPES.get(ptype)
        if name is None:
            return None
        _ln, off = _mqtt_varint(payload, 1)
        topic = client_id = ""
        status = STATUS_OK
        code = 0
        if ptype == 1:  # CONNECT: proto name, level, flags, keepalive,
            # [v5: properties], client id
            nlen = int.from_bytes(payload[off : off + 2], "big")
            p = off + 2 + nlen
            level = payload[p]
            p += 1 + 1 + 2  # level, connect flags, keepalive
            if level >= 5:  # MQTT 5 properties: varint length + body
                plen, p = _mqtt_varint(payload, p)
                p += max(plen, 0)
            clen = int.from_bytes(payload[p : p + 2], "big")
            client_id = payload[p + 2 : p + 2 + clen].decode(errors="replace")
        elif ptype == 2:  # CONNACK: flags + return code
            code = payload[off + 1] if len(payload) > off + 1 else 0
            if code:
                status = STATUS_SERVER_ERROR
        elif ptype == 3:  # PUBLISH: topic
            tlen = int.from_bytes(payload[off : off + 2], "big")
            topic = payload[off + 2 : off + 2 + tlen].decode(errors="replace")
        elif ptype in (8, 10):  # (UN)SUBSCRIBE: packet id [v5 props] topic
            p = off + 2
            # v5 detection without connection state: a valid v3 topic
            # length never starts with 0x00-high-byte+varint-looking
            # properties; probe — if the u16 at p yields a non-UTF8 or
            # zero-length topic and byte p parses as a properties varint
            # whose skip lands on a valid topic, prefer that. Cheap form:
            # try v3 first, fall back to skipping a properties varint.
            tlen = int.from_bytes(payload[p : p + 2], "big")
            if tlen == 0 or p + 2 + tlen > len(payload):
                plen, q = _mqtt_varint(payload, p)
                if plen >= 0:
                    p = q + plen
                    tlen = int.from_bytes(payload[p : p + 2], "big")
            topic = payload[p + 2 : p + 2 + tlen].decode(errors="replace")
        return L7Message(
            protocol=L7Protocol.MQTT,
            msg_type=MSG_RESPONSE if ptype in _MQTT_RESP else MSG_REQUEST,
            request_type=name,
            request_domain=client_id,
            request_resource=topic,
            endpoint=topic or name,
            status=status,
            status_code=code,
        )
    except (IndexError, ValueError):
        return None


# ---------------------------------------------------------------------------
# memcached (sql/memcached.rs) — text protocol

_MC_STORE = (b"set", b"add", b"replace", b"append", b"prepend", b"cas")
_MC_REQ = _MC_STORE + (b"get", b"gets", b"gat", b"gats", b"delete", b"incr",
                       b"decr", b"touch", b"stats", b"flush_all", b"version",
                       b"verbosity", b"quit")
_MC_RESP = (b"VALUE", b"STORED", b"NOT_STORED", b"EXISTS", b"NOT_FOUND",
            b"DELETED", b"TOUCHED", b"END", b"OK", b"VERSION", b"STAT",
            b"ERROR", b"CLIENT_ERROR", b"SERVER_ERROR")


def check_memcached(payload: bytes, port: int = 0) -> bool:
    if b"\r\n" not in payload[:1024]:
        return False
    first = payload.split(b"\r\n", 1)[0].split(b" ", 1)[0]
    return first in _MC_REQ or first in _MC_RESP


def parse_memcached(payload: bytes) -> L7Message | None:
    try:
        line = payload.split(b"\r\n", 1)[0]
        parts = line.split(b" ")
        word = parts[0]
        if word in _MC_REQ:
            cmd = word.decode()
            return L7Message(
                protocol=L7Protocol.MEMCACHED,
                msg_type=MSG_REQUEST,
                request_type=cmd,
                request_resource=line.decode(errors="replace"),
                endpoint=cmd,
            )
        if word in _MC_RESP:
            status = STATUS_OK
            if word == b"SERVER_ERROR":
                status = STATUS_SERVER_ERROR
            elif word in (b"ERROR", b"CLIENT_ERROR"):
                status = STATUS_CLIENT_ERROR
            return L7Message(
                protocol=L7Protocol.MEMCACHED,
                msg_type=MSG_RESPONSE,
                status=status,
                request_resource=line.decode(errors="replace"),
            )
        return None
    except (IndexError, ValueError):
        return None


# ---------------------------------------------------------------------------
# NATS (mq/nats.rs) — text control lines

_NATS_CLIENT = (b"CONNECT", b"PUB", b"HPUB", b"SUB", b"UNSUB", b"PING")
_NATS_SERVER = (b"INFO", b"MSG", b"HMSG", b"+OK", b"-ERR", b"PONG")


def check_nats(payload: bytes, port: int = 0) -> bool:
    head = payload[:16].upper()
    return any(head.startswith(w + b" ") or head.startswith(w + b"\r")
               for w in _NATS_CLIENT + _NATS_SERVER)


def parse_nats(payload: bytes) -> L7Message | None:
    try:
        line = payload.split(b"\r\n", 1)[0]
        parts = line.split(b" ")
        verb = parts[0].upper().decode(errors="replace")
        subject = ""
        status = STATUS_OK
        if verb in ("PUB", "HPUB", "SUB", "MSG", "HMSG", "UNSUB"):
            subject = parts[1].decode(errors="replace") if len(parts) > 1 else ""
        if verb == "-ERR":
            status = STATUS_SERVER_ERROR
        is_resp = parts[0].upper() in _NATS_SERVER
        return L7Message(
            protocol=L7Protocol.NATS,
            msg_type=MSG_RESPONSE if is_resp else MSG_REQUEST,
            request_type=verb,
            request_resource=subject,
            endpoint=subject or verb,
            status=status,
        )
    except (IndexError, ValueError):
        return None


# ---------------------------------------------------------------------------
# AMQP 0-9-1 (mq/amqp.rs) — "AMQP\0\0\x09\x01" header + framed methods

_AMQP_CLASSES = {10: "Connection", 20: "Channel", 40: "Exchange",
                 50: "Queue", 60: "Basic", 85: "Confirm", 90: "Tx"}
_AMQP_METHODS = {
    (10, 10): "Start", (10, 11): "StartOk", (10, 30): "Tune",
    (10, 31): "TuneOk", (10, 40): "Open", (10, 41): "OpenOk",
    (10, 50): "Close", (10, 51): "CloseOk",
    (20, 10): "Open", (20, 11): "OpenOk", (20, 40): "Close", (20, 41): "CloseOk",
    (40, 10): "Declare", (40, 11): "DeclareOk",
    (50, 10): "Declare", (50, 11): "DeclareOk", (50, 20): "Bind", (50, 21): "BindOk",
    (60, 20): "Consume", (60, 21): "ConsumeOk", (60, 40): "Publish",
    (60, 60): "Deliver", (60, 70): "Get", (60, 71): "GetOk", (60, 80): "Ack",
}
# *Ok methods pair as responses to their request; Start/Tune are the
# SERVER's handshake requests (answered by client StartOk/TuneOk) and
# Deliver is a server push — requests, or FIFO pairing inverts every
# handshake's client/server identity
_AMQP_RESP_METHODS = {m for m in _AMQP_METHODS if m[1] % 10 == 1}


def check_amqp(payload: bytes, port: int = 0) -> bool:
    if payload.startswith(b"AMQP\x00"):
        return True
    if len(payload) < 8:
        return False
    ftype = payload[0]
    size = int.from_bytes(payload[3:7], "big")
    # off-port we demand the whole frame in the segment WITH the 0xCE
    # frame-end octet (spec §2.3.5) — that end marker is what keeps
    # arbitrary length-prefixed binary from classifying as AMQP; on
    # :5672 a frame may span segments, so only a sane size bound applies
    if ftype not in (1, 2, 3, 8):
        return False
    if size + 8 <= len(payload):
        return payload[7 + size] == 0xCE
    return port == 5672 and size < 1 << 24


def parse_amqp(payload: bytes) -> L7Message | None:
    try:
        if payload.startswith(b"AMQP\x00"):
            return L7Message(
                protocol=L7Protocol.AMQP,
                msg_type=MSG_REQUEST,
                request_type="ProtocolHeader",
                version=f"{payload[6]}.{payload[7]}" if len(payload) >= 8 else "",
            )
        ftype = payload[0]
        if ftype != 1:  # header/body/heartbeat frames carry no method
            return L7Message(protocol=L7Protocol.AMQP, msg_type=MSG_REQUEST,
                             request_type={2: "ContentHeader", 3: "ContentBody",
                                           8: "Heartbeat"}.get(ftype, "Frame"))
        class_id = int.from_bytes(payload[7:9], "big")
        method_id = int.from_bytes(payload[9:11], "big")
        cname = _AMQP_CLASSES.get(class_id, str(class_id))
        mname = _AMQP_METHODS.get((class_id, method_id), str(method_id))
        req_type = f"{cname}.{mname}"
        status = STATUS_OK
        if (class_id, method_id) in ((10, 50), (20, 40)):  # Close carries a code
            code = int.from_bytes(payload[11:13], "big")
            if code >= 400:
                status = STATUS_SERVER_ERROR if code >= 500 else STATUS_CLIENT_ERROR
        return L7Message(
            protocol=L7Protocol.AMQP,
            msg_type=MSG_RESPONSE
            if (class_id, method_id) in _AMQP_RESP_METHODS
            else MSG_REQUEST,
            request_type=req_type,
            endpoint=req_type,
            status=status,
        )
    except (IndexError, ValueError):
        return None
