"""Dispatcher modes — vectorized local / mirror / analyzer orientation.

The reference runs one dispatcher flavor per deployment shape
(dispatcher/mod.rs DispatcherFlavor): *local* captures a host's own
interfaces (a side is "ours" when its MAC is a local interface MAC),
*mirror* receives bridge-mirrored VM traffic (side identity = the
controller-pushed VM MAC set, keyed on the MAC's low 32 bits,
mirror_mode_dispatcher.rs:103), and *analyzer* terminates span/ERSPAN
feeds where no endpoint is local and the outer VLAN id maps to a
tap_type (the trisolaris tap-type table). Flavors there are separate
recv pipelines; here orientation is one vectorized pass over the
parsed batch — the capture engine is shared, the MODE is data.

`orient()` returns per-packet (tap_type, l2_end_src, l2_end_dst):
which sides of each packet terminate on this agent's domain, and the
tap the packet was seen on. FlowMap folds these into per-flow lanes
(OR for ends, FIRST for tap_type) and emission derives tap_side the
way document.rs TapSide::from does."""

from __future__ import annotations

import dataclasses

import numpy as np

# TapType constants (the reference reserves 3 for "cloud"/local
# traffic; ISP span positions are 1/2/4..7, trident.proto TapType)
TAP_CLOUD = 3


@dataclasses.dataclass
class DispatcherConfig:
    mode: str = "local"  # local | mirror | analyzer
    # mirror mode: VM/bridge MAC set (low 32 bits, like the reference's
    # to_lower_32b keys); local mode: this host's interface MACs —
    # empty means "every packet is ours" (single-host default)
    macs: tuple[int, ...] = ()
    # analyzer mode: outer VLAN id → tap_type; unmapped VLANs fall to
    # default_tap_type
    vlan_tap_map: dict | None = None
    default_tap_type: int = TAP_CLOUD


class Dispatcher:
    def __init__(self, config: DispatcherConfig = DispatcherConfig()):
        if config.mode not in ("local", "mirror", "analyzer"):
            raise ValueError(f"unknown dispatcher mode {config.mode!r}")
        self.config = config
        # full 48-bit MACs are accepted and keyed on their low 32 bits
        # (the same to_lower_32b reduction the reference applies)
        self._mac_set = np.asarray(
            sorted({int(m) & 0xFFFFFFFF for m in config.macs}), np.uint32
        )
        vt = config.vlan_tap_map or {}
        self._vlan_ids = np.asarray(sorted(vt), np.uint32)
        self._vlan_taps = np.asarray(
            [vt[int(v)] for v in self._vlan_ids], np.uint32
        )
        self.counters = {"packets": 0, "oriented": 0}

    def _in_macs(self, macs: np.ndarray) -> np.ndarray:
        if self._mac_set.size == 0:
            return np.zeros(macs.shape[0], bool)
        idx = np.searchsorted(self._mac_set, macs)
        idx = np.clip(idx, 0, self._mac_set.size - 1)
        return self._mac_set[idx] == macs

    def orient(self, p) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """PacketBatch → (tap_type [N] u32, l2_end_src [N] bool,
        l2_end_dst [N] bool)."""
        n = p.size
        mode = self.config.mode
        self.counters["packets"] += int(n)
        tap = np.full(n, self.config.default_tap_type, np.uint32)
        if mode == "analyzer":
            # span feed: no side is local; tap from the VLAN table
            if self._vlan_ids.size:
                idx = np.clip(
                    np.searchsorted(self._vlan_ids, p.vlan_id),
                    0, self._vlan_ids.size - 1,
                )
                hit = self._vlan_ids[idx] == p.vlan_id
                tap = np.where(hit, self._vlan_taps[idx], tap).astype(np.uint32)
            return tap, np.zeros(n, bool), np.zeros(n, bool)
        if mode == "mirror":
            src = self._in_macs(p.mac_src_lo)
            dst = self._in_macs(p.mac_dst_lo)
        else:  # local
            if self._mac_set.size == 0:
                # single-host default: we captured it, so one side is
                # ours — the sender for egress frames; without MACs the
                # best static claim is both-ends-local loopback stance
                src = np.ones(n, bool)
                dst = np.ones(n, bool)
            else:
                src = self._in_macs(p.mac_src_lo)
                dst = self._in_macs(p.mac_dst_lo)
        self.counters["oriented"] += int((src | dst).sum())
        return tap, src, dst
