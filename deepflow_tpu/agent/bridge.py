"""FlowMap emissions → metrics-pipeline input.

In the reference, FlowMap's per-second TaggedFlow batches feed BOTH the
collector chain (QuadrupleGenerator → Collector → metric Documents) and
FlowAggr (minute flow logs) from the same queue (trident.rs pipeline
wiring). The L4_FLOW_LOG emission rows already ARE the FlowAggr input;
this bridge produces the other consumer's shape — a `FlowBatch` of tag
columns + FLOW_METER meters for `L4Pipeline.ingest`.
"""

from __future__ import annotations

import numpy as np

from ..datamodel.batch import FLOW_RECORD_TAG_FIELDS, FlowBatch
from ..datamodel.code import Direction
from ..datamodel.schema import FLOW_METER
from ..flowlog.aggr import FlowLogBatch
from ..flowlog.schema import L4_FLOW_LOG
from .flow_map import CLOSE_NONE, CLOSE_TIMEOUT

_M = FLOW_METER.index


def emissions_to_flow_batch(b: FlowLogBatch, *, epc0: int = 0, epc1: int = 0,
                            possible=None) -> FlowBatch:
    """L4_FLOW_LOG emission rows → metrics-path FlowBatch.

    `possible`: optional PossibleHostTable (agent/possible.py). When
    given, is_active_host0/1 come from observed-traffic activity
    instead of the all-active default (the quadruple generator's
    possible_host consult, quadruple_generator.rs:342)."""
    assert b.schema is L4_FLOW_LOG
    s = b.schema
    n = b.size
    tags = {f: np.zeros(n, np.uint32) for f in FLOW_RECORD_TAG_FIELDS}
    ic = b.col

    tags["timestamp"] = ic("end_time").astype(np.uint32)
    tags["agent_id"] = ic("agent_id").astype(np.uint32)
    tags["signal_source"] = ic("signal_source").astype(np.uint32)
    tags["is_ipv6"] = ic("is_ipv6").astype(np.uint32)
    for w in range(4):
        tags[f"ip0_w{w}"] = ic(f"ip0_w{w}").astype(np.uint32)
        tags[f"ip1_w{w}"] = ic(f"ip1_w{w}").astype(np.uint32)
    tags["l3_epc_id"][:] = epc0
    tags["l3_epc_id1"][:] = epc1
    tags["protocol"] = ic("protocol").astype(np.uint32)
    tags["server_port"] = ic("server_port").astype(np.uint32)
    tags["tap_port"] = ic("tap_port").astype(np.uint32)
    tags["tap_type"] = ic("tap_type").astype(np.uint32)
    tags["l7_protocol"] = ic("l7_protocol").astype(np.uint32)
    tags["direction0"][:] = int(Direction.CLIENT_TO_SERVER)
    tags["direction1"][:] = int(Direction.SERVER_TO_CLIENT)
    if possible is None:
        tags["is_active_host0"][:] = 1
        tags["is_active_host1"][:] = 1
    else:
        from .possible import _hash_ips

        valid_rows = np.asarray(b.valid, bool)
        ts_valid = tags["timestamp"][valid_rows]
        now = int(ts_valid.max()) if ts_valid.size else 0
        ip0 = np.stack([tags[f"ip0_w{w}"] for w in range(4)], axis=1)
        ip1 = np.stack([tags[f"ip1_w{w}"] for w in range(4)], axis=1)
        k0, k1 = _hash_ips(ip0), _hash_ips(ip1)  # hash once per side
        # an endpoint that transmitted in this flow is active by
        # observation; the table remembers it across flows/windows.
        # Invalid padding rows must neither stamp the table nor move
        # the clock.
        sent0 = valid_rows & (ic("packet_tx").astype(np.int64) > 0)
        sent1 = valid_rows & (ic("packet_rx").astype(np.int64) > 0)
        possible.add_keys(k0[sent0], now)
        possible.add_keys(k1[sent1], now)
        tags["is_active_host0"] = possible.check_keys(k0, now).astype(np.uint32)
        tags["is_active_host1"] = possible.check_keys(k1, now).astype(np.uint32)

    meters = np.zeros((n, FLOW_METER.num_fields), np.float32)
    for src, dst in (
        ("packet_tx", "packet_tx"),
        ("packet_rx", "packet_rx"),
        ("byte_tx", "byte_tx"),
        ("byte_rx", "byte_rx"),
        ("l4_byte_tx", "l4_byte_tx"),
        ("l4_byte_rx", "l4_byte_rx"),
        ("syn_count", "syn"),
        ("synack_count", "synack"),
        ("retrans_tx", "retrans_tx"),
        ("retrans_rx", "retrans_rx"),
    ):
        meters[:, _M(dst)] = b.col(src)

    close_type = ic("close_type")
    meters[:, _M("closed_flow")] = (close_type != CLOSE_NONE).astype(np.float32)
    meters[:, _M("new_flow")] = (ic("is_new_flow") != 0).astype(np.float32)
    meters[:, _M("tcp_timeout")] = (close_type == CLOSE_TIMEOUT).astype(np.float32)

    rtt_c = b.col("rtt_client_max")
    rtt_s = b.col("rtt_server_max")
    rtt = b.col("rtt")
    have = rtt > 0
    meters[:, _M("rtt_max")] = rtt
    meters[:, _M("rtt_sum")] = rtt
    meters[:, _M("rtt_count")] = have.astype(np.float32)
    meters[:, _M("rtt_client_max")] = rtt_c
    meters[:, _M("rtt_client_sum")] = rtt_c
    meters[:, _M("rtt_client_count")] = (rtt_c > 0).astype(np.float32)
    meters[:, _M("rtt_server_max")] = rtt_s
    meters[:, _M("rtt_server_sum")] = rtt_s
    meters[:, _M("rtt_server_count")] = (rtt_s > 0).astype(np.float32)

    return FlowBatch(tags=tags, meters=meters, valid=b.valid.copy())
