"""Stack symbolization + continuous-profiler aggregation — the eBPF
userspace half (reference: agent/src/ebpf/user/symbol.c ELF/symbol
resolution, profile/perf_profiler.c stack folding/aggregation,
profile/java jvm perf-map symbolization).

The kernel plane (perf events, uprobe attach) is environment-blocked in
this container; what the reference's USERSPACE does with the raw
samples is fully implemented here:

  * `ProcMaps` — /proc/<pid>/maps executable-range index (module base
    addresses for PIE/shared objects);
  * `ElfSymbols` — a dependency-free ELF64 .symtab/.dynsym reader
    (FUNC symbols, address-sorted) — symbol.c's bcc-backed table;
  * `JavaPerfMap` — /tmp/perf-<pid>.map (the JVM perf-map-agent /
    async-profiler convention symbol.c consumes for Java frames);
  * `Symbolizer` — address → "module!func" resolution with per-module
    caching and unknown-frame fallbacks ("[module+0xoff]");
  * `ProfileAggregator` — (pid, stack-addresses, weight) samples →
    folded "a;b;c weight" lines per interval, the wire shape the
    PROFILE ingest lane already accepts (integration/collector.py).
"""

from __future__ import annotations

import bisect
import dataclasses
import os
import struct


# ---------------------------------------------------------------------------
# /proc/<pid>/maps


@dataclasses.dataclass(frozen=True)
class MapRange:
    start: int
    end: int
    offset: int
    path: str


class ProcMaps:
    """Executable ranges of one process, sorted by start address."""

    def __init__(self, ranges: list[MapRange]):
        self.ranges = sorted(ranges, key=lambda r: r.start)
        self._starts = [r.start for r in self.ranges]

    @classmethod
    def read(cls, pid: int | str = "self") -> "ProcMaps":
        out = []
        try:
            with open(f"/proc/{pid}/maps") as f:
                for line in f:
                    parts = line.split(maxsplit=5)
                    if len(parts) < 5 or "x" not in parts[1]:
                        continue
                    lo, _, hi = parts[0].partition("-")
                    out.append(MapRange(
                        int(lo, 16), int(hi, 16), int(parts[2], 16),
                        parts[5].strip() if len(parts) == 6 else "",
                    ))
        except OSError:
            pass
        return cls(out)

    def find(self, addr: int) -> MapRange | None:
        i = bisect.bisect_right(self._starts, addr) - 1
        if i >= 0 and self.ranges[i].start <= addr < self.ranges[i].end:
            return self.ranges[i]
        return None


# ---------------------------------------------------------------------------
# ELF64 symbol tables (no pyelftools in-image — a ~60-line subset reads
# what symbol.c reads: FUNC symbols from .symtab and .dynsym)


def _read_elf_symbols(path: str) -> list[tuple[int, int, str]]:
    """[(addr, size, name)] for STT_FUNC symbols, both tables."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != b"\x7fELF" or data[4] != 2:  # ELF64 only
        return []
    little = data[5] == 1
    e = "<" if little else ">"
    shoff, = struct.unpack_from(e + "Q", data, 0x28)
    shentsize, shnum = struct.unpack_from(e + "HH", data, 0x3A)
    sections = []
    for i in range(shnum):
        off = shoff + i * shentsize
        if off + 64 > len(data):
            return []
        s_type, = struct.unpack_from(e + "I", data, off + 4)
        s_offset, s_size = struct.unpack_from(e + "QQ", data, off + 24)
        s_link, = struct.unpack_from(e + "I", data, off + 40)
        s_entsize, = struct.unpack_from(e + "Q", data, off + 56)
        sections.append((s_type, s_offset, s_size, s_link, s_entsize))
    out = []
    for s_type, s_offset, s_size, s_link, s_entsize in sections:
        if s_type not in (2, 11) or not s_entsize:  # SYMTAB, DYNSYM
            continue
        if s_link >= len(sections):
            continue
        _, str_off, str_size, _, _ = sections[s_link]
        strtab = data[str_off:str_off + str_size]
        for off in range(s_offset, s_offset + s_size, s_entsize):
            if off + 24 > len(data):
                break
            name_off, info = struct.unpack_from(e + "IB", data, off)
            value, size = struct.unpack_from(e + "QQ", data, off + 8)
            if info & 0xF != 2 or value == 0:  # STT_FUNC, defined
                continue
            end = strtab.find(b"\0", name_off)
            name = strtab[name_off:end].decode(errors="replace")
            if name:
                out.append((value, size, name))
    return out


class ElfSymbols:
    """Address-sorted FUNC symbols of one module."""

    def __init__(self, syms: list[tuple[int, int, str]]):
        self.syms = sorted(set(syms))
        self._addrs = [s[0] for s in self.syms]

    @classmethod
    def load(cls, path: str) -> "ElfSymbols":
        try:
            return cls(_read_elf_symbols(path))
        except (OSError, struct.error, IndexError, ValueError):
            # truncated/corrupt module files must not kill the
            # profiling loop — resolve falls back to module+offset
            return cls([])

    def resolve(self, vaddr: int) -> str | None:
        i = bisect.bisect_right(self._addrs, vaddr) - 1
        if i < 0:
            return None
        addr, size, name = self.syms[i]
        if size and vaddr >= addr + size:
            return None  # in a gap past the previous symbol
        return name


# ---------------------------------------------------------------------------
# JVM perf-map (symbol.c's java path: /tmp/perf-<pid>.map, lines of
# "HEXADDR HEXSIZE name")


class JavaPerfMap:
    def __init__(self, entries: list[tuple[int, int, str]]):
        self.entries = sorted(entries)
        self._addrs = [a for a, _, _ in self.entries]

    @classmethod
    def read(cls, pid: int, root: str = "/tmp") -> "JavaPerfMap":
        out = []
        try:
            with open(os.path.join(root, f"perf-{pid}.map")) as f:
                for line in f:
                    parts = line.split(maxsplit=2)
                    if len(parts) == 3:
                        try:
                            out.append(
                                (int(parts[0], 16), int(parts[1], 16),
                                 parts[2].strip())
                            )
                        except ValueError:
                            continue
        except OSError:
            pass
        return cls(out)

    def resolve(self, addr: int) -> str | None:
        i = bisect.bisect_right(self._addrs, addr) - 1
        if i < 0:
            return None
        start, size, name = self.entries[i]
        return name if addr < start + size else None


# ---------------------------------------------------------------------------
# symbolizer + profile aggregation


class Symbolizer:
    """Raw virtual addresses of one process → display frames."""

    def __init__(self, pid: int | str = "self", *, perf_map_root: str = "/tmp"):
        self.pid = pid
        self.maps = ProcMaps.read(pid)
        self._elfs: dict[str, ElfSymbols] = {}
        self.java = (
            JavaPerfMap.read(int(pid), perf_map_root)
            if str(pid).isdigit() else JavaPerfMap([])
        )
        self.counters = {"resolved": 0, "fallback": 0, "unknown": 0}

    def _module(self, path: str) -> ElfSymbols:
        m = self._elfs.get(path)
        if m is None:
            m = ElfSymbols.load(path) if path.startswith("/") else ElfSymbols([])
            self._elfs[path] = m
        return m

    def resolve(self, addr: int) -> str:
        jname = self.java.resolve(addr)
        if jname is not None:
            self.counters["resolved"] += 1
            return jname
        r = self.maps.find(addr)
        if r is None:
            self.counters["unknown"] += 1
            return f"[0x{addr:x}]"
        modname = os.path.basename(r.path) or "[anon]"
        # ET_DYN modules map at a base; symbol vaddrs are file-relative
        for vaddr in (addr - r.start + r.offset, addr):
            name = self._module(r.path).resolve(vaddr)
            if name is not None:
                self.counters["resolved"] += 1
                return f"{modname}!{name}"
        self.counters["fallback"] += 1
        return f"[{modname}+0x{addr - r.start:x}]"

    def fold(self, stack: list[int]) -> str:
        """Leaf-FIRST address list (the perf unwind order
        PerfStackSample documents) → root-first folded frame string.
        ';' inside a frame name (JVM signatures like 'Lcom/x/C;::m')
        would corrupt the folded framing — it maps to ':'."""
        return ";".join(
            self.resolve(a).replace(";", ":") for a in reversed(stack)
        )


class ProfileAggregator:
    """perf_profiler.c's fold/aggregate loop: raw samples in, folded
    per-interval lines out (the PROFILE wire shape)."""

    def __init__(self, *, app_service: str = "", event_type: str = "cpu"):
        self.app_service = app_service
        self.event_type = event_type
        self._symbolizers: dict[int | str, tuple[Symbolizer, float]] = {}
        self._counts: dict[str, int] = {}
        self.counters = {"samples": 0, "flushes": 0}

    # symbolizers refresh on an interval: pid reuse, late dlopen, and
    # growing JVM perf-maps all invalidate a snapshot (perf_profiler.c
    # re-reads its process caches the same way); the dict stays bounded
    # because expired entries are replaced in place and dead pids are
    # dropped at flush
    symbolizer_ttl_s: float = 60.0

    def symbolizer(self, pid: int | str) -> Symbolizer:
        import time as _time

        now = _time.monotonic()
        ent = self._symbolizers.get(pid)
        if ent is None or now - ent[1] > self.symbolizer_ttl_s:
            ent = (Symbolizer(pid), now)
            self._symbolizers[pid] = ent
        return ent[0]

    def observe(self, pid: int | str, stack: list[int], weight: int = 1) -> None:
        folded = self.symbolizer(pid).fold(stack)
        self._counts[folded] = self._counts.get(folded, 0) + int(weight)
        self.counters["samples"] += 1

    def observe_folded(self, folded: str, weight: int = 1) -> None:
        """Pre-symbolized stacks (the r4-era intake) share the window."""
        self._counts[folded] = self._counts.get(folded, 0) + int(weight)
        self.counters["samples"] += 1

    def flush(self, timestamp: int) -> bytes | None:
        """→ one PROFILE frame body ("svc\\0type\\0ts\\n" + folded lines),
        the shape integration/collector.py ships and the profile
        ingester decodes; None when the window is empty."""
        # prune symbolizers of exited processes (bounds the cache)
        for pid in [p for p in self._symbolizers
                    if str(p).isdigit() and not os.path.exists(f"/proc/{p}")]:
            del self._symbolizers[pid]
        if not self._counts:
            return None
        lines = "\n".join(
            f"{stack} {n}" for stack, n in sorted(self._counts.items())
        )
        head = f"{self.app_service}\x00{self.event_type}\x00{timestamp}\n"
        self._counts.clear()
        self.counters["flushes"] += 1
        return (head + lines).encode()
