"""ACL policy labeler — the agent's policy plane, vectorized.

The reference's policy module (agent/src/policy/labeler.rs endpoint
resolution; first_path/fast_path.rs ACL matching) classifies every
packet against operator ACLs and attaches actions: NPB forwarding,
policy-triggered PCAP, drop. Its two-tier first-path/fast-path cache
exists because scalar per-packet matching is expensive on a CPU; here
the whole batch matches against the whole ACL table in one broadcast
pass ([A, N] masks), which IS the fast path on this architecture —
no per-flow cache to invalidate (documented deviation).

Actions follow the reference's semantics:
  * DROP    — packet removed before FlowMap/L7 (policy drop).
  * PCAP    — packet captured into the pcap plane (RAW_PCAP frames →
              pcap ingester, server/ingester/pcap).
  * NPB     — counted and labeled; there is no packet-broker fabric in
              this environment, so NPB marks flows for export only.
ACL order is priority order: the first matching ACL wins
(first_path.rs first-hit semantics).
"""

from __future__ import annotations

import dataclasses
import logging
import struct

import numpy as np

from .packet import PacketBatch

ACTION_NONE = 0
ACTION_NPB = 1
ACTION_PCAP = 2
ACTION_DROP = 3


def parse_cidr(cidr: str) -> tuple[int, int]:
    """'10.0.0.0/8' → (u32 net, prefix_len). '0.0.0.0/0' matches any."""
    ip, _, plen = cidr.partition("/")
    parts = [int(x) for x in ip.split(".")]
    net = (parts[0] << 24) | (parts[1] << 16) | (parts[2] << 8) | parts[3]
    return net, int(plen or 32)


@dataclasses.dataclass(frozen=True)
class Acl:
    """One ACL entry (reference: trisolaris-pushed FlowAcl). IPv4 CIDRs;
    prefix 0 means any address (and also matches IPv6 packets — "any"
    is address-family agnostic, everything narrower is v4-only)."""

    id: int
    action: int = ACTION_NONE
    src: str = "0.0.0.0/0"
    dst: str = "0.0.0.0/0"
    src_ports: tuple | None = None  # (lo, hi) inclusive
    dst_ports: tuple | None = None
    protocol: int = 0  # 0 = any IP protocol
    symmetric: bool = True  # match the reverse direction too

    def __post_init__(self):
        # 0 is the no-match sentinel in match() output; an id-0 ACL's
        # hits would be silently dropped from usage metering
        if self.id < 1:
            raise ValueError(f"ACL id must be >= 1, got {self.id}")


class PolicyLabeler:
    def __init__(self, acls: list[Acl]):
        self.acls = list(acls)
        n = len(self.acls)
        self._ids = np.asarray([a.id for a in self.acls], np.uint32)
        self._actions = np.asarray([a.action for a in self.acls], np.uint32)
        self._proto = np.asarray([a.protocol for a in self.acls], np.uint32)
        self._sym = np.asarray([a.symmetric for a in self.acls], bool)

        def nets(field):
            net = np.zeros(n, np.uint32)
            mask = np.zeros(n, np.uint32)
            for i, a in enumerate(self.acls):
                v, plen = parse_cidr(getattr(a, field))
                net[i] = v
                mask[i] = ((0xFFFFFFFF << (32 - plen)) & 0xFFFFFFFF) if plen else 0
            return net & mask, mask

        self._src_net, self._src_mask = nets("src")
        self._dst_net, self._dst_mask = nets("dst")

        def ports(field):
            lo = np.zeros(n, np.uint32)
            hi = np.full(n, 65535, np.uint32)
            for i, a in enumerate(self.acls):
                r = getattr(a, field)
                if r is not None:
                    lo[i], hi[i] = r
            return lo, hi

        self._sp_lo, self._sp_hi = ports("src_ports")
        self._dp_lo, self._dp_hi = ports("dst_ports")
        self.counters = {"matched": 0, "dropped": 0, "pcap": 0, "npb": 0}

    def match(self, p: PacketBatch) -> tuple[np.ndarray, np.ndarray]:
        """→ (acl_id[N] u32, action[N] u32); 0/NONE where nothing hits.
        One broadcast pass: [A, 1] ACL columns against [N] packet rows.
        """
        if not self.acls:
            z = np.zeros(p.size, np.uint32)
            return z, z
        ip_s = p.ip_src[:, 3].astype(np.uint32)[None, :]  # [1, N]
        ip_d = p.ip_dst[:, 3].astype(np.uint32)[None, :]
        v4 = (p.is_ipv6 == 0)[None, :]
        sp = p.port_src[None, :]
        dp = p.port_dst[None, :]

        src_net = self._src_net[:, None]
        src_mask = self._src_mask[:, None]
        dst_net = self._dst_net[:, None]
        dst_mask = self._dst_mask[:, None]

        def side(ip, net, mask):
            # mask 0 ("any") also admits IPv6; narrower CIDRs are v4-only
            return ((ip & mask) == net) & (v4 | (mask == 0))

        proto_ok = (self._proto[:, None] == 0) | (
            self._proto[:, None] == p.protocol[None, :]
        )
        fwd = (
            side(ip_s, src_net, src_mask)
            & side(ip_d, dst_net, dst_mask)
            & (sp >= self._sp_lo[:, None]) & (sp <= self._sp_hi[:, None])
            & (dp >= self._dp_lo[:, None]) & (dp <= self._dp_hi[:, None])
        )
        rev = (
            side(ip_d, src_net, src_mask)
            & side(ip_s, dst_net, dst_mask)
            & (dp >= self._sp_lo[:, None]) & (dp <= self._sp_hi[:, None])
            & (sp >= self._dp_lo[:, None]) & (sp <= self._dp_hi[:, None])
        )
        hits = proto_ok & (fwd | (rev & self._sym[:, None]))  # [A, N]
        hits &= p.valid[None, :]

        any_hit = hits.any(axis=0)
        first = np.argmax(hits, axis=0)  # lowest ACL index = priority
        acl_id = np.where(any_hit, self._ids[first], 0).astype(np.uint32)
        action = np.where(any_hit, self._actions[first], 0).astype(np.uint32)
        # orientation of the winning ACL for the usage-doc tx/rx split
        self.last_forward = (proto_ok & fwd)[first, np.arange(p.size)] & any_hit

        self.counters["matched"] += int(any_hit.sum())
        self.counters["dropped"] += int((action == ACTION_DROP).sum())
        self.counters["pcap"] += int((action == ACTION_PCAP).sum())
        self.counters["npb"] += int((action == ACTION_NPB).sum())
        return acl_id, action


def pcap_frames(buf: np.ndarray, p: PacketBatch, idx: np.ndarray,
                acl_id: np.ndarray) -> list[bytes]:
    """Policy-PCAP packets → the pcap plane's binary frame layout
    ([flow_id u64 BE][ts_us u64 BE][len u32 BE][bytes] — must match
    server/events.py _pcap's `>QQI`). flow_id carries the ACL id so the
    pcap table records which policy fired."""
    out = []
    for i in idx:
        i = int(i)
        ln = min(int(p.packet_len[i]), buf.shape[1])
        ts = int(p.timestamp_s[i]) * 1_000_000 + int(p.timestamp_us[i])
        pkt = buf[i, :ln].tobytes()
        out.append(struct.pack(">QQI", int(acl_id[i]), ts, len(pkt)) + pkt)
    return out


class PolicyMeterAggregator:
    """ACL usage docs — the policy doc path (collector.rs:440-487).

    Packets matching an ACL accumulate per-(minute, acl_gid) UsageMeter
    lanes; `flush()` emits traffic_policy-shaped documents (CodeId.ACL,
    MeterId.USAGE) as a DocBatch carried in the FLOW_METER matrix (its
    packet/byte lanes — USAGE_METER maps 1:1 onto Traffic columns,
    datamodel/schema.py). tx = the ACL's forward orientation."""

    INTERVAL = 60

    def __init__(self, *, agent_id: int = 1):
        self.agent_id = agent_id
        self._acc: dict[tuple[int, int], np.ndarray] = {}  # (minute, acl) → [4]

    def update(self, p: PacketBatch, acl_id: np.ndarray, action: np.ndarray,
               forward: np.ndarray) -> None:
        sel = (acl_id > 0) & (action != ACTION_DROP) & p.valid
        if not sel.any():
            return
        minutes = (p.timestamp_s[sel] // self.INTERVAL).astype(np.int64)
        acls = acl_id[sel].astype(np.int64)
        fwd = forward[sel]
        nbytes = p.packet_len[sel].astype(np.int64)
        for key in np.unique(np.stack([minutes, acls], axis=1), axis=0):
            m = (minutes == key[0]) & (acls == key[1])
            row = self._acc.setdefault((int(key[0]), int(key[1])), np.zeros(4, np.int64))
            row[0] += int((m & fwd).sum())           # packet_tx
            row[1] += int((m & ~fwd).sum())          # packet_rx
            row[2] += int(nbytes[m & fwd].sum())     # byte_tx
            row[3] += int(nbytes[m & ~fwd].sum())    # byte_rx

    def flush(self, now_s: int):
        """Emit closed minutes (< current one) as a DocBatch, or None."""
        from ..datamodel.code import CodeId, MeterId
        from ..datamodel.schema import FLOW_METER, TAG_SCHEMA

        cur_min = now_s // self.INTERVAL
        done = [k for k in self._acc if k[0] < cur_min]
        if not done:
            return None
        n = len(done)
        tags = np.zeros((n, TAG_SCHEMA.num_fields), np.uint32)
        meters = np.zeros((n, FLOW_METER.num_fields), np.float32)
        ts = np.zeros((n,), np.uint32)
        mi = FLOW_METER.index
        for r, key in enumerate(sorted(done)):
            minute, acl = key
            row = self._acc.pop(key)
            ts[r] = minute * self.INTERVAL
            tags[r, TAG_SCHEMA.index("code_id")] = CodeId.ACL
            tags[r, TAG_SCHEMA.index("meter_id")] = MeterId.USAGE
            tags[r, TAG_SCHEMA.index("agent_id")] = self.agent_id
            tags[r, TAG_SCHEMA.index("acl_gid")] = acl
            meters[r, mi("packet_tx")] = row[0]
            meters[r, mi("packet_rx")] = row[1]
            meters[r, mi("byte_tx")] = row[2]
            meters[r, mi("byte_rx")] = row[3]
        from ..datamodel.batch import DocBatch

        return DocBatch(
            tags=tags, meters=meters, timestamp=ts,
            valid=np.ones((n,), bool),
            tag_schema=TAG_SCHEMA, meter_schema=FLOW_METER,
        )


_ACTION_NAMES = {
    "none": ACTION_NONE, "npb": ACTION_NPB, "pcap": ACTION_PCAP,
    "drop": ACTION_DROP,
}


def acls_from_config(spec: list[dict]) -> tuple[Acl, ...]:
    """Trisolaris-pushed FlowAcl payload → Acl tuple. Each entry:
    {"id": int, "action": "npb"|"pcap"|"drop"|"none", "src": cidr,
     "dst": cidr, "src_ports": [lo, hi], "dst_ports": [lo, hi],
     "protocol": int, "symmetric": bool} — all but id optional."""
    out = []
    for e in spec:
        if int(e.get("id", 0)) < 1:
            # a remotely pushed bad entry must not abort the whole
            # dynamic-config apply — skip it, keep the rest
            logging.warning("dropping ACL with invalid id %r", e.get("id"))
            continue
        out.append(
            Acl(
                id=int(e["id"]),
                action=_ACTION_NAMES.get(str(e.get("action", "none")).lower(), ACTION_NONE),
                src=e.get("src", "0.0.0.0/0"),
                dst=e.get("dst", "0.0.0.0/0"),
                src_ports=tuple(e["src_ports"]) if e.get("src_ports") else None,
                dst_ports=tuple(e["dst_ports"]) if e.get("dst_ports") else None,
                protocol=int(e.get("protocol", 0)),
                symmetric=bool(e.get("symmetric", True)),
            )
        )
    return tuple(out)
