"""ACL policy labeler — the agent's policy plane, vectorized.

The reference's policy module (agent/src/policy/labeler.rs endpoint
resolution; first_path/fast_path.rs ACL matching) classifies every
packet against operator ACLs and attaches actions: NPB forwarding,
policy-triggered PCAP, drop. Its two-tier first-path/fast-path cache
exists because scalar per-packet matching is expensive on a CPU; here
the whole batch matches against the whole ACL table in one broadcast
pass ([A, N] masks), which IS the fast path on this architecture —
no per-flow cache to invalidate (documented deviation).

Actions follow the reference's semantics:
  * DROP    — packet removed before FlowMap/L7 (policy drop).
  * PCAP    — packet captured into the pcap plane (RAW_PCAP frames →
              pcap ingester, server/ingester/pcap).
  * NPB     — counted and labeled; there is no packet-broker fabric in
              this environment, so NPB marks flows for export only.
ACL order is priority order: the first matching ACL wins
(first_path.rs first-hit semantics).
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

from .packet import PacketBatch

ACTION_NONE = 0
ACTION_NPB = 1
ACTION_PCAP = 2
ACTION_DROP = 3


def parse_cidr(cidr: str) -> tuple[int, int]:
    """'10.0.0.0/8' → (u32 net, prefix_len). '0.0.0.0/0' matches any."""
    ip, _, plen = cidr.partition("/")
    parts = [int(x) for x in ip.split(".")]
    net = (parts[0] << 24) | (parts[1] << 16) | (parts[2] << 8) | parts[3]
    return net, int(plen or 32)


@dataclasses.dataclass(frozen=True)
class Acl:
    """One ACL entry (reference: trisolaris-pushed FlowAcl). IPv4 CIDRs;
    prefix 0 means any address (and also matches IPv6 packets — "any"
    is address-family agnostic, everything narrower is v4-only)."""

    id: int
    action: int = ACTION_NONE
    src: str = "0.0.0.0/0"
    dst: str = "0.0.0.0/0"
    src_ports: tuple | None = None  # (lo, hi) inclusive
    dst_ports: tuple | None = None
    protocol: int = 0  # 0 = any IP protocol
    symmetric: bool = True  # match the reverse direction too


class PolicyLabeler:
    def __init__(self, acls: list[Acl]):
        self.acls = list(acls)
        n = len(self.acls)
        self._ids = np.asarray([a.id for a in self.acls], np.uint32)
        self._actions = np.asarray([a.action for a in self.acls], np.uint32)
        self._proto = np.asarray([a.protocol for a in self.acls], np.uint32)
        self._sym = np.asarray([a.symmetric for a in self.acls], bool)

        def nets(field):
            net = np.zeros(n, np.uint32)
            mask = np.zeros(n, np.uint32)
            for i, a in enumerate(self.acls):
                v, plen = parse_cidr(getattr(a, field))
                net[i] = v
                mask[i] = ((0xFFFFFFFF << (32 - plen)) & 0xFFFFFFFF) if plen else 0
            return net & mask, mask

        self._src_net, self._src_mask = nets("src")
        self._dst_net, self._dst_mask = nets("dst")

        def ports(field):
            lo = np.zeros(n, np.uint32)
            hi = np.full(n, 65535, np.uint32)
            for i, a in enumerate(self.acls):
                r = getattr(a, field)
                if r is not None:
                    lo[i], hi[i] = r
            return lo, hi

        self._sp_lo, self._sp_hi = ports("src_ports")
        self._dp_lo, self._dp_hi = ports("dst_ports")
        self.counters = {"matched": 0, "dropped": 0, "pcap": 0, "npb": 0}

    def match(self, p: PacketBatch) -> tuple[np.ndarray, np.ndarray]:
        """→ (acl_id[N] u32, action[N] u32); 0/NONE where nothing hits.
        One broadcast pass: [A, 1] ACL columns against [N] packet rows.
        """
        if not self.acls:
            z = np.zeros(p.size, np.uint32)
            return z, z
        ip_s = p.ip_src[:, 3].astype(np.uint32)[None, :]  # [1, N]
        ip_d = p.ip_dst[:, 3].astype(np.uint32)[None, :]
        v4 = (p.is_ipv6 == 0)[None, :]
        sp = p.port_src[None, :]
        dp = p.port_dst[None, :]

        src_net = self._src_net[:, None]
        src_mask = self._src_mask[:, None]
        dst_net = self._dst_net[:, None]
        dst_mask = self._dst_mask[:, None]

        def side(ip, net, mask):
            # mask 0 ("any") also admits IPv6; narrower CIDRs are v4-only
            return ((ip & mask) == net) & (v4 | (mask == 0))

        proto_ok = (self._proto[:, None] == 0) | (
            self._proto[:, None] == p.protocol[None, :]
        )
        fwd = (
            side(ip_s, src_net, src_mask)
            & side(ip_d, dst_net, dst_mask)
            & (sp >= self._sp_lo[:, None]) & (sp <= self._sp_hi[:, None])
            & (dp >= self._dp_lo[:, None]) & (dp <= self._dp_hi[:, None])
        )
        rev = (
            side(ip_d, src_net, src_mask)
            & side(ip_s, dst_net, dst_mask)
            & (dp >= self._sp_lo[:, None]) & (dp <= self._sp_hi[:, None])
            & (sp >= self._dp_lo[:, None]) & (sp <= self._dp_hi[:, None])
        )
        hits = proto_ok & (fwd | (rev & self._sym[:, None]))  # [A, N]
        hits &= p.valid[None, :]

        any_hit = hits.any(axis=0)
        first = np.argmax(hits, axis=0)  # lowest ACL index = priority
        acl_id = np.where(any_hit, self._ids[first], 0).astype(np.uint32)
        action = np.where(any_hit, self._actions[first], 0).astype(np.uint32)

        self.counters["matched"] += int(any_hit.sum())
        self.counters["dropped"] += int((action == ACTION_DROP).sum())
        self.counters["pcap"] += int((action == ACTION_PCAP).sum())
        self.counters["npb"] += int((action == ACTION_NPB).sum())
        return acl_id, action


def pcap_frames(buf: np.ndarray, p: PacketBatch, idx: np.ndarray,
                acl_id: np.ndarray) -> list[bytes]:
    """Policy-PCAP packets → the pcap plane's binary frame layout
    ([flow_id u64 BE][ts_us u64 BE][len u32 BE][bytes] — must match
    server/events.py _pcap's `>QQI`). flow_id carries the ACL id so the
    pcap table records which policy fired."""
    out = []
    for i in idx:
        i = int(i)
        ln = min(int(p.packet_len[i]), buf.shape[1])
        ts = int(p.timestamp_s[i]) * 1_000_000 + int(p.timestamp_us[i])
        pkt = buf[i, :ln].tobytes()
        out.append(struct.pack(">QQI", int(acl_id[i]), ts, len(pkt)) + pkt)
    return out
