"""XLA step-cost census — per-callable × bucket-shape attribution
(ISSUE 12, layer 2).

Rides `utils/spans.JitCacheMonitor`: the monitor already knows when the
fused step compiled; the census remembers WHAT compiled — the abstract
arg shapes (jax.ShapeDtypeStruct, a few hundred bytes per bucket, never
the live buffers) and the measured first-dispatch wall time — and can
later answer, per (step, bucket):

  * `cost_analysis()`    — flops + bytes accessed per dispatch,
  * `memory_analysis()`  — peak temp / argument / output bytes,
  * compile wall time    — the warmup tax a new bucket shape pays.

Capture is FREE on the hot path: observing a bucket stores shapes only
(no fetch, no compile); the expensive `fn.lower(shapes).compile()`
analysis runs lazily at `snapshot(analyze=True)` — the REST
`/v1/profile/device` pull, `dfctl profile device`, the bench embed —
and is cached per entry. On jax builds whose AOT path cannot analyze a
step (or for a GC'd callable), the entry degrades to shapes + compile
wall time with an `analysis_error` note instead of raising — the
profile surface must never take down the server.

Next on-chip session: PERF.md §21 reserves columns for these numbers —
per-bucket flops/bytes make the fused step's arithmetic intensity (and
therefore which window lever to pull next) a lookup, not a guess.
"""

from __future__ import annotations

import threading
import weakref


def _abstract(tree):
    """Pytree of live args → pytree of ShapeDtypeStructs (metadata
    only: holding the struct keeps no device buffer alive)."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, "shape") and hasattr(x, "dtype")
        else x,
        tree,
    )


class _Entry:
    __slots__ = ("service", "step", "bucket", "fn_ref", "abstract_args",
                 "compiles", "compile_wall_s", "first_dispatch_s",
                 "analysis", "analysis_error", "sorts", "sorts_error")

    def __init__(self, service, step, bucket, fn, abstract_args):
        self.service = service
        self.step = step
        self.bucket = bucket
        self.fn_ref = weakref.ref(fn) if fn is not None else None
        self.abstract_args = abstract_args
        self.compiles = 0
        self.compile_wall_s = 0.0
        self.first_dispatch_s = 0.0
        self.analysis: dict | None = None
        self.analysis_error: str | None = None
        self.sorts: int | None = None
        self.sorts_error: str | None = None


#: the headline cost_analysis keys (XLA also emits per-operand
#: `bytes_accessed<N>{}` / `utilization<N>{}` rows — noise for a
#: per-step census; the totals are what PERF.md §21 tabulates)
_COST_KEYS = ("flops", "bytes accessed", "transcendentals",
              "optimal_seconds")


def _flatten_cost(cost) -> dict:
    """Normalize XLA cost_analysis output across jax versions: a dict
    (new) or a one-element list of dicts (old); keys carry spaces
    ('bytes accessed'). Only the headline totals are kept."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    cost = dict(cost)
    out = {}
    for k in _COST_KEYS:
        if k in cost:
            try:
                out[k.replace(" ", "_")] = float(cost[k])
            except (TypeError, ValueError):
                continue
    return out


def _count_sort_eqns(jaxpr) -> int:
    """Recursively count `sort` primitive equations through every
    sub-jaxpr (pjit bodies, cond branches, scan/while bodies, custom
    call wrappers) — the static sorts-per-dispatch attribution of
    ISSUE 17. Conditional branches each count: the census reports the
    sorts a dispatch CAN pay, which is what the one-pass gate bounds."""
    import jax

    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "sort":
            total += 1
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                total += _count_sort_eqns(sub)
    return total


def _sub_jaxprs(v):
    """Yield every Jaxpr held by one eqn param value (handles Jaxpr,
    ClosedJaxpr, and lists/tuples of either)."""
    from jax.core import Jaxpr

    if isinstance(v, Jaxpr):
        yield v
    elif hasattr(v, "jaxpr") and isinstance(getattr(v, "jaxpr"), Jaxpr):
        yield v.jaxpr  # ClosedJaxpr
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _sub_jaxprs(x)


def _trace_sort_count(fn, abstract_args) -> int:
    """Sorts per dispatch of `fn` at the recorded bucket shapes —
    STATIC jaxpr inspection only: `jax.make_jaxpr` re-traces abstractly
    without touching the jit executable cache, so the count can ride
    the steady-state profile pull without tripping the zero-retrace or
    fetch-budget gates."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*abstract_args)
    return _count_sort_eqns(jaxpr.jaxpr)


class StepCostCensus:
    """Per-(service, step, bucket) compiled-step cost registry."""

    def __init__(self):
        self._entries: dict[tuple, _Entry] = {}
        self._lock = threading.Lock()

    # -- capture (hot path: metadata only) ------------------------------
    def seen(self, service: str, step: str, bucket: int) -> bool:
        """True when the bucket is recorded AND its callable is still
        alive — a dead ref (the previous same-shaped pipeline was
        collected) reports unseen so the caller re-observes and the
        entry re-points to the live step (observe() handles it)."""
        e = self._entries.get((service, step, int(bucket)))
        return e is not None and (e.fn_ref is None or e.fn_ref() is not None)

    def observe(self, service: str, step: str, bucket: int, fn, args) -> None:
        """Record one bucket shape the first time it dispatches: the
        callable (weak) + abstract arg shapes. Idempotent; no compile,
        no transfer. A restarted pipeline with the same (service, step,
        bucket) re-points a dead callable ref (compile counts keep
        accumulating — recompiles across restarts are real cost)."""
        key = (service, step, int(bucket))
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                if e.fn_ref is not None and e.fn_ref() is None:
                    e.fn_ref = weakref.ref(fn) if fn is not None else None
                    e.abstract_args = _abstract(args)
                    e.analysis = None
                    e.analysis_error = None
                return
            self._entries[key] = _Entry(service, step, int(bucket), fn,
                                        _abstract(args))

    def note_compile(self, service: str, step: str, bucket: int,
                     wall_s: float) -> None:
        """Attribute a measured compile (the JitCacheMonitor detected
        cache growth on this dispatch) to its bucket. `wall_s` is the
        first-dispatch wall time — compile + first execute, the real
        warmup tax a new shape pays."""
        key = (service, step, int(bucket))
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return
            e.compiles += 1
            e.compile_wall_s += float(wall_s)
            if e.first_dispatch_s == 0.0:
                e.first_dispatch_s = float(wall_s)

    # -- analysis (pull path: may compile) ------------------------------
    def _analyze(self, e: _Entry) -> None:
        if e.analysis is not None or e.analysis_error is not None:
            return
        fn = e.fn_ref() if e.fn_ref is not None else None
        if fn is None:
            e.analysis_error = "callable collected"
            return
        try:
            compiled = fn.lower(*e.abstract_args).compile()
            ana: dict = {}
            try:
                ana.update(_flatten_cost(compiled.cost_analysis()))
            except Exception as err:  # pragma: no cover - backend-dependent
                ana["cost_error"] = repr(err)
            try:
                mem = compiled.memory_analysis()
                for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                             "output_size_in_bytes", "alias_size_in_bytes",
                             "generated_code_size_in_bytes"):
                    v = getattr(mem, attr, None)
                    if v is not None:
                        ana[attr] = int(v)
            except Exception as err:  # pragma: no cover - backend-dependent
                ana["memory_error"] = repr(err)
            e.analysis = ana
        except Exception as err:
            e.analysis_error = repr(err)

    def _count_sorts(self, e: _Entry) -> None:
        """Lazy per-entry sorts/dispatch attribution (ISSUE 17): a pure
        abstract re-trace, cached after the first pull. No compile, no
        fetch — cheap enough for the default (analyze=False) snapshot
        that telemetry()["profile"] and the bench JSON embeds read."""
        if e.sorts is not None or e.sorts_error is not None:
            return
        fn = e.fn_ref() if e.fn_ref is not None else None
        if fn is None:
            e.sorts_error = "callable collected"
            return
        try:
            e.sorts = _trace_sort_count(fn, e.abstract_args)
        except Exception as err:
            e.sorts_error = repr(err)

    def snapshot(self, *, analyze: bool = False) -> list[dict]:
        """One JSON-able row per (service, step, bucket). With
        `analyze=True` each entry's compiled-module analyses are
        computed (cached after the first pull) — this may COMPILE the
        step for its recorded shapes via the AOT path, so it belongs on
        the profile pull, never inside ingest. The `sorts` column
        (sorts per dispatch, static jaxpr count) is computed on every
        pull — trace-only, cached, fetch-free."""
        with self._lock:
            entries = list(self._entries.values())
        rows = []
        for e in sorted(entries, key=lambda e: (e.service, e.step, e.bucket)):
            if analyze:
                self._analyze(e)
            self._count_sorts(e)
            row = {
                "service": e.service,
                "step": e.step,
                "bucket": e.bucket,
                "compiles": e.compiles,
                "compile_wall_s": round(e.compile_wall_s, 4),
                "first_dispatch_s": round(e.first_dispatch_s, 4),
            }
            if e.sorts is not None:
                row["sorts"] = e.sorts
            if e.sorts_error is not None:
                row["sorts_error"] = e.sorts_error
            if e.analysis is not None:
                row.update(e.analysis)
            if e.analysis_error is not None:
                row["analysis_error"] = e.analysis_error
            rows.append(row)
        return rows

    def get_counters(self) -> dict[str, int | float]:
        """Countable face — cheap scalars only (no analysis): entry and
        compile counts plus the cumulative compile wall time, so compile
        pressure is queryable from deepflow_system."""
        with self._lock:
            entries = list(self._entries.values())
        return {
            "entries": len(entries),
            "compiles": sum(e.compiles for e in entries),
            "compile_wall_ms": int(
                sum(e.compile_wall_s for e in entries) * 1e3
            ),
        }


#: process-wide default census (the REST / dfctl surface reads it);
#: registered as a Countable so compile pressure dogfoods too
default_census = StepCostCensus()

from ..utils.stats import register_countable  # noqa: E402

register_countable("tpu_step_census", default_census)
