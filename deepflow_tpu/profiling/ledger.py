"""Device memory ledger — per-plane HBM accounting for every
device-resident buffer the pipeline owns (ISSUE 12, layer 1).

The reference's fourth pillar (continuous profiling) covers the host;
the device — where every hot-path byte lives — was unobserved. The
ROADMAP's disaggregated-sketch-memory item ("cardinality density per
HBM byte") cannot even be scoped without knowing how many bytes each
plane holds per chip. This module is that ledger:

  * **Profilable** — a registration protocol: a component exposes
    `device_planes() -> {plane_name: pytree-of-device-arrays}`. The
    window managers, pipelines and the feeder sink implement it,
    enumerating every plane they own: stash, accumulator ring, counter
    ring + gate state, per-tier sketch slabs, cascade tier stashes/
    rings, staged upload buffers, CB lane vectors.
  * **DeviceMemoryLedger** — holds Profilables WEAKLY (the r13
    cascade-tier-registry stance: a torn-down pipeline leaves the
    ledger; `close()` deregisters eagerly) and snapshots per-plane
    bytes + high watermarks on demand. ZERO device fetches: `.nbytes`
    on a jax Array is shape×dtype metadata — no transfer, so the
    ledger is safe to sample from a ticking collector thread and from
    the REST pull path.
  * **Countable face** — the default ledger registers on the default
    StatsCollector as module `tpu_hbm`, so `tpu_hbm_sketch_bytes`,
    `tpu_hbm_stash_bytes`, … dogfood into `deepflow_system` and answer
    via SQL AND PromQL like every other lane (the acceptance pin).

Reconciliation contract (tests/test_profiling.py): Σ per-plane ledger
bytes == the summed `.nbytes` of exactly the pipeline-owned device
arrays, each of which is present in `jax.live_arrays()` — the ledger
never invents or misses an owned buffer, single-chip AND sharded, with
the sketch plane and cascade enabled.
"""

from __future__ import annotations

import threading
import weakref
from typing import Mapping, Protocol, runtime_checkable

#: canonical plane vocabulary (components may add ad-hoc names; docs
#: and the reconciliation test pin this set)
PLANE_STASH = "stash"
PLANE_ACCUMULATOR = "accumulator"
PLANE_STATS_RING = "stats_ring"
PLANE_SKETCH = "sketch"
# pooled sketch memory (ISSUE 20): with SketchConfig.pool set, the
# single "sketch" plane splits four ways — compact pool arenas, wide
# pool arenas, the closed-block pending ring, and routing/meta scalars —
# so HBM density (bytes per unit cardinality capacity) is attributable
# per pool, not per slab
PLANE_SKETCH_POOL_HOT = "sketch_pool_hot"
PLANE_SKETCH_POOL_WIDE = "sketch_pool_wide"
PLANE_SKETCH_PENDING = "sketch_pending"
PLANE_SKETCH_META = "sketch_meta"
PLANE_CASCADE = "cascade"
PLANE_LANES = "lanes"  # small CB lane vectors (fold_rows, casc, snap)
PLANE_STAGED = "staged"  # feeder double-buffer upload (StagedBatch)
PLANE_CHECKPOINT = "checkpoint_scratch"  # transient pack buffers (HWM only)


@runtime_checkable
class Profilable(Protocol):
    def device_planes(self) -> Mapping[str, object]: ...


def _leaf_arrays(tree) -> list:
    """Flatten a pytree-ish value into its device-array leaves without
    importing jax at module import time. Accepts arrays, None, lists/
    tuples/dicts, and registered dataclass pytrees (StashState &co)."""
    import jax

    return [
        leaf
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "nbytes") and hasattr(leaf, "dtype")
    ]


def plane_bytes(tree) -> tuple[int, int]:
    """(bytes, array_count) for one plane — metadata only, no transfer.
    Leaves are deduplicated by identity so a buffer shared between two
    entries of the same plane never double-counts."""
    seen: dict[int, int] = {}
    for leaf in _leaf_arrays(tree):
        seen[id(leaf)] = int(leaf.nbytes)
    return sum(seen.values()), len(seen)


class _Source:
    __slots__ = ("module", "tags", "devices", "_ref")

    def __init__(self, module: str, tags: dict, devices: int, profilable):
        self.module = module
        self.tags = tuple(sorted(tags.items()))
        self.devices = max(1, int(devices))
        self._ref = weakref.ref(profilable)

    def owner(self):
        return self._ref()


class DeviceMemoryLedger:
    """Weakly-held Profilable registry + per-plane byte accounting."""

    def __init__(self, name: str = "hbm"):
        self.name = name
        self._sources: list[_Source] = []
        self._lock = threading.Lock()
        # (module, tags, plane) -> high watermark bytes, surviving the
        # owner (a restarted pipeline's peak stays visible until reset)
        self._hwm: dict[tuple, int] = {}
        # transient planes (checkpoint pack scratch): bytes=0 steady,
        # only the watermark is meaningful
        self._transient_hwm: dict[str, int] = {}
        self.seq = 0  # bumped per snapshot/sample — ProfileSnapshot clock
        self.snapshots = 0

    # -- registry -------------------------------------------------------
    def register(self, module: str, profilable: Profilable, *,
                 devices: int = 1, **tags: str) -> _Source:
        src = _Source(module, tags, devices, profilable)
        with self._lock:
            self._sources = [s for s in self._sources if s.owner() is not None]
            self._sources.append(src)
        return src

    def deregister(self, src: _Source) -> None:
        with self._lock:
            if src in self._sources:
                self._sources.remove(src)

    def note_transient(self, plane: str, nbytes: int) -> None:
        """Record a short-lived scratch allocation (checkpoint pack
        buffers) — steady-state bytes stay 0, the watermark shows the
        peak the plane ever needed."""
        with self._lock:
            if nbytes > self._transient_hwm.get(plane, 0):
                self._transient_hwm[plane] = int(nbytes)

    # -- read faces -----------------------------------------------------
    def snapshot(self) -> list[dict]:
        """One row per (owner, plane): bytes, bytes/device, arrays,
        high watermark. Walks live owners only (dead weakrefs pruned);
        zero device fetches."""
        with self._lock:
            sources = list(self._sources)
        rows: list[dict] = []
        dead: list[_Source] = []
        for src in sources:
            owner = src.owner()
            if owner is None:
                dead.append(src)
                continue
            try:
                planes = owner.device_planes()
            except Exception:  # a torn-down owner must not kill the walk
                continue
            for plane, tree in sorted(planes.items()):
                nbytes, n_arrays = plane_bytes(tree)
                key = (src.module, src.tags, plane)
                with self._lock:
                    hwm = self._hwm[key] = max(self._hwm.get(key, 0), nbytes)
                rows.append({
                    "module": src.module,
                    "tags": dict(src.tags),
                    "plane": plane,
                    "bytes": nbytes,
                    "bytes_per_device": nbytes // src.devices,
                    "devices": src.devices,
                    "arrays": n_arrays,
                    "bytes_hwm": hwm,
                })
        with self._lock:
            if dead:
                self._sources = [s for s in self._sources if s not in dead]
            for plane, hwm in sorted(self._transient_hwm.items()):
                rows.append({
                    "module": "transient", "tags": {}, "plane": plane,
                    "bytes": 0, "bytes_per_device": 0, "devices": 1,
                    "arrays": 0, "bytes_hwm": hwm,
                })
            self.seq += 1
            self.snapshots += 1
        return rows

    def get_counters(self) -> dict[str, int]:
        """Countable face: per-plane byte totals summed across owners —
        `sketch_bytes` under module `tpu_hbm` becomes the
        `tpu_hbm_sketch_bytes` metric in deepflow_system (SQL + PromQL,
        the acceptance pin). Fetch-free like every Countable."""
        rows = self.snapshot()
        out: dict[str, int] = {}
        total = 0
        for r in rows:
            out[f"{r['plane']}_bytes"] = (
                out.get(f"{r['plane']}_bytes", 0) + r["bytes"]
            )
            hk = f"{r['plane']}_bytes_hwm"
            out[hk] = max(out.get(hk, 0), r["bytes_hwm"])
            total += r["bytes"]
        out["total_bytes"] = total
        out["planes"] = len({r["plane"] for r in rows})
        out["sources"] = len(self._sources)
        out["snapshots"] = self.snapshots
        return out


#: process-wide default ledger, mirroring utils/stats.default_collector;
#: registered there as module `tpu_hbm` so the dogfood loop closes with
#: no further wiring (an empty ledger emits no fields → no rows)
default_ledger = DeviceMemoryLedger()

from ..utils.stats import register_countable  # noqa: E402

register_countable("tpu_hbm", default_ledger)


def register_profilable(module: str, profilable: Profilable, *,
                        devices: int = 1, ledger: DeviceMemoryLedger | None = None,
                        **tags: str) -> _Source:
    """Register a component's device planes on the (default) ledger —
    the RegisterCountable twin for HBM accounting."""
    led = default_ledger if ledger is None else ledger
    return led.register(module, profilable, devices=devices, **tags)


def profile_tick_sink(bus, *, ledger: DeviceMemoryLedger | None = None,
                      db: str = "deepflow_system",
                      table: str = "deepflow_system"):
    """→ a StatsCollector sink publishing a `ProfileSnapshot` event on
    `bus` at each collector tick (ISSUE 12): the moment profiling rows
    land in deepflow_system, standing queries / span-latency alert
    rules over it re-evaluate — the push plane observing the profiler
    observing the pipeline. Sink-only (never fires on pull-path
    `sample()` reads, so dashboard pulls don't publish)."""
    led = default_ledger if ledger is None else ledger

    def sink(points) -> None:
        if not points or bus is None:
            return
        from ..querier.events import ProfileSnapshot

        with led._lock:
            led.seq += 1
            seq = led.seq
        # the event clock is the tick's own sample timestamp — the time
        # column the rows landed under — so rule evaluations run at
        # data time (deterministic under replay), never the wall
        t = max(int(p.timestamp) for p in points)
        bus.publish(ProfileSnapshot(db, table, seq, t))

    return sink
