"""Device profiling plane (ISSUE 12) — always-on, zero new device
fetches. Three layers:

  * `ledger` — DeviceMemoryLedger: per-plane HBM byte accounting over
    weakly-registered Profilables (`tpu_hbm_*` in deepflow_system);
  * `census` — StepCostCensus: per jitted-callable × bucket-shape XLA
    cost/memory analysis + compile wall time (`/v1/profile/device`);
  * span latency distributions live in `utils/spans` (per-stage
    log-histograms → p50/p95/p99 lanes), not here — the tracer predates
    this package and every host component already carries one.
"""

from .census import StepCostCensus, default_census
from .ledger import (
    PLANE_ACCUMULATOR,
    PLANE_CASCADE,
    PLANE_CHECKPOINT,
    PLANE_LANES,
    PLANE_SKETCH,
    PLANE_STAGED,
    PLANE_STASH,
    PLANE_STATS_RING,
    DeviceMemoryLedger,
    Profilable,
    default_ledger,
    plane_bytes,
    profile_tick_sink,
    register_profilable,
)

__all__ = [
    "DeviceMemoryLedger",
    "Profilable",
    "StepCostCensus",
    "default_census",
    "default_ledger",
    "plane_bytes",
    "profile_tick_sink",
    "register_profilable",
    "PLANE_STASH",
    "PLANE_ACCUMULATOR",
    "PLANE_STATS_RING",
    "PLANE_SKETCH",
    "PLANE_CASCADE",
    "PLANE_LANES",
    "PLANE_STAGED",
    "PLANE_CHECKPOINT",
]
