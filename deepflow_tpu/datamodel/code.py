"""Tag-code and enum model.

Mirrors the semantics of the reference's metric document model
(/root/reference/agent/src/metric/document.rs:124-312 — Code bitflags,
Direction, TapSide, DocumentFlag) and the server twin
(/root/reference/server/libs/flow-metrics/tag.go:38-98). Values are kept
bit-compatible so wire encodings and test fixtures are directly comparable
with the reference; the *representation* here is plain Python enums feeding
integer columns, not struct fields.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Mapping


class Code(enum.IntFlag):
    """Tag-combination bitflags (document.rs:124-151).

    A document's Code says which tag fields are populated; each metrics
    table is a fixed Code combination (tag.go:497-520).
    """

    NONE = 0

    IP = 1 << 0
    L3_EPC_ID = 1 << 1
    MAC = 1 << 11
    GPID = 1 << 15

    IP_PATH = 1 << 20
    L3_EPC_PATH = 1 << 21
    MAC_PATH = 1 << 31
    GPID_PATH = 1 << 35

    DIRECTION = 1 << 40
    ACL_GID = 1 << 41
    PROTOCOL = 1 << 42
    SERVER_PORT = 1 << 43
    TAP_TYPE = 1 << 45
    VTAP_ID = 1 << 47
    TAP_SIDE = 1 << 48
    TAP_PORT = 1 << 49
    L7_PROTOCOL = 1 << 51

    TUNNEL_IP_ID = 1 << 62

    def has_edge_tag(self) -> bool:
        # document.rs:154-156: any *_PATH bit set.
        return bool(int(self) & 0xFFFFF00000)


# The stash only ever sees a handful of Code combinations
# (collector.rs:156-194). We assign each a small dense id — this is the
# `CodeID` packed into the reference's fast_id — and use it as a key column.
class CodeId(enum.IntEnum):
    NONE = 0
    SINGLE_IP_PORT = 1
    SINGLE_MAC_IP_PORT = 2
    SINGLE_MAC_IP_PORT_APP = 3
    SINGLE_IP_PORT_APP = 4
    EDGE_IP_PORT = 5
    EDGE_MAC_IP_PORT = 6
    EDGE_IP_PORT_APP = 7
    EDGE_MAC_IP_PORT_APP = 8
    ACL = 9


_SINGLE_IP = Code.IP | Code.L3_EPC_ID | Code.GPID | Code.VTAP_ID | Code.PROTOCOL | Code.DIRECTION | Code.TAP_TYPE
_EDGE_IP = (
    Code.IP_PATH
    | Code.L3_EPC_PATH
    | Code.GPID_PATH
    | Code.VTAP_ID
    | Code.PROTOCOL
    | Code.DIRECTION
    | Code.TAP_TYPE
    | Code.TAP_PORT
)

CODE_OF_ID: dict[CodeId, Code] = {
    CodeId.NONE: Code.NONE,
    CodeId.SINGLE_IP_PORT: _SINGLE_IP | Code.SERVER_PORT,
    CodeId.SINGLE_MAC_IP_PORT: _SINGLE_IP | Code.MAC | Code.SERVER_PORT,
    CodeId.SINGLE_MAC_IP_PORT_APP: _SINGLE_IP | Code.MAC | Code.SERVER_PORT | Code.L7_PROTOCOL,
    CodeId.SINGLE_IP_PORT_APP: _SINGLE_IP | Code.SERVER_PORT | Code.L7_PROTOCOL,
    CodeId.EDGE_IP_PORT: _EDGE_IP | Code.SERVER_PORT,
    CodeId.EDGE_MAC_IP_PORT: _EDGE_IP | Code.MAC_PATH | Code.SERVER_PORT,
    CodeId.EDGE_IP_PORT_APP: _EDGE_IP | Code.SERVER_PORT | Code.L7_PROTOCOL,
    CodeId.EDGE_MAC_IP_PORT_APP: _EDGE_IP | Code.MAC_PATH | Code.SERVER_PORT | Code.L7_PROTOCOL,
    CodeId.ACL: Code.ACL_GID | Code.TUNNEL_IP_ID | Code.VTAP_ID,
}


class DocumentFlag(enum.IntFlag):
    NONE = 0  # per-minute metrics
    PER_SECOND_METRICS = 1 << 0


# Direction / TapSide bit layout (document.rs:166-239): low 3 bits are
# client/server/local, bits 3+ are the observation side.
_SIDE_NODE = 1 << 3
_SIDE_HYPERVISOR = 2 << 3
_SIDE_GATEWAY_HYPERVISOR = 3 << 3
_SIDE_GATEWAY = 4 << 3
_SIDE_PROCESS = 5 << 3
_SIDE_APP = 6 << 3

MASK_CLIENT_SERVER = 0x7
MASK_SIDE = 0xF8


class Direction(enum.IntEnum):
    NONE = 0
    CLIENT_TO_SERVER = 1 << 0
    SERVER_TO_CLIENT = 1 << 1
    LOCAL_TO_LOCAL = 1 << 2
    CLIENT_NODE_TO_SERVER = (1 << 0) | _SIDE_NODE
    SERVER_NODE_TO_CLIENT = (1 << 1) | _SIDE_NODE
    CLIENT_HYPERVISOR_TO_SERVER = (1 << 0) | _SIDE_HYPERVISOR
    SERVER_HYPERVISOR_TO_CLIENT = (1 << 1) | _SIDE_HYPERVISOR
    CLIENT_GATEWAY_HYPERVISOR_TO_SERVER = (1 << 0) | _SIDE_GATEWAY_HYPERVISOR
    SERVER_GATEWAY_HYPERVISOR_TO_CLIENT = (1 << 1) | _SIDE_GATEWAY_HYPERVISOR
    CLIENT_GATEWAY_TO_SERVER = (1 << 0) | _SIDE_GATEWAY
    SERVER_GATEWAY_TO_CLIENT = (1 << 1) | _SIDE_GATEWAY
    CLIENT_PROCESS_TO_SERVER = (1 << 0) | _SIDE_PROCESS
    SERVER_PROCESS_TO_CLIENT = (1 << 1) | _SIDE_PROCESS
    CLIENT_APP_TO_SERVER = (1 << 0) | _SIDE_APP
    SERVER_APP_TO_CLIENT = (1 << 1) | _SIDE_APP
    APP = _SIDE_APP

    def is_client_to_server(self) -> bool:
        return (self & MASK_CLIENT_SERVER) == Direction.CLIENT_TO_SERVER

    def is_server_to_client(self) -> bool:
        return (self & MASK_CLIENT_SERVER) == Direction.SERVER_TO_CLIENT


class TapSide(enum.IntEnum):
    REST = 0
    CLIENT = 1 << 0
    SERVER = 1 << 1
    LOCAL = 1 << 2
    CLIENT_NODE = (1 << 0) | _SIDE_NODE
    SERVER_NODE = (1 << 1) | _SIDE_NODE
    CLIENT_HYPERVISOR = (1 << 0) | _SIDE_HYPERVISOR
    SERVER_HYPERVISOR = (1 << 1) | _SIDE_HYPERVISOR
    CLIENT_GATEWAY_HYPERVISOR = (1 << 0) | _SIDE_GATEWAY_HYPERVISOR
    SERVER_GATEWAY_HYPERVISOR = (1 << 1) | _SIDE_GATEWAY_HYPERVISOR
    CLIENT_GATEWAY = (1 << 0) | _SIDE_GATEWAY
    SERVER_GATEWAY = (1 << 1) | _SIDE_GATEWAY
    CLIENT_PROCESS = (1 << 0) | _SIDE_PROCESS
    SERVER_PROCESS = (1 << 1) | _SIDE_PROCESS
    CLIENT_APP = (1 << 0) | _SIDE_APP
    SERVER_APP = (1 << 1) | _SIDE_APP
    APP = _SIDE_APP

    @staticmethod
    def from_direction(direction: "Direction") -> "TapSide":
        # document.rs:243-264 — TapSide is Direction with the direction
        # bit kept and NONE → REST.
        if direction == Direction.NONE:
            return TapSide.REST
        return TapSide(int(direction))


class SignalSource(enum.IntEnum):
    # agent/src/common/lookup_key.rs / flow.rs SignalSource
    PACKET = 0
    XFLOW = 1
    EBPF = 3
    OTEL = 4


class MeterId(enum.IntEnum):
    # meter.rs:23-25 — protobuf meter_id discriminants.
    FLOW = 1
    USAGE = 4
    APP = 5


# ---------------------------------------------------------------------------
# Packed tag words — the fingerprint's dense key representation.
#
# The group-by fingerprint used to murmur-fold every raw tag column
# (25-37 u32 lanes × 2 seeds); most of those columns carry far fewer
# than 32 meaningful bits (flags, enums, ports, i16 EPC ids). These
# helpers bin-pack the narrow columns into full u32 words once, so the
# fold runs over ~22 words instead of ~37 (PERF.md §9d). Packing is
# injective for in-range values: each field gets a disjoint bit span.
# Values wider than their declared span would alias, so the excess bits
# (value >> width) are rotated per-field and XOR-folded into one extra
# word — in-range inputs leave it all-zero, out-of-range inputs still
# perturb the hash instead of silently colliding.
#
# Widths are CONTRACTS: the decoders (ingest/codec.py, agent/packet.py)
# and the fanout stage produce values within them. Widening a field is
# a one-line change here; the excess word keeps even a violated
# contract collision-safe (astronomically unlikely structured collision
# instead of a guaranteed one).

# FlowBatch.FLOW_RECORD_TAG_FIELDS → bit width (pre-fanout raw records).
RAW_TAG_WIDTHS: dict[str, int] = {
    "timestamp": 32,
    "global_thread_id": 16,
    "agent_id": 16,
    "signal_source": 8,
    "is_ipv6": 1,
    "ip0_w0": 32, "ip0_w1": 32, "ip0_w2": 32, "ip0_w3": 32,
    "ip1_w0": 32, "ip1_w1": 32, "ip1_w2": 32, "ip1_w3": 32,
    "mac0_hi": 16, "mac0_lo": 32,
    "mac1_hi": 16, "mac1_lo": 32,
    "l3_epc_id": 16, "l3_epc_id1": 16,  # i16 sign-folded to u16
    "gpid0": 32, "gpid1": 32,
    "pod_id": 32,
    "protocol": 8,
    "server_port": 16,
    "tap_port": 32,
    "tap_type": 8,
    "l7_protocol": 8,
    "direction0": 8, "direction1": 8,  # Direction bit patterns ≤ 0x3f
    "is_active_host0": 1, "is_active_host1": 1,
    "is_vip0": 1, "is_vip1": 1,
    "is_active_service": 1,
    "endpoint_hash": 32,
    "biz_type": 8,
    "time_span": 32,
}

# TAG_SCHEMA key columns (post-fanout doc rows) → bit width.
DOC_KEY_WIDTHS: dict[str, int] = {
    "code_id": 4,  # dense CodeId ≤ 9
    "meter_id": 4,  # MeterId ≤ 5
    "global_thread_id": 16,
    "agent_id": 16,
    "is_ipv6": 1,
    "ip0_w0": 32, "ip0_w1": 32, "ip0_w2": 32, "ip0_w3": 32,
    "ip1_w0": 32, "ip1_w1": 32, "ip1_w2": 32, "ip1_w3": 32,
    "l3_epc_id": 16, "l3_epc_id1": 16,
    "mac0_hi": 16, "mac0_lo": 32,
    "mac1_hi": 16, "mac1_lo": 32,
    "direction": 8,
    "protocol": 8,
    "acl_gid": 16,
    "server_port": 16,
    "tap_port": 32,
    "tap_type": 8,
    "l7_protocol": 8,
    "gpid0": 32, "gpid1": 32,
    "endpoint_hash": 32,
    "time_span": 32,
    "biz_type": 8,
    "signal_source": 8,
}


@dataclasses.dataclass(frozen=True)
class TagPackPlan:
    """Static packing layout: `wide` columns pass through verbatim;
    each `packed` word is a tuple of (field, shift, width) spans."""

    wide: tuple[str, ...]
    packed: tuple[tuple[tuple[str, int, int], ...], ...]

    @property
    def num_words(self) -> int:
        # +1 for the excess word (present whenever anything is packed)
        return len(self.wide) + len(self.packed) + (1 if self.packed else 0)

    def field_names(self) -> tuple[str, ...]:
        return self.wide + tuple(f for w in self.packed for f, _, _ in w)


def plan_tag_pack(widths: Mapping[str, int]) -> TagPackPlan:
    """First-fit-decreasing bin packing of the sub-32-bit columns into
    u32 words. Deterministic for a given widths table (sorted by
    descending width then name), so device and host packers agree."""
    wide = tuple(sorted(f for f, w in widths.items() if w >= 32))
    narrow = sorted(
        ((w, f) for f, w in widths.items() if w < 32), key=lambda t: (-t[0], t[1])
    )
    bins: list[list[tuple[str, int, int]]] = []
    fill: list[int] = []
    for w, f in narrow:
        for i, used in enumerate(fill):
            if used + w <= 32:
                bins[i].append((f, used, w))
                fill[i] += w
                break
        else:
            bins.append([(f, 0, w)])
            fill.append(w)
    return TagPackPlan(wide=wide, packed=tuple(tuple(b) for b in bins))


RAW_TAG_PACK = plan_tag_pack(RAW_TAG_WIDTHS)
DOC_KEY_PACK = plan_tag_pack(DOC_KEY_WIDTHS)


def pack_tag_words(cols: Mapping, plan: TagPackPlan, xp):
    """Build the packed u32 word list from named [N] u32 columns.

    `cols` maps field name → array; `xp` is the array namespace (jnp on
    device, np in the oracle) — both implement wrapping u32 arithmetic.
    Returns wide words + packed words + the excess word (see module
    note). Safe under jit: the plan is static, so this unrolls to pure
    vector ops.
    """
    words = [xp.asarray(cols[f], dtype=xp.uint32) for f in plan.wide]
    excess = None
    rot = 1
    for spans in plan.packed:
        word = None
        for f, shift, width in spans:
            c = xp.asarray(cols[f], dtype=xp.uint32)
            part = c & xp.uint32((1 << width) - 1)
            if shift:
                part = part << xp.uint32(shift)
            word = part if word is None else (word | part)
            e = c >> xp.uint32(width)
            e = (e << xp.uint32(rot)) | (e >> xp.uint32(32 - rot))
            excess = e if excess is None else (excess ^ e)
            # period-31 walk (gcd(7,31)=1) keeps every field's rotation
            # distinct for plans up to 31 narrow fields — a shared
            # rotation would let two out-of-contract tuples cancel in
            # the XOR and collide deterministically
            rot = (rot + 7) % 31 + 1
        words.append(word)
    if excess is not None:
        words.append(excess)
    return words


class L7Protocol(enum.IntEnum):
    """Subset of the reference's L7Protocol registry
    (agent/crates/public/src/l7_protocol.rs). Values used as dense tag ids.
    """

    UNKNOWN = 0
    OTHER = 1
    HTTP1 = 20
    HTTP2 = 21
    DUBBO = 40
    GRPC = 41
    SOFARPC = 43
    FASTCGI = 44
    BRPC = 45
    TARS = 46
    SOME_IP = 47
    MYSQL = 60
    POSTGRESQL = 61
    ORACLE = 62
    REDIS = 80
    MONGODB = 81
    MEMCACHED = 82
    KAFKA = 100
    MQTT = 101
    AMQP = 102
    OPENWIRE = 103
    NATS = 104
    PULSAR = 105
    ZMTP = 106
    ROCKETMQ = 107
    DNS = 120
    TLS = 121
    PING = 122
    CUSTOM = 127
