"""Tag-code and enum model.

Mirrors the semantics of the reference's metric document model
(/root/reference/agent/src/metric/document.rs:124-312 — Code bitflags,
Direction, TapSide, DocumentFlag) and the server twin
(/root/reference/server/libs/flow-metrics/tag.go:38-98). Values are kept
bit-compatible so wire encodings and test fixtures are directly comparable
with the reference; the *representation* here is plain Python enums feeding
integer columns, not struct fields.
"""

from __future__ import annotations

import enum


class Code(enum.IntFlag):
    """Tag-combination bitflags (document.rs:124-151).

    A document's Code says which tag fields are populated; each metrics
    table is a fixed Code combination (tag.go:497-520).
    """

    NONE = 0

    IP = 1 << 0
    L3_EPC_ID = 1 << 1
    MAC = 1 << 11
    GPID = 1 << 15

    IP_PATH = 1 << 20
    L3_EPC_PATH = 1 << 21
    MAC_PATH = 1 << 31
    GPID_PATH = 1 << 35

    DIRECTION = 1 << 40
    ACL_GID = 1 << 41
    PROTOCOL = 1 << 42
    SERVER_PORT = 1 << 43
    TAP_TYPE = 1 << 45
    VTAP_ID = 1 << 47
    TAP_SIDE = 1 << 48
    TAP_PORT = 1 << 49
    L7_PROTOCOL = 1 << 51

    TUNNEL_IP_ID = 1 << 62

    def has_edge_tag(self) -> bool:
        # document.rs:154-156: any *_PATH bit set.
        return bool(int(self) & 0xFFFFF00000)


# The stash only ever sees a handful of Code combinations
# (collector.rs:156-194). We assign each a small dense id — this is the
# `CodeID` packed into the reference's fast_id — and use it as a key column.
class CodeId(enum.IntEnum):
    NONE = 0
    SINGLE_IP_PORT = 1
    SINGLE_MAC_IP_PORT = 2
    SINGLE_MAC_IP_PORT_APP = 3
    SINGLE_IP_PORT_APP = 4
    EDGE_IP_PORT = 5
    EDGE_MAC_IP_PORT = 6
    EDGE_IP_PORT_APP = 7
    EDGE_MAC_IP_PORT_APP = 8
    ACL = 9


_SINGLE_IP = Code.IP | Code.L3_EPC_ID | Code.GPID | Code.VTAP_ID | Code.PROTOCOL | Code.DIRECTION | Code.TAP_TYPE
_EDGE_IP = (
    Code.IP_PATH
    | Code.L3_EPC_PATH
    | Code.GPID_PATH
    | Code.VTAP_ID
    | Code.PROTOCOL
    | Code.DIRECTION
    | Code.TAP_TYPE
    | Code.TAP_PORT
)

CODE_OF_ID: dict[CodeId, Code] = {
    CodeId.NONE: Code.NONE,
    CodeId.SINGLE_IP_PORT: _SINGLE_IP | Code.SERVER_PORT,
    CodeId.SINGLE_MAC_IP_PORT: _SINGLE_IP | Code.MAC | Code.SERVER_PORT,
    CodeId.SINGLE_MAC_IP_PORT_APP: _SINGLE_IP | Code.MAC | Code.SERVER_PORT | Code.L7_PROTOCOL,
    CodeId.SINGLE_IP_PORT_APP: _SINGLE_IP | Code.SERVER_PORT | Code.L7_PROTOCOL,
    CodeId.EDGE_IP_PORT: _EDGE_IP | Code.SERVER_PORT,
    CodeId.EDGE_MAC_IP_PORT: _EDGE_IP | Code.MAC_PATH | Code.SERVER_PORT,
    CodeId.EDGE_IP_PORT_APP: _EDGE_IP | Code.SERVER_PORT | Code.L7_PROTOCOL,
    CodeId.EDGE_MAC_IP_PORT_APP: _EDGE_IP | Code.MAC_PATH | Code.SERVER_PORT | Code.L7_PROTOCOL,
    CodeId.ACL: Code.ACL_GID | Code.TUNNEL_IP_ID | Code.VTAP_ID,
}


class DocumentFlag(enum.IntFlag):
    NONE = 0  # per-minute metrics
    PER_SECOND_METRICS = 1 << 0


# Direction / TapSide bit layout (document.rs:166-239): low 3 bits are
# client/server/local, bits 3+ are the observation side.
_SIDE_NODE = 1 << 3
_SIDE_HYPERVISOR = 2 << 3
_SIDE_GATEWAY_HYPERVISOR = 3 << 3
_SIDE_GATEWAY = 4 << 3
_SIDE_PROCESS = 5 << 3
_SIDE_APP = 6 << 3

MASK_CLIENT_SERVER = 0x7
MASK_SIDE = 0xF8


class Direction(enum.IntEnum):
    NONE = 0
    CLIENT_TO_SERVER = 1 << 0
    SERVER_TO_CLIENT = 1 << 1
    LOCAL_TO_LOCAL = 1 << 2
    CLIENT_NODE_TO_SERVER = (1 << 0) | _SIDE_NODE
    SERVER_NODE_TO_CLIENT = (1 << 1) | _SIDE_NODE
    CLIENT_HYPERVISOR_TO_SERVER = (1 << 0) | _SIDE_HYPERVISOR
    SERVER_HYPERVISOR_TO_CLIENT = (1 << 1) | _SIDE_HYPERVISOR
    CLIENT_GATEWAY_HYPERVISOR_TO_SERVER = (1 << 0) | _SIDE_GATEWAY_HYPERVISOR
    SERVER_GATEWAY_HYPERVISOR_TO_CLIENT = (1 << 1) | _SIDE_GATEWAY_HYPERVISOR
    CLIENT_GATEWAY_TO_SERVER = (1 << 0) | _SIDE_GATEWAY
    SERVER_GATEWAY_TO_CLIENT = (1 << 1) | _SIDE_GATEWAY
    CLIENT_PROCESS_TO_SERVER = (1 << 0) | _SIDE_PROCESS
    SERVER_PROCESS_TO_CLIENT = (1 << 1) | _SIDE_PROCESS
    CLIENT_APP_TO_SERVER = (1 << 0) | _SIDE_APP
    SERVER_APP_TO_CLIENT = (1 << 1) | _SIDE_APP
    APP = _SIDE_APP

    def is_client_to_server(self) -> bool:
        return (self & MASK_CLIENT_SERVER) == Direction.CLIENT_TO_SERVER

    def is_server_to_client(self) -> bool:
        return (self & MASK_CLIENT_SERVER) == Direction.SERVER_TO_CLIENT


class TapSide(enum.IntEnum):
    REST = 0
    CLIENT = 1 << 0
    SERVER = 1 << 1
    LOCAL = 1 << 2
    CLIENT_NODE = (1 << 0) | _SIDE_NODE
    SERVER_NODE = (1 << 1) | _SIDE_NODE
    CLIENT_HYPERVISOR = (1 << 0) | _SIDE_HYPERVISOR
    SERVER_HYPERVISOR = (1 << 1) | _SIDE_HYPERVISOR
    CLIENT_GATEWAY_HYPERVISOR = (1 << 0) | _SIDE_GATEWAY_HYPERVISOR
    SERVER_GATEWAY_HYPERVISOR = (1 << 1) | _SIDE_GATEWAY_HYPERVISOR
    CLIENT_GATEWAY = (1 << 0) | _SIDE_GATEWAY
    SERVER_GATEWAY = (1 << 1) | _SIDE_GATEWAY
    CLIENT_PROCESS = (1 << 0) | _SIDE_PROCESS
    SERVER_PROCESS = (1 << 1) | _SIDE_PROCESS
    CLIENT_APP = (1 << 0) | _SIDE_APP
    SERVER_APP = (1 << 1) | _SIDE_APP
    APP = _SIDE_APP

    @staticmethod
    def from_direction(direction: "Direction") -> "TapSide":
        # document.rs:243-264 — TapSide is Direction with the direction
        # bit kept and NONE → REST.
        if direction == Direction.NONE:
            return TapSide.REST
        return TapSide(int(direction))


class SignalSource(enum.IntEnum):
    # agent/src/common/lookup_key.rs / flow.rs SignalSource
    PACKET = 0
    XFLOW = 1
    EBPF = 3
    OTEL = 4


class MeterId(enum.IntEnum):
    # meter.rs:23-25 — protobuf meter_id discriminants.
    FLOW = 1
    USAGE = 4
    APP = 5


class L7Protocol(enum.IntEnum):
    """Subset of the reference's L7Protocol registry
    (agent/crates/public/src/l7_protocol.rs). Values used as dense tag ids.
    """

    UNKNOWN = 0
    OTHER = 1
    HTTP1 = 20
    HTTP2 = 21
    DUBBO = 40
    GRPC = 41
    SOFARPC = 43
    FASTCGI = 44
    BRPC = 45
    TARS = 46
    SOME_IP = 47
    MYSQL = 60
    POSTGRESQL = 61
    ORACLE = 62
    REDIS = 80
    MONGODB = 81
    MEMCACHED = 82
    KAFKA = 100
    MQTT = 101
    AMQP = 102
    OPENWIRE = 103
    NATS = 104
    PULSAR = 105
    ZMTP = 106
    ROCKETMQ = 107
    DNS = 120
    TLS = 121
    PING = 122
    CUSTOM = 127
