"""Struct-of-arrays batch containers — host↔device ABI.

`FlowBatch` is the decoded input: one row per accumulated flow interval
(what the reference calls `FlowMeterWithFlow` entering `Collector::collect_l4`,
collector.rs:380). `DocBatch` is the post-fanout stream of candidate
documents: a u32 tag matrix + f32 meter matrix + timestamp + validity mask,
the shape every device kernel consumes.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import numpy as np

from .schema import FLOW_METER, TAG_SCHEMA, MeterSchema, TagSchema

# Input columns of a decoded flow record (pre-fanout). Everything u32
# except meters. direction0/1 use Direction values; is_active_host* are
# 0/1 flags (collector.rs:489-499 activity gating).
FLOW_RECORD_TAG_FIELDS: tuple[str, ...] = (
    "timestamp",  # seconds
    "global_thread_id",
    "agent_id",
    "signal_source",
    "is_ipv6",
    "ip0_w0",
    "ip0_w1",
    "ip0_w2",
    "ip0_w3",
    "ip1_w0",
    "ip1_w1",
    "ip1_w2",
    "ip1_w3",
    "mac0_hi",
    "mac0_lo",
    "mac1_hi",
    "mac1_lo",
    "l3_epc_id",
    "l3_epc_id1",
    "gpid0",
    "gpid1",
    "pod_id",
    "protocol",
    "server_port",
    "tap_port",
    "tap_type",
    "l7_protocol",
    "direction0",
    "direction1",
    "is_active_host0",
    "is_active_host1",
    "is_vip0",
    "is_vip1",
    "is_active_service",
    # L7-only fields (AppMeterWithFlow, collector.rs:101-112); zero for L4
    # records.
    "endpoint_hash",
    "biz_type",
    "time_span",
)

# The raw-tag packing plan (fingerprint hot path) must cover exactly
# these columns — a field added here without a width entry would be
# silently dropped from the group-by key, so fail at import instead.
from .code import RAW_TAG_PACK as _RAW_TAG_PACK  # noqa: E402

assert set(_RAW_TAG_PACK.field_names()) == set(FLOW_RECORD_TAG_FIELDS), (
    "RAW_TAG_WIDTHS (datamodel/code.py) out of sync with FLOW_RECORD_TAG_FIELDS"
)


@dataclasses.dataclass
class FlowBatch:
    """Decoded flow records, columnar. tags: [N] u32 per field; meters:
    [N, FLOW_METER.num_fields] f32; valid: [N] bool (padding mask)."""

    tags: dict[str, np.ndarray]
    meters: np.ndarray
    valid: np.ndarray

    @property
    def size(self) -> int:
        return int(self.meters.shape[0])

    @classmethod
    def from_records(cls, records: list[Mapping], meter_schema: MeterSchema = FLOW_METER) -> "FlowBatch":
        """Build a batch from per-flow dicts (test/replay convenience)."""
        n = len(records)
        tags = {f: np.zeros(n, dtype=np.uint32) for f in FLOW_RECORD_TAG_FIELDS}
        meters = np.zeros((n, meter_schema.num_fields), dtype=np.float32)
        for i, r in enumerate(records):
            for f in FLOW_RECORD_TAG_FIELDS:
                if f in r:
                    tags[f][i] = np.uint32(int(r[f]) & 0xFFFFFFFF)
            m = r.get("meter", {})
            for name, v in m.items():
                meters[i, meter_schema.index(name)] = v
        return cls(tags=tags, meters=meters, valid=np.ones(n, dtype=bool))

    def pad_to(self, n: int) -> "FlowBatch":
        """Pad to a static batch size (XLA wants fixed shapes)."""
        cur = self.size
        if cur == n:
            return self
        if cur > n:
            raise ValueError(f"batch of {cur} cannot pad to {n}")
        pad = n - cur
        tags = {k: np.concatenate([v, np.zeros(pad, dtype=v.dtype)]) for k, v in self.tags.items()}
        meters = np.concatenate([self.meters, np.zeros((pad, self.meters.shape[1]), dtype=self.meters.dtype)])
        valid = np.concatenate([self.valid, np.zeros(pad, dtype=bool)])
        return FlowBatch(tags=tags, meters=meters, valid=valid)

    def slice(self, start: int, stop: int) -> "FlowBatch":
        """Row-range view (the feeder splits decoded chunks across
        bucket boundaries; numpy basic slicing keeps this copy-free)."""
        return FlowBatch(
            tags={k: v[start:stop] for k, v in self.tags.items()},
            meters=self.meters[start:stop],
            valid=self.valid[start:stop],
        )

    @classmethod
    def concat(cls, parts: list["FlowBatch"]) -> "FlowBatch":
        """Row-wise concatenation of same-schema batches."""
        if len(parts) == 1:
            return parts[0]
        keys = parts[0].tags.keys()
        return cls(
            tags={k: np.concatenate([p.tags[k] for p in parts]) for k in keys},
            meters=np.concatenate([p.meters for p in parts]),
            valid=np.concatenate([p.valid for p in parts]),
        )


@dataclasses.dataclass
class DocBatch:
    """Candidate documents after tag fanout.

    tags:      [N, TAG_SCHEMA.num_fields] u32
    meters:    [N, meter_schema.num_fields] f32
    timestamp: [N] u32 (seconds)
    valid:     [N] bool
    """

    tags: np.ndarray
    meters: np.ndarray
    timestamp: np.ndarray
    valid: np.ndarray
    tag_schema: TagSchema = TAG_SCHEMA
    meter_schema: MeterSchema = FLOW_METER

    @property
    def size(self) -> int:
        return int(self.tags.shape[0])

    def tag(self, name: str) -> np.ndarray:
        return self.tags[:, self.tag_schema.index(name)]

    def meter(self, name: str) -> np.ndarray:
        return self.meters[:, self.meter_schema.index(name)]

    def to_dicts(self) -> list[dict]:
        """Expand valid rows to python dicts (tests / JSON export)."""
        out = []
        tag_names = self.tag_schema.field_names()
        meter_names = self.meter_schema.field_names()
        for i in range(self.size):
            if not self.valid[i]:
                continue
            out.append(
                {
                    "timestamp": int(self.timestamp[i]),
                    "tag": {n: int(self.tags[i, j]) for j, n in enumerate(tag_names)},
                    "meter": {n: float(self.meters[i, j]) for j, n in enumerate(meter_names)},
                }
            )
        return out
