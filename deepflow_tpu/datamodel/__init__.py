from .code import (
    Code,
    CodeId,
    Direction,
    DocumentFlag,
    L7Protocol,
    MeterId,
    SignalSource,
    TapSide,
)
from .schema import (
    APP_METER,
    FLOW_METER,
    USAGE_METER,
    MergeOp,
    MeterSchema,
    TAG_SCHEMA,
    TagSchema,
)
from .batch import FlowBatch, DocBatch

__all__ = [
    "Code",
    "CodeId",
    "Direction",
    "DocumentFlag",
    "L7Protocol",
    "MeterId",
    "SignalSource",
    "TapSide",
    "MergeOp",
    "MeterSchema",
    "TagSchema",
    "FLOW_METER",
    "APP_METER",
    "USAGE_METER",
    "TAG_SCHEMA",
    "FlowBatch",
    "DocBatch",
]
