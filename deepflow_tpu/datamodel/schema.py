"""Declarative tag/meter column registries — the XLA-facing ABI.

The reference's `Tagger` struct (document.rs:287-340) and meter structs
(meter.rs:88-560) become *named columns* of fixed dtype here. Every device
kernel is schema-driven: merge ops, reverse permutations and key-column
masks are all derived from these tables instead of hand-written per field,
so adding a field is a one-line change.

Merge semantics (meter.rs `sequential_merge`):
  * SUM  — counters (packets, bytes, latency sums/counts, anomalies).
  * MAX  — watermarks (latency maxima, direction_score).
`reverse()` (meter.rs:169-177) swaps tx/rx pairs and zeroes
direction_score; we encode it as a column permutation + zero mask.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Sequence

import numpy as np


class MergeOp(enum.Enum):
    SUM = "sum"
    MAX = "max"


@dataclasses.dataclass(frozen=True)
class MeterField:
    name: str
    op: MergeOp
    # Name of the field this one swaps with under reverse(); "" = no swap.
    reverse_with: str = ""
    # Zeroed on reverse (direction_score semantics, meter.rs:174).
    zero_on_reverse: bool = False


@dataclasses.dataclass(frozen=True)
class MeterSchema:
    """A flat meter layout: one f32 device column per field."""

    name: str
    fields: tuple[MeterField, ...]

    def __post_init__(self):
        object.__setattr__(self, "_index", {f.name: i for i, f in enumerate(self.fields)})

    @property
    def num_fields(self) -> int:
        return len(self.fields)

    def index(self, name: str) -> int:
        return self._index[name]

    @property
    def sum_mask(self) -> np.ndarray:
        return np.array([f.op is MergeOp.SUM for f in self.fields], dtype=bool)

    @property
    def max_mask(self) -> np.ndarray:
        return np.array([f.op is MergeOp.MAX for f in self.fields], dtype=bool)

    @property
    def reverse_perm(self) -> np.ndarray:
        """Column permutation implementing meter reverse() as a gather."""
        perm = np.arange(self.num_fields, dtype=np.int32)
        for i, f in enumerate(self.fields):
            if f.reverse_with:
                perm[i] = self.index(f.reverse_with)
        return perm

    @property
    def reverse_zero_mask(self) -> np.ndarray:
        return np.array([f.zero_on_reverse for f in self.fields], dtype=bool)

    def field_names(self) -> list[str]:
        return [f.name for f in self.fields]


def _sum(name: str, reverse_with: str = "") -> MeterField:
    return MeterField(name, MergeOp.SUM, reverse_with)


def _max(name: str, zero_on_reverse: bool = False) -> MeterField:
    return MeterField(name, MergeOp.MAX, zero_on_reverse=zero_on_reverse)


# FlowMeter = Traffic + Latency + Performance + Anomaly + FlowLoad
# (meter.rs:88-134, 141-176, 302-333, 345-366, 416-430).
#
# FlowLoad deviation: the reference updates flow_load with a sequential,
# order-dependent rule (meter.rs:420-428). A data-parallel reduce needs a
# commutative op, so we model load/flow_count as SUM of per-record deltas;
# the oracle mirrors this definition, and the divergence is bounded by the
# per-window closed-flow count (documented in ARCHITECTURE.md §5).
FLOW_METER = MeterSchema(
    "flow",
    tuple(
        [
            # Traffic (meter.rs:133-176)
            _sum("packet_tx", "packet_rx"),
            _sum("packet_rx", "packet_tx"),
            _sum("byte_tx", "byte_rx"),
            _sum("byte_rx", "byte_tx"),
            _sum("l3_byte_tx", "l3_byte_rx"),
            _sum("l3_byte_rx", "l3_byte_tx"),
            _sum("l4_byte_tx", "l4_byte_rx"),
            _sum("l4_byte_rx", "l4_byte_tx"),
            _sum("new_flow"),
            _sum("closed_flow"),
            _sum("l7_request"),
            _sum("l7_response"),
            _sum("syn"),
            _sum("synack"),
            _max("direction_score", zero_on_reverse=True),
            # Latency (meter.rs:202-276): 8 maxima, 8 sums, 8 counts.
            _max("rtt_max"),
            _max("rtt_client_max"),
            _max("rtt_server_max"),
            _max("srt_max"),
            _max("art_max"),
            _max("rrt_max"),
            _max("cit_max"),
            _max("tls_rtt_max"),
            _sum("rtt_sum"),
            _sum("rtt_client_sum"),
            _sum("rtt_server_sum"),
            _sum("srt_sum"),
            _sum("art_sum"),
            _sum("rrt_sum"),
            _sum("cit_sum"),
            _sum("tls_rtt_sum"),
            _sum("rtt_count"),
            _sum("rtt_client_count"),
            _sum("rtt_server_count"),
            _sum("srt_count"),
            _sum("art_count"),
            _sum("rrt_count"),
            _sum("cit_count"),
            _sum("tls_rtt_count"),
            # Performance (meter.rs:311-333)
            _sum("retrans_tx"),
            _sum("retrans_rx"),
            _sum("zero_win_tx"),
            _sum("zero_win_rx"),
            _sum("retrans_syn"),
            _sum("retrans_synack"),
            # Anomaly (meter.rs:345-391)
            _sum("client_rst_flow"),
            _sum("server_rst_flow"),
            _sum("client_ack_miss"),
            _sum("server_syn_miss"),
            _sum("client_half_close_flow"),
            _sum("server_half_close_flow"),
            _sum("client_source_port_reuse"),
            _sum("client_establish_reset"),
            _sum("server_reset"),
            _sum("server_queue_lack"),
            _sum("server_establish_reset"),
            _sum("tcp_timeout"),
            _sum("l7_client_error"),
            _sum("l7_server_error"),
            _sum("l7_timeout"),
            # FlowLoad (see deviation note above)
            _sum("flow_load"),
            _sum("flow_count"),
        ]
    ),
)

# AppMeter = AppTraffic + AppLatency + AppAnomaly (meter.rs:433-545).
APP_METER = MeterSchema(
    "app",
    tuple(
        [
            _sum("request", "response"),
            _sum("response", "request"),
            _max("direction_score", zero_on_reverse=True),
            _max("rrt_max"),
            _sum("rrt_sum"),
            _sum("rrt_count"),
            _sum("client_error"),
            _sum("server_error"),
            _sum("timeout"),
        ]
    ),
)

# UsageMeter (meter.rs:547-560). Emitted by the ACL/policy doc path
# (collector.rs:440-487); its fields map 1:1 onto Traffic columns so the L4
# stash can host Usage docs in the same meter matrix, discriminated by the
# `meter_id` tag column.
USAGE_METER = MeterSchema(
    "usage",
    tuple(
        [
            _sum("packet_tx", "packet_rx"),
            _sum("packet_rx", "packet_tx"),
            _sum("byte_tx", "byte_rx"),
            _sum("byte_rx", "byte_tx"),
            _sum("l3_byte_tx", "l3_byte_rx"),
            _sum("l3_byte_rx", "l3_byte_tx"),
            _sum("l4_byte_tx", "l4_byte_rx"),
            _sum("l4_byte_rx", "l4_byte_tx"),
        ]
    ),
)


@dataclasses.dataclass(frozen=True)
class TagField:
    name: str
    # All tag columns are uint32 on device. `key` says whether the column
    # participates in the group-by fingerprint (all of them do by default —
    # inactive fields are zeroed per Code by the fanout stage, reproducing
    # StashKey equality, collector.rs:128-139).
    key: bool = True


@dataclasses.dataclass(frozen=True)
class TagSchema:
    fields: tuple[TagField, ...]

    def __post_init__(self):
        object.__setattr__(self, "_index", {f.name: i for i, f in enumerate(self.fields)})

    @property
    def num_fields(self) -> int:
        return len(self.fields)

    def index(self, name: str) -> int:
        return self._index[name]

    def indices(self, names: Sequence[str]) -> np.ndarray:
        return np.array([self.index(n) for n in names], dtype=np.int32)

    @property
    def key_mask(self) -> np.ndarray:
        return np.array([f.key for f in self.fields], dtype=bool)

    def field_names(self) -> list[str]:
        return [f.name for f in self.fields]


# Tagger → columns (document.rs:287-340). IPs are 4×u32 words (IPv4 in
# word 3, words 0-2 zero, matching a right-aligned big-endian v6 layout);
# MACs are 2×u32 (hi16/lo32).
TAG_SCHEMA = TagSchema(
    tuple(
        [
            TagField("code_id"),  # dense CodeId — the fast_id CodeID bits
            TagField("meter_id"),  # MeterId discriminant (flow/app/usage)
            TagField("global_thread_id"),
            TagField("agent_id"),
            TagField("is_ipv6"),
            TagField("ip0_w0"),
            TagField("ip0_w1"),
            TagField("ip0_w2"),
            TagField("ip0_w3"),
            TagField("ip1_w0"),
            TagField("ip1_w1"),
            TagField("ip1_w2"),
            TagField("ip1_w3"),
            TagField("l3_epc_id"),  # i16 stored as u16 (sign-folded)
            TagField("l3_epc_id1"),
            TagField("mac0_hi"),
            TagField("mac0_lo"),
            TagField("mac1_hi"),
            TagField("mac1_lo"),
            TagField("direction"),
            # tap_side is a pure function of direction (document.rs:243) —
            # not part of StashKey equality.
            TagField("tap_side", key=False),
            TagField("protocol"),
            TagField("acl_gid"),
            TagField("server_port"),
            TagField("tap_port"),
            TagField("tap_type"),
            TagField("l7_protocol"),
            TagField("gpid0"),
            TagField("gpid1"),
            TagField("endpoint_hash"),
            TagField("time_span"),
            TagField("biz_type"),
            TagField("signal_source"),
            # pod_id rides along for server-side enrichment but is absent
            # from StashKey (collector.rs:128-139) — first-writer-wins.
            TagField("pod_id", key=False),
        ]
    )
)
