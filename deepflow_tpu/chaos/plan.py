"""Deterministic fault injection for the feeder→device→flush path.

The reference proves its ingest survives agent disconnects, ingester
restarts and backpressure by running them in anger; a reproduction
needs the same proof in CI, which means faults that are *scriptable
per step* and replay identically under a fixed seed. This module is
that harness:

  * a `FaultPlan` holds `FaultRule`s keyed by **site** — the named
    seams the production code already has (device dispatch, host
    fetch, feeder decode, journal/checkpoint I/O, sink writes);
  * production seams call `chaos.maybe_fail(site)`, a no-op (one
    global read) unless a plan is installed, so the fault surface
    costs nothing in steady state;
  * rules fire on exact per-site call indices (`at=(3, 7)`), windows
    (`start/count/every`), or a seeded probability (`p=`), so every
    scenario — "the 4th dispatch throws RESOURCE_EXHAUSTED twice" —
    reproduces bit-for-bit;
  * `KillPoint` derives from BaseException: it models *process death*
    and deliberately pierces every containment layer (retry loops and
    quarantine guards catch Exception only), so recovery tests can
    kill a pipeline mid-flush and rebuild from journal + checkpoint.

Frame-corruption helpers (`truncate_frame` / `bitflip_frame`) cover
the fault class that arrives as bytes rather than exceptions.

THE SEAM LIST (ISSUE 15 satellite — the named seams have grown across
r11/r15/r18 and were only discoverable by grep; this table is the one
place that enumerates them). Every seam is a `chaos.maybe_fail(site)`
call in production code; the "fires in" column is the exact module:

    site              fires in                          covers
    ----------------  --------------------------------  -----------------------------
    device.dispatch   aggregator/window.py,             fused-step dispatch (single-
                      parallel/sharded.py               chip AND sharded)
    host.fetch        aggregator/window.py,             device→host fetch (the
                      parallel/sharded.py               ≤3-fetch budget's seam)
    feeder.decode     feeder/runtime.py                 sink codec decode (poisoned-
                      (FrameCodecBase.decode_frame)     frame quarantine boundary)
    sink.write        storage/writer.py                 TableWriter → store.insert
    checkpoint.io     aggregator/checkpoint.py          window-state snapshot write
    journal.io        feeder/journal.py                 frame-journal append/rotate
    handoff.send      ingest/handoff.py                 misroute-handoff transport
                      (HandoffSender peer loop)         write (ISSUE 15: scripted
                                                        transport loss)
    rebalance.step    parallel/rebalance.py             each protocol step of a
                      (GroupRebalancer release/adopt)   shard-group handover
                                                        (ISSUE 15: mid-protocol
                                                        death via KillPoint)
"""

from __future__ import annotations

import dataclasses
import random
import threading
from contextlib import contextmanager

from ..utils.retry import TransientError

# ---------------------------------------------------------------------------
# fault sites — the seams production code exposes to the plan

SITE_DISPATCH = "device.dispatch"  # fused-step dispatch (window + sharded)
SITE_FETCH = "host.fetch"  # device→host fetch (WindowManager._fetch seam)
SITE_DECODE = "feeder.decode"  # sink codec decode (quarantine boundary)
SITE_SINK_WRITE = "sink.write"  # storage TableWriter → store.insert
SITE_CHECKPOINT_IO = "checkpoint.io"  # window-state snapshot write
SITE_JOURNAL_IO = "journal.io"  # frame-journal append/rotate
SITE_HANDOFF_SEND = "handoff.send"  # misroute-handoff transport write
SITE_REBALANCE_STEP = "rebalance.step"  # shard-group handover protocol step
SITE_WIRE_SEND = "wire.send"  # DFPUSH publisher result/alert upload write

FAULT_SITES = (
    SITE_DISPATCH,
    SITE_FETCH,
    SITE_DECODE,
    SITE_SINK_WRITE,
    SITE_CHECKPOINT_IO,
    SITE_JOURNAL_IO,
    SITE_HANDOFF_SEND,
    SITE_WIRE_SEND,
    SITE_REBALANCE_STEP,
)


# ---------------------------------------------------------------------------
# fault taxonomy

class InjectedFault(Exception):
    """Base marker for every chaos-raised failure."""


class TransientDeviceError(TransientError, InjectedFault):
    """RESOURCE_EXHAUSTED-style admission failure: the dispatch never
    started; the retry policy may re-issue it."""


class FetchTimeout(TransientError, InjectedFault):
    """host_fetch deadline blown (the ~150-200 ms tunnel round trip
    stalling); retryable — the device handle is still valid."""


class DeviceLost(InjectedFault):
    """Non-transient device failure: retrying is unsound (donated
    buffers may be consumed); containment must degrade instead."""


class SinkWriteError(InjectedFault, OSError):
    """Storage/sink write failure — OSError so the TableWriter's
    existing transient-retry loop exercises its real path."""


class CheckpointIOError(InjectedFault, OSError):
    """Checkpoint snapshot I/O failure (disk full, volume gone)."""


class KillPoint(BaseException):
    """Simulated process death. BaseException on purpose: retry and
    quarantine guards catch Exception, so a KillPoint rips straight
    through to the test driver exactly like SIGKILL would — nothing
    in-process may 'handle' its own death."""


class RebalanceAbortError(Exception):
    """A shard-group handover (parallel/rebalance.py) could not
    complete: quiesce never drained, the barrier checkpoint aborted, a
    concurrent rebalance holds the single-flight guard, or a scripted
    fault at the `rebalance.step` seam. Part of the fault taxonomy so
    CI can inject it mid-protocol; also raised by the real protocol —
    the old owner keeps serving the group, nothing has moved."""


# ---------------------------------------------------------------------------
# rules + plan


@dataclasses.dataclass
class FaultRule:
    """Fires `error` at matching per-site call indices (0-based).

    `at`: explicit index tuple (wins over start/count/every).
    `start/count/every`: fire `count` times, at indices start,
    start+every, … . `p`: instead of index matching, fire with
    probability p per call (seeded by the plan — deterministic),
    still bounded by `count`.
    """

    site: str
    error: type | BaseException = TransientDeviceError
    at: tuple[int, ...] | None = None
    start: int = 0
    count: int = 1
    every: int = 1
    p: float | None = None

    def _matches(self, n: int, fired: int, rng: random.Random) -> bool:
        if fired >= self.count and self.at is None:
            return False
        if self.at is not None:
            return n in self.at
        if self.p is not None:
            return n >= self.start and rng.random() < self.p
        return n >= self.start and (n - self.start) % max(1, self.every) == 0

    def _make(self) -> BaseException:
        if isinstance(self.error, BaseException):
            return self.error
        return self.error(f"injected fault at {self.site}")


class FaultPlan:
    """A seeded, scriptable fault schedule over the named sites.

    Thread-safe (the feeder pump, writer flusher and collector tick all
    cross seams concurrently). Per-site call counts and injection
    counts are exposed for test assertions; `calls`/`injected` survive
    uninstall so a finished scenario can still be audited.
    """

    def __init__(self, seed: int = 0, rules: list[FaultRule] | None = None):
        self.seed = seed
        self.rules = list(rules or ())
        self.calls: dict[str, int] = {}
        self.injected: dict[str, int] = {}
        self._rng = random.Random(seed)
        self._fired: dict[int, int] = {}  # id(rule) → times fired
        self._lock = threading.Lock()

    def add(self, *rules: FaultRule) -> "FaultPlan":
        self.rules.extend(rules)
        return self

    def fire(self, site: str) -> None:
        """Count one call at `site`; raise if a rule matches."""
        with self._lock:
            n = self.calls.get(site, 0)
            self.calls[site] = n + 1
            for rule in self.rules:
                if rule.site != site:
                    continue
                fired = self._fired.get(id(rule), 0)
                if rule._matches(n, fired, self._rng):
                    self._fired[id(rule)] = fired + 1
                    self.injected[site] = self.injected.get(site, 0) + 1
                    raise rule._make()


# ---------------------------------------------------------------------------
# the global hook production seams consult

_active: FaultPlan | None = None


def install(plan: FaultPlan) -> FaultPlan:
    global _active
    _active = plan
    return plan


def uninstall() -> None:
    global _active
    _active = None


@contextmanager
def active(plan: FaultPlan):
    install(plan)
    try:
        yield plan
    finally:
        uninstall()


def maybe_fail(site: str) -> None:
    """THE seam: free when no plan is installed."""
    plan = _active
    if plan is not None:
        plan.fire(site)


# ---------------------------------------------------------------------------
# byte-level corruption (the decode fault class arrives as data)


def truncate_frame(raw: bytes, rng: random.Random) -> bytes:
    """Cut a frame at a random interior point (1 ≤ cut < len)."""
    if len(raw) < 2:
        return raw[:0]
    return raw[: rng.randrange(1, len(raw))]


def bitflip_frame(raw: bytes, rng: random.Random, flips: int = 4) -> bytes:
    """Flip `flips` random bits anywhere in the frame."""
    buf = bytearray(raw)
    if not buf:
        return bytes(buf)
    for _ in range(flips):
        i = rng.randrange(len(buf))
        buf[i] ^= 1 << rng.randrange(8)
    return bytes(buf)
