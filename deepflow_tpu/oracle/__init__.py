from .numpy_oracle import OracleDoc, oracle_l4_rollup, oracle_l7_rollup

__all__ = ["OracleDoc", "oracle_l4_rollup", "oracle_l7_rollup"]
